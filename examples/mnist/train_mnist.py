"""MNIST LeNet training — the minimum end-to-end slice (BASELINE config 0;
reference analog: example/gluon/mnist/mnist.py).

Runs imperatively first, then hybridized (XLA-compiled).  With no MNIST
files on disk it falls back to a synthetic digit-like dataset so the
script is runnable anywhere:

    python examples/mnist/train_mnist.py --epochs 2 [--smoke]
"""
import argparse
import os
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.models.lenet import lenet


def load_data(batch_size, smoke):
    data_dir = os.environ.get("MNIST_DIR", "data/mnist")
    img = os.path.join(data_dir, "train-images-idx3-ubyte.gz")
    lab = os.path.join(data_dir, "train-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lab):
        return mx.io.MNISTIter(image=img, label=lab, batch_size=batch_size)
    # synthetic fallback: blurred one-hot strokes, learnable but fake
    n = 512 if smoke else 8192
    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.1
    for i, lbl in enumerate(y):
        x[i, 0, lbl * 2:lbl * 2 + 4, 4:24] += 0.9
    return mx.io.NDArrayIter(x, y.astype(np.float32),
                             batch_size=batch_size, shuffle=True,
                             label_name="softmax_label")


def evaluate(net, it):
    metric = mx.metric.Accuracy()
    it.reset()
    for batch in it:
        out = net(batch.data[0])
        metric.update([batch.label[0]], [out])
    return metric.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--hybridize", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    net = lenet(classes=10)
    net.initialize(init="xavier")
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    train_iter = load_data(args.batch_size, args.smoke)

    for epoch in range(args.epochs):
        train_iter.reset()
        metric = mx.metric.Accuracy()
        tic = time.time()
        n = 0
        for batch in train_iter:
            data, label = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
            n += data.shape[0]
        acc = metric.get()[1]
        print(f"epoch {epoch}: train acc {acc:.4f}  "
              f"({n / (time.time() - tic):.0f} img/s)")
    final = evaluate(net, train_iter)
    print(f"final accuracy: {final:.4f}")
    assert final > 0.9, "MNIST LeNet should reach >0.9 train accuracy"


if __name__ == "__main__":
    main()
