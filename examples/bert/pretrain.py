"""BERT-base MLM pretraining (BASELINE config 3; reference analog: the
GluonNLP BERT pretraining script — the in-repo capabilities it exercises
are Gluon blocks, LayerNorm/gelu/Embedding/batch_dot, AMP, LAMB, and the
data-parallel trainer, SURVEY §2.4).

TPU-native extras over the reference: the attention core is the Pallas
flash kernel on TPU, the step runs as one fused XLA program, and with
--mesh dp,tp,sp it shards over a device mesh (tensor/sequence parallel)
instead of a parameter server.

    python examples/bert/pretrain.py --smoke            # tiny model, CPU-ok
    python examples/bert/pretrain.py --steps 100        # bert-base
"""
import argparse
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import gluon, nd
from tpu_mx.models.bert import (BERTModel, bert_base_config,
                                bert_sharding_rules)
from tpu_mx.parallel import CompiledTrainStep


class MLMLoss(gluon.loss.Loss):
    """Masked-LM cross entropy over masked positions only."""

    def __init__(self, **kwargs):
        super().__init__(weight=None, batch_axis=0, **kwargs)
        self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def hybrid_forward(self, F, logits, labels):
        # labels: (B, T) with -1 on unmasked positions
        vocab = logits.shape[-1]
        flat_logits = F.reshape(logits, shape=(-1, vocab))
        flat_labels = F.reshape(labels, shape=(-1,))
        mask = flat_labels >= 0
        safe = F.where(mask, flat_labels,
                       F.zeros_like(flat_labels))
        ce = self._ce(flat_logits, safe)
        ce = F.where(mask, ce, F.zeros_like(ce))
        return F.sum(ce) / F.maximum(F.sum(mask.astype("float32")), 1.0)


def synthetic_batch(rng, batch, seqlen, vocab):
    tokens = rng.randint(4, vocab, (batch, seqlen)).astype(np.int32)
    labels = np.full((batch, seqlen), -1, np.int32)
    n_mask = max(1, int(0.15 * seqlen))
    for b in range(batch):
        pos = rng.choice(seqlen, n_mask, replace=False)
        labels[b, pos] = tokens[b, pos]
        tokens[b, pos] = 3  # [MASK]
    types = np.zeros((batch, seqlen), np.int32)
    return tokens, types, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--lr", type=float, default=None,
                    help="default: 1e-3 in --smoke (overfit), 1e-4 otherwise")
    ap.add_argument("--optimizer", default="lamb")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    if args.smoke:
        cfg = bert_base_config(vocab_size=1000, max_len=args.seq_len)
        # dropout=0 in smoke: the learn-signal is memorization of ONE fixed
        # batch, and dropout noise over 10 steps can swamp it.
        cfg.update(num_layers=2, units=128, hidden_size=512, num_heads=2,
                   dropout=0.0)
        args.steps = min(args.steps, 10)
    else:
        cfg = bert_base_config(max_len=args.seq_len)
    if args.lr is None:
        args.lr = 1e-3 if args.smoke else 1e-4

    net = BERTModel(cfg, dtype=args.dtype)
    net.initialize()
    rng = np.random.RandomState(0)
    t0, ty0, _ = synthetic_batch(rng, args.batch_size, args.seq_len,
                                 cfg["vocab_size"])
    net(nd.array(t0), nd.array(ty0))  # finalize shapes

    opt = mx.optimizer.create(args.optimizer, learning_rate=args.lr,
                              multi_precision=True)
    step = CompiledTrainStep(net, MLMLoss(), opt)

    fixed = synthetic_batch(rng, args.batch_size, args.seq_len,
                            cfg["vocab_size"]) if args.smoke else None
    losses, tic = [], time.time()
    for i in range(args.steps):
        # Smoke overfits one fixed batch (memorization is the reliable
        # learn-signal); real runs stream fresh batches.
        tokens, types, labels = fixed or synthetic_batch(
            rng, args.batch_size, args.seq_len, cfg["vocab_size"])
        loss = step.step(nd.array(tokens), nd.array(types), nd.array(labels))
        losses.append(float(loss.asnumpy()))
    n_seq = args.steps * args.batch_size
    print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f}  "
          f"({n_seq / (time.time() - tic):.1f} seq/s)")
    k = min(3, len(losses))
    assert sum(losses[-k:]) / k < sum(losses[:k]) / k, \
        "MLM loss should decrease"


if __name__ == "__main__":
    main()
