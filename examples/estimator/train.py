"""Estimator-API training (REF:python/mxnet/gluon/contrib/estimator) with
the process-worker DataLoader: a python-transform dataset feeds fork+shm
workers, the Estimator runs the fit loop with early stopping and best-
checkpointing, and evaluation reports loss + accuracy.

Usage: python examples/estimator/train.py [--smoke] [--epochs N]
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

import tpu_mx as mx  # noqa: E402
from tpu_mx import gluon, nd  # noqa: E402
from tpu_mx.gluon import nn  # noqa: E402
from tpu_mx.gluon.contrib.estimator import (CheckpointHandler,  # noqa: E402
                                            EarlyStoppingHandler, Estimator,
                                            LoggingHandler)
from tpu_mx.gluon.data import DataLoader  # noqa: E402


class TwoMoons:
    """Python-heavy per-sample transform — the case process workers are
    for (a thread pool would serialize on the GIL here)."""

    def __init__(self, n, noise=0.15):
        self._n = n
        self._noise = noise

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        label = i % 2
        t = rng.rand() * np.pi
        x = np.cos(t) if label == 0 else 1 - np.cos(t)
        y = np.sin(t) if label == 0 else 0.5 - np.sin(t)
        pt = np.array([x, y], np.float32) + \
            rng.randn(2).astype(np.float32) * self._noise
        return pt, np.float32(label)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-workers", type=int, default=2)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    n = 512 if args.smoke else 4096
    if args.smoke:
        args.epochs = min(args.epochs, 10)

    mx.random.seed(0)
    loader = DataLoader(TwoMoons(n), batch_size=args.batch_size,
                        shuffle=True, num_workers=args.num_workers,
                        thread_pool=False)  # fork + POSIX-shm transport

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=2),
            nn.Dense(32, activation="relu", in_units=32),
            nn.Dense(2, in_units=32))
    net.initialize()
    net.hybridize()

    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 5e-3}))
    ckdir = tempfile.mkdtemp(prefix="estimator_ck_")
    est.fit(loader, epochs=args.epochs, event_handlers=[
        LoggingHandler(log_interval=50),
        CheckpointHandler(ckdir, save_best=True, monitor="loss",
                          mode="min"),
        EarlyStoppingHandler(monitor="loss", patience=4, mode="min"),
    ])
    result = est.evaluate(loader)

    acc = mx.metric.Accuracy()
    for data, label in loader:
        acc.update([label], [net(data)])
    print(f"eval loss {result['loss']:.4f}  accuracy {acc.get()[1]:.3f}")
    assert result["loss"] < 0.45, f"did not learn: {result}"
    assert acc.get()[1] > 0.8, acc.get()
    saved = [f for f in os.listdir(ckdir) if f.endswith(".params")]
    assert any("best" in f for f in saved), saved
    print(f"checkpoints: {sorted(saved)[:3]}")


if __name__ == "__main__":
    main()
