"""INT8 post-training quantization example (reference analog:
REF:example/quantization/imagenet_gen_qsym_mkldnn.py — calibrate a trained
float model, swap conv/dense compute to int8, compare accuracy).

Trains a small CNN on synthetic separable data (or loads --params),
calibrates with a few batches, quantizes conv+dense to int8
(int8×int8→int32 on the MXU via `contrib.quantization.quantize_net`), and
reports float vs int8 accuracy and agreement.

    python examples/quantization/quantize_cnn.py [--smoke]
"""
import argparse
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.contrib.quantization import quantize_net
from tpu_mx.gluon import nn


def make_data(n, classes, size, seed=0):
    rs = np.random.RandomState(seed)
    ys = rs.randint(0, classes, n)
    xs = rs.rand(n, 1, size, size).astype(np.float32) * 0.3
    half = size // 2
    for i, y in enumerate(ys):
        r, c = divmod(int(y), 2)
        xs[i, 0, r * half:(r + 1) * half, c * half:(c + 1) * half] += 1.0
    return xs, ys.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--train-steps", type=int, default=60)
    ap.add_argument("--calib-batches", type=int, default=4)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.train_steps = 30

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Dense(32, activation="relu"),
            nn.Dense(args.classes))
    net.initialize(init="xavier")

    xs, ys = make_data(512, args.classes, args.size)
    xb, yb = nd.array(xs), nd.array(ys)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    for step in range(args.train_steps):
        with autograd.record():
            loss = loss_fn(net(xb), yb)
            loss.backward()
        trainer.step(len(xs))

    xe, ye = make_data(256, args.classes, args.size, seed=1)
    xeb = nd.array(xe)
    float_pred = np.argmax(net(xeb).asnumpy(), axis=1)
    float_acc = float((float_pred == ye).mean())

    calib = [nd.array(xs[i * 64:(i + 1) * 64])
             for i in range(args.calib_batches)]
    qnet = quantize_net(net, calib_iter=calib)
    tic = time.time()
    q_pred = np.argmax(qnet(xeb).asnumpy(), axis=1)
    q_time = time.time() - tic
    q_acc = float((q_pred == ye).mean())
    agree = float((q_pred == float_pred).mean())

    print(f"float32 accuracy: {float_acc:.4f}")
    print(f"int8    accuracy: {q_acc:.4f}  (drop {float_acc - q_acc:+.4f})")
    print(f"int8/float argmax agreement: {agree:.4f}")
    print(f"int8 eval time: {q_time * 1000:.1f} ms "
          f"({len(xe) / max(q_time, 1e-9):.0f} img/s)")
    if float_acc - q_acc > 0.02:
        print("FAILED: int8 accuracy drop exceeded 2%")
        raise SystemExit(1)
    print("PASSED")


if __name__ == "__main__":
    main()
