"""Sparse (mixture-of-experts) transformer-style LM block training demo
(above-parity capability: the reference has no MoE — parallel.MoEFFN's
docstring has the TPU-first design).

A tiny token-level model: embedding -> MoE FFN (top-2 gated, 4 experts)
-> tied-ish dense decoder, trained with the Switch load-balance auxiliary
on next-token prediction over synthetic data.  Shows the (y, aux_loss)
contract and the ep-sharded path:

    python examples/moe/train_moe_lm.py --smoke           # CPU-ok
    python examples/moe/train_moe_lm.py --mesh dp2,ep2    # expert-parallel
      (needs >= 4 devices, e.g. XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""
import argparse
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import gluon, nd
from tpu_mx.gluon import nn
from tpu_mx.gluon.block import HybridBlock
from tpu_mx.parallel import (CompiledTrainStep, MoEFFN, P, make_mesh,
                             moe_sharding_rules)


class MoELM(HybridBlock):
    """embed -> MoE FFN -> vocab head; forward returns the combined
    scalar training loss (CE + aux_weight * load-balance)."""

    def __init__(self, vocab, units, hidden, experts, top_k=2,
                 aux_weight=0.01, **kw):
        super().__init__(**kw)
        self.embed = nn.Embedding(vocab, units)
        self.moe = MoEFFN(units, hidden, experts, top_k=top_k)
        self.head = nn.Dense(vocab, flatten=False, in_units=units)
        self._aux_w = aux_weight
        self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(self, tokens, labels):
        x = self.embed(tokens)                       # (B, T, U)
        y, aux = self.moe(x)
        logits = self.head(x + y)                    # residual around MoE
        vocab = logits.shape[-1]
        ce = nd.mean(self._ce(nd.reshape(logits, shape=(-1, vocab)),
                              nd.reshape(labels, shape=(-1,))))
        return ce + self._aux_w * aux


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=None,
                    help="default: 40 under --smoke, 60 otherwise")
    ap.add_argument("--mesh", default=None,
                    help="e.g. dp2,ep2 (axis name + size, comma-sep)")
    args = ap.parse_args()

    vocab, units, hidden, experts = (64, 32, 64, 4) if args.smoke else \
        (1000, 256, 1024, 8)
    B, T = (8, 16) if args.smoke else (32, 64)
    steps = args.steps if args.steps is not None else \
        (40 if args.smoke else 60)

    mesh = None
    rules = None
    data_specs = None
    if args.mesh:
        import jax
        axes = {}
        for part in args.mesh.split(","):
            name = part.rstrip("0123456789")
            axes[name] = int(part[len(name):])
        mesh = make_mesh(axes, devices=jax.devices()[
            :int(np.prod(list(axes.values())))])
        rules = moe_sharding_rules()
        data_specs = (P("dp"), P("dp"), P())

    np.random.seed(0)
    net = MoELM(vocab, units, hidden, experts)
    net.initialize(init="xavier")
    # synthetic learnable stream: CHAIN the recurrence column by column —
    # next token = (3 * tok + 1) mod vocab everywhere, so each label is a
    # deterministic function of its input token (a vectorized one-shot
    # assignment would leave labels independent of inputs past column 0)
    toks = np.empty((B, T + 1), np.int64)
    toks[:, 0] = np.random.randint(0, vocab, B)
    for j in range(1, T + 1):
        toks[:, j] = (3 * toks[:, j - 1] + 1) % vocab
    x = nd.array(toks[:, :-1].astype(np.float32))
    y = nd.array(toks[:, 1:].astype(np.float32))
    net(x, y)

    step = CompiledTrainStep(
        net, gluon.loss.PassThrough(), mx.optimizer.create("adam", learning_rate=3e-3),
        mesh=mesh, rules=rules, data_specs=data_specs)
    dummy = nd.array(np.zeros((1,), np.float32))
    t0 = time.time()
    losses = []
    for i in range(steps):
        l = step.step(x, y, dummy)
        losses.append(float(np.asarray(l._data).ravel()[0]))
        if i % 10 == 0:
            print(f"step {i}: loss {losses[-1]:.4f}", flush=True)
    first, last = np.mean(losses[:5]), np.mean(losses[-5:])
    print(f"loss {first:.4f} -> {last:.4f} in {time.time() - t0:.1f}s "
          f"({'mesh ' + args.mesh if args.mesh else 'single device'})",
          flush=True)
    assert last < first, "MoE LM did not learn"


if __name__ == "__main__":
    main()
