"""SSD detection training (BASELINE config 4; reference analog:
example/ssd/train.py): MultiBoxPrior anchors, MultiBoxTarget matching with
hard negative mining, CE + smooth-L1 loss, MultiBoxDetection + box_nms
inference.

Data: --data-train <det .rec file> uses ImageDetIter; otherwise synthetic
boxes (colored rectangles whose class is their color) so the script runs
anywhere.

    python examples/ssd/train.py --smoke
"""
import argparse
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.models.ssd import SSD, SSDTrainingTargets, ssd_300, ssd_512


def synthetic_batch(rng, batch, size, num_classes):
    """Images containing one axis-aligned bright rectangle per class id."""
    x = rng.rand(batch, 3, size, size).astype(np.float32) * 0.1
    labels = np.full((batch, 2, 5), -1.0, np.float32)
    for b in range(batch):
        cls = rng.randint(0, num_classes)
        x0, y0 = rng.uniform(0.05, 0.5, 2)
        w, h = rng.uniform(0.2, 0.45, 2)
        x1, y1 = min(x0 + w, 0.95), min(y0 + h, 0.95)
        xi = (np.array([x0, x1]) * size).astype(int)
        yi = (np.array([y0, y1]) * size).astype(int)
        x[b, cls % 3, yi[0]:yi[1], xi[0]:xi[1]] = 1.0
        labels[b, 0] = [cls, x0, y0, x1, y1]
    return x, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="ssd_512")
    ap.add_argument("--num-classes", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--steps-per-epoch", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--data-train", default=None,
                    help="det .rec file; fed through the native "
                         "mx.io.ImageDetRecordIter (C++ decode + box-aware "
                         "augment); synthetic boxes when omitted")
    ap.add_argument("--backbone", default="compact",
                    choices=["compact", "vgg16_reduced"],
                    help="vgg16_reduced = the reference SSD feature "
                         "pyramid (scaled conv4_3 + atrous fc7)")
    ap.add_argument("--feed", default="f32", choices=["f32", "u8"],
                    help="u8 ships raw pixels and normalizes on device")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        if args.backbone != "compact":
            ap.error("--smoke uses the tiny compact net; "
                     "--backbone has no effect there")
        args.num_classes, args.batch_size = 3, 4
        args.epochs, args.steps_per_epoch = 2, 8
        size = 64
        net = SSD(args.num_classes, sizes=[[0.2, 0.35], [0.5, 0.7]],
                  ratios=[[1, 2, 0.5]] * 2, base_filters=(8, 16))
    else:
        size = 512 if args.network == "ssd_512" else 300
        net = (ssd_512 if size == 512 else ssd_300)(
            args.num_classes, backbone=args.backbone)

    net.initialize(init="xavier")
    targets = SSDTrainingTargets()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 5e-4})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss()
    rng = np.random.RandomState(0)

    det_iter = None
    MEAN, STD = (123.68, 116.28, 103.53), (58.395, 57.12, 57.375)
    if args.data_train:
        norm = {} if args.feed == "u8" else dict(
            mean_r=MEAN[0], mean_g=MEAN[1], mean_b=MEAN[2],
            std_r=STD[0], std_g=STD[1], std_b=STD[2])
        base_iter = mx.io.ImageDetRecordIter(
            args.data_train, (3, size, size), args.batch_size,
            shuffle=True, rand_crop=1, rand_mirror=True,
            output_dtype="uint8" if args.feed == "u8" else "float32",
            **norm)
        if args.feed == "u8":
            # raw pixels over the wire (4x fewer bytes), normalize on
            # device in the async prefetch op
            det_iter = mx.io.DevicePrefetchIter(
                base_iter, normalize=(MEAN, STD), normalize_axis=1)
        else:
            det_iter = base_iter

    def next_batch():
        if det_iter is None:
            xb, lb = synthetic_batch(rng, args.batch_size, size,
                                     args.num_classes)
            return nd.array(xb), nd.array(lb)
        try:
            batch = det_iter.next()
        except StopIteration:
            det_iter.reset()
            batch = det_iter.next()
        return batch.data[0], batch.label[0]

    first = last = None
    for epoch in range(args.epochs):
        tot, tic = 0.0, time.time()
        for _ in range(args.steps_per_epoch):
            x, labels = next_batch()
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                with autograd.pause():
                    loc_t, loc_m, cls_t = targets(anchors, labels, cls_preds)
                l = cls_loss(cls_preds, cls_t) + \
                    box_loss(box_preds * loc_m, loc_t * loc_m)
            l.backward()
            trainer.step(args.batch_size)
            tot += float(l.mean().asnumpy())
        avg = tot / args.steps_per_epoch
        print(f"epoch {epoch}: loss {avg:.4f}  "
              f"({args.steps_per_epoch * args.batch_size / (time.time() - tic):.1f} img/s)")
        first = avg if first is None else first
        last = avg
    assert last < first, "detection loss should decrease"
    # inference path: MultiBoxDetection + box_nms
    xb, _ = synthetic_batch(rng, 1, size, args.num_classes)
    det = net.detect(nd.array(xb), threshold=0.01)
    print("detections:", det.shape)


if __name__ == "__main__":
    main()
