"""Long-context causal LM via ring attention (SURVEY §5.7 — a capability
the reference did NOT have: its max sequence length was bounded by one
device's memory; here the sequence axis shards over the `sp` mesh axis and
K/V blocks stream around the ICI ring with O(T/n) memory per device).

The task is a synthetic long-range copy: the model must reproduce tokens
seen a configurable distance earlier in the sequence — solvable only by
attending across sequence shards, so learning proves the ring works.

    python examples/long_context/train.py --smoke     # 8 virtual devices
    python examples/long_context/train.py --mesh dp=2,sp=4 --seq-len 8192
"""
import argparse
import os
import sys
import time


def _parse_mesh(spec):
    axes = {}
    for part in spec.split(","):
        name, size = part.split("=")
        axes[name.strip()] = int(size)
    return axes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=250)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--copy-distance", type=int, default=96)
    ap.add_argument("--units", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--mesh", default="dp=2,sp=4")
    ap.add_argument("--sp-strategy", choices=["ring", "ulysses"],
                    default="ring",
                    help="sequence-parallel attention strategy (ulysses "
                         "needs heads %% sp == 0)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.steps = 180
        args.seq_len, args.copy_distance = 128, 48

    # the sp mesh needs multiple devices: virtualize on CPU if single-device
    # (must happen before the first backend query — mirrors __graft_entry__)
    axes = _parse_mesh(args.mesh)
    n_dev = 1
    for s in axes.values():
        n_dev *= s
    flag = f"--xla_force_host_platform_device_count={n_dev}"
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    import jax
    if len(jax.devices()) < n_dev:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from tpu_mx.parallel import (P, attention, make_mesh,
                                 set_sp_strategy)
    from tpu_mx.parallel.ring_attention import dispatch_counts

    set_sp_strategy(args.sp_strategy)

    mesh = make_mesh(axes, devices=jax.devices()[:n_dev])
    B, T, U, H, V = (args.batch_size, args.seq_len, args.units, args.heads,
                     args.vocab)
    D = U // H
    rng = np.random.RandomState(0)

    def batch():
        x = rng.randint(2, V, (B, T)).astype(np.int32)
        # copy task: position t must predict the token at t - distance
        y = np.roll(x, args.copy_distance, axis=1)
        y[:, :args.copy_distance] = 0
        return jnp.asarray(x), jnp.asarray(y)

    params = {
        "embed": jnp.asarray(rng.randn(V, U) * 0.05, jnp.float32),
        "pos": jnp.asarray(rng.randn(T, U) * 0.05, jnp.float32),
        "qkv": jnp.asarray(rng.randn(U, 3 * U) * (U ** -0.5), jnp.float32),
        "out": jnp.asarray(rng.randn(U, U) * (U ** -0.5), jnp.float32),
        "head": jnp.asarray(rng.randn(U, V) * (U ** -0.5), jnp.float32),
    }

    def forward(p, x):
        h = p["embed"][x] + p["pos"][None]
        qkv = (h @ p["qkv"]).reshape(B, T, 3, H, D)
        q, k, v = (jnp.transpose(qkv[:, :, i], (0, 2, 1, 3))
                   for i in range(3))
        o = attention(q, k, v, mesh=mesh, causal=True)   # ring over sp
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(B, T, U)
        h = h + o @ p["out"]
        return h @ p["head"]

    def loss_fn(p, x, y):
        logits = forward(p, x)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, y[..., None], axis=-1)[..., 0]
        mask = (jnp.arange(T) >= args.copy_distance)[None]
        return -(ll * mask).sum() / mask.sum() / B

    data_sh = jax.sharding.NamedSharding(
        mesh, P("dp" if "dp" in mesh.axis_names else None,
                "sp" if "sp" in mesh.axis_names else None))

    tmap = jax.tree_util.tree_map
    opt = {"m": tmap(jnp.zeros_like, params),
           "v": tmap(jnp.zeros_like, params)}

    @jax.jit
    def step(p, opt, t, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        m = tmap(lambda m_, g_: 0.9 * m_ + 0.1 * g_, opt["m"], g)
        v = tmap(lambda v_, g_: 0.999 * v_ + 0.001 * g_ * g_, opt["v"], g)
        mh = tmap(lambda m_: m_ / (1 - 0.9 ** t), m)
        vh = tmap(lambda v_: v_ / (1 - 0.999 ** t), v)
        p = tmap(lambda w, m_, v_: w - args.lr * m_ / (jnp.sqrt(v_) + 1e-8),
                 p, mh, vh)
        return l, p, {"m": m, "v": v}

    losses, tic = [], time.time()
    for i in range(args.steps):
        x, y = batch()
        x = jax.device_put(x, data_sh)
        y = jax.device_put(y, data_sh)
        l, params, opt = step(params, opt, jnp.float32(i + 1), x, y)
        losses.append(float(l))
    toks = args.steps * B * T / (time.time() - tic)
    print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f}  ({toks:.0f} tok/s)  "
          f"{args.sp_strategy}_dispatches="
          f"{dispatch_counts[args.sp_strategy]}")
    assert dispatch_counts[args.sp_strategy] > 0, \
        f"{args.sp_strategy} attention path did not engage"
    if args.smoke:
        # the tuned smoke config must learn decisively; arbitrary user
        # configs (longer T, larger distance) legitimately need more steps
        assert losses[-1] < 0.7 * losses[0], "long-range copy did not learn"
    elif losses[-1] > 0.9 * losses[0]:
        print(f"note: little progress in {args.steps} steps — harder "
              "configs need more steps/lr tuning")


if __name__ == "__main__":
    main()
