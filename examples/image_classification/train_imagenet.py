"""ImageNet-style classification training (BASELINE config 1; reference
analog: example/image-classification/train_imagenet.py + common/fit.py).

Uses the native C++ RecordIO pipeline when --data-train points at a .rec
file; otherwise synthetic data sized like ImageNet batches.  The train
step is the fused XLA path (forward+backward+update in one program) via
`tpu_mx.parallel.CompiledTrainStep`, with bf16 compute and fp32 master
weights — the AMP-equivalent default on TPU.

    python examples/image_classification/train_imagenet.py \
        --network resnet50_v1 --batch-size 128 [--data-train train.rec]
"""
import argparse
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import gluon, nd
from tpu_mx.gluon.model_zoo import vision
from tpu_mx.parallel import CompiledTrainStep


MEAN = (123.68, 116.78, 103.94)
STD = (58.39, 57.12, 57.37)


def data_iter(args):
    shape = (3, args.image_shape, args.image_shape)
    if args.data_train:
        norm = {} if args.feed == "u8" else dict(
            mean_r=MEAN[0], mean_g=MEAN[1], mean_b=MEAN[2],
            std_r=STD[0], std_g=STD[1], std_b=STD[2])
        return mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, resize=args.image_shape + 32,
            preprocess_threads=args.data_nthreads,
            output_dtype="uint8" if args.feed == "u8" else "float32",
            output_layout=args.layout, **norm)
    n = args.batch_size * (2 if args.smoke else 20)
    rng = np.random.RandomState(0)
    if args.layout == "NHWC":
        shape = (args.image_shape, args.image_shape, 3)
    x = rng.rand(n, *shape).astype(np.float32)
    y = rng.randint(0, args.num_classes, n).astype(np.float32)
    return mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                             label_name="softmax_label")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="resnet50_v1")
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--image-shape", type=int, default=224)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--data-train", default=None)
    ap.add_argument("--data-nthreads", type=int, default=8)
    ap.add_argument("--disp-batches", type=int, default=20)
    ap.add_argument("--model-prefix", default=None)
    ap.add_argument("--layout", default="NCHW", choices=["NCHW", "NHWC"],
                    help="NHWC is the TPU-native layout (pairs with the "
                         "s2d stem for the fast path)")
    ap.add_argument("--stem", default="classic", choices=["classic", "s2d"])
    ap.add_argument("--feed", default="f32", choices=["f32", "u8"],
                    help="u8 ships raw pixels and normalizes on device: "
                         "4x fewer host/interconnect bytes per batch")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.network, args.num_classes = "resnet18_v1", 100
        args.batch_size, args.image_shape = 8, 64
        args.lr = 0.02  # full-run lr diverges on the 16-sample smoke set

    from tpu_mx.layout import default_layout
    with default_layout(args.layout):
        if args.stem != "classic":
            # no silent fallback: an explicit --stem must be honored or fail
            net = vision.get_model(args.network, classes=args.num_classes,
                                   stem=args.stem)
        else:
            net = vision.get_model(args.network, classes=args.num_classes)
    net.initialize(init="xavier")
    in_shape = (args.batch_size, args.image_shape, args.image_shape, 3) \
        if args.layout == "NHWC" else (args.batch_size, 3,
                                       args.image_shape, args.image_shape)
    net(nd.array(np.zeros(in_shape, np.float32)))  # finalize deferred shapes
    net.cast("bfloat16")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=args.lr, momentum=0.9,
                              wd=1e-4, multi_precision=True)
    step = CompiledTrainStep(net, loss_fn, opt)

    # device-feed double buffering: the prefetch thread device_puts (and
    # bf16-casts) batch k+1 while the chip runs batch k
    norm = dict(normalize=(MEAN, STD),
                normalize_axis=-1 if args.layout == "NHWC" else 1) \
        if (args.feed == "u8" and args.data_train) else {}
    it = mx.io.DevicePrefetchIter(data_iter(args), cast_data="bfloat16",
                                  **norm)
    for epoch in range(args.epochs):
        it.reset()
        tic, n, last_loss = time.time(), 0, float("nan")
        for i, batch in enumerate(it):
            last_loss = step.step(batch.data[0], batch.label[0])
            n += args.batch_size
            if (i + 1) % args.disp_batches == 0:
                print(f"epoch {epoch} batch {i + 1}: "
                      f"loss {float(last_loss.asnumpy()):.4f} "
                      f"{n / (time.time() - tic):.0f} img/s")
        loss_val = float(last_loss.asnumpy())  # sync point
        print(f"epoch {epoch}: loss {loss_val:.4f} "
              f"{n / (time.time() - tic):.0f} img/s")
        if args.model_prefix:
            step.sync_to_net()
            net.save_parameters(f"{args.model_prefix}-{epoch:04d}.params")


if __name__ == "__main__":
    main()
