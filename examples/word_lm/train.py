"""PTB word-level language model (BASELINE config 2; reference analog:
example/gluon/word_language_model/train.py): multi-layer LSTM, truncated
BPTT with detached state, gradient clipping, perplexity metric.

Points --data at a PTB-format text file (one sentence per line); without
one it trains on a synthetic Markov corpus so the script runs anywhere.

    python examples/word_lm/train.py --epochs 2 [--smoke]
"""
import argparse
import math
import os
import time

import numpy as np

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.models.lstm_lm import RNNModel


def corpus(args):
    if args.data and os.path.exists(args.data):
        with open(args.data) as f:
            words = f.read().replace("\n", " <eos> ").split()
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
        ids = np.array([vocab[w] for w in words], np.int32)
        return ids, len(vocab)
    # synthetic Markov chain: learnable transition structure
    V = 200 if args.smoke else 1000
    n = 20000 if args.smoke else 200000
    rng = np.random.RandomState(0)
    trans = rng.dirichlet(np.ones(8), size=V)
    nxt = np.stack([rng.choice(V, 8, replace=False) for _ in range(V)])
    ids = np.empty(n, np.int32)
    ids[0] = 0
    for i in range(1, n):
        ids[i] = nxt[ids[i - 1], rng.choice(8, p=trans[ids[i - 1]])]
    return ids, V


def batchify(ids, batch_size):
    nb = len(ids) // batch_size
    return ids[:nb * batch_size].reshape(batch_size, nb).T  # (T, B)


def detach(state):
    if isinstance(state, (list, tuple)):
        return [detach(s) for s in state]
    return state.detach()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None)
    ap.add_argument("--emsize", type=int, default=200)
    ap.add_argument("--nhid", type=int, default=200)
    ap.add_argument("--nlayers", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--bptt", type=int, default=35)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--dropout", type=float, default=0.2)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.emsize = args.nhid = 64
        args.epochs = 1

    ids, vocab_size = corpus(args)
    data = batchify(ids, args.batch_size)
    print(f"corpus: {len(ids)} tokens, vocab {vocab_size}")

    model = RNNModel(mode="lstm", vocab_size=vocab_size,
                     num_embed=args.emsize, num_hidden=args.nhid,
                     num_layers=args.nlayers, dropout=args.dropout)
    model.initialize(init="xavier")
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    ppls = []
    for epoch in range(args.epochs):
        state = model.begin_state(args.batch_size)
        total_loss, total_tok = 0.0, 0
        tic = time.time()
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = nd.array(data[i:i + args.bptt])                # (T, B)
            y = nd.array(data[i + 1:i + 1 + args.bptt].reshape(-1))
            state = detach(state)
            with autograd.record():
                out, state = model(x, state)
                loss = loss_fn(out.reshape(-1, vocab_size), y)
            loss.backward()
            gluon.utils.clip_global_norm(
                [p.grad for p in model.collect_params().values()
                 if p.grad_req != "null"],
                args.clip * args.batch_size * args.bptt)
            trainer.step(args.batch_size * args.bptt)
            total_loss += float(loss.mean().asnumpy()) * y.shape[0]
            total_tok += y.shape[0]
        ppl = math.exp(total_loss / total_tok)
        tok_s = total_tok / (time.time() - tic)
        print(f"epoch {epoch}: ppl {ppl:.1f}  ({tok_s:.0f} tok/s)")
        ppls.append(ppl)
    assert ppls[-1] < vocab_size, "model should beat the uniform baseline"
    print("final perplexity:", ppls[-1])


if __name__ == "__main__":
    main()
