"""Elastic fleet membership (tpu_mx/parallel/fleet.py, ISSUE 17): the
membership-epoch protocol, exact-replay resharding of the data stream,
generation-tagged barriers, the chaos preempt/partition knobs, and — in the
slow tier — the cross-process kill-and-rejoin proof driven through
``tools/launch.py --supervise`` (docs/robustness.md "Elastic fleets")."""
import importlib
import json
import os
import pickle
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, nd, resume, supervisor
from tpu_mx import gluon, telemetry
from tpu_mx.base import MXNetError
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn
from tpu_mx.io import NDArrayIter
from tpu_mx.parallel import fleet as fleet_mod
from tpu_mx.parallel.fleet import Fleet, MembershipChange

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cval(name, **labels):
    m = telemetry.get(name, **labels)
    return 0 if m is None else m.value


def _import_launch():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return importlib.import_module("launch")
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# the membership-epoch protocol
# ---------------------------------------------------------------------------
def test_membership_epoch_lifecycle(tmp_path):
    """Launch -> lose a worker (lease expiry) -> quiesce -> reshard ->
    rejoin at the NEXT epoch: the whole protocol on one store."""
    root = tmp_path / "fleet"
    f0 = Fleet(root, member=0, controller=True, lease=0.2)
    assert f0.generation == 0 and f0.world() == []

    ep = f0.advance(world=[0, 1], reason="launch")
    assert ep["generation"] == 1 and ep["world"] == [0, 1]
    # optimistic admission: worker 1 has not booted yet, but it is
    # PENDING (no record at all), never "lost" — the lease judges only
    # members that have joined at least once
    assert f0.lost() == []

    f0.join()
    assert f0.acked_generation == 1 and f0.shard() == (0, 2)
    f1 = Fleet(root, member=1, lease=0.2)
    f1.join()
    assert f1.shard() == (1, 2)
    assert sorted(f0.live()) == [0, 1]

    # worker 1 goes silent; its lease expires; the controller evicts it
    time.sleep(0.3)
    f0.heartbeat()
    assert f0.lost() == [1]
    ep = f0.reconcile()
    assert ep["generation"] == 2 and ep["world"] == [0]

    # worker 0 notices at the next step boundary and quiesces
    with pytest.raises(MembershipChange) as ei:
        f0.check()
    assert ei.value.generation == 2 and ei.value.world_size == 1
    assert isinstance(ei.value, elastic.WorkerFailure)  # classify seam
    f0.ack()
    assert f0.shard() == (0, 1)
    f0.check()  # adopted: quiet again

    # worker 1 comes back: pending until the controller opens the NEXT
    # epoch (late joiners are admitted only at an epoch bump)
    f1.join()
    assert f1.acked_generation == 1  # still holds its stale epoch
    assert f0.joiners() == [1]
    ep = f0.reconcile()
    assert ep["generation"] == 3 and ep["world"] == [0, 1]
    assert ep["reason"] == "rejoin"
    f1.await_admission(timeout=5)
    assert f1.acked_generation == 3 and f1.shard() == (1, 2)

    # and worker 0 quiesces/reshards once more for the scale-up
    with pytest.raises(MembershipChange):
        f0.on_step()
    f0.ack()
    assert f0.shard() == (0, 2)


def test_fleet_handle_misuse_raises(tmp_path):
    f = Fleet(tmp_path / "f", controller=True)
    with pytest.raises(ValueError):
        f.join()  # no member slot
    with pytest.raises(elastic.WorkerFailure):
        f.ack()  # no epoch on disk yet
    w = Fleet(tmp_path / "f", member=3)
    w.join()
    with pytest.raises(elastic.WorkerFailure):
        w.shard()  # never admitted


def test_fleet_from_env(tmp_path):
    env = {fleet_mod.ENV_DIR: str(tmp_path / "fl"),
           fleet_mod.ENV_MEMBER: "2", fleet_mod.ENV_LEASE: "3.5"}
    f = Fleet.from_env(env)
    assert (f.member, f.lease) == (2, 3.5)
    assert Fleet.from_env({}) is None  # static-world processes


def test_leave_is_pending_not_lost(tmp_path):
    """A clean leaver withdraws its record; with no record it is pending,
    so the controller's reconcile does not burn an epoch evicting a
    worker that already said goodbye."""
    root = tmp_path / "f"
    f0 = Fleet(root, member=0, controller=True, lease=0.2)
    f0.advance(world=[0, 1])
    f0.join()
    f1 = Fleet(root, member=1, lease=0.2)
    f1.join()
    f1.leave()
    time.sleep(0.25)
    f0.heartbeat()
    assert f0.lost() == []
    assert f0.reconcile() is None  # membership unchanged


# ---------------------------------------------------------------------------
# satellite: generation-tagged barriers — zombies raise, never wedge
# ---------------------------------------------------------------------------
def test_barrier_stale_generation_raises_loudly(tmp_path):
    f = Fleet(tmp_path / "f", member=0, controller=True, lease=5.0)
    f.advance(world=[0], reason="launch")
    f.join()
    assert f.barrier_tag("grads") == "grads@1"
    elastic.barrier("grads", fleet=f)  # generations match: no-op, no raise

    f.advance(world=[0, 1], reason="scale-up")  # epoch moves under us
    with pytest.raises(elastic.WorkerFailure,
                       match="stale fleet generation 1"):
        elastic.barrier("grads", fleet=f)  # detected BEFORE the collective
    f.ack()
    assert f.barrier_tag("grads") == "grads@2"
    elastic.barrier("grads", fleet=f)


# ---------------------------------------------------------------------------
# exact-replay resharding of the data stream (io.NDArrayIter)
# ---------------------------------------------------------------------------
_X = np.arange(64, dtype=np.float32).reshape(64, 1)


def _iter(num_workers=1, rank=0, seed=5):
    return NDArrayIter(_X, batch_size=8, shuffle=True, seed=seed,
                       last_batch_handle="discard",
                       num_workers=num_workers, rank=rank)


def _gids(it):
    return [int(v) for v in it.global_batch_ids()]


def _mine(it):
    return [int(v) for v in it.getdata()[0].asnumpy().ravel()]


def _advance(it):
    if not it.iter_next():
        it.reset()
        assert it.iter_next()


def test_shards_compose_to_the_global_stream():
    """Every rank of a 2-world slices the SAME global selection the
    1-world consumes: concat of the rank slices == the oracle batch."""
    oracle = _iter()
    r0, r1 = _iter(2, 0), _iter(2, 1)
    assert r0.batch_size == 4  # batch_size is always the GLOBAL batch
    for _ in range(16):  # two epochs: reset parity rides the private RNG
        for it in (oracle, r0, r1):
            _advance(it)
        ref = _gids(oracle)
        assert _gids(r0) == ref and _gids(r1) == ref
        assert _mine(r0) + _mine(r1) == ref
        assert _mine(oracle) == ref


def test_set_shard_mid_epoch_continues_global_sequence():
    """The live 2->1->2 re-partition: only the local slice changes, the
    global cursor/permutation/RNG never move — the exact-replay
    invariant a membership change relies on."""
    oracle = _iter()
    it = _iter(2, 0)
    seq, ref = [], []
    for step in range(12):
        if step == 3:
            it.set_shard(0, 1)   # lost the peer: consume alone
        if step == 7:
            it.set_shard(1, 2)   # peer rejoined; we even switch rank
        _advance(it)
        _advance(oracle)
        seq.append(_gids(it))
        ref.append(_gids(oracle))
    assert seq == ref
    with pytest.raises(MXNetError, match="not\\s+divisible"):
        it.set_shard(0, 3)  # 8 % 3 != 0 — replay boundaries would shift


def test_state_v2_repartitions_across_worlds():
    """A v2 (sharded) state restores into ANY world at the same global
    batch — the capsule-driven N->M replay path."""
    src = _iter(2, 0)
    for _ in range(3):
        _advance(src)
    state = src.state_dict()
    assert state["version"] == 2
    assert state["shard"] == {"num_workers": 2, "rank": 0, "global_batch": 8}

    expect = []
    for _ in range(4):
        _advance(src)
        expect.append(_gids(src))

    for nw, rank in ((1, 0), (2, 1), (4, 3)):
        it = _iter(nw, rank)
        it.load_state_dict(state)  # keeps ITS OWN (rank, num_workers)
        got = []
        for _ in range(4):
            _advance(it)
            got.append(_gids(it))
            lb = 8 // nw
            assert _mine(it) == got[-1][rank * lb:(rank + 1) * lb]
        assert got == expect

    # captured at a different global batch: refused, not guessed
    other = NDArrayIter(_X, batch_size=16, shuffle=True, seed=5,
                        num_workers=2, rank=0,
                        last_batch_handle="discard")
    with pytest.raises(MXNetError, match="global batch"):
        other.load_state_dict(state)


def test_state_v1_into_sharded_iterator_refuses():
    """A v1 state has no shard map — it may be a per-worker LOCAL stream,
    so a sharded iterator refuses it; the blessed path (load unsharded,
    then set_shard) replays exactly."""
    src = _iter()
    for _ in range(2):
        _advance(src)
    state = src.state_dict()
    assert state["version"] == 1 and "shard" not in state

    with pytest.raises(MXNetError, match="v1 iterator state"):
        _iter(2, 0).load_state_dict(state)

    blessed = _iter()
    blessed.load_state_dict(state)  # unsharded: v1 means what it said
    blessed.set_shard(1, 2)
    _advance(src)
    _advance(blessed)
    assert _gids(blessed) == _gids(src)
    assert _mine(blessed) == _gids(src)[4:]


# ---------------------------------------------------------------------------
# capsules: v2 world map, v1 same-world compatibility + surfaced gap
# ---------------------------------------------------------------------------
def test_capsule_v2_records_the_world(tmp_path):
    it = _iter(2, 0)
    mgr = resume.CapsuleManager(str(tmp_path / "run"), iters=[it])
    cap = resume.read_capsule(mgr.write_epoch_file(3))
    assert cap["format"] == resume.CAPSULE_FORMAT
    assert cap["world"] == {"num_workers": 2, "rank": 0, "generation": 0}

    # fleet-attached capture records the ADOPTED epoch's coordinates
    f = Fleet(tmp_path / "fl", member=1, controller=True, lease=5.0)
    f.advance(world=[0, 1])
    f.join()
    mgr = resume.CapsuleManager(str(tmp_path / "run2"), iters=[it], fleet=f)
    cap = resume.read_capsule(mgr.write_epoch_file(0))
    assert cap["world"] == {"num_workers": 2, "rank": 1, "generation": 1}


def test_capsule_v1_epoch_restores_same_world(tmp_path):
    """Acceptance: pre-fleet capsule v1 files still restore on the
    unsharded (same-world) path — their fields mean what they always
    meant."""
    prefix = str(tmp_path / "run")
    src = _iter()
    for _ in range(3):
        _advance(src)
    mgr = resume.CapsuleManager(prefix, iters=[src])
    path = mgr.write_epoch_file(2)
    cap = json.loads(open(path).read())
    cap["format"] = resume.CAPSULE_FORMAT_V1
    cap.pop("world")
    with open(path, "w") as fh:
        fh.write(json.dumps(cap))

    dst = _iter()
    mgr2 = resume.CapsuleManager(prefix, iters=[dst])
    assert mgr2.restore(sup=None, resume_from=3) == 3
    assert telemetry.gauge("resume.resume_step_gap").value == 0
    _advance(src)
    _advance(dst)
    assert _gids(dst) == _gids(src)


def test_capsule_v1_step_under_sharded_world_surfaces_gap(tmp_path):
    """A v1 STEP capsule under a sharded pipeline cannot be
    re-partitioned: refused, and the unreplayable batches are SURFACED
    (resume.resume_step_gap), never guessed."""
    prefix = str(tmp_path / "run")
    it = _iter(2, 0)
    body = {"format": resume.CAPSULE_FORMAT_V1, "epoch": 0, "step": 3,
            "wall_time": 0.0,
            "rng": resume.encode_state(mx.random.get_state()),
            "iters": [resume.encode_state(it.state_dict())]}
    with open(resume.step_capsule_path(prefix), "w") as fh:
        fh.write(json.dumps(body))

    mgr = resume.CapsuleManager(prefix, iters=[it])
    assert mgr.restore(sup=None, resume_from=0) == 0
    assert telemetry.gauge("resume.resume_step_gap").value == 3


# ---------------------------------------------------------------------------
# satellite bugfix: kvstore world-size cache follows the membership epoch
# ---------------------------------------------------------------------------
def test_kvstore_cache_invalidated_on_generation_bump(tmp_path):
    kv = mx.kvstore.create("dist_sync")
    assert kv.num_workers == 1  # static single-process world
    f = Fleet(tmp_path / "fl", member=0, controller=True, lease=5.0)
    try:
        f.advance(world=[0, 1, 2, 3])
        f.join()  # bumps the process-global generation token
        assert kv.num_workers == 4  # cache re-read, fleet is authority
        f.advance(world=[0, 1])
        f.ack()
        assert kv.num_workers == 2
    finally:
        # drop the process-global fleet observation so later tests see a
        # static world again
        fleet_mod._live_world = None
        kv2 = mx.kvstore.create("dist_sync")
        assert kv2.num_workers == 1


# ---------------------------------------------------------------------------
# satellite: chaos knobs — preempt_worker_at_step / partition_worker
# ---------------------------------------------------------------------------
def test_chaos_partition_suppresses_heartbeats(tmp_path):
    f = Fleet(tmp_path / "f", member=1, controller=True, lease=5.0)
    f.advance(world=[1])
    f.join()
    beat0 = f.members()[1]["beat"]
    before = _cval("chaos.injections", kind="partition_worker")
    with chaos.enable(partition_worker=1) as cfg:
        assert chaos.partitioned(1) is True
        assert chaos.partitioned(0) is False
        assert chaos.partitioned(None) is False
        f.heartbeat()  # silently dropped — the ABSENCE is the fault
        f.heartbeat()
        assert f.members()[1]["beat"] == beat0
        assert cfg.partitions >= 3
        # counted once in injections{kind}, on the first suppressed beat
        assert _cval("chaos.injections",
                     kind="partition_worker") == before + 1
    assert chaos.partitioned(1) is False  # disarmed with the config
    f.heartbeat()
    assert f.members()[1]["beat"] == beat0 + 1


def test_chaos_preempt_sends_real_sigterm():
    fired = []
    prev = signal.signal(signal.SIGTERM, lambda s, _f: fired.append(s))
    try:
        before = _cval("chaos.injections", kind="preempt_worker")
        with chaos.enable(preempt_worker_at_step=3, preempt_rank=2) as cfg:
            chaos.maybe_preempt(2)
            chaos.maybe_preempt(0)  # other ranks don't advance the count
            chaos.maybe_preempt(2)
            assert not fired and cfg.fleet_steps_seen == 2
            chaos.maybe_preempt(2)  # rank 2's third step: SIGTERM
            time.sleep(0.05)
            assert fired == [signal.SIGTERM]
            assert cfg.preempts == 1
            assert _cval("chaos.injections",
                         kind="preempt_worker") == before + 1
            chaos.maybe_preempt(2)  # one-shot: the restart survives
            assert len(fired) == 1
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# supervisor classification: WorkerFailure + moved epoch == membership
# ---------------------------------------------------------------------------
def test_supervisor_classifies_membership_not_fault(tmp_path):
    """A peer dies MID-COLLECTIVE: the step raises a plain WorkerFailure
    (barrier timeout), the lease expires, and the supervisor classifies
    it as a membership event — reshard via restore_fn under the NEW
    world, no restart budget burned (max_restarts=0 proves it)."""
    root = tmp_path / "fleet"
    f0 = Fleet(root, member=0, controller=True, lease=0.15)
    f0.advance(world=[0, 1], reason="launch")
    f0.join()
    f1 = Fleet(root, member=1, lease=0.15)
    f1.join()  # ...and never beats again: the dead peer

    reshards0 = _cval("fleet.reshards")
    restore_worlds = []

    def restore_fn():
        # ack() ran BEFORE restore: the new world is already visible,
        # so the mesh rebuild / load_state_dict reshard happens here
        restore_worlds.append(f0.acked_world_size)
        return 0

    state = {"attempt": 0}

    def one_step():
        state["attempt"] += 1
        if state["attempt"] == 1:
            time.sleep(0.4)  # the peer's lease expires mid-collective
            f0.heartbeat()   # WE are alive — only the peer went silent
            raise elastic.WorkerFailure(
                "barrier 'grads@1' timed out after 0.4s: a worker is "
                "dead or hung")
        return 0.25

    sup = supervisor.Supervisor(None, restore_fn, fleet=f0,
                                max_restarts=0, resume=False, backoff=0.0)

    def epoch_fn(_epoch):
        for _ in range(2):
            sup.step(one_step)

    res = sup.run(epoch_fn, num_epoch=1)
    assert res.status == "completed"
    assert res.restarts == 0          # membership != fault: no budget burn
    assert restore_worlds == [1]
    assert f0.acked_generation == 2 and f0.acked_world_size == 1
    assert _cval("fleet.reshards") == reshards0 + 1


# ---------------------------------------------------------------------------
# reshard seam: dp=2 -> dp=1 -> dp=2 round-trip is bit-exact
# ---------------------------------------------------------------------------
def test_reshard_live_roundtrip_bit_exact():
    """Acceptance: weights AND optimizer state are bit-exact once back on
    the original mesh — the no-train reshard round-trip moves arrays
    between meshes without touching a single mantissa bit."""
    import jax
    from tpu_mx.parallel import CompiledTrainStep, make_mesh

    def build():
        mx.random.seed(123)
        net = nn.HybridSequential(prefix="fl_")
        net.add(nn.Dense(8, in_units=4, activation="relu", prefix="fc1_"))
        net.add(nn.Dense(2, in_units=8, prefix="fc2_"))
        net.initialize()
        net(nd.ones((1, 4)))
        return net

    def make_step(world):
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2]) \
            if world == 2 else make_mesh({"dp": 1},
                                         devices=jax.devices()[:1])
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        return CompiledTrainStep(net=build(),
                                 loss_fn=gluon.loss.SoftmaxCrossEntropyLoss(),
                                 optimizer=opt, mesh=mesh)

    rng = np.random.RandomState(7)
    x = nd.array(rng.rand(8, 4).astype(np.float32))
    y = nd.array(rng.randint(0, 2, (8,)).astype(np.float32))
    step2 = make_step(2)
    for _ in range(3):
        step2.step(x, y)  # momentum buffers move off zero
    ref = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, step2.state_dict()))

    reshards0 = _cval("fleet.reshards")
    step1 = fleet_mod.reshard_live(step2, lambda: make_step(1),
                                   from_world=2, to_world=1)
    back = fleet_mod.reshard_live(step1, lambda: make_step(2),
                                  from_world=1, to_world=2)
    assert _cval("fleet.reshards") == reshards0 + 2

    got = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, back.state_dict()))
    assert len(got) == len(ref)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)  # BIT-exact, optimizer included


# ---------------------------------------------------------------------------
# launcher pieces (pure)
# ---------------------------------------------------------------------------
def test_restart_backoff_jitter_bounds():
    import random as _random
    launch = _import_launch()
    rng = _random.Random(0)
    for attempt in range(1, 5):
        lo = 0.5 * 2 ** (attempt - 1) * 0.5
        hi = 0.5 * 2 ** (attempt - 1) * 1.5
        for _ in range(20):
            v = launch.restart_backoff(0.5, attempt, rng)
            assert lo <= v < hi


# ---------------------------------------------------------------------------
# slow tier: the cross-process kill-and-rejoin proof
# ---------------------------------------------------------------------------
_WORKER = textwrap.dedent("""
    import json, os, pickle, sys, time
    sys.path.insert(0, os.environ["TPUMX_REPO"])
    root = os.environ["TPUMX_TEST_ROOT"]
    member = int(os.environ["TPUMX_FLEET_MEMBER"])
    with open(os.path.join(root, f"started-{member}.log"), "a") as fh:
        fh.write(str(os.getpid()) + "\\n")

    # The CPU backend cannot run cross-process collectives, so this proof
    # exercises the fleet protocol (files) and the data stream (pure
    # function of the seed) WITHOUT jax.distributed: drop the coordinator
    # env before the tpu_mx import boots it.  That also keeps XLA's
    # preemption notifier from swallowing the chaos SIGTERM — default
    # SIGTERM disposition is the preemption being simulated.
    for k in ("TPUMX_COORDINATOR", "TPUMX_NUM_PROC", "TPUMX_PROC_ID"):
        os.environ.pop(k, None)

    import numpy as np
    from tpu_mx import checkpoint as ckpt
    from tpu_mx.io import NDArrayIter
    from tpu_mx.elastic import WorkerFailure
    from tpu_mx.parallel.fleet import Fleet, MembershipChange

    f = Fleet.from_env()
    f.join()
    f.await_admission(timeout=60)
    sync = time.monotonic() + 10  # don't step before the cohort is up —
    for m in f.world():           # but a peer that already finished and
        if m == f.member:         # left is not worth dying over, and the
            continue              # wait must not starve OUR OWN lease
        while m not in f.live() and time.monotonic() < sync:
            f.heartbeat()
            time.sleep(0.05)
    r, w = f.shard()

    GBS = 8
    X = np.arange(64, dtype=np.float32).reshape(64, 1)
    it = NDArrayIter(X, batch_size=GBS, shuffle=True, seed=5,
                     last_batch_handle="discard")
    spath = os.path.join(root, "stream.pkl")
    step = 0
    if os.path.exists(spath):      # restarted worker: adopt the published
        with open(spath, "rb") as fh:          # GLOBAL cursor (v2 state)
            pub = pickle.load(fh)
        it.load_state_dict(pub["state"])
        step = pub["step"]
    it.set_shard(r, w)

    # every incarnation consumes at least 8 batches past where it came in;
    # rank 0 additionally runs until it has lived the WHOLE churn story:
    # the rejoin epoch (generation >= 3) plus 3 batches back at full world
    target = max(16, step + 8)
    post_rejoin = 0
    led = open(os.path.join(root, f"ledger-{member}-{os.getpid()}.jsonl"),
               "a", buffering=1)
    pace = 0.25 if member == 0 else 0.05
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            f.on_step()
        except MembershipChange:
            f.ack()
            try:
                r, w = f.shard()
            except WorkerFailure:
                # evicted while quiesced (a pause outlived the lease):
                # rejoin at the next epoch instead of dying, and re-adopt
                # the published global cursor we fell behind on
                f.join()
                f.await_admission(timeout=60)
                r, w = f.shard()
                if os.path.exists(spath):
                    with open(spath, "rb") as fh:
                        pub = pickle.load(fh)
                    it.load_state_dict(pub["state"])
                    step = pub["step"]
            it.set_shard(r, w)
            led.write(json.dumps({"membership": True, "step": step,
                                  "gen": f.acked_generation,
                                  "world": w}) + "\\n")
            continue
        if member == 0:
            if step >= target and f.acked_generation >= 3 \
                    and post_rejoin >= 3:
                break
            if step >= 48:   # hard cap: let the assertions explain
                break
        elif step >= target:
            break
        if not it.iter_next():
            it.reset()
            assert it.iter_next()
        step += 1
        if member == 0 and f.acked_generation >= 3:
            post_rejoin += 1
        led.write(json.dumps(
            {"step": step, "gen": f.acked_generation, "rank": r,
             "world": w,
             "gids": [int(v) for v in it.global_batch_ids()],
             "mine": [int(v) for v in
                      it.getdata()[0].asnumpy().ravel()]}) + "\\n")
        if r == 0:  # publish the global stream for late joiners
            with ckpt.atomic_write(spath, mode="wb") as fh:
                pickle.dump({"step": step, "state": it.state_dict()}, fh)
        time.sleep(pace)
    f.leave()
    led.close()
""")


def _oracle_ids(steps=64):
    it = _iter()
    out = {}
    for s in range(1, steps + 1):
        _advance(it)
        out[s] = _gids(it)
    return out


def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _sub_env(extra=None):
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO, "TPUMX_REPO": REPO})
    env.update(extra or {})
    return env


@pytest.mark.slow
def test_supervised_fleet_kill_and_rejoin(tmp_path):
    """End-to-end churn under ``tools/launch.py --supervise``: chaos
    SIGTERMs rank 1 mid-run, the launcher evicts it (dp=2 -> dp=1),
    restarts it with the chaos knob stripped, admits it at the next epoch
    (dp=1 -> dp=2) — and every rank's sample-id ledger is IDENTICAL to an
    uninterrupted run's, with zero skipped or duplicated samples."""
    root = tmp_path / "run"
    root.mkdir()
    fdir = str(tmp_path / "fleet")
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "--supervise", "-n", "2", "--fleet-dir", fdir,
         "--max-restarts", "2", "--backoff", "3.0", "--lease", "2.0",
         "--join-timeout", "60",
         "--env", f"TPUMX_TEST_ROOT={root}",
         "--env", "TPUMX_CHAOS=preempt_worker_at_step=3,preempt_rank=1",
         sys.executable, str(worker)],
        env=_sub_env(), capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"

    # rank 1 really was SIGTERMed and restarted (two incarnations)
    pids1 = (root / "started-1.log").read_text().split()
    assert len(pids1) == 2, r.stderr
    assert len((root / "started-0.log").read_text().split()) == 1

    oracle = _oracle_ids()

    # rank 0's ledger: the uninterrupted global sequence, despite living
    # through dp=2 -> dp=1 -> dp=2 — no step skipped, none repeated
    rows0 = []
    for p in root.glob("ledger-0-*.jsonl"):
        rows0 += _read_jsonl(p)
    steps0 = sorted((row for row in rows0 if "gids" in row),
                    key=lambda row: row["step"])
    hi = steps0[-1]["step"]
    assert [row["step"] for row in steps0] == list(range(1, hi + 1))
    assert hi >= 16
    for row in steps0:
        assert row["gids"] == oracle[row["step"]]
    # zero skipped/duplicated samples in every full 64-sample epoch window
    for lo in range(1, hi - 6, 8):
        window = sum((oracle[s] for s in range(lo, lo + 8)), [])
        assert sorted(window) == list(range(64))
    worlds = [row["world"] for row in steps0]
    assert worlds[0] == 2, r.stderr      # launched at dp=2
    assert 1 in worlds, r.stderr         # consumed alone after the evict
    assert worlds[-1] == 2, r.stderr     # back at dp=2 after the rejoin
    memberships = [row for row in rows0 if row.get("membership")]
    assert len(memberships) >= 2  # the eviction AND the rejoin epochs
    assert memberships[-1]["gen"] >= 3

    # rank 1's SECOND incarnation: admitted at generation >= 3, resumed
    # from the published GLOBAL cursor, sliced the identical stream
    second = _read_jsonl(root / f"ledger-1-{pids1[1]}.jsonl")
    resumed = [row for row in second if "gids" in row]
    assert len(resumed) >= 4, "restarted worker barely consumed"
    for row in resumed:
        assert row["gen"] >= 3 and row["world"] == 2 and row["rank"] == 1
        assert row["gids"] == oracle[row["step"]]
        assert row["mine"] == oracle[row["step"]][4:]

    # the fleet store converged back to the full world
    gen = json.loads(open(os.path.join(fdir, "gen.json")).read())
    assert gen["world"] == [0, 1] and gen["generation"] >= 3


_BUDGET_WORKER = textwrap.dedent("""
    import json, os, sys, time
    member = int(os.environ["TPUMX_FLEET_MEMBER"])
    root = os.environ["TPUMX_TEST_ROOT"]
    with open(os.path.join(root, f"started-{member}.log"), "a") as fh:
        fh.write(str(os.getpid()) + "\\n")
    if member == 1:
        sys.exit(3)  # hopeless: dies before it ever joins

    sys.path.insert(0, os.environ["TPUMX_REPO"])
    for k in ("TPUMX_COORDINATOR", "TPUMX_NUM_PROC", "TPUMX_PROC_ID"):
        os.environ.pop(k, None)  # no collectives: see the churn worker
    from tpu_mx import checkpoint as ckpt
    from tpu_mx.parallel.fleet import Fleet, MembershipChange

    f = Fleet.from_env()
    f.join()
    f.await_admission(timeout=30)
    end = time.monotonic() + 2.0
    while time.monotonic() < end:
        try:
            f.on_step()
        except MembershipChange:
            f.ack()
        time.sleep(0.1)
    # the surviving world still commits durable work after the degrade
    with ckpt.atomic_write(os.path.join(root, "final-save.json"),
                           mode="w") as fh:
        fh.write(json.dumps({"world": sorted(f.world()),
                             "generation": f.acked_generation}))
    f.leave()
""")


@pytest.mark.slow
def test_supervised_restart_budget_degrades(tmp_path):
    """Restart-budget exhaustion: the launcher stops restarting the
    hopeless worker, dumps the black box, lets the healthy world finish
    its durable save — and the job still exits nonzero (a degraded run
    is not a clean one)."""
    root = tmp_path / "run"
    root.mkdir()
    fdir = str(tmp_path / "fleet")
    worker = tmp_path / "worker.py"
    worker.write_text(_BUDGET_WORKER)

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "--supervise", "-n", "2", "--fleet-dir", fdir,
         "--max-restarts", "1", "--backoff", "0.05", "--lease", "10",
         "--join-timeout", "5", "--min-workers", "1",
         "--env", f"TPUMX_TEST_ROOT={root}",
         sys.executable, str(worker)],
        env=_sub_env(), capture_output=True, text=True, timeout=180)
    assert r.returncode == 1, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "restart budget exhausted" in r.stderr

    # exactly max_restarts + 1 incarnations of the hopeless worker
    assert len((root / "started-1.log").read_text().split()) == 2
    # the degrade dumped the flight recorder next to the fleet store
    assert list(__import__("pathlib").Path(fdir).glob("*blackbox*.json"))
    # the healthy world finished and saved durably
    final = json.loads((root / "final-save.json").read_text())
    assert final["world"] == [0]
    gen = json.loads(open(os.path.join(fdir, "gen.json")).read())
    assert gen["world"] == [0]
