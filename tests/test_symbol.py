"""mx.symbol tests — composition, inference, executor, serialization.

Models the reference's tests/python/unittest/test_symbol.py and
test_executor.py coverage.
"""
import numpy as np
import pytest

import tpu_mx as mx


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.Variable("softmax_label"),
                                name="softmax")


def test_compose_and_listing():
    out = _mlp()
    assert out.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert out.list_outputs() == ["softmax_output"]
    assert out.list_auxiliary_states() == []


def test_infer_shape():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(
        data=(8, 20), softmax_label=(8,))
    assert arg_shapes == [(8, 20), (16, 20), (16,), (4, 16), (4,), (8,)]
    assert out_shapes == [(8, 4)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    d = mx.sym.var("data")
    c = mx.sym.Convolution(d, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="conv0")
    b = mx.sym.BatchNorm(c, name="bn0")
    f = mx.sym.FullyConnected(mx.sym.flatten(b), num_hidden=10, name="fc")
    assert b.list_auxiliary_states() == ["bn0_moving_mean", "bn0_moving_var"]
    arg_shapes, out_shapes, aux_shapes = f.infer_shape(data=(4, 3, 28, 28))
    assert arg_shapes[1] == (8, 3, 3, 3)
    assert aux_shapes == [(8,), (8,)]
    assert out_shapes == [(4, 10)]


def test_variable_shape_attr():
    d = mx.sym.var("data", shape=(2, 5))
    y = mx.sym.FullyConnected(d, num_hidden=3, name="fc")
    arg_shapes, out_shapes, _ = y.infer_shape()
    assert out_shapes == [(2, 3)]


def test_executor_forward_backward():
    out = _mlp()
    ex = out.simple_bind(mx.cpu(), data=(8, 20), softmax_label=(8,))
    rng = np.random.RandomState(0)
    ex.arg_dict["data"][:] = rng.randn(8, 20).astype("float32")
    ex.arg_dict["fc1_weight"][:] = rng.randn(16, 20).astype("float32") * 0.1
    ex.arg_dict["fc2_weight"][:] = rng.randn(4, 16).astype("float32") * 0.1
    ex.arg_dict["softmax_label"][:] = rng.randint(0, 4, (8,)).astype("float32")
    (y,) = ex.forward(is_train=True)
    np.testing.assert_allclose(y.asnumpy().sum(axis=1), np.ones(8), rtol=1e-5)
    ex.backward()
    g = ex.grad_dict["fc1_weight"].asnumpy()
    assert np.abs(g).sum() > 0
    # softmax output head: data grad == (p - onehot)/1
    p = y.asnumpy()
    lbl = ex.arg_dict["softmax_label"].asnumpy().astype(int)
    oh = np.eye(4)[lbl]
    gd = ex.grad_dict["data"].asnumpy()
    assert gd.shape == (8, 20)
    # fc2 bias grad equals column sums of (p - onehot)
    np.testing.assert_allclose(ex.grad_dict["fc2_bias"].asnumpy(),
                               (p - oh).sum(axis=0), rtol=1e-4, atol=1e-5)


def test_grad_req_add_and_null():
    out = _mlp()
    req = {n: "write" for n in out.list_arguments()}
    req["data"] = "null"
    req["fc1_weight"] = "add"
    ex = out.simple_bind(mx.cpu(), grad_req=req, data=(8, 20),
                         softmax_label=(8,))
    rng = np.random.RandomState(1)
    ex.arg_dict["data"][:] = rng.randn(8, 20).astype("float32")
    ex.arg_dict["fc1_weight"][:] = rng.randn(16, 20).astype("float32") * 0.1
    ex.arg_dict["fc2_weight"][:] = rng.randn(4, 16).astype("float32") * 0.1
    ex.forward(is_train=True)
    ex.backward()
    g1 = ex.grad_dict["fc1_weight"].asnumpy().copy()
    ex.forward(is_train=True)
    ex.backward()
    g2 = ex.grad_dict["fc1_weight"].asnumpy()
    np.testing.assert_allclose(g2, 2 * g1, rtol=1e-4, atol=1e-6)
    assert "data" not in ex.grad_dict


def test_operator_overloading():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = (a + b) * 2.0 - a / 2.0
    ex = c.simple_bind(mx.cpu(), a=(3,), b=(3,))
    ex.arg_dict["a"][:] = np.array([1.0, 2.0, 3.0], "float32")
    ex.arg_dict["b"][:] = np.array([4.0, 5.0, 6.0], "float32")
    (y,) = ex.forward()
    np.testing.assert_allclose(y.asnumpy(), [9.5, 13.0, 16.5], rtol=1e-6)


def test_group_and_getitem():
    a = mx.sym.var("a")
    s1 = a * 2.0
    s2 = a + 1.0
    g = mx.sym.Group([s1, s2])
    assert len(g.list_outputs()) == 2
    ex = g.simple_bind(mx.cpu(), a=(2,))
    ex.arg_dict["a"][:] = np.array([1.0, 2.0], "float32")
    y1, y2 = ex.forward()
    np.testing.assert_allclose(y1.asnumpy(), [2.0, 4.0])
    np.testing.assert_allclose(y2.asnumpy(), [2.0, 3.0])


def test_multi_output_split():
    a = mx.sym.var("a")
    parts = mx.sym.split(a, num_outputs=2, axis=1, name="sp")
    assert len(parts.list_outputs()) == 2
    right = parts[1]
    ex = right.simple_bind(mx.cpu(), a=(2, 4))
    ex.arg_dict["a"][:] = np.arange(8).reshape(2, 4).astype("float32")
    (y,) = ex.forward()
    np.testing.assert_allclose(y.asnumpy(), [[2, 3], [6, 7]])


def test_json_roundtrip(tmp_path):
    out = _mlp()
    path = str(tmp_path / "sym.json")
    out.save(path)
    loaded = mx.sym.load(path)
    assert loaded.list_arguments() == out.list_arguments()
    assert loaded.list_outputs() == out.list_outputs()
    ex = loaded.simple_bind(mx.cpu(), data=(4, 20), softmax_label=(4,))
    (y,) = ex.forward()
    assert y.shape == (4, 4)


def test_eval():
    a = mx.sym.var("a")
    y = a * 3.0
    (out,) = y.eval(a=mx.nd.array([1.0, 2.0]))
    np.testing.assert_allclose(out.asnumpy(), [3.0, 6.0])


def test_get_internals():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names
    fc1 = internals["fc1"]
    ex = fc1.simple_bind(mx.cpu(), data=(2, 20))
    (y,) = ex.forward()
    assert y.shape == (2, 16)


def test_attrs():
    a = mx.sym.var("a", shape=(2, 2))
    y = mx.sym.FullyConnected(a, num_hidden=2, name="fc",
                              attr={"__ctx_group__": "dev1"})
    assert y.attr("__ctx_group__") == "dev1"
    assert "fc" in y.attr_dict()


def test_regression_outputs():
    d = mx.sym.var("data")
    l = mx.sym.var("label")
    out = mx.sym.LinearRegressionOutput(d, l, name="lro")
    ex = out.simple_bind(mx.cpu(), data=(4, 3), label=(4, 3))
    rng = np.random.RandomState(2)
    x = rng.randn(4, 3).astype("float32")
    t = rng.randn(4, 3).astype("float32")
    ex.arg_dict["data"][:] = x
    ex.arg_dict["label"][:] = t
    (y,) = ex.forward(is_train=True)
    np.testing.assert_allclose(y.asnumpy(), x, rtol=1e-6)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), (x - t) / 4,
                               rtol=1e-5, atol=1e-6)


def test_print_summary_and_plot_network(capsys):
    """mx.viz.print_summary (REF:python/mxnet/visualization.py): layer
    table with shapes + param totals; plot_network raises a clear pointer
    without graphviz."""
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    out = mx.sym.softmax(fc2, name="softmax")
    total = mx.viz.print_summary(out, shape={"data": (2, 8)})
    captured = capsys.readouterr().out
    assert "fc1" in captured and "fc2" in captured
    # fc1: 16*8 + 16; fc2: 4*16 + 4
    assert total == 16 * 8 + 16 + 4 * 16 + 4
    assert f"Total params: {total}" in captured
    try:
        import graphviz  # noqa: F401
        has_gv = True
    except ImportError:
        has_gv = False
    if not has_gv:
        with pytest.raises(mx.MXNetError, match="print_summary"):
            mx.viz.plot_network(out)


def test_attr_scope_and_name_prefix():
    """mx.AttrScope attaches attrs to nodes created in scope (the
    group2ctx annotation surface); mx.name.Prefix prefixes auto names."""
    with mx.AttrScope(ctx_group="dev1", lr_mult="0.1"):
        a = mx.sym.Variable("a")
        fc = mx.sym.FullyConnected(a, num_hidden=4)
    assert fc.attr("ctx_group") == "dev1"
    assert fc.attr("lr_mult") == "0.1"
    # the scope annotates VARIABLES too (the group2ctx/lr_mult pattern
    # targets parameter variables), incl. auto-created weight/bias
    assert a.attr("ctx_group") == "dev1"
    attr_map = fc.attr_dict()
    wname = [k for k in fc.list_arguments() if k.endswith("_weight")][0]
    assert attr_map.get(wname, {}).get("ctx_group") == "dev1"
    # nesting: inner wins
    with mx.AttrScope(ctx_group="dev1"):
        with mx.AttrScope(ctx_group="dev2"):
            fc2 = mx.sym.FullyConnected(mx.sym.Variable("b"), num_hidden=2)
    assert fc2.attr("ctx_group") == "dev2"
    # outside scope: no attrs
    fc3 = mx.sym.FullyConnected(mx.sym.Variable("c"), num_hidden=2)
    assert fc3.attr("ctx_group") is None

    with mx.name.Prefix("stage1_"):
        s = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
    assert s.name.startswith("stage1_activation")


def test_util_np_scope():
    import tpu_mx.util as util
    assert not util.is_np_array()
    with util.np_array():
        assert util.is_np_array()
    assert not util.is_np_array()

    @util.use_np
    def inner():
        return util.is_np_array()
    assert inner() is True
    assert mx.lr_scheduler is not None and hasattr(mx.lr_scheduler,
                                                   "FactorScheduler")


@pytest.mark.parametrize("op,kwargs,shape", [
    ("mish", {}, (3, 4)),
    ("log_sigmoid", {}, (3, 4)),
    ("hard_swish", {}, (3, 4)),
    ("LRN", {"nsize": 3}, (1, 4, 3, 3)),
    ("im2col", {"kernel": (2, 2)}, (1, 2, 4, 4)),
])
def test_new_ops_nd_sym_parity(op, kwargs, shape):
    """The symbol stubs auto-generated for round-3 ops must compute the
    same values as the imperative path (the reference's nd/sym twin
    contract)."""
    rng = np.random.RandomState(0)
    x = rng.rand(*shape).astype(np.float32)
    nd_out = getattr(mx.nd, op)(mx.nd.array(x), **kwargs).asnumpy()
    data = mx.sym.Variable("data")
    sym = getattr(mx.sym, op)(data, **kwargs)
    ex = sym.simple_bind(mx.cpu(), data=shape)
    ex.arg_dict["data"][:] = x
    (y,) = ex.forward()
    np.testing.assert_allclose(y.asnumpy(), nd_out, rtol=1e-5, atol=1e-6)


def test_attr_scope_survives_json_roundtrip(tmp_path):
    with mx.AttrScope(ctx_group="dev1"):
        s = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=4,
                                  name="fc")
    f = str(tmp_path / "s.json")
    s.save(f)
    s2 = mx.sym.load(f)
    assert s2.attr("ctx_group") == "dev1"
    wname = [k for k in s2.list_arguments() if k.endswith("_weight")][0]
    assert s2.attr_dict().get(wname, {}).get("ctx_group") == "dev1"


def test_symbol_v1_aliases_bind_with_auto_params():
    """Deprecated 0.x aliases in the SYMBOL layer: auto-created
    weight/bias/gamma Variables must appear (old symbol JSON loads)."""
    import numpy as onp
    data = mx.sym.Variable("data")
    s = mx.sym.Convolution_v1(data, kernel=(3, 3), num_filter=4)
    ex = s.simple_bind(mx.cpu(), data=(1, 3, 8, 8))
    out = ex.forward(is_train=False,
                     data=onp.random.rand(1, 3, 8, 8).astype(onp.float32))
    assert out[0].shape == (1, 4, 6, 6)
    b = mx.sym.BatchNorm_v1(data)
    ex2 = b.simple_bind(mx.cpu(), data=(1, 3, 8, 8))
    assert ex2.forward(
        is_train=False,
        data=onp.random.rand(1, 3, 8, 8).astype(onp.float32))[0].shape \
        == (1, 3, 8, 8)
