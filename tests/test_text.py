"""contrib.text vocabulary + embeddings (REF:tests/python/unittest/
test_contrib_text.py patterns: counter -> vocab -> embedding matrix)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx.base import MXNetError
from tpu_mx.contrib import text


def test_count_tokens():
    c = text.count_tokens_from_str("a b b\nc c c", to_lower=False)
    assert c == {"a": 1, "b": 2, "c": 3}
    c2 = text.count_tokens_from_str("A a", to_lower=True)
    assert c2 == {"a": 2}


def test_vocabulary_order_and_limits():
    c = text.count_tokens_from_str("a b b c c c d")
    v = text.Vocabulary(c, most_freq_count=None, min_freq=1,
                        reserved_tokens=["<pad>"])
    # index 0 unk, 1 reserved, then by (-freq, token)
    assert v.idx_to_token[:3] == ["<unk>", "<pad>", "c"]
    assert v.to_indices("zzz") == 0  # unknown
    assert v.to_indices(["c", "b"]) == [2, 3]
    assert v.to_tokens([2, 3]) == ["c", "b"]
    v2 = text.Vocabulary(c, most_freq_count=3)
    assert len(v2) == 4  # unk + 3 most frequent counter tokens (ref contract)
    v3 = text.Vocabulary(c, min_freq=2)
    assert set(v3.idx_to_token) == {"<unk>", "b", "c"}
    with pytest.raises(MXNetError):
        text.Vocabulary(c, reserved_tokens=["<unk>"])


def _write_vecs(tmp_path):
    p = tmp_path / "vecs.txt"
    p.write_text("hello 1 2 3\nworld 4 5 6\n")
    return str(p)


def test_custom_embedding(tmp_path):
    emb = text.CustomEmbedding(_write_vecs(tmp_path))
    assert emb.vec_len == 3
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["world", "missing"]).asnumpy(),
        [[4, 5, 6], [0, 0, 0]])
    # matrix is Embedding-ready: rows match token indices
    mat = emb.idx_to_vec.asnumpy()
    assert mat.shape == (len(emb), 3)
    np.testing.assert_allclose(mat[emb.token_to_idx["hello"]], [1, 2, 3])
    emb.update_token_vectors("hello", np.array([9., 9., 9.]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [9, 9, 9])
    with pytest.raises(MXNetError):
        emb.update_token_vectors("nope", np.zeros(3))


def test_embedding_with_vocabulary(tmp_path):
    c = text.count_tokens_from_str("hello hello unseen")
    v = text.Vocabulary(c)
    emb = text.CustomEmbedding(_write_vecs(tmp_path), vocabulary=v,
                               init_unknown_vec=np.ones)
    # vocab token with no pretrained vec gets the unknown init
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("unseen").asnumpy(), [1, 1, 1])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3])
    # the vocabulary FILTERS the file: out-of-vocab rows ('world') are not
    # indexed, so the matrix matches the vocab size exactly
    assert len(emb) == len(v)
    assert "world" not in emb.token_to_idx
    assert emb.idx_to_vec.shape == (len(v), 3)


def test_composite_embedding(tmp_path):
    p2 = tmp_path / "v2.txt"
    p2.write_text("hello 7 8\n")
    c = text.count_tokens_from_str("hello world")
    v = text.Vocabulary(c)
    e1 = text.CustomEmbedding(_write_vecs(tmp_path))
    e2 = text.CustomEmbedding(str(p2))
    comp = text.CompositeEmbedding(v, [e1, e2])
    assert comp.vec_len == 5
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3, 7, 8])


def test_pretrained_catalog_documented_divergence():
    with pytest.raises(MXNetError, match="hermetic"):
        text.get_pretrained_file_names("glove")


def test_count_tokens_regex_delim_escaped():
    # '.' as a delimiter must be literal, not the regex wildcard
    c = text.count_tokens_from_str("a.b c", seq_delim=".")
    assert c == {"a": 1, "b": 1, "c": 1}
