"""Custom operator tests (reference analog:
tests/python/unittest/test_operator.py::test_custom_op)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd, autograd
from tpu_mx.base import MXNetError


@mx.operator.register("sq")
class SquareProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        class Square(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * in_data[0])

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0],
                            2.0 * in_data[0] * out_grad[0])
        return Square()


def test_custom_forward():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = nd.Custom(x, op_type="sq")
    np.testing.assert_array_equal(y.asnumpy(), x.asnumpy() ** 2)


def test_custom_backward():
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="sq")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * xv, rtol=1e-6)


def test_custom_composes_with_builtin_ops():
    xv = np.random.RandomState(0).rand(4, 4).astype(np.float32)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x * 2.0, op_type="sq")  # (2x)^2 = 4x^2
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 8 * xv, rtol=1e-5)


def test_custom_unregistered_raises():
    with pytest.raises(MXNetError, match="not registered"):
        nd.Custom(nd.array(np.ones(3)), op_type="nope")


def test_custom_multi_output():
    @mx.operator.register("split2")
    class Split2Prop(mx.operator.CustomOpProp):
        def list_outputs(self):
            return ["a", "b"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0], in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Split2(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * 1.0)
                    self.assign(out_data[1], req[1], in_data[0] * 3.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0],
                                out_grad[0] + 3.0 * out_grad[1])
            return Split2()

    x = nd.array(np.ones((2, 2), np.float32))
    x.attach_grad()
    with autograd.record():
        a, b = nd.Custom(x, op_type="split2")
        loss = (a + b).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.full((2, 2), 4.0), rtol=1e-6)
