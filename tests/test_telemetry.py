"""Unified runtime telemetry (ISSUE 3): registry semantics, the three
exporters (JSONL / Prometheus exposition / profiler chrome-trace merge),
and the instrumented hot paths — fusion, checkpoint, elastic, kvstore,
train step, chaos, Speedometer."""
import json
import os
import re
import threading
import types

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd, telemetry


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Every test starts from an empty registry (it is process-global)."""
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
def test_counter_and_gauge_basics():
    c = telemetry.counter("fusion.flushes")
    c.inc()
    c.inc(4)
    assert telemetry.counter("fusion.flushes") is c  # create-or-fetch
    assert c.value == 5
    g = telemetry.gauge("train_step.examples_per_sec")
    g.set(123.5)
    g.set(99)
    assert telemetry.gauge("train_step.examples_per_sec").value == 99.0


def test_labels_make_distinct_series():
    telemetry.counter("chaos.injections", kind="crash").inc()
    telemetry.counter("chaos.injections", kind="torn_write").inc(2)
    assert telemetry.counter("chaos.injections", kind="crash").value == 1
    assert telemetry.counter("chaos.injections",
                             kind="torn_write").value == 2
    # get() never creates
    assert telemetry.get("chaos.injections", kind="oserror") is None


def test_kind_conflict_raises():
    telemetry.counter("fusion.flushes")
    with pytest.raises(TypeError, match="already registered"):
        telemetry.gauge("fusion.flushes")


def test_histogram_buckets_minmax_and_monotonicity():
    h = telemetry.histogram("checkpoint.save_seconds")
    for v in (0.0005, 0.002, 0.002, 5.0, 100.0):  # 100s -> +Inf overflow
        h.observe(v)
    assert h.count == 5
    assert h.min == 0.0005 and h.max == 100.0
    assert abs(h.sum - 105.0045) < 1e-9
    cum = h.cumulative()
    counts = [c for _, c in cum]
    assert counts == sorted(counts), "cumulative counts must be monotone"
    assert cum[-1][0] == "+Inf" and cum[-1][1] == 5
    # fixed log-scale ladder: bucket edges are the documented constant
    assert h.buckets == telemetry.LATENCY_BUCKETS


def test_snapshot_records_are_schema_valid():
    telemetry.counter("fusion.flushes").inc()
    telemetry.gauge("speedometer.samples_per_sec").set(10.0)
    telemetry.histogram("train_step.seconds").observe(0.01)
    telemetry.counter("chaos.injections", kind="crash").inc()
    recs = telemetry.snapshot()
    assert len(recs) == 4
    for rec in recs:
        telemetry.validate_record(rec)
        json.dumps(rec)  # JSONL-serializable
    ts = {rec["ts"] for rec in recs}
    assert len(ts) == 1, "one snapshot shares one timestamp"


def test_validate_record_rejects_bad_records():
    with pytest.raises(ValueError, match="missing name"):
        telemetry.validate_record({"type": "counter", "value": 1, "ts": 1.0})
    with pytest.raises(ValueError, match="bad type"):
        telemetry.validate_record(
            {"name": "x", "type": "timer", "value": 1, "ts": 1.0})
    with pytest.raises(ValueError, match="numeric 'value'|missing numeric"):
        telemetry.validate_record(
            {"name": "x", "type": "counter", "value": "many", "ts": 1.0})
    base = {"name": "h", "type": "histogram", "value": 3, "ts": 1.0,
            "sum": 1.0}
    with pytest.raises(ValueError, match="not monotone"):
        telemetry.validate_record(
            dict(base, buckets=[[0.1, 2], [0.3, 1], ["+Inf", 3]]))
    with pytest.raises(ValueError, match=r"\+Inf"):
        telemetry.validate_record(
            dict(base, buckets=[[0.1, 2], [0.3, 3]]))
    with pytest.raises(ValueError, match="!= value"):
        telemetry.validate_record(
            dict(base, buckets=[[0.1, 2], ["+Inf", 2]]))


# ---------------------------------------------------------------------------
# JSONL exporter
# ---------------------------------------------------------------------------
def test_jsonl_flush_appends_and_final_is_complete(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    telemetry.counter("fusion.flushes").inc()
    telemetry.histogram("checkpoint.save_seconds").observe(0.002)
    assert telemetry.flush(path=path) is not None
    telemetry.counter("fusion.flushes").inc()
    telemetry.flush(path=path)

    def per_name(lines):
        names = [json.loads(ln)["name"] for ln in lines]
        return {n: names.count(n) for n in set(names)}

    lines = [ln for ln in open(path).read().splitlines() if ln]
    # two snapshots, appended (flush also refreshes bridge gauges like
    # tracing.events_dropped, so count per-series, not raw lines)
    assert per_name(lines)["fusion.flushes"] == 2
    assert per_name(lines)["checkpoint.save_seconds"] == 2
    # final snapshot: the whole history is rewritten atomically
    telemetry.flush(path=path, final=True)
    lines = [ln for ln in open(path).read().splitlines() if ln]
    assert per_name(lines)["fusion.flushes"] == 3
    for ln in lines:
        telemetry.validate_record(json.loads(ln))
    # the two counter snapshots carry the cumulative values 1 then 2
    vals = [json.loads(ln)["value"] for ln in lines
            if json.loads(ln)["name"] == "fusion.flushes"]
    assert vals == [1, 2, 2]
    assert not list(tmp_path.glob("*.tmp.*")), "atomic rewrite left debris"


def test_snapshot_consistent_under_concurrent_observes():
    """A snapshot taken while another thread observes must still satisfy
    the schema's +Inf-count == value invariant (records are built under
    the registry lock, never from torn reads)."""
    h = telemetry.histogram("train_step.seconds")
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            h.observe(0.001)

    t = threading.Thread(target=worker)
    t.start()
    try:
        for _ in range(400):
            for rec in telemetry.snapshot():
                telemetry.validate_record(rec)
            telemetry.exposition()
    finally:
        stop.set()
        t.join()


def test_atexit_hook_does_not_duplicate_explicit_final_flush(tmp_path,
                                                             monkeypatch):
    path = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("TPUMX_TELEMETRY", path)
    telemetry.counter("fusion.flushes").inc()
    telemetry.flush(final=True)
    before = open(path).read()
    telemetry._flush_at_exit()  # what interpreter shutdown would run
    assert open(path).read() == before, \
        "atexit must not append a second final snapshot"


def test_flush_without_sink_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("TPUMX_TELEMETRY", raising=False)
    telemetry.counter("fusion.flushes").inc()
    assert telemetry.flush() is None
    assert telemetry.configured_path() is None
    monkeypatch.setenv("TPUMX_TELEMETRY", str(tmp_path / "m.jsonl"))
    assert telemetry.configured_path() == str(tmp_path / "m.jsonl")
    assert telemetry.flush() is not None
    assert (tmp_path / "m.jsonl").exists()


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------
_PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+="[^"]*"'
    r'(,[a-zA-Z0-9_]+="[^"]*")*\})? [-+0-9.eE]+(inf)?$')


def test_exposition_parses_as_prometheus_text():
    telemetry.counter("fusion.flushes").inc(7)
    telemetry.gauge("train_step.examples_per_sec").set(1234.5)
    telemetry.histogram("train_step.seconds").observe(0.02)
    telemetry.counter("chaos.injections", kind="torn_write").inc()
    text = telemetry.exposition()
    assert text.endswith("\n")
    families = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            families[name] = kind
        else:
            assert _PROM_SAMPLE.match(line), f"unparseable line: {line!r}"
    assert families["tpumx_fusion_flushes_total"] == "counter"
    assert families["tpumx_train_step_examples_per_sec"] == "gauge"
    assert families["tpumx_train_step_seconds"] == "histogram"
    # histogram family completeness + cumulative bucket monotonicity
    buckets = [int(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
               if ln.startswith("tpumx_train_step_seconds_bucket")]
    assert buckets == sorted(buckets) and buckets[-1] == 1
    assert 'le="+Inf"' in text
    assert "tpumx_train_step_seconds_sum" in text
    assert "tpumx_train_step_seconds_count 1" in text
    assert 'tpumx_chaos_injections_total{kind="torn_write"} 1' in text


# ---------------------------------------------------------------------------
# spans + profiler merge
# ---------------------------------------------------------------------------
def test_span_observes_histogram_and_merges_into_profiler():
    from tpu_mx import profiler
    with profiler._lock:
        profiler._events.clear()
        profiler._agg.clear()
    profiler._state["running"], profiler._state["paused"] = True, False
    try:
        with telemetry.span("checkpoint.save_seconds"):
            pass
        h = telemetry.get("checkpoint.save_seconds")
        assert h is not None and h.count == 1
        names = [(e["name"], e.get("cat")) for e in profiler._events]
        assert ("checkpoint.save_seconds", "telemetry") in names
    finally:
        profiler._state["running"] = False
        with profiler._lock:
            profiler._events.clear()
            profiler._agg.clear()


def test_span_without_profiler_running_still_counts():
    with telemetry.span("checkpoint.save_seconds"):
        pass
    assert telemetry.get("checkpoint.save_seconds").count == 1


# ---------------------------------------------------------------------------
# instrumented paths
# ---------------------------------------------------------------------------
def test_fusion_flush_counters_and_cache_stats():
    from tpu_mx import engine, fusion
    x = nd.array(np.ones((4, 4), np.float32))
    for _ in range(3):
        with engine.bulk(16):
            y = nd.tanh(x * 1.5 + 0.5)
            y.wait_to_read()
    assert telemetry.counter("fusion.flushes").value == 3
    assert telemetry.counter("fusion.ops_fused").value == 9
    causes = [m for m in telemetry.snapshot()
              if m["name"] == "fusion.flush_cause"]
    assert sum(m["value"] for m in causes) == 3
    assert all("cause" in m["labels"] for m in causes)
    seg = telemetry.get("fusion.segment_ops")
    assert seg.count == 3 and seg.min == 3 and seg.max == 3
    assert seg.unit == "ops"
    # the jit program cache may be warm from earlier tests in this
    # process; hits + misses must still account for every flush
    cs = fusion.cache_stats()
    assert cs["hits"] + cs["misses"] == 3
    assert cs["segments_flushed"] == 3
    assert cs["programs"] >= 1
    assert cs["hits"] == telemetry.counter("fusion.cache_hits").value
    assert cs["misses"] == telemetry.counter("fusion.cache_misses").value


def test_fusion_eager_fallback_counter():
    from tpu_mx import engine
    x = nd.array(np.ones((4, 4), np.float32))
    with engine.bulk(16):
        # np.float32 is an np.generic, not a bakeable python scalar —
        # the fusion engine must fall back to eager dispatch and count it
        y = x * np.float32(2.0)
        y.wait_to_read()
    assert telemetry.counter("fusion.eager_fallbacks").value >= 1
    np.testing.assert_allclose(y.asnumpy(), 2.0)


def test_checkpoint_atomic_write_and_retry_counters(tmp_path):
    from tpu_mx import checkpoint
    with checkpoint.atomic_write(str(tmp_path / "a.bin")) as f:
        f.write(b"payload")
    assert telemetry.counter("checkpoint.atomic_writes").value == 1
    h = telemetry.get("checkpoint.write_seconds")
    assert h is not None and h.count == 1 and h.sum > 0

    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient")
        return "ok"

    assert checkpoint.retry(flaky, attempts=4, backoff=0.001,
                            max_backoff=0.002, seed=0) == "ok"
    assert telemetry.counter("checkpoint.retries").value == 2


def test_checkpoint_corrupt_detection_counter(tmp_path):
    from tpu_mx import checkpoint
    prefix = str(tmp_path / "ck")
    data = f"{prefix}-0000.params"
    with checkpoint.atomic_write(data) as f:
        f.write(b"x" * 64)
    checkpoint.write_manifest(prefix, 0, [data])
    assert checkpoint.verify_checkpoint(prefix, 0)[0] == "verified"
    assert telemetry.get("checkpoint.corrupt_detected") is None
    os.remove(data)
    status, problems = checkpoint.verify_checkpoint(prefix, 0)
    assert status == "corrupt" and problems
    assert telemetry.counter("checkpoint.corrupt_detected").value == 1
    assert telemetry.get("checkpoint.verify_seconds").count == 2


def test_elastic_resume_and_corrupt_skip_counters(tmp_path):
    from tpu_mx import elastic
    from tpu_mx.gluon import nn
    net = nn.Dense(3, in_units=2)
    net.initialize()
    net(nd.ones((1, 2)))
    prefix = str(tmp_path / "run")
    elastic.save_checkpoint(prefix, 1, net=net)
    elastic.save_checkpoint(prefix, 2, net=net)
    # corrupt the newest epoch's params behind the manifest's back
    with open(f"{prefix}-0002.params", "wb") as f:
        f.write(b"garbage")
    epoch, params = elastic.latest_checkpoint(prefix)
    assert epoch == 1
    assert telemetry.counter("elastic.epochs_skipped_corrupt").value >= 1
    assert elastic.auto_resume(prefix, net=net) == 2  # resumes FROM 1 -> 2
    assert telemetry.counter("elastic.resume_attempts").value >= 1
    assert telemetry.get("checkpoint.save_seconds").count == 2


def test_kvstore_push_pull_counters():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((4, 4)))
    grads = [nd.array(np.ones((4, 4), np.float32)),
             nd.array(np.ones((4, 4), np.float32))]
    kv.push("w", grads)
    out = nd.zeros((4, 4))
    kv.pull("w", out=out)
    assert telemetry.counter("kvstore.pushes").value == 1
    assert telemetry.counter("kvstore.pulls").value == 1
    # 4x4 float32 = 64 bytes; push saw a 2-element device list
    assert telemetry.counter("kvstore.push_bytes").value == 128
    assert telemetry.counter("kvstore.pull_bytes").value == 64


def test_chaos_injection_counter_under_env(tmp_path, monkeypatch):
    """Chaos faults fired under TPUMX_CHAOS are tagged by kind in the
    registry — chaos runs can assert observability of faults, not just
    survival."""
    from tpu_mx import checkpoint
    from tpu_mx.contrib import chaos
    monkeypatch.setattr(chaos, "_config", None)
    monkeypatch.setattr(chaos, "_env_parsed", False)
    monkeypatch.setenv("TPUMX_CHAOS", "torn_write=4,match=.chaosdat")
    target = str(tmp_path / "file.chaosdat")
    with checkpoint.atomic_write(target) as f:
        f.write(b"z" * 100)  # tail silently dropped: the tear
    assert os.path.getsize(target) == 4
    assert telemetry.counter("chaos.injections",
                             kind="torn_write").value >= 1
    monkeypatch.setattr(chaos, "_config", None)
    monkeypatch.setattr(chaos, "_env_parsed", False)


def test_speedometer_publishes_gauge():
    from tpu_mx import callback
    sp = callback.Speedometer(batch_size=32, frequent=2, auto_reset=False)
    p = types.SimpleNamespace(epoch=0, nbatch=2, eval_metric=None)
    sp(p)                    # arms the timer
    p = types.SimpleNamespace(epoch=0, nbatch=4, eval_metric=None)
    sp(p)                    # hits count % frequent == 0 -> publishes
    g = telemetry.get("speedometer.samples_per_sec")
    assert g is not None and g.value > 0


def test_train_step_counters_and_examples_gauge():
    from tpu_mx import gluon
    from tpu_mx.gluon import nn
    from tpu_mx.parallel import CompiledTrainStep
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 4)))
    X = np.random.RandomState(0).rand(16, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd", learning_rate=0.1))
    for _ in range(3):
        step.step(nd.array(X), nd.array(Y))
    assert telemetry.counter("train_step.recompiles").value == 1
    assert telemetry.counter("train_step.steps").value == 3
    assert telemetry.get("train_step.seconds").count == 3
    assert telemetry.gauge("train_step.examples_per_sec").value > 0


def test_known_metrics_catalog_covers_instrumentation():
    """Every name the instrumented tree emits must be in the stable
    catalog — this is the same contract tools/ci.py's obs tier enforces
    on a real run's JSONL."""
    emitted = {
        "fusion.flushes", "fusion.flush_cause", "fusion.segment_ops",
        "fusion.ops_fused", "fusion.segments_dead", "fusion.cache_hits",
        "fusion.cache_misses", "fusion.eager_fallbacks",
        "checkpoint.save_seconds", "checkpoint.write_seconds",
        "checkpoint.verify_seconds",
        "checkpoint.atomic_writes", "checkpoint.retries",
        "checkpoint.corrupt_detected", "elastic.resume_attempts",
        "elastic.epochs_skipped_corrupt", "elastic.legacy_fallbacks",
        "train_step.seconds",
        "train_step.steps", "train_step.recompiles",
        "train_step.examples_per_sec", "kvstore.pushes", "kvstore.pulls",
        "kvstore.push_bytes", "kvstore.pull_bytes", "chaos.injections",
        "speedometer.samples_per_sec",
    }
    assert emitted <= telemetry.KNOWN_METRICS


# ---------------------------------------------------------------------------
# telemetry_report --diff (ISSUE 7 satellite): delta view between two
# snapshot files — counters/histograms subtracted, gauges side by side
# ---------------------------------------------------------------------------
def _write_snapshot(path, mutate):
    telemetry.reset()
    mutate()
    with open(path, "w", encoding="utf-8") as f:
        for rec in telemetry.snapshot():
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    telemetry.reset()


def _run_report(*args):
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "telemetry_report.py"),
         *args], capture_output=True, text=True, timeout=120)


def test_report_diff_subtracts_counters_histograms_gauges(tmp_path):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")

    def soak_a():
        telemetry.counter("supervisor.restarts").inc(2)
        telemetry.histogram("train_step.seconds").observe(0.1)
        telemetry.gauge("train_step.examples_per_sec").set(100.0)

    def soak_b():
        telemetry.counter("supervisor.restarts").inc(7)
        for v in (0.1, 0.2, 0.3):
            telemetry.histogram("train_step.seconds").observe(v)
        telemetry.gauge("train_step.examples_per_sec").set(250.0)
        telemetry.counter("supervisor.rollbacks").inc()  # only in B

    _write_snapshot(a, soak_a)
    _write_snapshot(b, soak_b)
    run = _run_report("--diff", a, b, "--validate")
    assert run.returncode == 0, run.stdout + run.stderr
    out = run.stdout
    assert "supervisor.restarts" in out and "+5" in out  # 7 - 2
    assert "(A=2, B=7)" in out
    assert "count +2" in out            # 3 - 1 histogram observations
    assert "A=100" in out and "B=250" in out  # gauges side by side
    assert "(only in B)" in out and "supervisor.rollbacks" in out


def test_report_diff_validates_and_needs_two_files(tmp_path):
    a = str(tmp_path / "a.jsonl")
    with open(a, "w", encoding="utf-8") as f:
        f.write(json.dumps({"name": "not.in.catalog", "type": "counter",
                            "value": 1, "ts": 1.0}) + "\n")
    # --validate surfaces the unknown name in EITHER file
    run = _run_report("--diff", a, a, "--validate")
    assert run.returncode == 1
    assert "not.in.catalog" in run.stderr
    # without --validate the diff still renders (rc 0)
    assert _run_report("--diff", a, a).returncode == 0
    # wrong arity is a usage error, not a crash
    assert _run_report("--diff", a).returncode == 2
    assert _run_report(a, a).returncode == 2


def test_report_diff_honors_require_against_after_snapshot(tmp_path):
    """--require composes with --diff (gating B, the "after" file) — a
    soak comparison must not read green with its gate never evaluated."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_snapshot(a, lambda: telemetry.counter(
        "supervisor.restarts").inc())
    _write_snapshot(b, lambda: telemetry.counter(
        "supervisor.rollbacks").inc())
    run = _run_report("--diff", a, b, "--require", "supervisor.rollbacks")
    assert run.returncode == 0, run.stdout + run.stderr
    # restarts is present only in A: requiring it against B must fail
    run = _run_report("--diff", a, b, "--require", "supervisor.restarts")
    assert run.returncode == 1
    assert "supervisor.restarts" in run.stderr


# ---------------------------------------------------------------------------
# sliding windows (ISSUE 11): ring-of-subwindow aggregation — quantile
# accuracy vs exact numpy percentiles on adversarial distributions,
# expiry across subwindow rollover, and a concurrent observe+read hammer
# ---------------------------------------------------------------------------
def _assert_within_one_bucket(est, exact, buckets):
    """The accuracy contract: the bucket-merge estimate lands within one
    histogram bucket of the exact percentile, either side."""
    from bisect import bisect_left
    i = bisect_left(buckets, exact)
    lo = buckets[i - 2] if i >= 2 else 0.0
    hi = buckets[i + 1] if i + 1 < len(buckets) else float("inf")
    assert lo <= est <= hi, (est, exact, lo, hi)


@pytest.mark.parametrize("dist", ["bimodal", "heavy_tail", "one_bucket"])
def test_windowed_quantile_accuracy_vs_numpy(dist):
    rng = np.random.RandomState(7)
    if dist == "bimodal":
        vals = np.abs(np.concatenate([rng.normal(2e-3, 2e-4, 1500),
                                      rng.normal(8e-2, 8e-3, 500)]))
    elif dist == "heavy_tail":
        vals = rng.lognormal(np.log(1e-3), 1.2, 2000)
    else:   # every sample identical -> one bucket; estimate is EXACT
        vals = np.full(500, 0.01234)
    h = telemetry.histogram("serve.itl_seconds")   # the dense SLO ladder
    for v in vals:
        h.observe(float(v))
    for q in (0.5, 0.9, 0.99):
        est = h.window_quantile(q)
        exact = float(np.percentile(vals, q * 100))
        _assert_within_one_bucket(est, exact, h.buckets)
        # the lifetime estimator shares the math (same samples here)
        _assert_within_one_bucket(h.quantile(q), exact, h.buckets)
    if dist == "one_bucket":
        # min == max clamping makes the degenerate case exact
        assert h.window_quantile(0.99) == pytest.approx(0.01234)
    # attainment interpolation agrees with the empirical CDF
    thr = float(np.percentile(vals, 75))
    frac = h.window_fraction_le(thr)
    assert abs(frac - float((vals <= thr).mean())) < 0.05


def test_window_expiry_across_subwindow_rollover(monkeypatch):
    clock = [1000.0]
    monkeypatch.setattr(telemetry, "_monotonic", lambda: clock[0])
    h = telemetry.histogram("train_step.seconds")
    h.configure_window(10.0, 5)           # 2 s subwindows
    for _ in range(100):
        h.observe(0.001)
    clock[0] += 4.0                       # two subwindows later
    for _ in range(50):
        h.observe(0.1)
    st = h.window_stats()
    assert st["count"] == 150 and st["min"] == 0.001 and st["max"] == 0.1
    # a narrower read sees only the newest subwindows
    assert h.window_stats(window=2.0)["count"] == 50
    clock[0] += 7.0                       # first batch now > 10 s old
    st = h.window_stats()
    assert st["count"] == 50
    assert st["sum"] == pytest.approx(5.0)
    assert h.window_quantile(0.5) == pytest.approx(0.1, rel=0.2)
    clock[0] += 100.0                     # everything expired
    assert h.window_stats()["count"] == 0
    assert h.window_quantile(0.99) is None
    assert h.window_fraction_le(1.0) is None
    # cumulative state never expires
    assert h.count == 150
    # the record's window sub-object reflects the empty window but the
    # cumulative fields do not
    rec = telemetry.snapshot()[0]
    telemetry.validate_record(rec)
    assert rec["value"] == 150 and rec["window"]["count"] == 0


def test_windowed_counter_delta_and_rate(monkeypatch):
    clock = [500.0]
    monkeypatch.setattr(telemetry, "_monotonic", lambda: clock[0])
    c = telemetry.counter("serve.generated_tokens")
    c.configure_window(10.0, 5)
    c.inc(30)
    clock[0] += 6.0
    c.inc(10)
    assert c.window_delta() == 40
    # covered time is age-clamped: the ring is only 6 s old, so the
    # rate is 40/6, not 40/10 — a young ring must not claim the full
    # horizon and under-report warm-up throughput
    assert c.window_rate() == pytest.approx(40 / 6.0)
    assert c.window_delta(window=2.0) == 10
    clock[0] += 6.0                       # the 30-burst expired
    assert c.window_delta() == 10
    assert c.value == 40                  # cumulative untouched
    rec = c._record(1.0)
    telemetry.validate_record(rec)
    assert rec["window"]["value"] == 10


def test_windowed_read_hammer_under_concurrent_observes():
    """Thread-safety of the window ring under the registry lock: reads
    interleaved with observes never tear (monotone window buckets,
    schema-valid records, no exceptions)."""
    h = telemetry.histogram("serve.ttft_seconds")
    stop = threading.Event()
    errs = []

    def worker(seed):
        rng = np.random.RandomState(seed)
        try:
            while not stop.is_set():
                h.observe(float(rng.lognormal(np.log(1e-3), 1.0)))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            q = h.window_quantile(0.99)
            assert q is None or q > 0
            h.window_fraction_le(0.05)
            cum = h.window_cumulative()
            counts = [c for _, c in cum]
            assert counts == sorted(counts)
            assert cum[-1][0] == "+Inf"
            for rec in telemetry.snapshot():
                telemetry.validate_record(rec)
    finally:
        stop.set()
        for t in threads:
            t.join(30)
    assert not errs, errs


def test_validate_record_rejects_malformed_windows():
    base = {"name": "h", "type": "histogram", "value": 1, "ts": 1.0,
            "sum": 0.5, "buckets": [[0.1, 1], ["+Inf", 1]]}
    telemetry.validate_record(dict(base))           # no window: valid
    good_win = {"seconds": 60.0, "count": 1, "sum": 0.5,
                "buckets": [[0.1, 1], ["+Inf", 1]]}
    telemetry.validate_record(dict(base, window=good_win))
    with pytest.raises(ValueError, match="window missing numeric"):
        telemetry.validate_record(
            dict(base, window={"count": 1, "sum": 0.5,
                               "buckets": [["+Inf", 1]]}))
    with pytest.raises(ValueError, match="not monotone"):
        telemetry.validate_record(dict(base, window=dict(
            good_win, buckets=[[0.1, 2], [0.3, 1], ["+Inf", 2]])))
    with pytest.raises(ValueError, match=r"\+Inf"):
        telemetry.validate_record(dict(base, window=dict(
            good_win, buckets=[[0.1, 1], [0.3, 1]])))
    with pytest.raises(ValueError, match="!= *count|window"):
        telemetry.validate_record(dict(base, window=dict(
            good_win, count=7)))
    cbase = {"name": "c", "type": "counter", "value": 3, "ts": 1.0}
    telemetry.validate_record(
        dict(cbase, window={"seconds": 60.0, "value": 2}))
    with pytest.raises(ValueError, match="counter window"):
        telemetry.validate_record(dict(cbase, window={"seconds": 60.0}))


def test_parse_slo_spec_grammar():
    d = telemetry.parse_slo_spec("itl_p99 < 50ms")
    assert d["metric"] == "serve.itl_seconds"
    assert d["quantile"] == pytest.approx(0.99)
    assert d["threshold_seconds"] == pytest.approx(0.05)
    d = telemetry.parse_slo_spec("ttft_p50<2s")
    assert d["metric"] == "serve.ttft_seconds"
    assert d["threshold_seconds"] == pytest.approx(2.0)
    d = telemetry.parse_slo_spec("train_step.seconds_p90 < 300us")
    assert d["metric"] == "train_step.seconds"
    assert d["threshold_seconds"] == pytest.approx(3e-4)
    for bad in ("itl < 50ms", "itl_p99 > 50ms", "itl_p99 < 50", ""):
        with pytest.raises(ValueError):
            telemetry.parse_slo_spec(bad)


# ---------------------------------------------------------------------------
# Prometheus exposition escaping (ISSUE 11 satellite): label values with
# backslash/quote/newline must round-trip per the text-format spec, and
# histogram `le` bounds must render sorted with +Inf last
# ---------------------------------------------------------------------------
def _prom_unescape(s):
    """Inverse of the text-format label-value escaping."""
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def test_exposition_escapes_adversarial_label_values_roundtrip():
    evil = 'a\\b"c\nd'
    telemetry.counter("chaos.injections", kind=evil).inc(3)
    text = telemetry.exposition()
    assert "\n\n" not in text.strip(), "raw newline leaked into a sample"
    [line] = [ln for ln in text.splitlines()
              if ln.startswith("tpumx_chaos_injections_total{")]
    # every sample line must stay one physical line
    body = line[line.index("{") + 1:line.rindex("}")]
    assert body.startswith('kind="') and body.endswith('"')
    assert _prom_unescape(body[len('kind="'):-1]) == evil
    assert line.rsplit(" ", 1)[1] == "3"


def test_exposition_histogram_le_bounds_sorted_with_inf_last():
    # buckets deliberately passed unsorted + duplicated: the registry
    # must canonicalize so `le` renders ascending with +Inf last
    h = telemetry.histogram("serve.phase_seconds",
                            buckets=(0.3, 0.1, 0.3, 0.001), phase="prefill")
    assert h.buckets == (0.001, 0.1, 0.3)
    for v in (0.0005, 0.2, 5.0):
        h.observe(v)
    text = telemetry.exposition()
    les = []
    for ln in text.splitlines():
        if ln.startswith("tpumx_serve_phase_seconds_bucket"):
            body = ln[ln.index("{") + 1:ln.rindex("}")]
            le = [kv.split("=")[1].strip('"') for kv in body.split(",")
                  if kv.startswith("le=")][0]
            les.append(le)
    assert les[-1] == "+Inf"
    finite = [float(v) for v in les[:-1]]
    assert finite == sorted(finite) == [0.001, 0.1, 0.3]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
              if ln.startswith("tpumx_serve_phase_seconds_bucket")]
    assert counts == sorted(counts) and counts[-1] == 3


def test_flush_refreshes_tracing_dropped_gauge(tmp_path):
    from tpu_mx import tracing
    tracing.reset()
    prior = tracing.configure()
    try:
        tracing.configure(capacity=4)
        for i in range(9):
            tracing.emit("chaos.inject", kind="hang")
        assert tracing.stats()["dropped"] == 5
        telemetry.flush(path=str(tmp_path / "m.jsonl"))
        g = telemetry.get("tracing.events_dropped")
        assert g is not None and g.value == 5.0
    finally:
        tracing.configure(capacity=prior[1])
        tracing.reset()
