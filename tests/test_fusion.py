"""Lazy pointwise-fusion engine tests (tpu_mx/fusion.py + engine.bulk).

Equivalence contract: a fused segment executes the same primitive
sequence as eager dispatch, compiled as one XLA program.  Forward AND
backward are asserted BIT-IDENTICAL for every covered chain here.  The
one documented numerics divergence — XLA contracting a multiply that
feeds an add into an FMA inside a fused loop (excess precision, the more
accurate result) — gets its own test with the jit ground-truth oracle.
"""
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import autograd, engine, fusion, nd


@pytest.fixture(autouse=True)
def _fusion_stats():
    fusion.reset_stats()
    yield
    # no segment may leak past a test: every barrier design guarantees a
    # flush before observable reads, and tests end with reads
    assert fusion.pending_ops() == 0


def _x(shape=(8, 8), lo=-2.0, hi=2.0):
    n = int(np.prod(shape))
    return nd.array(np.linspace(lo, hi, n).reshape(shape), dtype="float32")


# chains with no multiply->add adjacency: bit-identical under fusion
CHAINS = {
    "unary": lambda v: nd.tanh(nd.sin(nd.exp(v * 0.25))),
    "scalar_mix": lambda v: (nd.sqrt(nd.abs(v / 1.7)) * 3).clip(0.05, 1.5),
    "broadcast": lambda v: nd.cos(
        v * nd.array(np.linspace(0.1, 1.1, 8), dtype="float32")),
    "cast": lambda v: nd.cast(nd.cast(nd.relu(v), "float16"), "float32"),
    "compare_where": lambda v: nd.where(v > 0.0, nd.sigmoid(v), -v) / 2.0,
    "reduce_tail": lambda v: nd.square(v).mean(axis=1) / 1.3,
    "softmax": lambda v: nd.log_softmax(v * 0.5, axis=-1),
    "sum_all": lambda v: (nd.exp(v * 0.1) / 2.5).sum(),
}


@pytest.mark.parametrize("name", sorted(CHAINS))
def test_fused_forward_bit_identical(name):
    chain = CHAINS[name]
    ref = chain(_x()).asnumpy()
    with engine.bulk(64):
        out = chain(_x()).asnumpy()
    np.testing.assert_array_equal(ref, out)
    assert engine.bulk_stats()["segments_flushed"] >= 1


@pytest.mark.parametrize("name", sorted(CHAINS))
def test_fused_backward_bit_identical(name):
    chain = CHAINS[name]
    xe, xf = _x(), _x()
    xe.attach_grad()
    xf.attach_grad()
    with autograd.record():
        le = chain(xe).sum()
    le.backward()
    with autograd.record():
        with engine.bulk(64):
            lf = chain(xf).sum()
    lf.backward()
    np.testing.assert_array_equal(xe.grad.asnumpy(), xf.grad.asnumpy())


def test_fma_chain_matches_jit_ground_truth():
    """multiply->add chains: XLA contracts into FMA inside a fused loop.
    The fused result must equal jax.jit of the same composite exactly
    (one-program semantics, same as hybridize) and eager to ~1 ulp."""
    import jax
    import jax.numpy as jnp
    x = _x((16, 16))
    b = nd.array(np.linspace(0.1, 1.1, 16), dtype="float32")

    def chain(v):
        y = v
        for _ in range(3):
            y = y * 1.0009 + b
            y = nd.tanh(y)
        return y

    eager = chain(x).asnumpy()
    with engine.bulk(64):
        fused = chain(x).asnumpy()

    scal = jnp.asarray(1.0009)  # fusion passes scalars as weak-typed args

    def composite(xv, bv, s):
        y = xv
        for _ in range(3):
            y = jnp.tanh(y * s + bv)
        return y

    truth = np.asarray(jax.jit(composite)(x._data, b._data, scal))
    np.testing.assert_array_equal(fused, truth)
    # 1-ulp-per-contraction-site excess precision, compounded through the
    # tanh chain; atol covers the zero-crossing cells
    np.testing.assert_allclose(eager, fused, rtol=1e-5, atol=1e-6)


def test_cache_hit_on_second_call():
    x = _x()
    with engine.bulk(64):
        a = nd.tanh(nd.sin(x) * 0.5).asnumpy()
    misses = fusion.stats["cache_misses"]
    with engine.bulk(64):
        b = nd.tanh(nd.sin(x) * 0.5).asnumpy()
    assert fusion.stats["cache_misses"] == misses
    assert fusion.stats["cache_hits"] >= 1
    np.testing.assert_array_equal(a, b)


def test_cache_shared_across_scalar_values():
    """Scalars ride as runtime args, so a schedule-style changing scalar
    reuses ONE compiled program (and stays bit-identical to eager)."""
    x = _x()
    with engine.bulk(64):
        nd.sin(x * 0.5).asnumpy()
    misses = fusion.stats["cache_misses"]
    with engine.bulk(64):
        out = nd.sin(x * 0.25).asnumpy()
    assert fusion.stats["cache_misses"] == misses
    np.testing.assert_array_equal(out, nd.sin(x * 0.25).asnumpy())


def test_flush_barrier_asnumpy():
    x = _x()
    with engine.bulk(64):
        y = nd.exp(x)
        assert y._lazy is not None and fusion.pending_ops() == 1
        val = y.asnumpy()             # read barrier
        assert y._lazy is None and fusion.pending_ops() == 0
    np.testing.assert_array_equal(val, nd.exp(x).asnumpy())


def test_flush_barrier_wait_to_read():
    x = _x()
    with engine.bulk(64):
        y = nd.sqrt(nd.abs(x))
        assert y._lazy is not None
        y.wait_to_read()
        assert y._lazy is None


def test_flush_barrier_nonfusible_consumer():
    x = _x()
    with engine.bulk(64):
        y = nd.relu(x)
        assert y._lazy is not None
        z = nd.dot(y, y)              # matmul is not in the fusible table
        assert y._lazy is None        # consumer realized the input
    ref = nd.dot(nd.relu(x), nd.relu(x))
    np.testing.assert_array_equal(z.asnumpy(), ref.asnumpy())


def test_flush_barrier_scope_exit():
    x = _x()
    with engine.bulk(64):
        y = nd.sin(x)
        assert y._lazy is not None
    assert y._lazy is None            # scope exit flushed
    assert fusion.stats["flush_reasons"].get("scope_exit", 0) >= 1
    np.testing.assert_array_equal(y.asnumpy(), nd.sin(x).asnumpy())


def test_flush_barrier_bulk_size():
    x = _x()
    with engine.bulk(4):
        y = x
        for _ in range(12):
            y = nd.sin(y)
        out = y.asnumpy()
    assert fusion.stats["flush_reasons"].get("bulk_size", 0) >= 3
    ref = x
    for _ in range(12):
        ref = nd.sin(ref)
    np.testing.assert_array_equal(out, ref.asnumpy())


def test_flush_barrier_backward():
    x = _x()
    x.attach_grad()
    with autograd.record():
        with engine.bulk(64):
            y = nd.tanh(x) * 2.0
            y.backward()              # backward() flushes the segment
    xe = _x()
    xe.attach_grad()
    with autograd.record():
        ye = nd.tanh(xe) * 2.0
    ye.backward()
    np.testing.assert_array_equal(x.grad.asnumpy(), xe.grad.asnumpy())


def test_lazy_metadata_does_not_flush():
    x = _x()
    with engine.bulk(64):
        y = nd.sin(x).sum(axis=0)
        assert y.shape == (8,)
        assert y.dtype == np.float32
        assert y.ndim == 1 and y.size == 8
        assert y._lazy is not None    # shape/dtype answered from avals
        y.asnumpy()


def test_mixed_fused_and_eager_autograd():
    """A fused segment in the middle of an eagerly-taped graph: gradients
    route through the segment's single tape node bit-identically."""
    def run(bulked):
        x = _x()
        x.attach_grad()
        with autograd.record():
            h = nd.dot(x, x)          # eager (non-fusible) producer
            if bulked:
                with engine.bulk(64):
                    h = nd.tanh(h * 0.01)
                    h = h + 0.5
            else:
                h = nd.tanh(h * 0.01)
                h = h + 0.5
            loss = nd.dot(h, h).sum() # eager consumer
        loss.backward()
        return x.grad.asnumpy()

    np.testing.assert_array_equal(run(False), run(True))


def test_grad_req_add_accumulates():
    def run(bulked):
        x = _x()
        x.attach_grad(grad_req="add")
        for _ in range(2):
            with autograd.record():
                if bulked:
                    with engine.bulk(64):
                        loss = (nd.sigmoid(x) * 3.0).sum()
                else:
                    loss = (nd.sigmoid(x) * 3.0).sum()
            loss.backward()
        return x.grad.asnumpy()

    np.testing.assert_array_equal(run(False), run(True))


def test_blockgrad_inside_segment():
    def run(bulked):
        x = _x()
        x.attach_grad()
        with autograd.record():
            if bulked:
                with engine.bulk(64):
                    loss = (nd.BlockGrad(nd.exp(x)) * nd.sin(x)).sum()
            else:
                loss = (nd.BlockGrad(nd.exp(x)) * nd.sin(x)).sum()
        loss.backward()
        return x.grad.asnumpy()

    np.testing.assert_array_equal(run(False), run(True))


def test_integer_chain_not_taped():
    x = _x()
    x.attach_grad()
    with autograd.record():
        with engine.bulk(64):
            idx = nd.cast(nd.abs(x) * 2.0, "int32")
            s = nd.sin(x).sum()
    assert idx._tape_node is None     # all-int output: unrecorded, eager parity
    assert idx.dtype == np.int32
    s.backward()
    np.testing.assert_array_equal(
        x.grad.asnumpy(), nd.cos(_x()).asnumpy())


def test_dead_intermediates_never_materialize():
    """Only live handles become program outputs; a fully-dead segment is
    dropped without executing."""
    x = _x()
    with engine.bulk(64):
        nd.exp(x)                     # result discarded immediately
        nd.sin(x)
    assert fusion.stats["segments_dead"] >= 1
    assert fusion.stats["segments_flushed"] == 0


def test_inplace_rebind_is_barrier():
    """Augmented assignment keeps strict eager rebind semantics (the
    in-place target realizes immediately) and stays correct in a scope."""
    x = _x()
    ref = x.copy()
    ref += 2.0
    ref = nd.sin(ref).asnumpy()
    with engine.bulk(64):
        y = x.copy()
        y += 2.0
        assert y._lazy is None
        out = nd.sin(y).asnumpy()
    np.testing.assert_array_equal(ref, out)


def test_out_kwarg_realizes():
    x = _x()
    with engine.bulk(64):
        tgt = nd.zeros((8, 8))
        res = nd.exp(x, out=tgt)
        assert res is tgt and tgt._lazy is None
    np.testing.assert_array_equal(tgt.asnumpy(), nd.exp(x).asnumpy())


def test_waitall_flushes():
    x = _x()
    with engine.bulk(64):
        y = nd.sin(x)
        assert y._lazy is not None
        nd.waitall()
        assert y._lazy is None


def test_env_fusion_off_restores_eager(monkeypatch):
    monkeypatch.setenv("TPUMX_FUSION", "0")
    x = _x()
    with engine.bulk(64):
        y = nd.sin(x)
        assert y._lazy is None        # eager exactly: no laziness at all
        assert fusion.stats["ops_fused"] == 0
    np.testing.assert_array_equal(y.asnumpy(), nd.sin(x).asnumpy())


def test_env_fusion_always_on(monkeypatch):
    monkeypatch.setenv("TPUMX_FUSION", "1")
    x = _x()
    y = nd.tanh(nd.sin(x))            # no bulk scope needed
    assert y._lazy is not None
    out = y.asnumpy()
    monkeypatch.delenv("TPUMX_FUSION")
    np.testing.assert_array_equal(out, nd.tanh(nd.sin(x)).asnumpy())


def test_bulk_size_one_disables():
    x = _x()
    with engine.bulk(1):
        y = nd.sin(x)
        assert y._lazy is None


def test_bulk_size_one_overrides_always_on(monkeypatch):
    """bulk(size<=1) is the reference's op-by-op escape hatch; it must
    win over TPUMX_FUSION=1 (review finding r6)."""
    monkeypatch.setenv("TPUMX_FUSION", "1")
    x = _x()
    with engine.bulk(1):
        y = nd.sin(x)
        assert y._lazy is None
    z = nd.sin(x)
    assert z._lazy is not None        # always-on resumes outside
    z.asnumpy()


def test_nondiff_op_blocks_gradients_like_eager():
    """A nondiff op (sgd_update, zeros_like...) inside a fused segment
    must stay a gradient DEAD END exactly as eager leaves it unrecorded
    (review finding r6: the segment vjp used to differentiate through)."""
    def run(bulked):
        w = _x()
        w.attach_grad()
        with autograd.record():
            if bulked:
                with engine.bulk(64):
                    new_w = nd.sgd_update(w, w * 0.1, lr=0.5)
                    loss = (new_w * nd.sin(w)).sum()
            else:
                new_w = nd.sgd_update(w, w * 0.1, lr=0.5)
                loss = (new_w * nd.sin(w)).sum()
        loss.backward()
        return w.grad.asnumpy()

    np.testing.assert_array_equal(run(False), run(True))


def test_nondiff_head_does_not_zero_leaf_grads():
    """backward() from a head that reaches a tracked leaf only through a
    nondiff fused node must leave the leaf's grad untouched (eager finds
    no tape path; a taped nondiff output would overwrite with zeros)."""
    def run(bulked):
        x = _x()
        x.attach_grad()
        with autograd.record():
            seed_loss = nd.sin(x).sum()
        seed_loss.backward()          # populate x.grad
        with autograd.record():
            if bulked:
                with engine.bulk(64):
                    head = nd.zeros_like(nd.exp(x)).sum()
            else:
                head = nd.zeros_like(nd.exp(x)).sum()
        head.backward()
        return x.grad.asnumpy()

    np.testing.assert_array_equal(run(False), run(True))
    assert np.abs(run(True)).max() > 0  # the seeded grad survived


def test_shared_buffer_handles_get_separate_grads():
    """detach() shares the underlying jax.Array; both handles must still
    receive their own cotangents through a fused segment (review finding
    r6: buffer-id dedup starved the second handle)."""
    def run(bulked):
        a = _x()
        d = a.detach()                # same jax.Array underneath
        a.attach_grad()
        d.attach_grad()
        with autograd.record():
            if bulked:
                with engine.bulk(64):
                    loss = (nd.sin(a) * nd.exp(d)).sum()
            else:
                loss = (nd.sin(a) * nd.exp(d)).sum()
        loss.backward()
        return a.grad.asnumpy(), d.grad.asnumpy()

    ea, ed = run(False)
    fa, fd = run(True)
    np.testing.assert_array_equal(ea, fa)
    np.testing.assert_array_equal(ed, fd)


def test_bulk_restores_size():
    prev = engine.set_bulk_size(7)
    try:
        with engine.bulk(31):
            pass
        assert engine.set_bulk_size(7) == 7
    finally:
        engine.set_bulk_size(prev)


def test_deferred_error_names_segment():
    x = _x((4, 4))
    b = nd.array(np.zeros((5,), np.float32))
    with pytest.raises(Exception, match="fused op segment"):
        with engine.bulk(64):
            y = nd.sin(x) + b         # invalid broadcast, surfaces at flush
            y.asnumpy()


def test_record_scope_is_tape_boundary():
    """Ops issued outside record() must not be taped even when their
    segment would otherwise flush inside the recording scope."""
    x = _x()
    x.attach_grad()
    with engine.bulk(64):
        pre = nd.sin(x)               # issued while NOT recording
        with autograd.record():       # boundary flushes the segment
            assert pre._lazy is None
            loss = (pre * nd.exp(x)).sum()
        loss.backward()
    xe = _x()
    xe.attach_grad()
    pre_e = nd.sin(xe)
    with autograd.record():
        loss_e = (pre_e * nd.exp(xe)).sum()
    loss_e.backward()
    np.testing.assert_array_equal(x.grad.asnumpy(), xe.grad.asnumpy())


def test_sgd_update_fuses_parameter_sweep():
    """The imperative optimizer path: a bulk() around a parameter-update
    sweep bulks the fusible sgd_update chains.  The update core is an
    FMA-bearing chain (wd*w feeds an add), so the contract is the
    contraction tolerance, not bit-identity."""
    rng = np.random.RandomState(0)
    ws = [nd.array(rng.rand(4, 4).astype(np.float32)) for _ in range(3)]
    gs = [nd.array(rng.rand(4, 4).astype(np.float32)) for _ in range(3)]
    refs = [mx.nd.sgd_update(w.copy(), g, lr=0.1, wd=0.01).asnumpy()
            for w, g in zip(ws, gs)]
    with engine.bulk(64):
        outs = [mx.nd.sgd_update(w.copy(), g, lr=0.1, wd=0.01)
                for w, g in zip(ws, gs)]
        assert fusion.stats["ops_fused"] >= 3
        outs = [o.asnumpy() for o in outs]
    for r, o in zip(refs, outs):
        np.testing.assert_allclose(r, o, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_fused_speedup_on_pointwise_chain():
    """Acceptance bar: >= 1.5x on a >= 32-op elementwise chain after
    cache warm-up (dispatch-overhead regime).  bench.py's fusion leg is
    the official measurement; this is the regression tripwire at a lower
    threshold so host noise can't flake it."""
    import time
    x = nd.array(np.random.RandomState(0).rand(64, 64).astype(np.float32))

    def chain32(v):
        y = v
        for _ in range(8):
            y = nd.sin(y)
            y = y * 1.0009
            y = y + 0.1
            y = nd.tanh(y)
        return y

    chain32(x).wait_to_read()
    with engine.bulk(64):
        chain32(x).wait_to_read()     # warm the fusion cache
    n = 30
    best_e = min(_timed(chain32, x, n, None) for _ in range(3))
    best_f = min(_timed(chain32, x, n, 64) for _ in range(3))
    assert best_e / best_f >= 1.3, \
        f"fused {best_f:.4f}s not faster than eager {best_e:.4f}s"


def _timed(chain, x, n, bulk_size):
    import time
    t0 = time.perf_counter()
    for _ in range(n):
        if bulk_size:
            with engine.bulk(bulk_size):
                chain(x).wait_to_read()
        else:
            chain(x).wait_to_read()
    return time.perf_counter() - t0


def test_scalar_spelling_does_not_collide_in_chain_cache():
    """clip(x, 0, 1) and clip(x, 0.0, 1.0) compare equal as Python values
    but bake DIFFERENT trace constants (int vs weak-float promotion) —
    the chain cache must key them apart, or the float-spelled call
    replays the int program and returns the wrong dtype vs eager."""
    xi = nd.array(np.arange(-2, 3, dtype=np.int32))
    with engine.bulk(4):
        a = nd.clip(xi, 0, 1)
        a.wait_to_read()
    with engine.bulk(4):
        b = nd.clip(xi, 0.0, 1.0)
        b.wait_to_read()
    eager_int = nd.clip(xi, 0, 1)      # no bulk scope: plain eager
    eager_float = nd.clip(xi, 0.0, 1.0)
    assert a.dtype == eager_int.dtype, (a.dtype, eager_int.dtype)
    assert b.dtype == eager_float.dtype, (b.dtype, eager_float.dtype)
    np.testing.assert_array_equal(a.asnumpy(), eager_int.asnumpy())
    np.testing.assert_array_equal(b.asnumpy(), eager_float.asnumpy())
