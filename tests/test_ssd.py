"""SSD model path: forward shapes, target generation, one training step,
detection inference (BASELINE config 4 slice)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.models import SSD, SSDTrainingTargets


def _tiny_ssd(num_classes=3):
    # 2 scales, small backbone -> fast CPU test
    return SSD(num_classes, sizes=[(0.2, 0.27), (0.4, 0.49)],
               ratios=[(1, 2, 0.5)] * 2, base_filters=(8, 16),
               scale_filters=16)


@pytest.mark.slow
def test_ssd_forward_shapes():
    net = _tiny_ssd()
    net.initialize(mx.init.Xavier())
    x = mx.nd.zeros((2, 3, 64, 64))
    anchors, cls_preds, box_preds = net(x)
    # backbone: 2 pools -> 16x16; scale1 -> 8x8; K=4 anchors/cell
    A = 16 * 16 * 4 + 8 * 8 * 4
    assert anchors.shape == (1, A, 4)
    assert cls_preds.shape == (2, A, 4)
    assert box_preds.shape == (2, A * 4)


@pytest.mark.slow
def test_ssd_train_step():
    net = _tiny_ssd()
    net.initialize(mx.init.Xavier())
    targets = SSDTrainingTargets()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(2, 3, 64, 64).astype("float32"))
    labels = mx.nd.array(np.array(
        [[[0, 0.1, 0.1, 0.4, 0.4], [1, 0.5, 0.5, 0.9, 0.9]],
         [[2, 0.2, 0.3, 0.6, 0.7], [-1, -1, -1, -1, -1]]], "float32"))
    losses = []
    for _ in range(3):
        with autograd.record():
            anchors, cls_preds, box_preds = net(x)
            with autograd.pause():
                loc_t, loc_m, cls_t = targets(anchors, labels, cls_preds)
            l_cls = cls_loss(cls_preds, cls_t)
            l_box = box_loss(box_preds * loc_m, loc_t * loc_m)
            l = l_cls + l_box
        l.backward()
        trainer.step(2)
        losses.append(float(l.mean().asnumpy()))
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_ssd_detect():
    net = _tiny_ssd()
    net.initialize(mx.init.Xavier())
    x = mx.nd.zeros((1, 3, 64, 64))
    det = net.detect(x, threshold=0.0)
    A = 16 * 16 * 4 + 8 * 8 * 4
    assert det.shape == (1, A, 6)
    d = det.asnumpy()
    kept = d[0][d[0, :, 0] >= 0]
    assert kept.shape[0] >= 1           # something survives NMS
    # scores in [0,1], class ids within range
    assert ((kept[:, 1] >= 0) & (kept[:, 1] <= 1)).all()
    assert kept[:, 0].max() < 3


def test_ssd_hybridize_consistency():
    net = _tiny_ssd()
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(1)
                    .randn(1, 3, 64, 64).astype("float32"))
    a1, c1, b1 = net(x)
    net.hybridize()
    a2, c2, b2 = net(x)
    np.testing.assert_allclose(a1.asnumpy(), a2.asnumpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(c1.asnumpy(), c2.asnumpy(), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(b1.asnumpy(), b2.asnumpy(), rtol=1e-4,
                               atol=1e-4)


@pytest.mark.slow
def test_ssd_512_config():
    net = mx.models.ssd_512(num_classes=20)
    net.initialize(mx.init.Xavier())
    x = mx.nd.zeros((1, 3, 128, 128))   # reduced res for test speed
    anchors, cls_preds, box_preds = net(x)
    assert anchors.shape[1] == cls_preds.shape[1]
    assert cls_preds.shape[2] == 21
    assert box_preds.shape[1] == anchors.shape[1] * 4


@pytest.mark.slow
def test_ssd300_vgg16_reduced_canonical_anchors_and_train():
    """backbone='vgg16_reduced' reproduces the reference SSD300 feature
    pyramid exactly (8732 anchors: 38/19/10/5/3/1 maps, [4,6,6,6,4,4]
    per-position), and a few training steps reduce the loss."""
    from tpu_mx.models.ssd import ssd_300, SSDTrainingTargets
    np.random.seed(0)
    net = ssd_300(num_classes=3, backbone="vgg16_reduced")
    net.initialize(init="xavier")
    x = nd.array(np.random.rand(2, 3, 300, 300).astype(np.float32) * 0.1)
    anchors, cls_preds, box_preds = net(x)
    assert anchors.shape == (1, 8732, 4)
    assert cls_preds.shape == (2, 8732, 4)
    assert box_preds.shape == (2, 8732 * 4)
    # one box per image; train a few steps
    labels = np.full((2, 1, 5), -1.0, np.float32)
    labels[0, 0] = [0, 0.1, 0.1, 0.5, 0.5]
    labels[1, 0] = [1, 0.3, 0.3, 0.8, 0.8]
    l_nd = nd.array(labels)
    targets = SSDTrainingTargets()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss()
    losses = []
    for _ in range(4):
        with autograd.record():
            a, c, b = net(x)
            with autograd.pause():
                loc_t, loc_m, cls_t = targets(a, l_nd, c)
            l = cls_loss(c, cls_t) + box_loss(b * loc_m, loc_t * loc_m)
        l.backward()
        trainer.step(2)
        losses.append(float(l.mean().asnumpy()))
    assert losses[-1] < losses[0], losses
