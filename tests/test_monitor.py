"""mx.monitor coverage (previously untested; ISSUE 3 satellite): forward
hooks collect per-layer stats, interval gating, pattern filtering, and —
the one that bites — uninstall actually detaching every hook."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.gluon import nn
from tpu_mx.monitor import Monitor


def _net():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"),
            nn.Dense(2, in_units=8))
    net.initialize()
    net(nd.ones((1, 4)))
    return net


def test_monitor_collects_layer_stats():
    net = _net()
    mon = Monitor(interval=1).install(net)
    mon.tic()
    net(nd.ones((2, 4)))
    res = mon.toc()
    assert res, "forward hooks must have recorded outputs"
    names = [name for _step, name, _stat in res]
    # children walk by registration key: hybridsequential.0 / .1
    assert {"hybridsequential.0", "hybridsequential.1"} <= set(names)
    assert any(n == "hybridsequential" for n in names)  # root included
    for step, _name, stat in res:
        assert step == 1  # tic() advances the batch count after arming
        assert isinstance(stat, float) and stat >= 0  # default: mean |x|


def test_monitor_default_stat_is_mean_abs():
    net = _net()
    mon = Monitor(interval=1, pattern="hybridsequential$").install(net)
    mon.tic()
    out = net(nd.ones((2, 4)))
    res = mon.toc()
    assert len(res) == 1
    assert res[0][2] == pytest.approx(
        float(np.abs(out.asnumpy()).mean()), rel=1e-6)


def test_monitor_interval_gating():
    net = _net()
    mon = Monitor(interval=2).install(net)
    seen = []
    for _ in range(4):
        mon.tic()
        net(nd.ones((2, 4)))
        seen.append(bool(mon.toc()))
    assert seen == [True, False, True, False]


def test_monitor_pattern_filters_layers():
    net = _net()
    mon = Monitor(interval=1, pattern=r".*\.0$").install(net)
    mon.tic()
    net(nd.ones((2, 4)))
    names = {name for _s, name, _v in mon.toc()}
    assert names and all(n.endswith(".0") for n in names)


def test_monitor_sort_orders_by_name():
    net = _net()
    mon = Monitor(interval=1, sort=True).install(net)
    mon.tic()
    net(nd.ones((2, 4)))
    names = [name for _s, name, _v in mon.toc()]
    assert names == sorted(names)


def test_monitor_custom_stat_func():
    net = _net()
    mon = Monitor(interval=1, stat_func=lambda a: float(a.max()),
                  pattern="hybridsequential$").install(net)
    mon.tic()
    out = net(nd.ones((2, 4)))
    res = mon.toc()
    assert res[0][2] == pytest.approx(float(out.asnumpy().max()), rel=1e-6)


def test_monitor_uninstall_detaches_every_hook():
    net = _net()
    mon = Monitor(interval=1).install(net)
    mon.tic()
    net(nd.ones((2, 4)))
    assert mon.toc()
    mon.uninstall()
    assert mon._handles == []
    # no block keeps a live hook behind uninstall's back
    def hooks_of(block):
        yield from block.__dict__.get("_fwd_hooks", ())
        for child in block._children.values():
            yield from hooks_of(child)
    assert not list(hooks_of(net))
    mon.tic()
    net(nd.ones((2, 4)))
    assert mon.toc() == [], "detached monitor must record nothing"


def test_monitor_toc_without_tic_is_empty():
    net = _net()
    mon = Monitor(interval=1).install(net)
    assert mon.toc() == []


def test_toc_print_smoke(capsys):
    net = _net()
    mon = Monitor(interval=1, pattern="hybridsequential$").install(net)
    mon.tic()
    net(nd.ones((2, 4)))
    mon.toc_print()
    out = capsys.readouterr().out
    assert "hybridsequential" in out and "Batch" in out
