"""mx.profiler coverage (previously untested; ISSUE 3 satellite): scope
aggregate math, pause/resume gating, dump round-trip + atomicity, and the
telemetry span merge point."""
import json
import os

import pytest

import tpu_mx as mx
from tpu_mx import profiler


@pytest.fixture(autouse=True)
def _fresh_profiler():
    """Profiler state is process-global — isolate every test."""
    def clear():
        with profiler._lock:
            profiler._agg.clear()
            profiler._events.clear()
        profiler._state["running"] = False
        profiler._state["paused"] = False
        profiler._state["jax_trace"] = False
    clear()
    yield
    clear()
    profiler._state["filename"] = "profile.json"


def _run(paused=False):
    profiler._state["running"] = True
    profiler._state["paused"] = paused


def test_scope_aggregate_math():
    """dumps() reproduces the reference aggregate table: calls, total,
    mean, min, max — deterministic via direct interval recording."""
    profiler._record_scope("train", 0.0, 0.1)
    profiler._record_scope("train", 1.0, 1.3)
    profiler._record_scope("io", 0.0, 0.05)
    out = profiler.dumps()
    lines = {ln.split()[0]: ln.split() for ln in out.splitlines()[1:]}
    name, calls, total, mean, mn, mx_ = lines["train"]
    assert int(calls) == 2
    assert float(total) == pytest.approx(400.0)
    assert float(mean) == pytest.approx(200.0)
    assert float(mn) == pytest.approx(100.0)
    assert float(mx_) == pytest.approx(300.0)
    assert int(lines["io"][1]) == 1
    # rows sort by descending total time
    assert out.splitlines()[1].startswith("train")


def test_dumps_reset_clears_aggregates():
    profiler._record_scope("uniq_scope", 0.0, 0.1)
    assert "uniq_scope" in profiler.dumps(reset=True)
    assert "uniq_scope" not in profiler.dumps()


def test_pause_resume_gates_recording():
    _run()
    with profiler.scope("a"):
        pass
    profiler.pause()
    with profiler.scope("b"):
        pass
    profiler.resume()
    with profiler.scope("c"):
        pass
    names = {e["name"] for e in profiler._events}
    assert names == {"a", "c"}, "paused interval must not record"


def test_task_event_counter_marker_emit():
    _run()
    t = profiler.Task("work")
    t.start()
    t.stop()
    c = profiler.Counter("items")
    c.increment(5)
    c.decrement(2)
    profiler.Marker("hit").mark("global")
    by_name = {}
    for e in profiler._events:
        by_name.setdefault(e["name"], []).append(e)
    assert by_name["work"][0]["ph"] == "X"
    assert by_name["items"][-1]["args"]["items"] == 3
    assert by_name["hit"][0]["ph"] == "i"
    assert by_name["hit"][0]["s"] == "g"


def test_record_span_merges_only_while_recording():
    profiler.record_span("tele", 0.0, 0.5)
    assert not profiler._events
    _run()
    profiler.record_span("tele", 0.0, 0.5)
    assert profiler._events[0]["name"] == "tele"
    assert profiler._events[0]["cat"] == "telemetry"
    assert "tele" in profiler.dumps()


def test_set_state_dump_roundtrip(tmp_path, monkeypatch):
    """run -> record -> stop writes chrome-trace JSON to the configured
    filename (the jax device trace is stubbed out — host events are what
    this asserts)."""
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", lambda *a, **k: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname)
    assert profiler._state["trace_dir"] == str(tmp_path / "profile_xla_trace")
    profiler.set_state("run")
    with profiler.scope("step"):
        pass
    profiler.set_state("stop")
    with open(fname) as f:
        trace = json.load(f)
    assert trace["displayTimeUnit"] == "ms"
    evs = [e for e in trace["traceEvents"] if e["name"] == "step"]
    assert evs and evs[0]["ph"] == "X" and evs[0]["dur"] >= 0
    with pytest.raises(ValueError):
        profiler.set_state("bogus")


def test_dump_is_atomic_under_mid_write_crash(tmp_path):
    """Satellite: profiler.dump rides checkpoint.atomic_write — a crash
    mid-dump leaves the previous complete profile.json, never a
    truncated one."""
    from tpu_mx.contrib import chaos
    from tpu_mx.contrib.chaos import ChaosCrash
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname)
    _run()
    with profiler.scope("first"):
        pass
    profiler._state["running"] = False
    profiler.dump()
    before = open(fname).read()
    json.loads(before)
    _run()
    with profiler.scope("second"):
        pass
    profiler._state["running"] = False
    with chaos.enable(crash_after_bytes=10, match="profile.json", seed=3):
        with pytest.raises(ChaosCrash):
            profiler.dump()
    assert open(fname).read() == before, \
        "crashed dump must leave the previous complete file untouched"
    assert any(".tmp." in p.name for p in tmp_path.iterdir()), \
        "a simulated crash leaves tmp debris (like a real kill)"


def test_set_state_run_clears_previous_session(monkeypatch):
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", lambda *a, **k: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    profiler._record_scope("stale", 0.0, 1.0)
    profiler.set_state("run")
    try:
        assert not profiler._events and not profiler._agg
    finally:
        profiler._state["running"] = False
        profiler._state["jax_trace"] = False
