"""bench.py supervisor resilience (VERDICT r3 ask#8): a wedged backend must
never zero a round that has a measured number on disk.

Round 3's official BENCH record was 0.0/error while a real measurement from
11 hours earlier existed only in a hand-written interim note.  The contract
now: every successful measurement is persisted to BENCH_LASTGOOD.json the
moment it exists, and when every bench attempt dies the supervisor emits
that last-good record marked ``"stale": true`` (with its measurement
timestamp and the failure reason) instead of a bare zero.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _load_bench_module():
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _bypass_platform_gate(monkeypatch):
    """The store-logic tests run on the CPU backend; without this bypass
    the platform gate (see test_cpu_platform_never_persists) would turn
    every persist into a no-op and the tests would assert on nothing."""
    monkeypatch.setenv("BENCH_PERSIST_ANY_PLATFORM", "1")


def test_cpu_platform_never_persists(tmp_path, monkeypatch):
    """A non-smoke run on a non-TPU backend must not write the store even
    with a production metric name: a JAX_PLATFORMS=cpu verification drive
    (BENCH_BATCH=4) clobbered the real-chip resnet record in r5.

    jax.devices is stubbed rather than called: the real probe would hang
    the whole pytest process on a wedged tunnel (and report tpu on the
    on-chip tier, inverting the assert)."""
    import types
    import jax
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    monkeypatch.delenv("BENCH_PERSIST_ANY_PLATFORM", raising=False)
    monkeypatch.setattr(jax, "devices",
                        lambda *a, **k: [types.SimpleNamespace(
                            platform="cpu")])
    bench = _load_bench_module()
    bench.persist_lastgood({"metric": bench.PRIMARY_METRIC, "value": 0.39})
    assert bench.load_lastgood() == (None, None)


def test_persist_and_load_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    rec = {"metric": "resnet50_train_images_per_sec_per_chip",
           "value": 2400.75, "unit": "img/s", "vs_baseline": 0.857}
    bench.persist_lastgood(rec)
    ts, loaded = bench.load_lastgood()
    assert loaded == rec
    assert ts  # a timestamp string was recorded


def test_smoke_records_never_persisted(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    bench.persist_lastgood({"metric": "resnet18_smoke_images_per_sec",
                            "value": 99.0})
    ts, loaded = bench.load_lastgood()
    assert loaded is None and ts is None


def test_smoke_env_never_persists_even_unmarked_metric(tmp_path,
                                                       monkeypatch):
    """A BENCH_SMOKE=1 process must not persist ANY record, even one whose
    metric name carries no 'smoke' (the scaling metric bit us here: a CPU
    smoke weak_scaling_efficiency_dp8 record clobbered the real-chip
    resnet lastgood)."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    monkeypatch.setenv("BENCH_SMOKE", "1")
    bench = _load_bench_module()
    bench.persist_lastgood({"metric": "weak_scaling_efficiency_dp8",
                            "value": 0.11})
    ts, loaded = bench.load_lastgood()
    assert loaded is None and ts is None


def test_secondary_metric_never_clobbers_primary(tmp_path, monkeypatch):
    """The store is keyed by metric: a later BENCH_MODELS=bert or scaling
    run must not overwrite the resnet record, and the resnet record stays
    the preferred stale-emission choice."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    resnet = {"metric": bench.PRIMARY_METRIC, "value": 2400.75}
    bench.persist_lastgood(resnet)
    bench.persist_lastgood({"metric": "bert_base_train_seqs_per_sec_per_chip",
                            "value": 150.0})
    bench.persist_lastgood({"metric": "weak_scaling_efficiency_dp8",
                            "value": 1.0})
    ts, loaded = bench.load_lastgood()
    # the primary stays the stale-emission choice, with the independently
    # stored bert + scaling records grafted in (a resnet-only run must not
    # cost the round its bert measurement — the r4 batch sweep did exactly
    # that), each carrying its OWN measured_at (they may come from
    # different runs than the primary)
    assert loaded["value"] == 2400.75
    assert loaded["bert"]["value"] == 150.0
    assert loaded["scaling"]["value"] == 1.0
    assert loaded["bert"]["measured_at"] and loaded["scaling"]["measured_at"]
    store = json.loads((tmp_path / "lg.json").read_text())
    assert len(store["records"]) == 3  # all three survive side by side


def test_scaling_graft_freshest_wins_and_dp1_placeholder_skipped(
        tmp_path, monkeypatch):
    """The scaling key family is dynamic (weak_scaling_efficiency_dp{n});
    the graft must pick the freshest by measured_at, not dict order, and
    the single-device dp1 placeholder must never mask a real record."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    bench.persist_lastgood({"metric": bench.PRIMARY_METRIC, "value": 2400.0})
    # hand-write two scaling entries with explicit timestamps (older dp8
    # real record listed AFTER a newer-keyed entry to defeat dict order)
    store = json.loads((tmp_path / "lg.json").read_text())
    store["records"]["weak_scaling_efficiency_dp4"] = {
        "measured_at": "2026-07-31T00:00:00+0000",
        "record": {"metric": "weak_scaling_efficiency_dp4", "value": 0.93}}
    store["records"]["weak_scaling_efficiency_dp8"] = {
        "measured_at": "2026-07-30T00:00:00+0000",
        "record": {"metric": "weak_scaling_efficiency_dp8", "value": 0.91}}
    (tmp_path / "lg.json").write_text(json.dumps(store))
    _, loaded = bench.load_lastgood()
    assert loaded["scaling"]["value"] == 0.93  # freshest, not last-listed
    # the dp1 placeholder is refused at the persist layer itself (it can
    # reach persist_lastgood both via the sub-record loop and as the
    # top-level record of a scaling-only run)
    bench.persist_lastgood({"metric": "weak_scaling_efficiency_dp1",
                            "value": 1.0})
    store = json.loads((tmp_path / "lg.json").read_text())
    assert "weak_scaling_efficiency_dp1" not in store["records"]


def test_graft_skips_invalid_and_own_family_records(tmp_path, monkeypatch):
    """A null/zero per-key record must not be grafted (same validity bar
    as primary selection), and a scaling primary must not carry a staler
    sibling scaling record nested inside itself."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    store = {"records": {
        "weak_scaling_efficiency_dp4": {
            "measured_at": "2026-07-31T00:00:00+0000",
            "record": {"metric": "weak_scaling_efficiency_dp4",
                       "value": 0.93}},
        "weak_scaling_efficiency_dp8": {
            "measured_at": "2026-07-30T00:00:00+0000",
            "record": {"metric": "weak_scaling_efficiency_dp8",
                       "value": 0.91}},
        "bert_base_train_seqs_per_sec_per_chip": {
            "measured_at": "2026-07-31T00:00:00+0000",
            "record": {"metric": "bert_base_train_seqs_per_sec_per_chip",
                       "value": None}},
    }}
    (tmp_path / "lg.json").write_text(json.dumps(store))
    _, loaded = bench.load_lastgood()
    # fallback primary = freshest entry (dp4); no sibling scaling nested,
    # and the null bert record is not grafted
    assert loaded["metric"] == "weak_scaling_efficiency_dp4"
    assert "scaling" not in loaded and "bert" not in loaded


def test_bert_only_store_never_self_nests(tmp_path, monkeypatch):
    """When the only stored record IS the bert record, the graft must not
    nest it inside itself."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    bert = {"metric": "bert_base_train_seqs_per_sec_per_chip",
            "value": 150.0}
    bench.persist_lastgood(bert)
    ts, loaded = bench.load_lastgood()
    assert loaded == bert and "bert" not in loaded


def test_graft_prefers_per_key_record_over_nested_copy(tmp_path,
                                                       monkeypatch):
    """The per-metric key is written by the same run that measured it, so
    it is always at least as fresh as a copy nested inside the primary —
    a later bert-only run must win over the stale nested value."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    resnet = {"metric": bench.PRIMARY_METRIC, "value": 2400.0,
              "bert": {"metric": "bert_base_train_seqs_per_sec_per_chip",
                       "value": 456.0}}
    bench.persist_lastgood(resnet)
    bench.persist_lastgood({"metric": "bert_base_train_seqs_per_sec_per_chip",
                            "value": 500.0})
    _, loaded = bench.load_lastgood()
    assert loaded["bert"]["value"] == 500.0


def test_corrupt_store_never_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    for content in ("null", "[1,2]", '{"records": {"m": "notadict"}}',
                    '{"records": {"m": {"record": {"value": "2400"}}}}'):
        (tmp_path / "lg.json").write_text(content)
        assert bench.load_lastgood() == (None, None)
    # and persisting over a corrupt store recovers it
    (tmp_path / "lg.json").write_text("null")
    rec = {"metric": bench.PRIMARY_METRIC, "value": 5.0}
    bench.persist_lastgood(rec)
    assert bench.load_lastgood()[1] == rec


def test_persist_failure_never_raises(tmp_path, monkeypatch):
    """A persist failure must not be able to kill a successful inner run
    (the measurement is still printed/emitted by the caller)."""
    monkeypatch.setenv("BENCH_LASTGOOD_PATH",
                       str(tmp_path / "no" / "such" / "dir" / "lg.json"))
    bench = _load_bench_module()
    bench.persist_lastgood({"metric": bench.PRIMARY_METRIC, "value": 5.0})


def test_zero_value_record_not_served(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "lg.json"))
    bench = _load_bench_module()
    bench.persist_lastgood({"metric": "resnet50_train_images_per_sec_per_chip",
                            "value": 0.0, "error": "boom"})
    ts, loaded = bench.load_lastgood()
    assert loaded is None


@pytest.mark.slow
def test_simulated_wedge_emits_stale_lastgood(tmp_path):
    """End-to-end: outer supervisor + a child wedged in the backend probe
    (BENCH_SIMULATE_WEDGE sleeps before 'backend up' is ever printed, the
    exact round-3 failure shape).  The emitted JSON must carry the
    persisted measurement, stale-marked, not 0.0."""
    lg = tmp_path / "lg.json"
    rec = {"metric": "resnet50_train_images_per_sec_per_chip",
           "value": 2400.75, "unit": "img/s", "vs_baseline": 0.857,
           "mfu": 0.2991}
    lg.write_text(json.dumps({"records": {rec["metric"]: {
        "measured_at": "2026-07-30T04:38:00", "record": rec}}}))
    env = dict(os.environ)
    env.update(BENCH_LASTGOOD_PATH=str(lg), BENCH_SIMULATE_WEDGE="1",
               BENCH_PROBE_TIMEOUT="3", BENCH_TIMEOUT="30",
               BENCH_ATTEMPTS="1", BENCH_SMOKE="1")
    out = subprocess.run([sys.executable, BENCH], env=env,
                         capture_output=True, text=True, timeout=120)
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    emitted = json.loads(line)
    assert emitted["value"] == 2400.75
    assert emitted["stale"] is True
    assert emitted["measured_at"] == "2026-07-30T04:38:00"
    assert "probe" in emitted["error"]
    # the on-disk record itself is untouched by the failed run
    assert json.loads(lg.read_text())["records"][rec["metric"]][
        "record"] == rec


@pytest.mark.slow
def test_simulated_wedge_without_lastgood_emits_zero(tmp_path):
    env = dict(os.environ)
    env.update(BENCH_LASTGOOD_PATH=str(tmp_path / "absent.json"),
               BENCH_SIMULATE_WEDGE="1", BENCH_PROBE_TIMEOUT="3",
               BENCH_TIMEOUT="30", BENCH_ATTEMPTS="1", BENCH_SMOKE="1")
    out = subprocess.run([sys.executable, BENCH], env=env,
                         capture_output=True, text=True, timeout=120)
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    emitted = json.loads(line)
    assert emitted["value"] == 0.0
    assert "stale" not in emitted


def test_run_ladder_oom_fallback():
    """The batch ladder falls back on OOM only, keeps the first success,
    and re-raises a last-rung OOM or any non-OOM error (the lstm/ssd
    benches joined the ladder in r4 s3 — 128 sits one doubling from the
    measured SSD OOM point, so the fallback is load-bearing)."""
    bench = _load_bench_module()

    calls = []

    def oom_then_ok(batch):
        calls.append(batch)
        if batch > 64:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return {"batch": batch}

    assert bench._run_ladder("t", (128, 64, 32), oom_then_ok) == \
        {"batch": 64}
    assert calls == [128, 64]

    # non-OOM errors do not fall back
    def boom(batch):
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        bench._run_ladder("t", (128, 64), boom)

    # OOM on the last rung re-raises
    def always_oom(batch):
        raise RuntimeError("ran out of memory")

    with pytest.raises(RuntimeError):
        bench._run_ladder("t", (128,), always_oom)

    # a bare "hbm" mention is NOT an OOM (guard against silent fallback)
    def hbm_note(batch):
        raise RuntimeError("hbm bandwidth note, not an allocation error")

    with pytest.raises(RuntimeError):
        bench._run_ladder("t", (128, 64), hbm_note)


def test_chip_lock_contention(tmp_path):
    """bench's outer waits (bounded) for the cooperative chip lock, the
    watcher's non-blocking acquire reports busy, and a watcher child
    (TPUMX_CHIP_LOCK_HELD=1) skips acquiring the lock its parent holds."""
    import time as _time
    holder = tmp_path / "hold.py"
    holder.write_text(
        "import fcntl, sys, time\n"
        f"f = open({os.path.join(REPO, '.chip_lock')!r}, 'w')\n"
        "fcntl.flock(f, fcntl.LOCK_EX)\n"
        "print('HELD', flush=True)\n"
        "time.sleep(60)\n")
    proc = subprocess.Popen([sys.executable, str(holder)],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "HELD"
        bench = _load_bench_module()
        # child mode: parent already holds the lock -> no acquisition
        os.environ["TPUMX_CHIP_LOCK_HELD"] = "1"
        try:
            assert bench._acquire_chip_lock() is None
        finally:
            del os.environ["TPUMX_CHIP_LOCK_HELD"]
        # bounded wait: deadline passes while the holder lives -> None
        # (honest "no exclusivity"), after waiting roughly the deadline
        os.environ["TPUMX_CHIP_LOCK_WAIT"] = "2"
        try:
            t0 = _time.time()
            assert bench._acquire_chip_lock() is None
            assert 1.5 < _time.time() - t0 < 30
        finally:
            del os.environ["TPUMX_CHIP_LOCK_WAIT"]
        # watcher side: non-blocking acquire reports busy
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import tpu_watch as w
        finally:
            sys.path.pop(0)
        with pytest.raises(w.ChipBusy):
            w._chip_lock()
    finally:
        proc.kill()
        proc.wait()
    # holder dead: both sides acquire freely
    bench = _load_bench_module()
    f = bench._acquire_chip_lock()
    assert f is not None
    f.close()


def _store_with(tmp_path, monkeypatch, rec, measured_at=None):
    """Persist rec via the real persist path, optionally rewriting the
    stored measured_at (to age the record for the freshness tests)."""
    path = tmp_path / "lg.json"
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(path))
    bench = _load_bench_module()
    bench.persist_lastgood(rec)
    if measured_at is not None:
        store = json.loads(path.read_text())
        store["records"][rec["metric"]]["measured_at"] = measured_at
        path.write_text(json.dumps(store))
    return bench


def test_fresh_stored_carries_recent_record(tmp_path, monkeypatch):
    """BENCH_SKIP_FRESH: a record measured minutes ago is carried with
    carried_fresh=True and its own measured_at, so a wedge-shortened
    retry spends the window on the legs still missing."""
    rec = {"metric": "bert_base_train_seqs_per_sec_per_chip",
           "value": 790.89, "iters": 20}
    bench = _store_with(tmp_path, monkeypatch, rec)
    got = bench._fresh_stored(rec["metric"], 3600)
    assert got is not None
    assert got["value"] == 790.89
    assert got["carried_fresh"] is True
    assert got["measured_at"]


def test_fresh_stored_rejects_old_record(tmp_path, monkeypatch):
    rec = {"metric": "bert_base_train_seqs_per_sec_per_chip",
           "value": 726.09}
    bench = _store_with(tmp_path, monkeypatch, rec,
                        measured_at="2026-07-31T11:52:17+0000")
    assert bench._fresh_stored(rec["metric"], 14400) is None


def test_fresh_stored_min_iters_gates_quick_bench(tmp_path, monkeypatch):
    """The quick stage's 5-iter resnet number must never be carried as
    the official 30-iter record."""
    rec = {"metric": "resnet50_train_images_per_sec_per_chip",
           "value": 2303.33, "iters": 5}
    bench = _store_with(tmp_path, monkeypatch, rec)
    assert bench._fresh_stored(rec["metric"], 3600, min_iters=30) is None
    assert bench._fresh_stored(rec["metric"], 3600, min_iters=5) is not None


def test_fresh_stored_require_narrows_match(tmp_path, monkeypatch):
    """The r4-era compact-backbone ssd record shares the official metric
    key; require={'backbone': 'vgg16_reduced'} must reject it."""
    rec = {"metric": "ssd512_train_images_per_sec_per_chip",
           "value": 485.18, "backbone": "compact"}
    bench = _store_with(tmp_path, monkeypatch, rec)
    key = rec["metric"]
    assert bench._fresh_stored(
        key, 3600, require={"backbone": "vgg16_reduced"}) is None
    assert bench._fresh_stored(
        key, 3600, require={"backbone": "compact"}) is not None


def test_fresh_stored_rejects_error_zero_and_future(tmp_path, monkeypatch):
    key = "lstm_ptb_train_tokens_per_sec_per_chip"
    bench = _store_with(tmp_path, monkeypatch, {"metric": key, "value": 0.0})
    assert bench._fresh_stored(key, 3600) is None
    bench = _store_with(tmp_path, monkeypatch,
                        {"metric": key, "value": 100.0, "error": "wedge"})
    assert bench._fresh_stored(key, 3600) is None
    # a future-dated measured_at (clock skew) must not qualify as fresh
    import datetime
    future = (datetime.datetime.now(datetime.timezone.utc) +
              datetime.timedelta(hours=2)).strftime("%Y-%m-%dT%H:%M:%S%z")
    bench = _store_with(tmp_path, monkeypatch,
                        {"metric": key, "value": 100.0},
                        measured_at=future)
    assert bench._fresh_stored(key, 3600) is None


def test_fresh_stored_missing_store_and_key(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LASTGOOD_PATH", str(tmp_path / "absent.json"))
    bench = _load_bench_module()
    assert bench._fresh_stored("anything", 3600) is None
    bench = _store_with(tmp_path, monkeypatch,
                        {"metric": "some_other_metric", "value": 5.0})
    assert bench._fresh_stored("not_that_metric", 3600) is None


def test_fresh_stored_extra_leg_min_iters(tmp_path, monkeypatch):
    """lstm/ssd honor BENCH_ITERS too: a short manual sanity run must not
    be carried as the official leg (review finding, session 4)."""
    rec = {"metric": "lstm_ptb_train_tokens_per_sec_per_chip",
           "value": 700000.0, "iters": 3}
    bench = _store_with(tmp_path, monkeypatch, rec)
    assert bench._fresh_stored(rec["metric"], 3600, min_iters=20) is None
