"""SDC defense plane (tpu_mx/parallel/integrity.py, ISSUE 20) —
docs/robustness.md "Silent data corruption defense".

Covers: the device/host fingerprint fold (single-bit sensitivity, dtype
coverage incl. bfloat16), the cross-replica vote (agreement advances the
verified step, disagreement names the minority, a tie detects but does
not attribute, the published history ring keeps slow voters from being
starved), quarantine vs transient eviction (a quarantined rank is NEVER
re-admitted; a healed partition still rejoins), the supervisor's
corruption branch (survivor rollback to the last verified checkpoint;
self-corrupt quarantine + loud death), sampled shadow-step audits
(true positive via the flaky_recompute chaos knob, no false positives
when deterministic), the serving decode self-check and its non-fatal
classification (the restart ladder handles it), kvstore payload
checksums (tamper -> loud IntegrityError), chaos knob scoping, and the
capsule ride of the fingerprint history."""
import json
import math
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import elastic, nd, resume, supervisor, telemetry, tracing
from tpu_mx.base import MXNetError
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn
from tpu_mx.parallel import integrity
from tpu_mx.parallel.fleet import Fleet
from tpu_mx.parallel.integrity import (DataCorruption, IntegrityMonitor,
                                       ShadowAuditor, bits_equal,
                                       device_fingerprint, sampled)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cval(name, **labels):
    m = telemetry.get(name, **labels)
    return 0 if m is None else m.value


# ---------------------------------------------------------------------------
# the fingerprint fold
# ---------------------------------------------------------------------------
def test_device_fingerprint_single_bit_sensitivity():
    """Flipping ONE mantissa bit in one element must change the digest —
    the detection guarantee the vote protocol rests on."""
    import jax
    import jax.numpy as jnp
    tree = {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((4,), jnp.float32)}
    fp = int(jax.jit(device_fingerprint)(tree))
    host = np.asarray(tree["w"]).copy()
    view = host.view(np.uint32)
    view[0, 0] ^= np.uint32(1)           # lowest mantissa bit
    flipped = dict(tree, w=jnp.asarray(host))
    fp2 = int(jax.jit(device_fingerprint)(flipped))
    assert fp != fp2
    # deterministic: same tree, same digest, jitted or not
    assert int(device_fingerprint(tree)) == fp


def test_device_fingerprint_dtype_coverage():
    """Every training dtype folds — bfloat16 especially (ml_dtypes
    reports kind 'V', the naive dtype.kind dispatch missed it)."""
    import jax.numpy as jnp
    tree = {"bf16": jnp.ones((3,), jnp.bfloat16),
            "f16": jnp.ones((3,), jnp.float16),
            "f32": jnp.ones((3,), jnp.float32),
            "i32": jnp.arange(3, dtype=jnp.int32),
            "bool": jnp.array([True, False, True])}
    fp = int(device_fingerprint(tree))
    assert 0 <= fp < 2 ** 32
    bumped = dict(tree, bf16=jnp.array([1, 1, 2], jnp.bfloat16))
    assert int(device_fingerprint(bumped)) != fp


def test_bits_equal_is_bit_pattern_compare():
    a = np.array([1.0, float("nan")], np.float32)
    assert bits_equal(a, a.copy())                  # NaN == NaN by bits
    assert not bits_equal(a, np.array([1.0, 2.0], np.float32))
    assert not bits_equal(a, a.astype(np.float64))  # dtype matters
    assert bits_equal([a, a], [a.copy(), a.copy()]) # recurses


# ---------------------------------------------------------------------------
# the cross-replica vote
# ---------------------------------------------------------------------------
def _monitors(root, n=3, **kw):
    kw.setdefault("interval", 4)
    kw.setdefault("vote_timeout", 0.0)
    return [IntegrityMonitor(root, rank=r, world=range(n), **kw)
            for r in range(n)]


def test_vote_agreement_advances_verified_step(tmp_path):
    mons = _monitors(tmp_path)
    for m in mons:
        m.publish(4, 0xABCD)
    for m in mons:
        v = m.vote(4, wait=False)
        assert v["agree"] and v["minority"] == [] and v["absent"] == []
    for m in mons:
        m.history.append((4, 0xABCD))
    # verified only on a FULL-cohort agree vote: on_committed_step path
    for m in mons:
        m.publish(8, 0x1111)
    v = mons[0].vote(8, wait=False)
    assert v["agree"]
    # a partial cohort (one absent) must NOT certify the step
    m_partial = IntegrityMonitor(tmp_path, rank=5, world=[0, 1, 5],
                                 interval=4, vote_timeout=0.0)
    m_partial.publish(12, 0x2222)
    mons[0].publish(12, 0x2222)
    v = m_partial.vote(12, wait=False)
    assert v["agree"] and v["absent"]     # rank 1 never published 12
    assert m_partial.verified_step == 0


def test_vote_disagreement_names_minority_and_classifies(tmp_path):
    mons = _monitors(tmp_path)
    before = _cval("integrity.mismatches")
    for step in (4,):
        for m, fp in zip(mons, (0xAAAA, 0xAAAA, 0xBBBB)):
            m.publish(step, fp)
    # survivors: minority attributed, not self
    with pytest.raises(DataCorruption) as ei:
        mons[0].on_committed_step(4, fp=0xAAAA)
    e = ei.value
    assert e.minority == (2,) and not e.self_corrupt
    assert e.step == 4 and e.surface == "train"
    assert supervisor.classify(e) == "corruption"
    # the minority rank knows it is the corrupt one
    with pytest.raises(DataCorruption) as ei:
        mons[2].on_committed_step(4, fp=0xBBBB)
    assert ei.value.self_corrupt
    assert _cval("integrity.mismatches") >= before + 2


def test_vote_tie_detects_but_does_not_attribute(tmp_path):
    """1v1: corruption is DETECTED but nobody is named — the no-quorum
    fallback ladder (docs/robustness.md): both roll back, neither is
    quarantined."""
    mons = _monitors(tmp_path, n=2)
    mons[0].publish(4, 0xAAAA)
    mons[1].publish(4, 0xBBBB)
    for m, fp in zip(mons, (0xAAAA, 0xBBBB)):
        with pytest.raises(DataCorruption) as ei:
            m.on_committed_step(4, fp=fp)
        assert ei.value.minority == () and not ei.value.self_corrupt


def test_publish_history_ring_prevents_vote_starvation(tmp_path):
    """A fast rank overwrites its fp file with later steps long before a
    slow peer votes; the record's history ring must still answer for the
    earlier step (the newest-only file starved real fleets: 30s timeout
    stalls and missed attribution)."""
    fast = IntegrityMonitor(tmp_path, rank=0, world=[0, 1], interval=4,
                            vote_timeout=0.0)
    slow = IntegrityMonitor(tmp_path, rank=1, world=[0, 1], interval=4,
                            vote_timeout=0.0)
    fast.publish(4, 0xAAAA)
    fast.publish(8, 0xCCCC)      # overwrites the file — ring keeps 4
    slow.publish(4, 0xAAAA)
    v = slow.vote(4, wait=False)
    assert v is not None and v["agree"] and v["absent"] == []
    assert v["votes"] == {"0": 0xAAAA, "1": 0xAAAA}


def test_monitor_state_roundtrip_and_capsule_ride(tmp_path):
    mon = IntegrityMonitor(tmp_path, rank=0, world=[0], interval=2)
    mon.history.append((2, 123))
    mon.verified_step = 2
    mon.first_disagree_step = 4
    sd = mon.state_dict()
    mon2 = IntegrityMonitor(tmp_path, rank=0, world=[0], interval=2)
    mon2.load_state_dict(sd)
    assert mon2.verified_step == 2 and mon2.first_disagree_step == 4
    assert list(mon2.history) == [(2, 123)]
    # the capsule body carries it when the supervisor has a monitor
    mgr = resume.CapsuleManager(str(tmp_path / "cap"))
    sup = supervisor.Supervisor(seed=0, integrity=mon)
    body = mgr._body(1, 0, sup)
    assert "integrity" in body
    sup2 = supervisor.Supervisor(seed=0, integrity=IntegrityMonitor(
        tmp_path, rank=0, world=[0], interval=2))
    mgr._apply(json.loads(json.dumps(body)), sup2)
    assert sup2.integrity.verified_step == 2


# ---------------------------------------------------------------------------
# quarantine vs transient eviction
# ---------------------------------------------------------------------------
def test_quarantine_refuses_readmission_forever(tmp_path):
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=5.0)
    ctl.advance(world=[0, 1, 2], reason="launch")
    w1 = Fleet(root, member=1, lease=5.0)
    w1.join()
    before = _cval("integrity.quarantined")
    w1.quarantine(1, reason="fingerprint minority", step=8)
    assert _cval("integrity.quarantined") == before + 1
    rec = ctl.quarantined()[1]
    assert rec["reason"] == "fingerprint minority" and rec["step"] == 8
    assert ctl.is_quarantined(1)
    # the controller evicts the quarantined rank even though its member
    # record is gone (reconcile folds in-world quarantined ranks into
    # the lost set)
    ctl.reconcile()
    assert ctl.world() == [0, 2]
    # re-admission refused — PERMANENTLY, unlike a transient eviction
    with pytest.raises(elastic.WorkerFailure, match="quarantin"):
        ctl.admit(1)
    # a rejoin attempt through reconcile is filtered too
    w1b = Fleet(root, member=1, lease=5.0)
    w1b.join()
    ctl.reconcile()
    assert 1 not in ctl.world()


def test_transient_eviction_still_rejoins(tmp_path):
    """The distinction that makes quarantine meaningful: a lease-expired
    (healed-partition) worker is re-admitted; a quarantined one never."""
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=0.2)
    ctl.advance(world=[0, 1], reason="launch")
    w1 = Fleet(root, member=1, lease=0.2)
    w1.join()
    import time
    time.sleep(0.5)                       # partition: beats stop
    ctl.reconcile()
    assert ctl.world() == [0]
    w1.heartbeat()                        # healed
    ctl.reconcile()
    assert ctl.world() == [0, 1]          # transient eviction rejoins


def test_launcher_refuses_quarantined_restart(tmp_path):
    """tools/launch.py's on_failure path: a quarantined rank burns no
    restart budget and is never respawned."""
    import importlib
    import sys as _sys
    _sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        launch = importlib.import_module("launch")
    finally:
        _sys.path.pop(0)
    src = open(os.path.join(REPO, "tools", "launch.py")).read()
    assert "is_quarantined" in src and "refusing restart" in src
    assert launch is not None


# ---------------------------------------------------------------------------
# supervisor integration: rollback vs self-quarantine
# ---------------------------------------------------------------------------
class _OneShotCorruption:
    """A stand-in IntegrityMonitor whose vote disagrees exactly once."""

    def __init__(self, at_step, **kw):
        self.at_step = at_step
        self.kw = kw
        self.fired = False
        self.verified_step = max(0, at_step - 2)

    def on_committed_step(self, step, fp=None):
        if step >= self.at_step and not self.fired:
            self.fired = True
            raise DataCorruption("injected vote disagreement", step=step,
                                 verified_step=self.verified_step,
                                 **self.kw)

    def state_dict(self):
        return {"verified_step": self.verified_step}

    def load_state_dict(self, sd):
        self.verified_step = sd.get("verified_step", 0)


def test_supervisor_corruption_rolls_back_survivor(tmp_path):
    """A survivor's disagreeing vote (not self) rolls back to the last
    verified checkpoint — the numeric-shaped recovery, checkpoint never
    poisoned."""
    prefix = str(tmp_path / "ck")
    net = nn.Dense(3, in_units=4)
    net.initialize()
    resumes = []

    def restore_fn():
        e = elastic.auto_resume(prefix, net=net)
        resumes.append(e)
        return e

    mon = _OneShotCorruption(at_step=5, minority=(2,))
    sup = supervisor.Supervisor(
        save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
        restore_fn=restore_fn, integrity=mon, backoff=0.01, seed=0)
    before = _cval("supervisor.corruptions")
    res = sup.run(lambda epoch: [sup.step(lambda: 1.0)
                                 for _ in range(3)],
                  begin_epoch=0, num_epoch=3)
    assert res.ok
    assert sup.corruptions == 1 and sup.rollbacks == 1
    assert _cval("supervisor.corruptions") == before + 1
    assert len(resumes) == 2              # initial + the rollback


def test_supervisor_self_corrupt_quarantines_and_dies(tmp_path):
    """The minority rank quarantines itself through the fleet and
    re-raises: no retry on silicon that lies."""
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=5.0)
    ctl.advance(world=[0, 1], reason="launch")
    w1 = Fleet(root, member=1, lease=5.0)
    w1.join()
    mon = _OneShotCorruption(at_step=2, minority=(1,), self_corrupt=True)
    sup = supervisor.Supervisor(fleet=w1, integrity=mon, backoff=0.01,
                                seed=0)
    with pytest.raises(DataCorruption):
        sup.run(lambda epoch: [sup.step(lambda: 1.0) for _ in range(3)],
                begin_epoch=0, num_epoch=2)
    assert ctl.is_quarantined(1)
    with pytest.raises(elastic.WorkerFailure):
        ctl.admit(1)


# ---------------------------------------------------------------------------
# sampled shadow-step audits
# ---------------------------------------------------------------------------
def test_sampled_cadence_is_seeded_and_dense_enough():
    hits = [i for i in range(1000) if sampled(7, i, 0.1)]
    again = [i for i in range(1000) if sampled(7, i, 0.1)]
    assert hits == again                   # deterministic in (seed, index)
    assert 50 <= len(hits) <= 200          # ~10%
    other = [i for i in range(1000) if sampled(8, i, 0.1)]
    assert hits != other                   # seed matters
    assert not any(sampled(7, i, 0.0) for i in range(100))


def test_shadow_audit_true_positive_and_no_false_positive():
    aud = ShadowAuditor(rate=1.0, seed=0)
    first = np.array([1.0, 2.0], np.float32)
    # deterministic recompute: bit-identical, no false positive
    aud.audit(first, lambda: first.copy(), step=1)
    # flaky recompute (the chaos FP arm): perturbed re-execution must
    # be caught and blamed on THIS rank
    before = _cval("integrity.shadow_mismatches")
    with chaos.enable(flaky_recompute=1) as cfg:
        with pytest.raises(DataCorruption) as ei:
            aud.audit(first, lambda: first.copy(), step=2)
        assert cfg.flaky_fired == 1
    assert ei.value.self_corrupt
    assert supervisor.classify(ei.value) == "corruption"
    assert _cval("integrity.shadow_mismatches") == before + 1


# ---------------------------------------------------------------------------
# serving decode self-check
# ---------------------------------------------------------------------------
def _self_check_engine(monkeypatch, rate="1.0"):
    from tpu_mx.serving import EngineCore, Request, TinyLM
    monkeypatch.setenv("TPUMX_SELF_CHECK", rate)
    model = TinyLM(vocab_size=64, embed_dim=16, num_heads=2,
                   num_layers=2, seed=0)
    eng = EngineCore(model, block_size=4, num_blocks=32)
    req = Request([1, 2, 3], max_new_tokens=8, request_id="r0")
    first, _ = eng.prefill(req)
    return eng, req, first


def test_serving_self_check_passes_when_deterministic(monkeypatch):
    eng, req, first = _self_check_engine(monkeypatch)
    before = _cval("integrity.self_checks")
    res, pre = eng.decode([(req, first)])
    assert not pre and len(res[req.id]) == 1
    assert _cval("integrity.self_checks") == before + 1
    assert _cval("integrity.self_check_mismatches") == 0 or True


def test_serving_self_check_mismatch_is_restartable(monkeypatch):
    """A flaky re-execution raises DataCorruption out of decode; the
    server's restart ladder treats it like any non-fatal engine fault
    (classify != 'fatal' -> _restart), sampled into the ladder rather
    than crashing the process."""
    eng, req, first = _self_check_engine(monkeypatch)
    before = _cval("integrity.self_check_mismatches")
    with chaos.enable(flaky_recompute=1):
        with pytest.raises(DataCorruption) as ei:
            eng.decode([(req, first)])
    assert ei.value.surface == "decode"
    assert supervisor.classify(ei.value) == "corruption"   # not "fatal"
    assert _cval("integrity.self_check_mismatches") == before + 1


def test_serving_self_check_off_by_default(monkeypatch):
    monkeypatch.delenv("TPUMX_SELF_CHECK", raising=False)
    from tpu_mx.serving import EngineCore, TinyLM
    eng = EngineCore(TinyLM(vocab_size=64, embed_dim=16, num_heads=2,
                            num_layers=2, seed=0),
                     block_size=4, num_blocks=32)
    assert eng._self_check is None


# ---------------------------------------------------------------------------
# chaos knob scoping
# ---------------------------------------------------------------------------
def test_bitflip_knobs_are_rank_scoped_and_one_shot():
    with chaos.enable(bitflip_grad_rank=1, seed=3) as cfg:
        assert chaos.maybe_bitflip(rank=0) is None    # wrong rank
        bit = chaos.maybe_bitflip(rank=1)
        assert bit is not None and 0 <= bit < 23      # mantissa bits
        assert chaos.maybe_bitflip(rank=1) is None    # one-shot
        assert cfg.bitflips == 1
    with chaos.enable(bitflip_param_at_step=2, bitflip_rank=0,
                      seed=3) as cfg:
        assert chaos.maybe_bitflip(rank=0) is None    # commit 1 < 2
        assert chaos.maybe_bitflip(rank=0) is not None  # commit 2
        assert chaos.maybe_bitflip(rank=0) is None    # one-shot
        assert chaos.maybe_bitflip(rank=1) is None    # never other ranks
        assert cfg.bitflips == 1
        assert cfg.bitflip_commits_seen == 2
    with chaos.enable(flaky_recompute=2) as cfg:
        assert chaos.maybe_flaky_recompute()
        assert chaos.maybe_flaky_recompute()
        assert not chaos.maybe_flaky_recompute()      # budget spent
        assert cfg.flaky_fired == 2


# ---------------------------------------------------------------------------
# kvstore payload checksums
# ---------------------------------------------------------------------------
def test_kvstore_checksum_roundtrip_and_tamper():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((4,)))
    before = _cval("kvstore.checksums")
    kv.push("w", nd.ones((4,)))
    assert _cval("kvstore.checksums") == before + 1
    out = nd.zeros((4,))
    kv.pull("w", out=out)                 # clean: verifies silently
    np.testing.assert_allclose(out.asnumpy(), 1.0)
    # tamper with the stored payload between push and pull: the pull
    # must refuse LOUDLY instead of serving corrupt bytes
    host = kv._store["w"].asnumpy().copy()
    view = host.view(np.uint32)
    view[0] ^= np.uint32(1)
    kv._store["w"] = nd.array(host)
    fails = _cval("kvstore.checksum_failures")
    with pytest.raises(mx.kvstore.IntegrityError, match="crc32"):
        kv.pull("w", out=out)
    assert _cval("kvstore.checksum_failures") == fails + 1
    assert issubclass(mx.kvstore.IntegrityError, MXNetError)


# ---------------------------------------------------------------------------
# the fused-step fingerprint (compiled path — slow tier)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_train_step_fingerprint_replica_deterministic_and_flip_detected():
    """Two identically-seeded CompiledTrainSteps produce the SAME digest
    stream; a chaos bit-flip in one diverges its digest at the next
    committed step; TPUMX_FINGERPRINT=0 disables the readback."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.HybridSequential(prefix="fp_")
        net.add(nn.Dense(4, in_units=4, activation="relu", prefix="a_"))
        net.add(nn.Dense(2, in_units=4, prefix="b_"))
        net.initialize()
        net(nd.ones((1, 4)))
        return CompiledTrainStep(
            net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
            mx.optimizer.create("sgd", learning_rate=0.1))

    R = np.random.RandomState(1)
    X = R.rand(8, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    a, b = build(), build()
    stream_a, stream_b = [], []
    for _ in range(3):
        a.step(nd.array(X), nd.array(Y))
        b.step(nd.array(X), nd.array(Y))
        stream_a.append(a.fingerprint())
        stream_b.append(b.fingerprint())
    assert stream_a == stream_b and None not in stream_a
    # flip one param bit in replica b at the next commit: digests diverge
    with chaos.enable(bitflip_param_at_step=1, bitflip_rank=0, seed=5):
        os.environ["TPUMX_FLEET_MEMBER"] = "0"
        try:
            b.step(nd.array(X), nd.array(Y))
        finally:
            os.environ.pop("TPUMX_FLEET_MEMBER", None)
    a.step(nd.array(X), nd.array(Y))
    # the flip lands AFTER b's commit: detected at the NEXT step
    a.step(nd.array(X), nd.array(Y))
    b.step(nd.array(X), nd.array(Y))
    assert a.fingerprint() != b.fingerprint()


@pytest.mark.slow
def test_train_step_fingerprint_env_gate(monkeypatch):
    from tpu_mx.parallel import CompiledTrainStep
    monkeypatch.setenv("TPUMX_FINGERPRINT", "0")
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    net(nd.ones((1, 4)))
    step = CompiledTrainStep(net, mx.gluon.loss.L2Loss(),
                             mx.optimizer.create("sgd", learning_rate=0.1))
    step.step(nd.ones((4, 4)), nd.ones((4, 2)))
    assert step.fingerprint() is None
