"""tpumx-lint (tools/tpumx_lint.py + tools/lint/): the static checker.

Per ISSUE 6 acceptance: every pass is demonstrated to BOTH fire on its
target pattern AND stay silent on the nearest legitimate look-alike
(atomic_write's own open, tpu_mx/random.py's own PRNGKey, a seeded
private RandomState, host np.prod in a hot path, ...), plus the
suppression- and baseline-mechanism tests and the repo-wide gate: the
tree this test suite ships with must lint clean.

ISSUE 10 added the interprocedural tier: caller-holds-lock proofs and
their FP guards, transitive unlocked-mutation witnesses, hot-path-purity
through one and two helper hops (incl. the PR-9 eager-asarray-in-decode
regression fixture), the wrapped-raw-open durability hop, re-exported
emitter aliases across modules, and index round-trip/staleness.
Multi-file fixtures go through ``lint_sources({relpath: src, ...})`` —
one project index spans the set, exactly like the real run.

No jax needed: the linter is pure stdlib and these tests drive it on
in-memory fixture snippets via ``lint_source(src, fake_relpath)``.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
sys.path.insert(0, TOOLS)

import tpumx_lint  # noqa: E402

CATALOG = frozenset({"fusion.flushes", "train_step.steps"})
EVENT_CATALOG = frozenset({"chaos.inject", "supervisor.restart"})


def run(src, path, rules=None, known=CATALOG, known_events=EVENT_CATALOG):
    found, suppressed = tpumx_lint.lint_source(
        textwrap.dedent(src), path, known_metrics=known, rules=rules,
        known_events=known_events)
    return found, suppressed


def run_multi(files, rules=None, known=CATALOG,
              known_events=EVENT_CATALOG):
    """Multi-file fixture: ONE project index spans the whole dict, so
    cross-module call chains and re-exports resolve (ISSUE 10)."""
    found, suppressed = tpumx_lint.lint_sources(
        {p: textwrap.dedent(s) for p, s in files.items()},
        known_metrics=known, rules=rules, known_events=known_events)
    return found, suppressed


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# durability
# ---------------------------------------------------------------------------
def test_durability_fires_on_raw_state_writes():
    found, _ = run("""
        import pickle
        import numpy as np

        def save(path, obj, arr):
            with open(path, "wb") as f:      # raw binary write
                f.write(b"x")
            pickle.dump(obj, open(path, "wb"))
            np.save("model.params", arr)
        """, "tpu_mx/foo.py", rules={"durability"})
    assert len(found) == 4  # two opens, one pickle.dump, one np.save
    assert set(rules_of(found)) == {"durability"}


def test_durability_silent_on_atomic_write_internals_and_reads():
    # the nearest look-alikes: the durability layer's OWN tmp open, plain
    # reads, an append-mode telemetry stream, and the serialize-to-BytesIO
    # idiom that feeds atomic_write
    found, _ = run("""
        import io
        import numpy as np

        def atomic_write(path, mode="wb"):
            raw = open(path + ".tmp", mode)   # the layer itself
            return raw

        def load(path):
            with open(path, "rb") as f:
                return f.read()

        def append_log(path, line):
            with open(path, "a") as f:
                f.write(line)

        def save(fname, payload):
            bio = io.BytesIO()
            np.savez(bio, **payload)
        """, "tpu_mx/foo.py", rules={"durability"})
    assert found == []


def test_durability_tools_scope_only_flags_state_shaped_paths():
    src = """
        import json

        def report(results):
            with open("bench_report.json", "w") as f:   # report: fine
                json.dump(results, f)

        def emergency(prefix, blob):
            with open(prefix + "-0001.params", "w") as f:   # state!
                f.write(blob)
        """
    found, _ = run(src, "tools/report.py", rules={"durability"})
    assert len(found) == 1
    assert "params" in found[0].message
    # the same source in library scope flags BOTH writes
    found_lib, _ = run(src, "tpu_mx/report.py", rules={"durability"})
    assert len(found_lib) == 2


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------
def test_determinism_fires_on_stray_rng():
    found, _ = run("""
        import time
        import numpy as np
        import jax

        def augment(x):
            return x * np.random.uniform()          # global stream

        def fresh_stream():
            return jax.random.PRNGKey(0)            # escapes capsules

        def entropy_seeded():
            return np.random.RandomState()          # OS entropy

        def wall_clock():
            rng = np.random.RandomState(int(time.time()))
            return rng
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 4
    assert set(rules_of(found)) == {"determinism"}


def test_determinism_silent_on_blessed_patterns():
    # seeded private RandomState (iterator pattern), host_rng() routing,
    # and take_key() are all contract-compliant
    found, _ = run("""
        import numpy as np
        from .random import host_rng, take_key

        class It:
            def __init__(self, seed):
                self._rng = np.random.RandomState(seed)

        def augment(x):
            return x * host_rng().uniform()

        def draw():
            return take_key()
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert found == []


def test_determinism_keyword_seed_is_seeded():
    # RandomState(seed=7) is the same blessed pattern as RandomState(7)
    found, _ = run("""
        import numpy as np
        a = np.random.RandomState(seed=7)
        b = np.random.default_rng(seed=0)
        c = np.random.RandomState(seed=None)    # explicit None: entropy
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 1
    assert found[0].line_text.strip().startswith("c =")


def test_determinism_exempts_the_framework_rng_and_tools():
    src = """
        import jax
        import numpy as np
        key = jax.random.PRNGKey(0)
        np.random.seed(7)
        """
    # tpu_mx/random.py IS the framework stream: its PRNGKey is the point
    found, _ = run(src, "tpu_mx/random.py", rules={"determinism"})
    assert found == []
    # tools are entry points that seed themselves; library scope only
    found, _ = run(src, "tools/bench_helper.py", rules={"determinism"})
    assert found == []
    found, _ = run(src, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 2


def test_determinism_time_seeding_flagged_everywhere():
    found, _ = run("""
        import random
        import time
        import numpy as np
        r = random.Random(time.time_ns())
        g = np.random.default_rng(seed=time.time_ns())   # keyword spelling
        """, "tools/launch_helper.py", rules={"determinism"})
    assert len(found) == 2
    assert all("wall-clock" in f.message for f in found)


def test_determinism_flags_typed_key_constructor():
    # jax.random.key() is the current recommended constructor — the same
    # capsule-escaping fresh stream as the legacy PRNGKey
    found, _ = run("""
        import jax
        k = jax.random.key(0)
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 1 and "take_key" in found[0].message
    # but an unrelated .key attribute call is not an RNG constructor
    found, _ = run("""
        def f(holder):
            return holder.key(0)
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert found == []


# ---------------------------------------------------------------------------
# sync-point
# ---------------------------------------------------------------------------
def test_sync_point_fires_in_hot_paths():
    src = """
        def flush(seg, loss):
            host = seg.out.asnumpy()            # implicit sync
            scalar = loss.item()                # implicit sync
            mean = float(loss.mean())           # blocking reduction
            return host, scalar, mean
        """
    found, _ = run(src, "tpu_mx/fusion.py", rules={"sync-point"})
    assert len(found) == 3
    assert set(rules_of(found)) == {"sync-point"}
    # optimizer scope: only update*/create_state*/step bodies are hot
    found, _ = run("""
        def update_core(w, g):
            return float(g.mean())
        def helper(g):
            return float(g.mean())
        """, "tpu_mx/optimizer/optimizer.py", rules={"sync-point"})
    assert len(found) == 1
    assert found[0].context == "update_core"


def test_sync_point_silent_on_look_alikes():
    found, _ = run("""
        import numpy as np

        def step(self, cfg, shape, x):
            lr = float(cfg.lr)                  # plain attribute: host
            thr = float(cfg.get("thr", 0.5))    # dict method: host
            n = int(np.prod(shape))             # host math on a shape
            x.wait_to_read()                    # EXPLICIT sync: allowed
            x.block_until_ready()               # EXPLICIT sync: allowed
            return lr, thr, n
        """, "tpu_mx/parallel/train_step.py", rules={"sync-point"})
    assert found == []
    # identical code OUTSIDE a hot path is never flagged
    found, _ = run("""
        def report(loss):
            return float(loss.mean()), loss.asnumpy()
        """, "tpu_mx/metric.py", rules={"sync-point"})
    assert found == []


# ---------------------------------------------------------------------------
# concurrency
# ---------------------------------------------------------------------------
def test_concurrency_fires_on_thread_and_lock_misuse():
    found, _ = run("""
        import threading

        class Loop:
            def __init__(self):
                self._lock = threading.Lock()
                self.gen = 0

            def start(self):
                t = threading.Thread(target=self.run)   # no daemon=
                t.start()

            def bump(self):
                with self._lock:
                    self.gen += 1

            def reset(self):
                self.gen = 0        # lock-free mutation of a guarded attr
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 2
    msgs = " ".join(f.message for f in found)
    assert "daemon" in msgs and "lock" in msgs


def test_concurrency_silent_on_disciplined_code():
    found, _ = run("""
        import threading

        class Loop:
            def __init__(self):
                self._lock = threading.Lock()
                self.gen = 0          # pre-publication: no thread yet

            def start(self):
                self.w = threading.Thread(target=self.run, daemon=True)
                self.w.start()
                j = threading.Thread(target=self.run, daemon=False)
                j.start()
                j.join()

            def bump(self):
                with self._lock:
                    self.gen += 1

            def free(self):
                self.other = 1        # never lock-guarded anywhere: fine
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_join_rule_ignores_path_and_string_joins():
    # os.path.join / ", ".join must not vacuously satisfy the
    # non-daemon-needs-a-join rule; a real t.join() must
    src = textwrap.dedent("""
        import os
        import threading

        def go(f):
            p = os.path.join("a", "b")
            s = ", ".join(["x"])
            t = threading.Thread(target=f, daemon=False)
            t.start()
            {join}return p, s
        """)
    found, _ = run(src.format(join=""), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert len(found) == 1 and "join" in found[0].message
    found, _ = run(src.format(join="t.join()\n    "), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert found == []


def test_concurrency_module_level_lock_dict_pair_fires():
    # the checkpoint._intended shape (ROADMAP limitation closed in
    # ISSUE 8): module-level lock/state pairs, not just class-scoped
    found, _ = run("""
        import threading

        _lock = threading.Lock()
        _intended = {}
        _count = 0

        def put(key, info):
            with _lock:
                _intended[key] = info

        def evict(key):
            _intended[key] = None       # lock-free subscript mutation

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0                  # lock-free global rebind
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 2
    msgs = " ".join(f.message for f in found)
    assert "_intended" in msgs and "_count" in msgs
    assert "module global" in msgs


def test_concurrency_module_level_silent_on_look_alikes():
    found, _ = run("""
        import threading

        _lock = threading.Lock()
        _intended = {}
        _env_parsed = False

        _intended["init"] = 1           # import time: pre-publication

        class Boot:
            _intended_copy = dict(_intended)   # class body: import time

        def put(key, info):
            with _lock:
                _intended[key] = info

        def parse():
            # never lock-guarded anywhere: single-discipline, fine
            global _env_parsed
            _env_parsed = True

        def local_shadow(_intended):
            _intended["x"] = 1          # parameter shadows the global

        def local_rebind():
            _intended = {}              # no global decl: a local
            _intended["x"] = 1
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_module_level_closure_under_lock_still_unguarded():
    # defining a function under a lock does not RUN it under the lock
    found, _ = run("""
        import threading

        _lock = threading.Lock()
        _state = {}

        def guarded(k, v):
            with _lock:
                _state[k] = v

        def maker():
            with _lock:
                def inner(k):
                    _state[k] = 0       # runs later, lock-free
                return inner
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 1 and "_state" in found[0].message


def test_concurrency_thread_alias_and_annotated_assign():
    # `from threading import Thread as T` must still be detected, and an
    # ANNOTATED lock-free assignment of a guarded attr must still flag
    found, _ = run("""
        from threading import Thread as T

        class C:
            def start(self, f):
                T(target=f).start()          # aliased, no daemon=

            def bump(self):
                with self._lock:
                    self.gen = 1

            def reset(self):
                self.gen: int = 0            # annotated, lock-free
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 2
    # a local class merely named Thread is NOT threading's
    found, _ = run("""
        from mypool import Thread

        def go(f):
            Thread(target=f).start()
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_closure_inside_init_keeps_exemption():
    # an init-time helper closure runs during construction, before the
    # object is published — its assignments are pre-publication too
    found, _ = run("""
        class C:
            def __init__(self):
                def setup():
                    self.x = 1
                setup()

            def bump(self):
                with self._lock:
                    self.x = 2
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_closure_under_lock_is_not_guarded():
    # defining a function under a lock does not make its body run under
    # the lock — assignments inside it must count as UNguarded
    found, _ = run("""
        class C:
            def a(self):
                with self._lock:
                    def cb():
                        self.x = 1          # runs later, lock-free
                    self.x = 2              # guarded
                    return cb

            def b(self):
                self.x = 3                  # unguarded -> finding
        """, "tpu_mx/foo.py", rules={"concurrency"})
    # both cb's assignment and b's assignment conflict with the guard
    assert len(found) == 2


# ---------------------------------------------------------------------------
# telemetry-catalog
# ---------------------------------------------------------------------------
def test_telemetry_catalog_fires_on_unknown_and_dynamic_names():
    found, _ = run("""
        from tpu_mx import telemetry

        def instrument(name):
            telemetry.counter("fusion.flushez").inc()    # typo
            telemetry.gauge(name).set(1)                 # unverifiable
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert len(found) == 2
    assert "fusion.flushez" in found[0].message


def test_telemetry_catalog_silent_on_known_names_and_other_objects():
    found, _ = run("""
        from tpu_mx import telemetry as _telemetry

        def instrument(db):
            _telemetry.counter("fusion.flushes").inc()
            with _telemetry.span("train_step.steps"):
                pass
            db.counter("not.a.metric")     # unrelated object's .counter
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert found == []
    # the telemetry module itself manipulates names generically: exempt
    found, _ = run("""
        from tpu_mx import telemetry
        telemetry.counter("internal.name")
        """, "tpu_mx/telemetry.py", rules={"telemetry-catalog"})
    assert found == []


def test_catalog_extraction_matches_the_live_module():
    known = tpumx_lint.load_known_metrics()
    assert known is not None
    # spot-check names every PR so far instrumented
    for name in ("fusion.flushes", "checkpoint.atomic_writes",
                 "supervisor.restarts", "resume.capsules_written"):
        assert name in known


def test_tracing_catalog_fires_on_unknown_and_dynamic_event_names():
    found, _ = run("""
        from tpu_mx import tracing as _tracing

        def instrument(name):
            _tracing.emit("supervisor.restartz", n=1)   # typo
            _tracing.emit(name, kind="hang")            # unverifiable
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert len(found) == 2
    assert "supervisor.restartz" in found[0].message
    assert "KNOWN_EVENTS" in found[0].message


def test_tracing_catalog_silent_on_known_names_and_lookalikes():
    found, _ = run("""
        from tpu_mx import tracing
        from tpu_mx.tracing import emit

        def instrument(logger):
            tracing.emit("chaos.inject", kind="hang")
            emit("supervisor.restart", n=2)     # from-imported emitter
            logger.emit("not.an.event")         # unrelated object's .emit
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert found == []
    # the tracing module itself manipulates names generically: exempt
    found, _ = run("""
        from tpu_mx import tracing
        tracing.emit("internal.name")
        """, "tpu_mx/tracing.py", rules={"telemetry-catalog"})
    assert found == []


def test_event_catalog_extraction_matches_the_live_module():
    known = tpumx_lint.load_known_events()
    assert known is not None
    import tpu_mx.tracing as live
    assert known == frozenset(live.KNOWN_EVENTS)
    for name in ("chaos.inject", "supervisor.watchdog_fire",
                 "train_step.phase", "resume.capsule_restore"):
        assert name in known


# ---------------------------------------------------------------------------
# suppression mechanism
# ---------------------------------------------------------------------------
def test_suppression_inline_and_comment_block():
    src = """
        def f(path, b):
            g = open(path, "wb")  # tpumx-lint: disable=durability -- why
            # tpumx-lint: disable=durability -- long justification that
            # wraps over several comment lines before the statement
            h = open(path, "wb")
            return g, h
        """
    found, suppressed = run(src, "tpu_mx/foo.py", rules={"durability"})
    assert found == []
    assert len(suppressed) == 2


def test_suppression_is_rule_specific():
    src = """
        import numpy as np
        def f(path):
            # tpumx-lint: disable=determinism -- wrong rule on purpose
            g = open(path, "wb")
            return g
        """
    found, suppressed = run(src, "tpu_mx/foo.py", rules={"durability"})
    assert len(found) == 1 and suppressed == []
    # disable=all suppresses any rule
    src2 = src.replace("disable=determinism", "disable=all")
    found, suppressed = run(src2, "tpu_mx/foo.py", rules={"durability"})
    assert found == [] and len(suppressed) == 1


# ---------------------------------------------------------------------------
# baseline mechanism
# ---------------------------------------------------------------------------
def test_baseline_round_trip_and_line_drift(tmp_path):
    src = 'def f(p):\n    return open(p, "wb")\n'
    found, _ = tpumx_lint.lint_source(src, "tpu_mx/foo.py",
                                      rules={"durability"})
    assert len(found) == 1
    bl = tmp_path / "baseline.json"
    tpumx_lint.write_baseline(str(bl), found)
    fps = tpumx_lint.read_baseline(str(bl))
    assert found[0].fingerprint() in fps
    # unrelated lines added ABOVE must not resurrect the finding: the
    # fingerprint hashes scope + line text, not the line number
    drifted = "import os\n\n\n" + src
    found2, _ = tpumx_lint.lint_source(drifted, "tpu_mx/foo.py",
                                       rules={"durability"})
    assert len(found2) == 1
    assert found2[0].fingerprint() in fps
    assert found2[0].line != found[0].line


def test_baseline_unknown_format_rejected(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"format": "something-else", "findings": []}))
    with pytest.raises(SystemExit):
        tpumx_lint.read_baseline(str(bl))


# ---------------------------------------------------------------------------
# CLI + repo-wide gate
# ---------------------------------------------------------------------------
def test_cli_json_format_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text('import pickle\n'
                   'def f(o, p):\n'
                   '    pickle.dump(o, open(p, "wb"))\n')
    # path under tmp is not library/tools scope for open(); force it via
    # a state-shaped literal to prove scoping, then check the JSON shape
    bad2 = tmp_path / "bad2.py"
    bad2.write_text('def f(b):\n'
                    '    with open("x-0001.params", "wb") as f:\n'
                    '        f.write(b)\n')
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
         str(bad2), "--format", "json", "--baseline",
         str(tmp_path / "none.json")],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] and \
        payload["findings"][0]["rule"] == "durability"
    assert {"rule", "path", "line", "col", "message", "context",
            "fingerprint"} <= set(payload["findings"][0])


def test_cli_fails_closed_on_missing_target_and_lost_catalog(
        tmp_path, monkeypatch, capsys):
    # a typo'd path must not read as a clean lint
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
         "no_such_file.py", "--baseline", str(tmp_path / "none.json")],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "not found" in out.stdout + out.stderr
    # and a catalog the extractor cannot parse must not silently disable
    # the telemetry-catalog pass: main() fails closed with a pointed
    # message (e.g. after KNOWN_METRICS becomes a computed expression)
    assert tpumx_lint.load_known_metrics(repo=str(tmp_path)) is None
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    # main() resolves the loaders from the cli module's namespace (the
    # tpumx_lint entry point re-exports it as tpumx_lint.cli)
    monkeypatch.setattr(tpumx_lint.cli, "load_known_metrics",
                        lambda **kw: None)
    rc = tpumx_lint.main([str(ok), "--baseline",
                          str(tmp_path / "none.json")])
    assert rc == 2
    assert "KNOWN_METRICS" in capsys.readouterr().err
    # the event catalog fails closed the same way (ISSUE 7: the
    # telemetry-catalog pass covers tracing.KNOWN_EVENTS too)
    monkeypatch.undo()
    assert tpumx_lint.load_known_events(repo=str(tmp_path)) is None
    monkeypatch.setattr(tpumx_lint.cli, "load_known_events",
                        lambda **kw: None)
    rc = tpumx_lint.main([str(ok), "--baseline",
                          str(tmp_path / "none.json")])
    assert rc == 2
    assert "KNOWN_EVENTS" in capsys.readouterr().err
    # but a rules subset that excludes the catalog pass still runs
    rc = tpumx_lint.main([str(ok), "--rules", "durability",
                          "--baseline", str(tmp_path / "none.json")])
    assert rc == 0


def test_repo_lints_clean():
    """The shipped tree must have zero unsuppressed findings — this is
    the same gate tools/ci.py's lint tier enforces."""
    known = tpumx_lint.load_known_metrics()
    known_events = tpumx_lint.load_known_events()
    findings, suppressed, errors = tpumx_lint.lint_paths(
        tpumx_lint.DEFAULT_TARGETS, known_metrics=known,
        known_events=known_events)
    assert errors == []
    baseline = tpumx_lint.read_baseline(
        os.path.join(TOOLS, "tpumx_lint_baseline.json"))
    fresh = [f for f in findings if f.fingerprint() not in baseline]
    assert fresh == [], "\n".join(f.render() for f in fresh)
    # every suppression in the tree must carry a justification ("--"):
    # a bare disable hides a contract violation with no explanation
    assert len(suppressed) >= 1
    repo = os.path.dirname(TOOLS)
    for f in suppressed:
        with open(os.path.join(repo, f.path), encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        block = [lines[f.line - 1]]
        ln = f.line - 2
        while ln >= 0 and lines[ln].lstrip().startswith("#"):
            block.append(lines[ln])
            ln -= 1
        directives = [t for t in block if "tpumx-lint: disable" in t]
        assert directives, f.render()
        assert any("--" in t for t in directives), (
            f"unjustified suppression at {f.path}:{f.line} — append "
            f"'-- <why the contract does not apply>'")


# ---------------------------------------------------------------------------
# interprocedural concurrency: caller-holds-lock proofs (ISSUE 10)
# ---------------------------------------------------------------------------
def test_caller_holds_lock_helper_proven_safe():
    # the train_step._reset_accumulation shape: every call site holds the
    # lock, so the helper's lock-free mutation is PROVEN safe — the
    # suppression that used to be required is now a lint no-op
    found, _ = run("""
        import threading

        class Step:
            def __init__(self):
                self._state_lock = threading.Lock()
                self.micro = 0

            def restore(self):
                with self._state_lock:
                    self.micro = 1
                    self._reset()

            def rollback(self):
                with self._state_lock:
                    self._reset()

            def _reset(self):
                self.micro = 0      # caller provably holds the lock
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_caller_holds_lock_fp_guard_one_unlocked_caller():
    # ONE lock-free caller breaks the proof: the finding returns and
    # names the lock-free witness chain
    found, _ = run("""
        import threading

        class Step:
            def __init__(self):
                self._state_lock = threading.Lock()
                self.micro = 0

            def restore(self):
                with self._state_lock:
                    self.micro = 1
                    self._reset()

            def public(self):
                self._reset()       # no lock: the proof fails

            def _reset(self):
                self.micro = 0
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 1
    assert "reached lock-free from" in found[0].message
    assert "Step.public" in found[0].message


def test_transitive_unlocked_mutation_two_hops():
    # entry -> _mid -> _reset: the mutation two hops below an UNLOCKED
    # public entry point is a finding carrying the whole witness chain
    src = """
        import threading

        class Step:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked_set(self):
                with self._lock:
                    self.n = 1

            def entry(self):
                {lock_prefix}self._mid()

            def _mid(self):
                self._reset()

            def _reset(self):
                self.n = 0
        """
    found, _ = run(src.format(lock_prefix=""), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert len(found) == 1
    assert "Step.entry -> Step._mid -> Step._reset" in found[0].message
    # FP guard: the SAME chain with the entry taking the lock is proven
    # safe end-to-end (lock context propagates through both hops)
    locked = src.format(
        lock_prefix="with self._lock:\n                    ")
    found, _ = run(locked, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_module_global_caller_holds_lock_proven():
    # the module-scoped analog: a helper mutating a module global is
    # proven safe when its only callers hold the module lock
    src = """
        import threading

        _lock = threading.Lock()
        _state = {{}}

        def put(k, v):
            with _lock:
                _state[k] = v
                _evict(k)

        def _evict(k):
            _state[k] = None

        {extra}
        """
    found, _ = run(src.format(extra=""), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert found == []
    # FP guard: one lock-free caller and the finding is back
    found, _ = run(src.format(
        extra="def flush_all(k):\n            _evict(k)"),
        "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 1 and "_state" in found[0].message
    assert "flush_all" in found[0].message


def test_cycle_optimism_never_memoized():
    # mutual recursion _x <-> _n with ONE lock-free entry: BOTH bodies'
    # mutations must be flagged whatever the evaluation order — the
    # optimistic in-cycle assumption is correct for the outermost query
    # but must never be CACHED (a memoized provisional 'locked' verdict
    # for _n would silently discharge a real race)
    src = """
        import threading

        _lock = threading.Lock()
        _state = {{}}

        def put(k):
            with _lock:
                _state[k] = 1
                _x(k)

        def _x(k):
            _state[k] = 2
            _n(k)

        def _n(k):
            _state[k] = 3
            _x(k)

        def entry(k):
            {prefix}_x(k)
        """
    found, _ = run(src.format(prefix=""), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert len(found) == 2
    assert all("_state" in f.message for f in found)
    # FP guard: the SAME cycle with every external entry locked is the
    # documented greatest-fixpoint case — proven safe end to end
    locked = src.format(prefix="with _lock:\n                ")
    found, _ = run(locked, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_train_step_lock_proof_holds_on_the_real_tree():
    """The ISSUE 10 acceptance bar: the caller-holds-lock suppressions in
    tpu_mx/parallel/train_step.py are GONE (the pass proves the shape),
    and the proof actually discharges on the shipped file."""
    repo = os.path.dirname(TOOLS)
    rel = "tpu_mx/parallel/train_step.py"
    with open(os.path.join(repo, rel), encoding="utf-8") as f:
        src = f.read()
    assert "disable=concurrency -- caller" not in src, (
        "caller-holds-lock suppressions must stay deleted: the "
        "interprocedural pass proves them now")
    found, _ = tpumx_lint.lint_source(src, rel, rules={"concurrency"})
    assert found == [], "\n".join(f.render() for f in found)
    idx = tpumx_lint.build_index({rel: tpumx_lint.FileCtx(rel, src)})
    assert idx.always_locked(rel, "CompiledTrainStep._reset_accumulation")


# ---------------------------------------------------------------------------
# hot-path-purity (ISSUE 10)
# ---------------------------------------------------------------------------
def test_hot_path_purity_jnp_asarray_one_helper_hop():
    found, _ = run("""
        import jax.numpy as jnp

        def decode_attention(q, cache, seq_ids, layer):
            return _prep(q)

        def _prep(q):
            return jnp.asarray(q)       # eager commit, one hop from root

        def offline_tool(q):
            return jnp.asarray(q)       # unreachable from any root: fine
        """, "tpu_mx/serving/attention.py", rules={"hot-path-purity"})
    assert len(found) == 1
    assert "decode_attention -> _prep" in found[0].message
    assert found[0].context == "_prep"


def test_hot_path_purity_silent_inside_jit_boundary():
    # jnp.asarray INSIDE a jitted function is a trace-time no-op — the
    # jit boundary is the blessed commit point (nearest look-alike)
    found, _ = run("""
        import jax
        import jax.numpy as jnp

        def decode_attention(q, cache, seq_ids, layer):
            return _commit(q)

        @jax.jit
        def _commit(q):
            return jnp.asarray(q)
        """, "tpu_mx/serving/attention.py", rules={"hot-path-purity"})
    assert found == []
    # and a conversion behind an isinstance fast-path guard (the
    # NDArray.__init__ / _as_i32 shape) stays silent too
    found, _ = run("""
        import numpy as np
        import jax.numpy as jnp

        def decode_attention(q, cache, seq_ids, layer):
            return _as_dev(q)

        def _as_dev(x):
            if not isinstance(x, np.ndarray):
                x = jnp.asarray(x)      # only foreign inputs pay
            return x
        """, "tpu_mx/serving/attention.py", rules={"hot-path-purity"})
    assert found == []


def test_hot_path_purity_two_helper_hops_cross_module():
    found, _ = run_multi({
        "tpu_mx/serving/attention.py": """
            from .kv_cache import prep

            def decode_attention(q, cache, seq_ids, layer):
                return prep(q)
            """,
        "tpu_mx/serving/kv_cache.py": """
            import jax.numpy as jnp

            def prep(q):
                return _stage(q)

            def _stage(q):
                return jnp.asarray(q)   # two hops, different module
            """,
    }, rules={"hot-path-purity"})
    assert len(found) == 1
    assert found[0].path == "tpu_mx/serving/kv_cache.py"
    assert "decode_attention -> prep -> _stage" in found[0].message


def test_hot_path_purity_pr9_decode_regression():
    """The exact PR-9 cliff, as a regression fixture: a cache-write
    helper on the decode path eagerly converting its operand before the
    jitted update (~73 µs of dispatch per operand per token) — a lint
    error now.  The fixed idiom (raw operand through the jit boundary)
    is the FP guard."""
    src = """
        import jax
        import jax.numpy as jnp

        _OPS = None

        def _ops():
            global _OPS
            if _OPS is None:
                _OPS = jax.jit(lambda pool, val: pool + val)
            return _OPS

        def decode_attention(q, cache, seq_ids, layer):
            return _write(cache, q)

        def _write(pool, val):
            op = _ops()
            return op(pool, {operand})
        """
    found, _ = run(src.format(operand="jnp.asarray(val)"),
                   "tpu_mx/serving/attention.py",
                   rules={"hot-path-purity"})
    assert len(found) == 1 and "PR-9" in found[0].message
    assert "_write" in found[0].message
    # the fix: the raw operand crosses the jit boundary (C++ fast path);
    # the memo-guarded jit construction in _ops is fine either way
    found, _ = run(src.format(operand="val"),
                   "tpu_mx/serving/attention.py",
                   rules={"hot-path-purity"})
    assert found == []


def test_hot_path_purity_np_asarray_device_readback():
    found, _ = run_multi({
        "tpu_mx/kernels/mykern.py": """
            def kern(q):
                return q
            """,
        "tpu_mx/serving/attention.py": """
            import numpy as np
            from ..kernels.mykern import kern

            def decode_attention(q, cache, seq_ids, layer):
                out = np.asarray(kern(q))    # device value -> host
                shape = np.asarray([1, 2])   # host math: silent
                return out, shape
            """,
    }, rules={"hot-path-purity"})
    assert len(found) == 1
    assert "reads a device value back to host" in found[0].message
    # same shape via a kernel-bound local (the _paged_decode fn= pattern)
    found, _ = run_multi({
        "tpu_mx/kernels/mykern.py": """
            def kern_a(q):
                return q

            def kern_b(q):
                return q
            """,
        "tpu_mx/serving/attention.py": """
            import numpy as np
            from ..kernels import mykern as _pk

            def decode_attention(q, cache, seq_ids, layer):
                fn = _pk.kern_a if layer else _pk.kern_b
                return np.asarray(fn(q))
            """,
        "tpu_mx/kernels/__init__.py": "",
    }, rules={"hot-path-purity"})
    assert len(found) == 1


def test_hot_path_purity_guarded_readback_fallback_exempt():
    """The guarded-fallback idiom (ISSUE 16): an np.asarray readback
    tested behind isinstance is the sanctioned device/host-polymorphic
    normalization — the kernel returns a device array only on the arm
    that ran it, and the fallback re-binds the SAME value.  The
    unguarded sibling readback must still fire."""
    found, _ = run_multi({
        "tpu_mx/kernels/mykern.py": """
            def kern(q):
                return q
            """,
        "tpu_mx/serving/attention.py": """
            import numpy as np
            from ..kernels.mykern import kern

            def decode_attention(q, cache, seq_ids, layer):
                out = kern(q)
                if not isinstance(out, np.ndarray):
                    out = np.asarray(out)        # guarded: exempt
                bad = np.asarray(kern(q))        # unguarded: finding
                return out, bad
            """,
    }, rules={"hot-path-purity"})
    assert len(found) == 1
    assert found[0].line and "reads a device value back" in found[0].message


def test_hot_path_purity_item_and_uncached_jit():
    found, _ = run("""
        import jax

        def decode_attention(q, cache, seq_ids, layer):
            s = _scalar(q)
            return _apply(q), s

        def _scalar(q):
            return q.item()                    # readback in a helper

        def _apply(q):
            return jax.jit(lambda x: x + 1)(q)  # fresh wrapper per call
        """, "tpu_mx/serving/attention.py", rules={"hot-path-purity"})
    assert len(found) == 2
    msgs = " ".join(f.message for f in found)
    assert ".item()" in msgs and "retraces" in msgs
    # memo-guarded construction (the _dev_ops shape) is the look-alike
    found, _ = run("""
        import jax

        _F = None

        def decode_attention(q, cache, seq_ids, layer):
            return _apply(q)

        def _apply(q):
            global _F
            if _F is None:
                _F = jax.jit(lambda x: x + 1)
            return _F(q)
        """, "tpu_mx/serving/attention.py", rules={"hot-path-purity"})
    assert found == []


# ---------------------------------------------------------------------------
# one-hop helper indirection: durability + sync-point (ISSUE 10)
# ---------------------------------------------------------------------------
def test_durability_wrapped_raw_open_one_hop():
    src = """
        def save(prefix, blob):
            dump(prefix + "-0001.params", blob)     # state via a wrapper

        def report(results):
            dump("bench_notes.txt", results)        # not state: fine

        def dump(path, blob):
            with open(path, "w") as f:
                f.write(blob)
        """
    found, _ = run(src, "tools/report.py", rules={"durability"})
    assert len(found) == 1
    assert found[0].context == "save"
    assert "wrapper" in found[0].message
    # a helper named like the durability layer IS the commit layer
    found, _ = run(src.replace("dump", "write_atomic"),
                   "tools/report.py", rules={"durability"})
    assert found == []


def test_durability_library_wrapper_not_double_flagged():
    # in library scope the helper's own open is the (one) finding; the
    # call site must not duplicate it
    found, _ = run("""
        def save(prefix, blob):
            dump(prefix + "-0001.params", blob)

        def dump(path, blob):
            with open(path, "w") as f:
                f.write(blob)
        """, "tpu_mx/foo.py", rules={"durability"})
    assert len(found) == 1
    assert found[0].context == "dump"


def test_sync_point_one_helper_hop():
    files = {
        "tpu_mx/parallel/train_step.py": """
            from ..metric import read_scalar

            def step(x):
                return read_scalar(x)
            """,
        "tpu_mx/metric.py": """
            def read_scalar(x):
                return x.item()
            """,
    }
    found, _ = run_multi(files, rules={"sync-point"})
    assert len(found) == 1
    assert found[0].path == "tpu_mx/parallel/train_step.py"
    assert "tpu_mx/metric.py" in found[0].message
    assert ".item()" in found[0].message
    # a justified suppression AT THE HELPER covers its callers too
    files["tpu_mx/metric.py"] = """
        def read_scalar(x):
            # tpumx-lint: disable=sync-point -- cold-path eval readback
            return x.item()
        """
    found, _ = run_multi(files, rules={"sync-point"})
    assert found == []


# ---------------------------------------------------------------------------
# re-exported emitter aliases across modules (ISSUE 10)
# ---------------------------------------------------------------------------
def test_telemetry_catalog_follows_cross_module_reexport():
    files = {
        "tpu_mx/telemetry.py": """
            def counter(name, **labels):
                pass
            """,
        "tpu_mx/obs.py": "from .telemetry import counter\n",
        "tpu_mx/user.py": """
            from .obs import counter as C

            def f():
                C("fusion.flushez")     # typo, two re-export hops away
                C("fusion.flushes")     # known: fine
            """,
    }
    found, _ = run_multi(files, rules={"telemetry-catalog"})
    assert len(found) == 1
    assert "fusion.flushez" in found[0].message
    # FP guard: a re-exported function that merely SHARES the emitter
    # name but comes from an unrelated module is not checked
    found, _ = run_multi({
        "tpu_mx/db.py": """
            def counter(name):
                pass
            """,
        "tpu_mx/user2.py": """
            from .db import counter

            def g():
                counter("not.a.metric")
            """,
    }, rules={"telemetry-catalog"})
    assert found == []


# ---------------------------------------------------------------------------
# index: round-trip, staleness, dirty region (ISSUE 10)
# ---------------------------------------------------------------------------
LOCK_FIXTURE = textwrap.dedent("""
    import threading

    class Step:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def restore(self):
            with self._lock:
                self.n = 1
                self._reset()

        def _reset(self):
            self.n = 0
    """)


def test_index_round_trip_and_staleness(tmp_path):
    rel = "tpu_mx/foo.py"
    idx = tpumx_lint.build_index(
        {rel: tpumx_lint.FileCtx(rel, LOCK_FIXTURE)})
    assert idx.always_locked(rel, "Step._reset")
    path = tmp_path / "index.json"
    tpumx_lint.write_index(str(path), idx)
    idx2 = tpumx_lint.read_index(str(path))
    assert idx2 is not None
    assert idx2.files == idx.files
    # verdict parity from the DESERIALIZED summaries: link() rebuilds the
    # call graph without re-parsing any source
    assert idx2.always_locked(rel, "Step._reset")
    # staleness is sha-keyed: touching the source changes the entry
    touched = tpumx_lint.summarize_file(
        tpumx_lint.FileCtx(rel, LOCK_FIXTURE + "\n# touched\n"))
    assert touched["sha"] != idx.files[rel]["sha"]
    # a foreign/stale format never loads (the cache rebuilds instead)
    path.write_text(json.dumps({"format": "something-else"}))
    assert tpumx_lint.read_index(str(path)) is None
    path.write_text("{not json")
    assert tpumx_lint.read_index(str(path)) is None


def test_index_dirty_region_spans_callers_and_callees():
    ctxs = {
        "tpu_mx/a.py": "from .b import f\n\ndef top():\n    return f()\n",
        "tpu_mx/b.py": "from .c import g\n\ndef f():\n    return g()\n",
        "tpu_mx/c.py": "def g():\n    return 1\n",
        "tpu_mx/d.py": "def lonely():\n    return 2\n",
    }
    idx = tpumx_lint.build_index(
        {p: tpumx_lint.FileCtx(p, s) for p, s in ctxs.items()})
    region = idx.dirty_region({"tpu_mx/b.py"})
    # a dirty b.py can change a.py's verdicts (lock context flows down)
    # and c.py's (reachability flows up) — d.py is untouched
    assert {"tpu_mx/a.py", "tpu_mx/b.py", "tpu_mx/c.py"} <= region
    assert "tpu_mx/d.py" not in region


def test_changed_only_cli_end_to_end(tmp_path):
    """--changed-only in a scratch git repo: only the dirty file's region
    is analyzed, findings surface, and the index cache round-trips."""
    repo = tmp_path / "repo"
    pkg = repo / "tpu_mx"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "good.py").write_text("def ok():\n    return 1\n")
    # --repo makes catalog extraction repo-relative (the scratch tree's
    # OWN contracts, not the host's) — and the tool fails closed without
    # them, so the scratch repo carries minimal literal catalogs
    (pkg / "telemetry.py").write_text('KNOWN_METRICS = frozenset({"m.ok"})\n')
    (pkg / "tracing.py").write_text('KNOWN_EVENTS = frozenset({"e.ok"})\n')
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=repo, env=env, check=True, timeout=60,
                       capture_output=True)
    # dirty file with a library-scope durability violation
    (pkg / "bad.py").write_text(
        'def f(p, b):\n    with open(p, "wb") as fh:\n        fh.write(b)\n')
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
         "tpu_mx", "--changed-only", "--format", "json",
         "--repo", str(repo),
         "--baseline", str(tmp_path / "none.json"),
         "--index", str(tmp_path / "index.json")],
        capture_output=True, text=True, timeout=120, cwd=repo,
        env={**env, "PYTHONPATH": ""})
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert [f["rule"] for f in payload["findings"]] == ["durability"]
    assert payload["changed_region"] == ["tpu_mx/bad.py"]
    assert os.path.exists(tmp_path / "index.json")

    def rerun():
        out = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
             "tpu_mx", "--changed-only", "--format", "json",
             "--repo", str(repo),
             "--baseline", str(tmp_path / "none.json"),
             "--index", str(tmp_path / "index.json")],
            capture_output=True, text=True, timeout=120, cwd=repo,
            env={**env, "PYTHONPATH": ""})
        return out, json.loads(out.stdout or "{}")

    # an untracked DIRECTORY: git prints one '?? tpu_mx/sub/' line — the
    # violating file inside must still enter the changed set
    (pkg / "bad.py").write_text("def f():\n    return 0\n")
    sub = pkg / "sub"
    sub.mkdir()
    (sub / "__init__.py").write_text("")
    (sub / "worse.py").write_text(
        'def g(p, b):\n    with open(p, "wb") as fh:\n        fh.write(b)\n')
    out, payload = rerun()
    assert out.returncode == 1, out.stdout + out.stderr
    assert [f["path"] for f in payload["findings"]] \
        == ["tpu_mx/sub/worse.py"]

    # sha staleness without git dirt: commit everything (tree clean),
    # then rewrite a tracked file IN the same commit shape a pull
    # produces — the cache's sha mismatch alone must re-analyze it
    for cmd in (["git", "add", "-A"], ["git", "commit", "-qm", "r2"]):
        subprocess.run(cmd, cwd=repo, env=env, check=True, timeout=60,
                       capture_output=True)
    (pkg / "good.py").write_text(
        'def ok(p, b):\n    with open(p, "wb") as fh:\n        fh.write(b)\n')
    for cmd in (["git", "add", "-A"], ["git", "commit", "-qm", "r3"]):
        subprocess.run(cmd, cwd=repo, env=env, check=True, timeout=60,
                       capture_output=True)
    out, payload = rerun()
    assert out.returncode == 1, out.stdout + out.stderr
    assert [f["path"] for f in payload["findings"]] == ["tpu_mx/good.py"]

    # deleting a tracked file is not an error: the entry leaves the
    # cache and the deleted path still shows in the reported region
    (pkg / "good.py").unlink()
    out, payload = rerun()
    assert out.returncode == 0, out.stdout + out.stderr
    assert payload["findings"] == []
    assert "tpu_mx/good.py" in payload["changed_region"]
    idx = json.load(open(tmp_path / "index.json"))
    assert "tpu_mx/good.py" not in idx["files"]

    # --write-baseline under --changed-only would shred the full
    # baseline: rejected as a usage error
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
         "tpu_mx", "--changed-only", "--write-baseline",
         "--repo", str(repo), "--index", str(tmp_path / "index.json")],
        capture_output=True, text=True, timeout=120, cwd=repo,
        env={**env, "PYTHONPATH": ""})
    assert out.returncode == 2
    assert "full run" in out.stderr


def test_lambda_under_lock_does_not_prove_callee_locked():
    # a lambda DEFINED inside `with lock:` may run later, off-lock (the
    # deferred-callback shape): its call must NOT count as a locked
    # call site, or always_locked() would discharge a real race
    found, _ = run("""
        import threading

        class Step:
            def __init__(self):
                self._lock = threading.Lock()
                self._cbs = []
                self.n = 0

            def locked_set(self):
                with self._lock:
                    self.n = 1
                    self._cbs.append(lambda: self._reset())

            def _reset(self):
                self.n = 0
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 1
    assert "reached lock-free from" in found[0].message
