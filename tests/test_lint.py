"""tpumx-lint (tools/tpumx_lint.py): the static contract checker.

Per ISSUE 6 acceptance: every pass is demonstrated to BOTH fire on its
target pattern AND stay silent on the nearest legitimate look-alike
(atomic_write's own open, tpu_mx/random.py's own PRNGKey, a seeded
private RandomState, host np.prod in a hot path, ...), plus the
suppression- and baseline-mechanism tests and the repo-wide gate: the
tree this test suite ships with must lint clean.

No jax needed: the linter is pure stdlib and these tests drive it on
in-memory fixture snippets via ``lint_source(src, fake_relpath)``.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
sys.path.insert(0, TOOLS)

import tpumx_lint  # noqa: E402

CATALOG = frozenset({"fusion.flushes", "train_step.steps"})
EVENT_CATALOG = frozenset({"chaos.inject", "supervisor.restart"})


def run(src, path, rules=None, known=CATALOG, known_events=EVENT_CATALOG):
    found, suppressed = tpumx_lint.lint_source(
        textwrap.dedent(src), path, known_metrics=known, rules=rules,
        known_events=known_events)
    return found, suppressed


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# durability
# ---------------------------------------------------------------------------
def test_durability_fires_on_raw_state_writes():
    found, _ = run("""
        import pickle
        import numpy as np

        def save(path, obj, arr):
            with open(path, "wb") as f:      # raw binary write
                f.write(b"x")
            pickle.dump(obj, open(path, "wb"))
            np.save("model.params", arr)
        """, "tpu_mx/foo.py", rules={"durability"})
    assert len(found) == 4  # two opens, one pickle.dump, one np.save
    assert set(rules_of(found)) == {"durability"}


def test_durability_silent_on_atomic_write_internals_and_reads():
    # the nearest look-alikes: the durability layer's OWN tmp open, plain
    # reads, an append-mode telemetry stream, and the serialize-to-BytesIO
    # idiom that feeds atomic_write
    found, _ = run("""
        import io
        import numpy as np

        def atomic_write(path, mode="wb"):
            raw = open(path + ".tmp", mode)   # the layer itself
            return raw

        def load(path):
            with open(path, "rb") as f:
                return f.read()

        def append_log(path, line):
            with open(path, "a") as f:
                f.write(line)

        def save(fname, payload):
            bio = io.BytesIO()
            np.savez(bio, **payload)
        """, "tpu_mx/foo.py", rules={"durability"})
    assert found == []


def test_durability_tools_scope_only_flags_state_shaped_paths():
    src = """
        import json

        def report(results):
            with open("bench_report.json", "w") as f:   # report: fine
                json.dump(results, f)

        def emergency(prefix, blob):
            with open(prefix + "-0001.params", "w") as f:   # state!
                f.write(blob)
        """
    found, _ = run(src, "tools/report.py", rules={"durability"})
    assert len(found) == 1
    assert "params" in found[0].message
    # the same source in library scope flags BOTH writes
    found_lib, _ = run(src, "tpu_mx/report.py", rules={"durability"})
    assert len(found_lib) == 2


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------
def test_determinism_fires_on_stray_rng():
    found, _ = run("""
        import time
        import numpy as np
        import jax

        def augment(x):
            return x * np.random.uniform()          # global stream

        def fresh_stream():
            return jax.random.PRNGKey(0)            # escapes capsules

        def entropy_seeded():
            return np.random.RandomState()          # OS entropy

        def wall_clock():
            rng = np.random.RandomState(int(time.time()))
            return rng
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 4
    assert set(rules_of(found)) == {"determinism"}


def test_determinism_silent_on_blessed_patterns():
    # seeded private RandomState (iterator pattern), host_rng() routing,
    # and take_key() are all contract-compliant
    found, _ = run("""
        import numpy as np
        from .random import host_rng, take_key

        class It:
            def __init__(self, seed):
                self._rng = np.random.RandomState(seed)

        def augment(x):
            return x * host_rng().uniform()

        def draw():
            return take_key()
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert found == []


def test_determinism_keyword_seed_is_seeded():
    # RandomState(seed=7) is the same blessed pattern as RandomState(7)
    found, _ = run("""
        import numpy as np
        a = np.random.RandomState(seed=7)
        b = np.random.default_rng(seed=0)
        c = np.random.RandomState(seed=None)    # explicit None: entropy
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 1
    assert found[0].line_text.strip().startswith("c =")


def test_determinism_exempts_the_framework_rng_and_tools():
    src = """
        import jax
        import numpy as np
        key = jax.random.PRNGKey(0)
        np.random.seed(7)
        """
    # tpu_mx/random.py IS the framework stream: its PRNGKey is the point
    found, _ = run(src, "tpu_mx/random.py", rules={"determinism"})
    assert found == []
    # tools are entry points that seed themselves; library scope only
    found, _ = run(src, "tools/bench_helper.py", rules={"determinism"})
    assert found == []
    found, _ = run(src, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 2


def test_determinism_time_seeding_flagged_everywhere():
    found, _ = run("""
        import random
        import time
        import numpy as np
        r = random.Random(time.time_ns())
        g = np.random.default_rng(seed=time.time_ns())   # keyword spelling
        """, "tools/launch_helper.py", rules={"determinism"})
    assert len(found) == 2
    assert all("wall-clock" in f.message for f in found)


def test_determinism_flags_typed_key_constructor():
    # jax.random.key() is the current recommended constructor — the same
    # capsule-escaping fresh stream as the legacy PRNGKey
    found, _ = run("""
        import jax
        k = jax.random.key(0)
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert len(found) == 1 and "take_key" in found[0].message
    # but an unrelated .key attribute call is not an RNG constructor
    found, _ = run("""
        def f(holder):
            return holder.key(0)
        """, "tpu_mx/foo.py", rules={"determinism"})
    assert found == []


# ---------------------------------------------------------------------------
# sync-point
# ---------------------------------------------------------------------------
def test_sync_point_fires_in_hot_paths():
    src = """
        def flush(seg, loss):
            host = seg.out.asnumpy()            # implicit sync
            scalar = loss.item()                # implicit sync
            mean = float(loss.mean())           # blocking reduction
            return host, scalar, mean
        """
    found, _ = run(src, "tpu_mx/fusion.py", rules={"sync-point"})
    assert len(found) == 3
    assert set(rules_of(found)) == {"sync-point"}
    # optimizer scope: only update*/create_state*/step bodies are hot
    found, _ = run("""
        def update_core(w, g):
            return float(g.mean())
        def helper(g):
            return float(g.mean())
        """, "tpu_mx/optimizer/optimizer.py", rules={"sync-point"})
    assert len(found) == 1
    assert found[0].context == "update_core"


def test_sync_point_silent_on_look_alikes():
    found, _ = run("""
        import numpy as np

        def step(self, cfg, shape, x):
            lr = float(cfg.lr)                  # plain attribute: host
            thr = float(cfg.get("thr", 0.5))    # dict method: host
            n = int(np.prod(shape))             # host math on a shape
            x.wait_to_read()                    # EXPLICIT sync: allowed
            x.block_until_ready()               # EXPLICIT sync: allowed
            return lr, thr, n
        """, "tpu_mx/parallel/train_step.py", rules={"sync-point"})
    assert found == []
    # identical code OUTSIDE a hot path is never flagged
    found, _ = run("""
        def report(loss):
            return float(loss.mean()), loss.asnumpy()
        """, "tpu_mx/metric.py", rules={"sync-point"})
    assert found == []


# ---------------------------------------------------------------------------
# concurrency
# ---------------------------------------------------------------------------
def test_concurrency_fires_on_thread_and_lock_misuse():
    found, _ = run("""
        import threading

        class Loop:
            def __init__(self):
                self._lock = threading.Lock()
                self.gen = 0

            def start(self):
                t = threading.Thread(target=self.run)   # no daemon=
                t.start()

            def bump(self):
                with self._lock:
                    self.gen += 1

            def reset(self):
                self.gen = 0        # lock-free mutation of a guarded attr
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 2
    msgs = " ".join(f.message for f in found)
    assert "daemon" in msgs and "lock" in msgs


def test_concurrency_silent_on_disciplined_code():
    found, _ = run("""
        import threading

        class Loop:
            def __init__(self):
                self._lock = threading.Lock()
                self.gen = 0          # pre-publication: no thread yet

            def start(self):
                self.w = threading.Thread(target=self.run, daemon=True)
                self.w.start()
                j = threading.Thread(target=self.run, daemon=False)
                j.start()
                j.join()

            def bump(self):
                with self._lock:
                    self.gen += 1

            def free(self):
                self.other = 1        # never lock-guarded anywhere: fine
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_join_rule_ignores_path_and_string_joins():
    # os.path.join / ", ".join must not vacuously satisfy the
    # non-daemon-needs-a-join rule; a real t.join() must
    src = textwrap.dedent("""
        import os
        import threading

        def go(f):
            p = os.path.join("a", "b")
            s = ", ".join(["x"])
            t = threading.Thread(target=f, daemon=False)
            t.start()
            {join}return p, s
        """)
    found, _ = run(src.format(join=""), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert len(found) == 1 and "join" in found[0].message
    found, _ = run(src.format(join="t.join()\n    "), "tpu_mx/foo.py",
                   rules={"concurrency"})
    assert found == []


def test_concurrency_module_level_lock_dict_pair_fires():
    # the checkpoint._intended shape (ROADMAP limitation closed in
    # ISSUE 8): module-level lock/state pairs, not just class-scoped
    found, _ = run("""
        import threading

        _lock = threading.Lock()
        _intended = {}
        _count = 0

        def put(key, info):
            with _lock:
                _intended[key] = info

        def evict(key):
            _intended[key] = None       # lock-free subscript mutation

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0                  # lock-free global rebind
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 2
    msgs = " ".join(f.message for f in found)
    assert "_intended" in msgs and "_count" in msgs
    assert "module global" in msgs


def test_concurrency_module_level_silent_on_look_alikes():
    found, _ = run("""
        import threading

        _lock = threading.Lock()
        _intended = {}
        _env_parsed = False

        _intended["init"] = 1           # import time: pre-publication

        class Boot:
            _intended_copy = dict(_intended)   # class body: import time

        def put(key, info):
            with _lock:
                _intended[key] = info

        def parse():
            # never lock-guarded anywhere: single-discipline, fine
            global _env_parsed
            _env_parsed = True

        def local_shadow(_intended):
            _intended["x"] = 1          # parameter shadows the global

        def local_rebind():
            _intended = {}              # no global decl: a local
            _intended["x"] = 1
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_module_level_closure_under_lock_still_unguarded():
    # defining a function under a lock does not RUN it under the lock
    found, _ = run("""
        import threading

        _lock = threading.Lock()
        _state = {}

        def guarded(k, v):
            with _lock:
                _state[k] = v

        def maker():
            with _lock:
                def inner(k):
                    _state[k] = 0       # runs later, lock-free
                return inner
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 1 and "_state" in found[0].message


def test_concurrency_thread_alias_and_annotated_assign():
    # `from threading import Thread as T` must still be detected, and an
    # ANNOTATED lock-free assignment of a guarded attr must still flag
    found, _ = run("""
        from threading import Thread as T

        class C:
            def start(self, f):
                T(target=f).start()          # aliased, no daemon=

            def bump(self):
                with self._lock:
                    self.gen = 1

            def reset(self):
                self.gen: int = 0            # annotated, lock-free
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert len(found) == 2
    # a local class merely named Thread is NOT threading's
    found, _ = run("""
        from mypool import Thread

        def go(f):
            Thread(target=f).start()
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_closure_inside_init_keeps_exemption():
    # an init-time helper closure runs during construction, before the
    # object is published — its assignments are pre-publication too
    found, _ = run("""
        class C:
            def __init__(self):
                def setup():
                    self.x = 1
                setup()

            def bump(self):
                with self._lock:
                    self.x = 2
        """, "tpu_mx/foo.py", rules={"concurrency"})
    assert found == []


def test_concurrency_closure_under_lock_is_not_guarded():
    # defining a function under a lock does not make its body run under
    # the lock — assignments inside it must count as UNguarded
    found, _ = run("""
        class C:
            def a(self):
                with self._lock:
                    def cb():
                        self.x = 1          # runs later, lock-free
                    self.x = 2              # guarded
                    return cb

            def b(self):
                self.x = 3                  # unguarded -> finding
        """, "tpu_mx/foo.py", rules={"concurrency"})
    # both cb's assignment and b's assignment conflict with the guard
    assert len(found) == 2


# ---------------------------------------------------------------------------
# telemetry-catalog
# ---------------------------------------------------------------------------
def test_telemetry_catalog_fires_on_unknown_and_dynamic_names():
    found, _ = run("""
        from tpu_mx import telemetry

        def instrument(name):
            telemetry.counter("fusion.flushez").inc()    # typo
            telemetry.gauge(name).set(1)                 # unverifiable
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert len(found) == 2
    assert "fusion.flushez" in found[0].message


def test_telemetry_catalog_silent_on_known_names_and_other_objects():
    found, _ = run("""
        from tpu_mx import telemetry as _telemetry

        def instrument(db):
            _telemetry.counter("fusion.flushes").inc()
            with _telemetry.span("train_step.steps"):
                pass
            db.counter("not.a.metric")     # unrelated object's .counter
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert found == []
    # the telemetry module itself manipulates names generically: exempt
    found, _ = run("""
        from tpu_mx import telemetry
        telemetry.counter("internal.name")
        """, "tpu_mx/telemetry.py", rules={"telemetry-catalog"})
    assert found == []


def test_catalog_extraction_matches_the_live_module():
    known = tpumx_lint.load_known_metrics()
    assert known is not None
    # spot-check names every PR so far instrumented
    for name in ("fusion.flushes", "checkpoint.atomic_writes",
                 "supervisor.restarts", "resume.capsules_written"):
        assert name in known


def test_tracing_catalog_fires_on_unknown_and_dynamic_event_names():
    found, _ = run("""
        from tpu_mx import tracing as _tracing

        def instrument(name):
            _tracing.emit("supervisor.restartz", n=1)   # typo
            _tracing.emit(name, kind="hang")            # unverifiable
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert len(found) == 2
    assert "supervisor.restartz" in found[0].message
    assert "KNOWN_EVENTS" in found[0].message


def test_tracing_catalog_silent_on_known_names_and_lookalikes():
    found, _ = run("""
        from tpu_mx import tracing
        from tpu_mx.tracing import emit

        def instrument(logger):
            tracing.emit("chaos.inject", kind="hang")
            emit("supervisor.restart", n=2)     # from-imported emitter
            logger.emit("not.an.event")         # unrelated object's .emit
        """, "tpu_mx/foo.py", rules={"telemetry-catalog"})
    assert found == []
    # the tracing module itself manipulates names generically: exempt
    found, _ = run("""
        from tpu_mx import tracing
        tracing.emit("internal.name")
        """, "tpu_mx/tracing.py", rules={"telemetry-catalog"})
    assert found == []


def test_event_catalog_extraction_matches_the_live_module():
    known = tpumx_lint.load_known_events()
    assert known is not None
    import tpu_mx.tracing as live
    assert known == frozenset(live.KNOWN_EVENTS)
    for name in ("chaos.inject", "supervisor.watchdog_fire",
                 "train_step.phase", "resume.capsule_restore"):
        assert name in known


# ---------------------------------------------------------------------------
# suppression mechanism
# ---------------------------------------------------------------------------
def test_suppression_inline_and_comment_block():
    src = """
        def f(path, b):
            g = open(path, "wb")  # tpumx-lint: disable=durability -- why
            # tpumx-lint: disable=durability -- long justification that
            # wraps over several comment lines before the statement
            h = open(path, "wb")
            return g, h
        """
    found, suppressed = run(src, "tpu_mx/foo.py", rules={"durability"})
    assert found == []
    assert len(suppressed) == 2


def test_suppression_is_rule_specific():
    src = """
        import numpy as np
        def f(path):
            # tpumx-lint: disable=determinism -- wrong rule on purpose
            g = open(path, "wb")
            return g
        """
    found, suppressed = run(src, "tpu_mx/foo.py", rules={"durability"})
    assert len(found) == 1 and suppressed == []
    # disable=all suppresses any rule
    src2 = src.replace("disable=determinism", "disable=all")
    found, suppressed = run(src2, "tpu_mx/foo.py", rules={"durability"})
    assert found == [] and len(suppressed) == 1


# ---------------------------------------------------------------------------
# baseline mechanism
# ---------------------------------------------------------------------------
def test_baseline_round_trip_and_line_drift(tmp_path):
    src = 'def f(p):\n    return open(p, "wb")\n'
    found, _ = tpumx_lint.lint_source(src, "tpu_mx/foo.py",
                                      rules={"durability"})
    assert len(found) == 1
    bl = tmp_path / "baseline.json"
    tpumx_lint.write_baseline(str(bl), found)
    fps = tpumx_lint.read_baseline(str(bl))
    assert found[0].fingerprint() in fps
    # unrelated lines added ABOVE must not resurrect the finding: the
    # fingerprint hashes scope + line text, not the line number
    drifted = "import os\n\n\n" + src
    found2, _ = tpumx_lint.lint_source(drifted, "tpu_mx/foo.py",
                                       rules={"durability"})
    assert len(found2) == 1
    assert found2[0].fingerprint() in fps
    assert found2[0].line != found[0].line


def test_baseline_unknown_format_rejected(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"format": "something-else", "findings": []}))
    with pytest.raises(SystemExit):
        tpumx_lint.read_baseline(str(bl))


# ---------------------------------------------------------------------------
# CLI + repo-wide gate
# ---------------------------------------------------------------------------
def test_cli_json_format_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text('import pickle\n'
                   'def f(o, p):\n'
                   '    pickle.dump(o, open(p, "wb"))\n')
    # path under tmp is not library/tools scope for open(); force it via
    # a state-shaped literal to prove scoping, then check the JSON shape
    bad2 = tmp_path / "bad2.py"
    bad2.write_text('def f(b):\n'
                    '    with open("x-0001.params", "wb") as f:\n'
                    '        f.write(b)\n')
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
         str(bad2), "--format", "json", "--baseline",
         str(tmp_path / "none.json")],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] and \
        payload["findings"][0]["rule"] == "durability"
    assert {"rule", "path", "line", "col", "message", "context",
            "fingerprint"} <= set(payload["findings"][0])


def test_cli_fails_closed_on_missing_target_and_lost_catalog(
        tmp_path, monkeypatch, capsys):
    # a typo'd path must not read as a clean lint
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpumx_lint.py"),
         "no_such_file.py", "--baseline", str(tmp_path / "none.json")],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "not found" in out.stdout + out.stderr
    # and a catalog the extractor cannot parse must not silently disable
    # the telemetry-catalog pass: main() fails closed with a pointed
    # message (e.g. after KNOWN_METRICS becomes a computed expression)
    assert tpumx_lint.load_known_metrics(repo=str(tmp_path)) is None
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    monkeypatch.setattr(tpumx_lint, "load_known_metrics", lambda: None)
    rc = tpumx_lint.main([str(ok), "--baseline",
                          str(tmp_path / "none.json")])
    assert rc == 2
    assert "KNOWN_METRICS" in capsys.readouterr().err
    # the event catalog fails closed the same way (ISSUE 7: the
    # telemetry-catalog pass covers tracing.KNOWN_EVENTS too)
    monkeypatch.undo()
    assert tpumx_lint.load_known_events(repo=str(tmp_path)) is None
    monkeypatch.setattr(tpumx_lint, "load_known_events", lambda: None)
    rc = tpumx_lint.main([str(ok), "--baseline",
                          str(tmp_path / "none.json")])
    assert rc == 2
    assert "KNOWN_EVENTS" in capsys.readouterr().err
    # but a rules subset that excludes the catalog pass still runs
    rc = tpumx_lint.main([str(ok), "--rules", "durability",
                          "--baseline", str(tmp_path / "none.json")])
    assert rc == 0


def test_repo_lints_clean():
    """The shipped tree must have zero unsuppressed findings — this is
    the same gate tools/ci.py's lint tier enforces."""
    known = tpumx_lint.load_known_metrics()
    known_events = tpumx_lint.load_known_events()
    findings, suppressed, errors = tpumx_lint.lint_paths(
        tpumx_lint.DEFAULT_TARGETS, known_metrics=known,
        known_events=known_events)
    assert errors == []
    baseline = tpumx_lint.read_baseline(
        os.path.join(TOOLS, "tpumx_lint_baseline.json"))
    fresh = [f for f in findings if f.fingerprint() not in baseline]
    assert fresh == [], "\n".join(f.render() for f in fresh)
    # every suppression in the tree must carry a justification ("--"):
    # a bare disable hides a contract violation with no explanation
    assert len(suppressed) >= 1
    repo = os.path.dirname(TOOLS)
    for f in suppressed:
        with open(os.path.join(repo, f.path), encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        block = [lines[f.line - 1]]
        ln = f.line - 2
        while ln >= 0 and lines[ln].lstrip().startswith("#"):
            block.append(lines[ln])
            ln -= 1
        directives = [t for t in block if "tpumx-lint: disable" in t]
        assert directives, f.render()
        assert any("--" in t for t in directives), (
            f"unjustified suppression at {f.path}:{f.line} — append "
            f"'-- <why the contract does not apply>'")
