"""Raw optimizer update ops (REF:src/operator/optimizer_op.cc surface):
formula checks against independent NumPy oracles + the reference's
in-place mutation contract (states rebound, out=weight idiom)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd

RS = np.random.RandomState(3)


def arrs(*shapes):
    return [RS.randn(*s).astype(np.float32) for s in shapes]


def as_nd(*xs):
    return [nd.array(x) for x in xs]


def test_sgd_mom_update_matches_numpy_and_mutates_mom():
    w0, g, m0 = arrs((4, 3), (4, 3), (4, 3))
    w, gg, m = as_nd(w0, g, m0)
    out = nd.sgd_mom_update(w, gg, m, lr=0.1, momentum=0.9, wd=0.01,
                            out=w)
    m_ref = 0.9 * m0 - 0.1 * (g + 0.01 * w0)
    w_ref = w0 + m_ref
    np.testing.assert_allclose(out.asnumpy(), w_ref, rtol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)
    assert out is w  # in-place idiom returns the out handle


def test_sgd_mom_matches_optimizer_class_trajectory():
    w0, g1, g2 = arrs((6,), (6,), (6,))
    # raw-op trajectory
    w, m = as_nd(w0, np.zeros(6, np.float32))
    for g in (g1, g2):
        nd.sgd_mom_update(w, nd.array(g), m, lr=0.05, momentum=0.9,
                          wd=0.001, out=w)
    # optimizer-class trajectory
    opt = mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9,
                              wd=0.001)
    w2 = nd.array(w0)
    state = opt.create_state(0, w2)
    for g in (g1, g2):
        state = opt.update(0, w2, nd.array(g), state)
    np.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_adam_update_no_bias_correction():
    w0, g, m0 = arrs((5,), (5,), (5,))
    v0 = np.abs(arrs((5,))[0])
    w, gg, m, v = as_nd(w0, g, m0, v0)
    nd.adam_update(w, gg, m, v, lr=0.01, beta1=0.9, beta2=0.99,
                   epsilon=1e-8, wd=0.1, out=w)
    gp = g + 0.1 * w0
    m_ref = 0.9 * m0 + 0.1 * gp
    v_ref = 0.99 * v0 + 0.01 * gp ** 2
    w_ref = w0 - 0.01 * m_ref / (np.sqrt(v_ref) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-6)


def test_nag_mom_update():
    w0, g, m0 = arrs((4,), (4,), (4,))
    w, gg, m = as_nd(w0, g, m0)
    nd.nag_mom_update(w, gg, m, lr=0.1, momentum=0.8, wd=0.01, out=w)
    gp = g + 0.01 * w0
    m_ref = 0.8 * m0 + gp
    w_ref = w0 - 0.1 * (gp + 0.8 * m_ref)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)


def test_rmsprop_update():
    w0, g = arrs((4,), (4,))
    n0 = np.abs(arrs((4,))[0])
    w, gg, n = as_nd(w0, g, n0)
    nd.rmsprop_update(w, gg, n, lr=0.01, gamma1=0.9, epsilon=1e-8,
                      wd=0.0, out=w)
    n_ref = 0.9 * n0 + 0.1 * g ** 2
    w_ref = w0 - 0.01 * g / (np.sqrt(n_ref) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5)
    np.testing.assert_allclose(n.asnumpy(), n_ref, rtol=1e-6)


def test_rmspropalex_update_centered():
    w0, g, gm0, d0 = arrs((4,), (4,), (4,), (4,))
    n0 = np.abs(arrs((4,))[0]) + 1.0
    w, gg, n, gm, d = as_nd(w0, g, n0, gm0, d0)
    nd.rmspropalex_update(w, gg, n, gm, d, lr=0.01, gamma1=0.95,
                          gamma2=0.9, epsilon=1e-4, out=w)
    n_ref = 0.95 * n0 + 0.05 * g ** 2
    g_ref = 0.95 * gm0 + 0.05 * g
    d_ref = 0.9 * d0 - 0.01 * g / np.sqrt(n_ref - g_ref ** 2 + 1e-4)
    np.testing.assert_allclose(w.asnumpy(), w0 + d_ref, rtol=1e-5)
    np.testing.assert_allclose(n.asnumpy(), n_ref, rtol=1e-6)
    np.testing.assert_allclose(gm.asnumpy(), g_ref, rtol=1e-6)
    np.testing.assert_allclose(d.asnumpy(), d_ref, rtol=1e-5)


def test_ftrl_update_sparsifies():
    w0, g = arrs((6,), (6,))
    z0 = np.zeros(6, np.float32)
    n0 = np.zeros(6, np.float32)
    w, gg, z, n = as_nd(w0, g, z0, n0)
    nd.ftrl_update(w, gg, z, n, lr=0.1, lamda1=1e4, beta=1.0, out=w)
    # with huge l1 strength the first step zeroes every weight
    assert np.all(w.asnumpy() == 0.0)
    np.testing.assert_allclose(n.asnumpy(), g ** 2, rtol=1e-6)


def test_ftml_update():
    w0, g = arrs((4,), (4,))
    d0 = np.zeros(4, np.float32)
    v0 = np.zeros(4, np.float32)
    z0 = np.zeros(4, np.float32)
    w, gg, d, v, z = as_nd(w0, g, d0, v0, z0)
    nd.ftml_update(w, gg, d, v, z, lr=0.1, t=1, beta1=0.6, beta2=0.999,
                   epsilon=1e-8, out=w)
    v_ref = 0.001 * g ** 2
    d_t = (1 - 0.6) / 0.1 * (np.sqrt(v_ref / 0.001) + 1e-8)
    sigma = d_t - 0.6 * d0
    z_ref = 0.4 * g - sigma * w0
    np.testing.assert_allclose(w.asnumpy(), -z_ref / d_t, rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-5)


def test_sign_ops():
    w0, g, m0 = arrs((5,), (5,), (5,))
    w, gg = as_nd(w0, g)
    nd.signsgd_update(w, gg, lr=0.1, wd=0.01, out=w)
    np.testing.assert_allclose(
        w.asnumpy(), (1 - 0.1 * 0.01) * w0 - 0.1 * np.sign(g), rtol=1e-6)

    w, gg, m = as_nd(w0, g, m0)
    nd.signum_update(w, gg, m, lr=0.1, momentum=0.9, wd=0.05, wd_lh=0.02,
                     out=w)
    m_ref = 0.9 * m0 - 0.1 * (g + 0.05 * w0)
    np.testing.assert_allclose(
        w.asnumpy(), (1 - 0.1 * 0.02) * w0 + 0.1 * np.sign(m_ref),
        rtol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)


def test_lamb_two_phase():
    w0, g, m0 = arrs((8,), (8,), (8,))
    v0 = np.abs(arrs((8,))[0])
    w, gg, m, v = as_nd(w0, g, m0, v0)
    gdir = nd.lamb_update_phase1(w, gg, m, v, beta1=0.9, beta2=0.99,
                                 epsilon=1e-6, t=2, wd=0.01)
    m_ref = 0.9 * m0 + 0.1 * g
    v_ref = 0.99 * v0 + 0.01 * g ** 2
    mhat = m_ref / (1 - 0.9 ** 2)
    vhat = v_ref / (1 - 0.99 ** 2)
    gdir_ref = mhat / (np.sqrt(vhat) + 1e-6) + 0.01 * w0
    np.testing.assert_allclose(gdir.asnumpy(), gdir_ref, rtol=1e-5)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)

    r1 = nd.array(np.array(np.linalg.norm(w0), np.float32))
    r2 = nd.array(np.array(np.linalg.norm(gdir_ref), np.float32))
    nd.lamb_update_phase2(w, gdir, r1, r2, lr=0.01, out=w)
    ratio = np.linalg.norm(w0) / np.linalg.norm(gdir_ref)
    np.testing.assert_allclose(w.asnumpy(), w0 - 0.01 * ratio * gdir_ref,
                               rtol=1e-5)


def test_mp_sgd_update_master_weights():
    w32_0, g = arrs((6,), (6,))
    w16 = nd.cast(nd.array(w32_0), "bfloat16")
    w32 = nd.array(w32_0)
    g16 = nd.cast(nd.array(g), "bfloat16")
    out = nd.mp_sgd_update(w16, g16, w32, lr=0.1, wd=0.01, out=w16)
    w32_ref = w32_0 - 0.1 * (np.asarray(g16.asnumpy(), np.float32)
                             + 0.01 * w32_0)
    np.testing.assert_allclose(w32.asnumpy(), w32_ref, rtol=1e-6)
    # low-precision weight is the cast of the f32 master
    np.testing.assert_allclose(out.asnumpy(),
                               w32_ref.astype(np.float32), rtol=1e-2)
    assert out.dtype == np.dtype("bfloat16") or str(out.dtype) == "bfloat16"


def test_adamw_update_decoupled_wd():
    """Upstream adamw.cc kernel contract: decoupled wd, NO in-kernel bias
    correction (the Python driver pre-scales lr, as with adam_update)."""
    w0, g, m0 = arrs((5,), (5,), (5,))
    v0 = np.abs(arrs((5,))[0])
    w, gg, m, v = as_nd(w0, g, m0, v0)
    nd.adamw_update(w, gg, m, v, rescale_grad=1.0, lr=0.01, beta1=0.9,
                    beta2=0.99, epsilon=1e-8, wd=0.1, eta=1.0, out=w)
    m_ref = 0.9 * m0 + 0.1 * g
    v_ref = 0.99 * v0 + 0.01 * g ** 2
    w_ref = w0 - (0.01 * m_ref / (np.sqrt(v_ref) + 1e-8) + 0.1 * w0)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5)
