"""Raw optimizer update ops (REF:src/operator/optimizer_op.cc surface):
formula checks against independent NumPy oracles + the reference's
in-place mutation contract (states rebound, out=weight idiom)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd

RS = np.random.RandomState(3)


def arrs(*shapes):
    return [RS.randn(*s).astype(np.float32) for s in shapes]


def as_nd(*xs):
    return [nd.array(x) for x in xs]


def test_sgd_mom_update_matches_numpy_and_mutates_mom():
    w0, g, m0 = arrs((4, 3), (4, 3), (4, 3))
    w, gg, m = as_nd(w0, g, m0)
    out = nd.sgd_mom_update(w, gg, m, lr=0.1, momentum=0.9, wd=0.01,
                            out=w)
    m_ref = 0.9 * m0 - 0.1 * (g + 0.01 * w0)
    w_ref = w0 + m_ref
    np.testing.assert_allclose(out.asnumpy(), w_ref, rtol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)
    assert out is w  # in-place idiom returns the out handle


def test_sgd_mom_matches_optimizer_class_trajectory():
    w0, g1, g2 = arrs((6,), (6,), (6,))
    # raw-op trajectory
    w, m = as_nd(w0, np.zeros(6, np.float32))
    for g in (g1, g2):
        nd.sgd_mom_update(w, nd.array(g), m, lr=0.05, momentum=0.9,
                          wd=0.001, out=w)
    # optimizer-class trajectory
    opt = mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9,
                              wd=0.001)
    w2 = nd.array(w0)
    state = opt.create_state(0, w2)
    for g in (g1, g2):
        state = opt.update(0, w2, nd.array(g), state)
    np.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_adam_update_no_bias_correction():
    w0, g, m0 = arrs((5,), (5,), (5,))
    v0 = np.abs(arrs((5,))[0])
    w, gg, m, v = as_nd(w0, g, m0, v0)
    nd.adam_update(w, gg, m, v, lr=0.01, beta1=0.9, beta2=0.99,
                   epsilon=1e-8, wd=0.1, out=w)
    gp = g + 0.1 * w0
    m_ref = 0.9 * m0 + 0.1 * gp
    v_ref = 0.99 * v0 + 0.01 * gp ** 2
    w_ref = w0 - 0.01 * m_ref / (np.sqrt(v_ref) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-6)


def test_nag_mom_update():
    w0, g, m0 = arrs((4,), (4,), (4,))
    w, gg, m = as_nd(w0, g, m0)
    nd.nag_mom_update(w, gg, m, lr=0.1, momentum=0.8, wd=0.01, out=w)
    gp = g + 0.01 * w0
    m_ref = 0.8 * m0 + gp
    w_ref = w0 - 0.1 * (gp + 0.8 * m_ref)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)


def test_rmsprop_update():
    w0, g = arrs((4,), (4,))
    n0 = np.abs(arrs((4,))[0])
    w, gg, n = as_nd(w0, g, n0)
    nd.rmsprop_update(w, gg, n, lr=0.01, gamma1=0.9, epsilon=1e-8,
                      wd=0.0, out=w)
    n_ref = 0.9 * n0 + 0.1 * g ** 2
    w_ref = w0 - 0.01 * g / (np.sqrt(n_ref) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5)
    np.testing.assert_allclose(n.asnumpy(), n_ref, rtol=1e-6)


def test_rmspropalex_update_centered():
    w0, g, gm0, d0 = arrs((4,), (4,), (4,), (4,))
    n0 = np.abs(arrs((4,))[0]) + 1.0
    w, gg, n, gm, d = as_nd(w0, g, n0, gm0, d0)
    nd.rmspropalex_update(w, gg, n, gm, d, lr=0.01, gamma1=0.95,
                          gamma2=0.9, epsilon=1e-4, out=w)
    n_ref = 0.95 * n0 + 0.05 * g ** 2
    g_ref = 0.95 * gm0 + 0.05 * g
    d_ref = 0.9 * d0 - 0.01 * g / np.sqrt(n_ref - g_ref ** 2 + 1e-4)
    np.testing.assert_allclose(w.asnumpy(), w0 + d_ref, rtol=1e-5)
    np.testing.assert_allclose(n.asnumpy(), n_ref, rtol=1e-6)
    np.testing.assert_allclose(gm.asnumpy(), g_ref, rtol=1e-6)
    np.testing.assert_allclose(d.asnumpy(), d_ref, rtol=1e-5)


def test_ftrl_update_sparsifies():
    w0, g = arrs((6,), (6,))
    z0 = np.zeros(6, np.float32)
    n0 = np.zeros(6, np.float32)
    w, gg, z, n = as_nd(w0, g, z0, n0)
    nd.ftrl_update(w, gg, z, n, lr=0.1, lamda1=1e4, beta=1.0, out=w)
    # with huge l1 strength the first step zeroes every weight
    assert np.all(w.asnumpy() == 0.0)
    np.testing.assert_allclose(n.asnumpy(), g ** 2, rtol=1e-6)


def test_ftml_update():
    w0, g = arrs((4,), (4,))
    d0 = np.zeros(4, np.float32)
    v0 = np.zeros(4, np.float32)
    z0 = np.zeros(4, np.float32)
    w, gg, d, v, z = as_nd(w0, g, d0, v0, z0)
    nd.ftml_update(w, gg, d, v, z, lr=0.1, t=1, beta1=0.6, beta2=0.999,
                   epsilon=1e-8, out=w)
    v_ref = 0.001 * g ** 2
    d_t = (1 - 0.6) / 0.1 * (np.sqrt(v_ref / 0.001) + 1e-8)
    sigma = d_t - 0.6 * d0
    z_ref = 0.4 * g - sigma * w0
    np.testing.assert_allclose(w.asnumpy(), -z_ref / d_t, rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-5)


def test_sign_ops():
    w0, g, m0 = arrs((5,), (5,), (5,))
    w, gg = as_nd(w0, g)
    nd.signsgd_update(w, gg, lr=0.1, wd=0.01, out=w)
    np.testing.assert_allclose(
        w.asnumpy(), (1 - 0.1 * 0.01) * w0 - 0.1 * np.sign(g), rtol=1e-6)

    w, gg, m = as_nd(w0, g, m0)
    nd.signum_update(w, gg, m, lr=0.1, momentum=0.9, wd=0.05, wd_lh=0.02,
                     out=w)
    m_ref = 0.9 * m0 - 0.1 * (g + 0.05 * w0)
    np.testing.assert_allclose(
        w.asnumpy(), (1 - 0.1 * 0.02) * w0 + 0.1 * np.sign(m_ref),
        rtol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)


def test_lamb_two_phase():
    w0, g, m0 = arrs((8,), (8,), (8,))
    v0 = np.abs(arrs((8,))[0])
    w, gg, m, v = as_nd(w0, g, m0, v0)
    gdir = nd.lamb_update_phase1(w, gg, m, v, beta1=0.9, beta2=0.99,
                                 epsilon=1e-6, t=2, wd=0.01)
    m_ref = 0.9 * m0 + 0.1 * g
    v_ref = 0.99 * v0 + 0.01 * g ** 2
    mhat = m_ref / (1 - 0.9 ** 2)
    vhat = v_ref / (1 - 0.99 ** 2)
    gdir_ref = mhat / (np.sqrt(vhat) + 1e-6) + 0.01 * w0
    np.testing.assert_allclose(gdir.asnumpy(), gdir_ref, rtol=1e-5)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)

    r1 = nd.array(np.array(np.linalg.norm(w0), np.float32))
    r2 = nd.array(np.array(np.linalg.norm(gdir_ref), np.float32))
    nd.lamb_update_phase2(w, gdir, r1, r2, lr=0.01, out=w)
    ratio = np.linalg.norm(w0) / np.linalg.norm(gdir_ref)
    np.testing.assert_allclose(w.asnumpy(), w0 - 0.01 * ratio * gdir_ref,
                               rtol=1e-5)


def test_mp_sgd_update_master_weights():
    w32_0, g = arrs((6,), (6,))
    w16 = nd.cast(nd.array(w32_0), "bfloat16")
    w32 = nd.array(w32_0)
    g16 = nd.cast(nd.array(g), "bfloat16")
    out = nd.mp_sgd_update(w16, g16, w32, lr=0.1, wd=0.01, out=w16)
    w32_ref = w32_0 - 0.1 * (np.asarray(g16.asnumpy(), np.float32)
                             + 0.01 * w32_0)
    np.testing.assert_allclose(w32.asnumpy(), w32_ref, rtol=1e-6)
    # low-precision weight is the cast of the f32 master
    np.testing.assert_allclose(out.asnumpy(),
                               w32_ref.astype(np.float32), rtol=1e-2)
    assert out.dtype == np.dtype("bfloat16") or str(out.dtype) == "bfloat16"


def test_adamw_update_decoupled_wd():
    """Upstream adamw.cc kernel contract: decoupled wd, NO in-kernel bias
    correction (the Python driver pre-scales lr, as with adam_update)."""
    w0, g, m0 = arrs((5,), (5,), (5,))
    v0 = np.abs(arrs((5,))[0])
    w, gg, m, v = as_nd(w0, g, m0, v0)
    nd.adamw_update(w, gg, m, v, rescale_grad=1.0, lr=0.01, beta1=0.9,
                    beta2=0.99, epsilon=1e-8, wd=0.1, eta=1.0, out=w)
    m_ref = 0.9 * m0 + 0.1 * g
    v_ref = 0.99 * v0 + 0.01 * g ** 2
    w_ref = w0 - (0.01 * m_ref / (np.sqrt(v_ref) + 1e-8) + 0.1 * w0)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5)


def test_multi_sgd_mom_update_matches_singles():
    """Fused interleaved multi-tensor update == per-weight updates."""
    ws = arrs((4, 3), (6,), (2, 2))
    gs = arrs((4, 3), (6,), (2, 2))
    ms = [np.zeros_like(w) for w in ws]
    lrs, wds = (0.1, 0.05, 0.2), (0.0, 0.01, 0.1)

    # singles
    singles = []
    for w0, g0, m0, lr, wd in zip(ws, gs, ms, lrs, wds):
        w, g, m = as_nd(w0, g0, m0)
        nd.sgd_mom_update(w, g, m, lr=lr, momentum=0.9, wd=wd, out=w)
        singles.append((w.asnumpy(), m.asnumpy()))

    # fused
    flat = []
    handles = []
    for w0, g0, m0 in zip(ws, gs, ms):
        w, g, m = as_nd(w0, g0, m0)
        flat += [w, g, m]
        handles.append((w, m))
    outs = [h[0] for h in handles]
    res = nd.multi_sgd_mom_update(*flat, num_weights=3, momentum=0.9,
                                  lrs=lrs, wds=wds, out=outs)
    assert res == outs
    for (w, m), (w_ref, m_ref) in zip(handles, singles):
        np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-6)
        np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)


def test_multi_mp_and_preloaded_variants():
    ws = arrs((5,), (3,))
    gs = arrs((5,), (3,))
    lrs, wds = (0.1, 0.2), (0.01, 0.0)

    # multi_mp_sgd_update: [w, g, w32] triples, f32 masters rebound
    flat, masters = [], []
    for w0, g0 in zip(ws, gs):
        w = nd.cast(nd.array(w0), "bfloat16")
        w32 = nd.array(w0)
        flat += [w, nd.cast(nd.array(g0), "bfloat16"), w32]
        masters.append((w, w32, w0, g0))
    nd.multi_mp_sgd_update(*flat, num_weights=2, lrs=lrs, wds=wds,
                           out=[m[0] for m in masters])
    for (w, w32, w0, g0), lr, wd in zip(masters, lrs, wds):
        g16 = np.asarray(nd.cast(nd.array(g0), "bfloat16").asnumpy(),
                         np.float32)
        ref = w0 - lr * (g16 + wd * w0)
        np.testing.assert_allclose(w32.asnumpy(), ref, rtol=1e-6)

    # preloaded: lrs/wds are device tensors trailing the interleaved data
    flat = []
    handles = []
    for w0, g0 in zip(ws, gs):
        w, g = as_nd(w0, g0)
        flat += [w, g]
        handles.append(w)
    lr_t = nd.array(np.asarray(lrs, np.float32))
    wd_t = nd.array(np.asarray(wds, np.float32))
    nd.preloaded_multi_sgd_update(*flat, lr_t, wd_t, num_weights=2,
                                  out=handles)
    for w, w0, g0, lr, wd in zip(handles, ws, gs, lrs, wds):
        np.testing.assert_allclose(w.asnumpy(), w0 - lr * (g0 + wd * w0),
                                   rtol=1e-6)


def test_multi_update_arity_errors():
    w, g = as_nd(*arrs((3,), (3,)))
    with pytest.raises(ValueError, match="expected"):
        nd.multi_sgd_update(w, g, w, num_weights=2, lrs=(0.1, 0.1))
    with pytest.raises(ValueError, match="lrs"):
        nd.multi_sgd_update(w, g, num_weights=1)


def test_multi_update_out_validation():
    """out validated BEFORE any state mutation: a bad out can never leave
    optimizer state half-rebound."""
    w0, g0, m0 = arrs((3,), (3,), (3,))
    w1, g1, m1 = arrs((4,), (4,), (4,))
    flat = as_nd(w0, g0, m0, w1, g1, m1)
    one_out = nd.array(w0)
    with pytest.raises(ValueError, match="out"):
        nd.multi_sgd_mom_update(*flat, num_weights=2, lrs=(0.1, 0.1),
                                out=one_out)
    with pytest.raises(ValueError, match="out"):
        nd.multi_sgd_mom_update(*flat, num_weights=2, lrs=(0.1, 0.1),
                                out=[one_out])
    # states untouched by the rejected calls
    np.testing.assert_array_equal(flat[2].asnumpy(), m0)
    np.testing.assert_array_equal(flat[5].asnumpy(), m1)
    with pytest.raises(ValueError, match="lrs/wds"):
        nd.multi_sgd_update(*as_nd(w0, g0, w1, g1), num_weights=2,
                            lrs=(0.1,))
