"""Op-parity audit enforcement (VERDICT r3 ask#6).

Three contracts against tools/ops_parity.py's curated upstream registry:
1. OPS_PARITY.md is the rendered registry (no silent drift);
2. every `yes` row with a concrete `nd.*` impl resolves to a callable;
3. every such op EXECUTES on tiny inputs — by-name template, else the
   generic unary/binary cascade.  An op nobody can invoke is not
   "implemented".
"""
import os
import sys

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import ops_parity  # noqa: E402


def _resolvable(impl):
    import re
    return bool(re.fullmatch(r"nd\.[A-Za-z_][\w.]*", impl))


def _resolve(impl):
    obj = mx
    for part in impl.split("."):
        obj = getattr(obj, part)
    return obj


def yes_rows():
    for fam, rows in ops_parity.ROWS.items():
        for name, status, impl, note in rows:
            if status == "yes" and _resolvable(impl):
                yield name, impl


def test_markdown_in_sync():
    with open(os.path.join(REPO, "OPS_PARITY.md")) as f:
        on_disk = f.read()
    assert on_disk.strip() == ops_parity.render().strip(), \
        "OPS_PARITY.md is stale — regenerate: python tools/ops_parity.py > OPS_PARITY.md"


def test_every_implemented_row_resolves():
    missing = []
    for name, impl in yes_rows():
        try:
            obj = _resolve(impl)
            if not callable(obj):
                missing.append(f"{name} -> {impl} (not callable)")
        except AttributeError:
            missing.append(f"{name} -> {impl} (missing)")
    assert not missing, missing


# ---------------------------------------------------------------------------
# smoke invocation
# ---------------------------------------------------------------------------
RS = np.random.RandomState(0)


def X(*s):
    return nd.array((RS.rand(*s) * 0.8 + 0.1).astype(np.float32))


def XI(*s, n=8):
    return nd.array(RS.randint(0, n, s).astype(np.int32))


def NCHW():
    return X(1, 3, 8, 8)


# by-op invocation templates; everything else goes through the generic
# unary→binary cascade
TEMPLATES = {
    "Activation": lambda f: f(X(2, 3), act_type="relu"),
    "BatchNorm": lambda f: f(NCHW(), X(3), X(3), X(3), X(3)),
    "BatchNorm_v1": lambda f: f(NCHW(), X(3), X(3), X(3), X(3)),
    "Convolution": lambda f: f(NCHW(), X(4, 3, 3, 3), X(4),
                               kernel=(3, 3), num_filter=4),
    "Convolution_v1": lambda f: f(NCHW(), X(4, 3, 3, 3), X(4),
                                  kernel=(3, 3), num_filter=4),
    "Deconvolution": lambda f: f(NCHW(), X(3, 4, 3, 3), X(4),
                                 kernel=(3, 3), num_filter=4),
    "Dropout": lambda f: f(X(2, 3), p=0.5),
    "Dropout (axes=)": lambda f: f(X(2, 3, 4), p=0.5, axes=(1,)),
    "Embedding": lambda f: f(XI(2, 3), X(8, 4), input_dim=8,
                             output_dim=4),
    "FullyConnected": lambda f: f(X(2, 6), X(4, 6), X(4), num_hidden=4),
    "GridGenerator": lambda f: f(X(1, 6), transform_type="affine",
                                 target_shape=(4, 4)),
    "GroupNorm": lambda f: f(X(1, 4, 8, 8), X(2), X(2), num_groups=2),
    "InstanceNorm": lambda f: f(NCHW(), X(3), X(3)),
    "L2Normalization": lambda f: f(X(2, 3)),
    "LRN": lambda f: f(NCHW(), nsize=3),
    "LayerNorm": lambda f: f(X(2, 6), X(6), X(6)),
    "LeakyReLU": lambda f: f(X(2, 3)),
    "MakeLoss": lambda f: f(X(2, 3)),
    "IdentityAttachKLSparseReg": lambda f: f(X(4, 3)),
    "Pad": lambda f: f(NCHW(), mode="constant",
                       pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
    "pad": lambda f: f(NCHW(), mode="constant",
                       pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
    "Pooling": lambda f: f(NCHW(), kernel=(2, 2), pool_type="max"),
    "Pooling_v1": lambda f: f(NCHW(), kernel=(2, 2), pool_type="max"),
    "RNN": lambda f: f(X(4, 2, 3),
                       X(int(nd.rnn_param_size("rnn_tanh", 3, 5, 1))),
                       X(1, 2, 5), state_size=5, num_layers=1,
                       mode="rnn_tanh"),
    "ROIPooling": lambda f: f(NCHW(), X(1, 5), pooled_size=(2, 2),
                              spatial_scale=1.0),
    "SVMOutput": lambda f: f(X(2, 5), nd.array(np.array([0., 1.],
                                                        np.float32))),
    "SequenceLast": lambda f: f(X(4, 2, 3)),
    "SequenceMask": lambda f: f(X(4, 2, 3)),
    "SequenceReverse": lambda f: f(X(4, 2, 3)),
    "SliceChannel": lambda f: f(X(2, 6), num_outputs=2),
    "SoftmaxActivation": lambda f: f(X(2, 3)),
    "SoftmaxOutput": lambda f: f(X(2, 5),
                                 nd.array(np.array([0., 1.], np.float32))),
    "Softmax": lambda f: f(X(2, 5),
                           nd.array(np.array([0., 1.], np.float32))),
    "SpatialTransformer": lambda f: f(
        NCHW(), X(1, 6), transform_type="affine", sampler_type="bilinear",
        target_shape=(4, 4)),
    "SwapAxis": lambda f: f(X(2, 3, 4), dim1=0, dim2=1),
    "UpSampling": lambda f: f(NCHW(), scale=2, sample_type="nearest"),
    "BilinearSampler": lambda f: f(NCHW(), X(1, 2, 4, 4)),
    "CTCLoss": lambda f: f(X(6, 2, 5), nd.array(
        np.array([[1, 2], [3, 4]], np.float32))),
    "BlockGrad": lambda f: f(X(2, 3)),
    "Custom": lambda f: True,  # needs a registered op; test_custom_op.py owns it
    "Correlation": lambda f: f(NCHW(), NCHW(), max_displacement=1,
                               pad_size=1),
    "Crop": lambda f: f(NCHW(), h_w=(4, 4)),
    "LinearRegressionOutput": lambda f: f(X(2, 3), X(2, 3)),
    "LogisticRegressionOutput": lambda f: f(X(2, 3), X(2, 3)),
    "MAERegressionOutput": lambda f: f(X(2, 3), X(2, 3)),
    # unary domain specials
    "arccosh": lambda f: f(nd.array(1.0 + RS.rand(2, 3).astype(
        np.float32))),
    "logical_not": lambda f: f(X(2, 3)),
    # shape/layout specials
    "Reshape": lambda f: f(X(2, 6), shape=(3, 4)),
    "reshape_like": lambda f: f(X(2, 6), X(3, 4)),
    "expand_dims": lambda f: f(X(2, 3), axis=0),
    "Concat": lambda f: f(X(2, 3), X(2, 3), dim=1),
    "stack": lambda f: f(X(2, 3), X(2, 3)),
    "split": lambda f: f(X(2, 6), num_outputs=2, axis=1),
    "slice": lambda f: f(X(4, 4), begin=(1, 0), end=(3, 2)),
    "slice_axis": lambda f: f(X(4, 4), axis=0, begin=1, end=3),
    "slice_like": lambda f: f(X(4, 4), X(2, 2)),
    "clip": lambda f: f(X(2, 3), a_min=0.2, a_max=0.8),
    "repeat": lambda f: f(X(2, 3), repeats=2),
    "tile": lambda f: f(X(2, 3), reps=(2, 1)),
    "flip": lambda f: f(X(2, 3), axis=0),
    "reverse": lambda f: f(X(2, 3), axis=0),
    "depth_to_space": lambda f: f(X(1, 4, 2, 2), block_size=2),
    "space_to_depth": lambda f: f(X(1, 1, 4, 4), block_size=2),
    "Cast": lambda f: f(X(2, 3), dtype="float32"),
    "amp_cast": lambda f: f(X(2, 3), dtype="float32"),
    "amp_multicast": lambda f: f(X(2, 3), X(2, 3), num_outputs=2),
    "khatri_rao": lambda f: f(X(2, 3), X(4, 3)),
    "im2col": lambda f: f(NCHW(), kernel=(3, 3)),
    "col2im": lambda f: f(nd.im2col(NCHW(), kernel=(3, 3)),
                          output_size=(8, 8), kernel=(3, 3)),
    "one_hot": lambda f: f(XI(4), depth=8),
    "take": lambda f: f(X(5, 3), XI(2, n=5)),
    "batch_take": lambda f: f(X(3, 4), XI(3, n=4)),
    "gather_nd": lambda f: f(X(4, 4), XI(2, 3, n=4)),
    "scatter_nd": lambda f: f(X(3), XI(2, 3, n=4), shape=(4, 4)),
    "ravel_multi_index": lambda f: f(XI(2, 3, n=4), shape=(4, 4)),
    "unravel_index": lambda f: f(XI(3, n=15), shape=(4, 4)),
    "choose_element_0index": lambda f: f(X(3, 4), XI(3, n=4)),
    "fill_element_0index": lambda f: f(X(3, 4), X(3), XI(3, n=4)),
    "where": lambda f: f(nd.greater(X(2, 3), 0.5), X(2, 3), X(2, 3)),
    "pick": lambda f: f(X(3, 4), XI(3, n=4)),
    "topk": lambda f: f(X(3, 6), k=2),
    "diag": lambda f: f(X(4, 4)),
    "shape_array": lambda f: f(X(2, 3)),
    "size_array": lambda f: f(X(2, 3)),
    "norm": lambda f: f(X(2, 3)),
    "moments": lambda f: f(X(2, 3), axes=(0,)),
    "multi_all_finite": lambda f: f(X(2, 3), X(2, 3), num_arrays=2),
    "cumsum": lambda f: f(X(2, 3), axis=1),
    "broadcast_like": lambda f: f(X(1, 3), X(4, 3)),
    "broadcast_to": lambda f: f(X(1, 3), shape=(4, 3)),
    "broadcast_axis": lambda f: f(X(1, 3), axis=0, size=4),
    "broadcast_axes": lambda f: f(X(1, 3), axis=0, size=4),
    "add_n": lambda f: f(X(2, 3), X(2, 3), X(2, 3)),
    # matrix
    "dot": lambda f: f(X(2, 3), X(3, 4)),
    "batch_dot": lambda f: f(X(2, 3, 4), X(2, 4, 5)),
    "linalg_gemm": lambda f: f(X(3, 3), X(3, 3), X(3, 3)),
    "linalg_gemm2": lambda f: f(X(3, 3), X(3, 3)),
    "linalg_potrf": lambda f: f(nd.array(np.eye(3, dtype=np.float32) * 2)),
    "linalg_potri": lambda f: f(nd.array(np.eye(3, dtype=np.float32) * 2)),
    "linalg_trmm": lambda f: f(nd.array(np.tril(np.eye(3) + 0.1).astype(
        np.float32)), X(3, 3)),
    "linalg_trsm": lambda f: f(nd.array(np.tril(np.eye(3) + 0.1).astype(
        np.float32)), X(3, 3)),
    "linalg_sumlogdiag": lambda f: f(nd.array(
        np.eye(3, dtype=np.float32) * 2)),
    "linalg_syrk": lambda f: f(X(3, 4)),
    "linalg_gelqf": lambda f: f(X(3, 4)),
    "linalg_syevd": lambda f: f(nd.array(
        (lambda a: ((a + a.T) / 2).astype(np.float32))(RS.rand(3, 3)))),
    "linalg_inverse": lambda f: f(nd.array(
        np.eye(3, dtype=np.float32) * 2)),
    "linalg_det": lambda f: f(X(3, 3)),
    "linalg_slogdet": lambda f: f(nd.array(
        np.eye(3, dtype=np.float32) * 2)),
    "linalg_extractdiag": lambda f: f(X(3, 3)),
    "linalg_makediag": lambda f: f(X(3)),
    "linalg_extracttrian": lambda f: f(X(3, 3)),
    "linalg_maketrian": lambda f: f(X(6)),
    # random
    "random_uniform": lambda f: f(0.0, 1.0, shape=(2, 3)),
    "random_normal": lambda f: f(0.0, 1.0, shape=(2, 3)),
    "random_gamma": lambda f: f(2.0, 1.0, shape=(2, 3)),
    "random_exponential": lambda f: f(1.0, shape=(2, 3)),
    "random_poisson": lambda f: f(2.0, shape=(2, 3)),
    "random_randint": lambda f: f(0, 5, shape=(2, 3)),
    "sample_uniform": lambda f: f(X(3), X(3) + 1.0),
    "sample_normal": lambda f: f(X(3), X(3)),
    "sample_gamma": lambda f: f(X(3) + 1, X(3) + 1),
    "sample_exponential": lambda f: f(X(3) + 1),
    "sample_poisson": lambda f: f(X(3) + 1),
    "sample_negative_binomial": lambda f: f(XI(3, n=4) + 1, X(3) * 0.5),
    "sample_generalized_negative_binomial": lambda f: f(X(3) + 1,
                                                        X(3) * 0.5),
    "sample_multinomial": lambda f: f(nd.softmax(X(2, 5))),
    "random_negative_binomial": lambda f: f(k=2, p=0.4, shape=(2,)),
    "random_generalized_negative_binomial": lambda f: f(mu=2.0, alpha=0.5,
                                                        shape=(2,)),
    "randn": lambda f: f(2, 3),
    "normal": lambda f: f(0.0, 1.0, shape=(2, 3)),
    "uniform": lambda f: f(0.0, 1.0, shape=(2, 3)),
    "shuffle": lambda f: f(X(4, 3)),
    # optimizer kernels
    "sgd_update": lambda f: f(X(3), X(3), lr=0.1),
    "sgd_mom_update": lambda f: f(X(3), X(3), X(3), lr=0.1, momentum=0.9),
    "mp_sgd_update": lambda f: f(X(3), X(3), X(3), lr=0.1),
    "mp_sgd_mom_update": lambda f: f(X(3), X(3), X(3), X(3), lr=0.1),
    "adam_update": lambda f: f(X(3), X(3), X(3), X(3), lr=0.1),
    "nag_mom_update": lambda f: f(X(3), X(3), X(3), lr=0.1),
    "mp_nag_mom_update": lambda f: f(X(3), X(3), X(3), X(3), lr=0.1),
    "rmsprop_update": lambda f: f(X(3), X(3), X(3), lr=0.1),
    "rmspropalex_update": lambda f: f(X(3), X(3), X(3), X(3), X(3),
                                      lr=0.1),
    "ftrl_update": lambda f: f(X(3), X(3), X(3), X(3), lr=0.1),
    "ftml_update": lambda f: f(X(3), X(3), X(3), X(3), X(3), lr=0.1, t=1),
    "signsgd_update": lambda f: f(X(3), X(3), lr=0.1),
    "signum_update": lambda f: f(X(3), X(3), X(3), lr=0.1),
    "multi_sgd_update": lambda f: f(X(3), X(3), X(4), X(4),
                                    num_weights=2, lrs=(0.1, 0.1)),
    "multi_sgd_mom_update": lambda f: f(X(3), X(3), X(3), X(4), X(4),
                                        X(4), num_weights=2,
                                        lrs=(0.1, 0.1)),
    "multi_mp_sgd_update": lambda f: f(X(3), X(3), X(3), X(4), X(4),
                                       X(4), num_weights=2,
                                       lrs=(0.1, 0.1)),
    "multi_mp_sgd_mom_update": lambda f: f(
        X(3), X(3), X(3), X(3), X(4), X(4), X(4), X(4), num_weights=2,
        lrs=(0.1, 0.1)),
    "preloaded_multi_sgd_update": lambda f: f(
        X(3), X(3), X(4), X(4), X(2), X(2), num_weights=2),
    "preloaded_multi_sgd_mom_update": lambda f: f(
        X(3), X(3), X(3), X(4), X(4), X(4), X(2), X(2), num_weights=2),
    "preloaded_multi_mp_sgd_update": lambda f: f(
        X(3), X(3), X(3), X(4), X(4), X(4), X(2), X(2), num_weights=2),
    "preloaded_multi_mp_sgd_mom_update": lambda f: f(
        X(3), X(3), X(3), X(3), X(4), X(4), X(4), X(4), X(2), X(2),
        num_weights=2),
    "lamb_update_phase1": lambda f: f(X(3), X(3), X(3), X(3)),
    "lamb_update_phase2": lambda f: f(
        X(3), X(3), nd.array(np.float32(1.5)), nd.array(np.float32(2.0)),
        lr=0.1),
    "adamw_update": lambda f: f(X(3), X(3), X(3), X(3), 1.0, lr=0.1),
    "mp_adamw_update": lambda f: f(X(3), X(3), X(3), X(3), X(3), 1.0,
                                   lr=0.1),
    # contrib detection
    "MultiBoxPrior": lambda f: f(NCHW(), sizes=(0.5,), ratios=(1.0,)),
    "MultiBoxTarget": lambda f: f(
        nd.contrib.MultiBoxPrior(NCHW(), sizes=(0.5,), ratios=(1.0,)),
        nd.array(np.array([[[0, 0.1, 0.1, 0.4, 0.4]]], np.float32)),
        nd.softmax(X(1, 2, 64))),
    "MultiBoxDetection": lambda f: f(
        nd.softmax(X(1, 2, 64)), X(1, 256),
        nd.contrib.MultiBoxPrior(NCHW(), sizes=(0.5,), ratios=(1.0,))),
    "box_nms": lambda f: f(X(1, 4, 6)),
    "box_iou": lambda f: f(X(2, 4), X(3, 4)),
    "bipartite_matching": lambda f: f(X(1, 3, 4), threshold=0.1),
    "Proposal": lambda f: f(nd.softmax(X(1, 2, 4, 4), axis=1),
                            X(1, 4, 4, 4), nd.array(
                                np.array([[8, 8, 1.0]], np.float32)),
                            feature_stride=2, scales=(4,), ratios=(1.0,),
                            rpn_pre_nms_top_n=8, rpn_post_nms_top_n=4),
    "MultiProposal": lambda f: f(nd.softmax(X(2, 2, 4, 4), axis=1),
                                 X(2, 4, 4, 4), nd.array(
                                     np.tile([8, 8, 1.0], (2, 1)).astype(
                                         np.float32)),
                                 feature_stride=2, scales=(4,),
                                 ratios=(1.0,), rpn_pre_nms_top_n=8,
                                 rpn_post_nms_top_n=4),
    "ROIAlign": lambda f: f(NCHW(), X(1, 5), pooled_size=(2, 2),
                            spatial_scale=1.0),
    "PSROIPooling": lambda f: f(X(1, 8, 8, 8), X(1, 5), output_dim=2,
                                pooled_size=2, group_size=2),
    "DeformablePSROIPooling": lambda f: f(
        X(1, 8, 8, 8), X(1, 5), X(1, 2, 2, 2), output_dim=2,
        pooled_size=2, group_size=2, part_size=2, trans_std=0.1),
    "DeformableConvolution": lambda f: f(
        NCHW(), X(1, 18, 6, 6), X(4, 3, 3, 3), X(4), kernel=(3, 3),
        num_filter=4),
    "BilinearResize2D": lambda f: f(NCHW(), height=4, width=4),
    "AdaptiveAvgPooling2D": lambda f: f(NCHW(), output_size=2),
    # contrib misc
    "count_sketch": lambda f: f(X(2, 8), XI(8, n=4),
                                nd.sign(X(8) - 0.5), out_dim=4),
    "fft": lambda f: f(X(2, 8)),
    "ifft": lambda f: f(X(2, 16)),
    "quadratic": lambda f: f(X(2, 3), a=1.0, b=1.0, c=1.0),
    "allclose": lambda f: f(X(2, 3), X(2, 3)),
    "arange_like": lambda f: f(X(2, 3)),
    "div_sqrt_dim": lambda f: f(X(2, 3)),
    "index_copy": lambda f: f(X(4, 3), XI(2, n=4), X(2, 3)),
    "index_array": lambda f: f(X(2, 3)),
    "boolean_mask": lambda f: f(X(4, 3), nd.array(
        np.array([1, 0, 1, 1], np.float32))),
    "gradientmultiplier": lambda f: f(X(2, 3), scalar=0.5),
    "hawkesll": lambda f: f(X(1, 2), X(2), X(2), X(1, 2), X(1, 4),
                            nd.array(np.zeros((1, 4), np.float32)),
                            nd.array(np.array([3.0], np.float32)),
                            nd.array(np.array([5.0], np.float32))),
    "cond": lambda f: f(nd.ones((1,)), lambda: nd.ones((2,)),
                        lambda: nd.zeros((2,))),
    "foreach": lambda f: f(lambda x, s: (x + s[0], [x + s[0]]),
                           X(3, 2), [nd.zeros((2,))]),
    "while_loop": lambda f: f(
        lambda i, s: nd.lesser(i, 3), lambda i, s: (i + 1, (i + 1, s)),
        (nd.zeros(()), nd.ones(())), max_iterations=4),
    "quantize": lambda f: f(X(2, 3)),
    "quantize_v2": lambda f: f(X(2, 3)),
    "dequantize": lambda f: True,  # needs a quantized triple; test_rtc_quant owns it
    "requantize": lambda f: True,  # same
    "quantized_conv": lambda f: True,   # test_rtc_quant owns the int8 paths
    "quantized_fully_connected": lambda f: True,
    "quantized_flatten": lambda f: True,
    "quantized_pooling": lambda f: f(
        nd.cast(XI(1, 2, 4, 4, n=100), "int8"),
        nd.array(np.float32(-1.0)), nd.array(np.float32(1.0)),
        kernel=(2, 2), pool_type="max", stride=(2, 2)),
    # sparse
    "cast_storage": lambda f: f(X(3, 4), "csr"),
    "sparse dot (csr)": lambda f: f(
        mx.nd.sparse.cast_storage(X(3, 4), "csr"), X(4, 2)),
    "sparse elemwise_add": lambda f: f(
        mx.nd.sparse.cast_storage(X(3, 4), "row_sparse"),
        mx.nd.sparse.cast_storage(X(3, 4), "row_sparse")),
    "retain": lambda f: f(
        mx.nd.sparse.cast_storage(X(3, 4), "row_sparse"),
        nd.array(np.array([0, 2], np.float32))),
    "row_sparse_array": lambda f: f(
        (X(2, 4), nd.array(np.array([0, 2], np.float32))), shape=(3, 4)),
    "csr_matrix": lambda f: f(
        (nd.array(np.array([1.0, 2.0], np.float32)),
         nd.array(np.array([1, 3], np.float32)),
         nd.array(np.array([0, 1, 2], np.float32))), shape=(2, 4)),
}
# rows whose impl isn't an nd.* path never reach the smoke loop; rows
# mapped to `True` above are owned by dedicated test files (asserted to
# exist below)
OWNED_ELSEWHERE = {
    "Custom": "test_custom_op.py",
    "dequantize": "test_rtc_quant.py",
    "requantize": "test_rtc_quant.py",
    "quantized_conv": "test_rtc_quant.py",
    "quantized_fully_connected": "test_rtc_quant.py",
    "quantized_flatten": "test_rtc_quant.py",
    "quantized_pooling": "test_rtc_quant.py",
}


def test_owned_elsewhere_files_exist():
    here = os.path.dirname(os.path.abspath(__file__))
    for op, fname in OWNED_ELSEWHERE.items():
        assert os.path.exists(os.path.join(here, fname)), (op, fname)


@pytest.mark.slow  # ~2 min for the full 280-op sweep; audit tier
@pytest.mark.parametrize("name,impl", list(yes_rows()),
                         ids=[n for n, _ in yes_rows()])
def test_smoke_invoke(name, impl):
    fn = _resolve(impl)
    tmpl = TEMPLATES.get(name)
    if tmpl is not None:
        out = tmpl(fn)
        assert out is not None
        return
    # generic cascade: unary, then binary
    try:
        out = fn(X(2, 3))
    except TypeError:
        out = fn(X(2, 3), X(2, 3))
    assert out is not None
