"""Pallas kernel tests — run in interpret mode on CPU, real Mosaic on TPU.

Oracle: dense jnp attention (the check_consistency pattern from the
reference's test strategy, SURVEY §4)."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_mx.kernels.flash_attention import (flash_attention,
                                            mha_flash_attention)


def dense_attention(q, k, v, causal=False):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("btd,bsd->bts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t, tk = s.shape[-2:]
        mask = np.arange(t)[:, None] >= np.arange(tk)[None, :]
        s = jnp.where(mask[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bts,bsd->btd", p, v.astype(jnp.float32))


def make_qkv(bh=2, t=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (bh, t, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal):
    q, k, v = make_qkv()
    out = flash_attention(q, k, v, 1.0 / math.sqrt(q.shape[-1]), causal)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_forward_bf16():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, 1.0 / math.sqrt(q.shape[-1]), False)
    ref = dense_attention(q, k, v, False)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_flash_backward_matches_dense(causal):
    q, k, v = make_qkv(bh=1, t=256, d=64)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, scale, causal) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_multiblock():
    # several q and k blocks: exercises the online-softmax carry
    q, k, v = make_qkv(bh=1, t=512, d=64, seed=3)
    out = flash_attention(q, k, v, 1.0 / math.sqrt(64), False,
                          block_q=128, block_k=128)
    ref = dense_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_mha_wrapper_layout():
    b, h, t, d = 2, 4, 128, 64
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, h, t, d)) for kk in ks)
    out = mha_flash_attention(q, k, v)
    ref = dense_attention(q.reshape(b * h, t, d), k.reshape(b * h, t, d),
                          v.reshape(b * h, t, d)).reshape(b, h, t, d)
    assert out.shape == (b, h, t, d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_under_jit():
    q, k, v = make_qkv(bh=1, t=128)
    fn = jax.jit(lambda a, b, c: flash_attention(a, b, c, 0.125, True))
    out = fn(q, k, v)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_default_scale():
    q, k, v = make_qkv(bh=1, t=128)
    out = flash_attention(q, k, v)  # no explicit scale
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_rejects_partial_kv_blocks():
    from tpu_mx.kernels.flash_attention import supported
    assert not supported((1, 256, 64), jnp.float32, kv_len=300)
    assert supported((1, 256, 64), jnp.float32, kv_len=512)


def test_flash_cross_attention_lengths():
    # Tq != Tkv but both tile-aligned
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (2, 128, 64))
    k = jax.random.normal(ks[1], (2, 384, 64))
    v = jax.random.normal(ks[2], (2, 384, 64))
    out = flash_attention(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_multiblock(causal):
    # explicit 128-blocks over t=256: exercises cross-block dq/dk/dv
    # accumulation and the causal skip predicates in the backward kernels
    q, k, v = make_qkv(bh=1, t=256, d=64, seed=11)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, None, causal,
                                block_q=128, block_k=128) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def dense_attention_masked(q, k, v, valid, causal=False):
    """Oracle with a key-padding mask: columns >= valid[b] excluded."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("btd,bsd->bts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    tk = k.shape[1]
    s = jnp.where(jnp.arange(tk)[None, None, :] < valid[:, None, None],
                  s, -1e30)
    if causal:
        t = q.shape[1]
        mask = np.arange(t)[:, None] >= np.arange(tk)[None, :]
        s = jnp.where(mask[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bts,bsd->btd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_padding_mask_matches_dense(causal):
    # ragged valid lengths incl. block-interior (200), block-boundary (128),
    # full (256) and minimal (1) — VERDICT r2 missing#2
    q, k, v = make_qkv(bh=4, t=256, d=64, seed=5)
    valid = jnp.asarray([200, 128, 256, 1], jnp.int32)
    out = flash_attention(q, k, v, causal=causal, kv_valid=valid,
                          block_q=128, block_k=128)
    ref = dense_attention_masked(q, k, v, valid, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_padding_mask_backward(causal):
    q, k, v = make_qkv(bh=3, t=256, d=64, seed=9)
    valid = jnp.asarray([130, 256, 7], jnp.int32)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=causal, kv_valid=valid,
            block_q=128, block_k=128)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(dense_attention_masked(q, k, v, valid,
                                                      causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")
    # padded keys (beyond valid) must receive exactly zero dk/dv
    dk = np.asarray(g_flash[1])
    assert np.all(dk[0, 130:] == 0.0) and np.all(dk[2, 7:] == 0.0)


def test_mha_valid_length_broadcasts_heads():
    # (B,) valid_length must apply identically to every head
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    B, H, T, D = 2, 2, 128, 64
    q, k, v = (jax.random.normal(kk, (B, H, T, D)) for kk in ks)
    valid = jnp.asarray([100, 37], jnp.int32)
    out = mha_flash_attention(q, k, v, valid_length=valid)
    flat = lambda x: x.reshape(B * H, T, D)
    ref = dense_attention_masked(flat(q), flat(k), flat(v),
                                 jnp.repeat(valid, H))
    np.testing.assert_allclose(np.asarray(flat(out)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("t", [96, 130, 320, 384, 640, 1000, 1536])
def test_pick_block_guard_odd_lengths(t):
    """Any T either runs correctly (vs dense oracle) or raises a clean
    ValueError — never a silent O(T^2)-VMEM single block (VERDICT r2
    weak#6/ask#9)."""
    from tpu_mx.kernels.flash_attention import MAX_BLOCK_ELEMS, _pick_block
    ks = jax.random.split(jax.random.PRNGKey(t), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 64)) for kk in ks)
    bq = min(_pick_block(t, 512), t)
    bk = min(_pick_block(t, 1024), t)
    if t % bq or t % bk or bq * bk > MAX_BLOCK_ELEMS:
        with pytest.raises(ValueError):
            flash_attention(q, k, v)
    else:
        out = flash_attention(q, k, v)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_flash_dropout_rejected_off_tpu():
    # the in-kernel PRNG has no interpret lowering; a clear error (and a
    # supported()=False gate) beats a crash deep inside Mosaic
    from tpu_mx.kernels.flash_attention import supported
    q, k, v = make_qkv(bh=1, t=128, d=64)
    if jax.default_backend() != "tpu":
        assert not supported(q.shape, q.dtype, dropout_rate=0.1)
        with pytest.raises(ValueError, match="dropout"):
            flash_attention(q, k, v, dropout_rate=0.1,
                            dropout_seed=jnp.zeros((1,), jnp.int32))


class TestFlashBias:
    """In-kernel additive attention bias (ALiBi/relative-position):
    fwd + all four grads vs the dense reference, every broadcast layout."""

    def _dense(self, q, k, v, bias, causal):
        import jax
        import jax.numpy as jnp
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        s = s + bias
        if causal:
            t, tk = q.shape[2], k.shape[2]
            m = jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :]
            s = jnp.where(m[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.parametrize("bias_shape,causal", [
        ((2, 4, 128, 128), False), ((1, 4, 128, 128), False),
        ((1, 1, 128, 128), False), ((2, 4, 128, 128), True),
    ])
    @pytest.mark.slow
    def test_bias_fwd_bwd_vs_dense(self, bias_shape, causal):
        import jax
        import jax.numpy as jnp
        from tpu_mx.kernels.flash_attention import mha_flash_attention
        rng = np.random.RandomState(0)
        B, H, T, D = 2, 4, 128, 64
        q, k, v = (jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
                   for _ in range(3))
        bias = jnp.asarray(rng.randn(*bias_shape).astype(np.float32))

        def loss_flash(q, k, v, bias):
            return jnp.sum(jnp.sin(mha_flash_attention(
                q, k, v, causal=causal, bias=bias,
                block_q=64, block_k=64)))

        def loss_dense(q, k, v, bias):
            return jnp.sum(jnp.sin(self._dense(q, k, v, bias, causal)))

        out_f = mha_flash_attention(q, k, v, causal=causal, bias=bias,
                                    block_q=64, block_k=64)
        out_d = self._dense(q, k, v, bias, causal)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                                   rtol=2e-4, atol=2e-5)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b, name in zip(gf, gd, "qkvb"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-5,
                                       err_msg=f"d{name} {bias_shape}")

    @pytest.mark.slow
    def test_bias_with_padding_mask(self):
        import jax.numpy as jnp
        from tpu_mx.kernels.flash_attention import mha_flash_attention
        rng = np.random.RandomState(1)
        B, H, T, D = 2, 2, 128, 32
        q, k, v = (jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
                   for _ in range(3))
        bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
        vl = np.array([128, 64])
        out = mha_flash_attention(q, k, v, valid_length=vl, bias=bias,
                                  block_q=64, block_k=64)
        # dense reference with key-padding mask
        import jax
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias
        km = (jnp.arange(T)[None, None, None, :] <
              jnp.asarray(vl)[:, None, None, None])
        s = jnp.where(km, s, -jnp.inf)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_bias_shape_validation(self):
        import jax.numpy as jnp
        from tpu_mx.kernels.flash_attention import flash_attention
        q = jnp.ones((4, 128, 32), jnp.float32)
        with pytest.raises(ValueError, match="bias shape"):
            flash_attention(q, q, q, bias=jnp.ones((3, 128, 128)))


def test_flash_bias_singleton_dims_and_ambiguity():
    """(1,H,1,T) ALiBi-layout biases broadcast correctly through the
    kernel path, and bare-divisor leading dims are rejected without
    bias_groups."""
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import (flash_attention,
                                               mha_flash_attention)
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 4, 128, 32
    q, k, v = (jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
               for _ in range(3))
    bias_row = jnp.asarray(rng.randn(1, H, 1, T).astype(np.float32))
    out = mha_flash_attention(q, k, v, bias=bias_row, block_q=64,
                              block_k=64)
    import jax
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias_row
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # divisor-without-groups is ambiguous -> rejected
    qf = q.reshape(B * H, T, D)
    with pytest.raises(ValueError, match="ambiguous"):
        flash_attention(qf, qf, qf, bias=jnp.ones((2, T, T)))
    # ...but explicit bias_groups makes it legal
    out2 = flash_attention(qf, qf, qf, bias=jnp.zeros((2, T, T)),
                           bias_groups=2, block_q=64, block_k=64)
    assert out2.shape == qf.shape


def test_attention_env_knob(monkeypatch):
    """TPUMX_ATTENTION measurement knob: bad values rejected, 'dense'
    always runs the XLA dense path."""
    import numpy as np
    import jax.numpy as jnp
    from tpu_mx.parallel.ring_attention import local_flash_attention
    q = jnp.asarray(np.random.RandomState(0).rand(1, 2, 128, 64),
                    jnp.float32)
    monkeypatch.setenv("TPUMX_ATTENTION", "bogus")
    with pytest.raises(ValueError, match="TPUMX_ATTENTION"):
        local_flash_attention(q, q, q)
    monkeypatch.setenv("TPUMX_ATTENTION", "dense")
    out = local_flash_attention(q, q, q)
    assert out.shape == q.shape


# ---------------------------------------------------------------------------
# paged-attention decode kernel (ISSUE 9) — interpret mode on CPU
# ---------------------------------------------------------------------------
def _paged_numpy_ref(q, k_pool, v_pool, tables, lengths):
    """Per-sequence dense truth: resolve each block table by hand."""
    b, h, d = q.shape
    bs = k_pool.shape[1]
    out = np.zeros_like(q)
    for i in range(b):
        length = int(lengths[i])
        nb = -(-length // bs)
        k = k_pool[tables[i, :nb]].reshape(-1, h, d)[:length]
        v = v_pool[tables[i, :nb]].reshape(-1, h, d)[:length]
        s = np.einsum("hd,khd->hk", q[i].astype(np.float64),
                      k.astype(np.float64)) / math.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("hk,khd->hd", p, v.astype(np.float64))
    return out


def _paged_case(seed=0, nblocks=24, bs=4, h=2, d=8, specs=((10, (7, 2, 9)),
                                                          (3, (5,)),
                                                          (16, (11, 1, 4, 8)))):
    """Fragmented tables, ragged lengths, rows 0-padded to a shared NB."""
    rng = np.random.RandomState(seed)
    kp = rng.randn(nblocks, bs, h, d).astype(np.float32)
    vp = rng.randn(nblocks, bs, h, d).astype(np.float32)
    b = len(specs)
    nb = max(len(t) for _, t in specs)
    tables = np.zeros((b, nb), np.int32)
    lens = np.zeros(b, np.int32)
    for i, (length, tab) in enumerate(specs):
        tables[i, :len(tab)] = tab
        lens[i] = length
    q = rng.randn(b, h, d).astype(np.float32)
    return q, kp, vp, tables, lens


@pytest.mark.parametrize("arm", ["kernel", "xla"])
def test_paged_attention_matches_reference(arm):
    from tpu_mx.kernels.paged_attention import (paged_attention,
                                                paged_attention_reference)
    q, kp, vp, tables, lens = _paged_case()
    fn = paged_attention if arm == "kernel" else paged_attention_reference
    out = np.asarray(fn(q, kp, vp, tables, lens))
    ref = _paged_numpy_ref(q, kp, vp, tables, lens)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_paged_attention_padding_blocks_cannot_leak():
    """Entries past a row's real blocks (0-padding) and slots past
    `lengths` inside the last block must be EXACTLY invisible: poison
    them and the output may not move a single bit."""
    from tpu_mx.kernels.paged_attention import paged_attention
    q, kp, vp, tables, lens = _paged_case()
    base = np.asarray(paged_attention(q, kp, vp, tables, lens))
    kp2, vp2 = kp.copy(), vp.copy()
    kp2[0] = 1e9          # block 0 backs every padded table entry
    vp2[0] = -1e9
    kp2[9, 2:] = 1e9      # row 0: length 10 ends 2 slots into block 9
    vp2[9, 2:] = -1e9
    kp2[5, 3:] = 1e9      # row 1: length 3 ends inside block 5
    vp2[5, 3:] = -1e9
    again = np.asarray(paged_attention(q, kp2, vp2, tables, lens))
    np.testing.assert_array_equal(base, again)


def test_paged_attention_accepts_single_token_axis():
    from tpu_mx.kernels.paged_attention import paged_attention
    q, kp, vp, tables, lens = _paged_case()
    out3 = np.asarray(paged_attention(q, kp, vp, tables, lens))
    out4 = np.asarray(paged_attention(q[:, None], kp, vp, tables, lens))
    assert out4.shape == (q.shape[0], 1) + q.shape[1:]
    np.testing.assert_array_equal(out4[:, 0], out3)


def _paged_numpy_window_ref(q, k_pool, v_pool, tables, lengths):
    """Window truth by reduction: row ``t`` of a ``Tq`` window is the
    single-token case at length ``lengths - (Tq-1-t)``."""
    b, tq, h, d = q.shape
    out = np.zeros((b, tq, h, d), np.float64)
    for t in range(tq):
        lens_t = (lengths - (tq - 1 - t)).astype(np.int32)
        out[:, t] = _paged_numpy_ref(q[:, t], k_pool, v_pool,
                                     tables, lens_t)
    return out


@pytest.mark.parametrize("arm", ["kernel", "walk", "xla"])
def test_paged_attention_window_matches_reference(arm):
    """The widened ``(B, Tq, H, D)`` query axis — the speculative verify
    call — must match the per-row single-token truth on every arm."""
    from tpu_mx.kernels import paged_attention as pk
    q1, kp, vp, tables, lens = _paged_case()
    rng = np.random.RandomState(7)
    tq = 3                                  # min length is 3 in the case
    q = rng.randn(len(lens), tq, q1.shape[-2],
                  q1.shape[-1]).astype(np.float32)
    scale = 1.0 / math.sqrt(q1.shape[-1])
    fn = {"kernel": pk.paged_attention,
          "walk": lambda *a: pk.window_walk(*a, scale),
          "xla": pk.paged_attention_reference}[arm]
    out = np.asarray(fn(q, kp, vp, tables, lens))
    ref = _paged_numpy_window_ref(q, kp, vp, tables, lens)
    assert out.shape == q.shape
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_paged_window_rows_are_causally_staggered():
    """Row ``t`` of the window sits at absolute position
    ``length - Tq + t``: poisoning the LAST occupied slot may move only
    the last row — earlier rows must not see their successors' keys."""
    from tpu_mx.kernels.paged_attention import paged_attention
    q1, kp, vp, tables, lens = _paged_case()
    rng = np.random.RandomState(8)
    tq = 3
    q = rng.randn(len(lens), tq, q1.shape[-2],
                  q1.shape[-1]).astype(np.float32)
    base = np.asarray(paged_attention(q, kp, vp, tables, lens))
    kp2, vp2 = kp.copy(), vp.copy()
    bs = kp.shape[1]
    for i in range(len(lens)):
        last = int(lens[i]) - 1             # final key slot of row i
        blk = int(tables[i, last // bs])
        kp2[blk, last % bs] = 1e6
        vp2[blk, last % bs] = -1e6
    again = np.asarray(paged_attention(q, kp2, vp2, tables, lens))
    np.testing.assert_array_equal(base[:, :-1], again[:, :-1])
    assert not np.array_equal(base[:, -1], again[:, -1])


def test_paged_attention_bf16_pool():
    import jax.numpy as jnp
    from tpu_mx.kernels.paged_attention import paged_attention
    q, kp, vp, tables, lens = _paged_case()
    out = np.asarray(paged_attention(
        jnp.asarray(q, jnp.bfloat16), jnp.asarray(kp, jnp.bfloat16),
        jnp.asarray(vp, jnp.bfloat16), tables, lens), np.float32)
    ref = _paged_numpy_ref(q, kp, vp, tables, lens)
    np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.05)


def test_paged_attention_rejects_mismatched_operands():
    from tpu_mx.kernels.paged_attention import paged_attention
    q, kp, vp, tables, lens = _paged_case()
    with pytest.raises(ValueError, match="pool heads/dim"):
        paged_attention(q[:, :1], kp, vp, tables, lens)
    with pytest.raises(ValueError, match="block_tables"):
        paged_attention(q, kp, vp, tables[:2], lens)
    with pytest.raises(ValueError, match="lengths"):
        paged_attention(q, kp, vp, tables, lens[:2])


def test_paged_supported_gate():
    """Interpret mode accepts anything (correctness-only); the real-TPU
    constraints are shape/dtype gates the dispatcher consults."""
    import jax
    from tpu_mx.kernels import paged_attention as pk
    if jax.default_backend() != "tpu":
        assert pk.supported(8, np.float32)
    else:
        assert pk.supported(64, np.float32, 16)
        assert not pk.supported(8, np.float32, 16)
