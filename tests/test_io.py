"""mx.io / mx.recordio tests — mirrors the reference's test_io.py /
test_recordio.py coverage (REF:tests/python/unittest/)."""
import os
import struct

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import io as mio
from tpu_mx import recordio


# ---------------------------------------------------------------- recordio --
def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"", b"x" * 1001, os.urandom(4096)]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()


def test_recordio_magic_layout(tmp_path):
    """First 4 bytes must be the dmlc magic so reference tools accept it."""
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"abc")
    w.close()
    raw = open(path, "rb").read()
    magic, lrec = struct.unpack("<II", raw[:8])
    assert magic == 0xCED7230A
    assert lrec & ((1 << 29) - 1) == 3
    assert len(raw) == 12  # 8 header + 3 data + 1 pad


def test_indexed_recordio(tmp_path):
    rec, idx = str(tmp_path / "t.rec"), str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(20):
        w.write_idx(i, f"record-{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.keys == list(range(20))
    for i in (7, 0, 19, 3):
        assert r.read_idx(i) == f"record-{i}".encode()
    r.close()


def test_pack_unpack_scalar_and_vector_label():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(h, b"payload")
    h2, data = recordio.unpack(s)
    assert data == b"payload" and h2.label == 3.0 and h2.id == 42

    lab = np.array([1.0, 2.0, 3.5], np.float32)
    s = recordio.pack(recordio.IRHeader(0, lab, 7, 0), b"img")
    h3, data = recordio.unpack(s)
    assert data == b"img"
    np.testing.assert_allclose(h3.label, lab)


def test_pack_img_roundtrip():
    img = (np.random.rand(32, 24, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    h, img2 = recordio.unpack_img(s)
    np.testing.assert_array_equal(img, img2)  # png is lossless


# -------------------------------------------------------------- NDArrayIter --
def _collect(it):
    it.reset()
    return list(it)


def test_ndarrayiter_basic():
    data = np.arange(60, dtype=np.float32).reshape(20, 3)
    label = np.arange(20, dtype=np.float32)
    it = mio.NDArrayIter(data, label, batch_size=6, last_batch_handle="pad")
    batches = _collect(it)
    assert len(batches) == 4  # ceil(20/6)
    assert batches[-1].pad == 4
    first = batches[0].data[0].asnumpy()
    np.testing.assert_allclose(first, data[:6])


def test_ndarrayiter_discard_and_shuffle():
    data = np.arange(20, dtype=np.float32).reshape(20, 1)
    it = mio.NDArrayIter(data, None, batch_size=6,
                         last_batch_handle="discard", shuffle=True)
    batches = _collect(it)
    assert len(batches) == 3
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in batches])
    assert len(set(seen.tolist())) == 18  # no duplicates within epoch


def test_ndarrayiter_roll_over():
    data = np.arange(20, dtype=np.float32).reshape(20, 1)
    it = mio.NDArrayIter(data, None, batch_size=6,
                         last_batch_handle="roll_over")
    ep1 = _collect(it)
    assert len(ep1) == 3  # 18 served, 2-sample tail deferred
    seen1 = np.concatenate([b.data[0].asnumpy().ravel() for b in ep1])
    assert len(np.unique(seen1)) == 18  # no duplication inside the epoch
    it.reset()
    ep2 = list(it)
    # tail (2) + fresh 20 = 22 -> 3 full batches, new tail of 4 deferred
    assert len(ep2) == 3
    head = ep2[0].data[0].asnumpy().ravel()
    np.testing.assert_allclose(head[:2], [18.0, 19.0])  # carried tail leads


def test_ndarrayiter_seed_reproducible():
    data = np.arange(20, dtype=np.float32).reshape(20, 1)
    a = mio.NDArrayIter(data, None, batch_size=5, shuffle=True, seed=7)
    b = mio.NDArrayIter(data, None, batch_size=5, shuffle=True, seed=7)
    for ba, bb in zip(_collect(a), _collect(b)):
        np.testing.assert_array_equal(ba.data[0].asnumpy(),
                                      bb.data[0].asnumpy())


def test_prefetching_iter_exhausted_no_hang():
    it = mio.PrefetchingIter(
        mio.NDArrayIter(np.zeros((10, 2), np.float32), batch_size=5))
    assert len(list(it)) == 2
    with pytest.raises(StopIteration):  # must not deadlock
        next(it)


def test_ndarrayiter_provide():
    it = mio.NDArrayIter({"a": np.zeros((10, 4), np.float32)},
                         {"lab": np.zeros((10,), np.float32)}, batch_size=5)
    d, = it.provide_data
    assert d.name == "a" and d.shape == (5, 4)
    l, = it.provide_label
    assert l.name == "lab"


def test_resize_iter():
    it = mio.NDArrayIter(np.zeros((20, 2), np.float32), batch_size=5)
    rit = mio.ResizeIter(it, 7)  # epoch forced to 7 batches, wraps around
    assert len(_collect(rit)) == 7


def test_prefetching_iter():
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    base = mio.NDArrayIter(data, np.zeros(20, np.float32), batch_size=5)
    pit = mio.PrefetchingIter(base)
    batches = list(pit)
    assert len(batches) == 4
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5])
    pit.reset()
    assert len(list(pit)) == 4


# --------------------------------------------------------------- CSV/MNIST --
def test_csviter(tmp_path):
    data = np.random.rand(17, 6).astype(np.float32)
    labels = np.arange(17, dtype=np.float32)
    dpath, lpath = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels, delimiter=",")
    it = mio.CSVIter(data_csv=dpath, data_shape=(6,), label_csv=lpath,
                     batch_size=5)
    b = next(iter(it))
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:5], rtol=1e-5)


def _write_idx_ubyte(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(np.uint8).tobytes())


def test_mnistiter(tmp_path):
    imgs = (np.random.rand(50, 28, 28) * 255).astype(np.uint8)
    labels = np.random.randint(0, 10, 50).astype(np.uint8)
    ipath, lpath = str(tmp_path / "img"), str(tmp_path / "lab")
    _write_idx_ubyte(ipath, imgs)
    _write_idx_ubyte(lpath, labels)
    it = mio.MNISTIter(image=ipath, label=lpath, batch_size=10, shuffle=False)
    b = next(iter(it))
    assert b.data[0].shape == (10, 1, 28, 28)
    np.testing.assert_allclose(b.data[0].asnumpy()[0, 0],
                               imgs[0].astype(np.float32) / 255.0)
    flat = mio.MNISTIter(image=ipath, label=lpath, batch_size=10, flat=True)
    assert next(iter(flat)).data[0].shape == (10, 784)


# --------------------------------------------------------- ImageRecordIter --
def test_image_record_iter(tmp_path):
    rec, idx = str(tmp_path / "im.rec"), str(tmp_path / "im.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(12):
        img = (rng.rand(40, 40, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()
    it = mio.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                             data_shape=(3, 32, 32), batch_size=4,
                             shuffle=True, rand_crop=True, rand_mirror=True,
                             preprocess_threads=2)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 32, 32)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert set(labels.tolist()) <= {0.0, 1.0, 2.0}


def test_device_prefetch_iter_matches_and_casts():
    """DevicePrefetchIter: same batches/order as the wrapped iterator,
    data staged on-device (optionally cast) off the training loop's
    critical path."""
    import jax
    rs = np.random.RandomState(0)
    x = rs.rand(20, 4).astype(np.float32)
    y = rs.randint(0, 3, 20).astype(np.float32)
    base = mx.io.NDArrayIter(x, y, batch_size=5)
    ref_batches = [(b.data[0].asnumpy(), b.label[0].asnumpy())
                   for b in base]
    base.reset()
    it = mx.io.DevicePrefetchIter(base, cast_data="bfloat16")
    got = list(it)
    assert len(got) == len(ref_batches) == 4
    for b, (rd, rl) in zip(got, ref_batches):
        assert str(b.data[0].dtype) == "bfloat16"
        np.testing.assert_allclose(b.data[0].asnumpy().astype(np.float32),
                                   rd, rtol=1e-2)
        np.testing.assert_array_equal(b.label[0].asnumpy(), rl)
        assert isinstance(b.data[0]._data, jax.Array)
    # reset restarts the epoch
    it.reset()
    assert len(list(it)) == 4


# ----------------------------------------------- deterministic resume -----
# state_dict/load_state_dict round trips (docs/robustness.md): a freshly
# constructed identical iterator, loaded with a mid-run snapshot, must
# produce exactly the not-yet-consumed batches — and identical shuffles on
# every later reset (the RNG stream rides the state).

def _drain_batches(it):
    """Remaining batches as comparable (data, label, pad) numpy tuples."""
    out = []
    while True:
        try:
            b = it.next()
        except StopIteration:
            return out
        out.append(([d.asnumpy() for d in b.data],
                    [l.asnumpy() for l in b.label], b.pad))


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for (da, la, pa), (db, lb, pb) in zip(a, b):
        assert pa == pb
        for x, y in zip(da, db):
            np.testing.assert_array_equal(x, y)
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)


def _epoch_sequence(it, epochs=2):
    """`epochs` full reset+drain cycles (proves the restored RNG stream
    reproduces future shuffles, not just the current epoch's tail)."""
    out = []
    for _ in range(epochs):
        it.reset()
        out.extend(_drain_batches(it))
    return out


@pytest.mark.parametrize("shuffle", [False, True])
@pytest.mark.parametrize("lbh", ["pad", "discard", "roll_over"])
def test_ndarrayiter_state_roundtrip_midepoch(shuffle, lbh):
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    label = np.arange(20, dtype=np.float32)

    def make():
        return mio.NDArrayIter(data, label, batch_size=6, shuffle=shuffle,
                               last_batch_handle=lbh, seed=3)

    it = make()
    it.reset()
    for _ in range(2):  # consume two batches, snapshot mid-epoch
        it.next()
    sd = it.state_dict()
    expect = _drain_batches(it) + _epoch_sequence(it, epochs=2)
    it2 = make()
    it2.load_state_dict(sd)
    got = _drain_batches(it2) + _epoch_sequence(it2, epochs=2)
    _assert_batches_equal(expect, got)


def test_ndarrayiter_state_roundtrip_at_epoch_boundary():
    """Snapshot AFTER the last batch (the per-epoch capsule point): the
    restored iterator is exhausted, and the next reset reshuffles with
    the exact restored stream — incl. the roll_over leftover."""
    data = np.arange(20, dtype=np.float32).reshape(20, 1)
    for lbh in ("pad", "roll_over"):
        def make():
            return mio.NDArrayIter(data, None, batch_size=6, shuffle=True,
                                   last_batch_handle=lbh, seed=5)
        it = make()
        it.reset()
        _drain_batches(it)          # consume the whole epoch
        sd = it.state_dict()
        expect = _epoch_sequence(it, epochs=2)
        it2 = make()
        it2.load_state_dict(sd)
        assert not it2.iter_next()  # restored at the boundary: exhausted
        got = _epoch_sequence(it2, epochs=2)
        _assert_batches_equal(expect, got)


def test_ndarrayiter_load_rejects_wrong_iterator_state():
    it = mio.NDArrayIter(np.zeros((8, 2), np.float32), batch_size=4)
    rit = mio.ResizeIter(mio.NDArrayIter(np.zeros((8, 2), np.float32),
                                         batch_size=4), 2)
    with pytest.raises(mx.base.MXNetError, match="captured from"):
        it.load_state_dict(rit.state_dict())


def test_resize_iter_state_roundtrip():
    data = np.arange(40, dtype=np.float32).reshape(20, 2)

    def make():
        return mio.ResizeIter(
            mio.NDArrayIter(data, None, batch_size=6, shuffle=True, seed=9),
            7)

    it = make()
    it.reset()
    for _ in range(3):
        it.next()
    sd = it.state_dict()
    expect = _drain_batches(it)
    it2 = make()
    it2.load_state_dict(sd)
    _assert_batches_equal(expect, _drain_batches(it2))


def test_libsvmiter_state_roundtrip(tmp_path):
    path = str(tmp_path / "d.svm")
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for i in range(11):
            feats = " ".join(f"{j}:{rng.rand():.6f}"
                             for j in sorted(rng.choice(8, 3, replace=False)))
            f.write(f"{i % 2} {feats}\n")

    def make():
        return mio.LibSVMIter(data_libsvm=path, data_shape=(8,),
                              batch_size=4)

    def tolist(it):
        out = []
        while it.iter_next():
            out.append((it.getdata()[0].asnumpy(),
                        it.getlabel()[0].asnumpy(), it.getpad()))
        return out

    it = make()
    it.reset()
    it.iter_next()  # consume one batch, snapshot mid-epoch
    sd = it.state_dict()
    expect = tolist(it)
    it2 = make()
    it2.load_state_dict(sd)
    got = tolist(it2)
    assert len(expect) == len(got)
    for (da, la, pa), (db, lb, pb) in zip(expect, got):
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(la, lb)
        assert pa == pb


def test_image_record_iter_state_roundtrip(tmp_path):
    rec, idx = str(tmp_path / "im.rec"), str(tmp_path / "im.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(12):
        img = (rng.rand(36, 36, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()

    def make():
        return mio.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, data_shape=(3, 32, 32),
            batch_size=4, shuffle=True, rand_crop=True, rand_mirror=True,
            preprocess_threads=2, seed=7, use_native=False)

    it = make()
    it.reset()
    it.next()  # mid-epoch snapshot: cursor + permutation + augment RNG
    sd = it.state_dict()
    expect = _drain_batches(it) + _epoch_sequence(it, epochs=1)
    it2 = make()
    it2.load_state_dict(sd)
    got = _drain_batches(it2) + _epoch_sequence(it2, epochs=1)
    _assert_batches_equal(expect, got)
    it.close()
    it2.close()


def test_prefetching_iter_state_roundtrip_and_inflight_not_lost():
    data = np.arange(80, dtype=np.float32).reshape(40, 2)
    label = np.arange(40, dtype=np.float32)

    def make():
        return mio.PrefetchingIter(
            mio.NDArrayIter(data, label, batch_size=5, shuffle=True,
                            seed=13))

    it = make()
    it.reset()
    for _ in range(2):
        it.next()
    sd = it.state_dict()  # drain-then-snapshot pauses the worker
    assert sd["delivered"] == 2
    # the live iterator keeps going and LOSES NOTHING: queued batches were
    # buffered by the snapshot, the worker resumes lazily for the rest
    expect = _drain_batches(it)
    assert len(expect) == 6  # 8 batches/epoch, 2 consumed
    it2 = make()
    it2.load_state_dict(sd)  # epoch-start state + fast-forward replay
    _assert_batches_equal(expect, _drain_batches(it2))
    it.close()
    it2.close()


def test_prefetching_iter_boundary_snapshot_needs_no_replay():
    """An end-of-epoch snapshot (the per-epoch capsule point) stores the
    wrapped iterators' final state directly — restore must not replay the
    whole epoch through decode/transfer just to advance cursors."""
    data = np.arange(40, dtype=np.float32).reshape(20, 2)

    def make():
        return mio.PrefetchingIter(
            mio.NDArrayIter(data, None, batch_size=5, shuffle=True, seed=3))

    it = make()
    it.reset()
    _drain_batches(it)  # consume the whole epoch
    sd = it.state_dict()
    assert sd["delivered"] == 0 and sd["exhausted"]  # no fast-forward
    expect = _epoch_sequence(it, epochs=2)
    it2 = make()
    it2.load_state_dict(sd)
    assert not it2.iter_next()  # restored at the boundary: exhausted
    _assert_batches_equal(expect, _epoch_sequence(it2, epochs=2))
    it.close()
    it2.close()


def test_prefetching_iter_close_joins_thread():
    """close() joins the prefetch thread even when the consumer abandons
    the epoch with the queue full (the pre-close leak: a blocked put)."""
    it = mio.PrefetchingIter(
        mio.NDArrayIter(np.zeros((100, 2), np.float32), batch_size=2),
        depth=1)
    it.next()  # worker running, queue refilling
    t = it._thread
    it.close()
    assert t is not None and not t.is_alive()
    assert it._thread is None
    # idempotent, and iteration reports exhaustion rather than hanging
    it.close()
    assert not it.iter_next()


def test_prefetching_iter_context_manager_and_exception_join():
    class Boom(mio.DataIter):
        def __init__(self):
            super().__init__(2)
            self.n = 0

        @property
        def provide_data(self):
            return [mio.DataDesc("data", (2, 2))]

        def iter_next(self):
            self.n += 1
            if self.n > 2:
                raise RuntimeError("decode failed")
            return True

        def getdata(self):
            return [mx.nd.zeros((2, 2))]

        def getlabel(self):
            return []

    with mio.PrefetchingIter(Boom(), depth=1) as it:
        it.next()
        with pytest.raises(RuntimeError, match="decode failed"):
            while True:
                it.next()
        thread = it._thread
    # the with-block exit closed it: no leaked prefetch thread
    assert it._thread is None
    assert thread is None or not thread.is_alive()


def test_device_prefetch_iter_mesh_sharding():
    """Meshed training feed: device= accepts a NamedSharding so batches
    arrive dp-sharded, compatible with a meshed CompiledTrainStep."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tpu_mx.parallel import make_mesh
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    rs = np.random.RandomState(0)
    x = rs.rand(32, 4).astype(np.float32)
    y = rs.randint(0, 3, 32).astype(np.float32)
    it = mx.io.DevicePrefetchIter(
        mx.io.NDArrayIter(x, y, batch_size=16),
        device=NamedSharding(mesh, P("dp")))
    batches = list(it)
    assert len(batches) == 2
    arr = batches[0].data[0]._data
    assert len(arr.sharding.device_set) == 8  # really dp-sharded
    np.testing.assert_allclose(np.asarray(arr), x[:16], rtol=1e-6)
