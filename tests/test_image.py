"""mx.image + mx.image.ImageDetIter tests (reference pattern:
tests/python/unittest/test_image.py) using synthetic PNGs and .rec files."""
import io as _io
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import image as img_mod


def _png_bytes(arr):
    from PIL import Image
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def _rand_img(h=32, w=48, seed=0):
    return np.random.RandomState(seed).randint(0, 255, (h, w, 3), "uint8")


def test_imdecode_imresize():
    arr = _rand_img()
    img = mx.image.imdecode(_png_bytes(arr))
    np.testing.assert_array_equal(img.asnumpy(), arr)
    small = mx.image.imresize(img, 24, 16)
    assert small.shape == (16, 24, 3)
    short = mx.image.resize_short(img, 16)
    assert min(short.shape[:2]) == 16


def test_augmenters():
    arr = _rand_img(40, 40)
    img = mx.nd.array(arr, dtype="uint8")
    crop = img_mod.CenterCropAug((24, 24))(img)
    assert crop.shape == (24, 24, 3)
    flip = img_mod.HorizontalFlipAug(1.0)(img)
    np.testing.assert_array_equal(flip.asnumpy(), arr[:, ::-1])
    cast = img_mod.CastAug()(img)
    assert cast.dtype == np.float32
    norm = img_mod.ColorNormalizeAug(np.array([10.0, 10, 10]),
                                     np.array([2.0, 2, 2]))(cast)
    np.testing.assert_allclose(norm.asnumpy(),
                               (arr.astype("float32") - 10) / 2, rtol=1e-5)


def _write_rec(tmp_path, n=6, det=False):
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        arr = _rand_img(seed=i)
        if det:
            label = np.array([float(i % 3), 0.1, 0.2, 0.6, 0.7], "float32")
        else:
            label = float(i % 3)
        header = mx.recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, mx.recordio.pack(header, _png_bytes(arr)))
    rec.close()
    return rec_path


def test_image_iter_rec(tmp_path):
    rec_path = _write_rec(tmp_path)
    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                            path_imgrec=rec_path)
    batch = next(it)
    assert batch.data[0].shape == (4, 3, 24, 24)
    assert batch.label[0].shape == (4,)
    batch2 = next(it)
    assert batch2.pad == 2    # 6 samples, batch 4
    it.reset()
    assert next(it).data[0].shape == (4, 3, 24, 24)


def test_image_det_iter(tmp_path):
    rec_path = _write_rec(tmp_path, det=True)
    it = mx.image.ImageDetIter(batch_size=3, data_shape=(3, 32, 32),
                               path_imgrec=rec_path, rand_mirror=False)
    batch = next(it)
    assert batch.data[0].shape == (3, 3, 32, 32)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (3, 1, 5)
    np.testing.assert_allclose(lab[0, 0, 1:], [0.1, 0.2, 0.6, 0.7],
                               rtol=1e-5)
    # provide_* feeds Module/SSD directly
    assert it.provide_data[0].shape == (3, 3, 32, 32)
    assert it.provide_label[0].shape == (3, 1, 5)


def test_det_flip_boxes():
    arr = _rand_img(20, 20)
    label = np.array([[1, 0.1, 0.2, 0.4, 0.6],
                      [-1, -1, -1, -1, -1]], "float32")
    img2, lab2 = img_mod.DetHorizontalFlipAug(1.0)(
        mx.nd.array(arr, dtype="uint8"), label)
    np.testing.assert_allclose(lab2[0], [1, 0.6, 0.2, 0.9, 0.6], rtol=1e-5)
    np.testing.assert_allclose(lab2[1], -1)
    np.testing.assert_array_equal(img2.asnumpy(), arr[:, ::-1])


def test_det_random_crop_keeps_box():
    arr = _rand_img(40, 40, seed=3)
    label = np.array([[0, 0.3, 0.3, 0.7, 0.7]], "float32")
    aug = img_mod.DetRandomCropAug(min_object_covered=0.5,
                                   area_range=(0.5, 0.9))
    img2, lab2 = aug(mx.nd.array(arr, dtype="uint8"), label)
    if (lab2[:, 0] >= 0).any():
        b = lab2[lab2[:, 0] >= 0][0, 1:]
        assert (b >= 0).all() and (b <= 1).all()
        assert b[2] > b[0] and b[3] > b[1]


def test_imglist_iter(tmp_path):
    from PIL import Image
    files = []
    for i in range(4):
        p = str(tmp_path / f"img{i}.png")
        Image.fromarray(_rand_img(seed=10 + i)).save(p)
        files.append((i % 2, f"img{i}.png"))
    it = mx.image.ImageIter(batch_size=2, data_shape=(3, 16, 16),
                            imglist=files, path_root=str(tmp_path))
    batch = next(it)
    assert batch.data[0].shape == (2, 3, 16, 16)
    np.testing.assert_allclose(batch.label[0].asnumpy(), [0, 1])


def test_round3_augmenters():
    """Hue/Lighting/RandomGray/RandomOrder/Sequential/RandomSizedCrop +
    CreateAugmenter(rand_resize/pca_noise/rand_gray) wiring."""
    from tpu_mx import image as img, nd
    rng = np.random.RandomState(0)
    src = nd.array((rng.rand(32, 48, 3) * 255).astype(np.float32))

    out, (x0, y0, w, h) = img.random_size_crop(src, (20, 20), (0.3, 0.9),
                                               (0.8, 1.25))
    assert out.shape == (20, 20, 3)
    assert 0 <= x0 and x0 + w <= 48 and 0 <= y0 and y0 + h <= 32

    hue = img.HueJitterAug(0.3)(src)
    assert hue.shape == src.shape
    assert not np.allclose(hue.asnumpy(), src.asnumpy())

    light = img.LightingAug(0.1, np.ones(3, np.float32),
                            np.eye(3, dtype=np.float32))(src)
    assert light.shape == src.shape

    gray = img.RandomGrayAug(1.0)(src).asnumpy()
    # all channels equal after gray
    np.testing.assert_allclose(gray[..., 0], gray[..., 1], rtol=1e-5)

    seq = img.SequentialAug([img.CastAug(), img.HorizontalFlipAug(0.0)])
    assert seq(src).shape == src.shape
    order = img.RandomOrderAug([img.BrightnessJitterAug(0.1)])
    assert order(src).shape == src.shape

    augs = img.CreateAugmenter((3, 20, 20), rand_crop=True, rand_resize=True,
                               rand_mirror=True, pca_noise=0.05,
                               rand_gray=0.2, mean=True, std=True)
    names = [type(a).__name__ for a in augs]
    assert "RandomSizedCropAug" in names and "LightingAug" in names
    assert "RandomGrayAug" in names
    x = src
    for a in augs:
        x = a(x)
    assert x.shape == (20, 20, 3)


def test_vision_transforms_hue_and_colorjitter():
    from tpu_mx.gluon.data.vision import transforms as T
    x = (np.random.RandomState(3).rand(12, 12, 3) * 255).astype(np.uint8)
    h = T.RandomHue(0.4).forward(x)
    assert h.shape == x.shape
    out = T.RandomColorJitter(0.2, 0.2, 0.2, 0.2).forward(x)
    assert out.shape == x.shape and np.isfinite(out).all()
    # Compose integration with the rest of the pipeline
    pipe = T.Compose([T.RandomColorJitter(hue=0.1), T.ToTensor()])
    y = pipe(x)
    assert y.shape == (3, 12, 12)


def test_crop_preserves_float_dtype_and_composite_dumps():
    from tpu_mx import image as img, nd
    x = nd.array(np.random.RandomState(0).rand(16, 16, 3)
                 .astype(np.float32))  # float pixels in [0,1]
    out = img.fixed_crop(x, 2, 2, 8, 8, size=(6, 6))
    a = out.asnumpy()
    assert a.dtype != np.uint8 and 0.0 < a.mean() < 1.0  # not truncated
    c, _ = img.random_size_crop(x, (6, 6), (0.3, 0.9), (0.9, 1.1))
    assert 0.0 < c.asnumpy().mean() < 1.0
    d = img.SequentialAug([img.CastAug(), img.HorizontalFlipAug(0.5)]).dumps()
    assert d[0] == "SequentialAug" and len(d[1]) == 2
    augs = img.CreateAugmenter((3, 8, 8), hue=0.1)
    assert any(type(a).__name__ == "HueJitterAug" for a in augs)
