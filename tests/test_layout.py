"""Channels-last (NHWC) layout support — the TPU-preferred image path.

Checks that a model built under `tpu_mx.layout.default_layout("NHWC")`
computes the same function as the default NCHW build (weights permuted
accordingly), for conv/pool/BN/deconv, and that a full model-zoo net trains
channels-last.
"""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.gluon import nn
from tpu_mx.layout import default_layout

pytestmark = pytest.mark.slow  # full-model NHWC train smokes (~3 min together)


def _to_nhwc(x):
    return np.transpose(x, (0, 2, 3, 1))


def test_conv2d_nhwc_matches_nchw():
    x = np.random.RandomState(0).rand(2, 5, 9, 9).astype(np.float32)
    conv = nn.Conv2D(7, kernel_size=3, strides=2, padding=1, in_channels=5)
    conv.initialize()
    y_ref = conv(nd.array(x)).asnumpy()

    with default_layout("NHWC"):
        conv2 = nn.Conv2D(7, kernel_size=3, strides=2, padding=1,
                          in_channels=5)
    conv2.initialize()
    # OIHW -> OHWI
    conv2.weight.set_data(nd.array(
        np.transpose(conv.weight.data().asnumpy(), (0, 2, 3, 1))))
    conv2.bias.set_data(conv.bias.data())
    y = conv2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(np.transpose(y, (0, 3, 1, 2)), y_ref,
                               rtol=1e-5, atol=1e-5)


def test_grouped_conv_nhwc():
    x = np.random.RandomState(1).rand(2, 6, 8, 8).astype(np.float32)
    conv = nn.Conv2D(6, kernel_size=3, padding=1, groups=6, in_channels=6,
                     use_bias=False)
    conv.initialize()
    y_ref = conv(nd.array(x)).asnumpy()
    with default_layout("NHWC"):
        conv2 = nn.Conv2D(6, kernel_size=3, padding=1, groups=6,
                          in_channels=6, use_bias=False)
    conv2.initialize()
    conv2.weight.set_data(nd.array(
        np.transpose(conv.weight.data().asnumpy(), (0, 2, 3, 1))))
    y = conv2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(np.transpose(y, (0, 3, 1, 2)), y_ref,
                               rtol=1e-5, atol=1e-5)


def test_conv2d_transpose_nhwc():
    x = np.random.RandomState(2).rand(2, 4, 5, 5).astype(np.float32)
    deconv = nn.Conv2DTranspose(3, kernel_size=3, strides=2, padding=1,
                                output_padding=1, in_channels=4)
    deconv.initialize()
    y_ref = deconv(nd.array(x)).asnumpy()
    with default_layout("NHWC"):
        d2 = nn.Conv2DTranspose(3, kernel_size=3, strides=2, padding=1,
                                output_padding=1, in_channels=4)
    d2.initialize()
    # IOHW -> IHWO
    d2.weight.set_data(nd.array(
        np.transpose(deconv.weight.data().asnumpy(), (0, 2, 3, 1))))
    d2.bias.set_data(deconv.bias.data())
    y = d2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(np.transpose(y, (0, 3, 1, 2)), y_ref,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pool_cls,kw", [
    (nn.MaxPool2D, dict(pool_size=3, strides=2, padding=1)),
    (nn.AvgPool2D, dict(pool_size=2, strides=2)),
    (nn.AvgPool2D, dict(pool_size=3, strides=2, padding=1, ceil_mode=True)),
    (nn.GlobalAvgPool2D, {}),
    (nn.GlobalMaxPool2D, {}),
])
def test_pool_nhwc(pool_cls, kw):
    x = np.random.RandomState(3).rand(2, 4, 9, 9).astype(np.float32)
    y_ref = pool_cls(**kw)(nd.array(x)).asnumpy()
    with default_layout("NHWC"):
        pool = pool_cls(**kw)
    y = pool(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(np.transpose(y, (0, 3, 1, 2)), y_ref,
                               rtol=1e-6, atol=1e-6)


def test_batchnorm_axis_follows_layout():
    bn_def = nn.BatchNorm()
    assert bn_def._axis == 1
    with default_layout("NHWC"):
        bn = nn.BatchNorm()
    assert bn._axis == -1
    x = np.random.RandomState(4).rand(2, 3, 5, 5).astype(np.float32)
    bn_def.initialize()
    bn.initialize()
    y_ref = bn_def(nd.array(x)).asnumpy()
    y = bn(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(np.transpose(y, (0, 3, 1, 2)), y_ref,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("factory,size", [
    ("squeezenet1_1", 64),
    ("densenet121", 32),
])
def test_concat_models_nhwc(factory, size):
    """Models with channel-axis concat (Fire / dense blocks) must follow the
    layout: same logits channels-last as channels-first."""
    from tpu_mx.gluon.model_zoo import vision
    net_ref = getattr(vision, factory)(classes=7)
    net_ref.initialize(init="xavier")
    x = np.random.RandomState(6).rand(1, 3, size, size).astype(np.float32)
    y_ref = net_ref(nd.array(x)).asnumpy()
    with default_layout("NHWC"):
        net = getattr(vision, factory)(classes=7)
    net.initialize(init="xavier")
    for p_src, p_dst in zip(net_ref.collect_params().values(),
                            net.collect_params().values()):
        a = p_src.data().asnumpy()
        if a.ndim == 4:
            a = np.transpose(a, (0, 2, 3, 1))
        p_dst.set_data(nd.array(a))
    y = net(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(y, y_ref, rtol=2e-3, atol=2e-3)


def test_default_layout_validates():
    with pytest.raises(ValueError):
        with default_layout("NHWc"):
            pass
    with default_layout("channels_last"):
        from tpu_mx.layout import bn_axis
        assert bn_axis() == -1


def test_resnet_nhwc_forward_and_train():
    """Full model-zoo net channels-last: same logits as NCHW with permuted
    weights, and a train step runs."""
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx import gluon
    from tpu_mx.parallel import CompiledTrainStep

    net_ref = vision.resnet18_v1(classes=10)
    net_ref.initialize(init="xavier")
    x = np.random.RandomState(5).rand(2, 3, 32, 32).astype(np.float32)
    y_ref = net_ref(nd.array(x)).asnumpy()

    with default_layout("NHWC"):
        net = vision.resnet18_v1(classes=10)
    net.initialize(init="xavier")
    # copy weights in construction order, permuting conv kernels OIHW->OHWI
    # (names differ between the two nets — global name counters)
    for p_src, p_dst in zip(net_ref.collect_params().values(),
                            net.collect_params().values()):
        a = p_src.data().asnumpy()
        if a.ndim == 4:
            a = np.transpose(a, (0, 2, 3, 1))
        p_dst.set_data(nd.array(a))
    y = net(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(y, y_ref, rtol=2e-4, atol=2e-4)

    # one compiled train step channels-last
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.01)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)
    label = nd.array(np.array([1, 2], dtype=np.float32))
    l1 = float(np.asarray(step.step(nd.array(_to_nhwc(x)), label)._data).ravel()[0])
    assert np.isfinite(l1)


def test_space_to_depth_op_roundtrip():
    """REF:src/operator/tensor/matrix_op.cc space_to_depth/depth_to_space:
    NCHW (N,C,H,W) -> (N, b*b*C, H/b, W/b), block offsets leading."""
    from tpu_mx.ndarray import ops
    x = nd.array(np.arange(2 * 3 * 8 * 8).reshape(2, 3, 8, 8)
                 .astype(np.float32))
    y = ops.space_to_depth(x, 4)
    assert y.shape == (2, 48, 2, 2)
    np.testing.assert_allclose(ops.depth_to_space(y, 4).asnumpy(),
                               x.asnumpy())
    # spot-check the rearrangement: out[n, (bh*b + bw)*C + c, i, j]
    # == in[n, c, i*b + bh, j*b + bw]
    xa, ya = x.asnumpy(), y.asnumpy()
    assert ya[1, (2 * 4 + 3) * 3 + 1, 0, 1] == xa[1, 1, 2, 7]


@pytest.mark.parametrize("layout", ["NHWC", "NCHW"])
def test_s2d_stem_forward_and_train(layout):
    """The TPU stem variant (4x4 space-to-depth + 3x3 conv, VERDICT r2
    ask#1) must produce the same feature-map geometry as the classic stem
    and train end-to-end in either layout."""
    from tpu_mx import gluon
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.parallel import CompiledTrainStep

    shape = (2, 64, 64, 3) if layout == "NHWC" else (2, 3, 64, 64)
    with default_layout(layout):
        net = vision.resnet18_v1(classes=10, stem="s2d")
        classic = vision.resnet18_v1(classes=10)
    net.initialize(init="xavier")
    classic.initialize(init="xavier")
    x = nd.array(np.random.RandomState(0).rand(*shape).astype(np.float32))
    out = net(x)
    assert out.shape == classic(x).shape == (2, 10)
    # stem output geometry matches classic (56x56-equivalent at 1/4 stride)
    s2d_feat = net.features._children["0"](x)
    classic_feat = x
    for i in range(4):  # conv, bn, relu, maxpool
        classic_feat = classic.features._children[str(i)](classic_feat)
    assert s2d_feat.shape == classic_feat.shape

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)
    label = nd.array(np.array([1, 2], dtype=np.float32))
    losses = [float(np.asarray(step.step(x, label)._data).ravel()[0])
              for _ in range(8)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
