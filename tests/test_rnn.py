"""RNN layer/cell tests (model: REF:tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import autograd, nd
from tpu_mx.gluon import rnn
from tpu_mx.test_utils import assert_almost_equal


def test_lstm_shapes():
    layer = rnn.LSTM(16, num_layers=2)
    layer.initialize()
    x = nd.array(np.random.rand(5, 3, 8).astype(np.float32))
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(batch_size=3)
    out, st = layer(x, states)
    assert out.shape == (5, 3, 16)
    assert st[0].shape == (2, 3, 16) and st[1].shape == (2, 3, 16)


def test_lstm_ntc_layout():
    layer = rnn.LSTM(8, layout="NTC")
    layer.initialize()
    out = layer(nd.array(np.random.rand(3, 5, 4).astype(np.float32)))
    assert out.shape == (3, 5, 8)


def test_bidirectional():
    layer = rnn.GRU(8, bidirectional=True)
    layer.initialize()
    out = layer(nd.array(np.random.rand(5, 2, 4).astype(np.float32)))
    assert out.shape == (5, 2, 16)


def test_rnn_gradients_flow():
    layer = rnn.LSTM(8, num_layers=1)
    layer.initialize()
    x = nd.array(np.random.rand(4, 2, 4).astype(np.float32))
    with autograd.record():
        loss = (layer(x) ** 2).sum()
    loss.backward()
    for p in layer.collect_params().values():
        assert float(np.abs(p.grad.asnumpy()).sum()) > 0


def test_lstm_vs_manual_numpy():
    """Fused scan LSTM against a manual numpy step loop with the same params."""
    H, C = 3, 2
    layer = rnn.LSTM(H, input_size=C)
    layer.initialize()
    x_np = np.random.rand(4, 1, C).astype(np.float32)
    out = layer(nd.array(x_np)).asnumpy()

    params = {k.split("_", 1)[1] if False else k: v.data().asnumpy()
              for k, v in layer.collect_params().items()}
    wi = [v for k, v in params.items() if "i2h_weight" in k][0]
    wh = [v for k, v in params.items() if "h2h_weight" in k][0]
    bi = [v for k, v in params.items() if "i2h_bias" in k][0]
    bh = [v for k, v in params.items() if "h2h_bias" in k][0]

    def sigmoid(z):
        return 1 / (1 + np.exp(-z))

    h = np.zeros((1, H), np.float32)
    c = np.zeros((1, H), np.float32)
    outs = []
    for t in range(4):
        gates = x_np[t] @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sigmoid(f) * c + sigmoid(i) * np.tanh(g)
        h = sigmoid(o) * np.tanh(c)
        outs.append(h.copy())
    manual = np.stack(outs)
    assert_almost_equal(out, manual, rtol=1e-4, atol=1e-5)


def test_gru_vs_manual_numpy():
    H, C = 3, 2
    layer = rnn.GRU(H, input_size=C)
    layer.initialize()
    x_np = np.random.rand(3, 1, C).astype(np.float32)
    out = layer(nd.array(x_np)).asnumpy()

    params = {k: v.data().asnumpy()
              for k, v in layer.collect_params().items()}
    wi = [v for k, v in params.items() if "i2h_weight" in k][0]
    wh = [v for k, v in params.items() if "h2h_weight" in k][0]
    bi = [v for k, v in params.items() if "i2h_bias" in k][0]
    bh = [v for k, v in params.items() if "h2h_bias" in k][0]

    def sigmoid(z):
        return 1 / (1 + np.exp(-z))

    h = np.zeros((1, H), np.float32)
    outs = []
    for t in range(3):
        i_all = x_np[t] @ wi.T + bi
        h_all = h @ wh.T
        i_r, i_z, i_n = np.split(i_all, 3, -1)
        h_r, h_z, h_n = np.split(h_all + bh, 3, -1)
        r = sigmoid(i_r + h_r)
        z = sigmoid(i_z + h_z)
        n = np.tanh(i_n + r * (h @ wh[2*H:].T + bh[2*H:]))
        h = (1 - z) * n + z * h
        outs.append(h.copy())
    manual = np.stack(outs)
    assert_almost_equal(out, manual, rtol=1e-4, atol=1e-5)


def test_cells_and_unroll():
    cell = rnn.LSTMCell(6)
    cell.initialize()
    x = nd.array(np.random.rand(2, 5, 4).astype(np.float32))
    outs, states = cell.unroll(5, x, layout="NTC")
    assert outs.shape == (2, 5, 6)
    assert len(states) == 2

    stack = rnn.SequentialRNNCell()
    stack.add(rnn.GRUCell(4))
    stack.add(rnn.GRUCell(4))
    stack.initialize()
    out, st = stack(nd.ones((2, 3)), stack.begin_state(2))
    assert out.shape == (2, 4) and len(st) == 2


def test_lstm_lm_model():
    from tpu_mx.models import RNNModel
    lm = RNNModel(vocab_size=30, num_embed=8, num_hidden=8, num_layers=1)
    lm.initialize()
    x = nd.array(np.random.randint(0, 30, (6, 2)), dtype="int32")
    logits = lm(x)
    assert logits.shape == (6, 2, 30)
    # with explicit state (TBPTT pattern)
    st = lm.begin_state(batch_size=2)
    logits, st2 = lm(x, st)
    assert logits.shape == (6, 2, 30)


def test_unroll_valid_length_masks_and_freezes_states():
    """unroll(valid_length=...): outputs past each sequence's length are
    zeroed and final states freeze at step valid_length-1 (the reference's
    SequenceMask + SequenceLast contract)."""
    from tpu_mx.gluon import rnn as grnn
    cell = grnn.LSTMCell(5)
    cell.initialize()
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(2, 4, 3).astype(np.float32))  # (N, T, C)
    vl = np.array([4, 2], np.float32)
    outs, states = cell.unroll(4, x, layout="NTC", valid_length=vl)
    o = np.asarray(outs._data)
    assert (o[1, 2:] == 0).all() and (o[1, :2] != 0).any()
    assert (o[0] != 0).any(axis=-1).all()
    # row 1 states must equal an unroll truncated at T=2
    outs2, states2 = cell.unroll(2, nd.array(
        np.asarray(x._data)[:, :2]), layout="NTC")
    np.testing.assert_allclose(np.asarray(states[0]._data)[1],
                                np.asarray(states2[0]._data)[1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(states[1]._data)[1],
                                np.asarray(states2[1]._data)[1], rtol=1e-6)


def test_bidirectional_cell_unroll():
    """BidirectionalCell == forward-LSTM ++ reversed backward-LSTM
    (REF rnn_cell.py:BidirectionalCell)."""
    from tpu_mx.gluon import rnn as grnn
    l, r = grnn.LSTMCell(4), grnn.LSTMCell(4)
    bi = grnn.BidirectionalCell(l, r)
    for c in (l, r):
        pass
    bi.initialize()
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(2, 5, 3).astype(np.float32))
    outs, states = bi.unroll(5, x, layout="NTC")
    assert outs.shape == (2, 5, 8)
    assert len(states) == 4
    # manual composition matches
    lo, _ = l.unroll(5, x, layout="NTC", merge_outputs=False)
    xs_rev = nd.flip(x, axis=1)
    ro, _ = r.unroll(5, xs_rev, layout="NTC", merge_outputs=False)
    ro = list(reversed(list(ro)))
    for t in range(5):
        np.testing.assert_allclose(
            np.asarray(outs._data)[:, t, :4], np.asarray(lo[t]._data),
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(outs._data)[:, t, 4:], np.asarray(ro[t]._data),
            rtol=1e-5)
    with pytest.raises(mx.MXNetError, match="unroll"):
        bi(x, states)


def test_cast_bf16_recurrence_stays_bf16():
    """cast('bfloat16') must reach the implicit zero states: an f32
    state would promote every scan step back to f32 (the r5 dtype audit
    found the 'bf16' PTB leg recurring in f32 exactly this way)."""
    lstm = rnn.LSTM(8, 1, input_size=4)
    lstm.initialize()
    x = nd.array(np.random.RandomState(0).rand(3, 2, 4)
                 .astype(np.float32))
    lstm(x)  # finalize
    lstm.cast("bfloat16")
    xb = nd.cast(x, "bfloat16")
    out = lstm(xb)
    assert str(out.dtype) == "bfloat16"
    # explicit begin_state follows the cast too
    states = lstm.begin_state(batch_size=2)
    assert all(str(s.dtype) == "bfloat16" for s in states)
    out2, new_states = lstm(xb, states)
    assert str(out2.dtype) == "bfloat16"
    assert all(str(s.dtype) == "bfloat16" for s in new_states)


def test_mixed_dtype_input_promotes_not_crashes():
    """f32 net fed bf16 input (or the reverse) must run with promoted-f32
    recurrence — the scan carry has to match what the dots produce
    (review r5: an inputs.dtype-only rule crashed this case)."""
    lstm = rnn.LSTM(8, 1, input_size=4)
    lstm.initialize()
    x = nd.array(np.random.RandomState(0).rand(3, 2, 4)
                 .astype(np.float32))
    lstm(x)
    out = lstm(nd.cast(x, "bfloat16"))     # f32 net, bf16 input
    assert str(out.dtype) == "float32"
    lstm.cast("bfloat16")
    out2 = lstm(x)                         # bf16 net, f32 input
    assert str(out2.dtype) == "float32"


def test_explicit_states_promote_after_cast():
    """Caller-provided states in a different dtype than the net/input
    must be promoted, not crash the scan carry (review r5: f32 states
    kept from before a cast, or begin_state dtype vs f32 input)."""
    lstm = rnn.LSTM(8, 1, input_size=4)
    lstm.initialize()
    x = nd.array(np.random.RandomState(0).rand(3, 2, 4)
                 .astype(np.float32))
    states_f32 = lstm.begin_state(batch_size=2)
    lstm(x)
    lstm.cast("bfloat16")
    # bf16 net + f32 input + bf16 begin_state -> promoted f32 recurrence
    out, ns = lstm(x, lstm.begin_state(batch_size=2))
    assert str(out.dtype) == "float32"
    # bf16 net + bf16 input + stale f32 states -> promoted f32 (no crash)
    out2, _ = lstm(nd.cast(x, "bfloat16"), states_f32)
    assert str(out2.dtype) == "float32"
    # fully bf16 call stays bf16
    out3, _ = lstm(nd.cast(x, "bfloat16"), lstm.begin_state(batch_size=2))
    assert str(out3.dtype) == "bfloat16"
