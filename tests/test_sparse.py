"""Sparse NDArray tests (reference analog:
tests/python/unittest/test_sparse_ndarray.py, test_sparse_operator.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.ndarray import sparse


def dense_csr_pair(m=6, n=5, density=0.4, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.rand(m, n).astype(np.float32)
    dense[rng.rand(m, n) > density] = 0.0
    return dense, sparse.csr_matrix(dense)


def test_csr_roundtrip():
    dense, csr = dense_csr_pair()
    assert csr.stype == "csr"
    assert csr.shape == dense.shape
    np.testing.assert_array_equal(csr.asnumpy(), dense)
    # 3-tuple construction matches scipy-style layout
    csr2 = sparse.csr_matrix((csr.data, csr.indices, csr.indptr),
                             shape=dense.shape)
    np.testing.assert_array_equal(csr2.asnumpy(), dense)


def test_csr_nnz_and_slice():
    dense, csr = dense_csr_pair()
    assert csr.nnz == int((dense != 0).sum())
    sl = csr.slice(1, 4)
    np.testing.assert_array_equal(sl.asnumpy(), dense[1:4])


def test_row_sparse_roundtrip():
    dense = np.zeros((8, 3), np.float32)
    dense[[1, 4, 6]] = np.random.RandomState(0).rand(3, 3)
    rsp = sparse.row_sparse_array(dense)
    assert rsp.stype == "row_sparse"
    assert list(rsp.indices.asnumpy()) == [1, 4, 6]
    np.testing.assert_array_equal(rsp.asnumpy(), dense)
    rsp2 = sparse.row_sparse_array((rsp.data, rsp.indices), shape=(8, 3))
    np.testing.assert_array_equal(rsp2.asnumpy(), dense)


def test_dot_csr_dense():
    dense, csr = dense_csr_pair()
    rhs = nd.array(np.random.RandomState(1).rand(5, 4).astype(np.float32))
    out = sparse.dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_dot_csr_transpose():
    dense, csr = dense_csr_pair()
    rhs = nd.array(np.random.RandomState(2).rand(6, 4).astype(np.float32))
    out = sparse.dot(csr, rhs, transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), dense.T @ rhs.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_tostype_and_cast_storage():
    dense, _ = dense_csr_pair()
    a = nd.array(dense)
    assert a.stype == "default"
    csr = a.tostype("csr")
    assert csr.stype == "csr"
    back = csr.tostype("default")
    np.testing.assert_array_equal(back.asnumpy(), dense)
    rsp = sparse.cast_storage(a, "row_sparse")
    np.testing.assert_array_equal(rsp.asnumpy(), dense)


def test_retain():
    dense = np.zeros((10, 2), np.float32)
    dense[[2, 5, 7]] = 1.0
    rsp = sparse.row_sparse_array(dense)
    kept = sparse.retain(rsp, nd.array(np.array([5, 7], np.int32)))
    expect = np.zeros_like(dense)
    expect[[5, 7]] = 1.0
    np.testing.assert_array_equal(kept.asnumpy(), expect)


def test_rowsparse_add_accumulates_duplicates():
    a = sparse.row_sparse_array((np.ones((2, 3), np.float32),
                                 np.array([1, 2])), shape=(5, 3))
    b = sparse.row_sparse_array((np.ones((2, 3), np.float32),
                                 np.array([2, 4])), shape=(5, 3))
    s = sparse.elemwise_add(a, b)
    expect = np.zeros((5, 3), np.float32)
    expect[[1, 4]] = 1.0
    expect[2] = 2.0
    np.testing.assert_array_equal(s.asnumpy(), expect)


def test_sparse_zeros():
    z = sparse.zeros("csr", (4, 6))
    assert z.stype == "csr" and z.nnz == 0
    np.testing.assert_array_equal(z.asnumpy(), np.zeros((4, 6)))
    zr = sparse.zeros("row_sparse", (4, 6))
    np.testing.assert_array_equal(zr.asnumpy(), np.zeros((4, 6)))


def test_dense_ops_reject_sparse():
    _, csr = dense_csr_pair()
    with pytest.raises(Exception):
        nd.dot(csr, csr)  # dense namespace must not silently densify


def test_libsvm_iter(tmp_path):
    p = tmp_path / "data.libsvm"
    p.write_text("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0 4:0.5\n0 0:2.0\n")
    from tpu_mx.io import LibSVMIter
    it = LibSVMIter(data_libsvm=str(p), data_shape=(5,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    b0 = batches[0]
    assert b0.data[0].stype == "csr"
    expect = np.zeros((2, 5), np.float32)
    expect[0, 0], expect[0, 3] = 1.5, 2.0
    expect[1, 1] = 1.0
    np.testing.assert_array_equal(b0.data[0].asnumpy(), expect)
    np.testing.assert_array_equal(b0.label[0].asnumpy(), [1.0, 0.0])
