"""Autograd tape tests (model: REF:tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import autograd, nd
from tpu_mx.test_utils import assert_almost_equal, check_numeric_gradient


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_and_branching():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 3
        b = x * x
        y = a + b  # dy/dx = 3 + 2x = 7
    y.backward()
    assert_almost_equal(x.grad, np.array([7.0]))


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(out_grad=nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([20.0, 200.0]))


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert_almost_equal(x.grad, np.array([6.0]))


def test_grad_req_write_overwrites():
    x = nd.array([1.0])
    x.attach_grad()
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert_almost_equal(x.grad, np.array([2.0]))


def test_not_recording_outside():
    x = nd.array([1.0])
    x.attach_grad()
    y = x * 2  # not recorded
    with pytest.raises(Exception):
        y.backward()
        assert False  # may be no-op; ensure grad unchanged instead
    assert not autograd.is_recording()


def test_pause():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 5  # not recorded
        w = y * 2
    w.backward()
    assert_almost_equal(x.grad, np.array([12.0]))


def test_train_predict_mode():
    assert not autograd.is_training()
    with autograd.record(train_mode=True):
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x  # d/dx = y = 4
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


def test_blockgrad_op():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * x) * x
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


def test_autograd_grad_function():
    x = nd.array([2.0])
    x.attach_grad()  # variables must be marked before recording (reference semantics)
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad([y], [x])
    assert_almost_equal(g, np.array([12.0]))


def test_multi_input_grads():
    a = nd.array([[1.0, 2.0]])
    b = nd.array([[3.0], [4.0]])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = nd.dot(a, b).sum()
    y.backward()
    assert_almost_equal(a.grad, b.asnumpy().T)
    assert_almost_equal(b.grad, a.asnumpy().T)


def test_numeric_gradient_elemwise():
    check_numeric_gradient(lambda xs: nd.sigmoid(xs[0]) * xs[1],
                           [np.random.rand(2, 3), np.random.rand(2, 3)])


def test_numeric_gradient_softmax():
    check_numeric_gradient(
        lambda xs: nd.log_softmax(xs[0]).sum(),
        [np.random.rand(3, 4)])


def test_custom_function():
    class MySigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.5, -0.5])
    x.attach_grad()
    f = MySigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, sig * (1 - sig), rtol=1e-4)


def test_mark_variables():
    x = nd.array([1.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 5
    y.backward()
    assert_almost_equal(x.grad, np.array([5.0]))


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad(grad_req="add")
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    y.backward()
    assert_almost_equal(x.grad, np.array([8.0]))


def test_grad_through_conv():
    check_numeric_gradient(
        lambda xs: nd.Convolution(xs[0], xs[1], kernel=(2, 2), num_filter=2,
                                  no_bias=True),
        [np.random.rand(1, 1, 4, 4), np.random.rand(2, 1, 2, 2)],
        rtol=2e-2, atol=2e-3)
