"""Self-healing supervisor (tpu_mx/supervisor.py) — every recovery path
is PROVOKED via chaos injection, not assumed (ISSUE 4).

Covers: the hung-step watchdog (incl. recompile-aware grace and the
deliberately hung elastic.barrier), the numeric sentinel (skip budget,
spike + grad-norm detection), failure classification, rollback to the
last *good* epoch under injected divergence (in-process AND subprocess),
transient restarts with resume, graceful degradation, and the
module.fit(supervised=) integration."""
import contextlib
import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, nd, resume, supervisor, \
    telemetry
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dense(value=1.0):
    net = nn.Dense(3, in_units=4)
    net.initialize()
    net.weight.set_data(nd.full((3, 4), float(value)))
    return net


def _sup(**kw):
    kw.setdefault("backoff", 0.01)
    kw.setdefault("seed", 0)
    return supervisor.Supervisor(**kw)


# ---------------------------------------------------------------------------
# run_with_deadline: the watchdog primitive
# ---------------------------------------------------------------------------
def test_watchdog_passes_value_and_exceptions_through():
    assert supervisor.run_with_deadline(lambda: 42, 5.0) == 42
    assert supervisor.run_with_deadline(lambda: 42, None) == 42  # off
    with pytest.raises(ZeroDivisionError):
        supervisor.run_with_deadline(lambda: 1 // 0, 5.0)


def test_watchdog_converts_hang_to_worker_failure():
    before = telemetry.counter("supervisor.watchdog_fires").value
    with pytest.raises(supervisor.WatchdogTimeout, match="hung past"):
        supervisor.run_with_deadline(lambda: time.sleep(5.0), 0.1,
                                     name="hung-step")
    # WatchdogTimeout IS a WorkerFailure (transient for classification)
    assert issubclass(supervisor.WatchdogTimeout, elastic.WorkerFailure)
    assert telemetry.counter("supervisor.watchdog_fires").value == before + 1


def test_watchdog_recompile_grace_extends_deadline():
    """A step past its deadline with the grace signal moved (= a jit build
    started) gets ONE grace extension instead of being killed."""
    sig = [0]

    def compiling_step():
        sig[0] += 1          # "a recompile started"
        time.sleep(0.3)      # ... and outlives the base deadline
        return "compiled"

    assert supervisor.run_with_deadline(
        compiling_step, 0.05, grace=5.0,
        grace_signal=lambda: sig[0]) == "compiled"

    # without a moved signal the same overrun still fires
    with pytest.raises(supervisor.WatchdogTimeout):
        supervisor.run_with_deadline(lambda: time.sleep(0.3), 0.05,
                                     grace=5.0, grace_signal=lambda: 0)


def test_watchdog_against_deliberately_hung_barrier(monkeypatch):
    """The satellite proof: a hung collective inside elastic.barrier (dead
    peer — sync_global_devices never returns) becomes a clean
    WorkerFailure within the timeout, not an eternal hang."""
    import jax
    from jax.experimental import multihost_utils
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda tag: threading.Event().wait())  # hangs forever
    t0 = time.time()
    with pytest.raises(elastic.WorkerFailure, match="timed out"):
        elastic.barrier("test-hung", timeout=0.2)
    assert time.time() - t0 < 5.0  # returned promptly, not after "forever"


# ---------------------------------------------------------------------------
# numeric sentinel
# ---------------------------------------------------------------------------
def test_sentinel_skip_budget_then_divergence():
    s = supervisor.NumericSentinel(skip_limit=2)
    assert s.observe(1.0) == "ok"
    assert s.observe(float("nan")) == "skip"
    assert s.observe(float("inf")) == "skip"
    assert s.observe(float("nan")) == "diverge"
    # a good batch in between resets the consecutive-bad streak
    s2 = supervisor.NumericSentinel(skip_limit=1)
    assert s2.observe(float("nan")) == "skip"
    assert s2.observe(1.0) == "ok"
    assert s2.observe(float("nan")) == "skip"
    # skip_limit=0: first bad batch escalates immediately
    s3 = supervisor.NumericSentinel(skip_limit=0)
    assert s3.observe(float("nan")) == "diverge"


def test_sentinel_spike_and_grad_norm():
    s = supervisor.NumericSentinel(skip_limit=0, spike_factor=10.0)
    for _ in range(6):
        assert s.observe(2.0) == "ok"
    assert s.observe(2.5) == "ok"          # ordinary wobble
    assert s.observe(50.0) == "diverge"    # 25× the median: a spike
    g = supervisor.NumericSentinel(skip_limit=0, max_grad_norm=100.0)
    assert g.observe(1.0, grad_norm=5.0) == "ok"
    assert g.observe(1.0, grad_norm=500.0) == "diverge"
    assert g.observe(1.0, grad_norm=float("nan")) == "diverge"


def test_classification_table():
    """The failure-classification table from docs/robustness.md."""
    c = supervisor.classify
    assert c(OSError("nfs hiccup")) == "transient"
    assert c(elastic.WorkerFailure("dead peer")) == "transient"
    assert c(supervisor.WatchdogTimeout("hung")) == "transient"
    assert c(chaos.ChaosCrash("simulated kill")) == "transient"
    assert c(supervisor.NumericDivergence("nan")) == "numeric"
    assert c(TypeError("a programming error")) == "fatal"
    assert c(mx.base.MXNetError("bad usage")) == "fatal"
    assert c(KeyboardInterrupt()) == "fatal"


# ---------------------------------------------------------------------------
# the supervised loop: restart / rollback / degrade
# ---------------------------------------------------------------------------
def test_transient_failure_restarts_and_resumes(tmp_path):
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    flaky = {"armed": True}
    sup = _sup(save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
               restore_fn=lambda: elastic.auto_resume(prefix, net=net))

    def epoch_fn(epoch):
        if epoch == 2 and flaky["armed"]:
            flaky["armed"] = False
            raise OSError("transient filesystem fault")
        for i in range(2):
            sup.step(lambda: 0.5 + epoch)

    res = sup.run(epoch_fn, begin_epoch=0, num_epoch=4)
    assert res.ok and res.restarts == 1
    assert elastic.latest_checkpoint(prefix)[0] == 3
    assert math.isfinite(res.final_loss)


def test_chaos_hang_step_fires_watchdog_then_recovers(tmp_path):
    """hang_step chaos stalls one step past the deadline; the watchdog
    converts it to a restart and the retried (disarmed) step succeeds."""
    prefix = str(tmp_path / "ck")
    net = _dense(2.0)
    sup = _sup(save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
               restore_fn=lambda: elastic.auto_resume(prefix, net=net),
               deadline=0.2, compile_grace=0.0)
    with chaos.enable(hang_step=3, hang_seconds=30.0) as cfg:
        res = sup.run(lambda epoch: [sup.step(lambda: 1.0)
                                     for _ in range(2)],
                      begin_epoch=0, num_epoch=3)
        assert cfg.hangs == 1
    assert res.ok and res.watchdog_fires == 1 and res.restarts == 1
    assert elastic.latest_checkpoint(prefix)[0] == 2


def test_divergence_rolls_back_to_last_good_epoch(tmp_path):
    """NaN streak past the skip budget → rollback lands on the last GOOD
    epoch's weights, and re-enters AT the poisoned epoch (which was never
    saved)."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    resumes = []

    def save_fn(epoch):
        # stamp the weights with the epoch so the restore is provable
        net.weight.set_data(nd.full((3, 4), 10.0 + epoch))
        elastic.save_checkpoint(prefix, epoch, net=net)

    def restore_fn():
        e = elastic.auto_resume(prefix, net=net)
        resumes.append(e)
        return e

    sup = _sup(save_fn=save_fn, restore_fn=restore_fn, skip_limit=1)
    poison = {"armed": True}

    def epoch_fn(epoch):
        if epoch == 2 and poison["armed"]:
            poison["armed"] = False
            with chaos.enable(nan_after=1, nan_streak=2):
                for _ in range(3):
                    sup.step(lambda: 1.0)
        else:
            for _ in range(3):
                sup.step(lambda: 1.0)

    res = sup.run(epoch_fn, begin_epoch=0, num_epoch=4)
    assert res.ok
    assert res.rollbacks == 1 and res.batches_skipped == 1
    # initial resume found nothing (0); the rollback resumed FROM epoch 2
    # (last good = epoch 1 — not the poisoned epoch 2, which never saved)
    assert resumes == [0, 2]
    assert elastic.latest_checkpoint(prefix)[0] == 3
    # weights on disk for epoch 1 are the last-good stamp
    net2 = nn.Dense(3, in_units=4)
    for epoch, params in elastic.candidate_checkpoints(prefix):
        if epoch == 1:
            net2.load_parameters(params)
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 11.0)


def test_fatal_error_propagates_immediately(tmp_path):
    sup = _sup(max_restarts=5)
    calls = []

    def epoch_fn(epoch):
        calls.append(epoch)
        raise TypeError("a programming error — must NOT be retried")

    with pytest.raises(TypeError):
        sup.run(epoch_fn, num_epoch=3)
    assert calls == [0] and sup.restarts == 0


def test_degradation_after_exhausted_restarts(tmp_path):
    """max-restarts exhausted → clean durable final save + structured
    degraded status + the degraded-mode gauge, NOT an unbounded loop."""
    prefix = str(tmp_path / "ck")
    net = _dense(7.0)
    hooked = []
    sup = _sup(save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
               restore_fn=lambda: elastic.auto_resume(prefix, net=net),
               max_restarts=2,
               on_degraded=lambda s, err: hooked.append(type(err).__name__))

    def epoch_fn(epoch):
        raise OSError("persistent fault")

    res = sup.run(epoch_fn, num_epoch=5)
    assert res.status == "degraded" and not res.ok
    assert "restarts exhausted" in res.reason
    assert res.restarts == 3  # 2 allowed + the one that broke the budget
    assert hooked == ["OSError"]
    # the degraded final save is durable and resumable
    epoch, _ = elastic.latest_checkpoint(prefix)
    assert epoch is not None
    assert ckpt.verify_checkpoint(prefix, epoch)[0] == "verified"
    assert telemetry.get("supervisor.degraded").value == 1


def test_rollback_budget_degrades(tmp_path):
    sup = _sup(restore_fn=lambda: 0, skip_limit=0, max_rollbacks=1)

    def epoch_fn(epoch):
        with chaos.enable(nan_after=1, nan_streak=1):
            sup.step(lambda: 1.0)

    res = sup.run(epoch_fn, num_epoch=3)
    assert res.status == "degraded"
    assert "rollbacks exhausted" in res.reason
    assert res.rollbacks == 2


def test_supervised_step_observable_forms():
    """Scalars, NDArrays, (loss, grad_norm) tuples and None all feed the
    sentinel correctly."""
    sup = _sup(skip_limit=0, max_grad_norm=10.0)
    sup._epoch = 0
    assert sup.step(lambda: 1.25) == 1.25
    out = sup.step(lambda: nd.array([1.0, 2.0]))
    np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])
    assert sup.step(lambda: (0.5, 3.0)) == (0.5, 3.0)
    assert sup.step(lambda: None) is None          # no numeric check
    assert sup.step(lambda: "opaque") == "opaque"  # non-numeric: no check
    with pytest.raises(supervisor.NumericDivergence):
        sup.step(lambda: (0.5, 99.0))  # grad norm over budget


# ---------------------------------------------------------------------------
# module.fit(supervised=) integration
# ---------------------------------------------------------------------------
def _toy_iter(batch_size=4, n=16):
    X = np.random.RandomState(0).rand(n, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    return mx.io.NDArrayIter(X, Y, batch_size=batch_size,
                             label_name="softmax_label")


def _toy_symbol():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=2, name="fc1")
    return mx.sym.SoftmaxOutput(fc, mx.sym.Variable("softmax_label"),
                                name="softmax")


def test_module_fit_supervised_checkpoints_and_completes(tmp_path):
    prefix = str(tmp_path / "fit")
    mod = mx.module.Module(_toy_symbol(), context=[mx.cpu()])
    res = mod.fit(_toy_iter(), num_epoch=3,
                  optimizer_params=(("learning_rate", 0.05),),
                  supervised=supervisor.Supervise(prefix=prefix, seed=0))
    assert res.ok and res.status == "completed"
    assert elastic.latest_checkpoint(prefix)[0] == 2
    assert ckpt.verify_checkpoint(prefix, 2)[0] == "verified"
    assert math.isfinite(res.final_loss)
    # a dict config works too, and resumes from the checkpoints above
    mod2 = mx.module.Module(_toy_symbol(), context=[mx.cpu()])
    res2 = mod2.fit(_toy_iter(), num_epoch=4,
                    supervised={"prefix": prefix, "seed": 0})
    assert res2.ok
    assert elastic.latest_checkpoint(prefix)[0] == 3


def test_module_fit_supervised_requires_prefix():
    mod = mx.module.Module(_toy_symbol(), context=[mx.cpu()])
    with pytest.raises(mx.base.MXNetError, match="prefix"):
        mod.fit(_toy_iter(), num_epoch=1,
                supervised=supervisor.Supervise())


def test_module_fit_supervised_rolls_back_on_divergence(tmp_path):
    """In-process divergence proof on the real Module path: nan_after
    poisons the sentinel observable mid-fit; the run still completes with
    ≥1 rollback and a verified final checkpoint."""
    prefix = str(tmp_path / "fit")
    mod = mx.module.Module(_toy_symbol(), context=[mx.cpu()])
    with chaos.enable(nan_after=6, nan_streak=2, seed=0) as cfg:
        res = mod.fit(_toy_iter(), num_epoch=3,
                      supervised=supervisor.Supervise(
                          prefix=prefix, skip_limit=1, seed=0))
        assert cfg.nans_fired == 2
    assert res.ok and res.rollbacks == 1 and res.batches_skipped == 1
    epoch, _ = elastic.latest_checkpoint(prefix)
    assert epoch == 2
    assert ckpt.verify_checkpoint(prefix, epoch)[0] == "verified"


# ---------------------------------------------------------------------------
# deterministic resume: the bit-identical proof (ISSUE 5 tentpole)
# ---------------------------------------------------------------------------
def _det_build(seed):
    """Fixed-seed net + compiled step + shuffled iterator — everything a
    run's trajectory depends on."""
    from tpu_mx import gluon
    from tpu_mx.parallel import CompiledTrainStep
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 4)))
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd", learning_rate=0.05))
    R = np.random.RandomState(7)
    X = R.rand(32, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8, shuffle=True,
                           last_batch_handle="discard", seed=seed)
    return net, step, it


def _det_run(prefix, crash_at=None, epochs=3):
    net, step, it = _det_build(11)
    mgr = resume.CapsuleManager(prefix, iters=[it], state=step, interval=1)
    sup = supervisor.Supervisor(capsule=mgr, backoff=0.01, seed=0)

    def save_fn(e):
        step.sync_to_net()
        elastic.save_checkpoint(prefix, e, net=net, capsule=mgr)

    def restore_fn():
        e = elastic.auto_resume(prefix, net=net)
        step.sync_from_net()
        return e

    sup.save_fn, sup.restore_fn = save_fn, restore_fn
    losses = {}

    def epoch_fn(epoch):
        if not sup.resume_step(epoch):
            it.reset()
        for batch in it:
            def one(b=batch):
                v = float(step.step(b.data[0], b.label[0]).asnumpy().mean())
                losses[(epoch, sup.step_in_epoch + 1)] = v
                return v
            sup.step(one)

    ctx = chaos.enable(crash_at_step=crash_at, seed=0) if crash_at \
        else contextlib.nullcontext()
    with ctx:
        res = sup.run(epoch_fn, begin_epoch=0, num_epoch=epochs)
    assert res.ok, res.as_dict()
    step.sync_to_net()
    weights = [p.data().asnumpy().copy()
               for p in net.collect_params().values()]
    return losses, weights, res


def test_bit_identical_resume_after_midepoch_crash(tmp_path):
    """THE acceptance proof: run A trains uninterrupted; run B is
    chaos-crashed mid-epoch (after step 6 of 12 commits) and supervised-
    resumed through the step capsule.  Their per-step loss sequences and
    final weights must match EXACTLY — the capsule restored the RNG
    streams, the shuffle/cursor and the mid-epoch train state, so run B
    re-fed nothing and skipped nothing."""
    la, wa, _ = _det_run(str(tmp_path / "a"))
    lb, wb, rb = _det_run(str(tmp_path / "b"), crash_at=6)
    assert rb.restarts == 1
    assert set(la) == {(e, s) for e in range(3) for s in range(1, 5)}
    assert la == lb  # float-exact per-step loss trajectories
    assert wa and all(np.array_equal(a, b) for a, b in zip(wa, wb))
    assert telemetry.gauge("resume.resume_step_gap").value == 0


def test_chaos_crash_at_step_fires_after_commit_and_disarms():
    sup = _sup(restore_fn=lambda: 0)
    seen = []
    with chaos.enable(crash_at_step=3, seed=0) as cfg:
        res = sup.run(lambda e: [sup.step(lambda: seen.append(1) or 1.0)
                                 for _ in range(4)], num_epoch=2)
        assert cfg.step_crashes == 1
    assert res.ok and res.restarts == 1
    # the 3rd step COMMITTED before the crash (raise-after-commit), then
    # the restart re-ran epoch 0 (no capsule manager armed here)
    assert len(seen) == 3 + 8
    assert telemetry.get("chaos.injections", kind="crash_step").value >= 1


def test_module_fit_capsule_resumes_midepoch_exactly(tmp_path):
    """module.fit(supervised=Supervise(capsule=True, capsule_interval=1))
    crashed mid-epoch resumes at the exact batch: final params are
    bit-identical to the uninterrupted fixed-seed fit."""
    def fit(prefix, crash_at=None):
        mx.random.seed(4)
        mod = mx.module.Module(_toy_symbol(), context=[mx.cpu()])
        X = np.random.RandomState(1).rand(16, 4).astype(np.float32)
        Y = (X.sum(1) > 2).astype(np.float32)
        it = mx.io.NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=4,
                               label_name="softmax_label")
        ctx = chaos.enable(crash_at_step=crash_at, seed=0) if crash_at \
            else contextlib.nullcontext()
        with ctx:
            res = mod.fit(it, num_epoch=3,
                          optimizer_params=(("learning_rate", 0.05),
                                            ("momentum", 0.9)),
                          supervised=supervisor.Supervise(
                              prefix=prefix, capsule=True,
                              capsule_interval=1, seed=0))
        assert res.ok, res.as_dict()
        arg, aux = mod.get_params()
        return {k: v.asnumpy() for k, v in arg.items()}, res

    wa, _ = fit(str(tmp_path / "a"))
    wb, rb = fit(str(tmp_path / "b"), crash_at=6)  # epoch 1, step 2 of 4
    assert rb.restarts == 1
    for k in wa:
        np.testing.assert_array_equal(wa[k], wb[k])
    # every epoch's manifest carries its verified capsule
    man = ckpt.read_manifest(str(tmp_path / "b"), 2)
    assert "b-0002.capsule.json" in man["files"]
    assert ckpt.verify_checkpoint(str(tmp_path / "b"), 2)[0] == "verified"
    assert telemetry.gauge("resume.resume_step_gap").value == 0


# ---------------------------------------------------------------------------
# the subprocess rollback proof (satellite)
# ---------------------------------------------------------------------------
_ROLLBACK_SCRIPT = """\
import os
import tpu_mx as mx
from tpu_mx import elastic, nd, supervisor
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn

prefix = os.environ["SUP_PREFIX"]
net = nn.Dense(3, in_units=4)
net.initialize()

def save_fn(epoch):
    net.weight.set_data(nd.full((3, 4), 10.0 + epoch))
    elastic.save_checkpoint(prefix, epoch, net=net)

def restore_fn():
    e = elastic.auto_resume(prefix, net=net)
    print("RESUME_FROM", e,
          "WEIGHT", float(net.weight.data().asnumpy()[0, 0]), flush=True)
    return e

sup = supervisor.Supervisor(save_fn=save_fn, restore_fn=restore_fn,
                            skip_limit=0, backoff=0.01, seed=0)
armed = [True]

def epoch_fn(epoch):
    if epoch == 2 and armed[0]:
        armed[0] = False
        with chaos.enable(nan_after=2, nan_streak=1):
            for _ in range(3):
                sup.step(lambda: 1.0)
    else:
        for _ in range(3):
            sup.step(lambda: 1.0)

res = sup.run(epoch_fn, begin_epoch=0, num_epoch=4)
assert res.ok, res.as_dict()
assert res.rollbacks == 1, res.as_dict()
print("STATUS", res.status, flush=True)
"""


@pytest.mark.slow
def test_subprocess_divergence_resumes_from_last_good_epoch(tmp_path):
    """A real training process hit by mid-training divergence rolls back
    to the last GOOD epoch (weights prove it — not the poisoned one) and
    finishes with every epoch durably verified."""
    prefix = str(tmp_path / "job")
    script = tmp_path / "train.py"
    script.write_text(_ROLLBACK_SCRIPT)
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["SUP_PREFIX"] = prefix
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("TPUMX_CHAOS", None)
    proc = subprocess.run([sys.executable, str(script)], text=True,
                          capture_output=True, timeout=240, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.startswith("RESUME")]
    # first resume: fresh start (epoch 0, random init).  The divergence at
    # epoch 2 rolled back to resume FROM epoch 2 with epoch 1's weights
    # (11.0) — the poisoned epoch was never committed
    assert lines[0].startswith("RESUME_FROM 0 "), lines
    assert lines[1] == "RESUME_FROM 2 WEIGHT 11.0", lines
    assert "STATUS completed" in proc.stdout
    for epoch in range(4):
        assert ckpt.verify_checkpoint(prefix, epoch)[0] == "verified"


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------
def test_numeric_degrade_restores_instead_of_saving_poison(tmp_path):
    """Rollback budget exhausted on divergence: the degraded exit must NOT
    commit the (poisoned) live weights as a newer verified epoch — it
    restores the last good checkpoint, which stays newest."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    saves, restores = [], []

    def save_fn(e):
        saves.append(e)
        elastic.save_checkpoint(prefix, e, net=net)

    def restore_fn():
        restores.append(1)
        return elastic.auto_resume(prefix, net=net)

    sup = _sup(save_fn=save_fn, restore_fn=restore_fn, skip_limit=0,
               max_rollbacks=1)
    good = {"done": False}

    def epoch_fn(epoch):
        if epoch == 0 and not good["done"]:
            good["done"] = True
            sup.step(lambda: 1.0)  # one good epoch checkpoints below
            return
        with chaos.enable(nan_after=1, nan_streak=1):
            sup.step(lambda: 1.0)

    res = sup.run(epoch_fn, num_epoch=5)
    assert res.status == "degraded"
    # only the good epochs were ever saved — no degraded-save of epoch ≥1
    assert saves == [0], saves
    assert elastic.latest_checkpoint(prefix)[0] == 0
    # and the degraded exit restored the last good state one final time
    assert len(restores) >= 3  # initial resume + rollbacks + final restore


def test_train_step_discards_stale_result_after_restore():
    """The zombie-step guard: a watchdog-abandoned step finishing AFTER a
    state restore must not apply its stale update over the restored
    weights."""
    from tpu_mx import gluon
    from tpu_mx.parallel import CompiledTrainStep
    net = nn.Dense(2, in_units=4)
    net.initialize()
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd", learning_rate=0.1))
    x = nd.array(np.random.RandomState(0).rand(4, 4).astype(np.float32))
    y = nd.array(np.zeros(4, dtype=np.float32))
    step.step(x, y)  # compile + one real step
    gen0 = step._generation
    t0 = step._t
    # "restore": rebind fresh param arrays (as auto_resume's
    # load_parameters does — the step donated the originals) and sync —
    # sync_from_net bumps the generation
    net.weight.set_data(nd.full((2, 4), 0.5))
    net.bias.set_data(nd.full((2,), 0.0))
    step.sync_from_net()
    vals0 = {k: np.asarray(v) for k, v in step.values.items()}
    assert step._generation == gen0 + 1
    # … so a step that started under the OLD generation is discarded
    loss = step._step((x, y), None, expect_gen=gen0)
    assert np.isfinite(float(loss.asnumpy()))
    assert step._t == t0  # no state advanced
    for k, v in step.values.items():
        np.testing.assert_array_equal(np.asarray(v), vals0[k])
    # a current-generation step applies normally
    step._step((x, y), None, expect_gen=step._generation)
    assert step._t == t0 + 1


def test_train_step_zombie_thread_mid_flight_restore_discarded():
    """The full race, on the DEFAULT path (no explicit expect_gen): a step
    blocked mid-execution on an abandoned thread, a restore on the main
    thread, then the step unblocks — its result must be discarded."""
    from tpu_mx import gluon
    from tpu_mx.parallel import CompiledTrainStep
    net = nn.Dense(2, in_units=4)
    net.initialize()
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd", learning_rate=0.1))
    x = nd.array(np.random.RandomState(0).rand(4, 4).astype(np.float32))
    y = nd.array(np.zeros(4, dtype=np.float32))
    step.step(x, y)  # compile + one real step
    orig_jitted = step._jitted
    entered, gate = threading.Event(), threading.Event()

    def blocking_jitted(*args):
        entered.set()
        assert gate.wait(30)  # "hung collective"
        return orig_jitted(*args)

    step._jitted = blocking_jitted
    zombie = threading.Thread(target=lambda: step._step((x, y), None),
                              daemon=True)
    zombie.start()
    assert entered.wait(30)
    # main thread: the watchdog fired, the supervisor restores
    step._jitted = orig_jitted
    net.weight.set_data(nd.full((2, 4), 0.5))
    net.bias.set_data(nd.full((2,), 0.0))
    step.sync_from_net()
    t_restored = step._t
    vals0 = {k: np.asarray(v) for k, v in step.values.items()}
    # the zombie unblocks and finishes — its stale result is discarded
    gate.set()
    zombie.join(30)
    assert not zombie.is_alive()
    assert step._t == t_restored
    for k, v in step.values.items():
        np.testing.assert_array_equal(np.asarray(v), vals0[k])


# ---------------------------------------------------------------------------
# the bit-identical-resume SUBPROCESS proof (ISSUE 5 acceptance)
# ---------------------------------------------------------------------------
_DETERMINISM_SCRIPT = """\
import json
import os
import numpy as np
import tpu_mx as mx
from tpu_mx import elastic, nd, resume, supervisor, gluon
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep

MODE = os.environ["DET_MODE"]          # "run" or "crash"
prefix = os.environ["DET_PREFIX"]
out = os.environ.get("DET_OUT", "")

mx.random.seed(11)
net = nn.HybridSequential()
net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
net.initialize()
net(nd.ones((1, 4)))
step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         mx.optimizer.create("sgd", learning_rate=0.05))
R = np.random.RandomState(7)
X = R.rand(32, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)
it = mx.io.NDArrayIter(X, Y, batch_size=8, shuffle=True,
                       last_batch_handle="discard", seed=11)

mgr = resume.CapsuleManager(prefix, iters=[it], state=step, interval=1)
sup = supervisor.Supervisor(capsule=mgr, backoff=0.01, seed=0)

def save_fn(e):
    step.sync_to_net()
    elastic.save_checkpoint(prefix, e, net=net, capsule=mgr)

def restore_fn():
    e = elastic.auto_resume(prefix, net=net)
    step.sync_from_net()
    return e

sup.save_fn, sup.restore_fn = save_fn, restore_fn
losses = {}

def epoch_fn(epoch):
    if not sup.resume_step(epoch):
        it.reset()
    for batch in it:
        def one(b=batch):
            v = float(step.step(b.data[0], b.label[0]).asnumpy().mean())
            losses["%d:%d" % (epoch, sup.step_in_epoch + 1)] = v
            return v
        sup.step(one)

if MODE == "crash":
    # a TRUE mid-epoch process death: os._exit(137) right after the 6th
    # supervised step commits (its update applied, its capsule written)
    with chaos.enable(crash_at_step=6, hard=1, seed=0):
        sup.run(epoch_fn, begin_epoch=0, num_epoch=3)
    raise SystemExit("crash_at_step did not fire")

res = sup.run(epoch_fn, begin_epoch=0, num_epoch=3)
assert res.ok, res.as_dict()
step.sync_to_net()
np.savez(out + ".npz", **{str(i): p.data().asnumpy() for i, p in
                          enumerate(net.collect_params().values())})
with open(out + ".json", "w") as f:
    json.dump(losses, f)
print("DET DONE", flush=True)
"""


@pytest.mark.slow
def test_subprocess_bit_identical_resume(tmp_path):
    """The headline cross-process proof: run A trains 3 epochs
    uninterrupted.  Run B is hard-killed (os._exit) mid-epoch after step
    6 commits; a FRESH process resumes it through the step capsule.  The
    resumed process's first recorded step is exactly step 7 (epoch 1,
    step 3 — nothing re-fed, nothing skipped), its per-step losses match
    run A's bit-for-bit, and so do the final weights."""
    script = tmp_path / "det.py"
    script.write_text(_DETERMINISM_SCRIPT)
    env_base = dict(os.environ)
    env_base["PALLAS_AXON_POOL_IPS"] = ""
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["PYTHONPATH"] = REPO + os.pathsep + env_base.get("PYTHONPATH",
                                                              "")
    env_base.pop("TPUMX_CHAOS", None)

    def run(mode, prefix, out=""):
        env = dict(env_base, DET_MODE=mode, DET_PREFIX=prefix, DET_OUT=out)
        return subprocess.run([sys.executable, str(script)], text=True,
                              capture_output=True, timeout=240, env=env)

    a = run("run", str(tmp_path / "a"), str(tmp_path / "out_a"))
    assert a.returncode == 0, a.stdout + a.stderr
    crash = run("crash", str(tmp_path / "b"))
    assert crash.returncode == 137, crash.stdout + crash.stderr
    b = run("run", str(tmp_path / "b"), str(tmp_path / "out_b"))
    assert b.returncode == 0, b.stdout + b.stderr

    la = json.loads((tmp_path / "out_a.json").read_text())
    lb = json.loads((tmp_path / "out_b.json").read_text())
    # the resumed process recorded ONLY steps 7..12: exact-batch resume —
    # epoch 1 steps 1-2 (committed before the kill) were never re-fed
    assert sorted(lb) == ["1:3", "1:4", "2:1", "2:2", "2:3", "2:4"], lb
    for k, v in lb.items():
        assert la[k] == v, (k, la[k], v)  # bit-identical losses
    wa = np.load(str(tmp_path / "out_a.npz"))
    wb = np.load(str(tmp_path / "out_b.npz"))
    for k in wa.files:
        np.testing.assert_array_equal(wa[k], wb[k])
    for epoch in range(3):
        assert ckpt.verify_checkpoint(str(tmp_path / "b"),
                                      epoch)[0] == "verified"


def test_for_module_rollback_reloads_optimizer_states(tmp_path):
    """With save_optimizer_states=True, a rollback restores the optimizer
    state WITH the weights (diverged momentum must not survive)."""
    prefix = str(tmp_path / "fit")
    mod = mx.module.Module(_toy_symbol(), context=[mx.cpu()])
    loaded = []
    orig_load = mod.load_optimizer_states
    mod.load_optimizer_states = lambda f: (loaded.append(f), orig_load(f))
    with chaos.enable(nan_after=6, nan_streak=2, seed=0):
        res = mod.fit(_toy_iter(), num_epoch=3,
                      optimizer="sgd",
                      optimizer_params=(("learning_rate", 0.05),
                                        ("momentum", 0.9)),
                      supervised=supervisor.Supervise(
                          prefix=prefix, skip_limit=1,
                          save_optimizer_states=True, seed=0))
    assert res.ok and res.rollbacks == 1
    # the rollback restore reloaded the last good epoch's .states
    assert loaded and all(f.endswith(".states") for f in loaded), loaded
    man = ckpt.read_manifest(prefix, 2)
    assert "fit-0002.states" in man["files"]
