"""Inference serving runtime (tpu_mx/serving/) — ISSUE 8.

Covers: the block allocator (exhaustion -> backpressure never OOM,
free-on-completion reuse, double-free detection, state under concurrent
alloc/free), the paged KV cache (block-table correctness vs a dense
reference cache — BIT-identical gathers and logits), the
continuous-batching scheduler (admission budget, bounded-queue
reject-with-reason, immediate eviction, requeue), the request front-end
(submit/stream, deterministic greedy generation), and the self-healing
paths (hung decode -> watchdog -> classified engine restart with zero
lost requests; NaN logits -> restart; chaos reject_storm; degraded
shutdown fails requests loudly)."""
import json
import os
import threading
import time

import numpy as np
import pytest

from tpu_mx import telemetry, tracing
from tpu_mx.base import MXNetError
from tpu_mx.contrib import chaos
from tpu_mx import serving
from tpu_mx.serving import (AdmissionReject, BlockAllocator, CacheExhausted,
                            ContinuousBatchingScheduler, EngineCore,
                            PagedKVCache, Request, Server,
                            StaticBatchingScheduler, TinyLM)
from tpu_mx.serving.attention import (decode_attention, dense_attention,
                                      dense_decode_attention,
                                      resolve_decode_path)
from tpu_mx.supervisor import NumericDivergence


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Tracing/telemetry state is process-global — isolate every test."""
    tracing.reset()
    yield
    tracing.reset()


def tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("seed", 0)
    return TinyLM(**kw)


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------
def test_allocator_roundtrip_and_exhaustion_is_backpressure():
    a = BlockAllocator(4)
    got = a.alloc(3)
    assert len(got) == 3 and a.available == 1
    # exhaustion raises CacheExhausted (backpressure), all-or-nothing:
    # the one free block must NOT leak on the failed 2-block grab
    with pytest.raises(CacheExhausted):
        a.alloc(2)
    assert a.available == 1
    a.free(got)
    assert a.available == 4 and a.used == 0


def test_allocator_free_reuse_is_copy_free_lifo():
    a = BlockAllocator(8)
    first = a.alloc(2)
    a.free(first)
    # the freed blocks are handed out again (reuse, no compaction)
    again = a.alloc(2)
    assert set(again) == set(first)


def test_allocator_double_free_is_loud():
    a = BlockAllocator(2)
    got = a.alloc(1)
    a.free(got)
    with pytest.raises(MXNetError):
        a.free(got)
    with pytest.raises(MXNetError):
        a.free([99])


def test_allocator_concurrent_alloc_free_invariants():
    """Hammer alloc/free from several threads: no block is ever held by
    two owners, nothing leaks, and the final free count is exact."""
    a = BlockAllocator(64)
    owned = [[] for _ in range(4)]
    errs = []

    def worker(i, iters=300):
        rng = np.random.RandomState(i)
        try:
            for _ in range(iters):
                if owned[i] and rng.rand() < 0.5:
                    a.free([owned[i].pop()])
                else:
                    try:
                        owned[i].extend(a.alloc(int(rng.randint(1, 4))))
                    except CacheExhausted:
                        if owned[i]:
                            a.free([owned[i].pop()])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs
    held = [b for lst in owned for b in lst]
    assert len(held) == len(set(held))          # no double ownership
    assert a.used == len(held)                  # exact accounting
    for lst in owned:
        a.free(lst)
    assert a.available == 64


# ---------------------------------------------------------------------------
# paged KV cache vs a dense reference cache
# ---------------------------------------------------------------------------
def test_prefill_gather_roundtrip_bit_identical():
    cache = PagedKVCache(num_layers=2, num_heads=2, head_dim=4,
                         block_size=4, num_blocks=16)
    rng = np.random.RandomState(0)
    k = rng.rand(2, 10, 2, 4).astype(np.float32)   # L=10 -> 3 blocks
    v = rng.rand(2, 10, 2, 4).astype(np.float32)
    cache.prefill("s", k, v)
    assert len(cache.block_table("s")) == 3
    assert cache.length("s") == 10
    for layer in range(2):
        gk, gv = cache.gather("s", layer)
        assert np.array_equal(gk, k[layer])
        assert np.array_equal(gv, v[layer])


def test_append_is_o1_and_block_table_grows_by_block_size():
    cache = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         block_size=4, num_blocks=8)
    cache.prefill("s", np.zeros((1, 1, 1, 2), np.float32),
                  np.zeros((1, 1, 1, 2), np.float32))
    for i in range(11):
        pos = cache.reserve("s")
        assert pos == 1 + i
        cache.write("s", 0, np.full((1, 2), i, np.float32),
                    np.full((1, 2), -i, np.float32))
    assert cache.length("s") == 12
    assert len(cache.block_table("s")) == 3     # ceil(12/4)
    gk, _ = cache.gather("s", 0)
    assert np.array_equal(gk[1:, 0, 0], np.arange(11))


def test_gather_batch_matches_dense_reference_after_interleaved_churn():
    """Block tables stay correct when sequences alloc/free around each
    other: the paged gather must be BIT-identical to a dense per-seq
    reference cache."""
    rng = np.random.RandomState(1)
    cache = PagedKVCache(num_layers=2, num_heads=2, head_dim=4,
                         block_size=4, num_blocks=32)
    ref = {}

    def add(seq, length):
        k = rng.rand(2, length, 2, 4).astype(np.float32)
        v = rng.rand(2, length, 2, 4).astype(np.float32)
        cache.prefill(seq, k, v)
        ref[seq] = [k, v]

    def append(seq):
        k = rng.rand(2, 1, 2, 4).astype(np.float32)
        v = rng.rand(2, 1, 2, 4).astype(np.float32)
        cache.reserve(seq)
        for layer in range(2):
            cache.write(seq, layer, k[layer, 0], v[layer, 0])
        ref[seq] = [np.concatenate([ref[seq][0], k], axis=1),
                    np.concatenate([ref[seq][1], v], axis=1)]

    add("a", 6)
    add("b", 3)
    append("a")
    cache.free_sequence("b")       # frees mid-pool blocks
    del ref["b"]
    add("c", 9)                    # reuses b's blocks
    for _ in range(5):
        append("c")
        append("a")
    kd, vd, lens = cache.gather_batch(["a", "c"], 1)
    assert list(lens) == [12, 14]
    for i, seq in enumerate(("a", "c")):
        assert np.array_equal(kd[i, :lens[i]], ref[seq][0][1])
        assert np.array_equal(vd[i, :lens[i]], ref[seq][1][1])
        # beyond `lens` is PADDING (may carry stale block tails — the
        # attention mask zeroes it); only finiteness is guaranteed
        assert np.all(np.isfinite(kd[i, lens[i]:]))


def test_free_on_completion_reuses_blocks():
    cache = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         block_size=2, num_blocks=4)
    z = np.zeros((1, 4, 1, 2), np.float32)
    cache.prefill("a", z, z)                     # takes 2 of 4 blocks
    cache.prefill("b", z, z)                     # pool now full
    with pytest.raises(CacheExhausted):
        cache.prefill("c", z, z)
    assert cache.free_sequence("a") == 2
    cache.prefill("c", z, z)                     # a's blocks, reused
    assert cache.allocator.available == 0
    assert cache.free_sequence("missing") == 0   # idempotent


def test_paged_decode_logits_bit_identical_to_dense_cache():
    """The tentpole correctness claim: generation through the paged
    cache (block-table gather) reproduces a dense contiguous reference
    cache's logits BIT-for-bit, even after other sequences churned the
    pool."""
    model = tiny()
    prompt = [3, 1, 4, 1, 5]
    steps = 12

    # dense reference: contiguous K/V, same attention math
    k, v, logits = model.prefill(prompt)
    dk, dv = k.copy(), v.copy()                   # (N, L, H, D)
    ref_tokens, ref_logits = [int(np.argmax(logits))], []
    for _ in range(steps):
        pos = dk.shape[1]
        h = model.embed(np.array([ref_tokens[-1]]), np.array([pos]))
        nk = np.empty((model.num_layers, 1, model.num_heads,
                       model.head_dim), np.float32)
        nv = np.empty_like(nk)
        for i in range(model.num_layers):
            q, ki, vi = model.layer_qkv(i, h)
            nk[i], nv[i] = ki, vi
            kcat = np.concatenate([dk[i], ki], axis=0)[None]
            vcat = np.concatenate([dv[i], vi], axis=0)[None]
            attn = dense_decode_attention(q, kcat, vcat,
                                          np.array([pos + 1], np.int32))
            h = model.layer_combine(i, h, attn)
        dk = np.concatenate([dk, nk], axis=1)
        dv = np.concatenate([dv, nv], axis=1)
        lg = model.logits(h)[0]
        ref_logits.append(lg)
        ref_tokens.append(int(np.argmax(lg)))

    # paged run, with churn from a second sequence sharing the pool
    eng = EngineCore(model, block_size=4, num_blocks=64)
    req = Request(prompt, max_new_tokens=steps + 1, request_id="main")
    other = Request([9, 9, 9], max_new_tokens=steps + 1,
                    request_id="other")
    first, _ = eng.prefill(req)
    eng.prefill(other)
    assert first == ref_tokens[0]
    got = [first]
    (first_ot,) = eng.decode([(other, 9)])[0][other.id]
    ot = [first_ot]
    for step in range(steps):
        if step == 4:
            eng.evict(other)                      # churn: free mid-run
        items = [(req, got[-1])]
        if step < 4:
            items.append((other, ot[-1]))
        res, pre = eng.decode(items)
        assert not pre
        got.extend(res[req.id])
        if step < 4:
            ot.extend(res[other.id])
    assert got == ref_tokens


# ---------------------------------------------------------------------------
# attention fallback
# ---------------------------------------------------------------------------
def test_dense_attention_respects_lengths_and_causality():
    rng = np.random.RandomState(0)
    q = rng.rand(2, 1, 2, 4).astype(np.float32)
    k = rng.rand(2, 6, 2, 4).astype(np.float32)
    v = rng.rand(2, 6, 2, 4).astype(np.float32)
    lens = np.array([3, 6], np.int32)
    out = dense_attention(q, k, v, lengths=lens)
    # row 0 must ignore keys >= 3: garbage there cannot change the output
    k2, v2 = k.copy(), v.copy()
    k2[0, 3:] = 1e6
    v2[0, 3:] = -1e6
    out2 = dense_attention(q, k2, v2, lengths=lens)
    assert np.array_equal(out[0], out2[0])
    assert np.array_equal(out[1], out2[1])
    # causal prefill: position i must ignore keys > i
    q3 = rng.rand(1, 4, 2, 4).astype(np.float32)
    k3 = rng.rand(1, 4, 2, 4).astype(np.float32)
    v3 = rng.rand(1, 4, 2, 4).astype(np.float32)
    full = dense_attention(q3, k3, v3, causal=True)
    k3[0, 3] = 77.0                                # future key for rows 0-2
    again = dense_attention(q3, k3, v3, causal=True)
    assert np.array_equal(full[0, :3], again[0, :3])


# ---------------------------------------------------------------------------
# paged decode: the kernel / device-pool arms (ISSUE 9)
# ---------------------------------------------------------------------------
# Attention-output tolerance between the dense-gather arm (numpy) and the
# paged arms (Pallas kernel / jitted XLA): identical math, f32 softmax
# stats on every arm, different reduction orders.  Documented in
# docs/DIVERGENCES.md #27; greedy argmax equivalence is asserted exactly.
PAGED_ATOL = 2e-5


def churned_cache(storage, seed=7):
    """A cache whose block tables are FRAGMENTED: interleaved prefills,
    appends and a mid-pool free leave sequences scattered (and block 0
    live inside a sequence, so padded table rows point at real, finite
    pool contents).  Returns (cache, ref) with ref the dense per-seq
    truth."""
    rng = np.random.RandomState(seed)
    cache = PagedKVCache(num_layers=2, num_heads=2, head_dim=4,
                         block_size=4, num_blocks=32, storage=storage)
    ref = {}

    def add(seq, length):
        k = rng.rand(2, length, 2, 4).astype(np.float32)
        v = rng.rand(2, length, 2, 4).astype(np.float32)
        cache.prefill(seq, k, v)
        ref[seq] = [k, v]

    def append(seq):
        k = rng.rand(2, 1, 2, 4).astype(np.float32)
        v = rng.rand(2, 1, 2, 4).astype(np.float32)
        cache.reserve(seq)
        for layer in range(2):
            cache.write(seq, layer, k[layer, 0], v[layer, 0])
        ref[seq] = [np.concatenate([ref[seq][0], k], axis=1),
                    np.concatenate([ref[seq][1], v], axis=1)]

    add("a", 6)                    # takes block 0 (LIFO free list)
    add("b", 3)
    append("a")
    cache.free_sequence("b")       # frees mid-pool blocks
    del ref["b"]
    add("c", 9)                    # reuses b's blocks
    add("d", 2)                    # ragged short row
    for _ in range(5):
        append("c")
        append("a")
    return cache, ref


@pytest.mark.parametrize("storage", ["host", "device"])
def test_device_pool_matches_host_pool_after_churn(storage):
    """Both storage modes must expose identical bytes through every
    reader: gather, gather_batch and the raw pool-by-table view."""
    cache, ref = churned_cache(storage)
    for layer in range(2):
        for seq in ("a", "c", "d"):
            gk, gv = cache.gather(seq, layer)
            assert np.array_equal(gk, ref[seq][0][layer])
            assert np.array_equal(gv, ref[seq][1][layer])
    kd, vd, lens = cache.gather_batch(["a", "c", "d"], 1)
    assert list(lens) == [12, 14, 2]
    tables, lens2 = cache.batch_tables(["a", "c", "d"])
    assert np.array_equal(lens, lens2)
    assert tables.shape[1] == 4                   # pow2-padded (max 3+1)
    assert tables.dtype == np.int32
    # table rows resolved against the pool reproduce the gather exactly
    kp, vp = cache.pool(1)
    kp = np.asarray(kp)
    for i, seq in enumerate(("a", "c", "d")):
        nb = cache.blocks_for(lens[i])
        resolved = kp[tables[i, :nb]].reshape(-1, 2, 4)[:lens[i]]
        assert np.array_equal(resolved, ref[seq][0][1])


@pytest.mark.parametrize("storage", ["host", "device"])
@pytest.mark.parametrize("kind", ["paged", "paged-kernel"])
def test_paged_decode_attention_parity_after_churn(storage, kind):
    """The tentpole parity claim: the paged arms (XLA twin and the real
    Pallas kernel in interpret mode) reproduce the dense-gather arm over
    fragmented block tables, ragged lengths and block-0-padded rows,
    within the documented f32-stats tolerance."""
    cache, _ = churned_cache(storage)
    rng = np.random.RandomState(3)
    seq_ids = ["a", "c", "d"]                      # ragged: 12 / 14 / 2
    q = rng.rand(3, 2, 4).astype(np.float32)
    want = decode_attention(q, cache, seq_ids, 0, kind="dense")
    got = decode_attention(q, cache, seq_ids, 0, kind=kind)
    assert got.shape == want.shape and got.dtype == want.dtype
    np.testing.assert_allclose(got, want, rtol=PAGED_ATOL, atol=PAGED_ATOL)
    # garbage beyond `lengths` cannot leak through the kernel's mask:
    # corrupt every free block and re-run (host pool mutated in place)
    if storage == "host":
        free = set(range(32)) - {b for s in seq_ids
                                 for b in cache.block_table(s)}
        cache.k_blocks[:, sorted(free)] = 1e9
        cache.v_blocks[:, sorted(free)] = -1e9
        again = decode_attention(q, cache, seq_ids, 0, kind=kind)
        np.testing.assert_allclose(again, got, rtol=0, atol=0)


def test_decode_attention_counts_kind_and_dispatches_env(monkeypatch):
    cache, _ = churned_cache("host")
    q = np.zeros((1, 2, 4), np.float32)
    telemetry.reset()
    try:
        monkeypatch.delenv("TPUMX_PAGED_DECODE", raising=False)
        assert resolve_decode_path() == "dense"
        monkeypatch.setenv("TPUMX_PAGED_DECODE", "1")
        assert resolve_decode_path() == "paged"
        monkeypatch.setenv("TPUMX_PAGED_DECODE", "kernel")
        assert resolve_decode_path() == "paged-kernel"
        # a typo'd arm must fail LOUDLY, never silently pick another arm
        # (a mis-spelled 'kernel' passing parity without running the
        # kernel would be an invisible hole in the CI gate)
        monkeypatch.setenv("TPUMX_PAGED_DECODE", "kernal")
        with pytest.raises(ValueError, match="TPUMX_PAGED_DECODE"):
            resolve_decode_path()
        monkeypatch.setenv("TPUMX_PAGED_DECODE", "kernel")
        decode_attention(q, cache, ["a"], 0)       # env-dispatched
        decode_attention(q, cache, ["a"], 0, kind="dense")
        assert telemetry.get("serve.decode_attention",
                             kind="paged-kernel").value == 1
        assert telemetry.get("serve.decode_attention",
                             kind="dense").value == 1
    finally:
        telemetry.reset()


@pytest.mark.parametrize("mode", ["1", "kernel"])
def test_server_token_streams_identical_across_decode_paths(monkeypatch,
                                                            mode):
    """Greedy decode through the full Server path must produce the SAME
    token stream on the paged arms as on the dense reference arm —
    the acceptance bar for routing production decode through the
    kernel."""
    prompts = [[5, 6, 7], [9, 2], [1] * 7]
    monkeypatch.delenv("TPUMX_PAGED_DECODE", raising=False)
    srv = Server(tiny(), num_blocks=64, max_batch=4)
    ref = [srv.submit(p, max_new_tokens=6) for p in prompts]
    srv.run_until_idle()

    monkeypatch.setenv("TPUMX_PAGED_DECODE", mode)
    srv2 = Server(tiny(), num_blocks=64, max_batch=4)
    assert srv2.engine.cache.device_resident
    got = [srv2.submit(p, max_new_tokens=6) for p in prompts]
    srv2.run_until_idle()
    for r, g in zip(ref, got):
        assert g.state == "done" and g.tokens == r.tokens
    gauge = telemetry.get("serve.pool_device_resident")
    assert gauge is not None and gauge.value == 1.0
    evs = [e for e in tracing.snapshot()
           if e["event"] == "serve.decode_path"]
    assert evs and evs[-1]["data"]["storage"] == "device"
    for e in evs:
        tracing.validate_event(e)


def test_paged_engine_restart_blackbox_records_decode_path(monkeypatch,
                                                           tmp_path):
    """A restarted paged engine must land its decode path on the black
    box timeline: one serve.decode_path per engine generation, and the
    post-restart run still completes on the paged arm with zero lost
    requests."""
    monkeypatch.setenv("TPUMX_PAGED_DECODE", "1")
    prefix = str(tmp_path / "pg")
    srv = Server(tiny(), num_blocks=64, max_batch=4, backoff=0.0,
                 blackbox=prefix)
    reqs = [srv.submit([4, 5], max_new_tokens=4) for _ in range(2)]
    with chaos.enable(nan_after=2):
        srv.run_until_idle()
    assert srv.restarts == 1
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 4
    box = json.load(open(tracing.blackbox_path(prefix)))
    tracing.validate_blackbox(box)
    paths = [e for e in box["events"] if e["event"] == "serve.decode_path"]
    assert len(paths) == 2                         # one per generation
    assert all(e["data"]["path"] == "paged" for e in paths)
    assert paths[1]["generation"] == paths[0]["generation"] + 1


def test_paged_cache_exhaustion_still_backpressures(monkeypatch):
    """The exhaustion-is-backpressure contract is storage-independent:
    an over-committed DEVICE pool serializes via preemption/requeue and
    every request completes."""
    monkeypatch.setenv("TPUMX_PAGED_DECODE", "1")
    srv = Server(tiny(), num_blocks=6, block_size=2, max_batch=4,
                 max_tokens=1000)
    reqs = [srv.submit([1, 2, 3], max_new_tokens=6) for _ in range(5)]
    srv.run_until_idle()
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 6, r
    assert srv.engine.cache.stats()["used_blocks"] == 0
    assert all(r.tokens == reqs[0].tokens for r in reqs)


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------
def test_scheduler_bounded_queue_rejects_with_reason():
    s = ContinuousBatchingScheduler(max_pending=2, max_batch=2,
                                    max_tokens=100)
    s.submit(Request([1], 4))
    s.submit(Request([1], 4))
    with pytest.raises(AdmissionReject) as e:
        s.submit(Request([1], 4))
    assert e.value.reason == "queue_full"
    with pytest.raises(AdmissionReject) as e:
        s.submit(Request([1] * 80, 40))
    assert e.value.reason == "request_too_large"
    # rejected requests are failed loudly, not left hanging
    assert e.value.reason in ("request_too_large",)


def test_scheduler_admission_respects_token_budget_and_batch():
    s = ContinuousBatchingScheduler(max_pending=8, max_batch=8,
                                    max_tokens=30)
    for _ in range(4):
        s.submit(Request([1] * 6, 6))             # 12 budget tokens each
    first = s.take_prefills()
    assert len(first) == 2                        # 24 <= 30 < 36
    for r in first:
        s.mark_running(r)
    assert s.take_prefills() == []                # budget holds
    s.finish(first[0])                            # immediate eviction
    assert len(s.take_prefills()) == 1            # slot refilled next step


def test_scheduler_requeue_discards_generation_and_fronts():
    s = ContinuousBatchingScheduler(max_pending=4, max_batch=4,
                                    max_tokens=1000)
    a, b = Request([1], 4, request_id="a"), Request([2], 4, request_id="b")
    s.submit(a)
    s.submit(b)
    for r in s.take_prefills():
        s.mark_running(r)
    a.record_token(7)
    s.requeue_all_running()
    assert a.tokens == [] and a.requeues == 1 and a.state == "queued"
    # fronted in arrival order: a decodes before b again
    assert [r.id for r in s.take_prefills()] == ["a", "b"]


def test_static_scheduler_waits_for_drain():
    s = StaticBatchingScheduler(max_pending=8, max_batch=2,
                                max_tokens=1000)
    for i in range(4):
        s.submit(Request([1], 2, request_id=f"r{i}"))
    batch = s.take_prefills()
    assert len(batch) == 2
    for r in batch:
        s.mark_running(r)
    assert s.take_prefills() == []                # no refill mid-batch
    assert s.finish(batch[0]) == []               # no eviction either
    assert len(s.decode_batch()) == 2             # finished slot = padding
    evicted = s.finish(batch[1])                  # drain -> evict both
    assert set(r.id for r in evicted) == {"r0", "r1"}
    assert len(s.take_prefills()) == 2            # next batch admitted


# ---------------------------------------------------------------------------
# server: the front-end
# ---------------------------------------------------------------------------
def test_server_generates_deterministically_and_streams():
    srv = Server(tiny(), num_blocks=64, max_batch=4)
    r1 = srv.submit([5, 6, 7], max_new_tokens=8)
    srv.run_until_idle()
    assert r1.state == "done" and len(r1.tokens) == 8
    # same prompt through stream() reproduces the greedy tokens exactly
    srv2 = Server(tiny(), num_blocks=64, max_batch=4)
    assert list(srv2.stream([5, 6, 7], max_new_tokens=8)) == r1.tokens
    # latency bookkeeping for the SLO metrics
    assert r1.ttft is not None and r1.ttft >= 0
    assert len(r1.token_times) == 8


def test_server_eos_finishes_early():
    srv = Server(tiny(), num_blocks=64)
    probe = srv.submit([5, 6, 7], max_new_tokens=4)
    srv.run_until_idle()
    eos = probe.tokens[1]
    srv2 = Server(tiny(), num_blocks=64, eos_id=eos)
    req = srv2.submit([5, 6, 7], max_new_tokens=10)
    srv2.run_until_idle()
    assert req.finish_reason == "eos"
    assert len(req.tokens) == 2


def test_server_cache_exhaustion_backpressures_and_completes_all():
    """A pool far too small for the offered load must serialize the work
    via preemption/requeue — every request still completes, nothing
    OOMs."""
    srv = Server(tiny(), num_blocks=6, block_size=2, max_batch=4,
                 max_tokens=1000)
    reqs = [srv.submit([1, 2, 3], max_new_tokens=6) for _ in range(5)]
    srv.run_until_idle()
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 6, r
    assert srv.engine.cache.stats()["used_blocks"] == 0
    # and all requests produced identical tokens (same prompt, greedy)
    assert all(r.tokens == reqs[0].tokens for r in reqs)


def test_server_request_events_carry_request_context():
    srv = Server(tiny(), num_blocks=64)
    req = srv.submit([1, 2], max_new_tokens=3)
    srv.run_until_idle()
    evs = tracing.snapshot()
    pre = [e for e in evs if e["event"] == "serve.prefill"]
    ev = [e for e in evs if e["event"] == "serve.evict"]
    assert pre and pre[0]["request"] == req.id
    assert ev and ev[0]["request"] == req.id
    dec = [e for e in evs if e["event"] == "serve.decode"]
    assert dec and dec[0]["request"] is None      # batch-scoped
    for e in evs:
        tracing.validate_event(e)


def test_server_telemetry_names_are_cataloged():
    telemetry.reset()
    try:
        srv = Server(tiny(), num_blocks=64)
        srv.submit([1, 2], max_new_tokens=3)
        srv.run_until_idle()
        recs = telemetry.snapshot()
        assert recs
        for rec in recs:
            telemetry.validate_record(rec)
            assert rec["name"] in telemetry.KNOWN_METRICS, rec["name"]
        names = {r["name"] for r in recs}
        assert {"serve.ttft_seconds", "serve.itl_seconds",
                "serve.generated_tokens", "serve.queue_depth",
                "serve.cache_utilization"} <= names
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# self-healing: the supervisor patterns under the server
# ---------------------------------------------------------------------------
def test_hung_decode_watchdog_restart_zero_lost_requests(tmp_path):
    prefix = str(tmp_path / "sv")
    srv = Server(tiny(), num_blocks=64, max_batch=4, deadline=0.5,
                 backoff=0.0, blackbox=prefix)
    reqs = [srv.submit([1, 2, 3], max_new_tokens=5) for _ in range(3)]
    with chaos.enable(slow_decode_step=2, slow_decode_seconds=30) as cfg:
        srv.run_until_idle()
    assert cfg.slow_decodes == 1
    assert srv.restarts == 1
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 5, r
    # the re-run reproduced the same greedy tokens it would have without
    # the fault (deterministic recovery)
    clean = Server(tiny(), num_blocks=64, max_batch=4)
    ref = clean.submit([1, 2, 3], max_new_tokens=5)
    clean.run_until_idle()
    assert all(r.tokens == ref.tokens for r in reqs)
    # black box: schema-valid, injection and restart share the context
    box = json.load(open(tracing.blackbox_path(prefix)))
    tracing.validate_blackbox(box)
    inj = [e for e in box["events"] if e["event"] == "chaos.inject"
           and e["data"]["kind"] == "slow_decode_step"]
    rst = [e for e in box["events"] if e["event"] == "serve.restart"]
    assert inj and rst
    assert (inj[0]["step"], inj[0]["generation"]) == \
        (rst[0]["step"], rst[0]["generation"])


def test_nan_logits_classified_restart(tmp_path):
    """chaos nan_after poisons the decode health scalar -> the engine
    raises NumericDivergence -> classified restart; requests survive."""
    prefix = str(tmp_path / "nan")
    srv = Server(tiny(), num_blocks=64, max_batch=4, backoff=0.0,
                 blackbox=prefix)
    reqs = [srv.submit([4, 5], max_new_tokens=4) for _ in range(2)]
    with chaos.enable(nan_after=2) as cfg:
        srv.run_until_idle()
    assert cfg.nans_fired >= 1
    assert srv.restarts == 1
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 4
    box = json.load(open(tracing.blackbox_path(prefix)))
    tracing.validate_blackbox(box)
    names = [e["event"] for e in box["events"]]
    assert "serve.restart" in names


def test_restart_budget_exhaustion_degrades_loudly():
    srv = Server(tiny(), num_blocks=64, max_restarts=1, backoff=0.0,
                 deadline=0.3)
    # max_new is deliberately > restarts + 1: each generation's replay
    # prefill legitimately delivers ONE fresh token (the prefill path
    # is not poisoned), so a short request could finish on prefills
    # alone — the budget must run out with tokens still owed
    reqs = [srv.submit([1], max_new_tokens=6) for _ in range(2)]
    with chaos.enable(nan_after=1, nan_streak=100):
        # every decode poisons -> restarts 1, 2 -> budget exceeded ->
        # degrade.  The fault is PERSISTENT, so the degraded drain (the
        # migrated running batch's final generation) faults too and the
        # remaining streams fail loudly; the now-idle degraded server
        # then refuses further steps.
        with pytest.raises(MXNetError):
            for _ in range(50):
                srv.step()
    assert srv.degraded
    for r in reqs:
        assert r.state == "failed" and "degraded" in r.finish_reason
    with pytest.raises(AdmissionReject) as e:
        srv.submit([1], max_new_tokens=1)
    assert e.value.reason == "degraded"


def test_reject_storm_counts_and_resubmit_succeeds():
    srv = Server(tiny(), num_blocks=64)
    with chaos.enable(reject_storm=2) as cfg:
        for _ in range(2):
            with pytest.raises(AdmissionReject) as e:
                srv.submit([1, 2], max_new_tokens=2)
            assert e.value.reason == "reject_storm"
        req = srv.submit([1, 2], max_new_tokens=2)   # storm exhausted
        srv.run_until_idle()
    assert cfg.rejects_forced == 2
    assert req.state == "done"
    rejects = [e for e in tracing.snapshot()
               if e["event"] == "serve.reject"]
    assert len(rejects) == 2
    assert all(e["data"]["reason"] == "reject_storm" for e in rejects)


def test_concurrent_submit_while_serving():
    """submit() from other threads while the step thread admits/evicts:
    allocator and scheduler stay consistent, every request completes."""
    srv = Server(tiny(), num_blocks=48, block_size=4, max_batch=4,
                 max_pending=200, max_tokens=100000)
    out, errs = [], []

    def feeder(i):
        try:
            for j in range(10):
                out.append(srv.submit([1 + i, 2 + j], max_new_tokens=3))
                time.sleep(0.0005)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=feeder, args=(i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    deadline = time.time() + 60
    while (any(t.is_alive() for t in threads)
           or not srv.scheduler.idle()):
        srv.step()
        assert time.time() < deadline, "serving wedged"
    for t in threads:
        t.join(10)
    assert not errs, errs
    assert len(out) == 30
    for r in out:
        assert r.state == "done" and len(r.tokens) == 3, r
    assert srv.engine.cache.stats()["used_blocks"] == 0


def test_degraded_rejects_are_counted_and_on_the_timeline():
    """A degraded-window reject must be observable like any other:
    counted in serve.requests{state=rejected} and emitted as a
    serve.reject event with reason 'degraded'."""
    srv = Server(tiny(), num_blocks=64, max_restarts=0, backoff=0.0)
    srv.submit([1], max_new_tokens=2)
    with chaos.enable(nan_after=1, nan_streak=100):
        for _ in range(5):
            if srv.degraded:
                break
            srv.step()
    assert srv.degraded
    telemetry.reset()
    with pytest.raises(AdmissionReject) as e:
        srv.submit([1], max_new_tokens=1)
    assert e.value.reason == "degraded"
    assert telemetry.get("serve.requests", state="rejected").value == 1
    rej = [ev for ev in tracing.snapshot() if ev["event"] == "serve.reject"]
    assert rej and rej[-1]["data"]["reason"] == "degraded"
    telemetry.reset()


def test_degrade_drains_running_and_fails_only_queued():
    """Budget exhaustion (ISSUE 19) fails QUEUED work loudly but never
    abandons mid-stream work: the running batch migrates (one replay
    prefill each) onto one final engine generation and drains to
    completion — a transient fault that exhausts the budget costs
    queued requests, not in-flight streams."""
    telemetry.reset()
    try:
        srv = Server(tiny(), num_blocks=64, max_restarts=0, backoff=0.0,
                     max_batch=1)
        running = srv.submit([1, 2], max_new_tokens=6)
        queued = srv.submit([3, 4], max_new_tokens=6)  # batch full: waits
        with chaos.enable(nan_after=1):   # ONE poisoned decode, then clean
            srv.step()   # prefill + first poisoned decode -> degrade
            if not srv.degraded:
                srv.step()
            assert srv.degraded
            # queued work failed loudly AT degrade time — once, never
            # re-admitted, the client unblocked immediately
            assert queued.state == "failed"
            assert "degraded" in queued.finish_reason
            assert queued.requeues == 0
            srv.run_until_idle()          # the degraded drain
        assert running.state == "done" and len(running.tokens) == 6
        assert running.requeues == 1      # the one migration
        # the drained stream is bit-identical to an uninterrupted run
        clean = Server(tiny(), num_blocks=64)
        ref = clean.submit([1, 2], max_new_tokens=6)
        clean.run_until_idle()
        assert running.tokens == ref.tokens
        # drained-idle degraded server refuses further steps
        with pytest.raises(MXNetError):
            srv.step()
    finally:
        telemetry.reset()


def test_prefill_backpressure_defers_without_requeue_count():
    """Admissions bounced by prefill-time cache exhaustion were never
    started: they are deferred, not requeued — the handle's requeues
    ledger stays 0 unless a real preemption/restart re-ran it."""
    srv = Server(tiny(), num_blocks=4, block_size=2, max_batch=4,
                 max_tokens=1000)
    reqs = [srv.submit([1, 2, 3], max_new_tokens=2) for _ in range(4)]
    srv.run_until_idle()
    assert all(r.state == "done" and len(r.tokens) == 2 for r in reqs)
    # prompt(3)+gen(2)=5 tokens = 3 blocks of 2; pool of 4 serializes
    # admissions via DEFER (never-started) — decode-time preemption can
    # still requeue, but at least one deferred-only request stays at 0
    assert min(r.requeues for r in reqs) == 0


def test_static_scheduler_survives_cache_preemption_of_padding_slots():
    """Regression (review finding): under StaticBatchingScheduler a
    finished batch member occupies its slot as padding; when the pool
    runs dry the engine must evict the PADDING first — never corrupt
    the done handle, never requeue it, and the run must complete."""
    srv = Server(tiny(), scheduler=StaticBatchingScheduler(
        max_pending=16, max_batch=3, max_tokens=100000),
        num_blocks=6, block_size=4)
    outs = [2, 2, 12]
    reqs = [srv.submit([1, 2, 3], max_new_tokens=n) for n in outs]
    srv.run_until_idle()
    for r, n in zip(reqs, outs):
        assert r.state == "done" and len(r.tokens) == n, r
    # the short (finished-early) members kept their delivered tokens and
    # were never flipped back to queued by a padding preemption
    assert reqs[0].requeues == 0 and reqs[1].requeues == 0
    evs = [e for e in tracing.snapshot() if e["event"] == "serve.evict"]
    assert any(e["data"]["reason"] == "padding" for e in evs)
    assert srv.engine.cache.stats()["used_blocks"] == 0


# ---------------------------------------------------------------------------
# continuous vs static batching (the mechanism; the bench measures time)
# ---------------------------------------------------------------------------
def test_continuous_batching_fills_slots_static_wastes_them():
    """With mixed output lengths, the static baseline burns decode-step
    slots on finished padding; continuous refills immediately — counted
    in engine decode steps, the deterministic proxy for the bench's
    wall-clock A/B."""
    def run(sched_cls):
        model = tiny()
        srv = Server(model, scheduler=sched_cls(max_pending=64,
                                                max_batch=2,
                                                max_tokens=100000),
                     num_blocks=256, block_size=4)
        outs = [2, 8, 2, 8]
        reqs = [srv.submit([1, 2, 3], max_new_tokens=n) for n in outs]
        srv.run_until_idle()
        assert all(r.state == "done" and len(r.tokens) == n
                   for r, n in zip(reqs, outs))
        return srv._steps

    continuous = run(ContinuousBatchingScheduler)
    static = run(StaticBatchingScheduler)
    assert continuous < static, (continuous, static)
