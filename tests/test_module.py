"""mx.module tests — mirrors the reference's tests/python/unittest/
test_module.py and tests/python/train/test_mlp.py ("does it learn")."""
import numpy as np
import pytest

import tpu_mx as mx


def _mlp_sym(num_hidden=32, num_classes=4):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.Variable("softmax_label"),
                                name="softmax")


def _toy_dataset(n=256, dim=10, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim) * 3.0
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, dim).astype("float64") * 0.5
    return x.astype("float32"), y.astype("float32")


def test_module_bind_forward():
    sym = _mlp_sym()
    mod = mx.module.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Xavier())
    batch = mx.io.DataBatch(data=[mx.nd.array(np.zeros((8, 10), "float32"))],
                            label=[mx.nd.array(np.zeros((8,), "float32"))])
    mod.forward(batch, is_train=False)
    (out,) = mod.get_outputs()
    assert out.shape == (8, 4)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(8),
                               rtol=1e-5)


def test_module_learns():
    """Train-threshold test, reference pattern tests/python/train/test_mlp.py."""
    x, y = _toy_dataset()
    train_iter = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                                   label_name="softmax_label")
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train_iter, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1,
                              "rescale_grad": 1.0 / 32},
            initializer=mx.init.Xavier(),
            eval_metric="acc")
    score_iter = mx.io.NDArrayIter(x, y, batch_size=32,
                                   label_name="softmax_label")
    res = dict(mod.score(score_iter, "acc"))
    assert res["accuracy"] > 0.9, res


def test_module_get_set_params():
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Normal(0.1))
    arg, aux = mod.get_params()
    assert set(arg) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    arg2 = {k: mx.nd.array(np.ones_like(v.asnumpy())) for k, v in arg.items()}
    mod.set_params(arg2, aux)
    got, _ = mod.get_params()
    np.testing.assert_allclose(got["fc1_weight"].asnumpy(), 1.0)


def test_module_checkpoint(tmp_path):
    prefix = str(tmp_path / "mlp")
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Normal(0.1))
    mod.save_checkpoint(prefix, 3)
    sym, arg, aux = mx.model.load_checkpoint(prefix, 3)
    assert set(arg) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    mod2 = mx.module.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 10))],
              label_shapes=[("softmax_label", (4,))])
    mod2.init_params()
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy())


def test_module_input_grads():
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params(initializer=mx.init.Normal(0.1))
    batch = mx.io.DataBatch(
        data=[mx.nd.array(np.random.randn(4, 10).astype("float32"))],
        label=[mx.nd.array(np.array([0, 1, 2, 3], "float32"))])
    mod.forward_backward(batch)
    (gin,) = mod.get_input_grads()
    assert gin.shape == (4, 10)
    assert np.abs(gin.asnumpy()).sum() > 0


def test_module_variable_last_batch():
    """Smaller final batch retraces the jit instead of needing reshape."""
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Normal(0.1))
    for bs in (8, 5):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(np.zeros((bs, 10), "float32"))],
            label=[mx.nd.array(np.zeros((bs,), "float32"))])
        mod.forward(batch, is_train=False)
        assert mod.get_outputs()[0].shape == (bs, 4)


def test_bucketing_module():
    """Bucketed executors sharing parameters (symbolic PTB pattern)."""

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        flat = mx.sym.reshape(data, shape=(-1, seq_len * 2))
        fc = mx.sym.FullyConnected(flat, num_hidden=3, name="shared_fc",
                                   no_bias=True)
        out = mx.sym.SoftmaxOutput(fc, mx.sym.Variable("softmax_label"),
                                   name="softmax")
        return out, ("data",), ("softmax_label",)

    # weight shape depends on bucket — use per-bucket distinct fc input dim,
    # so share only via same-name params with equal shapes: use seq-invariant
    # architecture instead (mean over time).
    def sym_gen2(seq_len):
        data = mx.sym.Variable("data")
        m = mx.sym.mean(data, axis=1)
        fc = mx.sym.FullyConnected(m, num_hidden=3, name="shared_fc")
        out = mx.sym.SoftmaxOutput(fc, mx.sym.Variable("softmax_label"),
                                   name="softmax")
        return out, ("data",), ("softmax_label",)

    bmod = mx.module.BucketingModule(sym_gen2, default_bucket_key=10,
                                     context=mx.cpu())
    bmod.bind(data_shapes=[("data", (4, 10, 6))],
              label_shapes=[("softmax_label", (4,))])
    bmod.init_params(initializer=mx.init.Normal(0.1))
    bmod.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1})

    for seq_len in (10, 7, 10, 13):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(np.random.randn(4, seq_len, 6)
                              .astype("float32"))],
            label=[mx.nd.array(np.array([0, 1, 2, 0], "float32"))])
        batch.bucket_key = seq_len
        bmod.forward(batch, is_train=True)
        bmod.backward()
        bmod.update()
        assert bmod.get_outputs()[0].shape == (4, 3)

    # parameters are shared handles: every bucket sees the updated weight
    w_default = bmod._buckets[10]._exec.arg_dict["shared_fc_weight"]
    w_7 = bmod._buckets[7]._exec.arg_dict["shared_fc_weight"]
    assert w_default is w_7


def test_module_multi_context_dp():
    """ctx list → SPMD batch sharding (the DataParallelExecutorGroup analog,
    SURVEY §2.3 row 1: grad allreduce becomes an XLA psum over the mesh)."""
    import jax
    ctxs = [mx.cpu(i) for i in range(len(jax.devices()))]
    x, y = _toy_dataset(n=128)
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label", last_batch_handle="discard")
    mod = mx.module.Module(_mlp_sym(), context=ctxs)
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1,
                              "rescale_grad": 1.0 / 32},
            initializer=mx.init.Xavier(), eval_metric="acc")
    res = dict(mod.score(mx.io.NDArrayIter(x, y, batch_size=32,
                                           label_name="softmax_label",
                                           last_batch_handle="discard"),
                         "acc"))
    assert res["accuracy"] > 0.9, res


def test_module_predict_and_pad():
    x, y = _toy_dataset(n=50)
    it = mx.io.NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Normal(0.1))
    out = mod.predict(it)
    assert out.shape == (50, 4)
    # score must strip pad rows: metric instance count == true sample count
    m = mx.metric.Accuracy()
    it.reset()
    mod.score(it, m)
    assert m.num_inst == 50


def test_checkpoint_exact_filename(tmp_path):
    """`<prefix>-NNNN.params` must exist under exactly that name."""
    import os
    prefix = str(tmp_path / "ck")
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Normal(0.1))
    mod.save_checkpoint(prefix, 7)
    assert os.path.exists(prefix + "-0007.params")
    assert os.path.exists(prefix + "-symbol.json")


def test_optimizer_state_roundtrip(tmp_path):
    """Momentum must survive save/load_optimizer_states (resume parity)."""
    prefix = str(tmp_path / "opt")
    x, y = _toy_dataset(n=64)
    it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / 32},
            initializer=mx.init.Xavier())
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
    mod2 = mx.module.Module.load(prefix, 2, load_optimizer_states=True)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9,
                                          "rescale_grad": 1.0 / 32})
    s1 = mod._updater_states["fc1_weight"]
    s2 = mod2._updater_states["fc1_weight"]
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)
    assert np.abs(np.asarray(s2)).sum() > 0
