"""Elastic auto-resume under chaos (tpu_mx/elastic.py + checkpoint.py).

The acceptance proof for ISSUE 2 lives here: a save killed mid-write (via
`crash_after_bytes`) must leave `auto_resume` restoring the last *verified*
checkpoint — a corrupt or truncated checkpoint is unreachable through the
elastic path, and `verify_checkpoint` names the torn file explicitly."""
import logging
import os
import pickle

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, nd
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn


def _dense(value):
    net = nn.Dense(3, in_units=4)
    net.initialize()
    net.weight.set_data(nd.full((3, 4), float(value)))
    net.bias.set_data(nd.full((3,), 0.0))
    return net


def _weight(net):
    return float(net.weight.data().asnumpy()[0, 0])


# ---------------------------------------------------------------------------
# the chaos recovery proof (acceptance criteria)
# ---------------------------------------------------------------------------
def test_crash_mid_save_auto_resume_recovers_previous_epoch(tmp_path):
    """Kill the epoch-2 save mid-write: epoch 1 must remain the newest
    verified checkpoint, and auto_resume restores IT — never the partial
    epoch-2 state."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net)
    assert ckpt.verify_checkpoint(prefix, 1)[0] == "verified"

    net.weight.set_data(nd.full((3, 4), 2.0))
    with chaos.enable(crash_after_bytes=100, match=".params") as cfg:
        with pytest.raises(chaos.ChaosCrash):
            mx.elastic.save_checkpoint(prefix, 2, net=net)
    assert cfg.crashes == 1
    # the crashed save left only tmp debris — no committed epoch-2 file
    assert not os.path.exists(f"{prefix}-0002.params")
    assert any(".tmp." in f for f in os.listdir(tmp_path))
    # epoch 2 is unreachable: latest is the verified epoch 1
    assert mx.elastic.latest_checkpoint(prefix)[0] == 1
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 2
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 1.0)


def test_torn_write_detected_and_skipped(tmp_path):
    """A torn write that os.replace COMMITS (short write + clean rename) is
    the nastier case: the file exists at full path with a manifest — the
    size/sha check must flag it and the elastic path must skip it."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net)
    net.weight.set_data(nd.full((3, 4), 2.0))
    with chaos.enable(torn_write=64, match=".params") as cfg:
        mx.elastic.save_checkpoint(prefix, 2, net=net)  # "succeeds"…
    assert cfg.tears >= 1
    status, problems = ckpt.verify_checkpoint(prefix, 2)
    assert status == "corrupt"
    assert any("torn" in p for p in problems), problems
    epoch, path = mx.elastic.latest_checkpoint(prefix)
    assert epoch == 1 and path.endswith("-0001.params")
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 2
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 1.0)


def test_manifestless_epoch_newer_than_manifested_is_skipped(tmp_path):
    """A save that dies between the params rename and the manifest commit
    leaves a VALID-looking manifest-less params file newer than the last
    manifested epoch.  It must be treated as an interrupted save and
    skipped — even though it would load — because its states/manifest
    never committed (the manifest is the commit point)."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net)
    net.weight.set_data(nd.full((3, 4), 2.0))
    net.save_parameters(f"{prefix}-0002.params")  # params landed, no manifest
    assert mx.elastic.latest_checkpoint(prefix)[0] == 1
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 2
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 1.0)


def test_auto_resume_raises_on_exhaustion_after_mutation(tmp_path):
    """If every candidate fails but a failed attempt already wrote into the
    net, auto_resume must raise — returning 0 ('fresh') over half-restored
    state would silently train from a partial mix."""
    from tpu_mx.base import MXNetError
    prefix = str(tmp_path / "ck")
    net, trainer = _trained_net_and_trainer(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net, trainer=trainer)
    # corrupt the ONLY epoch's states so it unpickles but fails to apply
    # (written durably + re-manifested so screening still says 'verified')
    with ckpt.atomic_write(f"{prefix}-0001.states") as f:
        f.write(pickle.dumps({"not": "a trainer payload"}))
    ckpt.write_manifest(prefix, 1,
                        [f"{prefix}-0001.params", f"{prefix}-0001.states"])
    assert ckpt.verify_checkpoint(prefix, 1)[0] == "verified"
    net2, trainer2 = _trained_net_and_trainer(5.0)
    with pytest.raises(MXNetError, match="re-initialize"):
        mx.elastic.auto_resume(prefix, net=net2, trainer=trainer2)


def test_truncated_legacy_checkpoint_falls_back_at_load(tmp_path):
    """The pre-durability failure mode, recreated by hand: a truncated
    manifest-less .params file is newest on disk.  Screening treats it as
    an interrupted save (older epochs have manifests) — and auto_resume
    falls back to the previous good epoch instead of crashing or loading
    garbage."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net)
    with open(f"{prefix}-0002.params", "wb") as f:
        f.write(b"PK\x03\x04 this is not a complete npz archive")
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 2
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 1.0)


# ---------------------------------------------------------------------------
# satellite: ≥5-digit epochs
# ---------------------------------------------------------------------------
def test_epoch_regex_accepts_five_plus_digits(tmp_path):
    prefix = str(tmp_path / "ck")
    net = _dense(3.0)
    for epoch in (9999, 10000, 123456):
        mx.elastic.save_checkpoint(prefix, epoch, net=net)
    assert os.path.exists(f"{prefix}-123456.params")  # %04d pads, not caps
    epoch, path = mx.elastic.latest_checkpoint(prefix)
    assert epoch == 123456 and path.endswith("-123456.params")
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 123457


# ---------------------------------------------------------------------------
# satellite: states validation before committing to an epoch
# ---------------------------------------------------------------------------
def _trained_net_and_trainer(value):
    from tpu_mx import autograd, gluon
    net = _dense(value)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.ones((2, 4))
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    trainer.step(2)
    return net, trainer


def test_auto_resume_validates_states_before_committing(tmp_path):
    """Epoch 2 has verified params but its .states file is garbage (written
    outside the durable path): with a trainer passed, auto_resume must fall
    back to epoch 1 BEFORE touching net state — no half-restore where the
    net holds epoch-2 weights and the trainer epoch-1 momenta."""
    prefix = str(tmp_path / "ck")
    net, trainer = _trained_net_and_trainer(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net, trainer=trainer)
    epoch1_w = net.weight.data().asnumpy().copy()

    net.weight.set_data(nd.full((3, 4), 2.0))
    mx.elastic.save_checkpoint(prefix, 2, net=net)  # params only
    with open(f"{prefix}-0002.states", "wb") as f:
        f.write(b"\x80\x04 truncated pickle garbage")

    net2, trainer2 = _trained_net_and_trainer(5.0)
    start = mx.elastic.auto_resume(prefix, net=net2, trainer=trainer2)
    assert start == 2  # fell back to epoch 1
    np.testing.assert_allclose(net2.weight.data().asnumpy(), epoch1_w)


def test_auto_resume_falls_back_when_states_fail_to_apply(tmp_path):
    """An epoch whose .states UNPICKLES but fails to APPLY (format drift:
    valid pickle, wrong payload shape) must also fall back — the
    no-half-restore contract covers apply failures, not just unpickling."""
    prefix = str(tmp_path / "ck")
    net, trainer = _trained_net_and_trainer(1.0)
    mx.elastic.save_checkpoint(prefix, 1, net=net, trainer=trainer)
    epoch1_w = net.weight.data().asnumpy().copy()

    net.weight.set_data(nd.full((3, 4), 2.0))
    mx.elastic.save_checkpoint(prefix, 2, net=net)
    with open(f"{prefix}-0002.states", "wb") as f:
        f.write(pickle.dumps({"not": "a trainer payload"}))  # valid pickle

    net2, trainer2 = _trained_net_and_trainer(5.0)
    start = mx.elastic.auto_resume(prefix, net=net2, trainer=trainer2)
    assert start == 2  # fell back to epoch 1, params re-overwritten
    np.testing.assert_allclose(net2.weight.data().asnumpy(), epoch1_w)


def test_auto_resume_with_valid_states_restores_trainer(tmp_path):
    prefix = str(tmp_path / "ck")
    net, trainer = _trained_net_and_trainer(1.0)
    num_update = trainer._optimizer.num_update
    mx.elastic.save_checkpoint(prefix, 3, net=net, trainer=trainer)
    assert ckpt.verify_checkpoint(prefix, 3)[0] == "verified"
    man = ckpt.read_manifest(prefix, 3)
    assert set(man["files"]) == {"ck-0003.params", "ck-0003.states"}

    net2, trainer2 = _trained_net_and_trainer(9.0)
    assert mx.elastic.auto_resume(prefix, net=net2, trainer=trainer2) == 4
    np.testing.assert_allclose(net2.weight.data().asnumpy(),
                               net.weight.data().asnumpy())
    assert trainer2._optimizer.num_update == num_update


# ---------------------------------------------------------------------------
# legacy (manifest-less) checkpoints keep loading, with a warning
# ---------------------------------------------------------------------------
def test_legacy_manifestless_checkpoint_loads_with_warning(tmp_path, caplog):
    prefix = str(tmp_path / "ck")
    net = _dense(4.0)
    net.save_parameters(f"{prefix}-0005.params")  # bare pre-durability save
    with caplog.at_level(logging.WARNING, logger="tpu_mx.elastic"):
        epoch, path = mx.elastic.latest_checkpoint(prefix)
        net2 = nn.Dense(3, in_units=4)
        start = mx.elastic.auto_resume(prefix, net=net2)
    assert (epoch, start) == (5, 6)
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 4.0)
    assert any("no manifest" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# durable save: retry/retention integration
# ---------------------------------------------------------------------------
def test_save_checkpoint_retries_transient_oserrors(tmp_path, monkeypatch):
    monkeypatch.setattr(ckpt.time, "sleep", lambda s: None)
    prefix = str(tmp_path / "ck")
    net = _dense(6.0)
    with chaos.enable(transient_oserror=2) as cfg:
        mx.elastic.save_checkpoint(prefix, 1, net=net)
    assert cfg.oserrors_fired == 2
    assert ckpt.verify_checkpoint(prefix, 1)[0] == "verified"


def test_save_checkpoint_retention_keeps_k(tmp_path):
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    for epoch in (1, 2, 3, 4):
        mx.elastic.save_checkpoint(prefix, epoch, net=net, keep_last=2)
    assert ckpt.list_epochs(prefix) == [3, 4]
    assert mx.elastic.latest_checkpoint(prefix)[0] == 4


# ---------------------------------------------------------------------------
# chaos kill_peer: the barrier failure path without a 2-process run
# ---------------------------------------------------------------------------
def test_barrier_kill_peer_chaos_raises_worker_failure():
    with chaos.enable(kill_peer=True):
        with pytest.raises(mx.elastic.WorkerFailure, match="resume"):
            mx.elastic.barrier("chaos-epoch", timeout=5)
    mx.elastic.barrier("chaos-epoch", timeout=5)  # disarmed: no-op again


def test_recovery_loop_pattern_with_kill_peer(tmp_path):
    """The documented supervisor pattern (docs/robustness.md): barrier
    raises WorkerFailure -> save what we have -> exit for restart ->
    restarted run auto_resumes the saved epoch."""
    prefix = str(tmp_path / "ck")
    net = _dense(1.0)
    completed = 0
    try:
        for epoch in (1, 2):
            net.weight.set_data(nd.full((3, 4), float(epoch * 10)))
            mx.elastic.save_checkpoint(prefix, epoch, net=net)
            completed = epoch
            if epoch == 2:
                with chaos.enable(kill_peer=True):
                    mx.elastic.barrier("epoch-end", timeout=5)
    except mx.elastic.WorkerFailure:
        pass
    assert completed == 2
    # "restarted" process:
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 3
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 20.0)
