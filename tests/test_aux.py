"""Aux subsystems: profiler, monitor, runtime features, engine API
(reference test analog: tests/python/unittest/test_profiler.py,
test_engine.py)."""
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd


def test_profiler_scope_and_dumps(tmp_path):
    fname = str(tmp_path / "profile.json")
    mx.profiler.set_config(filename=fname, profile_all=True)
    mx.profiler.set_state("run")
    with mx.profiler.scope("matmul_region"):
        a = nd.array(np.random.rand(32, 32).astype(np.float32))
        b = nd.dot(a, a)
        b.wait_to_read()
    task = mx.profiler.Task("mytask")
    task.start()
    task.stop()
    c = mx.profiler.Counter("imgs", value=0)
    c.increment(5)
    mx.profiler.Marker("tick").mark()
    mx.profiler.set_state("stop")
    assert os.path.exists(fname)
    table = mx.profiler.dumps()
    assert "matmul_region" in table
    assert "mytask" in table


def test_profiler_pause_resume(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "p.json"))
    mx.profiler.set_state("run")
    mx.profiler.pause()
    with mx.profiler.scope("hidden"):
        pass
    mx.profiler.resume()
    with mx.profiler.scope("visible"):
        pass
    mx.profiler.set_state("stop")
    table = mx.profiler.dumps(reset=True)
    assert "visible" in table and "hidden" not in table


def test_monitor_records_stats():
    from tpu_mx import gluon
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    mon = mx.monitor.Monitor(interval=2, pattern=".*")
    mon.install(net)
    x = nd.array(np.random.rand(2, 16).astype(np.float32))
    seen = []
    for _ in range(4):
        mon.tic()
        net(x)
        seen.append(mon.toc())
    # interval=2: batches 0 and 2 record, 1 and 3 do not
    assert len(seen[0]) > 0 and len(seen[2]) > 0
    assert seen[1] == [] and seen[3] == []
    step, name, stat = seen[0][0]
    assert isinstance(stat, float) and np.isfinite(stat)


def test_monitor_pattern_filter():
    from tpu_mx import gluon
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    mon = mx.monitor.Monitor(interval=1, pattern="nomatch_.*")
    mon.install(net)
    mon.tic()
    net(nd.array(np.random.rand(2, 8).astype(np.float32)))
    assert mon.toc() == []


def test_runtime_feature_list():
    feats = mx.runtime.feature_list()
    assert feats
    names = {f.name for f in feats}
    assert {"JAX", "CPU", "PROFILER"} <= names
    features = mx.runtime.Features()
    assert features.is_enabled("JAX")


def test_engine_api():
    assert mx.engine.engine_type() == "JaxAsyncDispatch"
    prev = mx.engine.set_bulk_size(32)
    assert mx.engine.set_bulk_size(prev) == 32
    with mx.engine.bulk(64):
        a = nd.array(np.ones((4, 4), np.float32))
        b = a * 2
    mx.engine.wait_for_all()
    np.testing.assert_allclose(b.asnumpy(), 2.0)


def test_monitor_uninstall():
    from tpu_mx import gluon
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    mon = mx.monitor.Monitor(interval=1)
    mon.install(net)
    mon.install(net)  # double install -> duplicated hooks until uninstall
    mon.uninstall()
    mon.tic()
    net(nd.array(np.random.rand(2, 8).astype(np.float32)))
    assert mon.toc() == []


def test_profiler_new_session_clears_events(tmp_path):
    f1, f2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    import json
    mx.profiler.set_config(filename=f1)
    mx.profiler.set_state("run")
    with mx.profiler.scope("first"):
        pass
    mx.profiler.set_state("stop")
    mx.profiler.set_config(filename=f2)
    mx.profiler.set_state("run")
    with mx.profiler.scope("second"):
        pass
    mx.profiler.set_state("stop")
    names = {e["name"] for e in json.load(open(f2))["traceEvents"]}
    assert "second" in names and "first" not in names


def test_lbsgd_trains():
    from tpu_mx import gluon, autograd
    net = gluon.nn.Dense(4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "lbsgd",
                            {"learning_rate": 0.5, "momentum": 0.9,
                             "warmup_epochs": 1, "updates_per_epoch": 2})
    X = np.random.RandomState(0).rand(16, 8).astype(np.float32)
    losses = []
    for _ in range(10):
        with autograd.record():
            loss = (net(nd.array(X)) ** 2).mean()
        loss.backward()
        trainer.step(16)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]


def test_inception_v3_registered():
    from tpu_mx.gluon.model_zoo import vision
    assert "inception_v3" in [m for m in vision.get_model.__globals__["_models"]]


def test_engine_push_async_hook():
    """The Horovod-era external-op injection point (MXEnginePushAsync
    analog): fn sees settled reads and can rebind writes."""
    import numpy as np
    from tpu_mx import engine, nd

    a = nd.array(np.array([1.0, 2.0], np.float32))
    out = nd.zeros((2,))

    def external(reads, writes):
        writes[0]._rebind((reads[0] * 3)._data)
        return "ok"

    assert engine.push_async(external, [a], [out]) == "ok"
    np.testing.assert_allclose(out.asnumpy(), [3.0, 6.0])
    assert engine.push_sync is engine.push_async


def test_persistent_compilation_cache(tmp_path):
    """runtime.set_compilation_cache writes program artifacts that a fresh
    process would reuse (cache dir gains entries after a novel compile)."""
    import jax
    import jax.numpy as jnp
    from tpu_mx import runtime
    d = tmp_path / "xla_cache"
    runtime.set_compilation_cache(str(d), min_compile_time_secs=0.0)
    try:
        @jax.jit
        def f(x):
            return (x @ x.T).sum() + 12345.678  # novel constant -> novel key
        f(jnp.ones((64, 64))).block_until_ready()
        entries = list(d.rglob("*")) if d.exists() else []
        assert entries, "no cache entries written"
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_mcc_and_nll_metrics():
    import tpu_mx.metric as M
    m = M.MCC()
    m.update([np.array([1, 1, 0, 0])], [np.array([0.9, 0.8, 0.2, 0.6])])
    assert abs(m.get()[1] - 2 / np.sqrt(12)) < 1e-6
    m.reset()
    assert m.get()[1] != m.get()[1] or m.num_inst == 0  # nan or empty
    nll = M.NegativeLogLikelihood()
    nll.update([np.array([0, 1])], [np.array([[0.9, 0.1], [0.4, 0.6]])])
    assert abs(nll.get()[1] -
               (-np.log(0.9) - np.log(0.6)) / 2) < 1e-6
    # registry creation by name
    assert mx.metric.create("mcc").name == "mcc"
    assert mx.metric.create("nll-loss").name == "nll-loss"


def test_mixed_and_load_initializers():
    import tpu_mx.initializer as I
    from tpu_mx.gluon import nn
    from tpu_mx import nd
    mix = I.Mixed([".*bias", ".*"], [I.Zero(), I.Constant(2.0)])
    net = nn.Dense(3, in_units=2)
    net.initialize(init=mix)
    assert (net.bias.data().asnumpy() == 0).all()
    assert (net.weight.data().asnumpy() == 2.0).all()
    ld = I.Load({"w": np.arange(4.0)}, default_init=I.Zero())
    assert (ld("w", (4,)) == np.arange(4.0)).all()
    import pytest as _pytest
    with _pytest.raises(ValueError, match="shape mismatch"):
        ld("w", (5,))


def test_device_init_samples_on_device():
    """Standard initializers sample with the device PRNG (no host numpy
    transfer), driven by mx.random.seed; see initializer.device_sample."""
    import jax
    import tpu_mx as mx
    import tpu_mx.initializer as I
    from tpu_mx.gluon import nn

    def build():
        mx.random.seed(7)
        net = nn.Dense(8, in_units=16)
        net.initialize(init="xavier")
        return net.weight.data().asnumpy(), net.bias.data().asnumpy()

    w1, b1 = build()
    w2, _ = build()
    assert (w1 == w2).all()          # device PRNG is mx.random.seed-driven
    assert (b1 == 0).all()           # name-dispatch: bias -> 0
    # xavier-uniform bounds: scale = sqrt(3 / avg_fan(16,8)) = 0.5
    assert abs(w1).max() <= 0.5 and abs(w1).std() > 0.05

    # direct surface: jax array of the requested dtype; aux names get
    # their convention constants
    out = I.Xavier().device_sample("blk_weight", (4, 8), "bfloat16")
    assert isinstance(out, jax.Array) and str(out.dtype) == "bfloat16"
    var = I.Xavier().device_sample("bn_running_var", (4,))
    assert (np.asarray(var) == 1.0).all()

    # no device rule / custom __call__ semantics -> host path (None)
    assert I.Orthogonal().device_sample("w", (4, 4)) is None
    assert I.Bilinear().device_sample("w", (1, 1, 4, 4)) is None
    assert I.LSTMBias().device_sample("h2h_bias", (8,)) is None
    # LSTMBias host path still sets the forget-gate block to 1
    b = I.LSTMBias()("h2h_bias", (8,))
    assert (b[2:4] == 1.0).all() and b.sum() == 2.0


def test_hybrid_first_call_deferred_init_no_tracer_leak():
    """Deferred init firing INSIDE the hybridize trace must fall back to
    the host path: device sampling (even jnp.full for aux params) would
    stage into the jaxpr and leave a tracer in Parameter._data."""
    import jax
    from tpu_mx import nd
    from tpu_mx.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    net.hybridize()
    out1 = net(nd.ones((2, 4)))  # params finalize inside this trace
    for p in net.collect_params().values():
        assert not isinstance(p.data()._data, jax.core.Tracer), p.name
    out2 = net(nd.ones((2, 4)))  # cached program, concrete params
    np.testing.assert_array_equal(out1.asnumpy(), out2.asnumpy())


def test_device_init_host_revert_knob(monkeypatch):
    import tpu_mx.initializer as I
    monkeypatch.setenv("TPUMX_HOST_INIT", "1")
    assert I.Xavier().device_sample("w", (2, 2)) is None
    monkeypatch.delenv("TPUMX_HOST_INIT")
    assert I.Xavier().device_sample("w", (2, 2)) is not None


def test_symbolic_check_helpers_and_tensorrt_stub():
    import tpu_mx.test_utils as T
    x = mx.sym.Variable("x")
    y = x * 2.0 + 1.0
    T.check_symbolic_forward(y, [np.array([1.0, 2.0], np.float32)],
                             [np.array([3.0, 5.0], np.float32)])
    T.check_symbolic_backward(y, [np.array([1.0, 2.0], np.float32)],
                              [np.ones(2, np.float32)],
                              [np.full(2, 2.0, np.float32)])
    T.assert_exception(lambda: 1 / 0, ZeroDivisionError)
    s2 = T.rand_shape_2d(5, 5)
    assert len(s2) == 2 and all(1 <= v <= 5 for v in s2)
    from tpu_mx.contrib import tensorrt
    with pytest.raises(mx.MXNetError, match="StableHLO"):
        tensorrt.optimize_graph(None)


def test_speedometer_and_do_checkpoint(tmp_path, caplog):
    """callback.Speedometer logs throughput; do_checkpoint saves epoch
    params loadable via model.load_checkpoint (REF callback.py/model.py)."""
    import logging
    from tpu_mx import callback, model as model_mod, nd
    from tpu_mx.gluon import nn

    class Batch:
        pass

    sp = callback.Speedometer(batch_size=32, frequent=2, auto_reset=False)
    p = Batch()
    p.epoch, p.nbatch, p.eval_metric = 0, 2, None
    with caplog.at_level(logging.INFO):
        sp(p)       # first call arms the timer
        p.nbatch = 4
        sp(p)       # second hits count %% frequent == 0 and logs
    assert any("Speed" in r.message or "samples/sec" in r.message
               for r in caplog.records), caplog.records

    net = nn.Dense(3, in_units=2)
    net.initialize()
    net(nd.ones((1, 2)))
    sym_name = str(tmp_path / "mm")
    # module-level checkpoint format helpers (reference filename contract)
    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                name="fc")
    args = {k: p_.data() for k, p_ in net.collect_params().items()}
    model_mod.save_checkpoint(sym_name, 3, sym, args, {})
    import os
    assert os.path.exists(sym_name + "-0003.params")
    loaded_sym, arg2, aux2 = model_mod.load_checkpoint(sym_name, 3)
    assert "fc" in [n for n in loaded_sym.get_internals().list_outputs()][0] \
        or loaded_sym is not None
    for k in args:
        np.testing.assert_allclose(arg2[k].asnumpy(), args[k].asnumpy())


def test_shared_compilation_cache_env_gate(monkeypatch, tmp_path):
    """enable_shared_compilation_cache: one env knob disables the cache
    for ALL on-chip tools; enabled path points at the repo .jax_cache."""
    from tpu_mx import runtime
    monkeypatch.setenv("BENCH_COMPILE_CACHE", "0")
    assert runtime.enable_shared_compilation_cache() is None
    monkeypatch.setenv("BENCH_COMPILE_CACHE", "1")
    d = runtime.enable_shared_compilation_cache()
    assert d is not None and d.endswith(".jax_cache")
