"""AMP tests (reference analog: tests/python/gpu/test_contrib_amp.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd, gluon, autograd
from tpu_mx.contrib import amp


@pytest.fixture
def amp_session():
    amp.init(target_dtype="bfloat16")
    yield
    from tpu_mx.contrib.amp.amp import _deinit
    _deinit()


def test_amp_casts_matmul_to_bf16(amp_session):
    a = nd.array(np.random.rand(8, 8).astype(np.float32))
    out = nd.dot(a, a)
    assert out.dtype == "bfloat16"
    # fp32 ops force float32 even on bf16 inputs
    s = nd.softmax(out, axis=-1)
    assert s.dtype == "float32"


def test_amp_widest_cast(amp_session):
    a = nd.array(np.random.rand(4, 4).astype(np.float32))
    b = a.astype("bfloat16")
    out = nd.concat(a, b, dim=0) if hasattr(nd, "concat") else nd.stack(a, b)
    assert out.dtype == "float32"


def test_loss_scaler_dynamics():
    s = amp.LossScaler(init_scale=16.0, scale_factor=2.0, scale_window=2,
                       target_dtype="float16")
    s.update_scale(overflow=True)
    assert s.loss_scale == 8.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 16.0
    b = amp.LossScaler(target_dtype="bfloat16")
    assert b.loss_scale == 1.0
    b.update_scale(True)
    assert b.loss_scale == 1.0


def test_amp_training_loop(amp_session):
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    amp.init_trainer(trainer)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    X = np.random.RandomState(0).rand(32, 8).astype(np.float32)
    Y = (X.sum(axis=1) > 4).astype(np.int32)
    losses = []
    for _ in range(8):
        with autograd.record():
            out = net(nd.array(X))
            loss = loss_fn(out, nd.array(Y))
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        trainer.step(32)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0]


def test_fp16_overflow_skips_step():
    net = gluon.nn.Dense(2)
    net.initialize()
    x = nd.array(np.random.rand(4, 4).astype(np.float32))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    from tpu_mx.contrib.amp import amp as amp_mod
    amp_mod._amp_state["target_dtype"] = "float16"
    try:
        amp.init_trainer(trainer)
    finally:
        amp_mod._amp_state["target_dtype"] = None
    scaler = trainer._amp_loss_scaler
    scale0 = scaler.loss_scale
    w0 = net.weight.data().asnumpy().copy()
    # poison a gradient with inf -> step must be skipped, scale halved
    g = net.weight.grad
    g._rebind(g._data.at[0, 0].set(np.inf))
    with pytest.warns(UserWarning, match="overflow"):
        trainer.step(4)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w0)
    assert scaler.loss_scale == scale0 / 2


def test_convert_model_keeps_norms_fp32():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8))
    net.add(gluon.nn.BatchNorm())
    net.initialize()
    net(nd.array(np.random.rand(2, 4).astype(np.float32)))
    amp.convert_model(net, target_dtype="bfloat16")
    assert net[0].weight.data().dtype == "bfloat16"
    assert net[1].gamma.data().dtype == "float32"


def test_convert_model_preserves_norm_values():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8))
    net.add(gluon.nn.BatchNorm())
    net.initialize()
    net(nd.array(np.random.rand(2, 4).astype(np.float32)))
    # give gamma values that do not survive a bf16 roundtrip
    gamma0 = np.full(8, 1.0009765625, np.float32)  # 1 + 2**-10
    net[1].gamma.set_data(nd.array(gamma0))
    amp.convert_model(net, target_dtype="bfloat16")
    np.testing.assert_array_equal(net[1].gamma.data().asnumpy(), gamma0)


def test_convert_model_excluded_sym_names():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    net(nd.array(np.random.rand(2, 4).astype(np.float32)))
    amp.convert_model(net, target_dtype="bfloat16", excluded_sym_names=["1"])
    assert net[0].weight.data().dtype == "bfloat16"
    assert net[1].weight.data().dtype == "float32"


def test_conditional_fp32_ops(amp_session):
    from tpu_mx.contrib.amp.amp import _deinit
    _deinit()
    amp.init(target_dtype="bfloat16",
             conditional_fp32_ops=[("Activation", "act_type", ["softsign"])])
    x = nd.array(np.random.rand(4, 4).astype(np.bfloat16)) \
        if hasattr(np, "bfloat16") else \
        nd.array(np.random.rand(4, 4).astype(np.float32)).astype("bfloat16")
    out = nd.Activation(x, act_type="softsign")
    assert out.dtype == "float32"
    out2 = nd.Activation(x, act_type="relu")
    assert out2.dtype == "bfloat16"


def test_hook_handle_detach():
    from tpu_mx.gluon.block import HookHandle
    calls = []
    net = gluon.nn.Dense(2)
    net.initialize()
    h = net.register_forward_hook(lambda blk, ins, out: calls.append(1))
    assert isinstance(h, HookHandle)
    net(nd.array(np.random.rand(2, 3).astype(np.float32)))
    h.remove()
    net(nd.array(np.random.rand(2, 3).astype(np.float32)))
    assert len(calls) == 1


def test_amp_kwarg_call_is_cast(amp_session):
    x = nd.array(np.random.rand(4, 4).astype(np.float32)).astype("bfloat16")
    out = nd.softmax(data=x, axis=-1)
    assert out.dtype == "float32"


def test_conditional_fp32_positional(amp_session):
    from tpu_mx.contrib.amp.amp import _deinit
    _deinit()
    amp.init(target_dtype="bfloat16",
             conditional_fp32_ops=[("Activation", "act_type", ["softsign"])])
    x = nd.array(np.random.rand(4, 4).astype(np.float32)).astype("bfloat16")
    out = nd.Activation(x, "softsign")
    assert out.dtype == "float32"


def test_amp_reinit_warns(amp_session):
    with pytest.warns(UserWarning, match="already ran"):
        amp.init(target_dtype="float16")


def test_convert_model_excluded_container():
    net = gluon.nn.Sequential()
    sub = gluon.nn.Sequential()
    sub.add(gluon.nn.Dense(8))
    net.add(sub)
    net.add(gluon.nn.Dense(4))
    net.initialize()
    net(nd.array(np.random.rand(2, 4).astype(np.float32)))
    amp.convert_model(net, target_dtype="bfloat16", excluded_sym_names=["0"])
    assert net[0][0].weight.data().dtype == "float32"
    assert net[1].weight.data().dtype == "bfloat16"
