"""On-chip test tier (`pytest -m tpu`): the kernel-tail checks that CPU
interpret mode cannot prove (VERDICT r3 weak#4 — real Mosaic enforces
constraints the interpreter does not; r2's PRNG seed-limit bug is the
canonical example).

These wrap tools/tpu_validate.py's check functions as pytest nodes;
tools/tpu_watch.py runs the same checks via the validate CLI and records
TPU_VALIDATION_r04.json.  The default conftest pins tests to CPU (the
chip serializes processes), so run the tier as:

    TPUMX_TEST_TPU=1 python -m pytest tests/ -m tpu

which skips the CPU pin; without the env var (or off-chip) every check
skips rather than green-washing.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

pytestmark = pytest.mark.tpu


def _on_tpu():
    import jax
    return jax.devices()[0].platform == "tpu"


@pytest.fixture(scope="module")
def tpu():
    if not _on_tpu():
        pytest.skip("no TPU backend in this process")


import tpu_validate as tv  # noqa: E402


@pytest.mark.parametrize("name,fn", tv.CHECKS,
                         ids=[n for n, _ in tv.CHECKS])
def test_chip_check(tpu, name, fn):
    fn()
