"""Gluon block/layer tests (model: REF:tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.gluon import nn
from tpu_mx.test_utils import assert_almost_equal


def test_dense_forward_deferred_init():
    net = nn.Dense(4, use_bias=True)
    net.initialize()
    x = nd.array(np.random.rand(2, 3).astype(np.float32))
    y = net(x)
    assert y.shape == (2, 4)
    assert net.weight.shape == (4, 3)
    manual = x.asnumpy() @ net.weight.data().asnumpy().T + \
        net.bias.data().asnumpy()
    assert_almost_equal(y, manual, rtol=1e-5)


def test_dense_flatten():
    net = nn.Dense(5, flatten=True)
    net.initialize()
    y = net(nd.ones((2, 3, 4)))
    assert y.shape == (2, 5)
    net2 = nn.Dense(5, flatten=False)
    net2.initialize()
    assert net2(nd.ones((2, 3, 4))).shape == (2, 3, 5)


def test_uninitialized_raises():
    net = nn.Dense(4)
    with pytest.raises(mx.MXNetError):
        net(nd.ones((2, 3)))


def test_conv_layers():
    net = nn.Conv2D(8, kernel_size=3, strides=2, padding=1)
    net.initialize()
    y = net(nd.ones((2, 3, 16, 16)))
    assert y.shape == (2, 8, 8, 8)
    assert net.weight.shape == (8, 3, 3, 3)
    net1d = nn.Conv1D(4, kernel_size=3)
    net1d.initialize()
    assert net1d(nd.ones((2, 3, 10))).shape == (2, 4, 8)


def test_pool_layers():
    assert nn.MaxPool2D(2)(nd.ones((1, 2, 8, 8))).shape == (1, 2, 4, 4)
    assert nn.AvgPool2D(2)(nd.ones((1, 2, 8, 8))).shape == (1, 2, 4, 4)
    assert nn.GlobalAvgPool2D()(nd.ones((1, 2, 8, 8))).shape == (1, 2, 1, 1)
    # avg pooling value correctness
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    y = nn.AvgPool2D(2)(x)
    assert_almost_equal(y, np.array([[[[2.5, 4.5], [10.5, 12.5]]]]))


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = nd.array(np.random.rand(4, 3, 5, 5).astype(np.float32) * 10)
    with autograd.record():
        y = bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0)  # stats updated in training
    y_inf = bn(x)  # inference path uses running stats
    assert y_inf.shape == x.shape


def test_dropout_train_vs_eval():
    do = nn.Dropout(0.5)
    x = nd.ones((100, 100))
    y_eval = do(x)
    assert_almost_equal(y_eval, x.asnumpy())  # identity at inference
    with autograd.record():
        y_train = do(x)
    frac_zero = float((y_train.asnumpy() == 0).mean())
    assert 0.3 < frac_zero < 0.7


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = nd.array(np.array([1, 3, 5]), dtype="int32")
    y = emb(idx)
    assert y.shape == (3, 4)
    assert_almost_equal(y, emb.weight.data().asnumpy()[[1, 3, 5]])


def test_sequential_and_getitem():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    assert len(net) == 2
    assert isinstance(net[0], nn.Dense)
    y = net(nd.ones((2, 3)))
    assert y.shape == (2, 4)


def test_hybridize_consistency():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8))
    net.initialize()
    x = nd.array(np.random.rand(4, 10).astype(np.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    assert_almost_equal(y_eager, y_hybrid, rtol=1e-5)


def test_hybrid_training_matches_eager():
    def build():
        mx.random.seed(7)  # init is device-PRNG-driven (r5); np seed alone
        net = nn.HybridSequential()  # no longer pins parameter values
        net.add(nn.Dense(8, activation="tanh"), nn.Dense(4))
        net.initialize()
        # finalize deferred shapes EAGERLY so both builds take the
        # device-PRNG init path; a trace-time finalize falls back to the
        # host RNG (docs/DIVERGENCES.md #23) and the params would differ
        net(x)
        return net

    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    lbl = nd.array(np.array([0, 1, 2, 3]), dtype="float32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    grads = []
    for hybrid in (False, True):
        net = build()
        if hybrid:
            net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        with autograd.record():
            loss = loss_fn(net(x), lbl).mean()
        loss.backward()
        g = {k: p.grad.asnumpy().copy()
             for k, p in net.collect_params().items()}
        grads.append(g)
    # align by insertion order: numeric name suffixes sort inconsistently
    # across digit boundaries (dense9 vs dense10)
    for (k1, g1), (k2, g2) in zip(list(grads[0].items()),
                                  list(grads[1].items())):
        assert_almost_equal(g1, g2, rtol=1e-4, atol=1e-5)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4))
    net.initialize()
    net(nd.ones((1, 3)))
    f = str(tmp_path / "p.npz")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8), nn.Dense(4))
    net2.load_parameters(f)
    assert_almost_equal(net2(nd.ones((1, 3))), net(nd.ones((1, 3))))


def test_trainer_sgd_step():
    net = nn.Dense(2, use_bias=False)
    net.initialize(init="ones")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    x = nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=1)
    # dL/dW = x broadcast to (2,2) of ones; W_new = 1 - 1*1 = 0
    assert_almost_equal(net.weight.data(), np.zeros((2, 2)))


def test_losses_values():
    l2 = gluon.loss.L2Loss()
    pred = nd.array([[1.0, 2.0]])
    label = nd.array([[0.0, 0.0]])
    assert_almost_equal(l2(pred, label), np.array([1.25]))  # mean(sq)/2
    l1 = gluon.loss.L1Loss()
    assert_almost_equal(l1(pred, label), np.array([1.5]))
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    logits = nd.array([[10.0, 0.0], [0.0, 10.0]])
    labels = nd.array([0, 1], dtype="float32")
    assert float(sce(logits, labels).mean().asscalar()) < 0.01
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    p = nd.array([[100.0], [-100.0]])
    t = nd.array([[1.0], [0.0]])
    assert float(bce(p, t).mean().asscalar()) < 1e-5


def test_constant_param():
    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.const = self.params.get_constant("const",
                                                  np.array([2.0, 3.0]))

        def hybrid_forward(self, F, x, const):
            return x * const

    net = Net()
    net.initialize()
    y = net(nd.ones((2,)))
    assert_almost_equal(y, np.array([2.0, 3.0]))


def test_grad_req_null_excluded():
    net = nn.Dense(2)
    net.initialize()
    net.weight.grad_req = "null"
    net(nd.ones((1, 2)))
    tr = gluon.Trainer(net.collect_params(), "sgd")
    assert len(tr._params) == 1  # only bias


def test_model_zoo_lenet():
    from tpu_mx.models import lenet
    net = lenet()
    net.initialize()
    y = net(nd.ones((2, 1, 28, 28)))
    assert y.shape == (2, 10)


def test_clip_global_norm():
    arrays = [nd.array([3.0]), nd.array([4.0])]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    assert abs(norm - 5.0) < 1e-5
    total = np.sqrt(sum(float((a.asnumpy() ** 2).sum()) for a in arrays))
    assert abs(total - 1.0) < 1e-4


def test_split_and_load():
    data = nd.arange(0, 8).reshape(8, 1)
    parts = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(0)])
    assert len(parts) == 2 and parts[0].shape == (4, 1)


@pytest.mark.slow
def test_trainer_fused_matches_per_param():
    """Fused multi-tensor update must be numerically identical to the
    per-parameter loop (reference multi_sgd vs sgd_update equivalence)."""
    import numpy as np
    from tpu_mx import nd, autograd, gluon

    def build_and_train(fuse, opt_name, opt_kw):
        mx.random.seed(0)  # device-PRNG init (r5): np seed alone no
        net = gluon.nn.Sequential()  # longer pins parameter values
        net.add(gluon.nn.Dense(16, activation="relu", in_units=8))
        net.add(gluon.nn.Dense(4, in_units=16))
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), opt_name, dict(opt_kw),
                                fuse_update=fuse)
        X = np.random.RandomState(1).rand(8, 8).astype(np.float32)
        for _ in range(4):
            with autograd.record():
                loss = (net(nd.array(X)) ** 2).mean()
            loss.backward()
            trainer.step(8)
        return [p.data().asnumpy() for p in net.collect_params().values()]

    for opt_name, kw in [("sgd", {"learning_rate": 0.1, "momentum": 0.9,
                                  "wd": 1e-3}),
                         ("adam", {"learning_rate": 0.01})]:
        fused = build_and_train(True, opt_name, kw)
        loop = build_and_train(False, opt_name, kw)
        for a, b in zip(fused, loop):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6,
                                       err_msg=opt_name)


def test_trainer_fused_multi_precision():
    import numpy as np
    from tpu_mx import nd, autograd, gluon
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, in_units=4))
    net.add(gluon.nn.Dense(2, in_units=8))
    net.initialize()
    net.cast("bfloat16")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9,
                             "multi_precision": True})
    X = np.random.RandomState(0).rand(4, 4).astype(np.float32)
    losses = []
    for _ in range(6):
        with autograd.record():
            loss = (net(nd.cast(nd.array(X), "bfloat16")).astype("float32")
                    ** 2).mean()
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]
    # master copies live in the fused states as fp32
    st = trainer._states[0]
    assert st[0].dtype == "float32"


def test_remat_grads_match_and_checkpoint_traced():
    """block.remat(): jax.checkpoint wraps the child segment inside the
    compiled trace — gradients must be bit-comparable to the non-remat
    run, BN running stats must still update, and the remat primitive must
    actually appear in the jaxpr (i.e. the flag is not a no-op)."""
    import jax
    import jax.numpy as jnp
    import tpu_mx as mx
    from tpu_mx import nd, autograd, gluon
    from tpu_mx.gluon import nn

    def build(remat):
        mx.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            for _ in range(2):
                blk = nn.HybridSequential()
                with blk.name_scope():
                    blk.add(nn.Dense(16, in_units=16))
                    blk.add(nn.BatchNorm(in_channels=16))
                    blk.add(nn.Activation("relu"))
                if remat:
                    blk.remat()
                net.add(blk)
        net.initialize()
        net.hybridize()
        return net

    x = nd.array(np.random.RandomState(0).randn(4, 16).astype(np.float32))

    def run(net):
        xx = x.copy()
        xx.attach_grad()
        with autograd.record():
            y = net(xx)
            loss = y.square().sum()
        loss.backward()
        grads = {k: np.asarray(p.grad._data)
                 for k, p in net.collect_params().items()
                 if p.grad_req != "null"}
        return np.asarray(loss._data), grads, np.asarray(xx.grad._data)

    # same net both runs (init draws are name-keyed; a fresh build would
    # differ for reasons unrelated to remat) — toggle the flag + re-trace
    net = build(remat=False)
    l0, g0, xg0 = run(net)
    for blk in net._children.values():
        blk.remat()
    net.hybridize()  # drop the cached non-remat trace
    l1, g1, xg1 = run(net)
    assert np.allclose(l0, l1, rtol=1e-5, atol=1e-5)
    assert np.allclose(xg0, xg1, rtol=1e-5, atol=1e-5)
    assert sorted(g0) == sorted(g1)
    for k in g0:
        assert np.allclose(g0[k], g1[k], rtol=1e-5, atol=1e-5), k

    # BN running stats updated on the remat path too
    net = build(remat=True)
    bn = [c for blk in net._children.values()
          for c in blk._children.values()
          if isinstance(c, nn.BatchNorm)][0]
    before = np.asarray(bn.running_mean.data()._data).copy()
    with autograd.record():
        net(x).sum().backward()
    after = np.asarray(bn.running_mean.data()._data)
    assert not np.allclose(before, after)

    # the checkpoint (remat) primitive must be in the traced jaxpr
    net2 = build(remat=True)
    params = {k: p.data()._data for k, p in net2.collect_params().items()}
    jaxpr = jax.make_jaxpr(
        lambda pm, xx: net2._functional_call(pm, jax.random.PRNGKey(0),
                                             True, (xx,))[0])(params, x._data)
    assert "remat" in str(jaxpr) or "checkpoint" in str(jaxpr)


def test_groupnorm_matches_torch_semantics():
    """nn.GroupNorm vs the manual group-stat computation, fwd + grads."""
    from tpu_mx.gluon import nn as gnn
    gn = gnn.GroupNorm(num_groups=2)
    gn.initialize()
    assert gn.gamma.shape == (2,)  # per-GROUP affine, reference contract
    x = np.random.RandomState(0).randn(2, 4, 3, 3).astype(np.float32)
    out = np.asarray(gn(nd.array(x))._data)
    xf = x.reshape(2, 2, -1)
    mu = xf.mean(axis=2, keepdims=True)
    var = xf.var(axis=2, keepdims=True)
    ref = ((xf - mu) / np.sqrt(var + 1e-5)).reshape(x.shape)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    # grads flow to gamma/beta
    xx = nd.array(x)
    with autograd.record():
        y = gn(xx).square().sum()
    y.backward()
    assert float(np.abs(np.asarray(gn.gamma.grad._data)).max()) > 0
    # divisibility guard
    bad = gnn.GroupNorm(num_groups=3)
    bad.initialize()
    with pytest.raises(mx.base.MXNetError, match="divisible"):
        bad(nd.array(x))


def test_poisson_nll_loss():
    from tpu_mx.gluon.loss import PoissonNLLLoss
    pred = nd.array(np.array([[0.5], [1.0]]))  # log-rates
    label = nd.array(np.array([[1.0], [2.0]]))
    l = PoissonNLLLoss(from_logits=True)(pred, label)
    ref = (np.exp([0.5, 1.0]) - np.array([1.0, 2.0]) *
           np.array([0.5, 1.0])).mean()
    np.testing.assert_allclose(float(l.asscalar()), ref, rtol=1e-5)
    # rate-space path + grads
    rate = nd.array(np.array([[2.0], [0.5]]))
    rate.attach_grad()
    with autograd.record():
        l2 = PoissonNLLLoss(from_logits=False)(rate, label)
    l2.backward()
    assert np.isfinite(rate.grad.asnumpy()).all()
    full = PoissonNLLLoss(from_logits=True, compute_full=True)(pred, label)
    assert float(full.asscalar()) > float(l.asscalar())  # stirling adds


def test_reflectionpad_and_conv3dtranspose():
    rp = nn.ReflectionPad2D(1)
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    y = rp(x)
    ref = np.pad(np.asarray(x._data), ((0, 0), (0, 0), (1, 1), (1, 1)),
                 mode="reflect")
    np.testing.assert_array_equal(np.asarray(y._data), ref)
    # grads flow through the pad
    xx = nd.array(np.random.rand(1, 1, 4, 4).astype(np.float32))
    xx.attach_grad()
    with autograd.record():
        rp(xx).square().sum().backward()
    assert np.isfinite(np.asarray(xx.grad._data)).all()

    ct = nn.Conv3DTranspose(4, 3, in_channels=2)
    ct.initialize()
    assert ct(nd.ones((1, 2, 4, 4, 4))).shape == (1, 4, 6, 6, 6)


def test_infer_shape_container_propagates():
    """HybridBlock.infer_shape on a container finalizes every child's
    deferred-shape params without the user running a forward themselves
    (VERDICT r3 weak#3: was a dead no-op loop)."""
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    dense0 = net._children["0"]
    assert dense0.weight._shape_incomplete()
    net.infer_shape(nd.ones((2, 5)))
    assert dense0.weight.shape == (8, 5)
    assert net._children["1"].weight.shape == (3, 8)
    # and a subsequent forward uses the finalized params
    assert net(nd.ones((2, 5))).shape == (2, 3)


def test_infer_shape_custom_block_without_override_raises():
    from tpu_mx.base import MXNetError

    class Custom(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.w = self.params.get("w", shape=(4, 0),
                                         allow_deferred_init=True)

        def hybrid_forward(self, F, x, w):
            return F.dot(x, w.T if hasattr(w, "T") else w)

    c = Custom()
    c.initialize()
    with pytest.raises(MXNetError, match="infer_shape"):
        c(nd.ones((2, 5)))


@pytest.mark.slow
def test_bert_remat_policy_grads_match():
    """remat_policy (save-dots vs recompute-all) changes memory/FLOPs,
    never numerics: grads match the no-remat model."""
    import jax
    from tpu_mx.models.bert import BERTModel, bert_base_config
    cfg = bert_base_config(vocab_size=64, max_len=32)
    cfg.update(num_layers=2, units=32, hidden_size=64, num_heads=2)
    toks = nd.array(np.random.RandomState(0).randint(4, 64, (2, 16)),
                    dtype="int32")
    types = nd.zeros((2, 16), dtype="int32")

    def grads(**kw):
        mx.random.seed(0)
        np.random.seed(0)
        net = BERTModel(cfg, **kw)
        net.initialize(init="xavier")
        net(toks, types)
        keys = list(net.collect_params().keys())   # structural order
        params = {k: net.collect_params()[k].data()._data for k in keys}
        def loss(params):
            out, _ = net._functional_call(params, jax.random.PRNGKey(0),
                                          False, (toks, types))
            return (out.astype("float32") ** 2).mean()
        g = jax.grad(loss)(params)
        # name-scope counters differ per instantiation AND jax sorts dict
        # keys — align by the net's own collect_params (structural) order
        return [(k, np.asarray(g[k], np.float32)) for k in keys]

    g_plain = grads(remat=False)
    g_dots = grads(remat=True, remat_policy="dots_saveable")
    for (ka, va), (kb, vb) in zip(g_plain, g_dots):
        np.testing.assert_allclose(va, vb, rtol=2e-3, atol=1e-5,
                                   err_msg=f"{ka} vs {kb}")
    with pytest.raises(ValueError, match="remat policy"):
        BERTModel(cfg, remat=True, remat_policy="bogus_policy")


def test_bert_remat_policy_without_remat_raises():
    from tpu_mx.models.bert import BERTModel, bert_base_config
    cfg = bert_base_config(vocab_size=64, max_len=32)
    cfg.update(num_layers=1, units=32, hidden_size=64, num_heads=2)
    with pytest.raises(ValueError, match="remat=True"):
        BERTModel(cfg, remat=False, remat_policy="dots_saveable")


def test_parameter_own_init_beats_global_initializer():
    """A parameter's own init (layer weight_initializer, constants like
    the SSD L2-norm scale) must take precedence over the initializer
    passed to net.initialize() — the global is the DEFAULT for params
    without one (REF gluon ParameterDict.initialize semantics)."""
    from tpu_mx import initializer as init_mod

    class WithConst(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.dense = nn.Dense(4, in_units=3,
                                  weight_initializer=init_mod.Constant(2.5))
            self.scale = self.params.get("scale", shape=(1, 8),
                                         init=init_mod.Constant(20.0))
            # no init of its own: must fall through to the GLOBAL default
            self.raw = self.params.get("raw", shape=(2, 3))

        def hybrid_forward(self, F, x, scale, raw):
            return self.dense(x) * scale[:, :4] + raw.sum()

    net = WithConst()
    # a Constant global makes the fall-through observable: a param whose
    # own init were (incorrectly) consulted first could never land on 3.0
    net.initialize(init=init_mod.Constant(3.0))
    np.testing.assert_array_equal(
        net.scale.data().asnumpy(), np.full((1, 8), 20.0, np.float32))
    np.testing.assert_array_equal(
        net.dense.weight.data().asnumpy(),
        np.full((4, 3), 2.5, np.float32))
    # param WITHOUT its own init gets the global default...
    np.testing.assert_array_equal(
        net.raw.data().asnumpy(), np.full((2, 3), 3.0, np.float32))
    # ...while Dense's bias keeps its OWN default init (zeros), which
    # also takes precedence over the global
    np.testing.assert_array_equal(
        net.dense.bias.data().asnumpy(), np.zeros(4, np.float32))


def test_batchnorm_onepass_matches_legacy(monkeypatch):
    """The r5 one-pass f32-stat BN (sum/sum-of-squares, folded
    scale/bias) must match the legacy two-pass form on fwd, backward,
    and running stats — eager and hybridized (TPUMX_BN_ONEPASS A/B)."""
    np.random.seed(0)
    x_np = (np.random.randn(4, 5, 8) * 2 + 1.5).astype(np.float32)
    w_np = np.random.randn(4, 5, 8).astype(np.float32)

    def run(onepass, hybrid):
        monkeypatch.setenv("TPUMX_BN_ONEPASS", "1" if onepass else "0")
        np.random.seed(1)
        net = nn.BatchNorm(axis=-1, in_channels=8)
        net.initialize()
        net.gamma.set_data(nd.array(
            np.random.rand(8).astype(np.float32) + 0.5))
        net.beta.set_data(nd.array(np.random.randn(8).astype(np.float32)))
        if hybrid:
            net.hybridize()
        x = nd.array(x_np)
        w = nd.array(w_np)
        x.attach_grad()
        with autograd.record():
            y = net(x)            # training-mode forward (batch stats)
            l = (y * w).sum()
        l.backward()
        return (y.asnumpy(), x.grad.asnumpy(), net.gamma.grad.asnumpy(),
                net.beta.grad.asnumpy(),
                net.running_mean.data().asnumpy(),
                net.running_var.data().asnumpy(), net(x).asnumpy())

    for hybrid in (False, True):
        a, b = run(True, hybrid), run(False, hybrid)
        for u, v in zip(a, b):
            assert_almost_equal(u, v, rtol=2e-5, atol=2e-5)


def test_bert_dtype_casts_whole_model():
    """dtype='bfloat16' must reach EVERY parameter (the r4 bench bug:
    only the embedding tables were cast, f32 params promoted all
    activations) and the MLM logits must still return f32."""
    from tpu_mx.models.bert import BERTModel, bert_base_config
    cfg = bert_base_config(vocab_size=64, max_len=16)
    cfg.update(num_layers=1, units=32, hidden_size=64, num_heads=2)
    net = BERTModel(cfg, dtype="bfloat16")
    net.initialize()
    tokens = nd.array(np.zeros((2, 16), np.int32))
    types = nd.array(np.zeros((2, 16), np.int32))
    out = net(tokens, types)
    dtypes = {str(p.data().dtype)
              for p in net.collect_params().values()}
    assert dtypes == {"bfloat16"}, dtypes
    assert str(out.dtype) == "float32", out.dtype


def test_finalize_shapes_noop_when_fully_declared():
    """finalize_shapes runs a forward only when deferred params remain;
    fully-declared models skip the device round-trip entirely."""
    calls = []

    class Probe(nn.Dense):
        def forward(self, *a):
            calls.append(1)
            return super().forward(*a)

    full = Probe(4, in_units=3)
    full.initialize()
    assert full.finalize_shapes(nd.ones((2, 3))) is full
    assert not calls                 # no forward: nothing deferred
    deferred = Probe(4)
    deferred.initialize()
    deferred.finalize_shapes(nd.ones((2, 3)))
    assert calls                     # forward ran to finalize
    assert deferred.weight.shape == (4, 3)
