"""mx.np / mx.npx namespace (REF:python/mxnet/numpy — the ver>=1.6 numpy
API).  Checks: numpy-parity results, autograd through np ops, functional
trace compatibility, random/linalg submodules, npx extensions."""
import numpy as onp
import pytest

import tpu_mx as mx
from tpu_mx import autograd, nd
from tpu_mx.ndarray import NDArray

np = mx.np
npx = mx.npx


def test_creation_and_default_dtype():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(a, NDArray) and a.dtype == onp.float32
    assert np.zeros((2, 3)).dtype == onp.float32
    assert np.arange(5).dtype == onp.int32
    assert np.linspace(0, 1, 5).shape == (5,)
    onp.testing.assert_allclose(np.eye(3).asnumpy(), onp.eye(3))
    assert np.full((2,), 7).asnumpy().tolist() == [7, 7]


def test_numpy_parity_broad():
    rng = onp.random.RandomState(0)
    x = rng.rand(3, 4).astype(onp.float32)
    y = rng.rand(3, 4).astype(onp.float32)
    ax, ay = np.array(x), np.array(y)
    cases = [
        (np.add(ax, ay), x + y),
        (np.matmul(ax, ay.T if hasattr(ay, "T") else ay), x @ y.T),
        (np.sum(ax, 1), x.sum(1)),
        (np.mean(ax), x.mean()),
        (np.concatenate([ax, ay], 0), onp.concatenate([x, y], 0)),
        (np.stack([ax, ay]), onp.stack([x, y])),
        (np.where(ax > 0.5, ax, ay), onp.where(x > 0.5, x, y)),
        (np.clip(ax, 0.2, 0.8), onp.clip(x, 0.2, 0.8)),
        (np.transpose(ax), x.T),
        (np.sqrt(ax), onp.sqrt(x)),
        (np.argmax(ax, 1), onp.argmax(x, 1)),
        (np.tile(ax, (2, 1)), onp.tile(x, (2, 1))),
        (np.cumsum(ax, 1), onp.cumsum(x, 1)),
        (np.maximum(ax, ay), onp.maximum(x, y)),
        (np.tensordot(ax, ay, ([1], [1])),
         onp.tensordot(x, y, ([1], [1]))),
        (np.einsum("ij,kj->ik", ax, ay), onp.einsum("ij,kj->ik", x, y)),
    ]
    for got, want in cases:
        onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5,
                                    atol=1e-6)


def test_autograd_through_np_ops():
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = np.sum(np.square(np.sin(x)))
    y.backward()
    expect = 2 * onp.sin(x.asnumpy()) * onp.cos(x.asnumpy())
    onp.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-5)


def test_multi_output_and_int_ops():
    a = np.array([3.0, 1.0, 2.0])
    parts = np.split(np.arange(6), 3)
    assert len(parts) == 3 and parts[1].asnumpy().tolist() == [2, 3]
    assert np.sort(a).asnumpy().tolist() == [1, 2, 3]
    u = np.unique(np.array([1, 1, 2]))
    assert u.asnumpy().tolist() == [1, 2]


def test_np_random_and_seed():
    np.random.seed(42)
    a = np.random.uniform(0, 1, (100,))
    np.random.seed(42)
    b = np.random.uniform(0, 1, (100,))
    onp.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    assert 0.0 <= float(a.asnumpy().min()) and float(a.asnumpy().max()) <= 1
    n = np.random.normal(2.0, 0.5, (2000,))
    assert abs(float(np.mean(n).asnumpy()) - 2.0) < 0.1
    r = np.random.randint(0, 10, (50,))
    assert r.dtype == onp.int32 and r.asnumpy().max() < 10
    p = np.random.permutation(10)
    assert sorted(p.asnumpy().tolist()) == list(range(10))


def test_np_linalg():
    a = np.array([[2.0, 1.0], [1.0, 3.0]])
    onp.testing.assert_allclose(float(np.linalg.det(a).asnumpy()), 5.0,
                                rtol=1e-5)
    inv = np.linalg.inv(a)
    onp.testing.assert_allclose((inv.asnumpy() @ a.asnumpy()), onp.eye(2),
                                atol=1e-5)
    assert abs(float(np.linalg.norm(a).asnumpy()) -
               onp.linalg.norm(a.asnumpy())) < 1e-5
    # grad through linalg
    x = np.array([[2.0, 0.0], [0.0, 3.0]])
    x.attach_grad()
    with autograd.record():
        l = np.sum(np.linalg.inv(x))
    l.backward()
    assert onp.isfinite(x.grad.asnumpy()).all()


def test_npx_extensions():
    x = np.array([[1.0, -1.0], [0.5, 2.0]])
    onp.testing.assert_allclose(npx.relu(x).asnumpy(),
                                onp.maximum(x.asnumpy(), 0))
    s = npx.softmax(x, axis=-1).asnumpy()
    onp.testing.assert_allclose(s.sum(-1), [1, 1], rtol=1e-6)
    oh = npx.one_hot(np.array([0, 2]).astype("int32"), 3)
    onp.testing.assert_allclose(oh.asnumpy(),
                                [[1, 0, 0], [0, 0, 1]])
    assert not npx.is_np_array()
    npx.set_np()
    assert npx.is_np_array()
    npx.reset_np()
    assert not npx.is_np_array()


def test_np_in_functional_trace():
    """np ops must trace into hybridized blocks (one compiled graph)."""
    from tpu_mx import gluon
    from tpu_mx.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.fc = nn.Dense(4, in_units=3)

        def hybrid_forward(self, F, x):
            return np.tanh(self.fc(x)) + np.ones(4)

    net = Net()
    net.initialize()
    x = nd.array(onp.ones((2, 3), onp.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    onp.testing.assert_allclose(eager, hybrid, rtol=1e-6)


def test_np_extended_coverage():
    """Round-3 widening: nan-reductions, bit ops, take_along_axis, ptp,
    average, polyval, logspace, empty, indices/diag_indices."""
    a = np.array([[1.0, 5.0], [3.0, onp.nan]])
    assert float(np.nanmax(a)) == 5.0
    assert float(np.nanmin(a)) == 1.0
    onp.testing.assert_allclose(float(np.nansum(a)), 9.0)
    onp.testing.assert_allclose(float(np.nanmean(a)), 3.0)

    b = np.array([[3, 1], [2, 4]]).astype("int32")
    onp.testing.assert_array_equal(
        np.bitwise_and(b, np.array(1).astype("int32")).asnumpy(),
        [[1, 1], [0, 0]])
    assert float(np.ptp(b)) == 3.0

    idx = np.argsort(b, axis=1)
    gathered = np.take_along_axis(b, idx, 1)
    onp.testing.assert_array_equal(gathered.asnumpy(), [[1, 3], [2, 4]])

    w = np.array([1.0, 3.0])
    onp.testing.assert_allclose(
        float(np.average(np.array([2.0, 4.0]), weights=w)), 3.5)

    onp.testing.assert_allclose(
        np.polyval(np.array([1.0, 0.0, -1.0]), np.array([2.0])).asnumpy(),
        [3.0])

    ls = np.logspace(0, 2, 3)
    onp.testing.assert_allclose(ls.asnumpy(), [1, 10, 100], rtol=1e-5)
    assert np.empty((2, 3)).shape == (2, 3)
    ii = np.indices((2, 3))
    assert ii.shape == (2, 2, 3)  # numpy contract: one stacked array
    r, c = np.diag_indices(3)
    onp.testing.assert_array_equal(r.asnumpy(), [0, 1, 2])

    onp.testing.assert_array_equal(
        np.isclose(np.array([1.0, 2.0]), np.array([1.0, 2.1])).asnumpy(),
        [True, False])
    assert float(np.vdot(np.array([1.0, 2.0]), np.array([3.0, 4.0]))) == 11.0
    onp.testing.assert_array_equal(
        np.flatnonzero(np.array([0.0, 3.0, 0.0, 4.0])).asnumpy(), [1, 3])


def test_np_fft_roundtrip_and_grad():
    """fft module: roundtrip + autograd through rfft power spectrum."""
    from tpu_mx import autograd
    sig = np.array(onp.sin(onp.linspace(0, 8 * onp.pi, 64))
                   .astype(onp.float32))
    spec = np.fft.fft(sig)
    back = np.fft.ifft(spec)
    onp.testing.assert_allclose(back.asnumpy().real, sig.asnumpy(),
                                atol=1e-4)
    freqs = np.fft.fftfreq(64)
    assert freqs.shape == (64,)

    x = np.array(onp.random.RandomState(0).randn(32).astype(onp.float32))
    x.attach_grad()
    with autograd.record():
        power = np.sum(np.abs(np.fft.rfft(x)) ** 2)
    power.backward()
    # Parseval: d/dx sum|rfft(x)|^2 = 2N x (within rfft halving details);
    # just require a finite, nonzero gradient of the right shape
    g = x.grad.asnumpy()
    assert g.shape == (32,) and onp.isfinite(g).all() and (g != 0).any()
