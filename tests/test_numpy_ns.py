"""mx.np / mx.npx namespace (REF:python/mxnet/numpy — the ver>=1.6 numpy
API).  Checks: numpy-parity results, autograd through np ops, functional
trace compatibility, random/linalg submodules, npx extensions."""
import numpy as onp
import pytest

import tpu_mx as mx
from tpu_mx import autograd, nd
from tpu_mx.ndarray import NDArray

np = mx.np
npx = mx.npx


def test_creation_and_default_dtype():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(a, NDArray) and a.dtype == onp.float32
    assert np.zeros((2, 3)).dtype == onp.float32
    assert np.arange(5).dtype == onp.int32
    assert np.linspace(0, 1, 5).shape == (5,)
    onp.testing.assert_allclose(np.eye(3).asnumpy(), onp.eye(3))
    assert np.full((2,), 7).asnumpy().tolist() == [7, 7]


def test_numpy_parity_broad():
    rng = onp.random.RandomState(0)
    x = rng.rand(3, 4).astype(onp.float32)
    y = rng.rand(3, 4).astype(onp.float32)
    ax, ay = np.array(x), np.array(y)
    cases = [
        (np.add(ax, ay), x + y),
        (np.matmul(ax, ay.T if hasattr(ay, "T") else ay), x @ y.T),
        (np.sum(ax, 1), x.sum(1)),
        (np.mean(ax), x.mean()),
        (np.concatenate([ax, ay], 0), onp.concatenate([x, y], 0)),
        (np.stack([ax, ay]), onp.stack([x, y])),
        (np.where(ax > 0.5, ax, ay), onp.where(x > 0.5, x, y)),
        (np.clip(ax, 0.2, 0.8), onp.clip(x, 0.2, 0.8)),
        (np.transpose(ax), x.T),
        (np.sqrt(ax), onp.sqrt(x)),
        (np.argmax(ax, 1), onp.argmax(x, 1)),
        (np.tile(ax, (2, 1)), onp.tile(x, (2, 1))),
        (np.cumsum(ax, 1), onp.cumsum(x, 1)),
        (np.maximum(ax, ay), onp.maximum(x, y)),
        (np.tensordot(ax, ay, ([1], [1])),
         onp.tensordot(x, y, ([1], [1]))),
        (np.einsum("ij,kj->ik", ax, ay), onp.einsum("ij,kj->ik", x, y)),
    ]
    for got, want in cases:
        onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5,
                                    atol=1e-6)


def test_autograd_through_np_ops():
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = np.sum(np.square(np.sin(x)))
    y.backward()
    expect = 2 * onp.sin(x.asnumpy()) * onp.cos(x.asnumpy())
    onp.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-5)


def test_multi_output_and_int_ops():
    a = np.array([3.0, 1.0, 2.0])
    parts = np.split(np.arange(6), 3)
    assert len(parts) == 3 and parts[1].asnumpy().tolist() == [2, 3]
    assert np.sort(a).asnumpy().tolist() == [1, 2, 3]
    u = np.unique(np.array([1, 1, 2]))
    assert u.asnumpy().tolist() == [1, 2]


def test_np_random_and_seed():
    np.random.seed(42)
    a = np.random.uniform(0, 1, (100,))
    np.random.seed(42)
    b = np.random.uniform(0, 1, (100,))
    onp.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    assert 0.0 <= float(a.asnumpy().min()) and float(a.asnumpy().max()) <= 1
    n = np.random.normal(2.0, 0.5, (2000,))
    assert abs(float(np.mean(n).asnumpy()) - 2.0) < 0.1
    r = np.random.randint(0, 10, (50,))
    assert r.dtype == onp.int32 and r.asnumpy().max() < 10
    p = np.random.permutation(10)
    assert sorted(p.asnumpy().tolist()) == list(range(10))


def test_np_linalg():
    a = np.array([[2.0, 1.0], [1.0, 3.0]])
    onp.testing.assert_allclose(float(np.linalg.det(a).asnumpy()), 5.0,
                                rtol=1e-5)
    inv = np.linalg.inv(a)
    onp.testing.assert_allclose((inv.asnumpy() @ a.asnumpy()), onp.eye(2),
                                atol=1e-5)
    assert abs(float(np.linalg.norm(a).asnumpy()) -
               onp.linalg.norm(a.asnumpy())) < 1e-5
    # grad through linalg
    x = np.array([[2.0, 0.0], [0.0, 3.0]])
    x.attach_grad()
    with autograd.record():
        l = np.sum(np.linalg.inv(x))
    l.backward()
    assert onp.isfinite(x.grad.asnumpy()).all()


def test_npx_extensions():
    x = np.array([[1.0, -1.0], [0.5, 2.0]])
    onp.testing.assert_allclose(npx.relu(x).asnumpy(),
                                onp.maximum(x.asnumpy(), 0))
    s = npx.softmax(x, axis=-1).asnumpy()
    onp.testing.assert_allclose(s.sum(-1), [1, 1], rtol=1e-6)
    oh = npx.one_hot(np.array([0, 2]).astype("int32"), 3)
    onp.testing.assert_allclose(oh.asnumpy(),
                                [[1, 0, 0], [0, 0, 1]])
    assert not npx.is_np_array()
    npx.set_np()
    assert npx.is_np_array()
    npx.reset_np()
    assert not npx.is_np_array()


def test_np_in_functional_trace():
    """np ops must trace into hybridized blocks (one compiled graph)."""
    from tpu_mx import gluon
    from tpu_mx.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.fc = nn.Dense(4, in_units=3)

        def hybrid_forward(self, F, x):
            return np.tanh(self.fc(x)) + np.ones(4)

    net = Net()
    net.initialize()
    x = nd.array(onp.ones((2, 3), onp.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    onp.testing.assert_allclose(eager, hybrid, rtol=1e-6)


def test_np_extended_coverage():
    """Round-3 widening: nan-reductions, bit ops, take_along_axis, ptp,
    average, polyval, logspace, empty, indices/diag_indices."""
    a = np.array([[1.0, 5.0], [3.0, onp.nan]])
    assert float(np.nanmax(a)) == 5.0
    assert float(np.nanmin(a)) == 1.0
    onp.testing.assert_allclose(float(np.nansum(a)), 9.0)
    onp.testing.assert_allclose(float(np.nanmean(a)), 3.0)

    b = np.array([[3, 1], [2, 4]]).astype("int32")
    onp.testing.assert_array_equal(
        np.bitwise_and(b, np.array(1).astype("int32")).asnumpy(),
        [[1, 1], [0, 0]])
    assert float(np.ptp(b)) == 3.0

    idx = np.argsort(b, axis=1)
    gathered = np.take_along_axis(b, idx, 1)
    onp.testing.assert_array_equal(gathered.asnumpy(), [[1, 3], [2, 4]])

    w = np.array([1.0, 3.0])
    onp.testing.assert_allclose(
        float(np.average(np.array([2.0, 4.0]), weights=w)), 3.5)

    onp.testing.assert_allclose(
        np.polyval(np.array([1.0, 0.0, -1.0]), np.array([2.0])).asnumpy(),
        [3.0])

    ls = np.logspace(0, 2, 3)
    onp.testing.assert_allclose(ls.asnumpy(), [1, 10, 100], rtol=1e-5)
    assert np.empty((2, 3)).shape == (2, 3)
    ii = np.indices((2, 3))
    assert ii.shape == (2, 2, 3)  # numpy contract: one stacked array
    r, c = np.diag_indices(3)
    onp.testing.assert_array_equal(r.asnumpy(), [0, 1, 2])

    onp.testing.assert_array_equal(
        np.isclose(np.array([1.0, 2.0]), np.array([1.0, 2.1])).asnumpy(),
        [True, False])
    assert float(np.vdot(np.array([1.0, 2.0]), np.array([3.0, 4.0]))) == 11.0
    onp.testing.assert_array_equal(
        np.flatnonzero(np.array([0.0, 3.0, 0.0, 4.0])).asnumpy(), [1, 3])


def test_np_fft_roundtrip_and_grad():
    """fft module: roundtrip + autograd through rfft power spectrum."""
    from tpu_mx import autograd
    sig = np.array(onp.sin(onp.linspace(0, 8 * onp.pi, 64))
                   .astype(onp.float32))
    spec = np.fft.fft(sig)
    back = np.fft.ifft(spec)
    onp.testing.assert_allclose(back.asnumpy().real, sig.asnumpy(),
                                atol=1e-4)
    freqs = np.fft.fftfreq(64)
    assert freqs.shape == (64,)

    x = np.array(onp.random.RandomState(0).randn(32).astype(onp.float32))
    x.attach_grad()
    with autograd.record():
        power = np.sum(np.abs(np.fft.rfft(x)) ** 2)
    power.backward()
    # Parseval: d/dx sum|rfft(x)|^2 = 2N x (within rfft halving details);
    # just require a finite, nonzero gradient of the right shape
    g = x.grad.asnumpy()
    assert g.shape == (32,) and onp.isfinite(g).all() and (g != 0).any()


def test_control_flow_foreach_eager_and_traced():
    """contrib.foreach: python loop eagerly (tape-recorded), ONE lax.scan
    in traces; both match a manual unroll, grads flow."""
    from tpu_mx import autograd, gluon
    from tpu_mx.ndarray import contrib as C

    data = nd.array(onp.arange(12, dtype=onp.float32).reshape(4, 3))
    w = nd.array(onp.ones(3, onp.float32) * 0.5)
    w.attach_grad()

    def body(x, s):
        out = x * w + s
        return out, out

    with autograd.record():
        outs, final = C.foreach(body, data, nd.zeros(3))
        loss = outs.sum()
    loss.backward()
    # manual: cumulative sum of x*w rows; dL/dw = sum over t of (T-t)*x_t
    x = onp.arange(12, dtype=onp.float32).reshape(4, 3)
    ref = onp.cumsum(x * 0.5, axis=0)
    onp.testing.assert_allclose(outs.asnumpy(), ref, rtol=1e-6)
    onp.testing.assert_allclose(final.asnumpy(), ref[-1], rtol=1e-6)
    wg = (x * onp.arange(4, 0, -1)[:, None]).sum(axis=0)
    onp.testing.assert_allclose(w.grad.asnumpy(), wg, rtol=1e-6)

    # traced through a hybridized block: same numbers
    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, d):
            outs, _ = C.foreach(lambda x, s: ((x + s), (x + s)), d,
                                np.zeros(3))
            return outs

    net = Net()
    net.initialize()
    eager = net(data).asnumpy()
    net.hybridize()
    onp.testing.assert_allclose(net(data).asnumpy(), eager, rtol=1e-6)


def test_control_flow_while_loop_and_cond():
    from tpu_mx.ndarray import contrib as C

    # sum integers until the running total exceeds 20 (5.5 steps -> 6)
    def w_cond(i, total):
        return total < 20.0

    def w_func(i, total):
        new_total = total + i
        return new_total, (i + 1.0, new_total)

    outs, (i_fin, total_fin), steps = C.while_loop(
        w_cond, w_func, (nd.array([1.0]), nd.array([0.0])),
        max_iterations=10)
    assert steps == 6  # 1+2+...+6 = 21 >= 20
    assert float(total_fin.asnumpy()[0]) == 21.0
    assert outs.shape == (10, 1)
    assert float(outs.asnumpy()[5, 0]) == 21.0
    assert (outs.asnumpy()[6:] == 0).all()  # zero padding

    # cond: eager branch pick
    r = C.cond(nd.array([1.0]), lambda: nd.array([2.0]),
               lambda: nd.array([3.0]))
    assert float(r.asnumpy()[0]) == 2.0

    # traced while_loop + cond inside a hybridized block
    from tpu_mx import gluon

    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            outs, (_, tot), steps = C.while_loop(
                lambda i, t: t < 20.0,
                lambda i, t: (t + i, (i + 1.0, t + i)),
                (F.ones((1,)), F.zeros((1,))), max_iterations=10)
            return C.cond(steps > 5, lambda: tot, lambda: tot * 0.0)

    net = Net()
    net.initialize()
    eager = net(nd.array([0.0])).asnumpy()
    net.hybridize()
    hybrid = net(nd.array([0.0])).asnumpy()
    onp.testing.assert_allclose(eager, [21.0])
    onp.testing.assert_allclose(hybrid, [21.0])


def test_npx_round3_aliases():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert npx.batch_flatten(a).shape == (2, 2)
    al = npx.arange_like(a)
    onp.testing.assert_allclose(al.asnumpy(), [[0, 1], [2, 3]])
    ln = npx.layer_norm(a, np.ones(2), np.zeros(2))
    assert ln.shape == (2, 2)
    sl1 = npx.smooth_l1(np.array([0.5, 2.0]))
    onp.testing.assert_allclose(sl1.asnumpy(), [0.125, 1.5])
    assert npx.foreach is not None and npx.while_loop is not None


def test_while_loop_zero_trips_eager_traced_agree():
    """A loop whose condition is False on entry returns the SAME all-zero
    buffer + steps=0 in eager and traced mode (no eager-only crash)."""
    from tpu_mx import gluon
    from tpu_mx.ndarray import contrib as C

    def run():
        return C.while_loop(lambda i, t: t < 0.0,
                            lambda i, t: (t + i, (i + 1.0, t + i)),
                            (nd.ones((1,)), nd.zeros((1,))),
                            max_iterations=4)

    outs, (i_f, t_f), steps = run()
    assert steps == 0 and outs.shape == (4, 1)
    assert (outs.asnumpy() == 0).all()
    assert float(i_f.asnumpy()[0]) == 1.0  # loop vars untouched

    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            outs, _, steps = C.while_loop(
                lambda i, t: t < 0.0,
                lambda i, t: (t + i, (i + 1.0, t + i)),
                (F.ones((1,)), F.zeros((1,))), max_iterations=4)
            return outs + F.reshape(x * 0.0, shape=(1, 1))

    net = Net()
    net.initialize()
    net.hybridize()
    assert (net(nd.array([5.0])).asnumpy() == 0).all()


def test_attention_sp_strategy_typo_raises():
    import jax.numpy as jnp
    from tpu_mx.parallel import attention, make_mesh
    mesh = make_mesh({"sp": 8})
    q = jnp.ones((1, 8, 32, 4), jnp.float32)
    with pytest.raises(ValueError, match="sp_strategy"):
        attention(q, q, q, mesh=mesh, sp_strategy="ulyses")


@pytest.mark.slow
def test_np_random_samplers_distribution_means():
    """Round-3 sampler widening: each new distribution's sample mean lands
    near its analytic mean (seeded, n=4000)."""
    import tpu_mx.numpy.random as R
    mx.random.seed(0)
    cases = [
        (lambda: R.poisson(4.0, size=(4000,)), 4.0),
        (lambda: R.binomial(10, 0.3, size=(4000,)), 3.0),
        (lambda: R.chisquare(3.0, size=(4000,)), 3.0),
        (lambda: R.geometric(0.35, size=(4000,)), 1 / 0.35),
        (lambda: R.gumbel(1.0, 2.0, size=(4000,)), 1.0 + 2.0 * 0.5772),
        (lambda: R.laplace(2.0, 1.0, size=(4000,)), 2.0),
        (lambda: R.logistic(3.0, 1.0, size=(4000,)), 3.0),
        (lambda: R.lognormal(0.0, 0.5, size=(4000,)), float(onp.exp(0.125))),
        (lambda: R.pareto(3.0, size=(4000,)), 0.5),
        (lambda: R.power(2.0, size=(4000,)), 2 / 3),
        (lambda: R.rayleigh(2.0, size=(4000,)),
         2.0 * float(onp.sqrt(onp.pi / 2))),
        (lambda: R.weibull(2.0, size=(4000,)), 0.8862),
    ]
    for fn, mean in cases:
        a = fn().asnumpy().astype(onp.float64)
        assert abs(a.mean() - mean) < 0.35 * max(1.0, abs(mean)), \
            (fn, a.mean(), mean)


def test_np_linalg_eig_and_cond():
    m = np.array([[2.0, 1.0], [0.0, 3.0]])
    w = np.linalg.eigvals(m)
    onp.testing.assert_allclose(sorted(onp.real(w.asnumpy())), [2.0, 3.0],
                                atol=1e-5)
    w2, v = np.linalg.eig(np.array([[4.0, 0.0], [0.0, 9.0]]))
    onp.testing.assert_allclose(sorted(onp.real(w2.asnumpy())), [4.0, 9.0],
                                atol=1e-5)
    c = np.linalg.cond(np.array([[2.0, 0.0], [0.0, 3.0]]))
    onp.testing.assert_allclose(float(c.asnumpy()), 1.5, rtol=1e-5)


def test_np_r4_long_tail_names():
    """allclose/array_split/divmod/frexp/logaddexp2/vander (r4 audit)."""
    a = mx.np.array([1.0, 2.0, 3.0])
    assert float(mx.np.allclose(a, a + 1e-9).asnumpy()) == 1.0
    parts = mx.np.array_split(mx.np.arange(7), 3)
    assert [int(p.size) for p in parts] == [3, 2, 2]
    q, r = mx.np.divmod(mx.np.array([7.0, 9.0]), 4.0)
    onp.testing.assert_allclose(q.asnumpy(), [1.0, 2.0])
    onp.testing.assert_allclose(r.asnumpy(), [3.0, 1.0])
    m, e = mx.np.frexp(mx.np.array([8.0, 0.5]))
    onp.testing.assert_allclose(m.asnumpy() * 2.0 ** e.asnumpy(),
                               [8.0, 0.5])
    onp.testing.assert_allclose(
        mx.np.logaddexp2(mx.np.array([1.0]), mx.np.array([1.0])).asnumpy(),
        [2.0])
    v = mx.np.vander(mx.np.array([1.0, 2.0]), 3)
    onp.testing.assert_allclose(v.asnumpy(), [[1, 1, 1], [4, 2, 1]])


def test_np_split_family_backward():
    """List-returning jnp ops (array_split/split/hsplit) must backprop:
    the pullback pytree is normalized at record time (r4 review fix)."""
    x = mx.np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    x.attach_grad()
    with autograd.record():
        parts = mx.np.array_split(x, 2)      # sizes 3, 2
        (parts[0] * 3.0).sum().backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [3, 3, 3, 0, 0])
    y = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    y.attach_grad()
    with autograd.record():
        a, b = mx.np.split(y, 2, axis=1)
        (a * 2.0 + 0.0).sum().backward()
    onp.testing.assert_allclose(y.grad.asnumpy(), [[2, 0], [2, 0]])


def test_np_frexp_mantissa_gradient():
    """Mixed float/int outputs stay on the tape: d(mantissa)/dx = 1/2^e,
    not the silent zeros the all-inexact gate used to produce."""
    x = mx.np.array([8.0, 0.75])
    x.attach_grad()
    with autograd.record():
        m, e = mx.np.frexp(x)
        (m * 2.0).sum().backward()
    onp.testing.assert_allclose(
        x.grad.asnumpy(), 2.0 / 2.0 ** e.asnumpy().astype(onp.float32),
        rtol=1e-6)


def test_np_frexp_edge_values_bit_exact():
    """The straight-through gradient must not perturb the VALUES: zero,
    negatives, the extremes of the normal range and infinities return
    numpy frexp's exact bits, and no input may produce a nan mantissa
    (inf - inf in a naive straight-through would).  Subnormal inputs are
    backend-FTZ — divergence #26 — so they are only required to match
    raw jnp.frexp, nan-free."""
    import jax.numpy as jnp
    vals = onp.array([0.0, -0.0, 1e38, 2e-38, -3.0, onp.inf, -onp.inf],
                     onp.float32)
    m, e = mx.np.frexp(mx.np.array(vals))
    em, ee = onp.frexp(vals)
    onp.testing.assert_array_equal(m.asnumpy(), em)
    onp.testing.assert_array_equal(e.asnumpy(), ee)
    subs = onp.array([1e-40, -1e-40, onp.nan], onp.float32)
    ms, es = mx.np.frexp(mx.np.array(subs))
    jm, je = jnp.frexp(jnp.asarray(subs))
    onp.testing.assert_array_equal(ms.asnumpy(), onp.asarray(jm))
    onp.testing.assert_array_equal(es.asnumpy(), onp.asarray(je))
    assert not onp.isnan(ms.asnumpy()[:2]).any()
    # gradient stays finite and exact through the split half-power
    # scaling down to the bottom of the normal exponent range (a single
    # exp2(-e) factor would overflow there); the top of the range is
    # excluded — its true gradient 2**-127 is itself subnormal, FTZ'd
    x = mx.np.array([2.0e-38, 4.0])
    x.attach_grad()
    with autograd.record():
        m, e = mx.np.frexp(x)
        m.sum().backward()
    onp.testing.assert_allclose(
        x.grad.asnumpy(), 1.0 / 2.0 ** e.asnumpy().astype(onp.float32),
        rtol=1e-6)
