"""Deterministic-resume capsules (tpu_mx/resume.py) + the mx.random state
token API — the unit layer under tests/test_supervisor.py's bit-identical
resume proofs (docs/robustness.md "Deterministic resume")."""
import json
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, nd, resume, supervisor
from tpu_mx import telemetry


# ---------------------------------------------------------------------------
# mx.random: observable, restorable state
# ---------------------------------------------------------------------------
def test_random_state_roundtrip_replays_both_streams():
    mx.random.seed(5)
    tok = mx.random.get_state()
    k1 = np.asarray(mx.random.take_key())
    n1 = np.random.rand(4)
    mx.random.set_state(tok)
    np.testing.assert_array_equal(np.asarray(mx.random.take_key()), k1)
    np.testing.assert_array_equal(np.random.rand(4), n1)


def test_seed_returns_prior_token():
    mx.random.seed(1)
    a1 = np.asarray(mx.random.take_key())  # advances the stream
    tok = mx.random.seed(999)              # the prior token: post-a1 state
    mx.random.take_key()
    np.random.rand(3)
    mx.random.set_state(tok)               # back to just-after-a1
    a2 = np.asarray(mx.random.take_key())
    assert not np.array_equal(a1, a2)      # the stream CONTINUED, no replay
    mx.random.seed(1)
    np.testing.assert_array_equal(np.asarray(mx.random.take_key()), a1)


def test_random_state_survives_json_roundtrip():
    """A capsule serializes the token through JSON: set_state must accept
    the decoded (list-ified) form bit-exactly."""
    mx.random.seed(17)
    tok = mx.random.get_state()
    decoded = resume.decode_state(
        json.loads(json.dumps(resume.encode_state(tok))))
    k1 = np.asarray(mx.random.take_key())
    n1 = np.random.rand(2)
    mx.random.set_state(decoded)
    np.testing.assert_array_equal(np.asarray(mx.random.take_key()), k1)
    np.testing.assert_array_equal(np.random.rand(2), n1)


# ---------------------------------------------------------------------------
# encode/decode
# ---------------------------------------------------------------------------
def test_encode_decode_exact_arrays():
    state = {"a": np.arange(7, dtype=np.uint32),
             "b": [np.float64(0.1), np.array([[1.5, -2.25]], np.float32)],
             "c": {"nested": None, "s": "x", "i": 3, "f": 0.25,
                   "t": (1, 2)}}
    out = resume.decode_state(json.loads(json.dumps(
        resume.encode_state(state))))
    np.testing.assert_array_equal(out["a"], state["a"])
    assert out["a"].dtype == np.uint32
    assert out["b"][0] == 0.1
    np.testing.assert_array_equal(out["b"][1], state["b"][1])
    assert out["b"][1].dtype == np.float32
    assert out["c"]["nested"] is None and out["c"]["s"] == "x"
    assert out["c"]["t"] == [1, 2]  # tuples come back as lists (documented)


def test_encode_rejects_opaque_objects():
    with pytest.raises(mx.base.MXNetError, match="cannot encode"):
        resume.encode_state({"bad": object()})


# ---------------------------------------------------------------------------
# epoch capsules ride the manifest
# ---------------------------------------------------------------------------
def _net():
    from tpu_mx.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    return net


def test_epoch_capsule_rides_verified_manifest(tmp_path):
    prefix = str(tmp_path / "ck")
    it = mx.io.NDArrayIter(np.zeros((8, 4), np.float32), batch_size=4,
                           shuffle=True, seed=1)
    mgr = resume.CapsuleManager(prefix, iters=[it])
    elastic.save_checkpoint(prefix, 0, net=_net(), capsule=mgr)
    cap_path = resume.capsule_path(prefix, 0)
    assert os.path.exists(cap_path)
    man = ckpt.read_manifest(prefix, 0)
    assert os.path.basename(cap_path) in man["files"]
    assert ckpt.verify_checkpoint(prefix, 0)[0] == "verified"
    cap = resume.read_capsule(cap_path)
    assert cap["format"] == resume.CAPSULE_FORMAT and cap["epoch"] == 0
    # a corrupted capsule flips the epoch to corrupt — it is VERIFIED state
    with open(cap_path, "a") as f:
        f.write(" ")
    status, problems = ckpt.verify_checkpoint(prefix, 0)
    assert status == "corrupt" and any("capsule" in p for p in problems)


def test_unknown_capsule_format_is_ignored(tmp_path):
    path = str(tmp_path / "x-step.capsule.json")
    with open(path, "w") as f:
        json.dump({"format": "tpu_mx-capsule-v999", "epoch": 0}, f)
    assert resume.read_capsule(path) is None


def test_epoch_capsule_restores_rng_and_iterator(tmp_path):
    prefix = str(tmp_path / "ck")
    data = np.arange(32, dtype=np.float32).reshape(16, 2)

    def make():
        return mx.io.NDArrayIter(data, batch_size=4, shuffle=True, seed=2)

    it = make()
    mgr = resume.CapsuleManager(prefix, iters=[it])
    mx.random.seed(3)
    for _ in range(2):
        it.next()
    mx.random.take_key()
    elastic.save_checkpoint(prefix, 0, net=_net(), capsule=mgr)
    expect_key = np.asarray(mx.random.take_key())
    it.reset()
    expect = [b.data[0].asnumpy() for b in it]

    # a "fresh process": different RNG position, fresh iterator
    mx.random.seed(999)
    it2 = make()
    mgr2 = resume.CapsuleManager(prefix, iters=[it2])
    assert mgr2.restore(resume_from=1) == 1
    np.testing.assert_array_equal(np.asarray(mx.random.take_key()),
                                  expect_key)
    it2.reset()
    got = [b.data[0].asnumpy() for b in it2]
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)
    assert telemetry.gauge("resume.resume_step_gap").value == 0


# ---------------------------------------------------------------------------
# step capsule: sidecar verification + fallbacks
# ---------------------------------------------------------------------------
class _FakeState:
    def __init__(self):
        self.arr = np.zeros(3, np.float32)
        self.loaded = None

    def state_dict(self):
        return {"arr": self.arr.copy()}

    def load_state_dict(self, sd):
        self.loaded = sd["arr"]


class _FakeSup:
    def __init__(self, epoch=1, step=2):
        self._epoch = epoch
        self.step_in_epoch = step
        self.steps = step
        self.batches_skipped = 0
        self._pending_resume = None
        self.sentinel = supervisor.NumericSentinel()


def test_step_capsule_roundtrip_and_pending_resume(tmp_path):
    prefix = str(tmp_path / "ck")
    it = mx.io.NDArrayIter(np.zeros((8, 2), np.float32), batch_size=4)
    st = _FakeState()
    st.arr[:] = 7.5
    mgr = resume.CapsuleManager(prefix, iters=[it], state=st, interval=1)
    sup = _FakeSup(epoch=1, step=2)
    sup.sentinel.observe(0.5)
    mgr.write_step(sup)

    st2 = _FakeState()
    it2 = mx.io.NDArrayIter(np.zeros((8, 2), np.float32), batch_size=4)
    mgr2 = resume.CapsuleManager(prefix, iters=[it2], state=st2, interval=1)
    sup2 = _FakeSup(epoch=0, step=0)
    out = mgr2.restore(sup2, resume_from=1)
    assert out == 1 and sup2._pending_resume == (1, 2)
    np.testing.assert_array_equal(st2.loaded, [7.5, 7.5, 7.5])
    assert sup2.sentinel.last_good == 0.5  # the skip ledger rode along
    assert telemetry.gauge("resume.resume_step_gap").value == 0


def test_torn_sidecar_falls_back_to_epoch_capsule(tmp_path):
    prefix = str(tmp_path / "ck")
    it = mx.io.NDArrayIter(np.zeros((8, 2), np.float32), batch_size=4)
    st = _FakeState()
    mgr = resume.CapsuleManager(prefix, iters=[it], state=st, interval=1)
    mgr.write_epoch_file(0)
    mgr.write_step(_FakeSup(epoch=1, step=3))
    with open(resume.step_state_path(prefix), "ab") as f:
        f.write(b"torn")  # sidecar no longer matches the capsule's sha256
    st2 = _FakeState()
    sup2 = _FakeSup(epoch=0, step=0)
    mgr2 = resume.CapsuleManager(prefix, iters=[it], state=st2, interval=1)
    out = mgr2.restore(sup2, resume_from=1)
    assert out == 1
    assert sup2._pending_resume is None   # epoch-boundary resume instead
    assert st2.loaded is None             # the torn sidecar was never applied


def test_numeric_rollback_discards_step_capsule(tmp_path):
    prefix = str(tmp_path / "ck")
    st = _FakeState()
    mgr = resume.CapsuleManager(prefix, state=st, interval=1)
    mgr.write_epoch_file(0)
    mgr.write_step(_FakeSup(epoch=1, step=2))
    assert os.path.exists(resume.step_capsule_path(prefix))
    mx.random.seed(999)
    live_key = np.asarray(mx.random.get_state()["jax_key"])
    sup = _FakeSup(epoch=0, step=0)
    out = mgr.restore(sup, resume_from=1, use_step=False)
    assert out == 1 and sup._pending_resume is None
    # the diverged trajectory's capsule is gone — it cannot resurrect
    assert not os.path.exists(resume.step_capsule_path(prefix))
    assert not os.path.exists(resume.step_state_path(prefix))
    # and the epoch capsule was deliberately NOT applied: rewinding the
    # RNG would make the retry an exact replay that re-diverges — the
    # live stream must keep running so the retried epoch re-randomizes
    np.testing.assert_array_equal(
        np.asarray(mx.random.get_state()["jax_key"]), live_key)


def test_capsule_manager_fails_fast_on_unsnapshotable_iter():
    class NoSnap(mx.io.DataIter):
        pass

    with pytest.raises(mx.base.MXNetError, match="cannot snapshot"):
        resume.CapsuleManager("p", iters=[NoSnap()])


def test_resume_step_gap_reported_without_capsules(tmp_path):
    """No epoch capsule and an unusable step capsule (no sidecar): the
    batches the dead run consumed are unreplayable — the gauge says so."""
    prefix = str(tmp_path / "ck")
    mgr = resume.CapsuleManager(prefix, interval=1)  # no state object
    mgr.write_step(_FakeSup(epoch=0, step=5))
    out = mgr.restore(_FakeSup(epoch=0, step=0), resume_from=0)
    assert out == 0
    assert telemetry.gauge("resume.resume_step_gap").value == 5
