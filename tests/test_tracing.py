"""Flight recorder (tpu_mx/tracing.py) — ISSUE 7.

Covers: the bounded ring buffer (memory under sustained emit,
thread-safety under concurrent emit+snapshot), the typed KNOWN_EVENTS
catalog, trace-context propagation across the watchdog thread boundary,
the subsystem instrumentation (train-step phases, fusion flushes,
capsule writes, chaos injections), and the crash black box on EVERY
supervisor exit path — watchdog restart, numeric rollback, transient
crash, degrade, SIGTERM preemption — each schema-valid and correlated
(injection -> detection -> decision share the (epoch, step, generation)
trace context)."""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, nd, supervisor, telemetry, \
    tracing
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Tracing state is process-global by design — isolate every test."""
    tracing.reset()
    tracing.configure(enabled=True, capacity=512)
    yield
    tracing.reset()
    tracing.configure(enabled=True, capacity=512)


def events(name=None):
    evs = tracing.snapshot()
    return [e for e in evs if name is None or e["event"] == name]


# ---------------------------------------------------------------------------
# emit + catalog
# ---------------------------------------------------------------------------
def test_emit_stamps_trace_context():
    tracing.set_context(epoch=3, step=12, generation=2)
    rec = tracing.emit("chaos.inject", kind="hang")
    assert rec["epoch"] == 3 and rec["step"] == 12
    assert rec["generation"] == 2
    assert rec["run_id"] and isinstance(rec["ts"], float)
    assert rec["data"] == {"kind": "hang"}
    tracing.validate_event(rec)


def test_unknown_event_name_rejected():
    with pytest.raises(ValueError, match="unknown event name"):
        tracing.emit("supervisor.totally_new_event")


def test_undeclared_payload_field_rejected():
    with pytest.raises(ValueError, match="undeclared payload field"):
        tracing.emit("chaos.inject", kind="hang", severity=9)


def test_payload_types_enforced():
    with pytest.raises(ValueError, match="must be str"):
        tracing.emit("chaos.inject", kind=42)
    with pytest.raises(ValueError, match="must be int"):
        tracing.emit("fusion.flush", cause="read_barrier", ops="three")
    # float fields accept ints; bool is NOT an int here
    tracing.emit("train_step.phase", phase="dispatch", seconds=1)
    with pytest.raises(ValueError, match="must be int"):
        tracing.emit("fusion.flush", cause="x", ops=True)


def test_unknown_context_field_rejected():
    with pytest.raises(ValueError, match="unknown trace-context field"):
        tracing.set_context(world_size=8)


def test_emit_is_reentrant_for_signal_handlers():
    """The SIGTERM preemption handler runs on the main thread between
    bytecodes and emits events — if the interrupted frame holds the
    tracing lock, emit must not self-deadlock (the lock is reentrant by
    requirement)."""
    with tracing._lock:
        rec = tracing.emit("chaos.inject", kind="hang")
    assert rec is not None


def test_nonfinite_floats_encode_as_strings_strict_json(tmp_path):
    """Strict JSON has no NaN token; a NaN loss — exactly what a
    divergence box records — must encode as its string form so jq /
    browsers / any spec-compliant reader can parse the box."""
    rec = tracing.emit("supervisor.sentinel_skip", loss=float("nan"),
                       consecutive_bad=1)
    assert rec["data"]["loss"] == "nan"
    assert tracing.emit("train_step.phase", phase="dispatch",
                        seconds=float("inf"))["data"]["seconds"] == "inf"
    assert tracing.emit("train_step.phase", phase="dispatch",
                        seconds=float("-inf"))["data"]["seconds"] == "-inf"
    tracing.validate_event(rec)  # the string spelling is schema-legal
    path = tracing.dump_blackbox(str(tmp_path / "ck"), reason="nan box")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    assert "NaN" not in text and "Infinity" not in text
    tracing.validate_blackbox(json.loads(text))


def test_span_endpoints_fill_seconds():
    t0 = time.perf_counter()
    rec = tracing.emit("train_step.phase", t0=t0, t1=t0 + 0.25,
                       phase="dispatch")
    assert rec["data"]["seconds"] == pytest.approx(0.25)


def test_events_merge_into_profiler_with_qualified_names():
    """Chrome-trace merge: the span name carries the categorical field
    — five phases must not collapse into one aggregate row."""
    from tpu_mx import profiler
    profiler.set_state("run")
    try:
        t0 = time.perf_counter()
        tracing.emit("train_step.phase", t0=t0, t1=t0 + 0.001,
                     phase="dispatch")
        tracing.emit("train_step.phase", t0=t0, t1=t0 + 0.002,
                     phase="loss_readback")
        tracing.emit("chaos.inject", kind="hang")
        names = {e["name"] for e in profiler._events
                 if e.get("cat") == "tracing"}
    finally:
        profiler.set_state("stop")
        profiler.dumps(reset=True)
    assert {"train_step.phase:dispatch", "train_step.phase:loss_readback",
            "chaos.inject:hang"} <= names


def test_validate_event_rejections():
    good = tracing.emit("chaos.inject", kind="nan")
    for mutate, match in [
            (lambda r: r.update(event="nope"), "unknown event name"),
            (lambda r: r.pop("ts"), "numeric 'ts'"),
            (lambda r: r.update(run_id=""), "run_id"),
            (lambda r: r.update(generation="x"), "generation"),
            (lambda r: r.update(epoch="x"), "epoch"),
            (lambda r: r.update(data={"kind": 7}), "must be str"),
            (lambda r: r.update(data={"oops": 1}), "undeclared")]:
        bad = dict(good, data=dict(good["data"]))
        mutate(bad)
        with pytest.raises(ValueError, match=match):
            tracing.validate_event(bad)


def test_disabled_path_records_nothing():
    tracing.configure(enabled=False)
    assert tracing.emit("chaos.inject", kind="hang") is None
    assert tracing.snapshot() == []
    assert tracing.stats()["emitted"] == 0
    tracing.configure(enabled=True)
    assert tracing.emit("chaos.inject", kind="hang") is not None


# ---------------------------------------------------------------------------
# the ring buffer
# ---------------------------------------------------------------------------
def test_ring_bounded_under_sustained_emit():
    tracing.configure(capacity=64)
    for i in range(10_000):
        tracing.emit("train_step.phase", phase="dispatch", seconds=0.001)
    st = tracing.stats()
    assert st["size"] == 64 and st["capacity"] == 64
    assert st["emitted"] == 10_000
    assert st["dropped"] == 10_000 - 64
    assert len(tracing.snapshot()) == 64


def test_snapshot_keeps_newest_and_last_n():
    tracing.configure(capacity=4)
    for i in range(8):
        tracing.emit("fusion.flush", cause=f"c{i}", ops=i)
    causes = [e["data"]["cause"] for e in tracing.snapshot()]
    assert causes == ["c4", "c5", "c6", "c7"]  # oldest evicted, order kept
    assert [e["data"]["cause"] for e in tracing.snapshot(last=2)] \
        == ["c6", "c7"]


def test_configure_capacity_keeps_newest():
    for i in range(10):
        tracing.emit("fusion.flush", cause=f"c{i}", ops=i)
    tracing.configure(capacity=3)
    assert [e["data"]["cause"] for e in tracing.snapshot()] \
        == ["c7", "c8", "c9"]
    with pytest.raises(ValueError):
        tracing.configure(capacity=0)


def test_thread_safety_concurrent_emit_and_snapshot():
    tracing.configure(capacity=128)
    N_THREADS, N_EMITS = 8, 500
    errors = []
    stop = threading.Event()

    def emitter(tid):
        try:
            for i in range(N_EMITS):
                tracing.emit("train_step.phase", phase="dispatch",
                             seconds=float(i))
        except Exception as e:  # pragma: no cover - the failure mode
            errors.append(e)

    def snapshotter():
        try:
            while not stop.is_set():
                for rec in tracing.snapshot():
                    tracing.validate_event(rec)  # never a torn record
                tracing.stats()
        except Exception as e:  # pragma: no cover - the failure mode
            errors.append(e)

    threads = [threading.Thread(target=emitter, args=(t,), daemon=True)
               for t in range(N_THREADS)]
    snap = threading.Thread(target=snapshotter, daemon=True)
    snap.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    stop.set()
    snap.join(30)
    assert not errors
    st = tracing.stats()
    assert st["emitted"] == N_THREADS * N_EMITS
    assert st["size"] == 128
    assert st["dropped"] == st["emitted"] - 128


def test_context_propagates_across_watchdog_thread():
    """The satellite proof: the supervisor runs steps on a daemon
    watchdog thread; an event emitted THERE must carry the step context
    set on the main thread (the context is process-global, not
    thread-local)."""
    tracing.set_context(epoch=5, step=7, generation=1)
    tid = {}

    def on_watchdog_thread():
        tid["worker"] = threading.get_ident()
        return tracing.emit("chaos.inject", kind="hang")

    rec = supervisor.run_with_deadline(on_watchdog_thread, 5.0)
    assert tid["worker"] != threading.get_ident()  # really another thread
    assert (rec["epoch"], rec["step"], rec["generation"]) == (5, 7, 1)


# ---------------------------------------------------------------------------
# subsystem instrumentation
# ---------------------------------------------------------------------------
def _train_step():
    from tpu_mx import gluon
    from tpu_mx.parallel import CompiledTrainStep
    net = nn.HybridSequential()
    net.add(nn.Dense(6, activation="relu"), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 4)))
    return net, CompiledTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.create("sgd", learning_rate=0.05))


def test_train_step_phase_events():
    net, step = _train_step()
    X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    for _ in range(2):
        step.step(nd.array(X), nd.array(Y))
    phases = [e["data"]["phase"] for e in events("train_step.phase")]
    assert phases.count("data_wait") == 2
    assert phases.count("dispatch") == 2
    assert phases.count("optimizer_update") == 2
    assert phases.count("recompile") == 1  # first step only
    for e in events("train_step.phase"):
        assert e["data"]["seconds"] >= 0
        assert e["data"]["phase"] in tracing.TRAIN_STEP_PHASES


def test_train_step_loss_readback_phase_under_watchdog():
    net, step = _train_step()
    X = np.random.RandomState(0).rand(4, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    step.step(nd.array(X), nd.array(Y), deadline=30.0)
    phases = [e["data"]["phase"] for e in events("train_step.phase")]
    assert "loss_readback" in phases


def test_fusion_flush_event():
    from tpu_mx import engine
    x = nd.array(np.ones((4, 4), np.float32))
    with engine.bulk(8):
        nd.tanh(x * 1.5 + 0.5).wait_to_read()
    flushes = events("fusion.flush")
    assert flushes, "no fusion.flush event emitted"
    assert flushes[-1]["data"]["cause"] == "read_barrier"
    assert flushes[-1]["data"]["ops"] >= 3


def test_checkpoint_and_capsule_events(tmp_path):
    from tpu_mx import resume as tresume
    prefix = str(tmp_path / "ck")
    net = nn.Dense(3, in_units=4)
    net.initialize()
    elastic.save_checkpoint(prefix, 0, net=net)
    mgr = tresume.CapsuleManager(prefix)
    mgr.write_epoch_file(0)
    ckpt.verify_checkpoint(prefix, 0)
    assert events("checkpoint.save")[-1]["data"]["epoch"] == 0
    assert events("resume.capsule_write")[-1]["data"]["kind"] == "epoch"
    ver = events("checkpoint.verify")[-1]["data"]
    assert ver["epoch"] == 0 and ver["status"] == "verified"


def test_chaos_injection_shares_step_context():
    tracing.set_context(epoch=2, step=9, generation=0)
    with chaos.enable(nan_after=1):
        assert np.isnan(chaos.poison_loss(1.0))
    inj = events("chaos.inject")[-1]
    assert inj["data"]["kind"] == "nan"
    assert (inj["epoch"], inj["step"]) == (2, 9)


# ---------------------------------------------------------------------------
# the black box
# ---------------------------------------------------------------------------
def test_dump_blackbox_schema_and_atomicity(tmp_path):
    tracing.set_context(epoch=1, step=2, generation=0)
    tracing.emit("chaos.inject", kind="hang")
    before = telemetry.counter("tracing.blackbox_dumps").value
    path = tracing.dump_blackbox(str(tmp_path / "ck"), reason="unit test")
    assert path == str(tmp_path / "ck-blackbox.json")
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    tracing.validate_blackbox(doc)
    assert doc["reason"] == "unit test"
    assert doc["context"]["epoch"] == 1
    assert any(e["event"] == "chaos.inject" for e in doc["events"])
    assert doc["environment"]["pid"] == os.getpid()
    # the telemetry snapshot rode along, schema-valid
    for rec in doc["telemetry"]:
        telemetry.validate_record(rec)
    assert telemetry.counter("tracing.blackbox_dumps").value == before + 1
    # went through atomic_write: no tmp debris next to it
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


def test_validate_blackbox_rejections(tmp_path):
    doc = tracing.blackbox_doc(reason="x")
    tracing.validate_blackbox(doc)
    with pytest.raises(ValueError, match="format"):
        tracing.validate_blackbox(dict(doc, format="v999"))
    with pytest.raises(ValueError, match="events"):
        tracing.validate_blackbox(dict(doc, events="nope"))
    bad_event = dict(doc, events=[{"event": "nope"}])
    with pytest.raises(ValueError, match=r"events\[0\]"):
        tracing.validate_blackbox(bad_event)
    with pytest.raises(ValueError, match="context"):
        tracing.validate_blackbox(dict(doc, context={"run_id": "r"}))
    # an EXTRA context key must not mask a missing required one (the
    # generation field is what the correlation join relies on)
    with pytest.raises(ValueError, match="context"):
        tracing.validate_blackbox(dict(doc, context={
            "run_id": "r", "epoch": 1, "step": 2, "extra": 1}))


# -- every supervisor exit path dumps one --------------------------------
def _sup(prefix, **kw):
    kw.setdefault("backoff", 0.01)
    kw.setdefault("seed", 0)
    kw.setdefault("blackbox", prefix)
    return supervisor.Supervisor(**kw)


def _load_box(prefix):
    path = tracing.blackbox_path(prefix)
    assert os.path.exists(path), "no black box dumped"
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    tracing.validate_blackbox(doc)
    return doc


def _chain(doc, kind, *wanted):
    """Injection -> detection -> decision share (epoch, generation)."""
    evs = doc["events"]
    inj = [e for e in evs if e["event"] == "chaos.inject"
           and e["data"]["kind"] == kind]
    assert inj, [e["event"] for e in evs]
    key = (inj[0]["epoch"], inj[0]["generation"])
    got = [e["event"] for e in evs if (e["epoch"], e["generation"]) == key]
    for name in wanted:
        assert name in got, (kind, name, got)
    return inj[0]


def test_blackbox_on_watchdog_restart(tmp_path):
    prefix = str(tmp_path / "ck")
    sup = _sup(prefix, restore_fn=lambda: 0, deadline=0.2,
               compile_grace=0.0)
    armed = {"on": True}

    def epoch_fn(epoch):
        for _ in range(2):
            if epoch == 0 and armed["on"]:
                armed["on"] = False
                with chaos.enable(hang_step=1, hang_seconds=10.0):
                    sup.step(lambda: 1.0)
            else:
                sup.step(lambda: 1.0)

    res = sup.run(epoch_fn, num_epoch=2)
    assert res.ok and res.watchdog_fires == 1
    doc = _load_box(prefix)
    inj = _chain(doc, "hang", "supervisor.watchdog_fire",
                 "supervisor.classify", "supervisor.restart")
    assert inj["step"] == 1
    cls = [e for e in doc["events"] if e["event"] == "supervisor.classify"]
    assert cls[0]["data"]["kind"] == "transient"


def test_blackbox_on_numeric_rollback(tmp_path):
    prefix = str(tmp_path / "ck")
    net = nn.Dense(3, in_units=4)
    net.initialize()
    sup = _sup(prefix,
               save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
               restore_fn=lambda: elastic.auto_resume(prefix, net=net),
               skip_limit=1)
    armed = {"on": True}

    def epoch_fn(epoch):
        if epoch == 1 and armed["on"]:
            armed["on"] = False
            with chaos.enable(nan_after=1, nan_streak=2):
                for _ in range(3):
                    sup.step(lambda: 1.0)
        else:
            for _ in range(3):
                sup.step(lambda: 1.0)

    res = sup.run(epoch_fn, num_epoch=3)
    assert res.ok and res.rollbacks == 1
    doc = _load_box(prefix)
    _chain(doc, "nan", "supervisor.sentinel_skip", "supervisor.classify",
           "supervisor.rollback")
    skips = [e for e in doc["events"]
             if e["event"] == "supervisor.sentinel_skip"]
    assert skips and skips[0]["data"]["consecutive_bad"] == 1
    assert skips[0]["data"]["loss"] == "nan"  # strict-JSON encoding


def test_blackbox_on_transient_crash_restart(tmp_path):
    prefix = str(tmp_path / "ck")
    net = nn.Dense(3, in_units=4)
    net.initialize()
    sup = _sup(prefix,
               save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
               restore_fn=lambda: elastic.auto_resume(prefix, net=net))
    armed = {"on": True}

    def save_and_maybe_crash(epoch):
        if epoch == 1 and armed["on"]:
            armed["on"] = False
            with chaos.enable(crash_after_bytes=50, match=".params"):
                elastic.save_checkpoint(prefix, epoch, net=net)
        else:
            elastic.save_checkpoint(prefix, epoch, net=net)

    sup.save_fn = save_and_maybe_crash

    def epoch_fn(epoch):
        for _ in range(2):
            sup.step(lambda: 1.0)

    res = sup.run(epoch_fn, num_epoch=3)
    assert res.ok and res.restarts == 1
    doc = _load_box(prefix)
    _chain(doc, "crash", "supervisor.classify", "supervisor.restart")


def test_blackbox_on_degrade(tmp_path):
    prefix = str(tmp_path / "ck")
    sup = _sup(prefix, restore_fn=lambda: 0, max_restarts=1)

    def epoch_fn(epoch):
        raise OSError("persistent fault")

    res = sup.run(epoch_fn, num_epoch=2)
    assert res.status == "degraded"
    doc = _load_box(prefix)
    names = [e["event"] for e in doc["events"]]
    assert "supervisor.degrade" in names
    deg = [e for e in doc["events"]
           if e["event"] == "supervisor.degrade"][0]
    assert deg["data"]["budget"] == "restarts"
    assert "black box" not in doc["reason"] or doc["reason"]
    assert doc["reason"].startswith("degraded:")


def test_blackbox_on_sigterm_preemption(tmp_path):
    prefix = str(tmp_path / "ck")
    net = nn.Dense(3, in_units=4)
    net.initialize()
    handle = ckpt.preemption_handler(
        lambda: elastic.save_checkpoint(prefix, 0, net=net),
        exit=False, blackbox_prefix=prefix)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(200):
            if handle.triggered:
                break
            time.sleep(0.01)
    finally:
        handle.uninstall()
    assert handle.triggered and handle.save_ok
    doc = _load_box(prefix)
    pre = [e for e in doc["events"]
           if e["event"] == "checkpoint.preemption"]
    assert pre and pre[0]["data"]["save_ok"] is True
    assert pre[0]["data"]["signum"] == signal.SIGTERM
    assert doc["reason"].startswith("preemption signal")


def test_blackbox_dump_failure_never_masks_the_fault(tmp_path,
                                                     monkeypatch):
    """A broken dump path must not turn a recoverable fault into a new
    crash — forensics are best-effort."""
    prefix = str(tmp_path / "ck")
    sup = _sup(prefix, restore_fn=lambda: 0, max_restarts=2)
    monkeypatch.setattr(tracing, "dump_blackbox",
                        lambda *a, **k: 1 / 0)
    armed = {"on": True}

    def epoch_fn(epoch):
        if armed["on"]:
            armed["on"] = False
            raise OSError("transient")

    res = sup.run(epoch_fn, num_epoch=1)
    assert res.ok and res.restarts == 1


# ---------------------------------------------------------------------------
# blackbox_report.py (rendered WITHOUT jax — subprocess-proven)
# ---------------------------------------------------------------------------
def _report(box_path, *extra):
    import subprocess
    import sys
    report = os.path.join(REPO, "tools", "blackbox_report.py")
    args = [box_path, *extra]
    code = ("import sys, runpy; "
            "sys.modules['jax'] = None; sys.modules['tpu_mx'] = None; "
            f"sys.argv = ['blackbox_report.py'] + {list(args)!r}; "
            f"runpy.run_path({report!r}, run_name='__main__')")
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)


def test_blackbox_report_renders_without_jax(tmp_path):
    tracing.set_context(epoch=2, step=3, generation=0)
    tracing.emit("chaos.inject", kind="hang")
    tracing.emit("supervisor.watchdog_fire", name="step@epoch2",
                 deadline_seconds=30.0)
    tracing.emit("supervisor.classify", kind="transient",
                 error="WatchdogTimeout", message="hung")
    tracing.emit("supervisor.restart", n=2, backoff_seconds=0.5,
                 resume_epoch=3)
    path = tracing.dump_blackbox(str(tmp_path / "ck"), reason="unit")
    run = _report(path, "--validate")
    assert run.returncode == 0, run.stdout + run.stderr
    out = run.stdout
    # the human-readable chain the ISSUE asks for, one line
    assert "chaos hang injected -> watchdog fired at 30s -> " \
           "classified transient (WatchdogTimeout) -> " \
           "restart #2 from epoch 3" in out
    assert "epoch 2 step 3:" in out
    assert "schema OK" in out


def test_blackbox_report_validate_fails_on_bad_box(tmp_path):
    path = str(tmp_path / "bad-blackbox.json")
    doc = tracing.blackbox_doc()
    doc["events"] = [{"event": "not.in.catalog"}]
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(doc))
    run = _report(path, "--validate")
    assert run.returncode == 1
    assert "VALIDATION FAILED" in run.stderr
    # without --validate it still renders (post-mortems beat strictness)
    run2 = _report(path)
    assert run2.returncode == 0
    run3 = _report(str(tmp_path / "missing.json"))
    assert run3.returncode == 2
