"""gluon.contrib layers/cells/estimator (REF:tests/python/unittest/
test_gluon_contrib.py territory)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd, autograd, gluon
from tpu_mx.gluon import nn
from tpu_mx.gluon.contrib import nn as cnn
from tpu_mx.gluon.contrib import rnn as crnn
from tpu_mx.gluon.contrib.estimator import (CheckpointHandler,
                                            EarlyStoppingHandler, Estimator,
                                            LoggingHandler)


def test_concurrent_concat():
    net = cnn.HybridConcurrent(axis=-1)
    net.add(nn.Dense(3, in_units=4))
    net.add(nn.Dense(5, in_units=4))
    net.add(cnn.Identity())
    net.initialize()
    x = nd.array(np.random.rand(2, 4).astype(np.float32))
    out = net(x)
    assert out.shape == (2, 3 + 5 + 4)
    np.testing.assert_allclose(np.asarray(out._data)[:, -4:],
                               np.asarray(x._data), rtol=1e-6)


def test_pixelshuffle_2d_matches_manual():
    ps = cnn.PixelShuffle2D(2)
    x = np.arange(1 * 8 * 2 * 3, dtype=np.float32).reshape(1, 8, 2, 3)
    out = np.asarray(ps(nd.array(x))._data)
    assert out.shape == (1, 2, 4, 6)
    # manual: (N, C r1 r2, H, W) -> (N, C, H r1, W r2)
    ref = x.reshape(1, 2, 2, 2, 2, 3).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(1, 2, 4, 6)
    np.testing.assert_array_equal(out, ref)


def test_pixelshuffle_1d_and_3d_shapes():
    x1 = nd.array(np.random.rand(2, 6, 5).astype(np.float32))
    assert cnn.PixelShuffle1D(3)(x1).shape == (2, 2, 15)
    x3 = nd.array(np.random.rand(1, 16, 2, 3, 4).astype(np.float32))
    assert cnn.PixelShuffle3D(2)(x3).shape == (1, 2, 4, 6, 8)


def test_sync_batchnorm_global_stats_under_dp_mesh():
    """The TPU-native sync-BN property: with the batch sharded over an
    8-device dp mesh, BatchNorm statistics are computed over the GLOBAL
    batch (GSPMD all-reduces the partial moments) — per-shard stats would
    give a different output for a heterogeneous batch."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    bn = cnn.SyncBatchNorm(in_channels=4, num_devices=8)
    bn.initialize()
    # heterogeneous batch: each of 8 shards has a wildly different scale,
    # so per-shard normalization != global normalization
    x = np.concatenate([np.random.RandomState(i).randn(2, 4, 3, 3) *
                        (10.0 ** (i % 4)) for i in range(8)]).astype(
        np.float32)

    with autograd.record():
        ref = bn(nd.array(x))  # single-device: global stats by definition
    ref = np.asarray(ref._data)

    devices = jax.devices("cpu")[:8]
    mesh = Mesh(np.array(devices), ("dp",))
    sharded = jax.device_put(x, NamedSharding(mesh, P("dp")))
    params = {k: p.data()._data for k, p in bn.collect_params().items()}

    def fwd(pm, xx):
        out, _ = bn._functional_call(pm, jax.random.PRNGKey(0), True, (xx,))
        return out

    with mesh:
        out = jax.jit(fwd)(params, sharded)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_lstmp_cell_projection_shapes():
    cell = crnn.LSTMPCell(hidden_size=8, projection_size=5)
    cell.initialize()
    x = nd.array(np.random.rand(3, 4).astype(np.float32))
    states = cell.begin_state(batch_size=3)
    out, new_states = cell(x, states)
    assert out.shape == (3, 5)
    assert new_states[0].shape == (3, 5)   # projected h
    assert new_states[1].shape == (3, 8)   # cell state


def test_variational_dropout_locked_mask():
    base = crnn.LSTMPCell(hidden_size=6, projection_size=4)
    cell = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    cell.initialize()
    x = nd.array(np.ones((2, 3), np.float32))
    states = cell.begin_state(batch_size=2)
    with autograd.record():
        o1, states = cell(x, states)
        o2, states = cell(x, states)
    z1 = np.asarray(o1._data) == 0.0
    z2 = np.asarray(o2._data) == 0.0
    # locked mask: the SAME output units are dropped at both steps
    np.testing.assert_array_equal(z1, z2)
    assert z1.any()  # rate 0.5 on 8 units: P(no drop) = 2^-8
    # a new sequence (unroll resets) must redraw the mask eventually:
    # P(same 8-unit mask 12 times) = 2^-96
    seq = nd.array(np.ones((2, 3, 3), np.float32))
    changed = False
    for _ in range(12):
        with autograd.record():
            outs, _ = cell.unroll(3, seq, layout="NTC")
        z = np.asarray(outs._data)[:, 0, :] == 0.0
        if not np.array_equal(z, z1):
            changed = True
            break
    assert changed, "variational mask never redrawn across sequences"


@pytest.mark.parametrize("cell_cls,ndim", [
    (crnn.Conv1DLSTMCell, 1), (crnn.Conv2DLSTMCell, 2),
    (crnn.Conv2DGRUCell, 2), (crnn.Conv2DRNNCell, 2),
    (crnn.Conv3DLSTMCell, 3),
])
def test_conv_rnn_cells_step(cell_cls, ndim):
    spatial = (5, 6, 7)[:ndim]
    cell = cell_cls(hidden_channels=4, kernel=3,
                    input_shape=(3,) + spatial)
    cell.initialize()
    x = nd.array(np.random.rand(2, 3, *spatial).astype(np.float32))
    states = cell.begin_state(batch_size=2)  # input_shape makes this work
    out, states = cell(x, states)
    assert out.shape == (2, 4) + spatial
    out2, _ = cell(x, states)  # second step, same input channels
    assert out2.shape == (2, 4) + spatial
    assert not np.allclose(np.asarray(out._data), np.asarray(out2._data))


def test_conv_rnn_unroll_and_deferred_state_error():
    # unroll through the standard protocol, states from begin_state
    cell = crnn.Conv2DLSTMCell(hidden_channels=2, kernel=3,
                               input_shape=(1, 4, 4))
    cell.initialize()
    seq = nd.array(np.random.rand(2, 3, 1, 4, 4).astype(np.float32))
    outs, states = cell.unroll(3, seq, layout="NTC")
    assert outs.shape == (2, 3, 2, 4, 4)
    # without input_shape and before any forward: loud error
    cell2 = crnn.Conv2DLSTMCell(hidden_channels=2, kernel=3)
    with pytest.raises(mx.base.MXNetError, match="input_shape"):
        cell2.begin_state(batch_size=2)


@pytest.mark.slow
def test_conv_lstm_unroll_learns():
    """2-step unrolled Conv2DLSTM regression — checks grads flow through
    the recurrent conv."""
    cell = crnn.Conv2DLSTMCell(hidden_channels=2, kernel=3)
    cell.initialize()
    head = nn.Dense(1, flatten=True)
    head.initialize()
    params = list(cell.collect_params().values()) + \
        list(head.collect_params().values())
    xs = [nd.array(np.random.RandomState(i).rand(4, 1, 4, 4)
                   .astype(np.float32)) for i in range(2)]
    target = nd.array(np.random.RandomState(9).rand(4, 1)
                      .astype(np.float32))
    trainer = gluon.Trainer({p.name: p for p in params}, "adam",
                            {"learning_rate": 0.05})
    first = None
    for it in range(12):
        states = [nd.zeros((4, 2, 4, 4)), nd.zeros((4, 2, 4, 4))]
        with autograd.record():
            out = None
            for x in xs:
                out, states = cell(x, states)
            pred = head(out)
            loss = ((pred - target) ** 2).mean()
        loss.backward()
        trainer.step(4)
        v = float(np.asarray(loss._data))
        first = v if first is None else first
    assert v < first, (first, v)


def test_estimator_fit_and_early_stop(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8))
    net.add(nn.Dense(2, in_units=16))
    net.initialize()
    net.hybridize()
    X = np.random.RandomState(0).rand(64, 8).astype(np.float32)
    Y = (X.sum(axis=1) > 4.0).astype(np.float32)
    data = [(nd.array(X[i:i + 16]), nd.array(Y[i:i + 16]))
            for i in range(0, 64, 16)]
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 0.05}))
    ckpt = CheckpointHandler(str(tmp_path), max_checkpoints=2)
    early = EarlyStoppingHandler(monitor="loss", patience=2, mode="min")
    est.fit(data, epochs=8, event_handlers=[ckpt, early,
                                            LoggingHandler(log_interval=100)])
    # loss metric decreased vs an untrained net / checkpoints written
    saved = list(tmp_path.glob("model-epoch*.params"))
    assert 1 <= len(saved) <= 2
    result = est.evaluate(data)
    assert result["loss"] < 0.69  # below chance-level CE
