"""Durability layer (tpu_mx/checkpoint.py) under injected faults.

Every claim in docs/robustness.md has a falsifying chaos test here:
atomic commit vs crash, manifest-vs-torn-write, retention safety, retry
backoff, preemption-handler emergency save, and the kvstore persistence
satellites (ISSUE 2)."""
import json
import os
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, nd
from tpu_mx.base import MXNetError
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dense(value=1.0):
    net = nn.Dense(3, in_units=4)
    net.initialize()
    net.weight.set_data(nd.full((3, 4), float(value)))
    return net


# ---------------------------------------------------------------------------
# atomic_write
# ---------------------------------------------------------------------------
def test_atomic_write_commits_and_leaves_no_debris(tmp_path):
    p = tmp_path / "out.bin"
    with ckpt.atomic_write(str(p)) as f:
        f.write(b"hello durable world")
    assert p.read_bytes() == b"hello durable world"
    assert [x for x in os.listdir(tmp_path) if ".tmp." in x] == []


def test_atomic_write_exception_preserves_old_content(tmp_path):
    p = tmp_path / "out.bin"
    p.write_bytes(b"OLD")
    with pytest.raises(RuntimeError):
        with ckpt.atomic_write(str(p)) as f:
            f.write(b"NEW-PARTIAL")
            raise RuntimeError("writer blew up")
    assert p.read_bytes() == b"OLD"  # destination untouched
    assert [x for x in os.listdir(tmp_path) if ".tmp." in x] == []


def test_atomic_write_text_mode(tmp_path):
    p = tmp_path / "out.json"
    with ckpt.atomic_write(str(p), "w") as f:
        f.write(json.dumps({"a": 1}))
    assert json.loads(p.read_text()) == {"a": 1}


def test_chaos_crash_leaves_old_file_and_tmp_debris(tmp_path):
    """A simulated kill mid-write must look like a real one: destination
    keeps its previous content, the partial tmp file stays on disk, and a
    later (post-restart) save over the same path succeeds."""
    p = tmp_path / "state.bin"
    p.write_bytes(b"EPOCH1" * 10)
    with chaos.enable(crash_after_bytes=16) as cfg:
        with pytest.raises(chaos.ChaosCrash):
            with ckpt.atomic_write(str(p)) as f:
                f.write(b"EPOCH2" * 100)
    assert cfg.crashes == 1
    assert p.read_bytes() == b"EPOCH1" * 10
    debris = [x for x in os.listdir(tmp_path) if ".tmp." in x]
    assert debris, "a crash leaves the partial tmp file behind"
    # recovery save (chaos disarmed) goes through cleanly
    with ckpt.atomic_write(str(p)) as f:
        f.write(b"EPOCH2" * 100)
    assert p.read_bytes() == b"EPOCH2" * 100


# ---------------------------------------------------------------------------
# manifests + verification
# ---------------------------------------------------------------------------
def test_manifest_roundtrip_verifies(tmp_path):
    prefix = str(tmp_path / "ck")
    nd.save(f"{prefix}-0001.params", {"w": nd.ones((2, 2))})
    man = ckpt.write_manifest(prefix, 1, [f"{prefix}-0001.params"])
    assert man["format"] == ckpt.MANIFEST_FORMAT
    assert "ck-0001.params" in man["files"]
    assert man["files"]["ck-0001.params"]["size"] > 0
    status, problems = ckpt.verify_checkpoint(prefix, 1)
    assert (status, problems) == ("verified", [])


def test_verify_flags_torn_file_explicitly(tmp_path):
    """The acceptance-criteria check: a torn write (disk bytes < intended
    bytes) is named file-by-file by verify_checkpoint."""
    prefix = str(tmp_path / "ck")
    with chaos.enable(torn_write=64, match=".params") as cfg:
        nd.save(f"{prefix}-0001.params", {"w": nd.ones((8, 8))})
        ckpt.write_manifest(prefix, 1, [f"{prefix}-0001.params"])
    assert cfg.tears >= 1
    assert os.path.getsize(f"{prefix}-0001.params") == 64
    status, problems = ckpt.verify_checkpoint(prefix, 1)
    assert status == "corrupt"
    assert any("ck-0001.params" in p and "torn" in p for p in problems), \
        problems


def test_verify_flags_bitrot_via_sha256(tmp_path):
    prefix = str(tmp_path / "ck")
    nd.save(f"{prefix}-0001.params", {"w": nd.ones((4, 4))})
    ckpt.write_manifest(prefix, 1, [f"{prefix}-0001.params"])
    # same-size corruption: size check passes, sha256 must catch it
    with open(f"{prefix}-0001.params", "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    status, problems = ckpt.verify_checkpoint(prefix, 1)
    assert status == "corrupt"
    assert any("sha256" in p for p in problems), problems


def test_verify_missing_file_and_legacy_status(tmp_path):
    prefix = str(tmp_path / "ck")
    nd.save(f"{prefix}-0001.params", {"w": nd.ones((2, 2))})
    ckpt.write_manifest(prefix, 1, [f"{prefix}-0001.params"])
    os.remove(f"{prefix}-0001.params")
    status, problems = ckpt.verify_checkpoint(prefix, 1)
    assert status == "corrupt" and any("missing" in p for p in problems)
    # manifest-less epoch with files on disk = legacy (loadable, unverified)
    nd.save(f"{prefix}-0002.params", {"w": nd.ones((2, 2))})
    assert ckpt.verify_checkpoint(prefix, 2)[0] == "legacy"
    # nothing at all = corrupt
    assert ckpt.verify_checkpoint(prefix, 3)[0] == "corrupt"


def test_unreadable_manifest_is_corrupt_not_crash(tmp_path):
    prefix = str(tmp_path / "ck")
    nd.save(f"{prefix}-0001.params", {"w": nd.ones((2, 2))})
    with open(ckpt.manifest_path(prefix, 1), "w") as f:
        f.write('{"format": "tpu_mx-manifest-v1", "files": {')  # truncated
    status, problems = ckpt.verify_checkpoint(prefix, 1)
    assert status == "corrupt" and any("unreadable" in p for p in problems)


def test_update_manifest_adds_states_file(tmp_path):
    prefix = str(tmp_path / "ck")
    nd.save(f"{prefix}-0001.params", {"w": nd.ones((2, 2))})
    ckpt.write_manifest(prefix, 1, [f"{prefix}-0001.params"])
    with ckpt.atomic_write(f"{prefix}-0001.states") as f:
        f.write(pickle.dumps({"momentum": 0.9}))
    ckpt.update_manifest(prefix, 1, [f"{prefix}-0001.states"])
    man = ckpt.read_manifest(prefix, 1)
    assert set(man["files"]) == {"ck-0001.params", "ck-0001.states"}
    assert ckpt.verify_checkpoint(prefix, 1)[0] == "verified"


# ---------------------------------------------------------------------------
# retention
# ---------------------------------------------------------------------------
def _write_epoch(prefix, epoch, value):
    nd.save(f"{prefix}-{epoch:04d}.params", {"w": nd.full((2, 2), value)})
    ckpt.write_manifest(prefix, epoch, [f"{prefix}-{epoch:04d}.params"])


def test_retention_keeps_last_k(tmp_path):
    prefix = str(tmp_path / "ck")
    for e in range(1, 6):
        _write_epoch(prefix, e, e)
    removed = ckpt.apply_retention(prefix, keep_last=2)
    assert removed == [1, 2, 3]
    assert ckpt.list_epochs(prefix) == [4, 5]
    assert ckpt.verify_checkpoint(prefix, 5)[0] == "verified"


def test_retention_never_deletes_newest_verified(tmp_path):
    """keep_last=1 with a corrupt newest epoch must still keep the newest
    VERIFIED epoch — retention can't destroy the only recovery point."""
    prefix = str(tmp_path / "ck")
    for e in (1, 2, 3):
        _write_epoch(prefix, e, e)
    # corrupt the newest epoch's params (truncate under the manifest)
    with open(f"{prefix}-0003.params", "r+b") as f:
        f.truncate(16)
    assert ckpt.verify_checkpoint(prefix, 3)[0] == "corrupt"
    removed = ckpt.apply_retention(prefix, keep_last=1)
    assert removed == [1]
    assert ckpt.list_epochs(prefix) == [2, 3]  # 2 = newest verified, kept
    assert ckpt.verify_checkpoint(prefix, 2)[0] == "verified"


def test_retention_spares_shared_symbol_json(tmp_path):
    """prefix-symbol.json is shared by every epoch: retention of old epochs
    must not delete it (the Module checkpoint layout)."""
    prefix = str(tmp_path / "net")
    sym_path = f"{prefix}-symbol.json"
    with open(sym_path, "w") as f:
        f.write("{}")
    for e in (1, 2, 3):
        nd.save(f"{prefix}-{e:04d}.params", {"w": nd.ones((2, 2))})
        ckpt.write_manifest(prefix, e,
                            [sym_path, f"{prefix}-{e:04d}.params"])
    ckpt.apply_retention(prefix, keep_last=1)
    assert os.path.exists(sym_path)
    assert ckpt.list_epochs(prefix) == [3]
    assert ckpt.verify_checkpoint(prefix, 3)[0] == "verified"


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------
def test_retry_transient_oserror_succeeds(monkeypatch):
    sleeps = []
    monkeypatch.setattr(ckpt.time, "sleep", sleeps.append)
    calls = []
    with chaos.enable(transient_oserror=2) as cfg:
        def op():
            calls.append(1)
            chaos.maybe_oserror("probe")
            return "ok"
        assert ckpt.retry(op, attempts=4, seed=0) == "ok"
    assert len(calls) == 3 and cfg.oserrors_fired == 2
    assert len(sleeps) == 2
    # jittered exponential growth: second sleep strictly above base*2 floor
    assert sleeps[0] >= 0.05 and sleeps[1] >= 0.10


def test_retry_exhaustion_reraises(monkeypatch):
    monkeypatch.setattr(ckpt.time, "sleep", lambda s: None)
    with chaos.enable(transient_oserror=10):
        def op():
            chaos.maybe_oserror("probe")
        with pytest.raises(OSError, match="transient"):
            ckpt.retry(op, attempts=3, seed=0)


def test_retry_never_swallows_chaos_crash(monkeypatch):
    """A simulated kill is not a transient error: retry must re-raise it
    immediately instead of retrying a 'crashed' process."""
    monkeypatch.setattr(ckpt.time, "sleep", lambda s: None)
    calls = []
    def op():
        calls.append(1)
        raise chaos.ChaosCrash("dead")
    with pytest.raises(chaos.ChaosCrash):
        ckpt.retry(op, attempts=5, seed=0)
    assert len(calls) == 1


def test_retry_backoff_deterministic_under_seed(monkeypatch):
    def run():
        sleeps = []
        monkeypatch.setattr(ckpt.time, "sleep", sleeps.append)
        def op():
            if len(sleeps) < 3:
                raise OSError("flaky fs")
            return "done"
        assert ckpt.retry(op, attempts=5, seed=42) == "done"
        return sleeps
    assert run() == run()


# ---------------------------------------------------------------------------
# TPUMX_CHAOS env parsing
# ---------------------------------------------------------------------------
def test_chaos_env_config_parsing(monkeypatch):
    monkeypatch.setenv(
        "TPUMX_CHAOS", "torn_write=128,match=.params,seed=7,slow_io=0.5")
    monkeypatch.setattr(chaos, "_env_parsed", False)
    monkeypatch.setattr(chaos, "_config", None)
    cfg = chaos.configure_from_env()
    assert cfg.torn_write == 128 and cfg.match == ".params"
    assert cfg.seed == 7 and cfg.slow_io == 0.5
    assert cfg.matches("x-0001.params") and not cfg.matches("x.manifest.json")
    monkeypatch.setattr(chaos, "_config", None)  # disarm for other tests


def test_chaos_env_not_parsed_when_unset(monkeypatch):
    monkeypatch.delenv("TPUMX_CHAOS", raising=False)
    monkeypatch.setattr(chaos, "_env_parsed", False)
    monkeypatch.setattr(chaos, "_config", None)
    assert chaos.configure_from_env() is None


# ---------------------------------------------------------------------------
# module/model checkpoint path commits a manifest
# ---------------------------------------------------------------------------
def _mlp_sym():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    return mx.sym.SoftmaxOutput(fc, mx.sym.Variable("softmax_label"),
                                name="softmax")


def test_module_checkpoint_commits_verified_manifest(tmp_path):
    prefix = str(tmp_path / "mlp")
    mod = mx.module.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Normal(0.1))
    mod.init_optimizer(optimizer="sgd")
    mod.save_checkpoint(prefix, 3, save_optimizer_states=True)
    status, problems = ckpt.verify_checkpoint(prefix, 3)
    assert (status, problems) == ("verified", [])
    man = ckpt.read_manifest(prefix, 3)
    # the shared, every-save-rewritten symbol.json is deliberately NOT in
    # the verified file set (it would corrupt older epochs on a symbol
    # change); its save-time hash rides the unverified "shared" table
    assert set(man["files"]) == {"mlp-0003.params", "mlp-0003.states"}
    assert man["shared"]["mlp-symbol.json"]["sha256"]
    assert man["git_head"] and man["epoch"] == 3


# ---------------------------------------------------------------------------
# orbax (CompiledTrainStep) commit marker + fallback
# ---------------------------------------------------------------------------
def _small_step():
    from tpu_mx import gluon
    from tpu_mx.parallel import CompiledTrainStep, make_mesh
    mx.random.seed(3)
    net = nn.Dense(4, in_units=8, prefix="ckstep_")
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    return CompiledTrainStep(net, loss_fn, opt, mesh=make_mesh({"dp": 8}))


def test_orbax_commit_marker_and_fallback(tmp_path):
    step = _small_step()
    x = nd.array(np.random.RandomState(0).rand(8, 8).astype(np.float32))
    y = nd.array(np.arange(8, dtype=np.float32) % 4)
    step.step(x, y)
    good = str(tmp_path / "good")
    step.save_checkpoint(good)
    marker = step.commit_marker_path(good)
    assert os.path.exists(marker)
    assert json.load(open(marker))["format"] == "tpu_mx-orbax-commit-v1"

    step.step(x, y)
    uncommitted = str(tmp_path / "uncommitted")
    step.save_checkpoint(uncommitted)
    os.remove(step.commit_marker_path(uncommitted))  # simulate interruption

    fresh = _small_step()
    restored = fresh.load_checkpoint(uncommitted, fallback_paths=[good])
    assert restored == os.path.abspath(good)  # marker-less primary skipped
    assert fresh._t == 1

    with pytest.raises(MXNetError, match="no restorable checkpoint"):
        fresh.load_checkpoint(str(tmp_path / "never-existed"),
                              fallback_paths=[str(tmp_path / "also-missing")])


def test_orbax_back_to_back_async_saves_both_get_markers(tmp_path):
    """A second async save must not orphan the first save's pending commit
    marker: both checkpoints end up verified."""
    step = _small_step()
    x = nd.array(np.random.RandomState(0).rand(8, 8).astype(np.float32))
    y = nd.array(np.arange(8, dtype=np.float32) % 4)
    step.step(x, y)
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    step.save_checkpoint(a, block=False)
    step.step(x, y)
    step.save_checkpoint(b, block=False)  # no wait_for_checkpoint between
    step.wait_for_checkpoint()
    assert os.path.exists(step.commit_marker_path(a))
    assert os.path.exists(step.commit_marker_path(b))
    # each marker records the t of the state it SAVED, not stamp-time t
    assert json.load(open(step.commit_marker_path(a)))["t"] == 1
    assert json.load(open(step.commit_marker_path(b)))["t"] == 2


def test_chaos_torn_write_text_mode_byte_boundary(tmp_path):
    """Byte-count faults apply to the utf-8 ENCODING in text mode: a
    multi-byte payload tears at the configured byte offset (nearest char
    boundary at-or-before it), not at a character count."""
    p = tmp_path / "unicode.json"
    payload = "é" * 50  # 2 bytes per char: 100 bytes, 50 chars
    with chaos.enable(torn_write=25) as cfg:
        with ckpt.atomic_write(str(p), "w") as f:
            f.write(payload)
    assert cfg.tears == 1
    on_disk = p.read_bytes()
    assert len(on_disk) == 24  # 25 splits an 'é': partial byte dropped
    assert on_disk.decode("utf-8") == "é" * 12


# ---------------------------------------------------------------------------
# preemption handler
# ---------------------------------------------------------------------------
def test_preemption_handler_in_process(tmp_path):
    """SIGINT triggers exactly one emergency save; uninstall restores the
    previous handler (in-process variant: exit=False)."""
    prefix = str(tmp_path / "pre")
    net = _dense(5.0)
    saves = []
    def save():
        saves.append(1)
        mx.elastic.save_checkpoint(prefix, 9, net=net)
    h = ckpt.preemption_handler(save, signals=(signal.SIGUSR1,), exit=False)
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0.05)
        # reentrancy guard: a second delivery must not save twice (after
        # the first fire the handler restores the previous disposition, so
        # exercise the guard by invoking the handler body directly)
        h._handle(signal.SIGUSR1, None)
    finally:
        h.uninstall()
    assert h.triggered and h.save_ok and saves == [1]
    assert ckpt.verify_checkpoint(prefix, 9)[0] == "verified"
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 10
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 5.0)


@pytest.mark.slow
def test_preemption_handler_sigterm_subprocess(tmp_path):
    """The real contract: a SIGTERM'd training process writes one durable,
    resumable checkpoint on its way out (exit code 128+15)."""
    prefix = str(tmp_path / "job")
    script = tmp_path / "train.py"
    script.write_text(
        "import sys, time\n"
        "import tpu_mx as mx\n"
        "from tpu_mx import nd\n"
        "from tpu_mx.gluon import nn\n"
        f"prefix = {str(prefix)!r}\n"
        "net = nn.Dense(3, in_units=4)\n"
        "net.initialize()\n"
        "net.weight.set_data(nd.full((3, 4), 7.0))\n"
        "epoch = [4]\n"
        "h = mx.checkpoint.preemption_handler(\n"
        "    lambda: mx.elastic.save_checkpoint(prefix, epoch[0], net=net))\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)  # 'training'; the driver SIGTERMs us mid-sleep\n")
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = proc.stdout.readline()
        assert "READY" in line, line
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        proc.kill()
    assert rc == 128 + signal.SIGTERM
    assert ckpt.verify_checkpoint(prefix, 4)[0] == "verified"
    net2 = nn.Dense(3, in_units=4)
    assert mx.elastic.auto_resume(prefix, net=net2) == 5
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 7.0)


# ---------------------------------------------------------------------------
# kvstore persistence satellites
# ---------------------------------------------------------------------------
def test_kvstore_uninitialized_key_raises_mxnet_error():
    kv = mx.kv.create("local")
    with pytest.raises(MXNetError, match="not initialized; call kv.init"):
        kv.push("w", nd.ones((3,)))
    with pytest.raises(MXNetError, match="not initialized; call kv.init"):
        kv.pull("w", out=nd.zeros((3,)))
    kv.init("w", nd.zeros((3,)))
    kv.push("w", nd.ones((3,)))  # initialized: fine
    out = nd.zeros((3,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0)


def test_kvstore_dump_optimizer_roundtrip(tmp_path):
    fname = str(tmp_path / "opt.states")
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.25,
                                         momentum=0.9))
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.ones((4,)))
    kv.save_optimizer_states(fname, dump_optimizer=True)
    # a FRESH kvstore with no optimizer set restores both states and the
    # optimizer object (the reference's PS-server pickle contract)
    kv2 = mx.kv.create("local")
    kv2.load_optimizer_states(fname)
    assert kv2._optimizer is not None
    assert kv2._optimizer.lr == 0.25 and kv2._optimizer.momentum == 0.9
    assert kv2._updater is not None
    assert set(kv2._updater.get_states()) == set(kv._updater.get_states())


def test_kvstore_states_without_optimizer_stays_legacy_format(tmp_path):
    fname = str(tmp_path / "opt.states")
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd"))
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.ones((4,)))
    kv.save_optimizer_states(fname)  # dump_optimizer=False (default)
    with open(fname, "rb") as f:
        payload = pickle.load(f)
    assert "__tpumx_format__" not in payload  # bare states dict, as before
    kv2 = mx.kv.create("local")
    kv2.set_optimizer(mx.optimizer.create("sgd"))
    kv2.load_optimizer_states(fname)
    assert set(kv2._updater.get_states()) == set(kv._updater.get_states())


# ---------------------------------------------------------------------------
# shared symbol.json vs per-epoch manifests (the parked ROADMAP bug)
# ---------------------------------------------------------------------------
def _module_symbol(extra_layer=False):
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc1")
    if extra_layer:
        fc = mx.sym.Activation(fc, act_type="relu", name="relu1")
    return fc


def test_symbol_rewrite_keeps_older_epochs_verified(tmp_path):
    """`{prefix}-symbol.json` is rewritten by EVERY model.save_checkpoint;
    listing it in per-epoch manifests made a later save with a changed
    symbol flip every older epoch to "corrupt", defeating the
    fall-back-to-older-epoch contract.  It is excluded now (its content
    hash rides the manifest's unverified "shared" table instead)."""
    prefix = str(tmp_path / "m")
    arg = {"fc1_weight": nd.ones((3, 4)), "fc1_bias": nd.zeros((3,))}
    mx.model.save_checkpoint(prefix, 0, _module_symbol(), arg, {})
    mx.model.save_checkpoint(prefix, 1, _module_symbol(), arg, {})
    man = ckpt.read_manifest(prefix, 0)
    assert "m-symbol.json" not in man["files"]
    assert man["shared"]["m-symbol.json"]["sha256"]

    # the symbol CHANGES (a new layer): older epochs must stay verified
    mx.model.save_checkpoint(prefix, 2, _module_symbol(extra_layer=True),
                             arg, {})
    for epoch in (0, 1, 2):
        assert ckpt.verify_checkpoint(prefix, epoch)[0] == "verified", epoch

    # torn-fallback proof: corrupt the newest epoch's params; the elastic
    # path must fall back to epoch 1 — which a symbol-bearing manifest
    # would have declared corrupt too, leaving nothing to resume from
    with open(f"{prefix}-0002.params", "r+b") as f:
        f.truncate(10)
    assert ckpt.verify_checkpoint(prefix, 2)[0] == "corrupt"
    assert mx.elastic.latest_checkpoint(prefix)[0] == 1
    sym, arg2, aux2 = mx.model.load_checkpoint(prefix, 1)
    np.testing.assert_allclose(arg2["fc1_weight"].asnumpy(), 1.0)


def test_module_save_checkpoint_states_ride_manifest_after_symbol_fix(
        tmp_path):
    """Module.save_checkpoint(save_optimizer_states=True) still folds the
    .states file into the (symbol-less) manifest."""
    from tpu_mx.io.io import DataBatch
    prefix = str(tmp_path / "mod")
    sym = mx.sym.SoftmaxOutput(_module_symbol(),
                               mx.sym.Variable("softmax_label"))
    mod = mx.module.Module(sym, context=[mx.cpu()])
    mod.bind(data_shapes=[("data", (2, 4))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    mod.init_optimizer()
    mod.forward_backward(DataBatch(data=[nd.ones((2, 4))],
                                   label=[nd.zeros((2,))]))
    mod.update()
    mod.save_checkpoint(prefix, 3, save_optimizer_states=True)
    man = ckpt.read_manifest(prefix, 3)
    assert set(man["files"]) == {"mod-0003.params", "mod-0003.states"}
    assert "mod-symbol.json" not in man["files"]
    assert ckpt.verify_checkpoint(prefix, 3)[0] == "verified"
