"""Zero-regeneration serving recovery (tpu_mx/serving/) — ISSUE 19.

Covers: the committed-token journal (durability discipline, never-guess
recovery semantics, compaction), prefill-replay restarts (restart-storm
stream bit-equality across decode modes × sharing × sampling, the
exactly-one-prefill receipt, sharing-aware replay), cross-process
kill −9 recovery (a real ``os._exit(137)`` inside a decode step, a new
process resuming every stream from the journal), graceful drain and hot
engine handoff (zero client-visible failures, nothing re-yielded), and
the per-request samplers whose RNG-is-data capsules make non-greedy
streams replayable."""
import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from tpu_mx import telemetry, tracing
from tpu_mx.base import MXNetError
from tpu_mx.contrib import chaos
from tpu_mx.serving import AdmissionReject, Request, Server, TinyLM
from tpu_mx.serving import journal as journal_mod
from tpu_mx.serving.journal import TokenJournal, journal_path
from tpu_mx.serving.sampling import (GreedySampler, TopKSampler, fold_seed,
                                     make_sampler, parse_sampling)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Tracing/telemetry state is process-global — isolate every test."""
    tracing.reset()
    telemetry.reset()
    yield
    tracing.reset()
    telemetry.reset()


def tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("seed", 0)
    return TinyLM(**kw)


def counter_value(name, **labels):
    c = telemetry.get(name, **labels)
    return 0 if c is None else c.value


def clean_reference(prompts, max_new, **server_kw):
    """The uninterrupted run every recovery path must bit-match."""
    srv = Server(tiny(), num_blocks=256, **server_kw)
    reqs = [srv.submit(p, max_new, request_id=f"r{i}")
            for i, p in enumerate(prompts)]
    srv.run_until_idle()
    return [list(r.tokens) for r in reqs]


# ---------------------------------------------------------------------------
# samplers: the RNG-is-data unit of replayability
# ---------------------------------------------------------------------------
def test_parse_sampling_specs_and_rejects():
    assert parse_sampling("greedy") == ("greedy", None)
    assert parse_sampling("") == ("greedy", None)   # unset -> default
    assert parse_sampling("top_k:8") == ("top_k", 8)
    for bad in ("top_k", "top_k:0", "top_k:x", "nucleus:0.9"):
        with pytest.raises(MXNetError):
            parse_sampling(bad)


def test_fold_seed_is_deterministic_and_id_sensitive():
    assert fold_seed(7, "r1") == fold_seed(7, "r1")
    assert fold_seed(7, "r1") != fold_seed(7, "r2")
    assert fold_seed(7, "r1") != fold_seed(8, "r1")


def test_top_k_sampler_state_roundtrip_resumes_mid_roll():
    logits = np.linspace(-1.0, 1.0, 64)
    a = TopKSampler(8, seed=123)
    first = [a.sample(logits) for _ in range(5)]
    capsule = a.state_dict()
    rest = [a.sample(logits) for _ in range(5)]
    # a FRESH sampler loaded from the capsule continues the same roll
    b = TopKSampler(8, seed=0)
    b.load_state_dict(capsule)
    assert [b.sample(logits) for _ in range(5)] == rest
    # reset() rewinds to the construction-time state
    a.reset()
    assert [a.sample(logits) for _ in range(5)] == first
    # capsule kind/k mismatches refuse loudly
    with pytest.raises(MXNetError):
        TopKSampler(4, seed=0).load_state_dict(capsule)
    assert make_sampler("greedy", None, 0) is None


# ---------------------------------------------------------------------------
# the journal file: durability + never-guess recovery
# ---------------------------------------------------------------------------
def _journal_with_traffic(prefix, n_tokens=4):
    j = TokenJournal(prefix)
    req = Request([1, 2, 3], 8, request_id="r1")
    j.begin(req)
    for t in range(n_tokens):
        req.tokens.append(10 + t)
        j.commit_token(req, 10 + t)
    j.flush()
    return j, req


def test_journal_roundtrip_and_end_retires(tmp_path):
    j, req = _journal_with_traffic(str(tmp_path / "j"))
    entries = journal_mod.load(j.path)
    e = entries["r1"]
    assert e["prompt"] == [1, 2, 3] and e["max_new"] == 8
    assert e["tokens"] == [10, 11, 12, 13]
    assert not e["ended"] and not e["fallback"]
    j.end(req, "length")
    j.flush()
    assert journal_mod.load(j.path)["r1"]["ended"]
    j.close()


def test_journal_compact_drops_retired_keeps_live(tmp_path):
    j, req = _journal_with_traffic(str(tmp_path / "j"))
    done = Request([9], 1, request_id="done")
    j.begin(done)
    done.tokens.append(5)
    j.commit_token(done, 5)
    j.end(done, "length")
    j.flush()
    assert j.compact() == 1
    entries = journal_mod.load(j.path)
    assert set(entries) == {"r1"}
    assert entries["r1"]["tokens"] == [10, 11, 12, 13]
    # the compacted file is a valid journal that accepts appends
    req.tokens.append(14)
    j.commit_token(req, 14)
    j.flush()
    assert journal_mod.load(j.path)["r1"]["tokens"][-1] == 14
    j.close()


def test_journal_torn_final_line_dropped_loudly(tmp_path):
    j, _ = _journal_with_traffic(str(tmp_path / "j"))
    j.close()
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"op":"token","request":"r1","i":4,"tok')  # torn append
    e = journal_mod.load(j.path)["r1"]
    # the torn record was never fsync'd complete -> dropped; everything
    # BEFORE it is trusted (no fallback)
    assert e["tokens"] == [10, 11, 12, 13] and not e["fallback"]


def test_journal_midfile_corruption_degrades_all_unfinished(tmp_path):
    j, _ = _journal_with_traffic(str(tmp_path / "j"))
    j.close()
    lines = open(j.path, encoding="utf-8").read().splitlines()
    lines[2] = "NOT JSON"   # corrupt a middle record, keep later ones
    with open(j.path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    e = journal_mod.load(j.path)["r1"]
    # framing is gone: identity survives, tokens are FORFEITED — prompt
    # replay, never a guessed resume
    assert e["fallback"] and e["tokens"] == []


def test_journal_token_index_gap_degrades_that_stream(tmp_path):
    j, _ = _journal_with_traffic(str(tmp_path / "j"))
    j.close()
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"op":"token","request":"r1","i":9,"token":3,'
                '"rng":null}\n')
    e = journal_mod.load(j.path)["r1"]
    assert e["fallback"] and e["tokens"] == []


def test_journal_unknown_format_header_refuses(tmp_path):
    p = tmp_path / "weird-journal.jsonl"
    p.write_text('{"format":"somebody-elses-v9"}\n')
    with pytest.raises(MXNetError):
        journal_mod.load(str(p))


# ---------------------------------------------------------------------------
# prefill-replay restarts: bit-equality + the one-prefill receipt
# ---------------------------------------------------------------------------
PROMPTS = ([1, 2, 3], [1, 2, 4], [7, 8])


@pytest.mark.parametrize("paged", ["0", "1"])
@pytest.mark.parametrize("sharing", ["0", "1"])
@pytest.mark.parametrize("sampling", ["greedy", "top_k:8"])
def test_restart_storm_streams_bit_identical(monkeypatch, paged, sharing,
                                             sampling):
    """Three back-to-back classified restarts (chaos ``restart_storm``)
    mid-decode: every stream finishes bit-identical to the uninterrupted
    run, across decode modes × prefix sharing × sampling modes."""
    monkeypatch.setenv("TPUMX_PAGED_DECODE", paged)
    monkeypatch.setenv("TPUMX_PREFIX_SHARING", sharing)
    kw = dict(sampling=sampling, sampling_seed=11)
    ref = clean_reference(PROMPTS, 10, **kw)
    tracing.reset()
    srv = Server(tiny(), num_blocks=256, max_restarts=5, backoff=0.0, **kw)
    reqs = [srv.submit(p, 10, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    for _ in range(2):
        srv.step()   # commit a few tokens before the storm
    with chaos.enable(restart_storm=3) as cfg:
        srv.run_until_idle()
    assert cfg.storms_fired == 3 and srv.restarts == 3
    assert [list(r.tokens) for r in reqs] == ref
    # replay kept the ledger: requeues happened, nothing was re-decoded
    assert all(r.requeues >= 1 for r in reqs)
    assert counter_value("serve.redecode_tokens") == 0


def test_restart_recovery_is_one_prefill_no_redecode():
    """The acceptance receipt: recovery issues exactly one prefill per
    in-flight sequence — ``serve.replay_requests`` counts sequences,
    ``serve.replay_tokens`` counts their committed ledgers, and ZERO
    tokens are re-decoded."""
    srv = Server(tiny(), num_blocks=256, max_restarts=3, backoff=0.0)
    reqs = [srv.submit(p, 12, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    for _ in range(5):
        srv.step()
    committed = {r.id: len(r.tokens) for r in reqs}
    assert all(n >= 4 for n in committed.values())
    with chaos.enable(restart_storm=1):
        srv.run_until_idle()
    assert srv.restarts == 1
    assert counter_value("serve.replay_requests") == len(reqs)
    assert counter_value("serve.replay_tokens") == sum(committed.values())
    assert counter_value("serve.redecode_tokens") == 0
    # the serve.prefill events receipt the replay per sequence: one
    # replayed prefill per request, carrying its ledger length
    replays = [e for e in tracing.snapshot()
               if e["event"] == "serve.prefill"
               and e["data"]["replayed"] > 0]
    assert sorted(e["data"]["replayed"] for e in replays) == \
        sorted(committed.values())


def test_legacy_prompt_replay_arm_redecodes_and_charges_restart_penalty():
    """``replay=False`` keeps the old arm alive for the A/B: restarts
    discard the ledger, catch-up re-decodes are counted and charged to
    ``restart_penalty`` — the cost the replay arm removes."""
    ref = clean_reference(PROMPTS, 10)
    tracing.reset()
    srv = Server(tiny(), num_blocks=256, max_restarts=3, backoff=0.0,
                 replay=False)
    reqs = [srv.submit(p, 10, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    for _ in range(5):
        srv.step()
    committed = sum(len(r.tokens) for r in reqs)
    assert committed > 0
    with chaos.enable(restart_storm=1):
        srv.run_until_idle()
    assert [list(r.tokens) for r in reqs] == ref
    assert counter_value("serve.redecode_tokens") == committed
    assert counter_value("serve.replay_tokens") == 0
    for r in reqs:
        assert r.timeline.phases.get("restart_penalty", 0.0) > 0.0


def test_replay_rides_prefix_cache_across_restart(monkeypatch):
    """Satellite bugfix: with sharing on, N restarted requests carrying
    one template re-prefill the shared prefix ONCE — the replay path
    routes through match_prefix like any first-time prefill."""
    monkeypatch.setenv("TPUMX_PREFIX_SHARING", "1")
    template = list(range(1, 17))   # a full block of shared prefix
    prompts = [template + [50 + i] for i in range(3)]
    srv = Server(tiny(), num_blocks=256, max_restarts=3, backoff=0.0,
                 prefix_sharing=True)
    reqs = [srv.submit(p, 8, request_id=f"r{i}")
            for i, p in enumerate(prompts)]
    for _ in range(3):
        srv.step()
    with chaos.enable(restart_storm=1):
        srv.run_until_idle()
    assert all(r.state == "done" for r in reqs)
    st = srv.engine.cache.prefix_stats()
    # the REBUILT engine's index served replay hits: lookups/hits are
    # generation-local, so any hit here happened after the restart
    assert st["hits"] > 0, st
    assert st["cached_tokens"] > 0


# ---------------------------------------------------------------------------
# in-process journal recovery (the cross-process path minus the kill)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("sampling", ["greedy", "top_k:8"])
def test_journal_recover_resumes_bit_identical(tmp_path, sampling):
    kw = dict(sampling=sampling, sampling_seed=7)
    ref = clean_reference(PROMPTS, 12, **kw)
    prefix = str(tmp_path / "jr")
    tracing.reset()
    srv = Server(tiny(), num_blocks=256, journal=prefix, **kw)
    reqs = [srv.submit(p, 12, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    for _ in range(6):
        srv.step()
    mid = {r.id: list(r.tokens) for r in reqs}
    assert all(mid.values())
    # the process "dies" here: a brand-new server on the same journal
    tracing.reset()
    srv2 = Server(tiny(), num_blocks=256, journal=prefix, **kw)
    handles = srv2.recover()
    assert set(handles) == set(mid)
    for rid, h in handles.items():
        assert list(h.tokens) == mid[rid]   # the ledger survived intact
    srv2.run_until_idle()
    assert [list(handles[f"r{i}"].tokens)
            for i in range(len(PROMPTS))] == ref
    # a finished journal recovers to nothing left to do
    srv3 = Server(tiny(), num_blocks=256, journal=prefix, **kw)
    again = srv3.recover()
    assert all(h.state == "done" for h in again.values()) or not again


def test_recover_bypasses_admission_gates(tmp_path):
    """A server killed at full load journals more unfinished streams
    than its successor's ``max_pending`` — recovery must bypass the
    admission gates (``scheduler.restore``) instead of queue_full-
    rejecting the overflow and aborting the rest: zero lost streams."""
    prefix = str(tmp_path / "full")
    prompts = [[1, 2, 3 + i] for i in range(6)]
    ref = clean_reference(prompts, 8)
    srv = Server(tiny(), num_blocks=256, journal=prefix)
    reqs = [srv.submit(p, 8, request_id=f"r{i}")
            for i, p in enumerate(prompts)]
    for _ in range(3):
        srv.step()
    assert all(r.tokens for r in reqs)
    # the process "dies"; the successor is provisioned SMALLER than the
    # journaled load (max_pending=2 < 6 unfinished streams)
    srv2 = Server(tiny(), num_blocks=256, journal=prefix, max_pending=2)
    handles = srv2.recover()
    assert len(handles) == 6   # nothing rejected, nothing lost
    srv2.run_until_idle()
    assert [list(handles[f"r{i}"].tokens) for i in range(6)] == ref
    assert all(h.state == "done" for h in handles.values())


def test_sampling_runs_on_driver_thread_only():
    """Zombie-step discipline for sampler RNG: with the watchdog armed,
    engine prefill/decode run on abandoned-able daemon threads — the
    journaled RNG must only ever advance on the driver thread (the
    engine hands logits back; the server samples after the join)."""
    kw = dict(sampling="top_k:8", sampling_seed=3)
    ref = clean_reference(PROMPTS, 8, **kw)
    srv = Server(tiny(), num_blocks=256, deadline=30.0, **kw)
    reqs = [srv.submit(p, 8, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    sample_threads = set()
    for r in reqs:
        orig = r.sampler.sample

        def spy(logits, _orig=orig):
            sample_threads.add(threading.current_thread())
            return _orig(logits)

        r.sampler.sample = spy
    srv.run_until_idle()
    assert sample_threads == {threading.main_thread()}
    assert [list(r.tokens) for r in reqs] == ref


def test_rejected_submit_journal_entry_is_retired(tmp_path):
    """``begin`` lands before the request is schedulable, so a rejected
    admission must retire its entry — a recovering successor must never
    resurrect (and generate) a request whose client saw the reject."""
    prefix = str(tmp_path / "rej")
    srv = Server(tiny(), num_blocks=256, journal=prefix, max_pending=1)
    srv.submit([1, 2, 3], 8, request_id="kept")
    with pytest.raises(AdmissionReject) as e:
        srv.submit([4, 5, 6], 8, request_id="bounced")
    assert e.value.reason == "queue_full"
    entries = journal_mod.load(journal_path(prefix))
    assert entries["bounced"]["ended"]
    assert not entries["kept"]["ended"]
    srv2 = Server(tiny(), num_blocks=256, journal=prefix, max_pending=1)
    handles = srv2.recover()
    assert set(handles) == {"kept"}   # the reject stayed rejected
    srv2.run_until_idle()
    assert handles["kept"].state == "done"


@pytest.mark.parametrize("sampling", ["greedy", "top_k:8"])
def test_legacy_arm_requeue_keeps_journal_indices_consistent(
        tmp_path, sampling):
    """Journal armed on the legacy arm (``replay=False``): a restart
    discards the ledger and the re-rolled stream journals from i=0
    again — the requeue must re-begin the entry (last-incarnation-wins)
    or load()'s index-gap check degrades every stream to prompt replay."""
    prefix = str(tmp_path / "legacy")
    kw = dict(sampling=sampling, sampling_seed=9)
    srv = Server(tiny(), num_blocks=256, journal=prefix, replay=False,
                 max_restarts=3, backoff=0.0, **kw)
    reqs = [srv.submit(p, 10, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    for _ in range(4):
        srv.step()
    assert all(r.tokens for r in reqs)
    with chaos.enable(restart_storm=1):
        srv.step()   # classified restart: ledgers discarded, re-begin
    for _ in range(3):
        srv.step()   # the re-rolled streams journal from i=0 again
    entries = journal_mod.load(journal_path(prefix))
    for i, r in enumerate(reqs):
        e = entries[f"r{i}"]
        # no index-gap degrade, no duplicate-index confusion: the file
        # reads back as the LAST incarnation's consistent stream
        assert not e["fallback"]
        assert e["tokens"] == list(r.tokens)[:len(e["tokens"])]
    srv.run_until_idle()
    entries = journal_mod.load(journal_path(prefix))
    assert all(e["ended"] and not e["fallback"]
               for e in entries.values())


def test_recover_without_journal_is_loud():
    with pytest.raises(MXNetError):
        Server(tiny(), num_blocks=64).recover()


def test_recover_from_corrupt_journal_falls_back_to_prompt(tmp_path):
    """Torn mid-file journal: recovery NEVER guesses — the stream
    restarts from its prompt (fallback counted) and still completes
    with the deterministic greedy tokens."""
    ref = clean_reference([[1, 2, 3]], 8)
    prefix = str(tmp_path / "jr")
    srv = Server(tiny(), num_blocks=256, journal=prefix)
    srv.submit([1, 2, 3], 8, request_id="r0")
    for _ in range(4):
        srv.step()
    path = journal_path(prefix)
    lines = open(path, encoding="utf-8").read().splitlines()
    lines[2] = '{"op":'   # corrupt a middle record
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    tracing.reset()
    srv2 = Server(tiny(), num_blocks=256, journal=prefix)
    handles = srv2.recover()
    assert list(handles["r0"].tokens) == []   # forfeited, not guessed
    assert counter_value("serve.replay_fallbacks") == 1
    srv2.run_until_idle()
    assert list(handles["r0"].tokens) == ref[0]


# ---------------------------------------------------------------------------
# cross-process kill −9: the real thing
# ---------------------------------------------------------------------------
KILL9_CHILD = textwrap.dedent("""\
    import json, os, sys
    os.environ["TPUMX_CHAOS"] = "kill9_at_decode_step=4"
    from tpu_mx.serving import Server, TinyLM
    model = TinyLM(vocab_size=64, embed_dim=16, num_heads=2,
                   num_layers=2, seed=0)
    srv = Server(model, num_blocks=256, journal=sys.argv[1])
    prompts = [[1, 2, 3], [1, 2, 4], [7, 8]]
    for i, p in enumerate(prompts):
        srv.submit(p, 12, request_id=f"r{i}")
    srv.run_until_idle()   # dies at decode step 4 with os._exit(137)
    print("SHOULD NOT REACH HERE")
""")


def test_kill9_cross_process_recovery_zero_lost_tokens(tmp_path):
    """A REAL ``os._exit(137)`` inside a decode step (chaos
    ``kill9_at_decode_step``), then a fresh process recovers from the
    journal: every stream resumes exactly where the dead process's
    fsync'd ledger left it and finishes bit-identical to the
    uninterrupted run — zero lost, duplicated, or re-yielded tokens."""
    prefix = str(tmp_path / "k9")
    env = {k: v for k, v in os.environ.items() if k != "TPUMX_CHAOS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", KILL9_CHILD, prefix],
                          capture_output=True, text=True, timeout=120,
                          env=env, cwd="/root/repo")
    assert proc.returncode == 137, (proc.returncode, proc.stderr[-2000:])
    assert "SHOULD NOT REACH HERE" not in proc.stdout
    entries = journal_mod.load(journal_path(prefix))
    assert len(entries) == 3
    survivors = {rid: e["tokens"] for rid, e in entries.items()}
    assert any(survivors.values())   # the dead process committed work
    assert not any(e["fallback"] for e in entries.values())
    ref = clean_reference(PROMPTS, 12)
    tracing.reset()
    srv = Server(tiny(), num_blocks=256, journal=prefix)
    handles = srv.recover()
    for rid, h in handles.items():
        assert list(h.tokens) == survivors[rid]
    srv.run_until_idle()
    for i in range(3):
        got = list(handles[f"r{i}"].tokens)
        assert got == ref[i], (i, got, ref[i])
        # the committed prefix was NEVER regenerated: it is a prefix of
        # the final stream, untouched
        assert got[:len(survivors[f"r{i}"])] == survivors[f"r{i}"]
    assert counter_value("serve.redecode_tokens") == 0


# ---------------------------------------------------------------------------
# drain & handoff: planned maintenance, zero client-visible failures
# ---------------------------------------------------------------------------
def test_drain_quiesces_closes_admission_and_reopens():
    ref = clean_reference(PROMPTS, 10)
    tracing.reset()
    srv = Server(tiny(), num_blocks=256)
    reqs = [srv.submit(p, 10, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    srv.step()
    srv.drain()
    assert [list(r.tokens) for r in reqs] == ref
    assert all(r.state == "done" for r in reqs)
    with pytest.raises(AdmissionReject) as e:
        srv.submit([1], 2)
    assert e.value.reason == "draining"
    evs = [ev for ev in tracing.snapshot() if ev["event"] == "serve.drain"]
    assert evs and evs[0]["data"]["kind"] == "drain"
    srv.resume_admission()
    late = srv.submit([1], 2)
    srv.run_until_idle()
    assert late.state == "done"


@pytest.mark.parametrize("sampling", ["greedy", "top_k:8"])
def test_handoff_migrates_live_sessions_bit_identical(sampling):
    """A hot handoff mid-decode: every live session continues on the
    fresh engine generation with zero failures and an unchanged
    stream; no restart budget is consumed."""
    kw = dict(sampling=sampling, sampling_seed=5)
    ref = clean_reference(PROMPTS, 10, **kw)
    tracing.reset()
    srv = Server(tiny(), num_blocks=256, **kw)
    reqs = [srv.submit(p, 10, request_id=f"r{i}")
            for i, p in enumerate(PROMPTS)]
    for _ in range(3):
        srv.step()
    before = [list(r.tokens) for r in reqs]
    assert any(before)
    gen = srv.generation
    assert srv.handoff() == len(reqs)
    assert srv.generation == gen + 1 and srv.restarts == 0
    # handoff never rewinds a stream (nothing to re-yield)
    for r, b in zip(reqs, before):
        assert list(r.tokens)[:len(b)] == b
    srv.run_until_idle()
    assert [list(r.tokens) for r in reqs] == ref
    assert all(r.state == "done" for r in reqs)
    evs = [ev for ev in tracing.snapshot()
           if ev["event"] == "serve.drain"]
    assert evs and evs[-1]["data"]["kind"] == "handoff"
    assert evs[-1]["data"]["inflight"] == len(reqs)


def test_handoff_under_journal_keeps_ledger_durable(tmp_path):
    """Handoff flushes the journal at the boundary: a kill right after
    a handoff loses nothing the clients saw."""
    prefix = str(tmp_path / "ho")
    srv = Server(tiny(), num_blocks=256, journal=prefix)
    req = srv.submit([1, 2, 3], 10, request_id="r0")
    for _ in range(4):
        srv.step()
    srv.handoff()
    on_disk = journal_mod.load(journal_path(prefix))["r0"]["tokens"]
    assert on_disk == list(req.tokens)
    srv.run_until_idle()
    assert req.state == "done"
