"""Speculative multi-token decode + fused whole-step decode — ISSUE 16.

Covers: the draft/verify/accept protocol (greedy acceptance is LOSSLESS,
so spec on/off streams are bit-identical on every decode arm, host and
fused), full-window rejection and disagreement at the first drafted
slot (cache truncation restores exact lengths, zero block leaks), EOS
landing inside an accepted draft (tokens past EOS never committed),
engine restart mid-draft losing zero requests, pool exhaustion under
window reservations (backpressure, never OOM), and the per-token
host-crossing receipt (fused: constant 3 per step; host paged:
4 x num_layers; dense: 0)."""
import json

import numpy as np
import pytest

from tpu_mx import telemetry, tracing
from tpu_mx.contrib import chaos
from tpu_mx.serving import EngineCore, Request, Server, TinyLM
from tpu_mx.serving.jax_model import (JaxTinyLM, fused_requested,
                                      resolve_fused)
from tpu_mx.serving.speculative import (DEFAULT_WINDOW, SiblingProposer,
                                        accept_prefix, resolve_spec_window)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    tracing.reset()
    telemetry.reset()
    yield
    tracing.reset()
    telemetry.reset()


def tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("seed", 0)
    return TinyLM(**kw)


def set_arms(monkeypatch, mode, fused, spec):
    monkeypatch.setenv("TPUMX_PAGED_DECODE", mode)
    monkeypatch.setenv("TPUMX_FUSED_DECODE", fused)
    monkeypatch.setenv("TPUMX_SPECULATIVE", spec)


def run_streams(monkeypatch, mode, fused, spec, prompts, steps=8, **kw):
    set_arms(monkeypatch, mode, fused, spec)
    srv = Server(tiny(), num_blocks=64, max_batch=4, **kw)
    reqs = [srv.submit(p, max_new_tokens=steps) for p in prompts]
    srv.run_until_idle()
    for r in reqs:
        assert r.state == "done", (r.state, r.error)
    return srv, [r.tokens for r in reqs]


# ---------------------------------------------------------------------------
# knob resolution
# ---------------------------------------------------------------------------
def test_resolve_spec_window_env(monkeypatch):
    for off in ("", "0", "off", "no"):
        monkeypatch.setenv("TPUMX_SPECULATIVE", off)
        assert resolve_spec_window() == 1
    for on in ("1", "on", "yes", "auto"):
        monkeypatch.setenv("TPUMX_SPECULATIVE", on)
        assert resolve_spec_window() == DEFAULT_WINDOW
    monkeypatch.setenv("TPUMX_SPECULATIVE", "6")
    assert resolve_spec_window() == 6
    # a typo'd knob must fail LOUDLY, never silently disable speculation
    for bad in ("fast", "-2"):
        monkeypatch.setenv("TPUMX_SPECULATIVE", bad)
        with pytest.raises(ValueError, match="TPUMX_SPECULATIVE"):
            resolve_spec_window()


def test_resolve_fused_env_and_downgrade(monkeypatch):
    model = tiny()
    monkeypatch.setenv("TPUMX_FUSED_DECODE", "1")
    assert fused_requested()
    assert resolve_fused("paged", model)
    assert resolve_fused("paged-kernel", model)
    # dense has no device pool for the program to own: downgrade
    assert not resolve_fused("dense", model)
    monkeypatch.setenv("TPUMX_FUSED_DECODE", "0")
    assert not resolve_fused("paged", model)
    monkeypatch.setenv("TPUMX_FUSED_DECODE", "sometimes")
    with pytest.raises(ValueError, match="TPUMX_FUSED_DECODE"):
        fused_requested()


# ---------------------------------------------------------------------------
# accept protocol
# ---------------------------------------------------------------------------
def test_accept_prefix_protocol():
    draft = np.array([7, 3, 5, 9])           # draft[0] is the input token
    # verify output: out[j] is greedy-next after consuming draft[:j+1]
    assert accept_prefix(draft, np.array([3, 5, 9, 2])) == 3   # all agree
    assert accept_prefix(draft, np.array([3, 5, 1, 2])) == 2   # tail cut
    assert accept_prefix(draft, np.array([3, 1, 9, 2])) == 1
    # disagreement at the FIRST drafted slot: nothing speculative lands,
    # the step still emits out[0] (the true greedy token)
    assert accept_prefix(draft, np.array([1, 5, 9, 2])) == 0
    # agreement past a mismatch must NOT resurrect the tail
    assert accept_prefix(draft, np.array([3, 1, 9, 9])) == 1
    assert accept_prefix(np.array([7]), np.array([4])) == 0    # K == 1


# ---------------------------------------------------------------------------
# bit-equality across every arm combination
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode,fused", [
    ("0", "0"), ("0", "1"),                  # dense ("1" downgrades)
    ("1", "0"), ("1", "1"),                  # paged host / fused
    ("kernel", "0"), ("kernel", "1"),        # paged-kernel host / fused
])
def test_spec_on_off_streams_bit_identical(monkeypatch, mode, fused):
    """THE acceptance bar: greedy verification makes speculation
    lossless, so every (decode arm, fused arm, window) combination must
    produce the same token streams as the plain dense reference."""
    prompts = [[5, 6, 7], [9, 2], [1] * 7]
    _, ref = run_streams(monkeypatch, "0", "0", "0", prompts)
    for spec in ("0", "1", "3"):
        srv, got = run_streams(monkeypatch, mode, fused, spec, prompts)
        assert got == ref, (mode, fused, spec)
        assert srv.engine.fused == (fused == "1" and mode != "0")
        if spec != "0" and srv.engine.spec_window > 1:
            ratio = telemetry.get("serve.spec_accept_ratio")
            assert ratio is not None and 0.0 <= ratio.value <= 1.0


# ---------------------------------------------------------------------------
# rejection edges
# ---------------------------------------------------------------------------
def test_full_window_rejection_truncates_exactly(monkeypatch):
    """A proposer that is ALWAYS wrong at the first drafted slot: every
    step degenerates to one true token, the cache length never drifts,
    and no block leaks."""
    prompts = [[5, 6, 7]]
    _, ref = run_streams(monkeypatch, "0", "0", "0", prompts, steps=6)
    bad_token = next(t for t in range(64) if t not in ref[0])

    set_arms(monkeypatch, "1", "0", "4")
    eng = EngineCore(tiny(), block_size=4, num_blocks=64)

    class AlwaysWrong:
        def draft(self, last_tokens, positions, n):
            return np.full((len(last_tokens), n), bad_token, np.int64)

    eng.proposer = AlwaysWrong()
    req = Request([5, 6, 7], max_new_tokens=6, request_id="r")
    first, _ = eng.prefill(req)
    got = [first]
    base_len = eng.cache.length(req.id)
    for step in range(5):
        res, pre = eng.decode([(req, got[-1])])
        assert not pre
        assert len(res[req.id]) == 1          # full-window rejection
        got.extend(res[req.id])
        # truncation restored the exact post-commit length: base + steps
        assert eng.cache.length(req.id) == base_len + step + 1
    assert got == ref[0]
    assert telemetry.get("serve.spec_drafted").value == 3 * 5
    assert telemetry.get("serve.spec_accept_ratio").value == 0.0
    assert telemetry.get("serve.spec_accepted") is None
    eng.evict(req)
    assert eng.cache.stats()["used_blocks"] == 0


def test_eos_inside_accepted_draft(monkeypatch):
    """EOS produced inside an accepted window must terminate the stream
    exactly where the non-speculative run does — accepted tokens past
    EOS are dropped by the commit loop, never leaked to the client."""
    prompts = [[5, 6, 7]]
    _, ref = run_streams(monkeypatch, "0", "0", "0", prompts, steps=8)
    eos = ref[0][4]                           # mid-stream, mid-window
    _, ref_eos = run_streams(monkeypatch, "0", "0", "0", prompts,
                             steps=8, eos_id=eos)
    assert len(ref_eos[0]) < 8                # EOS actually fired early
    for mode, fused in (("1", "0"), ("1", "1")):
        _, got = run_streams(monkeypatch, mode, fused, "4", prompts,
                             steps=8, eos_id=eos)
        assert got == ref_eos, (mode, fused)


def test_spec_window_exhaustion_is_still_backpressure(monkeypatch):
    """Window reservations grab up to K slots at once — an
    over-committed pool must preempt/requeue (all-or-nothing rollback
    in reserve_window), complete every request, and leak nothing."""
    prompts = [[1, 2, 3]] * 5
    _, ref = run_streams(monkeypatch, "0", "0", "0", prompts, steps=6)
    set_arms(monkeypatch, "1", "0", "4")
    srv = Server(tiny(), num_blocks=6, block_size=2, max_batch=4,
                 max_tokens=1000)
    reqs = [srv.submit(p, max_new_tokens=6) for p in prompts]
    srv.run_until_idle()
    for r, t in zip(reqs, ref):
        assert r.state == "done" and r.tokens == t
    assert srv.engine.cache.stats()["used_blocks"] == 0


def test_restart_mid_draft_loses_zero_requests(monkeypatch, tmp_path):
    """A NaN storm landing mid-speculative-run restarts the engine; the
    requeued requests replay from their prompts and finish with the
    exact clean-run streams."""
    prompts = [[4, 5], [7, 1]]
    _, ref = run_streams(monkeypatch, "0", "0", "0", prompts, steps=4)
    tracing.reset()                           # drop the baseline's events
    set_arms(monkeypatch, "1", "1", "4")
    prefix = str(tmp_path / "spec")
    srv = Server(tiny(), num_blocks=64, max_batch=4, backoff=0.0,
                 blackbox=prefix)
    reqs = [srv.submit(p, max_new_tokens=4) for p in prompts]
    with chaos.enable(nan_after=2):
        srv.run_until_idle()
    assert srv.restarts == 1
    for r, t in zip(reqs, ref):
        assert r.state == "done" and r.tokens == t
    assert srv.engine.cache.stats()["used_blocks"] == 0
    box = json.load(open(tracing.blackbox_path(prefix)))
    tracing.validate_blackbox(box)
    paths = [e for e in box["events"]
             if e["event"] == "serve.decode_path"]
    assert len(paths) == 2                    # one per engine generation
    for e in paths:
        assert e["data"]["fused"] is True
        assert e["data"]["spec_window"] == 4


# ---------------------------------------------------------------------------
# host-crossing receipt
# ---------------------------------------------------------------------------
def test_host_crossings_receipt_o1_vs_olayers(monkeypatch):
    """The ISSUE 16 perf receipt in telemetry: the fused program crosses
    the host<->device boundary a CONSTANT 3 times per step; the
    host-resident paged arm pays 4 per layer; dense crosses zero."""
    prompts = [[5, 6, 7]]
    srv, _ = run_streams(monkeypatch, "1", "1", "0", prompts)
    assert telemetry.get("serve.host_crossings_per_token").value == 3.0
    assert telemetry.get("serve.fused_steps").value > 0
    telemetry.reset()

    srv, _ = run_streams(monkeypatch, "1", "0", "0", prompts)
    layers = srv.engine.model.num_layers
    assert telemetry.get(
        "serve.host_crossings_per_token").value == 4.0 * layers
    assert telemetry.get("serve.fused_steps") is None
    telemetry.reset()

    run_streams(monkeypatch, "0", "0", "0", prompts)
    assert telemetry.get("serve.host_crossings_per_token").value == 0.0
    assert telemetry.get("serve.host_crossings") is None


def test_fused_decode_path_event_validates(monkeypatch):
    set_arms(monkeypatch, "kernel", "1", "1")
    srv = Server(tiny(), num_blocks=64, max_batch=4)
    r = srv.submit([3, 1, 4], max_new_tokens=4)
    srv.run_until_idle()
    assert r.state == "done"
    evs = [e for e in tracing.snapshot()
           if e["event"] == "serve.decode_path"]
    assert evs
    for e in evs:
        tracing.validate_event(e)
    assert evs[-1]["data"] == {
        "path": "paged-kernel", "storage": "device",
        "sharing": evs[-1]["data"]["sharing"],
        "fused": True, "spec_window": DEFAULT_WINDOW,
        "sampling": "greedy"}


# ---------------------------------------------------------------------------
# proposer
# ---------------------------------------------------------------------------
def test_sibling_proposer_shapes_and_determinism():
    model = tiny()
    prop = SiblingProposer(model)
    last = np.array([3, 9], np.int64)
    pos = np.array([5, 2], np.int64)
    a = prop.draft(last, pos, 3)
    b = prop.draft(last, pos, 3)
    assert a.shape == (2, 3) and a.dtype == np.int64
    assert np.array_equal(a, b)               # drafting is deterministic
    assert ((0 <= a) & (a < model.vocab_size)).all()
    # drafts near the position ceiling must clamp, not crash
    top = np.array([model.max_positions - 1], np.int64)
    prop.draft(np.array([1], np.int64), top, 3)
