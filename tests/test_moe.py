"""MoE FFN + expert parallelism (tpu_mx.parallel.moe — above-parity
capability; ep sharding is pure GSPMD via moe_sharding_rules)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import autograd, gluon, nd
from tpu_mx.parallel import MoEFFN, moe_sharding_rules


def _ref_moe(x, gw, w1, b1, w2, b2, top_k, capacity, act=None):
    """Per-token python reference: same priority/capacity semantics as
    the einsum kernel (k=0 picks queue before all k=1 picks)."""
    import scipy.special as sp
    S, U = x.shape
    E = w1.shape[0]
    probs = sp.softmax(x.astype(np.float64) @ gw.T.astype(np.float64), -1)
    act = act or (lambda v: 0.5 * v * (1 + sp.erf(v / np.sqrt(2))))
    # selections per k-round
    sel = []           # (k, S) expert ids
    masked = probs.copy()
    gates = []
    for _ in range(top_k):
        ids = masked.argmax(-1)
        gates.append(probs[np.arange(S), ids])
        masked[np.arange(S), ids] = 0.0
        sel.append(ids)
    if top_k > 1:
        gsum = np.sum(gates, axis=0) + 1e-9
        gates = [g / gsum for g in gates]
    counts = np.zeros(E, int)
    y = np.zeros_like(x, dtype=np.float64)
    for k in range(top_k):
        for s in range(S):
            e = sel[k][s]
            if counts[e] < capacity:
                h = act(w1[e].astype(np.float64) @ x[s].astype(np.float64)
                        + b1[e])
                o = w2[e].astype(np.float64) @ h + b2[e]
                y[s] += gates[k][s] * o
                counts[e] += 1
    return y.astype(np.float32)


@pytest.mark.slow
@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_reference_loop(top_k):
    np.random.seed(0)
    S, U, H, E = 12, 8, 16, 4
    layer = MoEFFN(U, H, E, top_k=top_k, capacity_factor=1.25)
    layer.initialize(init="xavier")
    x = nd.array(np.random.randn(S, U).astype(np.float32) * 0.5)
    y, aux = layer(x)
    import math
    capacity = max(1, math.ceil(1.25 * S * top_k / E))
    ref = _ref_moe(x.asnumpy(),
                   layer.gate_weight.data().asnumpy(),
                   layer.expert_w1.data().asnumpy(),
                   layer.expert_b1.data().asnumpy(),
                   layer.expert_w2.data().asnumpy(),
                   layer.expert_b2.data().asnumpy(),
                   top_k, capacity)
    np.testing.assert_allclose(y.asnumpy(), ref, rtol=2e-4, atol=2e-4)
    assert float(aux.asnumpy()) >= 0.99  # >= 1 at/above perfect balance


def test_moe_capacity_drops_tokens():
    """capacity_factor -> 0 forces drops: dropped tokens produce ZERO
    output (the residual around the layer carries them)."""
    np.random.seed(1)
    S, U, H, E = 16, 4, 8, 2
    layer = MoEFFN(U, H, E, top_k=1, capacity_factor=0.1)
    layer.initialize(init="xavier")
    x = nd.array(np.random.randn(S, U).astype(np.float32))
    y, _ = layer(x)
    yn = np.abs(y.asnumpy()).sum(axis=-1)
    # capacity = ceil(0.1 * 16 / 2) = 1 slot/expert -> at most 2 pass
    assert (yn > 1e-6).sum() <= 2, yn


@pytest.mark.slow
def test_moe_top1_router_gets_task_gradient():
    """Switch (top-1) keeps the RAW router prob as the combine weight,
    so gate_weight must receive a real task-loss gradient (a
    renormalized top-1 gate would pin the weight at ~1 and starve it)."""
    np.random.seed(4)
    layer = MoEFFN(8, 16, 4, top_k=1)
    layer.initialize(init="xavier")
    x = nd.array(np.random.randn(10, 8).astype(np.float32))
    with autograd.record():
        y, aux = layer(x)
        l = (y * y).sum()      # task loss only — no aux term
    l.backward()
    g = layer.gate_weight.grad
    g = g() if callable(g) else g
    assert float(np.abs(g.asnumpy()).max()) > 1e-5


def test_moe_grads_flow_and_trains():
    """Gate AND expert weights receive gradients; a tiny regression task
    shows decreasing loss through CompiledTrainStep (batch dims fold)."""
    from tpu_mx.gluon.block import HybridBlock
    from tpu_mx.parallel import CompiledTrainStep

    np.random.seed(2)
    B, T, U = 4, 6, 8

    class Net(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.moe = MoEFFN(U, 16, 4, top_k=2)

        def forward(self, x, target):
            y, aux = self.moe(x)
            from tpu_mx import nd as _nd
            err = _nd.mean(_nd.square(y - target))
            return err + 0.01 * aux

    net = Net()
    net.initialize(init="xavier")
    x = nd.array(np.random.randn(B, T, U).astype(np.float32))
    t = nd.array(np.random.randn(B, T, U).astype(np.float32) * 0.1)
    net(x, t)
    step = CompiledTrainStep(net, gluon.loss.PassThrough(),
                             mx.optimizer.create("adam", learning_rate=3e-3))
    dummy = nd.array(np.zeros((1,), np.float32))
    losses = [float(np.asarray(step.step(x, t, dummy)._data).ravel()[0])
              for _ in range(25)]
    assert losses[-1] < losses[0] * 0.9, losses
    assert all(np.isfinite(losses))


@pytest.mark.slow
def test_moe_ep_sharded_matches_dense():
    """The SAME MoE layer under an ep mesh (experts GSPMD-sharded via
    moe_sharding_rules) produces the single-device result and trains."""
    import jax
    from tpu_mx.gluon.block import HybridBlock
    from tpu_mx.parallel import CompiledTrainStep, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    np.random.seed(3)
    B, T, U = 8, 4, 8

    class Net(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.moe = MoEFFN(U, 16, 4, top_k=1)

        def forward(self, x, target):
            y, aux = self.moe(x)
            from tpu_mx import nd as _nd
            return _nd.mean(_nd.square(y - target)) + 0.01 * aux

    x_np = np.random.randn(B, T, U).astype(np.float32)
    t_np = (np.random.randn(B, T, U) * 0.1).astype(np.float32)
    dummy = nd.array(np.zeros((1,), np.float32))

    def run(mesh, rules, steps=5):
        mx.random.seed(11)  # device-PRNG init (r5): reseed per build
        net = Net()
        net.initialize(init="xavier")
        x, t = nd.array(x_np), nd.array(t_np)
        net(x, t)
        step = CompiledTrainStep(
            net, gluon.loss.PassThrough(),
            mx.optimizer.create("sgd", learning_rate=0.1),
            mesh=mesh, rules=rules,
            data_specs=(P_dp, P_dp, P_none) if mesh is not None else None)
        return [float(np.asarray(step.step(x, t, dummy)._data).ravel()[0])
                for _ in range(steps)]

    from tpu_mx.parallel import P
    P_dp, P_none = P("dp"), P()
    dense = run(None, None)
    mesh = make_mesh({"dp": 2, "ep": 2}, devices=jax.devices()[:4])
    sharded = run(mesh, moe_sharding_rules())
    np.testing.assert_allclose(dense, sharded, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_moe_bert_variant_trains():
    """BERTModel(moe_every=2): every 2nd layer sparse; forward returns
    (logits, aux); an MLM step through CompiledTrainStep learns.  The
    default (moe_every=0) keeps the plain single-output contract."""
    from tpu_mx.models.bert import BERTModel, bert_base_config
    from tpu_mx.parallel import CompiledTrainStep

    cfg = bert_base_config(vocab_size=96, max_len=16)
    cfg.update(num_layers=2, units=32, hidden_size=64, num_heads=2)
    # default: single output
    plain = BERTModel(cfg)
    plain.initialize()
    t = nd.array(np.zeros((2, 16), np.int32))
    ty = nd.array(np.zeros((2, 16), np.int32))
    out = plain(t, ty)
    assert not isinstance(out, tuple)

    np.random.seed(5)
    net = BERTModel(cfg, moe_every=2, moe_experts=4, moe_top_k=2)
    net.initialize()
    rng = np.random.RandomState(0)
    B, T = 8, 16
    tokens = rng.randint(4, 96, (B, T)).astype(np.int32)
    types = np.zeros((B, T), np.int32)
    logits, aux = net(nd.array(tokens), nd.array(types))
    assert logits.shape == (B, T, 96) and float(aux.asnumpy()) > 0

    from tpu_mx.gluon.block import HybridBlock

    class MoEBertTrain(HybridBlock):
        """Loss-in-forward wrapper (the SSD/CompiledTrainStep pattern for
        multi-output nets: the step keeps only a net's FIRST output, so
        the aux term must fold into the objective before it returns)."""

        def __init__(self, bert, **kw):
            super().__init__(**kw)
            self.bert = bert
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def forward(self, tokens, types, labels):
            logits, aux = self.bert(tokens, types)
            v = logits.shape[-1]
            ce = nd.mean(self._ce(nd.reshape(logits, shape=(-1, v)),
                                  nd.reshape(labels, shape=(-1,))))
            return ce + 0.01 * aux

    wrapper = MoEBertTrain(net)
    step = CompiledTrainStep(
        wrapper, gluon.loss.PassThrough(),
        mx.optimizer.create("adam", learning_rate=2e-3))
    t_nd, ty_nd = nd.array(tokens), nd.array(types)
    l_nd = nd.array(tokens)  # identity-denoise objective: learnable
    dummy = nd.array(np.zeros((1,), np.float32))
    losses = [float(np.asarray(
        step.step(t_nd, ty_nd, l_nd, dummy)._data).ravel()[0])
        for _ in range(20)]
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(losses))
