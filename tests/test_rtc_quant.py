"""mx.rtc (Pallas user kernels) + contrib.quantization tests."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd, gluon
from tpu_mx.base import MXNetError
from tpu_mx.contrib import quantization as q


def test_rtc_kernel_launch():
    def scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[:] = x_ref[:] * alpha

    mod = mx.rtc.PallasModule({"scale": scale_kernel})
    k = mod.get_kernel("scale", alpha=3.0)
    x = nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    y = k.launch((x,), out_shape=x.shape)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 3.0)


def test_rtc_two_input_kernel():
    def addmul(a_ref, b_ref, o_ref):
        o_ref[:] = a_ref[:] * b_ref[:] + a_ref[:]

    mod = mx.rtc.PallasModule(addmul)
    k = mod.get_kernel("addmul")
    a = nd.array(np.full((4, 4), 2.0, np.float32))
    b = nd.array(np.full((4, 4), 5.0, np.float32))
    np.testing.assert_allclose(k((a, b)).asnumpy(), 12.0)


def test_rtc_unknown_kernel():
    mod = mx.rtc.PallasModule({}, exports=[])
    with pytest.raises(MXNetError):
        mod.get_kernel("nope")


def test_quantize_dequantize_roundtrip():
    x = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    qx, lo, hi = q.quantize(nd.array(x))
    assert qx.dtype == "int8"
    back = q.dequantize(qx, lo, hi)
    amax = max(abs(lo), abs(hi))
    np.testing.assert_allclose(back.asnumpy(), x, atol=amax / 127 + 1e-6)


def test_quantized_dense_close_to_float():
    rng = np.random.RandomState(1)
    net = gluon.nn.Dense(8, in_units=16)
    net.initialize()
    x = nd.array(rng.rand(4, 16).astype(np.float32))
    ref = net(x).asnumpy()
    qd = q.QuantizedDense(net, (0.0, 1.0))
    out = qd(x).asnumpy()
    scale = np.abs(ref).max() + 1e-8
    assert np.abs(out - ref).max() / scale < 0.05


def test_quantize_net_end_to_end():
    rng = np.random.RandomState(2)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu", in_units=16))
    net.add(gluon.nn.Dense(4, in_units=32))
    net.initialize()
    calib = nd.array(rng.rand(16, 16).astype(np.float32))
    qnet = q.quantize_net(net, calib_data=calib)
    x = nd.array(rng.rand(8, 16).astype(np.float32))
    ref = net(x).asnumpy()
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max() + 1e-8
    assert np.abs(out - ref).max() / scale < 0.12, \
        f"int8 divergence {np.abs(out - ref).max() / scale}"


# ---------------------------------------------------------------------------
# INT8 conv inference (VERDICT r3 ask#5: quantized conv + pool/activation
# passthrough; REF:src/operator/quantization/quantized_conv.cc,
# REF:src/operator/subgraph/mkldnn/)
# ---------------------------------------------------------------------------
def _train_small_cnn(steps=40):
    """Tiny CNN trained on linearly-separable synthetic images so the
    accuracy-drop contract (<=1%) is measurable, not vacuous."""
    import tpu_mx as mx
    from tpu_mx import autograd, gluon
    from tpu_mx.gluon import nn
    rs = np.random.RandomState(0)
    n, classes = 256, 4
    ys = rs.randint(0, classes, n)
    xs = rs.rand(n, 1, 12, 12).astype(np.float32) * 0.3
    for i, y in enumerate(ys):          # class-dependent bright quadrant
        r, c = divmod(int(y), 2)
        xs[i, 0, r * 6:(r + 1) * 6, c * 6:(c + 1) * 6] += 1.0

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(pool_size=2),
            nn.Conv2D(16, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(pool_size=2),
            nn.Dense(32, activation="relu"),
            nn.Dense(classes))
    net.initialize(init="xavier")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    xb, yb = nd.array(xs), nd.array(ys.astype(np.float32))
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(xb), yb)
            loss.backward()
        trainer.step(n)
    return net, xs, ys


@pytest.mark.slow
def test_quantized_cnn_accuracy_drop_under_1pct():
    from tpu_mx.contrib.quantization import quantize_net
    net, xs, ys = _train_small_cnn()
    xb = nd.array(xs)
    float_pred = np.argmax(net(xb).asnumpy(), axis=1)
    float_acc = float(np.mean(float_pred == ys))
    assert float_acc > 0.9  # the float net actually learned the task

    qnet = quantize_net(net, calib_data=xb)
    q_pred = np.argmax(qnet(xb).asnumpy(), axis=1)
    q_acc = float(np.mean(q_pred == ys))
    assert float_acc - q_acc <= 0.01, (float_acc, q_acc)
    # convs actually run int8 (not just the Dense tail)
    from tpu_mx.contrib.quantization import QuantizedConv, _named_quantizable
    n_conv = sum(isinstance(q, QuantizedConv)
                 for q in qnet._qmap.values())
    assert n_conv == 2


def test_quantized_resnet_block_residual_structure():
    """Residual/branchy blocks keep their control flow under quantization
    (the leaf-patching design): int8 output stays close to float."""
    from tpu_mx.gluon.model_zoo.vision.resnet import BasicBlockV1
    from tpu_mx.contrib.quantization import quantize_net
    rs = np.random.RandomState(1)
    blk = BasicBlockV1(8, stride=1, in_channels=8)
    blk.initialize(init="xavier")
    x = nd.array(rs.rand(2, 8, 8, 8).astype(np.float32))
    ref = blk(x).asnumpy()

    qblk = quantize_net(blk, calib_data=x)
    out = qblk(x).asnumpy()
    rel = np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-8)
    assert rel < 0.1, rel
    corr = np.corrcoef(out.ravel(), ref.ravel())[0, 1]
    assert corr > 0.99


def test_quantized_net_not_bypassed_by_hybridize():
    """A hybridized net's cached float program must not silently serve
    quantized calls — the wrapper forces the eager (patched) path."""
    from tpu_mx.contrib.quantization import quantize_net
    net, xs, _ = _train_small_cnn(steps=5)
    xb = nd.array(xs[:16])
    q_eager = quantize_net(net, calib_data=xb)(xb).asnumpy()

    net.hybridize()
    _ = net(xb)   # build the float jit cache
    q_hybrid = quantize_net(net, calib_data=xb)(xb).asnumpy()
    np.testing.assert_allclose(q_hybrid, q_eager, rtol=1e-5, atol=1e-6)
    # and hybridization is restored afterwards
    assert net._active


def test_quantized_net_with_shared_layer():
    """A layer registered under two names (weight sharing) is patched and
    unpatched exactly once — no AttributeError in the unpatch path."""
    from tpu_mx.contrib.quantization import quantize_net
    from tpu_mx.gluon import nn
    shared = nn.Dense(6, activation="relu")
    net = nn.HybridSequential()
    net.add(nn.Dense(6, in_units=6), shared, shared, nn.Dense(3))
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(0).rand(4, 6).astype(np.float32))
    ref = net(x).asnumpy()
    qnet = quantize_net(net, calib_data=x)
    out = qnet(x).asnumpy()     # must not crash
    assert np.isfinite(out).all()
    rel = np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-8)
    assert rel < 0.1
    # net restored: float path unchanged afterwards
    np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=1e-6)


def test_quantized_net_jit_matches_eager(monkeypatch):
    """The jitted quantized program must be numerically equivalent to
    the eager patched path (jit fuses what eager runs op-by-op, so tiny
    rounding differences are expected), and the float net's own
    hybridize cache must stay un-poisoned (still float after quantized
    calls)."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon import nn
    from tpu_mx.contrib.quantization import quantize_net

    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
            nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    net(x)
    net.hybridize()
    float_out = net(x).asnumpy()

    qnet = quantize_net(net, calib_data=x)
    jit_out = qnet(x).asnumpy()
    monkeypatch.setenv("TPUMX_QUANT_JIT", "0")
    eager_out = qnet(x).asnumpy()
    # jit fuses what eager runs op-by-op: tiny rounding differences are
    # expected, numerical equivalence is the contract
    np.testing.assert_allclose(jit_out, eager_out, rtol=1e-5, atol=1e-6)
    # quantization changes numerics vs float (otherwise the patch was
    # silently bypassed by a cached float program)
    assert np.abs(jit_out - float_out).max() > 0
    # the float net still serves FLOAT results from its own cache
    np.testing.assert_array_equal(net(x).asnumpy(), float_out)


def test_quantized_net_jit_multi_output():
    """Structure-agnostic includes multi-head nets: the jitted wrapper
    must handle tuple outputs (reproduces the r4 review crash)."""
    import numpy as np
    from tpu_mx import nd
    from tpu_mx.gluon import nn
    from tpu_mx.gluon.block import HybridBlock
    from tpu_mx.contrib.quantization import quantize_net

    class TwoHead(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.body = nn.Dense(8, activation="relu")
            self.h1 = nn.Dense(3)
            self.h2 = nn.Dense(5)

        def forward(self, x):
            z = self.body(x)
            return self.h1(z), self.h2(z)

    np.random.seed(1)
    net = TwoHead()
    net.initialize()
    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    net(x)
    qnet = quantize_net(net, calib_data=x)
    a, b = qnet(x)
    assert a.shape == (4, 3) and b.shape == (4, 5)
