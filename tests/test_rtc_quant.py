"""mx.rtc (Pallas user kernels) + contrib.quantization tests."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd, gluon
from tpu_mx.base import MXNetError
from tpu_mx.contrib import quantization as q


def test_rtc_kernel_launch():
    def scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[:] = x_ref[:] * alpha

    mod = mx.rtc.PallasModule({"scale": scale_kernel})
    k = mod.get_kernel("scale", alpha=3.0)
    x = nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    y = k.launch((x,), out_shape=x.shape)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 3.0)


def test_rtc_two_input_kernel():
    def addmul(a_ref, b_ref, o_ref):
        o_ref[:] = a_ref[:] * b_ref[:] + a_ref[:]

    mod = mx.rtc.PallasModule(addmul)
    k = mod.get_kernel("addmul")
    a = nd.array(np.full((4, 4), 2.0, np.float32))
    b = nd.array(np.full((4, 4), 5.0, np.float32))
    np.testing.assert_allclose(k((a, b)).asnumpy(), 12.0)


def test_rtc_unknown_kernel():
    mod = mx.rtc.PallasModule({}, exports=[])
    with pytest.raises(MXNetError):
        mod.get_kernel("nope")


def test_quantize_dequantize_roundtrip():
    x = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    qx, lo, hi = q.quantize(nd.array(x))
    assert qx.dtype == "int8"
    back = q.dequantize(qx, lo, hi)
    amax = max(abs(lo), abs(hi))
    np.testing.assert_allclose(back.asnumpy(), x, atol=amax / 127 + 1e-6)


def test_quantized_dense_close_to_float():
    rng = np.random.RandomState(1)
    net = gluon.nn.Dense(8, in_units=16)
    net.initialize()
    x = nd.array(rng.rand(4, 16).astype(np.float32))
    ref = net(x).asnumpy()
    qd = q.QuantizedDense(net, (0.0, 1.0))
    out = qd(x).asnumpy()
    scale = np.abs(ref).max() + 1e-8
    assert np.abs(out - ref).max() / scale < 0.05


def test_quantize_net_end_to_end():
    rng = np.random.RandomState(2)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu", in_units=16))
    net.add(gluon.nn.Dense(4, in_units=32))
    net.initialize()
    calib = nd.array(rng.rand(16, 16).astype(np.float32))
    qnet = q.quantize_net(net, calib_data=calib)
    x = nd.array(rng.rand(8, 16).astype(np.float32))
    ref = net(x).asnumpy()
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max() + 1e-8
    assert np.abs(out - ref).max() / scale < 0.12, \
        f"int8 divergence {np.abs(out - ref).max() / scale}"
