"""Extended operator families (VERDICT r1 item 4): linalg la_op, ROI ops,
spatial transforms, CTC, fused RNN, int8 compute, per-element samplers.
Oracles: numpy/scipy math, torch CPU (CTC, RNN), analytic identities."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.test_utils import check_numeric_gradient


rs = np.random.RandomState(42)


# ---------------------------------------------------------------------------
# linalg (REF:src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------
class TestLinalg:
    def _spd(self, n=4):
        a = rs.rand(n, n).astype(np.float32)
        return a @ a.T + n * np.eye(n, dtype=np.float32)

    def test_trsm(self):
        a = np.tril(rs.rand(4, 4).astype(np.float32)) + 2 * np.eye(4, dtype=np.float32)
        b = rs.rand(4, 3).astype(np.float32)
        x = nd.linalg_trsm(nd.array(a), nd.array(b), alpha=2.0).asnumpy()
        np.testing.assert_allclose(a @ x, 2.0 * b, rtol=1e-4, atol=1e-5)
        # rightside: X op(A) = alpha B with B (3, 4)
        b2 = rs.rand(3, 4).astype(np.float32)
        x2 = nd.linalg_trsm(nd.array(a), nd.array(b2), rightside=True).asnumpy()
        np.testing.assert_allclose(x2 @ a, b2, rtol=1e-4, atol=1e-5)

    def test_trmm(self):
        a = rs.rand(4, 4).astype(np.float32)
        b = rs.rand(4, 3).astype(np.float32)
        out = nd.linalg_trmm(nd.array(a), nd.array(b)).asnumpy()
        np.testing.assert_allclose(out, np.tril(a) @ b, rtol=1e-5)
        out_t = nd.linalg_trmm(nd.array(a), nd.array(b), transpose=True).asnumpy()
        np.testing.assert_allclose(out_t, np.tril(a).T @ b, rtol=1e-5)

    def test_det_slogdet_inverse(self):
        a = self._spd()
        np.testing.assert_allclose(nd.linalg_det(nd.array(a)).asnumpy(),
                                   np.linalg.det(a), rtol=1e-3)
        sign, logabs = nd.linalg_slogdet(nd.array(a))
        s_ref, l_ref = np.linalg.slogdet(a)
        np.testing.assert_allclose(sign.asnumpy(), s_ref, rtol=1e-5)
        np.testing.assert_allclose(logabs.asnumpy(), l_ref, rtol=1e-4)
        np.testing.assert_allclose(nd.linalg_inverse(nd.array(a)).asnumpy(),
                                   np.linalg.inv(a), rtol=1e-3, atol=1e-4)

    def test_potri(self):
        spd = self._spd()
        L = np.linalg.cholesky(spd).astype(np.float32)
        out = nd.linalg_potri(nd.array(L)).asnumpy()
        np.testing.assert_allclose(out, np.linalg.inv(spd), rtol=1e-2,
                                   atol=1e-3)

    def test_diag_roundtrip(self):
        v = rs.rand(5).astype(np.float32)
        m = nd.linalg_makediag(nd.array(v)).asnumpy()
        np.testing.assert_allclose(m, np.diag(v))
        np.testing.assert_allclose(
            nd.linalg_extractdiag(nd.array(m)).asnumpy(), v)
        m1 = nd.linalg_makediag(nd.array(v), offset=1).asnumpy()
        np.testing.assert_allclose(m1, np.diag(v, k=1))

    def test_trian_roundtrip(self):
        a = rs.rand(4, 4).astype(np.float32)
        packed = nd.linalg_extracttrian(nd.array(a)).asnumpy()
        assert packed.shape == (10,)
        back = nd.linalg_maketrian(nd.array(packed)).asnumpy()
        np.testing.assert_allclose(back, np.tril(a), rtol=1e-6)

    def test_gelqf(self):
        a = rs.rand(3, 5).astype(np.float32)
        L, Q = nd.linalg_gelqf(nd.array(a))
        L, Q = L.asnumpy(), Q.asnumpy()
        np.testing.assert_allclose(L @ Q, a, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(Q @ Q.T, np.eye(3), atol=1e-5)
        assert np.all(np.diag(L) >= 0)

    def test_syevd(self):
        a = self._spd()
        U, lam = nd.linalg_syevd(nd.array(a))
        U, lam = U.asnumpy(), lam.asnumpy()
        np.testing.assert_allclose(U.T @ np.diag(lam) @ U, a, rtol=1e-3,
                                   atol=1e-3)

    def test_sumlogdiag(self):
        a = self._spd()
        np.testing.assert_allclose(
            nd.linalg_sumlogdiag(nd.array(a)).asnumpy(),
            np.sum(np.log(np.diag(a))), rtol=1e-5)

    def test_det_gradient(self):
        a = self._spd(3)
        check_numeric_gradient(lambda xs: nd.linalg_det(xs[0]), [a],
                               rtol=1e-2, atol=1e-2)

    def test_trsm_gradient(self):
        a = np.tril(rs.rand(3, 3).astype(np.float32)) + 2 * np.eye(3, dtype=np.float32)
        b = rs.rand(3, 2).astype(np.float32)
        check_numeric_gradient(
            lambda xs: nd.sum(nd.linalg_trsm(nd.array(a), xs[0])),
            [b], rtol=1e-2, atol=1e-2)


# ---------------------------------------------------------------------------
# ROI + spatial transforms
# ---------------------------------------------------------------------------
class TestVisionOps:
    def test_roipooling_uniform(self):
        # constant feature map -> every pooled cell equals the constant
        x = np.full((1, 2, 8, 8), 5.0, np.float32)
        rois = np.array([[0, 0, 0, 7, 7], [0, 2, 2, 5, 5]], np.float32)
        out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(2, 2),
                            spatial_scale=1.0).asnumpy()
        assert out.shape == (2, 2, 2, 2)
        np.testing.assert_allclose(out, 5.0)

    def test_roipooling_max_structure(self):
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 0, 0] = 9.0  # hot corner
        rois = np.array([[0, 0, 0, 3, 3]], np.float32)
        out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(2, 2)
                            ).asnumpy()
        assert out[0, 0, 0, 0] == 9.0 and out[0, 0, 1, 1] == 0.0

    @pytest.mark.slow
    def test_roialign_uniform_and_grad(self):
        x = np.full((1, 3, 8, 8), 2.5, np.float32)
        rois = np.array([[0, 1.0, 1.0, 6.0, 6.0]], np.float32)
        out = nd.ROIAlign(nd.array(x), nd.array(rois), pooled_size=(3, 3),
                          spatial_scale=1.0).asnumpy()
        np.testing.assert_allclose(out, 2.5, rtol=1e-6)
        xv = rs.rand(1, 1, 6, 6).astype(np.float32)
        check_numeric_gradient(
            lambda xs: nd.sum(nd.ROIAlign(xs[0], nd.array(rois),
                                          pooled_size=(2, 2))),
            [xv], rtol=1e-2, atol=1e-2)

    def test_grid_generator_identity(self):
        theta = np.array([[1, 0, 0, 0, 1, 0]], np.float32)  # identity affine
        grid = nd.GridGenerator(nd.array(theta), "affine",
                                target_shape=(4, 6)).asnumpy()
        assert grid.shape == (1, 2, 4, 6)
        np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, 6),
                                   atol=1e-6)
        np.testing.assert_allclose(grid[0, 1, :, 0], np.linspace(-1, 1, 4),
                                   atol=1e-6)

    def test_spatial_transformer_identity(self):
        x = rs.rand(2, 3, 5, 5).astype(np.float32)
        theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
        out = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                                    target_shape=(5, 5)).asnumpy()
        np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_bilinear_sampler_grad(self):
        x = rs.rand(1, 2, 5, 5).astype(np.float32)
        theta = np.array([[0.8, 0.1, 0.0, -0.1, 0.9, 0.05]], np.float32)
        grid = nd.GridGenerator(nd.array(theta), "affine", target_shape=(4, 4))
        check_numeric_gradient(
            lambda xs: nd.sum(nd.BilinearSampler(xs[0], grid)),
            [x], rtol=1e-2, atol=1e-2)

    def test_bilinear_resize_and_upsampling(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = nd.BilinearResize2D(nd.array(x), height=8, width=8).asnumpy()
        assert out.shape == (1, 1, 8, 8)
        assert abs(out.mean() - x.mean()) < 0.2
        up = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest"
                           ).asnumpy()
        assert up.shape == (1, 1, 8, 8)
        np.testing.assert_allclose(up[0, 0, :2, :2], x[0, 0, 0, 0])

    @pytest.mark.slow
    def test_proposal_shapes_and_validity(self):
        N, A, Hf, Wf = 1, 3, 4, 4
        cls = rs.rand(N, 2 * A, Hf, Wf).astype(np.float32)
        deltas = (rs.rand(N, 4 * A, Hf, Wf).astype(np.float32) - 0.5) * 0.1
        im_info = np.array([[64, 64, 1.0]], np.float32)
        rois = nd.Proposal(nd.array(cls), nd.array(deltas), nd.array(im_info),
                           rpn_pre_nms_top_n=20, rpn_post_nms_top_n=8,
                           feature_stride=16, scales=(2, 4, 8),
                           ratios=(1.0,), rpn_min_size=1).asnumpy()
        assert rois.shape == (1, 8, 5)
        assert np.all(rois[..., 0] == 0)  # batch index
        assert np.all(rois[..., 1:] >= 0) and np.all(rois[..., 1:] <= 63)
        assert np.all(rois[..., 3] >= rois[..., 1])  # x2 >= x1


# ---------------------------------------------------------------------------
# CTC vs torch (REF:src/operator/contrib/ctc_loss)
# ---------------------------------------------------------------------------
class TestCTC:
    def _torch_ctc(self, acts, labels, in_lens, lab_lens, blank):
        import torch
        logp = torch.log_softmax(torch.tensor(acts), dim=-1)
        return torch.nn.functional.ctc_loss(
            logp, torch.tensor(labels), torch.tensor(in_lens),
            torch.tensor(lab_lens), blank=blank, reduction="none",
            zero_infinity=False).numpy()

    @pytest.mark.slow
    def test_matches_torch_blank_first(self):
        T, N, C, L = 10, 3, 6, 4
        acts = rs.rand(T, N, C).astype(np.float32) * 2
        labels = rs.randint(1, C, (N, L)).astype(np.float32)
        lab_lens = np.array([4, 2, 3])
        padded = labels.copy()
        for i, ll in enumerate(lab_lens):
            padded[i, ll:] = 0  # blank_label='first': 0-padding ends label
        out = nd.ctc_loss(nd.array(acts), nd.array(padded)).asnumpy()
        ref = self._torch_ctc(acts, labels.astype(np.int64),
                              [T] * N, lab_lens, blank=0)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)

    @pytest.mark.slow
    def test_matches_torch_with_lengths(self):
        T, N, C, L = 12, 2, 5, 3
        acts = rs.rand(T, N, C).astype(np.float32)
        labels = rs.randint(0, C - 1, (N, L)).astype(np.float32)
        in_lens = np.array([12, 9])
        lab_lens = np.array([3, 2])
        out = nd.ctc_loss(nd.array(acts), nd.array(labels),
                          data_lengths=nd.array(in_lens),
                          label_lengths=nd.array(lab_lens),
                          use_data_lengths=True, use_label_lengths=True,
                          blank_label="last").asnumpy()
        ref = self._torch_ctc(acts, labels.astype(np.int64), in_lens,
                              lab_lens, blank=C - 1)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)

    @pytest.mark.slow
    def test_gluon_ctc_loss_and_grad(self):
        from tpu_mx import autograd, gluon
        T, N, C = 8, 2, 5
        acts = nd.array(rs.rand(N, T, C).astype(np.float32))  # NTC layout
        labels = nd.array(np.array([[1, 2, 0], [3, 1, 4]], np.float32))
        loss_fn = gluon.loss.CTCLoss()
        acts.attach_grad()
        with autograd.record():
            l = loss_fn(acts, labels).mean()
        l.backward()
        g = acts.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).max() > 0


# ---------------------------------------------------------------------------
# fused RNN op vs torch (REF:src/operator/rnn.cc)
# ---------------------------------------------------------------------------
class TestFusedRNN:
    def _pack_torch(self, tmod, mode, num_layers, bidirectional):
        """Pack torch weights into the cuDNN-layout blob RNN expects."""
        parts_w, parts_b = [], []
        d = 2 if bidirectional else 1
        for layer in range(num_layers):
            for di in range(d):
                sfx = f"_l{layer}" + ("_reverse" if di else "")
                parts_w.append(getattr(tmod, f"weight_ih{sfx}").detach().numpy().ravel())
                parts_w.append(getattr(tmod, f"weight_hh{sfx}").detach().numpy().ravel())
        for layer in range(num_layers):
            for di in range(d):
                sfx = f"_l{layer}" + ("_reverse" if di else "")
                parts_b.append(getattr(tmod, f"bias_ih{sfx}").detach().numpy().ravel())
                parts_b.append(getattr(tmod, f"bias_hh{sfx}").detach().numpy().ravel())
        return np.concatenate(parts_w + parts_b).astype(np.float32)

    @pytest.mark.parametrize("mode,layers,bi", [
        ("lstm", 1, False), ("lstm", 2, False), ("lstm", 1, True),
        ("gru", 1, False), ("gru", 2, True),
        ("rnn_tanh", 1, False), ("rnn_relu", 1, False),
    ])
    def test_matches_torch(self, mode, layers, bi):
        import torch
        T, N, I, H = 5, 3, 4, 6
        d = 2 if bi else 1
        torch.manual_seed(0)
        cls = {"lstm": torch.nn.LSTM, "gru": torch.nn.GRU}.get(mode)
        if cls is None:
            tmod = torch.nn.RNN(I, H, layers, bidirectional=bi,
                                nonlinearity=mode.split("_")[1])
        else:
            tmod = cls(I, H, layers, bidirectional=bi)
        x = rs.rand(T, N, I).astype(np.float32)
        h0 = np.zeros((layers * d, N, H), np.float32)
        params = self._pack_torch(tmod, mode, layers, bi)
        from tpu_mx.ndarray.rnn_op import rnn_param_size
        assert params.size == rnn_param_size(mode, I, H, layers, bi)

        args = dict(state_size=H, num_layers=layers, mode=mode,
                    bidirectional=bi, state_outputs=True)
        if mode == "lstm":
            c0 = np.zeros((layers * d, N, H), np.float32)
            out, hN, cN = nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                                 nd.array(c0), **args)
        else:
            out, hN = nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                             **args)
        with torch.no_grad():
            if mode == "lstm":
                t_out, (t_h, t_c) = tmod(torch.tensor(x))
                np.testing.assert_allclose(cN.asnumpy(), t_c.numpy(),
                                           rtol=1e-4, atol=1e-5)
            else:
                t_out, t_h = tmod(torch.tensor(x))
        np.testing.assert_allclose(out.asnumpy(), t_out.numpy(), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(hN.asnumpy(), t_h.numpy(), rtol=1e-4,
                                   atol=1e-5)

    @pytest.mark.slow
    def test_rnn_grad_flows(self):
        from tpu_mx import autograd
        from tpu_mx.ndarray.rnn_op import rnn_param_size
        T, N, I, H = 4, 2, 3, 5
        params = nd.array(rs.rand(
            rnn_param_size("lstm", I, H)).astype(np.float32) * 0.1)
        x = nd.array(rs.rand(T, N, I).astype(np.float32))
        h0 = nd.zeros((1, N, H))
        c0 = nd.zeros((1, N, H))
        params.attach_grad()
        with autograd.record():
            out = nd.RNN(x, params, h0, c0, state_size=H, num_layers=1,
                         mode="lstm")
            loss = nd.sum(out)
        loss.backward()
        assert float(nd.norm(params.grad).asnumpy()) > 0


# ---------------------------------------------------------------------------
# int8 quantized compute (REF:src/operator/quantization/)
# ---------------------------------------------------------------------------
class TestQuantized:
    def test_quantize_dequantize_roundtrip(self):
        x = (rs.rand(4, 8).astype(np.float32) - 0.5) * 6
        q, mn, mx_ = nd.quantize_v2(nd.array(x))
        assert q.dtype == np.int8
        back = nd.dequantize(q, mn, mx_).asnumpy()
        assert np.abs(back - x).max() < np.abs(x).max() / 127 + 1e-6

    def test_quantized_fully_connected_vs_float(self):
        x = (rs.rand(5, 16).astype(np.float32) - 0.5) * 4
        w = (rs.rand(8, 16).astype(np.float32) - 0.5) * 2
        qx, mnx, mxx = nd.quantize_v2(nd.array(x))
        qw, mnw, mxw = nd.quantize_v2(nd.array(w))
        y32, mny, mxy = nd.quantized_fully_connected(
            qx, qw, None, mnx, mxx, mnw, mxw, num_hidden=8, no_bias=True)
        assert y32.dtype == np.int32
        y = nd.dequantize(nd.cast(y32, "int8"), mny, mxy)  # not the real path
        # proper dequant of the int32 accumulator:
        amax = float(mxy.asnumpy().ravel()[0] if hasattr(mxy, 'asnumpy') else mxy)
        y_real = y32.asnumpy().astype(np.float32) * (amax / 127.0 ** 2)
        ref = x @ w.T
        tol = np.abs(ref).max() * 0.03 + 0.05
        assert np.abs(y_real - ref).max() < tol

    def test_quantized_conv_vs_float(self):
        x = (rs.rand(1, 4, 6, 6).astype(np.float32) - 0.5) * 2
        w = (rs.rand(3, 4, 3, 3).astype(np.float32) - 0.5)
        qx, mnx, mxx = nd.quantize_v2(nd.array(x))
        qw, mnw, mxw = nd.quantize_v2(nd.array(w))
        y32, mny, mxy = nd.quantized_conv(
            qx, qw, None, mnx, mxx, mnw, mxw, kernel=(3, 3), num_filter=3,
            pad=(1, 1))
        amax = float(mxy.asnumpy().ravel()[0])
        y_real = y32.asnumpy().astype(np.float32) * (amax / 127.0 ** 2)
        import jax.numpy as jnp
        from jax import lax
        ref = np.asarray(lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW")))
        tol = np.abs(ref).max() * 0.05 + 0.05
        assert np.abs(y_real - ref).max() < tol

    def test_requantize(self):
        x = (rs.rand(3, 3).astype(np.float32) - 0.5) * 8
        qx, mn, mx_ = nd.quantize_v2(nd.array(x))
        # fake an int32 accumulator representing x directly
        import numpy as np_
        q32 = nd.cast(nd.array(np.round(x * (127.0 ** 2) / 8.0)), "int32")
        q8, mn8, mx8 = nd.requantize(q32, nd.array(-8.0), nd.array(8.0))
        back = q8.asnumpy().astype(np.float32) * \
            (float(mx8.asnumpy().ravel()[0]) / 127.0)
        assert np.abs(back - x).max() < 0.2


# ---------------------------------------------------------------------------
# per-element samplers (REF:src/operator/random/multisample_op.cc)
# ---------------------------------------------------------------------------
class TestSamplers:
    def test_sample_normal_shapes_and_moments(self):
        mu = nd.array(np.array([[0.0, 10.0]], np.float32))
        sig = nd.array(np.array([[1.0, 0.1]], np.float32))
        out = nd.sample_normal(mu, sig, shape=4000).asnumpy()
        assert out.shape == (1, 2, 4000)
        assert abs(out[0, 0].mean()) < 0.15
        assert abs(out[0, 1].mean() - 10.0) < 0.05

    def test_sample_gamma_mean(self):
        alpha = nd.array(np.array([2.0, 9.0], np.float32))
        beta = nd.array(np.array([3.0, 0.5], np.float32))
        out = nd.sample_gamma(alpha, beta, shape=4000).asnumpy()
        assert out.shape == (2, 4000)
        np.testing.assert_allclose(out.mean(1), [6.0, 4.5], rtol=0.15)

    def test_sample_exponential_poisson(self):
        lam = nd.array(np.array([0.5, 4.0], np.float32))
        e = nd.sample_exponential(lam, shape=4000).asnumpy()
        np.testing.assert_allclose(e.mean(1), [2.0, 0.25], rtol=0.2)
        p = nd.sample_poisson(lam, shape=4000).asnumpy()
        np.testing.assert_allclose(p.mean(1), [0.5, 4.0], rtol=0.2)

    @pytest.mark.slow
    def test_negative_binomial_mean(self):
        k = nd.array(np.array([4.0], np.float32))
        p = nd.array(np.array([0.5], np.float32))
        out = nd.sample_negative_binomial(k, p, shape=4000).asnumpy()
        # mean = k (1-p)/p = 4
        np.testing.assert_allclose(out.mean(), 4.0, rtol=0.25)
        g = nd.sample_generalized_negative_binomial(
            nd.array(np.array([3.0], np.float32)),
            nd.array(np.array([0.4], np.float32)), shape=4000).asnumpy()
        np.testing.assert_allclose(g.mean(), 3.0, rtol=0.25)
        r = nd.random_negative_binomial(k=3, p=0.4, shape=(2000,))
        np.testing.assert_allclose(r.asnumpy().mean(), 4.5, rtol=0.3)


def test_longtail_parity_ops():
    """linalg_gemm / batch_take / diag / smooth_l1 / ravel pair / Crop /
    hard_sigmoid (REF:src/operator/tensor round-out, VERDICT r2 missing#5)."""
    from tpu_mx.ndarray import ops
    rng = np.random.RandomState(0)
    a = nd.array(rng.rand(2, 3, 4).astype(np.float32))
    b = nd.array(rng.rand(2, 4, 5).astype(np.float32))
    c = nd.array(rng.rand(2, 3, 5).astype(np.float32))
    out = ops.linalg_gemm(a, b, c, alpha=2.0, beta=0.5)
    ref = 2.0 * np.matmul(a.asnumpy(), b.asnumpy()) + 0.5 * c.asnumpy()
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)

    x = nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    idx = nd.array(np.array([1, 0, 3], np.int32))
    assert ops.batch_take(x, idx).asnumpy().tolist() == [1.0, 4.0, 11.0]

    m = nd.array(rng.rand(4, 4).astype(np.float32))
    np.testing.assert_allclose(ops.diag(m).asnumpy(),
                               np.diagonal(m.asnumpy()))
    t3 = nd.array(rng.rand(2, 3, 4).astype(np.float32))
    # reference N-D default: diagonal over (axis1=0, axis2=1), NOT numpy's
    np.testing.assert_allclose(
        ops.diag(t3).asnumpy(), np.diagonal(t3.asnumpy(), 0, 0, 1))
    np.testing.assert_allclose(
        ops.diag(t3, axis1=1, axis2=2).asnumpy(),
        np.diagonal(t3.asnumpy(), 0, 1, 2))
    v = nd.array(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(ops.diag(v).asnumpy(), np.diag([1.0, 2.0]))

    s = nd.array(np.array([-2.0, 0.5, 2.0], np.float32))
    np.testing.assert_allclose(ops.smooth_l1(s).asnumpy(),
                               [1.5, 0.125, 1.5], rtol=1e-6)

    flat = nd.array(np.array([5, 7], np.int32))
    coords = ops.unravel_index(flat, shape=(3, 4))
    assert coords.asnumpy().tolist() == [[1, 1], [1, 3]]
    back = ops.ravel_multi_index(coords, shape=(3, 4))
    assert back.asnumpy().tolist() == [5, 7]

    with pytest.raises(ValueError, match="h_w"):
        ops.Crop(nd.array(np.zeros((1, 1, 4, 4), np.float32)),
                 offset=(1, 1))
    img = nd.array(rng.rand(1, 2, 8, 8).astype(np.float32))
    assert ops.Crop(img, h_w=(4, 6), offset=(1, 2)).shape == (1, 2, 4, 6)
    like = nd.array(np.zeros((1, 2, 5, 5), np.float32))
    np.testing.assert_allclose(
        ops.Crop(img, like).asnumpy(), img.asnumpy()[:, :, :5, :5])

    hs = ops.hard_sigmoid(nd.array(np.array([-10.0, 0.0, 10.0],
                                            np.float32)))
    np.testing.assert_allclose(hs.asnumpy(), [0.0, 0.5, 1.0])

    # grads flow through the differentiable ones
    from tpu_mx import autograd
    g = nd.array(np.array([0.3], np.float32))
    g.attach_grad()
    with autograd.record():
        l = ops.smooth_l1(g).sum()
    l.backward()
    np.testing.assert_allclose(g.grad.asnumpy(), [0.3], rtol=1e-5)


class TestRound3LongTail:
    def test_activations_and_special(self):
        x = nd.array(np.array([-2.0, 0.0, 1.5]))
        np.testing.assert_allclose(
            nd.log_sigmoid(x).asnumpy(),
            np.log(1 / (1 + np.exp(-np.array([-2.0, 0.0, 1.5])))),
            rtol=1e-5)
        m = nd.mish(x).asnumpy()
        xs = np.array([-2.0, 0.0, 1.5])
        np.testing.assert_allclose(
            m, xs * np.tanh(np.log1p(np.exp(xs))), rtol=1e-5)
        hs = nd.hard_swish(nd.array(np.array([-4.0, 0.0, 3.0])))
        np.testing.assert_allclose(hs.asnumpy(), [0.0, 0.0, 3.0], atol=1e-6)
        import scipy.special as sp
        np.testing.assert_allclose(
            nd.digamma(nd.array(np.array([1.0, 2.5]))).asnumpy(),
            sp.digamma([1.0, 2.5]), rtol=1e-5)
        np.testing.assert_allclose(
            nd.polygamma(1, nd.array(np.array([1.0, 2.0]))).asnumpy(),
            sp.polygamma(1, [1.0, 2.0]), rtol=1e-4)
        np.testing.assert_allclose(
            nd.gammainc(nd.array(np.array([2.0])),
                        nd.array(np.array([1.5]))).asnumpy(),
            sp.gammainc(2.0, 1.5), rtol=1e-5)
        np.testing.assert_allclose(
            nd.erfcinv(nd.array(np.array([0.5]))).asnumpy(),
            sp.erfcinv(0.5), rtol=1e-5)

    def test_moments_and_all_finite(self):
        x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
        mu, var = nd.moments(x, axes=(1,))
        np.testing.assert_allclose(mu.asnumpy(), [1.5, 5.5, 9.5])
        np.testing.assert_allclose(var.asnumpy(), [1.25] * 3)
        good = nd.multi_all_finite(x, nd.ones((2,)))
        assert float(good.asnumpy()[0]) == 1.0
        bad = nd.multi_all_finite(x, nd.array(np.array([np.inf])))
        assert float(bad.asnumpy()[0]) == 0.0

    def test_khatri_rao(self):
        a = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]]))
        b = nd.array(np.array([[1.0, 0.0], [0.0, 1.0], [2.0, 2.0]]))
        out = nd.khatri_rao(a, b).asnumpy()
        assert out.shape == (6, 2)
        # column k = kron(a[:,k], b[:,k])
        np.testing.assert_allclose(out[:, 0],
                                   np.kron([1.0, 3.0], [1.0, 0.0, 2.0]))
        np.testing.assert_allclose(out[:, 1],
                                   np.kron([2.0, 4.0], [0.0, 1.0, 2.0]))

    def test_masked_softmax(self):
        x = nd.array(np.array([[1.0, 2.0, 3.0], [1.0, 1.0, 1.0]]))
        mask = nd.array(np.array([[1, 1, 0], [0, 0, 0]], np.int32))
        p = nd.masked_softmax(x, mask).asnumpy()
        np.testing.assert_allclose(p[0, :2],
                                   np.exp([1.0, 2.0]) /
                                   np.exp([1.0, 2.0]).sum(), rtol=1e-5)
        assert p[0, 2] == 0.0 and (p[1] == 0.0).all()
        lp = nd.masked_log_softmax(x, mask).asnumpy()
        np.testing.assert_allclose(np.exp(lp[0, :2]), p[0, :2], rtol=1e-5)

    def test_im2col_col2im_roundtrip(self):
        x = nd.array(np.random.RandomState(0).rand(2, 3, 6, 6)
                     .astype(np.float32))
        cols = nd.im2col(x, kernel=(3, 3), stride=(1, 1))
        assert cols.shape == (2, 27, 16)
        # col2im of im2col counts each pixel once per window covering it
        back = nd.col2im(cols, (6, 6), kernel=(3, 3), stride=(1, 1))
        counts = nd.col2im(nd.ones_like(cols), (6, 6), kernel=(3, 3),
                           stride=(1, 1))
        np.testing.assert_allclose(
            (back / counts).asnumpy(), x.asnumpy(), rtol=1e-5)

    def test_indexing_helpers_and_lrn(self):
        l = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
        r = nd.array(np.array([0, 2, 1, 0], np.float32))
        picked = nd.choose_element_0index(l, r).asnumpy()
        np.testing.assert_allclose(picked, [0, 5, 7, 9])
        filled = nd.fill_element_0index(
            l, nd.array(np.full((4,), -1.0, np.float32)), r).asnumpy()
        assert (filled[np.arange(4), [0, 2, 1, 0]] == -1).all()

        x = nd.array(np.random.RandomState(1).rand(1, 5, 4, 4)
                     .astype(np.float32))
        y = nd.LRN(x, nsize=3).asnumpy()
        # manual channel-window normalization for channel 2
        sq = np.square(x.asnumpy())
        acc = sq[:, 1] + sq[:, 2] + sq[:, 3]
        ref = x.asnumpy()[:, 2] / (2.0 + 1e-4 * acc / 3) ** 0.75
        np.testing.assert_allclose(y[:, 2], ref, rtol=1e-4)


@pytest.mark.slow
def test_round3_optimizers_converge():
    """DCASGD/SGLD/Adamax/Nadam/FTML minimize a quadratic through the
    Updater path (REF optimizer families)."""
    from tpu_mx import autograd, nd
    from tpu_mx.optimizer import Updater
    lrs = {"dcasgd": 0.05, "sgld": 0.05, "adamax": 0.1, "nadam": 0.05,
           "ftml": 0.5}
    for name, lr in lrs.items():
        mx.random.seed(0)
        w = nd.array(np.array([5.0, -3.0], np.float32))
        w.attach_grad()
        upd = Updater(mx.optimizer.create(name, learning_rate=lr))
        for t in range(250):
            with autograd.record():
                loss = (w * w).sum()
            loss.backward()
            upd(0, w.grad, w)
        final = float((w.asnumpy() ** 2).sum())
        # SGLD carries injected noise ~ sqrt(lr): a loose bowl is the pass
        bound = 1.0 if name != "sgld" else 2.0
        assert final < bound, (name, w.asnumpy())


def test_round3_optimizers_in_compiled_step():
    """The new optimizers' update_core traces into CompiledTrainStep."""
    from tpu_mx import gluon, nd
    from tpu_mx.gluon import nn
    from tpu_mx.parallel import CompiledTrainStep
    for name in ("adamax", "nadam", "ftml"):
        np.random.seed(1)
        net = nn.Dense(2, in_units=4)
        net.initialize()
        net(nd.ones((1, 4)))
        step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 mx.optimizer.create(name,
                                                     learning_rate=0.05))
        x = nd.array(np.random.rand(8, 4).astype(np.float32))
        y = nd.array(np.random.randint(0, 2, (8,)).astype(np.float32))
        losses = [float(np.asarray(step.step(x, y)._data))
                  for _ in range(12)]
        assert losses[-1] < losses[0], (name, losses)


@pytest.mark.slow
def test_round3_ops_numeric_gradients():
    """Finite-difference gradient checks for this round's differentiable
    additions (the reference test strategy's core tool, SURVEY §4)."""
    from tpu_mx.test_utils import check_numeric_gradient
    rng = np.random.RandomState(0)
    x34 = rng.rand(3, 4).astype(np.float32) + 0.1

    check_numeric_gradient(lambda a: nd.mish(a[0]), [x34])
    check_numeric_gradient(lambda a: nd.log_sigmoid(a[0]), [x34])
    check_numeric_gradient(lambda a: nd.hard_swish(a[0]), [x34 + 1.0])
    check_numeric_gradient(
        lambda a: nd.masked_softmax(
            a[0], nd.array(np.array([[1, 1, 0, 1]] * 3, np.int32))),
        [x34])
    m, v = None, None
    check_numeric_gradient(lambda a: nd.moments(a[0], axes=(1,))[0], [x34])
    check_numeric_gradient(lambda a: nd.moments(a[0], axes=(1,))[1], [x34])
    check_numeric_gradient(lambda a: nd.khatri_rao(a[0], a[1]),
                           [x34, rng.rand(2, 4).astype(np.float32)])
    check_numeric_gradient(
        lambda a: nd.im2col(a[0], kernel=(2, 2)),
        [rng.rand(1, 2, 4, 4).astype(np.float32)])
    check_numeric_gradient(
        lambda a: nd.LRN(a[0], nsize=3),
        [rng.rand(1, 4, 3, 3).astype(np.float32)])
    # GroupNorm: finite differences are noise-dominated here (rsqrt of a
    # small-group variance has high curvature; and sum(out) is constant in
    # x), so check analytically against torch instead
    import torch
    from tpu_mx import autograd as ag
    x = rng.rand(2, 4, 3, 3).astype(np.float32)
    g = (rng.rand(2) + 0.5).astype(np.float32)
    b = rng.rand(2).astype(np.float32)
    xx, gg, bb = nd.array(x), nd.array(g), nd.array(b)
    for a in (xx, gg, bb):
        a.attach_grad()
    with ag.record():
        nd.GroupNorm(xx, gg, bb, num_groups=2).square().sum().backward()
    tx = torch.tensor(x, requires_grad=True)
    tg = torch.tensor(np.repeat(g, 2), requires_grad=True)
    tb = torch.tensor(np.repeat(b, 2), requires_grad=True)
    (torch.nn.functional.group_norm(tx, 2, tg, tb, eps=1e-5) ** 2) \
        .sum().backward()
    np.testing.assert_allclose(xx.grad.asnumpy(), tx.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gg.grad.asnumpy(),
                               tg.grad.numpy().reshape(2, 2).sum(1),
                               rtol=1e-4)


# ---------------------------------------------------------------------------
# r4 long-tail parity ops (REF:src/operator/contrib/**, svm_output.cc)
# ---------------------------------------------------------------------------
class TestR4LongTail:
    def test_argmax_channel(self):
        x = rs.rand(4, 7).astype(np.float32)
        out = nd.argmax_channel(nd.array(x))
        np.testing.assert_array_equal(out.asnumpy(),
                                      np.argmax(x, axis=1).astype(np.float32))

    def test_svm_output_l2_grad(self):
        from tpu_mx import autograd
        x = rs.randn(3, 5).astype(np.float32)
        y = np.array([0, 2, 4], np.float32)
        xx = nd.array(x)
        xx.attach_grad()
        with autograd.record():
            out = nd.SVMOutput(xx, nd.array(y), margin=1.0,
                               regularization_coefficient=0.5)
            out.backward()
        np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)  # identity fwd
        g = xx.grad.asnumpy()
        # hand gradient: j!=y: 2*lam*max(0, m + x_j - x_y); y: -sum
        for i in range(3):
            yi = int(y[i])
            h = np.maximum(0.0, 1.0 + x[i] - x[i, yi])
            ref = 2 * 0.5 * h
            ref[yi] = 0.0
            ref_y = -ref.sum()
            np.testing.assert_allclose(g[i, yi], ref_y, rtol=1e-5)
            mask = np.arange(5) != yi
            np.testing.assert_allclose(g[i, mask], ref[mask], rtol=1e-5)

    def test_quadratic_and_div_sqrt_dim(self):
        x = rs.rand(3, 4).astype(np.float32)
        out = nd.contrib.quadratic(nd.array(x), a=2.0, b=-1.0, c=0.5)
        np.testing.assert_allclose(out.asnumpy(), 2 * x * x - x + 0.5,
                                   rtol=1e-6)
        out = nd.contrib.div_sqrt_dim(nd.array(x))
        np.testing.assert_allclose(out.asnumpy(), x / np.sqrt(4.0),
                                   rtol=1e-6)

    def test_arange_like(self):
        x = nd.ones((2, 3))
        out = nd.contrib.arange_like(x)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.arange(6, dtype=np.float32)
                                   .reshape(2, 3))
        out = nd.contrib.arange_like(x, axis=1, start=5.0, step=2.0)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.array([5.0, 7.0, 9.0], np.float32))

    def test_allclose_op(self):
        a = nd.ones((3,))
        b = nd.array(np.array([1.0, 1.0, 1.0 + 1e-7], np.float32))
        assert float(nd.contrib.allclose(a, b).asnumpy()) == 1.0
        c = nd.array(np.array([1.0, 2.0, 1.0], np.float32))
        assert float(nd.contrib.allclose(a, c).asnumpy()) == 0.0

    def test_index_copy_and_index_array(self):
        old = nd.zeros((5, 3))
        new = nd.ones((2, 3))
        idx = nd.array(np.array([1, 3], np.float32))
        out = nd.contrib.index_copy(old, idx, new)
        ref = np.zeros((5, 3), np.float32)
        ref[[1, 3]] = 1.0
        np.testing.assert_array_equal(out.asnumpy(), ref)

        ia = nd.contrib.index_array(nd.ones((2, 3)))
        assert ia.shape == (2, 3, 2)
        np.testing.assert_array_equal(ia.asnumpy()[1, 2], [1, 2])
        ia1 = nd.contrib.index_array(nd.ones((2, 3)), axes=(1,))
        np.testing.assert_array_equal(ia1.asnumpy()[..., 0],
                                      [[0, 1, 2], [0, 1, 2]])

    def test_gradientmultiplier_scales_grad(self):
        from tpu_mx import autograd
        x = nd.array(rs.rand(4).astype(np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.contrib.gradientmultiplier(x, scalar=-0.5)
            y.sum().backward()
        np.testing.assert_allclose(x.grad.asnumpy(), -0.5 * np.ones(4),
                                   rtol=1e-6)

    def test_fft_ifft_roundtrip(self):
        x = rs.rand(2, 8).astype(np.float32)
        f = nd.contrib.fft(nd.array(x))
        assert f.shape == (2, 16)
        ref = np.fft.fft(x, axis=-1)
        np.testing.assert_allclose(f.asnumpy()[:, 0::2], ref.real.astype(
            np.float32), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(f.asnumpy()[:, 1::2], ref.imag.astype(
            np.float32), rtol=1e-4, atol=1e-4)
        # unnormalized inverse (reference cuFFT contract): /n recovers x
        back = nd.contrib.ifft(f)
        np.testing.assert_allclose(back.asnumpy() / 8.0, x, rtol=1e-4,
                                   atol=1e-5)

    def test_adaptive_avg_pooling(self):
        x = rs.rand(2, 3, 6, 8).astype(np.float32)
        out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x), output_size=2)
        assert out.shape == (2, 3, 2, 2)
        ref = x.reshape(2, 3, 2, 3, 2, 4).mean(axis=(3, 5))
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)
        # non-divisible output size still averages disjoint-ish bins
        out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x),
                                              output_size=(3, 5))
        assert out.shape == (2, 3, 3, 5)
        np.testing.assert_allclose(out.asnumpy().mean(), x.mean(axis=(2, 3),
                                   keepdims=True).mean(), rtol=0.05)

    def test_bipartite_matching(self):
        s = np.array([[[0.9, 0.1], [0.8, 0.7], [0.1, 0.6]]], np.float32)
        row, col = nd.contrib.bipartite_matching(nd.array(s),
                                                 threshold=0.5)
        # greedy: (0,0)=0.9 first, then (1,1)=0.7; row 2 unmatched
        np.testing.assert_array_equal(row.asnumpy(), [[0, 1, -1]])
        np.testing.assert_array_equal(col.asnumpy(), [[0, 1]])


class TestCorrelation:
    """FlowNet cost volume vs a naive NumPy oracle
    (REF:src/operator/correlation.cc semantics)."""

    @staticmethod
    def _naive(x1, x2, K, md, s1, s2, pad, multiply):
        b, c, h, w = x1.shape
        kr = (K - 1) // 2
        bd = md + kr
        ph, pw = h + 2 * pad, w + 2 * pad
        th = -(-(ph - 2 * bd) // s1)
        tw = -(-(pw - 2 * bd) // s1)
        p1 = np.pad(x1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        p2 = np.pad(x2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        disps = range(-(md // s2) * s2, md // s2 * s2 + 1, s2)
        out = np.zeros((b, len(list(disps)) ** 2, th, tw), np.float32)
        for bi in range(b):
            for di, dy in enumerate(disps):
                for dj, dx in enumerate(disps):
                    for yi in range(th):
                        for xi in range(tw):
                            yc, xc = bd + yi * s1, bd + xi * s1
                            acc = 0.0
                            for oy in range(-kr, kr + 1):
                                for ox in range(-kr, kr + 1):
                                    a = p1[bi, :, yc + oy, xc + ox]
                                    v = p2[bi, :, yc + oy + dy,
                                           xc + ox + dx]
                                    acc += float((a * v).sum() if multiply
                                                 else np.abs(a - v).sum())
                            out[bi, di * len(list(disps)) + dj, yi, xi] = \
                                acc / (K * K * c)
        return out

    @pytest.mark.parametrize("cfg", [
        dict(K=1, md=1, s1=1, s2=1, pad=1, multiply=True),
        dict(K=3, md=2, s1=2, s2=2, pad=2, multiply=True),
        dict(K=1, md=1, s1=1, s2=1, pad=1, multiply=False),
    ])
    def test_matches_naive(self, cfg):
        x1 = rs.rand(2, 3, 8, 9).astype(np.float32)
        x2 = rs.rand(2, 3, 8, 9).astype(np.float32)
        out = nd.Correlation(nd.array(x1), nd.array(x2),
                             kernel_size=cfg["K"],
                             max_displacement=cfg["md"],
                             stride1=cfg["s1"], stride2=cfg["s2"],
                             pad_size=cfg["pad"],
                             is_multiply=cfg["multiply"])
        ref = self._naive(x1, x2, cfg["K"], cfg["md"], cfg["s1"],
                          cfg["s2"], cfg["pad"], cfg["multiply"])
        assert out.shape == ref.shape
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5,
                                   atol=1e-6)

    def test_grads_flow(self):
        from tpu_mx import autograd
        x1 = nd.array(rs.rand(1, 2, 6, 6).astype(np.float32))
        x2 = nd.array(rs.rand(1, 2, 6, 6).astype(np.float32))
        x1.attach_grad(); x2.attach_grad()
        with autograd.record():
            nd.Correlation(x1, x2, max_displacement=1, pad_size=1
                           ).sum().backward()
        assert np.abs(x1.grad.asnumpy()).sum() > 0
        assert np.abs(x2.grad.asnumpy()).sum() > 0


def test_v1_deprecated_aliases_warn_and_forward():
    import warnings
    x = nd.array(rs.rand(1, 3, 8, 8).astype(np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = nd.Pooling_v1(x, kernel=(2, 2), pool_type="max")
        assert any(issubclass(i.category, DeprecationWarning) for i in w)
    ref = nd.Pooling(x, kernel=(2, 2), pool_type="max")
    np.testing.assert_array_equal(out.asnumpy(), ref.asnumpy())


class TestPSROI:
    """Position-sensitive ROI ops (REF:contrib/{psroi_pooling,
    deformable_psroi_pooling}.cc + roi_align position_sensitive)."""

    def _ps_data(self, D=2, g=3, H=9, W=9):
        # channel c holds constant value c so the position-sensitive
        # channel MAPPING is directly observable in the output
        C = D * g * g
        x = np.tile(np.arange(C, dtype=np.float32)[None, :, None, None],
                    (1, 1, H, W))
        return x, C

    def test_psroi_pooling_channel_mapping(self):
        D, g = 2, 3
        x, C = self._ps_data(D, g)
        rois = np.array([[0, 0, 0, 8, 8]], np.float32)  # whole image
        out = nd.PSROIPooling(nd.array(x), nd.array(rois),
                              spatial_scale=1.0, output_dim=D,
                              pooled_size=g, group_size=g)
        assert out.shape == (1, D, g, g)
        ref = np.empty((D, g, g), np.float32)
        for d in range(D):
            for i in range(g):
                for j in range(g):
                    ref[d, i, j] = (d * g + i) * g + j
        np.testing.assert_allclose(out.asnumpy()[0], ref, rtol=1e-6)

    def test_psroi_pooling_averages_region(self):
        # one output dim, k=g=1: plain average over the rounded ROI
        H = W = 8
        x = np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W)
        rois = np.array([[0, 2, 2, 5, 5]], np.float32)
        out = nd.PSROIPooling(nd.array(x), nd.array(rois),
                              output_dim=1, pooled_size=1, group_size=1)
        # rounded end = round(x2+1)*scale = 6 (exclusive): rows/cols 2..5
        ref = x[0, 0, 2:6, 2:6].mean()
        np.testing.assert_allclose(float(np.asarray(out.asnumpy()).ravel()[0]),
                                   ref, rtol=0.05)

    @pytest.mark.slow
    def test_deformable_psroi_no_trans_matches_zero_offsets(self):
        D, g = 2, 3
        x, C = self._ps_data(D, g)
        rois = np.array([[0, 1, 1, 7, 7]], np.float32)
        base = nd.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), no_trans=True, output_dim=D,
            pooled_size=g, group_size=g, sample_per_part=2)
        zero_t = nd.array(np.zeros((1, 2, g, g), np.float32))
        with_zero = nd.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), zero_t, output_dim=D,
            pooled_size=g, group_size=g, sample_per_part=2, trans_std=0.1)
        np.testing.assert_allclose(base.asnumpy(), with_zero.asnumpy(),
                                   rtol=1e-6)
        assert base.shape == (1, D, g, g)
        # constant-channel data: the channel mapping shows through exactly
        ref = np.empty((D, g, g), np.float32)
        for d in range(D):
            for i in range(g):
                for j in range(g):
                    ref[d, i, j] = (d * g + i) * g + j
        np.testing.assert_allclose(base.asnumpy()[0], ref, rtol=1e-6)

    @pytest.mark.slow
    def test_deformable_psroi_offsets_shift_sampling(self):
        # gradient image along x: positive dx offset must increase values
        H = W = 12
        x = np.tile(np.arange(W, dtype=np.float32)[None, None, None, :],
                    (1, 1, H, 1))
        rois = np.array([[0, 2, 2, 7, 7]], np.float32)
        t0 = np.zeros((1, 2, 2, 2), np.float32)
        tx = t0.copy()
        tx[0, 1] = 1.0  # dx channel (odd index)
        out0 = nd.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), nd.array(t0), output_dim=1,
            pooled_size=2, group_size=1, part_size=2, trans_std=0.2)
        outx = nd.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), nd.array(tx), output_dim=1,
            pooled_size=2, group_size=1, part_size=2, trans_std=0.2)
        assert (outx.asnumpy() > out0.asnumpy()).all()
        # and grads flow into the offsets
        from tpu_mx import autograd
        tt = nd.array(tx)
        tt.attach_grad()
        with autograd.record():
            nd.DeformablePSROIPooling(
                nd.array(x), nd.array(rois), tt, output_dim=1,
                pooled_size=2, group_size=1, part_size=2,
                trans_std=0.2).sum().backward()
        assert np.abs(tt.grad.asnumpy()).sum() > 0

    def test_roi_align_position_sensitive(self):
        D, ph = 2, 2
        C = D * ph * ph
        x = np.tile(np.arange(C, dtype=np.float32)[None, :, None, None],
                    (1, 1, 8, 8))
        rois = np.array([[0, 0, 0, 7, 7]], np.float32)
        out = nd.ROIAlign(nd.array(x), nd.array(rois), pooled_size=(ph, ph),
                          position_sensitive=True)
        assert out.shape == (1, D, ph, ph)
        ref = np.arange(C, dtype=np.float32).reshape(D, ph, ph)
        np.testing.assert_allclose(out.asnumpy()[0], ref, rtol=1e-6)


def test_softmax_0x_alias_is_softmax_output():
    """Upstream add_alias: nd.Softmax IS SoftmaxOutput (fwd softmax +
    injected CE grad), not nd.softmax."""
    import warnings
    from tpu_mx import autograd
    x = rs.randn(3, 5).astype(np.float32)
    y = np.array([0, 2, 4], np.float32)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = nd.Softmax(nd.array(x), nd.array(y))
        assert any(issubclass(i.category, DeprecationWarning) for i in w)
    ref = nd.SoftmaxOutput(nd.array(x), nd.array(y))
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-6)
    xx = nd.array(x)
    xx.attach_grad()
    with autograd.record():
        nd.Softmax(xx, nd.array(y)).backward()
    g = xx.grad.asnumpy()
    p = np.exp(x) / np.exp(x).sum(1, keepdims=True)
    oh = np.eye(5, dtype=np.float32)[y.astype(int)]
    np.testing.assert_allclose(g, p - oh, rtol=1e-5, atol=1e-6)


def test_bilinear_border_extension_exact():
    """OOB samples converge EXACTLY to the border value (clamp before
    weights): a learned deformable offset pushing the window far outside
    must read the edge, not a blend of edge and interior rows."""
    H = W = 8
    x = np.tile(np.arange(H, dtype=np.float32)[None, None, :, None],
                (1, 1, 1, W))  # row r = value r
    rois = np.array([[0, 2, 2, 5, 5]], np.float32)
    t_up = np.zeros((1, 2, 1, 1), np.float32)
    t_up[0, 0] = -100.0  # dy: far above the image
    out = nd.DeformablePSROIPooling(
        nd.array(x), nd.array(rois), nd.array(t_up), output_dim=1,
        pooled_size=1, group_size=1, part_size=1, trans_std=1.0)
    np.testing.assert_allclose(out.asnumpy().ravel(), [0.0], atol=1e-6)


class TestHawkesLL:
    """hawkesll vs a brute-force oracle + the state-carry composition
    property (REF:src/operator/contrib/hawkes_ll.cc)."""

    @staticmethod
    def _ref(mu, a, b, r0, times_marks, mt):
        ll = 0.0
        for idx, (ti, mi) in enumerate(times_marks):
            lam = mu[mi] + a[mi] * b[mi] * (
                r0[mi] * np.exp(-b[mi] * ti) +
                sum(np.exp(-b[mi] * (ti - tj))
                    for tj, mj in times_marks[:idx] if mj == mi))
            ll += np.log(lam)
        comp = 0.0
        for k in range(len(a)):
            comp += mu[k] * mt + a[k] * r0[k] * (1 - np.exp(-b[k] * mt))
            comp += a[k] * sum(1 - np.exp(-b[k] * (mt - tj))
                               for tj, mj in times_marks if mj == k)
        return ll - comp

    def _mk(self, seed=0, K=3, n=5, T=8, mt=6.0, r0=None):
        r = np.random.RandomState(seed)
        times = np.sort(r.uniform(0.2, mt - 0.5, n))
        marks = r.randint(0, K, n)
        lags = np.zeros(T, np.float32)
        lags[:n] = np.diff(np.concatenate([[0.0], times])).astype(np.float32)
        lags[n:] = 0.33  # padded garbage must be masked out
        mk = np.zeros(T, np.int32)
        mk[:n] = marks
        mk[n:] = r.randint(0, K, T - n)
        mu = r.uniform(0.2, 0.8, K).astype(np.float32)
        a = r.uniform(0.1, 0.5, K).astype(np.float32)
        b = r.uniform(0.5, 2.0, K).astype(np.float32)
        r0 = np.zeros(K, np.float32) if r0 is None else r0
        return mu, a, b, r0, times, marks, lags, mk, n, mt

    def test_matches_bruteforce(self):
        mu, a, b, r0, times, marks, lags, mk, n, mt = self._mk()
        ll, state = nd.contrib.hawkesll(
            nd.array(mu[None]), nd.array(a), nd.array(b),
            nd.array(r0[None]), nd.array(lags[None]),
            nd.array(mk[None].astype(np.float32)),
            nd.array(np.array([n], np.float32)),
            nd.array(np.array([mt], np.float32)))
        ref = self._ref(mu, a, b, r0, list(zip(times, marks)), mt)
        np.testing.assert_allclose(float(np.asarray(ll.asnumpy()).ravel()[0]),
                                   ref, rtol=1e-4)
        # state = per-mark excitation decayed to the horizon
        state_ref = np.array(
            [r0[k] * np.exp(-b[k] * mt) +
             sum(np.exp(-b[k] * (mt - tj))
                 for tj, mj in zip(times, marks) if mj == k)
             for k in range(3)], np.float32)
        np.testing.assert_allclose(state.asnumpy()[0], state_ref, rtol=1e-4)

    def test_state_carry_composes(self):
        """LL over [0, mt] == LL[0, s] + LL[s, mt] with the returned state
        carried (the truncated-sequence contract)."""
        mu, a, b, r0, times, marks, lags, mk, n, mt = self._mk(seed=3,
                                                              mt=8.0)
        split = 4.0
        first = times <= split
        t1, m1 = times[first], marks[first]
        t2, m2 = times[~first], marks[~first]

        def run(mu, a, b, r0, times, marks, t_origin, mt_win):
            n = len(times)
            T = max(n, 1) + 2
            lags = np.zeros(T, np.float32)
            prev = t_origin
            for i, t in enumerate(times):
                lags[i] = t - prev
                prev = t
            mkv = np.zeros(T, np.float32)
            mkv[:n] = marks
            return nd.contrib.hawkesll(
                nd.array(mu[None]), nd.array(a), nd.array(b),
                nd.array(r0[None]), nd.array(lags[None]),
                nd.array(mkv[None]),
                nd.array(np.array([n], np.float32)),
                nd.array(np.array([mt_win], np.float32)))

        ll_full, _ = run(mu, a, b, r0, times, marks, 0.0, mt)
        ll1, s1 = run(mu, a, b, r0, t1, m1, 0.0, split)
        ll2, _ = run(mu, a, b, s1.asnumpy()[0], t2 - split, m2, 0.0,
                     mt - split)
        total = float(np.asarray(ll1.asnumpy()).ravel()[0]) + \
            float(np.asarray(ll2.asnumpy()).ravel()[0])
        np.testing.assert_allclose(
            float(np.asarray(ll_full.asnumpy()).ravel()[0]), total,
            rtol=1e-4)


def test_identity_attach_kl_sparse_reg():
    """Identity fwd; backward adds penalty*KL'(rho||rho_hat) per unit;
    moving_avg aux rebound in place with momentum."""
    from tpu_mx import autograd
    x = (rs.rand(8, 4) * 0.8 + 0.1).astype(np.float32)  # (0,1) acts
    ma0 = np.full(4, 0.5, np.float32)
    ma = nd.array(ma0)
    xx = nd.array(x)
    xx.attach_grad()
    with autograd.record():
        out = nd.IdentityAttachKLSparseReg(xx, sparseness_target=0.2,
                                           penalty=0.01, momentum=0.9,
                                           moving_avg=ma)
        out.sum().backward()
    np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)  # identity
    rho_hat = np.clip(0.9 * ma0 + 0.1 * x.mean(0), 1e-6, 1 - 1e-6)
    kl = 0.01 * (-0.2 / rho_hat + 0.8 / (1 - rho_hat))
    np.testing.assert_allclose(xx.grad.asnumpy(),
                               np.broadcast_to(1.0 + kl, (8, 4)),
                               rtol=1e-5)
    # aux rebound with momentum
    np.testing.assert_allclose(ma.asnumpy(), 0.9 * ma0 + 0.1 * x.mean(0),
                               rtol=1e-6)
    # without moving_avg: batch mean alone
    xx2 = nd.array(x)
    xx2.attach_grad()
    with autograd.record():
        nd.IdentityAttachKLSparseReg(xx2, sparseness_target=0.2,
                                     penalty=0.01).sum().backward()
    rho_hat2 = np.clip(x.mean(0), 1e-6, 1 - 1e-6)
    kl2 = 0.01 * (-0.2 / rho_hat2 + 0.8 / (1 - rho_hat2))
    np.testing.assert_allclose(xx2.grad.asnumpy(),
                               np.broadcast_to(1.0 + kl2, (8, 4)),
                               rtol=1e-5)


def test_identity_attach_kl_sparse_reg_aux_semantics():
    """Aux updates only on TRAINING forwards; traces with moving_avg
    error loudly instead of silently freezing the statistic."""
    from tpu_mx import autograd
    from tpu_mx.base import MXNetError
    x = (rs.rand(8, 4) * 0.8 + 0.1).astype(np.float32)
    ma0 = np.full(4, 0.5, np.float32)
    ma = nd.array(ma0)
    # inference forward: moving_avg untouched
    nd.IdentityAttachKLSparseReg(nd.array(x), moving_avg=ma)
    np.testing.assert_array_equal(ma.asnumpy(), ma0)
    # training forward: updated with momentum
    xx = nd.array(x)
    xx.attach_grad()
    with autograd.record():
        nd.IdentityAttachKLSparseReg(xx, moving_avg=ma).sum().backward()
    np.testing.assert_allclose(ma.asnumpy(), 0.9 * ma0 + 0.1 * x.mean(0),
                               rtol=1e-6)
    # hybridize trace with moving_avg: loud error (batch-mean mode works)
    from tpu_mx.gluon import nn

    class Net(mx.gluon.HybridBlock):
        def __init__(self, ma=None):
            super().__init__()
            self._ma = ma

        def hybrid_forward(self, F, x):
            return F.IdentityAttachKLSparseReg(x, moving_avg=self._ma)

    net = Net(ma)
    net.initialize()
    net.hybridize()
    with pytest.raises(MXNetError, match="moving_avg"):
        net(nd.array(x))
    net2 = Net(None)
    net2.initialize()
    net2.hybridize()
    out = net2(nd.array(x))
    np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)


def test_l2normalization_bf16_accumulates_f32():
    """Channel L2Normalization on bf16 input must accumulate its
    sum-of-squares in f32 (norm-op precision policy): the result then
    matches the f32 oracle to bf16 resolution even over many channels."""
    from tpu_mx import nd
    rng = np.random.RandomState(0)
    x = rng.rand(2, 512, 4, 4).astype(np.float32) + 0.5
    ref = nd.L2Normalization(nd.array(x), mode="channel").asnumpy()
    out = nd.L2Normalization(nd.cast(nd.array(x), "bfloat16"),
                             mode="channel")
    assert str(out.dtype) == "bfloat16"
    np.testing.assert_allclose(out.asnumpy().astype(np.float32), ref,
                               rtol=1.2e-2, atol=1e-3)
