"""Serving SLO engine (ISSUE 11): per-request latency attribution
(tpu_mx/serving/timeline.py), the live SLO monitor
(tpu_mx/serving/slo.py — windowed attainment, multi-window burn rate,
breach events, the scheduler signal hook), and the jax-less ops surface
(tools/slo_report.py).

The attribution invariant under test everywhere: the typed phases
(queue_wait / prefill / decode_gap / restart_penalty / defer_stall)
sum to every request's independently stamped wall clock within 5%, and
the first-token snapshot sums to the measured TTFT — including across
engine restarts (restart_penalty) and cache-backpressure deferrals."""
import json
import os
import subprocess
import sys

import pytest

from tpu_mx import serving, telemetry, tracing
from tpu_mx.contrib import chaos
from tpu_mx.serving import SLO, SLOMonitor, Server, TinyLM
from tpu_mx.serving.timeline import PHASES, RequestTimeline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    """Telemetry/tracing are process-global — isolate every test."""
    telemetry.reset()
    tracing.reset()
    yield
    telemetry.reset()
    tracing.reset()


def tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("seed", 0)
    return TinyLM(**kw)


def assert_attributed(req, tol=0.05):
    """The CI serve tier's invariant, as a test helper."""
    tl = req.timeline
    lat = req.finished_at - req.submitted_at
    assert tl.ended_at is not None and tl.outcome is not None
    assert abs(tl.total - lat) <= max(tol * lat, 1e-3), (
        req.id, tl.total, lat, tl.phases)
    if req.tokens:
        ttft_sum = sum(tl.ttft_breakdown.values())
        assert abs(ttft_sum - req.ttft) <= max(tol * req.ttft, 1e-3), (
            req.id, ttft_sum, req.ttft, tl.ttft_breakdown)
    assert set(tl.phases) <= set(PHASES)


# ---------------------------------------------------------------------------
# per-request attribution
# ---------------------------------------------------------------------------
def test_attribution_sums_to_wall_clock_happy_path():
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4)
    reqs = [srv.submit([1, 2, 3], max_new_tokens=5) for _ in range(6)]
    srv.run_until_idle()
    for r in reqs:
        assert r.state == "done"
        assert_attributed(r)
        # a healthy run attributes to the three live phases only
        assert r.timeline.phases.get("prefill", 0) > 0
        assert r.timeline.phases.get("decode_gap", 0) > 0
        assert r.timeline.requeues == 0
        assert "restart_penalty" not in r.timeline.phases
    # one serve.request_timeline event per request, schema-valid, and
    # its phase fields reproduce the in-process ledger
    evs = [e for e in tracing.snapshot()
           if e["event"] == "serve.request_timeline"]
    assert len(evs) == len(reqs)
    for e in evs:
        tracing.validate_event(e)
        assert e["data"]["outcome"] == "done"
        total = sum(e["data"][p] for p in PHASES)
        assert abs(total - e["data"]["latency"]) <= 1e-6
    # per-phase histograms landed (windowed like every histogram)
    h = telemetry.get("serve.phase_seconds", phase="decode_gap")
    assert h is not None and h.count == len(reqs)
    assert h.window_stats()["count"] == len(reqs)


def test_attribution_restart_penalty_on_engine_restart():
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 backoff=0.0)
    with chaos.enable(seed=0, nan_after=4):
        reqs = [srv.submit([1, 2, 3], max_new_tokens=6) for _ in range(4)]
        srv.run_until_idle()
    assert srv.restarts == 1
    bounced = [r for r in reqs if r.timeline.requeues]
    assert bounced, "the restart must have requeued in-flight requests"
    for r in reqs:
        assert r.state == "done"
        assert_attributed(r)
    for r in bounced:
        # the fault + rebuild + re-queue wait + replay prefill is
        # attributed, not smeared into queue_wait
        assert r.timeline.phases.get("restart_penalty", 0) > 0
        # prefill-replay recovery (ISSUE 19): the committed tokens and
        # the TTFT already measured STAND — nothing was re-yielded, so
        # the breakdown still reflects the original path to the first
        # token, without a restart_penalty component
        assert "restart_penalty" not in r.timeline.ttft_breakdown
    # the LEGACY prompt-replay arm discards the generation: TTFT
    # re-measures to the final attempt's first token, restart penalty
    # included in its breakdown
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 backoff=0.0, replay=False)
    with chaos.enable(seed=0, nan_after=4):
        reqs = [srv.submit([1, 2, 3], max_new_tokens=6) for _ in range(4)]
        srv.run_until_idle()
    assert srv.restarts == 1
    bounced = [r for r in reqs if r.timeline.requeues]
    assert bounced
    for r in bounced:
        assert r.state == "done"
        assert_attributed(r)
        assert r.timeline.ttft_breakdown.get("restart_penalty", 0) > 0


def test_attribution_defer_stall_on_cache_backpressure():
    # 3 prompts of 3 blocks each against an 8-block pool: the third
    # prefill admission bounces on CacheExhausted and is deferred until
    # decode evictions free blocks
    srv = Server(tiny(), num_blocks=8, block_size=4, max_batch=4,
                 max_tokens=10 ** 6)
    reqs = [srv.submit([1] * 10, max_new_tokens=4) for _ in range(3)]
    srv.run_until_idle()
    deferred = [r for r in reqs if r.timeline.defers]
    assert deferred, "the pool was sized to force a deferral"
    for r in reqs:
        assert r.state == "done"
        assert_attributed(r)
    for r in deferred:
        assert r.timeline.phases.get("defer_stall", 0) > 0


def test_attribution_rejected_request_closes_as_reject():
    srv = Server(tiny(), num_blocks=96, block_size=8)
    with chaos.enable(seed=0, reject_storm=1):
        with pytest.raises(serving.AdmissionReject):
            srv.submit([1, 2], max_new_tokens=2)
    evs = [e for e in tracing.snapshot()
           if e["event"] == "serve.request_timeline"]
    assert len(evs) == 1
    d = evs[0]["data"]
    assert d["outcome"] == "rejected"
    assert d["tokens"] == 0
    assert abs(sum(d[p] for p in PHASES) - d["latency"]) <= 1e-6


def test_timeline_mid_decode_fail_residual_is_decode_gap(monkeypatch):
    """A request failed while in flight (degraded drain of RUNNING
    requests) attributes its final interval to decode_gap — the time was
    spent decoding, not queued — while a fail during a genuine wait
    keeps the wait's label."""
    import tpu_mx.serving.timeline as _tlmod
    clock = [100.0]
    monkeypatch.setattr(_tlmod.time, "perf_counter", lambda: clock[0])
    tl = RequestTimeline()
    clock[0] = 100.1
    tl.mark_prefill_start()   # 0.1 queue_wait
    clock[0] = 100.2
    tl.mark_prefill_end()     # 0.1 prefill
    tl.mark_token(now=100.5)  # 0.3 decode_gap
    tl.finalize("req-m", "failed", now=101.5)   # 1.0 in-flight residual
    assert tl.phases["decode_gap"] == pytest.approx(1.3)
    assert tl.phases["queue_wait"] == pytest.approx(0.1)
    # a requeued-then-failed-waiting request stays on the wait label
    clock[0] = 100.0
    tl2 = RequestTimeline()
    tl2.mark_prefill_start()
    tl2.mark_prefill_end()
    tl2.mark_token(now=100.5)
    clock[0] = 101.0
    tl2.mark_requeue()        # 0.5 restart_penalty so far
    tl2.finalize("req-w", "failed", now=103.0)  # +2.0 still the penalty
    assert tl2.phases["restart_penalty"] == pytest.approx(2.5)
    assert tl2.phases["decode_gap"] == pytest.approx(0.5)


def test_timeline_is_idempotent_and_standalone():
    tl = RequestTimeline(t0=100.0)
    # un-marked timelines finalize cleanly (Request used outside a
    # Server, e.g. scheduler unit tests)
    tl.finalize("req-x", "done")
    ended = tl.ended_at
    tl.finalize("req-x", "failed")   # second finalize is a no-op
    assert tl.ended_at == ended and tl.outcome == "done"


# ---------------------------------------------------------------------------
# the SLO monitor
# ---------------------------------------------------------------------------
def test_slo_parse_and_validation():
    s = SLO.parse("itl_p99 < 50ms")
    assert s.metric == "serve.itl_seconds"
    assert s.threshold_seconds == pytest.approx(0.05)
    assert s.objective == pytest.approx(0.99)
    with pytest.raises(ValueError):
        SLO("m", quantile=1.5, threshold_seconds=0.1)
    with pytest.raises(ValueError, match="duplicate"):
        SLOMonitor(("itl_p99 < 50ms", "itl_p99 < 60ms"))
    with pytest.raises(ValueError, match="window"):
        SLOMonitor(windows=())


def test_slo_monitor_burn_rate_and_breach_transition_event():
    h = telemetry.histogram("serve.itl_seconds")
    # 3% of samples over the 50 ms threshold against a 1% budget: burn 3x
    for _ in range(970):
        h.observe(0.005)
    for _ in range(30):
        h.observe(0.2)
    mon = SLOMonitor(("itl_p99 < 50ms",), windows=(5.0, 30.0))
    sig = mon.refresh(force=True)
    st = sig["slos"]["itl_p99"]
    assert st["breaching"] and sig["breaching"]
    assert sig["max_burn_rate"] == pytest.approx(3.0, rel=0.15)
    for w in (5.0, 30.0):
        assert st["windows"][w]["attainment"] == pytest.approx(0.97,
                                                               abs=0.005)
    # gauges published, catalog-valid
    assert telemetry.get("serve.slo_breaching", slo="itl_p99").value == 1.0
    assert telemetry.get("serve.slo_burn_rate", slo="itl_p99",
                         window="30s").value == pytest.approx(3.0, rel=0.15)
    est = telemetry.get("serve.slo_estimate_seconds", slo="itl_p99").value
    assert est > 0.05   # the p99 estimate is over the threshold
    for rec in telemetry.snapshot():
        telemetry.validate_record(rec)
        assert rec["name"] in telemetry.KNOWN_METRICS
    # exactly one breach-transition event; a second refresh in the same
    # state emits nothing new
    evs = [e for e in tracing.snapshot() if e["event"] == "serve.slo"]
    assert len(evs) == 1 and evs[0]["data"]["breaching"] is True
    tracing.validate_event(evs[0])
    mon.refresh(force=True)
    assert len([e for e in tracing.snapshot()
                if e["event"] == "serve.slo"]) == 1


def test_slo_monitor_recovers_when_window_expires(monkeypatch):
    clock = [2000.0]
    monkeypatch.setattr(telemetry, "_monotonic", lambda: clock[0])
    h = telemetry.histogram("serve.itl_seconds")
    for _ in range(10):
        h.observe(0.5)   # every sample breaches
    mon = SLOMonitor(("itl_p99 < 50ms",), windows=(10.0, 60.0))
    assert mon.refresh(force=True)["breaching"]
    clock[0] += 120.0    # the bad minute scrolls out of the ring
    sig = mon.refresh(force=True)
    # empty windows are healthy-by-absence, and the flip emitted the
    # breach-cleared transition event
    assert not sig["breaching"]
    evs = [e for e in tracing.snapshot() if e["event"] == "serve.slo"]
    assert [e["data"]["breaching"] for e in evs] == [True, False]


def test_slo_monitor_requires_breach_in_all_windows(monkeypatch):
    clock = [3000.0]
    monkeypatch.setattr(telemetry, "_monotonic", lambda: clock[0])
    h = telemetry.histogram("serve.itl_seconds")
    for _ in range(100):
        h.observe(0.5)   # an old burst of pure badness
    clock[0] += 50.0     # ... 50 s ago
    for _ in range(100):
        h.observe(0.001)  # the recent window is clean
    mon = SLOMonitor(("itl_p99 < 50ms",), windows=(10.0, 60.0))
    sig = mon.refresh(force=True)
    st = sig["slos"]["itl_p99"]
    # slow window still burning, fast window clean -> no breach (the
    # multi-window AND kills flapping)
    assert st["windows"][60.0]["burn_rate"] >= 1.0
    assert st["windows"][10.0]["burn_rate"] == 0.0
    assert not st["breaching"]


def test_server_slo_hook_publishes_signal_to_scheduler():
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 slo=("itl_p99 < 30s", "ttft_p99 < 30s"))
    assert isinstance(srv.slo, SLOMonitor)
    reqs = [srv.submit([1, 2, 3], max_new_tokens=4) for _ in range(3)]
    srv.run_until_idle()
    assert all(r.state == "done" for r in reqs)
    sig = srv.slo_signal
    assert sig is not None and not sig["breaching"]
    assert srv.scheduler.slo_signal is sig
    assert telemetry.get("serve.slo_estimate_seconds",
                         slo="itl_p99") is not None
    # a server without a monitor reports None and sets nothing
    srv2 = Server(tiny(), num_blocks=32)
    assert srv2.slo_signal is None


def test_ttft_observed_once_per_request_across_restarts():
    """serve.ttft_seconds gets ONE sample per request, stamped from the
    final attempt: a per-attempt observe would let a restart's discarded
    attempt contribute an extra, optimistic (no restart penalty) sample
    to exactly the histogram the SLO monitor alerts on mid-incident."""
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 backoff=0.0)
    with chaos.enable(seed=0, nan_after=4):
        reqs = [srv.submit([1, 2, 3], max_new_tokens=6) for _ in range(4)]
        srv.run_until_idle()
    assert srv.restarts == 1 and all(r.state == "done" for r in reqs)
    assert any(r.requeues for r in reqs)   # a restart actually happened
    h = telemetry.get("serve.ttft_seconds")
    assert h.count == len(reqs), (h.count, len(reqs))
    # every sample carries final-attempt semantics: the histogram's max
    # is at least the slowest request's measured (restart-inclusive) TTFT
    assert h.max == pytest.approx(max(r.ttft for r in reqs), rel=1e-6)


def test_slo_gauges_publish_no_data_when_window_empties(monkeypatch):
    """A gauge frozen at its last non-empty value would read as live
    after traffic stops — an empty window publishes the NO_DATA
    sentinel (-1; NaN would break the strict-JSON black-box
    contract)."""
    from tpu_mx.serving.slo import NO_DATA
    clock = [1000.0]
    monkeypatch.setattr(telemetry, "_monotonic", lambda: clock[0])
    h = telemetry.histogram("serve.itl_seconds")
    h.observe(0.002)
    mon = SLOMonitor(("itl_p99 < 50ms",), windows=(5.0,),
                     min_refresh_seconds=0.0)
    mon.refresh(force=True)
    g = telemetry.get("serve.slo_estimate_seconds", slo="itl_p99")
    assert g.value == pytest.approx(0.002, rel=0.1)
    clock[0] += 1e4   # the whole ring expires
    mon.refresh(force=True)
    assert g.value == NO_DATA
    assert telemetry.get("serve.slo_attainment", slo="itl_p99",
                         window="5s").value == NO_DATA
    # burn/breaching stay honest zeros (no evidence = no breach)
    assert telemetry.get("serve.slo_breaching", slo="itl_p99").value == 0.0
    # every record (and hence every black box) stays strict-JSON clean
    for rec in telemetry.snapshot():
        json.loads(json.dumps(rec, allow_nan=False))


def test_server_slo_false_means_unarmed():
    srv = Server(tiny(), num_blocks=32, slo=False)
    assert srv.slo is None and srv.slo_signal is None
    r = srv.submit([1, 2], max_new_tokens=2)
    srv.run_until_idle()
    assert r.state == "done"


def test_server_slo_accepts_single_spec_string_and_rejects_junk():
    srv = Server(tiny(), num_blocks=32, slo="itl_p99 < 30s")
    assert isinstance(srv.slo, SLOMonitor)
    assert [s.name for s in srv.slo.slos] == ["itl_p99"]
    r = srv.submit([1, 2], max_new_tokens=2)
    srv.run_until_idle()
    assert r.state == "done" and srv.slo_signal is not None
    with pytest.raises(TypeError, match="slo="):
        Server(tiny(), num_blocks=32, slo=object())


def test_prefill_fault_requeues_popped_admissions():
    """A non-CacheExhausted engine fault mid-prefill must not lose the
    admissions take_prefills() already popped: the restart path only
    requeues RUNNING requests, so the server has to put the popped ones
    back itself — a lost request's wait() would hang forever."""
    from tpu_mx.supervisor import NumericDivergence
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 backoff=0.0)
    real_prefill = srv.engine.prefill
    fired = []

    def poisoned(req):
        if not fired:
            fired.append(req.id)
            raise NumericDivergence("injected prefill fault")
        return real_prefill(req)

    srv.engine.prefill = poisoned
    reqs = [srv.submit([1, 2, 3], max_new_tokens=4) for _ in range(3)]
    srv.run_until_idle()
    assert fired and srv.restarts == 1
    assert all(r.state == "done" for r in reqs), [r.state for r in reqs]
    faulted = [r for r in reqs if r.id == fired[0]][0]
    assert faulted.requeues == 1
    assert faulted.timeline.phases.get("restart_penalty", 0) > 0
    for r in reqs:
        assert_attributed(r)


def test_nan_sample_dropped_visibly_not_misfiled():
    """A non-finite observation has no honest bucket (bisect would call
    NaN the fastest sample; the overflow would force false breaches for
    legitimate >30s samples; nan+x poisons the sum forever) — it is
    dropped and surfaced via the record's dropped_nonfinite field."""
    h = telemetry.histogram("serve.itl_seconds")
    for _ in range(99):
        h.observe(0.01)
    h.observe(float("nan"))
    assert h.count == 99 and h.dropped_nonfinite == 1
    assert h.window_fraction_le(0.05) == pytest.approx(1.0)
    # the record stays strict-JSON clean and carries the drop count
    rec = h._record(1.0)
    json.loads(json.dumps(rec, allow_nan=False))
    assert rec["sum"] == pytest.approx(0.99)
    assert rec["dropped_nonfinite"] == 1
    # a legitimately slow finite sample above the ladder top still
    # attains a threshold above it (no false breach)
    h.observe(40.0)
    assert h.window_fraction_le(60.0) == pytest.approx(1.0)


def test_histogram_nonfinite_never_reaches_buckets():
    """Neither NaN nor ±Inf may perturb the bucket counts, quantiles,
    or min/max — they are dropped (visibly; see the sibling test)."""
    h = telemetry.histogram("serve.itl_seconds")
    h.observe(0.001)
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe(float("-inf"))
    cum = dict(h.cumulative())
    assert cum["+Inf"] == 1 and h.count == 1
    assert h.dropped_nonfinite == 3
    assert h.window_quantile(0.99) == pytest.approx(0.001)
    assert (h.min, h.max) == (0.001, 0.001)


def test_restart_black_box_captures_slo_window_state(tmp_path):
    prefix = str(tmp_path / "sv")
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 backoff=0.0, blackbox=prefix, slo=True)
    with chaos.enable(seed=0, nan_after=4):
        reqs = [srv.submit([1, 2, 3], max_new_tokens=6) for _ in range(4)]
        srv.run_until_idle()
    assert srv.restarts == 1 and all(r.state == "done" for r in reqs)
    box = json.load(open(tracing.blackbox_path(prefix)))
    tracing.validate_blackbox(box)
    names = {(r["name"], json.dumps(r.get("labels", {}), sort_keys=True))
             for r in box["telemetry"]}
    assert ("serve.slo_estimate_seconds",
            '{"slo": "itl_p99"}') in names, sorted(names)[:20]
    # the box's tracing.events_dropped gauge rode along
    assert any(r["name"] == "tracing.events_dropped"
               for r in box["telemetry"])


# ---------------------------------------------------------------------------
# tools/slo_report.py (jax-less, rc 0/1/2)
# ---------------------------------------------------------------------------
def _make_artifacts(tmp_path):
    """A real storm's telemetry JSONL + end-of-run audit box."""
    jsonl = str(tmp_path / "m.jsonl")
    prefix = str(tmp_path / "audit")
    srv = Server(tiny(), num_blocks=96, block_size=8, max_batch=4,
                 backoff=0.0, slo=True)
    with chaos.enable(seed=0, nan_after=4):
        reqs = [srv.submit([1, 2, 3], max_new_tokens=5) for _ in range(4)]
        srv.run_until_idle()
    assert all(r.state == "done" for r in reqs)
    tracing.dump_blackbox(prefix, reason="slo audit")
    telemetry.flush(path=jsonl, final=True)
    return jsonl, tracing.blackbox_path(prefix)


def _run_slo_report(*args, poison=True):
    """Run the tool in a subprocess with jax/tpu_mx poisoned — it must
    never import either."""
    tool = os.path.join(REPO, "tools", "slo_report.py")
    preamble = ("import sys, runpy; "
                + ("sys.modules['jax'] = None; "
                   "sys.modules['tpu_mx'] = None; " if poison else "")
                + f"sys.argv = ['slo_report.py'] + {list(args)!r}; "
                + f"runpy.run_path({tool!r}, run_name='__main__')")
    return subprocess.run([sys.executable, "-c", preamble],
                          capture_output=True, text=True, timeout=120)


def test_slo_report_renders_and_validates_without_jax(tmp_path):
    jsonl, box = _make_artifacts(tmp_path)
    run = _run_slo_report(jsonl, "--box", box, "--validate")
    out = run.stdout + run.stderr
    assert run.returncode == 0, out
    assert "Windowed latency state" in out
    assert "SLO targets" in out
    assert "serve.itl_seconds" in out
    assert "Live monitor gauges" in out
    assert "Worst requests by latency" in out
    assert "restart_penalty" in out      # the faulted requests' phases
    assert "schema OK" in out
    assert "top 5 of 0" not in out       # timelines actually rendered


def test_slo_report_breach_rendering(tmp_path):
    # a file whose window clearly breaches a tight target
    h = telemetry.histogram("serve.itl_seconds")
    for _ in range(100):
        h.observe(0.2)
    jsonl = str(tmp_path / "m.jsonl")
    telemetry.flush(path=jsonl)
    run = _run_slo_report(jsonl, "--slo", "itl_p99 < 50ms")
    assert run.returncode == 0, run.stdout + run.stderr
    assert "BREACH" in run.stdout


def test_slo_report_rc1_on_schema_violations(tmp_path):
    jsonl, box = _make_artifacts(tmp_path)
    with open(jsonl, "a", encoding="utf-8") as f:
        f.write(json.dumps({"name": "not.in.catalog", "type": "counter",
                            "value": 1, "ts": 1.0}) + "\n")
    run = _run_slo_report(jsonl, "--validate")
    assert run.returncode == 1
    assert "not.in.catalog" in run.stderr
    # without --validate it renders anyway (ops view of a dirty file)
    assert _run_slo_report(jsonl).returncode == 0


def test_slo_report_rc1_on_attribution_invariant_break(tmp_path):
    jsonl, box_path = _make_artifacts(tmp_path)
    box = json.load(open(box_path))
    for e in box["events"]:
        if e["event"] == "serve.request_timeline":
            e["data"]["latency"] = e["data"]["latency"] + 10.0
    tampered = str(tmp_path / "tampered.json")
    with open(tampered, "w", encoding="utf-8") as f:
        json.dump(box, f)
    run = _run_slo_report(jsonl, "--box", tampered, "--validate")
    assert run.returncode == 1
    assert "phases sum to" in run.stderr


def test_slo_report_rc2_on_unreadable_input(tmp_path):
    run = _run_slo_report(str(tmp_path / "missing.jsonl"))
    assert run.returncode == 2
    jsonl, _ = _make_artifacts(tmp_path)
    run = _run_slo_report(jsonl, "--box", str(tmp_path / "nope.json"))
    assert run.returncode == 2
