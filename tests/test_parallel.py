"""Parallel layer tests on the virtual 8-device CPU mesh (SURVEY §4:
localhost multi-device testing; XLA CPU = the fake TPU)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import gluon, nd
from tpu_mx.gluon import nn

pytestmark = pytest.mark.slow  # 8-device virtual-mesh compiles (~4 min together)


def _mesh(**axes):
    from tpu_mx.parallel import make_mesh
    return make_mesh(axes)


def test_make_mesh_shapes():
    import jax
    from tpu_mx.parallel import make_mesh
    m = make_mesh({"dp": 8})
    assert m.shape["dp"] == 8
    m2 = make_mesh({"dp": 2, "tp": -1})
    assert m2.shape["tp"] == 4
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})


def test_ring_attention_matches_local():
    import jax.numpy as jnp
    from tpu_mx.parallel import local_flash_attention, ring_attention
    mesh = _mesh(sp=8)
    B, H, T, D = 2, 2, 32, 4
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    ref = local_flash_attention(q, k, v)
    out = ring_attention(q, k, v, mesh)
    assert float(jnp.abs(ref - out).max()) < 1e-5
    ref_c = local_flash_attention(q, k, v, causal=True)
    out_c = ring_attention(q, k, v, mesh, causal=True)
    assert float(jnp.abs(ref_c - out_c).max()) < 1e-5


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_backward_matches_dense(causal):
    """Gradients through the shard_map/ppermute/scan composition must equal
    the dense-attention gradients (VERDICT r1 weak#5: a vjp bug here would
    silently corrupt training)."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import ring_attention

    mesh = _mesh(sp=8)
    B, H, T, D = 2, 2, 32, 4
    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))

    def dense_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(jnp.sin(o))  # nonlinear scalarizer

    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=causal)
        return jnp.sum(jnp.sin(o))

    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5, err_msg=f"d{name}")


def test_attention_dispatch_counter():
    """Each attention trace records which path it took (VERDICT r1 weak#6)."""
    import jax.numpy as jnp
    from tpu_mx.parallel import ring_attention, local_flash_attention
    from tpu_mx.parallel import ring_attention as _ra_fn  # module attr via pkg
    from tpu_mx.parallel.ring_attention import dispatch_counts

    before = dict(dispatch_counts)
    q = jnp.ones((1, 1, 8, 4), jnp.float32)
    local_flash_attention(q, q, q)
    local_flash_attention(q, q, q)  # same signature: deduped
    assert dispatch_counts["xla_dense"] == before["xla_dense"] + 1  # CPU
    mesh = _mesh(sp=8)
    x = jnp.ones((1, 1, 32, 4), jnp.float32)
    ring_attention(x, x, x, mesh)
    assert dispatch_counts["ring"] == before["ring"] + 1


def test_sharded_checkpoint_reshard_dp2tp2_to_dp4(tmp_path):
    """Save a sharded checkpoint on a dp=2×tp=4 mesh with TP rules, restore
    onto a dp=8 mesh: training resumes with identical loss (SURVEY §5.4).
    (The 8-device CPU mesh analog of the verdict's dp=2×tp=2 → dp=4.)"""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(11)
        net = nn.HybridSequential(prefix="ckmodel_")
        net.add(nn.Dense(16, in_units=8, activation="relu", prefix="fc1_"))
        net.add(nn.Dense(4, in_units=16, prefix="fc2_"))
        net.initialize(init="xavier")
        return net

    rules = [("fc1_weight", P("tp", None)),
             ("fc2_weight", P(None, "tp"))]
    x = nd.array(np.random.RandomState(1).rand(8, 8).astype(np.float32))
    y = nd.array(np.array([0, 1, 2, 3, 0, 1, 2, 3], dtype=np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def make_step(net, mesh, rules):
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        return CompiledTrainStep(net, loss_fn, opt, mesh=mesh, rules=rules)

    # run A on dp=2 x tp=2: two steps, save, one more step -> loss3_ref
    step_a = make_step(build(), _mesh(dp=2, tp=4), rules)
    step_a.step(x, y)
    step_a.step(x, y)
    ck = str(tmp_path / "ck")
    step_a.save_checkpoint(ck)
    loss3_ref = float(np.asarray(step_a.step(x, y)._data))

    # run B on dp=4 (different mesh AND different param layout: replicated)
    step_b = make_step(build(), _mesh(dp=8), None)
    step_b.step(x, y)  # move state off its initial values; must be overwritten
    step_b.load_checkpoint(ck)
    assert step_b._t == 2
    loss3 = float(np.asarray(step_b.step(x, y)._data))
    assert abs(loss3 - loss3_ref) < 1e-5, (loss3, loss3_ref)


def test_attention_softmax_property():
    import jax.numpy as jnp
    from tpu_mx.parallel import local_flash_attention
    # constant V -> attention output must equal V rows regardless of scores
    q = jnp.asarray(np.random.rand(1, 1, 8, 4).astype(np.float32))
    k = jnp.asarray(np.random.rand(1, 1, 8, 4).astype(np.float32))
    v = jnp.ones((1, 1, 8, 4), jnp.float32) * 3.0
    out = local_flash_attention(q, k, v)
    assert float(jnp.abs(out - 3.0).max()) < 1e-5


def test_compiled_train_step_dp_matches_single_device():
    """DP over the mesh must produce the same math as one device (sync DP is
    semantically a larger batch — the reference's dist_sync contract)."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        net(nd.ones((1, 8)))
        return net

    x = nd.array(np.random.RandomState(0).rand(8, 8).astype(np.float32))
    y = nd.array(np.random.RandomState(1).randint(0, 4, (8,)), dtype="float32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    results = []
    for mesh in (None, _mesh(dp=8)):
        net = build()
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        step = CompiledTrainStep(net, loss_fn, opt, mesh=mesh)
        losses = [float(step.step(x, y).asscalar()) for _ in range(3)]
        step.sync_to_net()
        w = {k: p.data().asnumpy() for k, p in net.collect_params().items()}
        results.append((losses, w))
    (l1, w1), (l2, w2) = results
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    # auto-generated name prefixes differ between builds: align by
    # INSERTION order (numeric name suffixes sort inconsistently across
    # digit boundaries, e.g. dense9 vs dense10)
    for (_, a), (_, b) in zip(list(w1.items()), list(w2.items())):
        # cross-device psum reassociates the batch sum: bitwise inequality
        # is expected, agreement to f32 reduction tolerance is the contract
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


def test_compiled_train_step_learns():
    from tpu_mx.parallel import CompiledTrainStep
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 4)))
    X = np.random.RandomState(0).rand(32, 4).astype(np.float32)
    Y = (X.sum(1) > 2).astype(np.float32)
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("adam", learning_rate=0.05),
                             mesh=_mesh(dp=8))
    losses = [float(step.step(nd.array(X), nd.array(Y)).asscalar())
              for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5


def test_tp_sharded_dense_matches():
    """Megatron-style TP on a Dense stack must match unsharded output."""
    from tpu_mx.parallel import CompiledTrainStep, P

    def build():
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        net(nd.ones((1, 16)))
        return net

    x = nd.array(np.random.RandomState(2).rand(8, 16).astype(np.float32))
    y = nd.array(np.zeros(8), dtype="float32")
    rules = [(r"hybridsequential.*dense.*0_weight$", P("tp", None)),
             (r"hybridsequential.*dense.*0_bias$", P("tp"))]
    outs = []
    for mesh, r in ((None, None), (_mesh(dp=2, tp=4), rules)):
        net = build()
        step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 mx.optimizer.create("sgd", learning_rate=0.1),
                                 mesh=mesh, rules=r)
        losses = [float(step.step(x, y).asscalar()) for _ in range(2)]
        outs.append(losses)
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5)


def test_graft_dryrun_8():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_kvstore_push_pull_math():
    """Reference nightly-kvstore pattern: known values in, exact aggregates
    out (REF:tests/nightly/dist_sync_kvstore.py)."""
    kv = mx.kv.create("device")
    kv.init(3, nd.ones((2, 2)))
    kv.push(3, [nd.ones((2, 2)) * i for i in range(4)])  # sum = 6
    out = nd.zeros((2, 2))
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 6.0))
    # pull without intervening push returns stored value
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 6.0))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_padding_mask_matches_dense(causal):
    """valid_length rides the rotating K index: the ring result on ragged
    batches must equal dense masked attention, fwd AND bwd (VERDICT r2
    missing#2/ask#4)."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import ring_attention

    mesh = _mesh(sp=8)
    B, H, T, D = 3, 2, 32, 4
    rng = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    valid = jnp.asarray([20, 32, 1], jnp.int32)  # mid-shard, full, minimal

    def dense_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        km = jnp.arange(T)[None, None, None, :] < valid[:, None, None, None]
        s = jnp.where(km, s, -jnp.inf)
        if causal:
            cm = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(cm[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.sin(jnp.einsum("bhqk,bhkd->bhqd", p, v)))

    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=causal, valid_length=valid)
        return jnp.sum(jnp.sin(o))

    assert abs(float(ring_loss(q, k, v)) - float(dense_loss(q, k, v))) < 1e-4
    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5, err_msg=f"d{name}")
    # keys beyond valid_length contribute nothing: exact zero dk
    dk = np.asarray(g[1])
    assert np.all(dk[0, :, 20:] == 0.0) and np.all(dk[2, :, 1:] == 0.0)


def test_attention_dropout_train_vs_eval():
    """SelfAttention's attention-prob dropout must perturb outputs under
    record() and vanish in eval (VERDICT r2 weak#3/ask#5)."""
    from tpu_mx import autograd
    from tpu_mx.models.bert import SelfAttention

    attn = SelfAttention(units=16, num_heads=2, dropout=0.5)
    attn.initialize()
    x = nd.array(np.random.RandomState(0).rand(2, 8, 16).astype(np.float32))
    eval_out = attn(x).asnumpy()
    eval_out2 = attn(x).asnumpy()
    np.testing.assert_allclose(eval_out, eval_out2)  # eval: deterministic
    with autograd.record():
        train_out = attn(x).asnumpy()
        train_out2 = attn(x).asnumpy()
    assert np.abs(train_out - eval_out).max() > 1e-4   # train != eval
    assert np.abs(train_out - train_out2).max() > 1e-4  # fresh keys per call


def test_attention_dropout_zero_is_noop():
    from tpu_mx import autograd
    from tpu_mx.models.bert import SelfAttention

    attn = SelfAttention(units=16, num_heads=2, dropout=0.0)
    attn.initialize()
    x = nd.array(np.random.RandomState(1).rand(2, 8, 16).astype(np.float32))
    eval_out = attn(x).asnumpy()
    with autograd.record():
        train_out = attn(x).asnumpy()
    np.testing.assert_allclose(train_out, eval_out, rtol=1e-6)


def test_bert_valid_length_masks_padding():
    """BERT logits at non-padded positions must be invariant to token
    content beyond valid_length when it is passed, and must differ when it
    is not (proves the mask reaches every layer's attention)."""
    from tpu_mx.models.bert import BERTModel, bert_base_config

    cfg = bert_base_config(vocab_size=50, max_len=16)
    cfg.update(num_layers=2, units=16, hidden_size=32, num_heads=2,
               dropout=0.0)
    net = BERTModel(cfg)
    net.initialize()
    rng = np.random.RandomState(2)
    tokens = rng.randint(4, 50, (2, 16)).astype(np.int32)
    types = np.zeros((2, 16), np.int32)
    valid = nd.array(np.array([10, 16], np.int32))
    tokens2 = tokens.copy()
    tokens2[0, 10:] = (tokens2[0, 10:] + 7) % 46 + 4  # scramble padding

    out1 = net(nd.array(tokens), nd.array(types), valid).asnumpy()
    out2 = net(nd.array(tokens2), nd.array(types), valid).asnumpy()
    # row 0, positions < 10 see identical context -> identical logits
    np.testing.assert_allclose(out1[0, :10], out2[0, :10], rtol=1e-5,
                               atol=1e-5)
    # row 1 untouched
    np.testing.assert_allclose(out1[1], out2[1], rtol=1e-5, atol=1e-5)
    # without the mask, scrambled padding leaks into position 0..9
    u1 = net(nd.array(tokens), nd.array(types)).asnumpy()
    u2 = net(nd.array(tokens2), nd.array(types)).asnumpy()
    assert np.abs(u1[0, :10] - u2[0, :10]).max() > 1e-4


def _mlp_stage(params, x):
    import jax.numpy as jnp
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _mk_stage_params(rng, d, hidden):
    import jax.numpy as jnp
    return {"w1": jnp.asarray(rng.randn(d, hidden) * 0.3, jnp.float32),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "w2": jnp.asarray(rng.randn(hidden, d) * 0.3, jnp.float32),
            "b2": jnp.zeros((d,), jnp.float32)}


@pytest.mark.parametrize("axes,micro", [({"dp": 4, "pp": 2}, 4),
                                        ({"dp": 2, "pp": 4}, 4),
                                        ({"dp": 4, "pp": 2}, 8)])
def test_pipeline_matches_sequential(axes, micro):
    """GPipe microbatch schedule over shard_map+ppermute must equal plain
    sequential stage application, forward AND gradients (VERDICT r2 ask#8)."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import P, pipeline_apply, stack_stage_params

    mesh = _mesh(**axes)
    S = axes["pp"]
    rng = np.random.RandomState(0)
    stages = [_mk_stage_params(rng, 8, 16) for _ in range(S)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.randn(32, 8), jnp.float32)
    dspec = P("dp") if "dp" in axes else None

    def piped_loss(stacked, x):
        y = pipeline_apply(_mlp_stage, stacked, x, mesh,
                           num_microbatches=micro, data_spec=dspec)
        return jnp.sum(jnp.sin(y))

    def seq_loss(stacked, x):
        y = x
        for s in range(S):
            p = jax.tree_util.tree_map(lambda a: a[s], stacked)
            y = _mlp_stage(p, y)
        return jnp.sum(jnp.sin(y))

    assert abs(float(piped_loss(stacked, x)) -
               float(seq_loss(stacked, x))) < 1e-4
    g1 = jax.grad(piped_loss)(stacked, x)
    g2 = jax.grad(seq_loss)(stacked, x)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_pipeline_trains():
    """A dp×pp-pipelined regression MLP must learn under jit + grad."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import P, pipeline_apply, stack_stage_params

    mesh = _mesh(dp=4, pp=2)
    rng = np.random.RandomState(1)
    stages = [_mk_stage_params(rng, 4, 8) for _ in range(2)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.randn(16, 4), jnp.float32)
    t = jnp.asarray(np.asarray(x) @ (rng.randn(4, 4) * 0.3), jnp.float32)

    @jax.jit
    def step(stacked, x, t):
        def loss(stacked):
            y = pipeline_apply(_mlp_stage, stacked, x, mesh,
                               num_microbatches=4, data_spec=P("dp"))
            return jnp.mean((y - t) ** 2)
        l, g = jax.value_and_grad(loss)(stacked)
        return l, jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg,
                                         stacked, g)

    losses = []
    for _ in range(60):
        l, stacked = step(stacked, x, t)
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0], losses


@pytest.mark.parametrize("ctype", ["2bit", "int8", "fp8"])
def test_compressed_instep_allreduce(ctype):
    """Quantized in-step gradient psum (SURVEY §2.3 stretch / VERDICT r2
    ask#7): with error feedback the compressed run must track the
    uncompressed run within quantization tolerance and still learn."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        net(nd.ones((1, 8)))
        return net

    x = nd.array(np.random.RandomState(2).rand(16, 8).astype(np.float32))
    y = nd.array(np.random.RandomState(3).randint(0, 4, (16,)),
                 dtype="float32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = _mesh(dp=8)

    def run(compression):
        net = build()
        opt = mx.optimizer.create("sgd", learning_rate=0.1)
        step = CompiledTrainStep(net, loss_fn, opt, mesh=mesh,
                                 gradient_compression=compression)
        return [float(step.step(x, y).asscalar()) for _ in range(15)]

    ref = run(None)
    comp = run({"type": ctype, "threshold": 0.05})
    assert comp[-1] < comp[0], "compressed run did not learn"
    # error feedback keeps the trajectories close (not bitwise equal)
    assert abs(comp[-1] - ref[-1]) < 0.35 * ref[0], (ref[-1], comp[-1])


def test_compression_rejects_bad_configs():
    from jax.sharding import PartitionSpec as P
    from tpu_mx.parallel import CompiledTrainStep

    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=8))
    net.initialize()
    net(nd.ones((1, 8)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd")
    with pytest.raises(ValueError, match="mesh"):
        CompiledTrainStep(net, loss_fn, opt, mesh=None,
                          gradient_compression={"type": "2bit"})
    with pytest.raises(ValueError, match="pure-DP"):
        CompiledTrainStep(net, loss_fn, opt, mesh=_mesh(dp=4, tp=2),
                          rules=[("weight", P("tp", None))],
                          gradient_compression={"type": "2bit"})
    with pytest.raises(ValueError, match="type"):
        CompiledTrainStep(net, loss_fn, opt, mesh=_mesh(dp=8),
                          gradient_compression={"type": "4bit"})
    with pytest.raises(ValueError, match="'dp' only"):
        CompiledTrainStep(net, loss_fn, opt, mesh=_mesh(dp=4, sp=2),
                          data_specs=(P(("dp", "sp")), P(("dp", "sp"))),
                          gradient_compression={"type": "int8"})


def test_bert_masked_positions_match_full_logits():
    """masked_positions must equal gathering the full-T logits at those
    positions (the reference pretraining head contract) and train through
    CompiledTrainStep with a None valid_length passthrough."""
    from tpu_mx.models.bert import BERTModel, bert_base_config
    from tpu_mx.parallel import CompiledTrainStep

    cfg = bert_base_config(vocab_size=60, max_len=12)
    cfg.update(num_layers=1, units=16, hidden_size=32, num_heads=2,
               dropout=0.0)
    net = BERTModel(cfg)
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = rng.randint(4, 60, (2, 12)).astype(np.int32)
    types = np.zeros((2, 12), np.int32)
    pos = np.stack([rng.choice(12, 3, replace=False)
                    for _ in range(2)]).astype(np.int32)

    full = net(nd.array(tokens), nd.array(types)).asnumpy()
    masked = net(nd.array(tokens), nd.array(types), None,
                 nd.array(pos)).asnumpy()
    ref = np.take_along_axis(full, pos[..., None], axis=1)
    np.testing.assert_allclose(masked, ref, rtol=1e-4, atol=1e-5)

    class L(gluon.loss.Loss):
        def __init__(self, **kw):
            super().__init__(weight=None, batch_axis=0, **kw)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, logits, labels):
            v = logits.shape[-1]
            return F.mean(self._ce(F.reshape(logits, shape=(-1, v)),
                                   F.reshape(labels, shape=(-1,))))

    labels = np.take_along_axis(tokens, pos, axis=1)
    opt = mx.optimizer.create("adam", learning_rate=3e-3)
    step = CompiledTrainStep(net, L(), opt)
    losses = [float(step.step(nd.array(tokens), nd.array(types), None,
                              nd.array(pos), nd.array(labels)).asscalar())
              for _ in range(8)]
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_bias_matches_dense(causal):
    """Additive attention bias (ALiBi/relative-position style) must ride
    the ring: per-step column slices of the global bias reproduce dense
    biased attention, fwd AND bwd (VERDICT r2 weak#4)."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import ring_attention

    mesh = _mesh(sp=8)
    B, H, T, D = 2, 2, 32, 4
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    # ALiBi-style distance bias, distinct per head
    dist = jnp.abs(jnp.arange(T)[:, None] - jnp.arange(T)[None, :])
    bias = -jnp.stack([0.1 * dist, 0.03 * dist])[None].astype(jnp.float32)
    bias = jnp.broadcast_to(bias, (B, H, T, T))

    def dense_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias
        if causal:
            cm = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(cm[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.sin(jnp.einsum("bhqk,bhkd->bhqd", p, v)))

    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=causal, bias=bias)
        return jnp.sum(jnp.sin(o))

    assert abs(float(ring_loss(q, k, v)) - float(dense_loss(q, k, v))) < 1e-4
    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5, err_msg=f"d{name}")


def test_attention_bias_broadcast_shapes():
    """(1, 1, T, T) bias broadcasts over batch and heads on both paths."""
    import jax.numpy as jnp
    from tpu_mx.parallel import local_flash_attention, ring_attention

    mesh = _mesh(sp=8)
    B, H, T, D = 2, 3, 32, 4
    rng = np.random.RandomState(6)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    bias = jnp.asarray(rng.rand(1, 1, T, T).astype(np.float32))
    ref = local_flash_attention(q, k, v, bias=bias)
    out = ring_attention(q, k, v, mesh, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_grad_accumulation_matches_big_batch():
    """K microbatch step()s must produce exactly the update of one step on
    the concatenated K-times batch (mean-of-means == global mean for equal
    microbatches) — the reference grad_req='add' + delayed Trainer.step
    contract."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(9)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="tanh"), nn.Dense(3))
        net.initialize()
        net(nd.ones((1, 6)))
        return net

    rng = np.random.RandomState(4)
    micro = [(rng.rand(4, 6).astype(np.float32),
              rng.randint(0, 3, (4,)).astype(np.float32))
             for _ in range(3)]
    big_x = np.concatenate([m[0] for m in micro])
    big_y = np.concatenate([m[1] for m in micro])
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # K=3 accumulation
    net_a = build()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    step_a = CompiledTrainStep(net_a, loss_fn, opt, accum_steps=3)
    for x, y in micro:
        step_a.step(nd.array(x), nd.array(y))
    assert step_a._t == 1  # one applied update
    step_a.sync_to_net()
    wa = {k: p.data().asnumpy() for k, p in net_a.collect_params().items()}

    # one big-batch step
    net_b = build()
    opt_b = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    step_b = CompiledTrainStep(net_b, loss_fn, opt_b)
    step_b.step(nd.array(big_x), nd.array(big_y))
    step_b.sync_to_net()
    wb = {k: p.data().asnumpy() for k, p in net_b.collect_params().items()}

    for (_, a), (_, b) in zip(list(wa.items()), list(wb.items())):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_grad_accumulation_learns_on_mesh():
    from tpu_mx.parallel import CompiledTrainStep

    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 4)))
    x = nd.array(np.random.RandomState(0).rand(8, 4).astype(np.float32))
    y = nd.array(np.random.RandomState(1).randint(0, 2, (8,)),
                 dtype="float32")
    opt = mx.optimizer.create("adam", learning_rate=3e-3)
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             opt, mesh=_mesh(dp=8), accum_steps=2)
    losses = [float(step.step(x, y).asscalar()) for _ in range(20)]
    assert step._t == 10
    assert losses[-1] < losses[0]
    # accum x compression is now SUPPORTED (compress-once-per-update);
    # its equivalence contract is tested in
    # test_compressed_accumulation_compress_once_per_update


def test_grad_accumulation_reset_on_load():
    """Restoring state mid-accumulation must discard in-flight microbatch
    gradients (they were computed against the discarded weights)."""
    from tpu_mx.parallel import CompiledTrainStep

    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    net(nd.ones((1, 3)))
    x = nd.array(np.random.RandomState(0).rand(4, 3).astype(np.float32))
    y = nd.array(np.array([0, 1, 2, 3], np.float32))
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             opt, accum_steps=3)
    sd = step.state_dict()
    step.step(x, y)
    step.step(x, y)  # mid-accumulation: _micro == 2
    assert step._micro == 2
    step.load_state_dict(sd)
    assert step._micro == 0
    assert all(float(np.abs(np.asarray(v)).max()) == 0.0
               for v in step._gacc.values())


def test_ulysses_attention_matches_dense():
    """Ulysses all-to-all path == dense attention, fwd, causal and padded
    (same contract as the ring tests)."""
    import jax.numpy as jnp
    from tpu_mx.parallel import local_flash_attention, ulysses_attention
    mesh = _mesh(sp=8)
    B, H, T, D = 2, 8, 32, 4  # H divisible by sp=8
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    ref = local_flash_attention(q, k, v)
    out = ulysses_attention(q, k, v, mesh)
    assert float(jnp.abs(ref - out).max()) < 1e-5
    ref_c = local_flash_attention(q, k, v, causal=True)
    out_c = ulysses_attention(q, k, v, mesh, causal=True)
    assert float(jnp.abs(ref_c - out_c).max()) < 1e-5
    vl = np.array([T, T // 2])
    ref_m = local_flash_attention(q, k, v, valid_length=vl)
    out_m = ulysses_attention(q, k, v, mesh, valid_length=vl)
    assert float(jnp.abs(ref_m - out_m).max()) < 1e-5


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_backward_matches_dense(causal):
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import ulysses_attention

    mesh = _mesh(sp=8)
    B, H, T, D = 2, 8, 32, 4
    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))

    def dense_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(jnp.sin(o))

    def uly_loss(q, k, v):
        return jnp.sum(jnp.sin(ulysses_attention(q, k, v, mesh,
                                                 causal=causal)))

    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(uly_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        assert float(jnp.abs(a - b).max()) < 1e-5


def test_ulysses_bias_and_head_constraint():
    import jax.numpy as jnp
    from tpu_mx.parallel import local_flash_attention, ulysses_attention
    mesh = _mesh(sp=8)
    B, H, T, D = 1, 8, 32, 4
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    # per-head additive bias (ALiBi-style): must slice the device's heads
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
    ref = local_flash_attention(q, k, v, bias=bias)
    out = ulysses_attention(q, k, v, mesh, bias=bias)
    assert float(jnp.abs(ref - out).max()) < 1e-4
    # H=6 not divisible by 8 -> loud error
    q6 = jnp.asarray(rng.rand(B, 6, T, D).astype(np.float32))
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q6, q6, q6, mesh)


def test_attention_sp_strategy_dispatch():
    """attention() strategy switch: ulysses taken when selected and legal,
    ring fallback when heads don't divide, counters updated."""
    import jax.numpy as jnp
    from tpu_mx.parallel import attention, set_sp_strategy
    from tpu_mx.parallel.ring_attention import dispatch_counts
    mesh = _mesh(sp=8)
    # T=64: a signature no earlier test used, so the dedup'd dispatch
    # counter must strictly increment if (and only if) ulysses runs
    B, T, D = 2, 64, 4
    rng = np.random.RandomState(1)

    def mk(h):
        return (jnp.asarray(rng.rand(B, h, T, D).astype(np.float32))
                for _ in range(3))

    prev = set_sp_strategy("ulysses")
    try:
        before = dict(dispatch_counts)
        q, k, v = mk(8)
        a1 = attention(q, k, v, mesh=mesh)
        # strict: this exact (B=2,H=8,T=32) signature is new to the
        # counter, so the ulysses path MUST have incremented it
        assert dispatch_counts["ulysses"] == before["ulysses"] + 1
        # heads=6: quiet ring fallback
        q6, k6, v6 = mk(6)
        a2 = attention(q6, k6, v6, mesh=mesh)
        assert a2.shape == (B, 6, T, D)
        # per-call override beats the module default
        a3 = attention(q, k, v, mesh=mesh, sp_strategy="ring")
        assert float(jnp.abs(a1 - a3).max()) < 1e-5
    finally:
        set_sp_strategy(prev)


def test_async_checkpoint_overlaps_training(tmp_path):
    """save_checkpoint(block=False) snapshots state at save time: training
    continues (mutating/donating the live buffers) while tensorstore
    commits; restore must bring back the SAVE-TIME state, not the later
    one."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=8, activation="relu"), nn.Dense(4))
        net.initialize()
        net(nd.ones((1, 8)))
        return net

    x = nd.array(np.random.RandomState(1).rand(8, 8).astype(np.float32))
    y = nd.array(np.array([0, 1, 2, 3, 0, 1, 2, 3], dtype=np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def make(net):
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        return CompiledTrainStep(net, loss_fn, opt, mesh=_mesh(dp=8))

    # reference: sync save at t=2, one more step -> loss3_ref
    step_a = make(build())
    step_a.step(x, y)
    step_a.step(x, y)
    ck_sync = str(tmp_path / "sync")
    step_a.save_checkpoint(ck_sync)
    loss3_ref = float(np.asarray(step_a.step(x, y)._data))

    # async: identical run, async save at t=2, keep training THROUGH the
    # commit window, then restore and compare
    step_b = make(build())
    step_b.step(x, y)
    step_b.step(x, y)
    ck_async = str(tmp_path / "async")
    step_b.save_checkpoint(ck_async, block=False)
    for _ in range(4):           # donates/overwrites live buffers
        step_b.step(x, y)
    step_b.wait_for_checkpoint()
    step_b.load_checkpoint(ck_async)
    assert step_b._t == 2
    loss3 = float(np.asarray(step_b.step(x, y)._data))
    assert abs(loss3 - loss3_ref) < 1e-5, (loss3, loss3_ref)


def test_compressed_accumulation_compress_once_per_update():
    """accum_steps=2 + compression == compression alone on the concatenated
    batch (BN/dropout-free net): the accumulated mean is quantized ONCE
    with the same EF state, so the applied updates must match bitwise-
    close.  Also sanity: the combined mode learns over steps."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(21)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=8, activation="tanh"), nn.Dense(4))
        net.initialize()
        net(nd.ones((1, 8)))
        return net

    mesh = _mesh(dp=8)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x1 = rng.rand(8, 8).astype(np.float32)
    x2 = rng.rand(8, 8).astype(np.float32)
    y1 = rng.randint(0, 4, (8,)).astype(np.float32)
    y2 = rng.randint(0, 4, (8,)).astype(np.float32)

    def weights(step):
        step.sync_to_net()
        return {k: p.data().asnumpy()
                for k, p in step.net.collect_params().items()}

    # A: one compressed update on the concat batch
    net_a = build()
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    step_a = CompiledTrainStep(net_a, loss_fn, opt, mesh=mesh,
                               gradient_compression={"type": "int8"})
    step_a.step(nd.array(np.concatenate([x1, x2])),
                nd.array(np.concatenate([y1, y2])))
    wa = weights(step_a)

    # B: two microbatches, accumulated, compressed once at apply
    net_b = build()
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    step_b = CompiledTrainStep(net_b, loss_fn, opt, mesh=mesh,
                               gradient_compression={"type": "int8"},
                               accum_steps=2)
    step_b.step(nd.array(x1), nd.array(y1))   # accumulate (no update)
    w_mid = weights(step_b)
    step_b.step(nd.array(x2), nd.array(y2))   # apply
    wb = weights(step_b)

    for (ka, va), (kb, vb) in zip(list(wa.items()), list(wb.items())):
        # align by insertion order (names differ across builds); the
        # per-shard partial means are mathematically identical but
        # f32-reassociated, so int8 bucket edges can flip a few values:
        # agreement to ~1e-4 is the contract, bit-equality is not
        np.testing.assert_allclose(va, vb, rtol=1e-3, atol=1e-4,
                                   err_msg=f"{ka} vs {kb}")
    # the microbatch step must NOT have moved the weights
    net_a2 = build()
    w0 = {k: p.data().asnumpy()
          for k, p in net_a2.collect_params().items()}
    for (k0, v0), (km, vm) in zip(list(w0.items()), list(w_mid.items())):
        np.testing.assert_allclose(v0, vm, rtol=1e-6, err_msg=f"{k0}")

    # learning sanity over several accumulated+compressed updates
    losses = []
    for _ in range(6):
        step_b.step(nd.array(x1), nd.array(y1))
        out = step_b.step(nd.array(x2), nd.array(y2))
        losses.append(float(np.asarray(out._data)))
    assert losses[-1] < losses[0], losses


def test_fsdp_rules_shard_params_and_match_replicated():
    """fsdp_rules: params >= min_size shard over dp (XLA gathers in the
    forward, reduce-scatters grads); training math must equal the
    replicated run, and the live buffers must actually be dp-sharded."""
    import jax
    from tpu_mx.parallel import CompiledTrainStep, fsdp_rules

    def build():
        mx.random.seed(31)
        net = nn.HybridSequential()
        net.add(nn.Dense(64, in_units=16, activation="relu"),
                nn.Dense(4, in_units=64))
        net.initialize()
        net(nd.ones((1, 16)))
        return net

    mesh = _mesh(dp=8)
    x = nd.array(np.random.RandomState(0).rand(16, 16).astype(np.float32))
    y = nd.array(np.random.RandomState(1).randint(0, 4, (16,))
                 .astype(np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    losses = {}
    for mode in ("replicated", "fsdp"):
        net = build()
        rules = None
        if mode == "fsdp":
            rules = fsdp_rules({k: p.data()
                                for k, p in net.collect_params().items()},
                               min_size=256, axis_size=8)
            assert rules, "no params sharded"
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        step = CompiledTrainStep(net, loss_fn, opt, mesh=mesh, rules=rules)
        losses[mode] = [float(np.asarray(step.step(x, y)._data))
                        for _ in range(4)]
        if mode == "fsdp":
            # every large param must live dp-sharded on device
            big = [k for k, v in step.values.items()
                   if int(np.prod(v.shape)) >= 256]
            for k in big:
                spec = step.values[k].sharding.spec
                assert any(ax == "dp" for ax in spec), (k, spec)
    np.testing.assert_allclose(losses["replicated"], losses["fsdp"],
                               rtol=2e-4, atol=1e-5)


def test_fsdp_rules_divisibility():
    """Params with no axis divisible by the mesh size stay replicated
    instead of producing invalid shardings."""
    from tpu_mx.parallel import fsdp_rules, P
    params = {"odd": np.zeros((100, 17)),     # no axis % 8 == 0
              "even": np.zeros((64, 100)),    # 64 % 8 == 0
              "tiny": np.zeros((4,))}
    rules = fsdp_rules(params, min_size=64, axis_size=8)
    names = [r[0] for r in rules]
    assert any("even" in n for n in names)
    assert not any("odd" in n or "tiny" in n for n in names)
    spec = dict((r[0], r[1]) for r in rules)[[n for n in names
                                              if "even" in n][0]]
    assert spec == P("dp", None)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_chunked_step_matches_dense(causal):
    """step_chunk < Tb exercises the inner online-softmax scan (the
    O(T/n·C) memory path): numerics must equal dense, fwd AND bwd, with
    bias + padding in the mix."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import ring_attention
    mesh = _mesh(sp=8)
    B, H, T, D = 2, 2, 64, 8
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32) * 0.1)
    vl = np.array([T, T // 2])

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias
        if causal:
            cm = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(cm[None, None], s, -jnp.inf)
        km = (jnp.arange(T)[None, None, None, :] <
              jnp.asarray(vl)[:, None, None, None])
        s = jnp.where(km, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(jnp.isnan(p), 0.0, p)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def ringf(q, k, v):
        return ring_attention(q, k, v, mesh, causal=causal,
                              valid_length=vl, bias=bias,
                              step_chunk=4)  # Tb=8 -> 2 inner chunks

    out = ringf(q, k, v)
    ref = dense(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    g1 = jax.grad(lambda *a: jnp.sum(jnp.sin(ringf(*a))),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(jnp.sin(dense(*a))),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_ring_attention_long_seq_chunked():
    """T=2048 over sp=8 with 128-sized inner chunks (Tb=256 -> 2 chunks):
    the realistic long-context shape class, forward vs dense."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.parallel import ring_attention
    mesh = _mesh(sp=8)
    B, H, T, D = 1, 2, 2048, 16
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.rand(B, H, T, D).astype(np.float32))
               for _ in range(3))
    out = ring_attention(q, k, v, mesh, causal=True, step_chunk=128)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    cm = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
    s = jnp.where(cm[None, None], s, -jnp.inf)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_fused_flat_update_matches_per_param(monkeypatch):
    """The fused flat-concat update (mesh=None + elementwise optimizer)
    must produce bit-identical training to the per-param path, including
    bf16 params with f32 masters (multi_precision) and momentum state."""
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(11)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(16,
                activation="relu"), nn.Dense(4))
        net.initialize()
        net(nd.ones((1, 8)))
        net.cast("bfloat16")
        return net

    x = nd.cast(nd.array(np.random.RandomState(0).rand(8, 8)
                         .astype(np.float32)), "bfloat16")
    y = nd.array(np.random.RandomState(1).randint(0, 4, (8,)),
                 dtype="float32")
    results = []
    for fused in ("1", "0"):
        monkeypatch.setenv("TPUMX_FUSED_UPDATE", fused)
        net = build()
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                                  wd=1e-4, multi_precision=True)
        step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 opt, mesh=None)
        losses = [float(step.step(x, y).asscalar()) for _ in range(4)]
        if fused == "1":
            # the fused path must actually engage (>1 param per group)
            assert step._fuse_groups and \
                sum(len(g) for g in step._fuse_groups) >= 2, \
                step._fuse_groups
        step.sync_to_net()
        w = {k: p.data().asnumpy().astype(np.float32)
             for k, p in net.collect_params().items()}
        m = {k: np.asarray(v) for k, v in step.masters.items()}
        results.append((losses, w, m))
    (l1, w1, m1), (l2, w2, m2) = results
    np.testing.assert_array_equal(l1, l2)
    # auto-generated name prefixes differ between builds: align by
    # insertion order (same construction order => same param order)
    for (ka, a), (kb, b) in zip(list(w1.items()), list(w2.items())):
        np.testing.assert_array_equal(a, b, err_msg=f"{ka} vs {kb}")
    for a, b in zip(list(m1.values()), list(m2.values())):
        np.testing.assert_array_equal(a, b)


def test_fused_update_groups_respect_mults():
    """Params with distinct lr_mult/wd_mult must not be folded into one
    flat group (their update programs differ)."""
    from tpu_mx.parallel import CompiledTrainStep
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    net(nd.ones((1, 6)))
    params = net.collect_params()
    first = list(params.keys())[0]
    params[first].lr_mult = 0.5
    # build the per-param oracle net FIRST and copy weights before any
    # step runs: donation deletes the source net's live buffers
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net2.initialize()
    net2(nd.ones((1, 6)))
    p2 = net2.collect_params()
    for (k1, v1), (k2, v2) in zip(list(params.items()), list(p2.items())):
        v2.set_data(nd.array(v1.data().asnumpy()))
        v2.lr_mult = v1.lr_mult
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    import os
    os.environ["TPUMX_FUSED_UPDATE"] = "1"   # opt-in path under test
    try:
        step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 opt, mesh=None)
        x = nd.array(np.random.RandomState(3).rand(4, 6)
                     .astype(np.float32))
        y = nd.array(np.zeros(4), dtype="float32")
        l0 = float(step.step(x, y).asscalar())
    finally:
        os.environ.pop("TPUMX_FUSED_UPDATE", None)
    try:
        step2 = CompiledTrainStep(net2,
                                  gluon.loss.SoftmaxCrossEntropyLoss(),
                                  mx.optimizer.create(
                                      "sgd", learning_rate=0.1,
                                      momentum=0.9), mesh=None)
        l1 = float(step2.step(x, y).asscalar())
    finally:
        os.environ.pop("TPUMX_FUSED_UPDATE", None)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    step.sync_to_net()
    step2.sync_to_net()
    for (ka, a), (kb, b) in zip(list(params.items()), list(p2.items())):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy(),
                                      err_msg=f"{ka} vs {kb}")
