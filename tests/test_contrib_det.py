"""Detection contrib ops vs plain-numpy oracles (reference test pattern:
tests/python/unittest/test_contrib_operator.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd


def np_iou(a, b):
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    aa = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    ab = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    union = aa[:, None] + ab[None, :] - inter
    return np.where(union > 0, inter / union, 0)


def test_box_iou():
    rng = np.random.RandomState(0)
    a = np.sort(rng.rand(6, 2, 2), axis=2).reshape(6, 4)[:, [0, 2, 1, 3]]
    b = np.sort(rng.rand(4, 2, 2), axis=2).reshape(4, 4)[:, [0, 2, 1, 3]]
    got = mx.nd.contrib.box_iou(mx.nd.array(a), mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(got, np_iou(a, b), rtol=1e-5, atol=1e-6)


def test_multibox_prior():
    x = mx.nd.zeros((1, 3, 4, 6))
    anchors = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25),
                                          ratios=(1, 2)).asnumpy()
    # K = S + R - 1 = 3 anchors per cell
    assert anchors.shape == (1, 4 * 6 * 3, 4)
    # first cell center = (0.5/6, 0.5/4); first anchor size .5 ratio 1
    cx, cy = 0.5 / 6, 0.5 / 4
    np.testing.assert_allclose(anchors[0, 0],
                               [cx - 0.25, cy - 0.25, cx + 0.25, cy + 0.25],
                               rtol=1e-5, atol=1e-6)
    # ratio-2 anchor: w = s*sqrt(2), h = s/sqrt(2)
    w, h = 0.5 * np.sqrt(2), 0.5 / np.sqrt(2)
    np.testing.assert_allclose(anchors[0, 2],
                               [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                               rtol=1e-5, atol=1e-6)
    clipped = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.9,), clip=True).asnumpy()
    assert clipped.min() >= 0 and clipped.max() <= 1


def test_box_nms():
    boxes = np.array([
        [0, 0.9, 0.1, 0.1, 0.5, 0.5],
        [0, 0.8, 0.12, 0.12, 0.52, 0.52],   # overlaps first -> suppressed
        [0, 0.7, 0.6, 0.6, 0.9, 0.9],       # far -> kept
        [1, 0.6, 0.1, 0.1, 0.5, 0.5],       # other class -> kept
        [0, 0.0, 0, 0, 0, 0],               # below valid_thresh
    ], dtype="float32")
    out = mx.nd.contrib.box_nms(mx.nd.array(boxes), overlap_thresh=0.5,
                                valid_thresh=0.01, id_index=0,
                                coord_start=2, score_index=1).asnumpy()
    kept_scores = sorted(out[out[:, 1] > 0][:, 1].tolist())
    np.testing.assert_allclose(kept_scores, [0.6, 0.7, 0.9], rtol=1e-6)
    # force_suppress removes the class distinction
    out2 = mx.nd.contrib.box_nms(mx.nd.array(boxes), overlap_thresh=0.5,
                                 valid_thresh=0.01, id_index=0,
                                 coord_start=2, score_index=1,
                                 force_suppress=True).asnumpy()
    kept2 = sorted(out2[out2[:, 1] > 0][:, 1].tolist())
    np.testing.assert_allclose(kept2, [0.7, 0.9], rtol=1e-6)


def test_box_nms_topk_bounds_output():
    rng = np.random.RandomState(1)
    # 6 far-apart valid boxes, no overlaps
    boxes = np.zeros((6, 6), "float32")
    for i in range(6):
        boxes[i] = [0, 0.9 - 0.1 * i, 0.15 * i, 0.0, 0.15 * i + 0.1, 0.1]
    out = mx.nd.contrib.box_nms(mx.nd.array(boxes), overlap_thresh=0.5,
                                valid_thresh=0.01, id_index=0, coord_start=2,
                                score_index=1, topk=2).asnumpy()
    assert (out[:, 1] > 0).sum() == 2       # only top-2 survive


def test_box_nms_format_conversion():
    # center-format input, corner output
    row = np.array([[0, 0.9, 0.5, 0.5, 0.2, 0.2]], "float32")
    out = mx.nd.contrib.box_nms(mx.nd.array(row), valid_thresh=0.01,
                                id_index=0, coord_start=2, score_index=1,
                                in_format="center",
                                out_format="corner").asnumpy()
    np.testing.assert_allclose(out[0, 2:], [0.4, 0.4, 0.6, 0.6], atol=1e-6)
    # corner input, center output
    row2 = np.array([[0, 0.9, 0.4, 0.4, 0.6, 0.6]], "float32")
    out2 = mx.nd.contrib.box_nms(mx.nd.array(row2), valid_thresh=0.01,
                                 id_index=0, coord_start=2, score_index=1,
                                 in_format="corner",
                                 out_format="center").asnumpy()
    np.testing.assert_allclose(out2[0, 2:], [0.5, 0.5, 0.2, 0.2], atol=1e-6)


def test_multibox_target():
    anchors = np.array([[0.1, 0.1, 0.3, 0.3],
                        [0.5, 0.5, 0.9, 0.9],
                        [0.0, 0.0, 0.05, 0.05]], "float32")[None]
    # one gt matching anchor 0 well, padded row
    label = np.array([[[1, 0.1, 0.1, 0.3, 0.3],
                       [-1, -1, -1, -1, -1]]], "float32")
    cls_pred = np.zeros((1, 3, 3), "float32")
    loc_t, loc_m, cls_t = mx.sym.contrib.MultiBoxTarget(
        mx.sym.var("anc"), mx.sym.var("lab"), mx.sym.var("pred")
    ).eval(anc=mx.nd.array(anchors), lab=mx.nd.array(label),
           pred=mx.nd.array(cls_pred)) if False else \
        mx.nd.contrib.MultiBoxTarget(mx.nd.array(anchors),
                                     mx.nd.array(label),
                                     mx.nd.array(cls_pred))
    cls_t = cls_t.asnumpy()
    loc_m = loc_m.asnumpy()
    loc_t = loc_t.asnumpy()
    assert cls_t.shape == (1, 3)
    assert cls_t[0, 0] == 2.0          # class 1 -> target 1+1
    assert cls_t[0, 1] == 0.0          # background
    assert loc_m.shape == (1, 12)
    np.testing.assert_allclose(loc_m[0, :4], 1.0)   # anchor 0 matched
    np.testing.assert_allclose(loc_m[0, 4:], 0.0)
    # perfect overlap -> zero offsets
    np.testing.assert_allclose(loc_t[0, :4], 0.0, atol=1e-5)


def test_multibox_target_negative_mining():
    rng = np.random.RandomState(0)
    A = 20
    anchors = np.sort(rng.rand(A, 2, 2), axis=1).transpose(0, 2, 1)\
        .reshape(A, 4)[None].astype("float32")
    anchors = np.concatenate([np.array([[[0.1, 0.1, 0.4, 0.4]]],
                                       "float32"), anchors], axis=1)
    label = np.array([[[0, 0.1, 0.1, 0.4, 0.4]]], "float32")
    cls_pred = rng.rand(1, 2, A + 1).astype("float32")
    _, _, cls_t = mx.nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred),
        negative_mining_ratio=3.0, negative_mining_thresh=0.5)
    cls_t = cls_t.asnumpy()[0]
    n_pos = (cls_t > 0).sum()
    n_neg = (cls_t == 0).sum()
    n_ign = (cls_t == -1).sum()
    assert n_pos >= 1
    assert n_neg <= 3 * n_pos
    assert n_ign > 0


def test_multibox_detection_roundtrip():
    """Encode with MultiBoxTarget then decode with MultiBoxDetection: the
    decoded box must reproduce the ground truth."""
    anchors = np.array([[0.15, 0.15, 0.35, 0.45],
                        [0.5, 0.5, 0.9, 0.9]], "float32")[None]
    gt = np.array([[[0, 0.1, 0.2, 0.4, 0.4]]], "float32")
    cls_pred = np.zeros((1, 2, 2), "float32")
    loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(gt), mx.nd.array(cls_pred),
        overlap_threshold=0.3)
    assert cls_t.asnumpy()[0, 0] == 1.0
    # build cls_prob consistent with the match
    cls_prob = np.array([[[0.1, 0.9], [0.9, 0.1]]], "float32")  # (B,C+1,A)
    out = mx.nd.contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), loc_t, mx.nd.array(anchors),
        threshold=0.5, clip=False).asnumpy()
    det = out[0, 0]
    assert det[0] == 0.0               # class id 0
    np.testing.assert_allclose(det[1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(det[2:], [0.1, 0.2, 0.4, 0.4], atol=1e-5)


def test_multibox_symbolic():
    anc = mx.sym.var("anchor")
    lab = mx.sym.var("label")
    pred = mx.sym.var("cls_pred")
    tgt = mx.sym.contrib.MultiBoxTarget(anc, lab, pred, name="target")
    assert len(tgt.list_outputs()) == 3
    ex = tgt.simple_bind(mx.cpu(), anchor=(1, 3, 4), label=(1, 2, 5),
                         cls_pred=(1, 3, 3))
    ex.arg_dict["label"][:] = -np.ones((1, 2, 5), "float32")
    outs = ex.forward()
    assert outs[2].shape == (1, 3)


def test_deformable_conv_zero_offset_equals_conv():
    """With all-zero offsets DCN must reproduce the plain convolution
    (REF:contrib/deformable_convolution.cc identity property)."""
    from tpu_mx.ndarray import contrib, ops
    rng = np.random.RandomState(0)
    N, C, H, W, Cout, K = 2, 4, 8, 8, 6, 3
    x = nd.array(rng.rand(N, C, H, W).astype(np.float32))
    w = nd.array(rng.rand(Cout, C, K, K).astype(np.float32) * 0.2)
    b = nd.array(rng.rand(Cout).astype(np.float32))
    off = nd.zeros((N, 2 * K * K, H, W))
    out = contrib.DeformableConvolution(
        x, off, w, b, kernel=(K, K), pad=(1, 1), num_filter=Cout)
    ref = ops.Convolution(x, w, b, kernel=(K, K), pad=(1, 1),
                          num_filter=Cout)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_integer_shift():
    """A constant integer offset samples the shifted input: interior
    outputs must equal the plain conv of the rolled feature map."""
    from tpu_mx.ndarray import contrib, ops
    rng = np.random.RandomState(1)
    N, C, H, W, Cout, K = 1, 2, 10, 10, 3, 3
    x = rng.rand(N, C, H, W).astype(np.float32)
    w = nd.array(rng.rand(Cout, C, K, K).astype(np.float32))
    off = np.zeros((N, 2 * K * K, H - 2, W - 2), np.float32)
    off[:, 0::2] = 1.0  # dy = +1 for every tap
    out = contrib.DeformableConvolution(
        nd.array(x), nd.array(off), w, kernel=(K, K), num_filter=Cout,
        no_bias=True)
    shifted = np.roll(x, -1, axis=2)  # sampling y+1 == shifting map up
    ref = ops.Convolution(nd.array(shifted), w, kernel=(K, K),
                          num_filter=Cout, no_bias=True)
    # rows whose +1-shifted taps stay in range: all but the last output row
    np.testing.assert_allclose(out.asnumpy()[:, :, :-1],
                               ref.asnumpy()[:, :, :-1], rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_grad_flows_to_offsets():
    from tpu_mx import autograd
    from tpu_mx.ndarray import contrib
    rng = np.random.RandomState(2)
    x = nd.array(rng.rand(1, 2, 6, 6).astype(np.float32))
    w = nd.array(rng.rand(2, 2, 3, 3).astype(np.float32))
    off = nd.array(rng.rand(1, 18, 4, 4).astype(np.float32) * 0.3)
    off.attach_grad()
    x.attach_grad()
    with autograd.record():
        y = contrib.DeformableConvolution(x, off, w, kernel=(3, 3),
                                          num_filter=2, no_bias=True)
        loss = (y * y).sum()
    loss.backward()
    assert np.isfinite(off.grad.asnumpy()).all()
    assert np.abs(off.grad.asnumpy()).max() > 0
    assert np.abs(x.grad.asnumpy()).max() > 0


def test_count_sketch():
    from tpu_mx.ndarray import contrib
    rng = np.random.RandomState(3)
    x = rng.rand(2, 5).astype(np.float32)
    h = np.array([0, 2, 2, 1, 0], np.int32)   # collisions accumulate
    s = np.array([1, -1, 1, 1, -1], np.float32)
    out = contrib.count_sketch(nd.array(x), nd.array(h), nd.array(s),
                               out_dim=3).asnumpy()
    ref = np.zeros((2, 3), np.float32)
    for i in range(5):
        ref[:, h[i]] += s[i] * x[:, i]
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_boolean_mask():
    from tpu_mx import gluon
    from tpu_mx.ndarray import contrib
    x = nd.array(np.arange(12).reshape(4, 3).astype(np.float32))
    idx = nd.array(np.array([1, 0, 1, 0], np.float32))
    out = contrib.boolean_mask(x, idx)
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(12).reshape(4, 3)[[0, 2]])

    # inside a functional trace: clean refusal, not an XLA crash
    class Bad(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return contrib.boolean_mask(x, x[:, 0] > 0)

    net = Bad()
    net.initialize()
    net.hybridize()
    import pytest as _pytest
    from tpu_mx.base import MXNetError
    with _pytest.raises((MXNetError, Exception), match="boolean_mask|static"):
        net(x)
