"""Fleet observability plane (tpu_mx/parallel/fleet_obs.py, ISSUE 18):
per-rank snapshot shipping, the cross-worker merge and its exactness
invariant (fleet counter == sum of per-rank counters), histogram
bucket-merge accuracy, stale-generation exclusion, missing-rank gap
reporting, cross-rank straggler attribution, the ``slow_worker`` chaos
knob, and the jax-less report tools over the fleet black box
(docs/observability.md "Fleet observability")."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_mx import telemetry, tracing
from tpu_mx.contrib import chaos
from tpu_mx.parallel import fleet as fleet_mod
from tpu_mx.parallel import fleet_obs
from tpu_mx.parallel.fleet import Fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registries():
    telemetry.reset()
    tracing.reset()
    yield
    telemetry.reset()
    tracing.reset()


def _worker(root, rank, lease=5.0):
    """An admitted worker handle on the store (registry is process-
    global, so callers reset between 'ranks')."""
    w = Fleet(root, member=rank, lease=lease)
    w.join()
    w.await_admission(timeout=10)
    return w


def _counter_rec(name, value, rank, generation, ts=1000.0, **labels):
    rec = {"name": name, "type": "counter", "value": value, "ts": ts,
           "rank": rank, "fleet_generation": generation}
    if labels:
        rec["labels"] = labels
    return rec


def _phase_events(rank, generation, steps, slow=0.0):
    """Synthetic train_step.phase events for one rank (data_wait carries
    the injected slowness)."""
    out = []
    for s in range(steps):
        for ph, sec in (("data_wait", 0.01 + slow), ("dispatch", 0.005),
                        ("loss_readback", 0.002)):
            out.append({"event": "train_step.phase", "ts": 1000.0 + s,
                        "epoch": 0, "step": s, "generation": 0,
                        "rank": rank, "fleet_generation": generation,
                        "data": {"phase": ph, "seconds": sec}})
    return out


# ---------------------------------------------------------------------------
# identity stamping (fleet.py -> telemetry/tracing)
# ---------------------------------------------------------------------------
def test_adopt_stamps_fleet_identity(tmp_path):
    """Adopting a membership epoch stamps rank + generation onto every
    subsequent telemetry record and trace event — the fields the merge
    keys stale exclusion and step correlation on."""
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=5.0)
    ctl.advance(world=[3], reason="launch")
    w = _worker(root, 3)
    assert telemetry.fleet_identity() == (3, 1)
    ctx = tracing.get_context()
    assert ctx["rank"] == 3 and ctx["fleet_generation"] == 1
    telemetry.counter("train_step.steps").inc()
    (rec,) = [r for r in telemetry.snapshot()
              if r["name"] == "train_step.steps"]
    assert rec["rank"] == 3 and rec["fleet_generation"] == 1
    tracing.emit("train_step.phase", phase="data_wait", seconds=0.1)
    ev = tracing.snapshot(last=1)[0]
    assert ev["rank"] == 3 and ev["fleet_generation"] == 1
    w.leave()


# ---------------------------------------------------------------------------
# the merge core and its exactness invariant
# ---------------------------------------------------------------------------
def test_counter_sum_identity_under_concurrent_shipping(tmp_path):
    """The invariant under fire: a worker ships rolling snapshots while
    its counters move, a second rank's stream sits on disk, and a
    concurrent aggregator polls throughout — EVERY poll must see merged
    counters exactly equal to their per-rank sums, and every shipped
    line must be schema-clean (atomic whole-file rewrites mean no torn
    reads)."""
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=5.0)
    ctl.advance(world=[0, 1], reason="launch")
    # rank 1's stream: static, written by hand
    obs = os.path.join(ctl.root, fleet_obs.OBS_DIR)
    os.makedirs(obs, exist_ok=True)
    with open(os.path.join(obs, "rank-1.jsonl"), "w") as f:
        f.write(json.dumps(_counter_rec("train_step.steps", 7, 1, 1)) + "\n")
        f.write(json.dumps(_counter_rec("chaos.injections", 2, 1, 1,
                                        kind="slow_worker")) + "\n")
    w = _worker(root, 0)
    shipper = fleet_obs.ObsShipper(w, interval=0.0)
    agg = fleet_obs.FleetAggregator(ctl, interval=0.0)
    stop = threading.Event()
    failures = []

    def pound():
        steps = telemetry.counter("train_step.steps")
        while not stop.is_set():
            steps.inc()
            try:
                shipper.ship(force=True)
            except Exception as e:          # noqa: BLE001 — collected
                failures.append(f"ship: {e!r}")

    t = threading.Thread(target=pound)
    t.start()
    try:
        deadline = time.monotonic() + 2.0
        polls = 0
        while time.monotonic() < deadline:
            res = agg.poll(force=True)
            if res is None or 0 not in res["info"]["ranks"]:
                continue
            polls += 1
            for rec in res["merged"]:
                telemetry.validate_record(rec)
                if rec["type"] != "counter":
                    continue
                assert rec["value"] == sum(rec["per_rank"].values()), \
                    f"identity broken on {rec['name']}: {rec}"
            steps = [r for r in res["merged"]
                     if r["name"] == "train_step.steps"]
            assert steps and steps[0]["per_rank"]["1"] == 7
    finally:
        stop.set()
        t.join()
    assert not failures, failures
    assert polls > 0
    w.leave()


def test_histogram_bucket_merge_matches_exact_quantiles():
    """Bucket-merged quantile estimates on the union must land within
    one bucket of numpy's exact quantiles over the concatenated
    samples (cumulative counts are element-wise summable because
    cumulation is linear)."""
    rng = np.random.RandomState(7)
    samples = {0: rng.gamma(2.0, 0.01, 400), 1: rng.gamma(6.0, 0.02, 300)}
    recs = {}
    for rank, xs in samples.items():
        telemetry.reset()
        h = telemetry.histogram("train_step.seconds")
        for x in xs:
            h.observe(float(x))
        (rec,) = [r for r in telemetry.snapshot()
                  if r["name"] == "train_step.seconds"]
        rec["rank"] = rank
        recs[rank] = [rec]
    merged, info = fleet_obs.merge_streams(recs)
    (m,) = merged
    assert m["value"] == 700 and info["ranks"] == [0, 1]
    union = np.concatenate(list(samples.values()))
    bounds, _cum = telemetry._split_record_buckets(m["buckets"])

    def bucket_index(v):
        return next((i for i, b in enumerate(bounds) if v <= b),
                    len(bounds))

    for q in (0.5, 0.9, 0.99):
        est = telemetry.quantile_from_cumulative(
            m["buckets"], q, vmin=m.get("min"), vmax=m.get("max"))
        exact = float(np.quantile(union, q))
        assert abs(bucket_index(est) - bucket_index(exact)) <= 1, \
            f"q{q}: estimate {est} vs exact {exact} off by > 1 bucket"


def test_histogram_merge_refuses_mismatched_buckets():
    a = {"name": "train_step.seconds", "type": "histogram", "value": 1,
         "sum": 0.1, "ts": 1.0, "buckets": [[0.1, 1], ["+Inf", 1]]}
    b = dict(a, buckets=[[0.2, 1], ["+Inf", 1]])
    with pytest.raises(ValueError, match="bucket edges differ"):
        fleet_obs.merge_streams({0: [a], 1: [b]})


def test_stale_generation_records_excluded():
    """An evicted rank's snapshot from a previous membership epoch must
    not pollute the current epoch's rollup: stamped-stale records are
    dropped (and counted), a fully-stale rank disappears from the
    reporting set, unstamped records ride along."""
    streams = {
        0: [_counter_rec("train_step.steps", 10, 0, 2)],
        1: [_counter_rec("train_step.steps", 99, 1, 1)],      # stale
        2: [{"name": "fleet.worker_restarts", "type": "counter",
             "value": 4, "ts": 1000.0}],                      # unstamped
    }
    merged, info = fleet_obs.merge_streams(streams, generation=2)
    assert info["stale_dropped"] == 1
    assert info["ranks"] == [0, 2]          # rank 1 fully stale -> gone
    (steps,) = [r for r in merged if r["name"] == "train_step.steps"]
    assert steps["value"] == 10 and list(steps["per_rank"]) == ["0"]
    assert [r for r in merged if r["name"] == "fleet.worker_restarts"]


def test_missing_rank_is_a_gap_never_interpolated(tmp_path):
    """World {0, 1, 2} with only ranks 0 and 2 shipping: the aggregator
    reports the gap (fleet.ranks_reporting == 2) and no merged record
    invents a rank-1 contribution."""
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=5.0)
    ctl.advance(world=[0, 1, 2], reason="launch")
    obs = os.path.join(ctl.root, fleet_obs.OBS_DIR)
    os.makedirs(obs, exist_ok=True)
    for rank in (0, 2):
        with open(os.path.join(obs, f"rank-{rank}.jsonl"), "w") as f:
            f.write(json.dumps(
                _counter_rec("train_step.steps", 5, rank, 1)) + "\n")
    agg = fleet_obs.FleetAggregator(ctl)
    res = agg.poll(force=True)
    assert res["info"]["ranks"] == [0, 2]
    assert telemetry.get("fleet.ranks_reporting").value == 2
    for rec in res["merged"]:
        assert "1" not in rec.get("per_rank", {})
    (steps,) = [r for r in res["merged"]
                if r["name"] == "train_step.steps"]
    assert steps["value"] == 10                  # 5 + 5, nothing imputed


# ---------------------------------------------------------------------------
# straggler attribution
# ---------------------------------------------------------------------------
def test_correlate_steps_attributes_slowest_rank_and_phase():
    events = {0: _phase_events(0, 1, steps=6),
              1: _phase_events(1, 1, steps=6, slow=0.3)}
    correlated = fleet_obs.correlate_steps(events, generation=1)
    assert len(correlated) == 6
    for c in correlated:
        assert c["slowest_rank"] == 1
        assert c["dominant_phase"] == "data_wait"
        assert c["skew_seconds"] == pytest.approx(0.3)
    # single-rank steps never correlate — skew needs >= 2 observers
    assert fleet_obs.correlate_steps({0: _phase_events(0, 1, 4)}) == []
    # generation alignment: the same (epoch, step) under another
    # membership epoch is a DIFFERENT step
    assert fleet_obs.correlate_steps(events, generation=2) == []


def test_straggler_detector_flags_persistent_rank_and_flips_back():
    det = fleet_obs.StragglerDetector(window=8, frac=0.5, min_steps=4)
    events = {0: _phase_events(0, 1, steps=6),
              1: _phase_events(1, 1, steps=6, slow=0.2)}
    sig = det.update(fleet_obs.correlate_steps(events, generation=1))
    assert sig["straggling"] and sig["rank"] == 1
    assert sig["dominant_phase"] == "data_wait"
    assert sig["excess_seconds"] == pytest.approx(0.2)
    flips = [e for e in tracing.snapshot()
             if e["event"] == "fleet.straggler"]
    assert flips and flips[-1]["data"]["rank"] == 1
    # feeding the SAME correlated steps again must not re-judge them
    # (shipped event snapshots are rolling and overlap poll to poll)
    assert det.update(fleet_obs.correlate_steps(events, generation=1)) \
        == sig
    # recovery: rank 1 goes fast for a full window -> all-clear flip
    healed = {0: [], 1: []}
    for r in (0, 1):
        evs = _phase_events(r, 1, steps=20, slow=0.2 if r == 0 else 0.0)
        healed[r] = [e for e in evs if e["step"] >= 6]
    sig2 = det.update(fleet_obs.correlate_steps(healed, generation=1))
    assert sig2["rank"] == 0 or not sig2["straggling"]
    flips = [e for e in tracing.snapshot()
             if e["event"] == "fleet.straggler"]
    assert len(flips) >= 2                       # the state flipped again


def test_chaos_slow_worker_fires_only_on_matching_rank():
    with chaos.enable(slow_worker_rank=1, slow_worker_seconds=0.01) as cfg:
        chaos.maybe_slow_worker(rank=0)
        assert cfg.slow_worker_fires == 0
        t0 = time.perf_counter()
        chaos.maybe_slow_worker(rank=1)
        assert time.perf_counter() - t0 >= 0.01
        assert cfg.slow_worker_fires == 1
    m = telemetry.get("chaos.injections", kind="slow_worker")
    assert m is not None and m.value == 1


# ---------------------------------------------------------------------------
# the fleet black box + the jax-less tools
# ---------------------------------------------------------------------------
def _build_fleet_run(tmp_path):
    """Ship two ranks (one straggling), aggregate, return (ctl, agg)."""
    root = tmp_path / "fleet"
    ctl = Fleet(root, member=None, controller=True, lease=5.0)
    ctl.advance(world=[0, 1], reason="launch")
    for rank in (0, 1):
        telemetry.reset()
        tracing.reset()
        w = _worker(root, rank)
        telemetry.counter("train_step.steps").inc(10 + rank)
        telemetry.histogram("train_step.seconds").observe(0.01)
        for ev in _phase_events(rank, 1, steps=6,
                                slow=0.25 if rank == 1 else 0.0):
            tracing.set_context(epoch=ev["epoch"], step=ev["step"])
            tracing.emit("train_step.phase", **ev["data"])
        fleet_obs.ObsShipper(w).ship(force=True)
        w.leave()
    telemetry.reset()
    tracing.reset()
    return ctl, fleet_obs.FleetAggregator(ctl)


def test_fleet_blackbox_roundtrip_and_report_tools(tmp_path):
    """ship -> aggregate -> dump -> validate: the black box carries the
    cross-rank section, the in-module validator re-proves the identity,
    and both report tools exit 0 on it (fleet_report additionally names
    the straggling rank and its dominant phase in the rendering)."""
    ctl, agg = _build_fleet_run(tmp_path)
    res = agg.poll(force=True)
    assert res["signal"]["straggling"] and res["signal"]["rank"] == 1
    path = fleet_obs.dump_fleet_blackbox(ctl.root, reason="test dump",
                                         aggregator=agg)
    assert path == fleet_obs.fleet_blackbox_path(ctl.root)
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    fleet_obs.validate_fleet_section(doc, telemetry=telemetry)
    # tampering with one per-rank value must break the identity check
    bad = json.loads(json.dumps(doc))
    for rec in bad["fleet"]["aggregate"]:
        if rec["type"] == "counter":
            rec["value"] += 1
            break
    with pytest.raises(ValueError, match="identity"):
        fleet_obs.validate_fleet_section(bad)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         path, "--validate"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "slowest=rank 1" in r.stdout
    assert "data_wait" in r.stdout
    assert "aggregation identity holds" in r.stdout


def test_telemetry_report_merge_mode(tmp_path):
    """--merge folds per-rank files through the same merge core and
    composes with --validate/--require (the fleet_obs preset's
    obs-shipping counter rides in the worker streams)."""
    ctl, _agg = _build_fleet_run(tmp_path)
    obs = os.path.join(ctl.root, fleet_obs.OBS_DIR)
    files = [os.path.join(obs, f"rank-{r}.jsonl") for r in (0, 1)]
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "telemetry_report.py"), "--merge",
         *files, "--validate",
         "--require", "fleet.obs_records,train_step.steps"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "aggregation identity holds" in r.stdout
    # a required-but-absent metric still fails the merged gate
    r2 = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "telemetry_report.py"), "--merge",
         *files, "--require", "serve.requests"],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 1
