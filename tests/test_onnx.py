"""ONNX export/import roundtrip (REF:tests/python-pytest/onnx/ — the
reference tested via the onnx package; none exists here, so the oracle is
the roundtrip itself: export a Symbol net to ONNX bytes, re-import through
the self-contained wire-format parser, and compare executor outputs."""
import numpy as np
import pytest

import tpu_mx as mx
import tpu_mx.symbol as S
from tpu_mx import nd
from tpu_mx.contrib import onnx as onnx_mx
from tpu_mx.contrib._protobuf import Msg, decode, decode_packed_ints


def test_protobuf_roundtrip():
    m = (Msg().int(1, 8).bytes(2, "hello").float(3, 2.5)
         .ints(4, [3, -1, 7]).bytes(5, Msg().int(1, 42)))
    f = decode(m.tobytes())
    assert f[1] == [8]
    assert f[2] == [b"hello"]
    assert abs(f[3][0] - 2.5) < 1e-7
    assert decode_packed_ints(f[4]) == [3, -1, 7]
    assert decode(f[5][0])[1] == [42]


def _convnet():
    x = S.Variable("data")
    c1 = S.Convolution(x, kernel=(3, 3), num_filter=8, pad=(1, 1),
                       name="c1")
    b1 = S.BatchNorm(c1, fix_gamma=False, name="bn1")
    a1 = S.Activation(b1, act_type="relu", name="a1")
    p1 = S.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                   name="p1")
    c2 = S.Convolution(p1, kernel=(1, 1), num_filter=4, no_bias=True,
                       name="c2")
    g = S.Pooling(c2, global_pool=True, kernel=(1, 1), pool_type="avg",
                  name="g")
    f = S.Flatten(g, name="f")
    fc = S.FullyConnected(f, num_hidden=10, name="fc")
    return S.softmax(fc, name="out")


def _init_params(sym, data_shape):
    rng = np.random.RandomState(0)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    params = {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name == "data":
            continue
        params[name] = nd.array(rng.uniform(-0.2, 0.2, shp)
                                .astype(np.float32))
    for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
        val = np.ones(shp, np.float32) if "var" in name \
            else np.zeros(shp, np.float32)
        params[name] = nd.array(val)
    return params


def _forward(sym, params, data):
    feeds = {"data": data}
    feeds.update(params)
    return sym.eval(**feeds)[0].asnumpy()


def test_onnx_roundtrip_convnet(tmp_path):
    sym = _convnet()
    shape = (2, 3, 16, 16)
    params = _init_params(sym, shape)
    data = nd.array(np.random.RandomState(1).rand(*shape)
                    .astype(np.float32))
    y_ref = _forward(sym, params, data)

    path = str(tmp_path / "net.onnx")
    onnx_mx.export_model(sym, params, [shape], path)
    meta = onnx_mx.get_model_metadata(path)
    assert meta["input_tensor_data"] == ["data"]

    sym2, arg2, aux2 = onnx_mx.import_model(path)
    params2 = dict(arg2)
    params2.update(aux2)
    y = _forward(sym2, params2, data)
    np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)
    # aux split: BN running stats land in aux_params (reference contract)
    assert any("moving_mean" in k or "mean" in k for k in aux2), aux2.keys()


def test_onnx_roundtrip_mlp_embedding(tmp_path):
    tok = S.Variable("tokens")
    emb = S.Embedding(tok, input_dim=20, output_dim=8, name="emb")
    f = S.Flatten(emb, name="fl")
    fc1 = S.FullyConnected(f, num_hidden=16, name="fc1")
    act = S.Activation(fc1, act_type="tanh", name="act")
    drop = S.Dropout(act, p=0.3, name="drop")
    out = S.FullyConnected(drop, num_hidden=4, name="fc2")

    rng = np.random.RandomState(2)
    params = {
        "emb_weight": nd.array(rng.randn(20, 8).astype(np.float32)),
        "fc1_weight": nd.array(rng.randn(16, 32).astype(np.float32) * 0.1),
        "fc1_bias": nd.array(np.zeros(16, np.float32)),
        "fc2_weight": nd.array(rng.randn(4, 16).astype(np.float32) * 0.1),
        "fc2_bias": nd.array(np.zeros(4, np.float32)),
    }
    data = nd.array(rng.randint(0, 20, (3, 4)).astype(np.int32))
    feeds = {"tokens": data}
    feeds.update(params)
    y_ref = out.eval(**feeds)[0].asnumpy()

    path = str(tmp_path / "mlp.onnx")
    onnx_mx.export_model(out, params, {"tokens": (3, 4)}, path,
                         input_dtypes={"tokens": "int32"})
    # declared input elem_type must be INT32 (6), not the float default —
    # foreign runtimes reject misdeclared feeds
    with open(path, "rb") as f:
        graph = decode(decode(f.read())[7][0])
    vi = decode(graph[11][0])
    ttype = decode(decode(vi[2][0])[1][0])
    assert ttype[1][0] == 6, "tokens input must be declared int32"
    sym2, arg2, aux2 = onnx_mx.import_model(path)
    feeds2 = {"tokens": data}
    feeds2.update(arg2)
    y = sym2.eval(**feeds2)[0].asnumpy()
    np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)


def test_onnx_residual_and_concat(tmp_path):
    x = S.Variable("data")
    c1 = S.Convolution(x, kernel=(3, 3), num_filter=4, pad=(1, 1), name="r1")
    c2 = S.Convolution(x, kernel=(1, 1), num_filter=4, name="r2")
    added = S.broadcast_add(c1, c2, name="add")
    cat = S.Concat(added, c1, dim=1, name="cat")
    lr = S.LeakyReLU(cat, slope=0.1, name="lrelu")

    shape = (1, 2, 8, 8)
    params = _init_params(lr, shape)
    data = nd.array(np.random.RandomState(3).rand(*shape)
                    .astype(np.float32))
    y_ref = _forward(lr, params, data)
    path = str(tmp_path / "res.onnx")
    onnx_mx.export_model(lr, params, [shape], path)
    sym2, arg2, aux2 = onnx_mx.import_model(path)
    params2 = dict(arg2)
    params2.update(aux2)
    y = _forward(sym2, params2, data)
    np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)


def test_onnx_export_rejects_unsupported(tmp_path):
    x = S.Variable("data")
    bad = S.linalg_syevd(x) if hasattr(S, "linalg_syevd") else None
    if bad is None:
        pytest.skip("no unsupported op available to test")
    with pytest.raises(mx.base.MXNetError, match="unsupported"):
        onnx_mx.export_model(bad[0] if isinstance(bad, tuple) else bad,
                             {}, [(4, 4)], str(tmp_path / "x.onnx"))


def test_onnx_resnet18_zoo_roundtrip(tmp_path):
    """The headline parity check: a real model-zoo-style residual stack
    exports and re-imports with numerically identical inference."""
    x = S.Variable("data")
    y = S.Convolution(x, kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                      num_filter=8, no_bias=True, name="conv0")
    y = S.BatchNorm(y, fix_gamma=True, name="bn0")
    y = S.Activation(y, act_type="relu", name="relu0")
    y = S.Pooling(y, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                  pool_type="max", name="pool0")
    res = y
    y = S.Convolution(y, kernel=(3, 3), pad=(1, 1), num_filter=8,
                      no_bias=True, name="rb_c1")
    y = S.BatchNorm(y, fix_gamma=False, name="rb_bn1")
    y = S.Activation(y, act_type="relu", name="rb_a1")
    y = S.Convolution(y, kernel=(3, 3), pad=(1, 1), num_filter=8,
                      no_bias=True, name="rb_c2")
    y = S.BatchNorm(y, fix_gamma=False, name="rb_bn2")
    y = S.Activation(S.broadcast_add(y, res, name="rb_add"),
                     act_type="relu", name="rb_out")
    y = S.Pooling(y, global_pool=True, kernel=(1, 1), pool_type="avg",
                  name="gap")
    y = S.FullyConnected(S.Flatten(y, name="fl"), num_hidden=10, name="head")

    shape = (2, 3, 32, 32)
    params = _init_params(y, shape)
    data = nd.array(np.random.RandomState(4).rand(*shape)
                    .astype(np.float32))
    y_ref = _forward(y, params, data)
    path = str(tmp_path / "rn.onnx")
    onnx_mx.export_model(y, params, [shape], path)
    sym2, arg2, aux2 = onnx_mx.import_model(path)
    params2 = dict(arg2)
    params2.update(aux2)
    np.testing.assert_allclose(_forward(sym2, params2, data), y_ref,
                               rtol=1e-4, atol=1e-5)
