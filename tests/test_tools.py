"""Tools: im2rec + launch.py (reference analog: the dmlc local tracker
distributed tests, SURVEY §4 'distributed tests without a real cluster')."""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
cv2 = pytest.importorskip("cv2")


def _env_cpu():
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_im2rec_roundtrip(tmp_path):
    # class-per-folder layout
    for cls in ("cat", "dog"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = (np.random.RandomState(i).rand(32, 40, 3) * 255
                   ).astype(np.uint8)
            cv2.imwrite(str(d / f"{i}.jpg"), img)
    prefix = str(tmp_path / "out")
    subprocess.run([sys.executable, os.path.join(REPO, "tools/im2rec.py"),
                    "--list", prefix, str(tmp_path / "imgs")],
                   check=True, env=_env_cpu())
    assert os.path.exists(prefix + ".lst")
    subprocess.run([sys.executable, os.path.join(REPO, "tools/im2rec.py"),
                    prefix, str(tmp_path / "imgs")],
                   check=True, env=_env_cpu())
    from tpu_mx import recordio
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(r.keys) == 6
    header, img = recordio.unpack_img(r.read_idx(r.keys[0]))
    assert img.shape == (32, 40, 3)
    labels = set()
    for k in r.keys:
        h, _ = recordio.unpack(r.read_idx(k))
        labels.add(float(np.asarray(h.label).ravel()[0]))
    assert labels == {0.0, 1.0}
    # and the native pipeline can consume the packed file
    from tpu_mx.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         data_shape=(3, 16, 16), batch_size=3)
    assert next(iter(it)).data[0].shape == (3, 3, 16, 16)


def test_launch_local_spmd(tmp_path):
    """launch.py -n 2: both processes join one jax.distributed group and
    agree on rank/size (the dist_sync_kvstore.py pattern)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import tpu_mx as mx\n"
        "ok = mx.kvstore.dist_init()\n"
        "assert ok\n"
        "kv = mx.kvstore.create('dist_sync')\n"
        "print(f'RANK={kv.rank} SIZE={kv.num_workers}', flush=True)\n"
        "assert kv.num_workers == 2\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/launch.py"), "-n", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, env=_env_cpu(), timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    ranks = sorted(l for l in out.stdout.splitlines() if l.startswith("RANK"))
    assert ranks == ["RANK=0 SIZE=2", "RANK=1 SIZE=2"], out.stdout
