"""Tools: im2rec + launch.py (reference analog: the dmlc local tracker
distributed tests, SURVEY §4 'distributed tests without a real cluster')."""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
cv2 = pytest.importorskip("cv2")


def _multiprocess_collectives_supported():
    """Whether the jax backend can run CROSS-PROCESS collectives.  The
    CPU backend cannot: any 2-process psum/barrier raises
    INVALID_ARGUMENT "Multiprocess computations aren't implemented on
    the CPU backend" (jax 0.4.37) — process-group formation and virtual
    single-process meshes work, the collective dispatch itself does not.
    Capability-keyed (not env-keyed) so the skip lifts itself the moment
    these tests run against a real TPU/GPU backend."""
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:  # no jax at all: the tests below cannot run either
        return False


# The three 2-process tests below exercise REAL cross-process collectives
# (elastic barrier death detection, dist_sync kvstore reduce, multi-host
# CompiledTrainStep).  They failed on every CPU-backend run since the
# seed — a backend capability gap, not a regression — and were carried as
# "fails at seed too" folklore until ISSUE 10 made the condition explicit.
_needs_multiprocess_collectives = pytest.mark.skipif(
    not _multiprocess_collectives_supported(),
    reason="needs cross-process collectives: the CPU jax backend raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend' (capability gap, present at seed; runs on TPU/GPU)")


def _env_cpu():
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_im2rec_roundtrip(tmp_path):
    # class-per-folder layout
    for cls in ("cat", "dog"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = (np.random.RandomState(i).rand(32, 40, 3) * 255
                   ).astype(np.uint8)
            cv2.imwrite(str(d / f"{i}.jpg"), img)
    prefix = str(tmp_path / "out")
    subprocess.run([sys.executable, os.path.join(REPO, "tools/im2rec.py"),
                    "--list", prefix, str(tmp_path / "imgs")],
                   check=True, env=_env_cpu())
    assert os.path.exists(prefix + ".lst")
    subprocess.run([sys.executable, os.path.join(REPO, "tools/im2rec.py"),
                    prefix, str(tmp_path / "imgs")],
                   check=True, env=_env_cpu())
    from tpu_mx import recordio
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(r.keys) == 6
    header, img = recordio.unpack_img(r.read_idx(r.keys[0]))
    assert img.shape == (32, 40, 3)
    labels = set()
    for k in r.keys:
        h, _ = recordio.unpack(r.read_idx(k))
        labels.add(float(np.asarray(h.label).ravel()[0]))
    assert labels == {0.0, 1.0}
    # and the native pipeline can consume the packed file
    from tpu_mx.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         data_shape=(3, 16, 16), batch_size=3)
    assert next(iter(it)).data[0].shape == (3, 3, 16, 16)


@pytest.mark.slow
def test_launch_local_spmd(tmp_path):
    """launch.py -n 2: both processes join one jax.distributed group and
    agree on rank/size (the dist_sync_kvstore.py pattern)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import tpu_mx as mx\n"
        "ok = mx.kvstore.dist_init()\n"
        "assert ok\n"
        "kv = mx.kvstore.create('dist_sync')\n"
        "print(f'RANK={kv.rank} SIZE={kv.num_workers}', flush=True)\n"
        "assert kv.num_workers == 2\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/launch.py"), "-n", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, env=_env_cpu(), timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    # the two workers share the stdout pipe; writes can interleave mid-line
    import re
    ranks = sorted(re.findall(r"RANK=(\d) SIZE=(\d)", out.stdout))
    assert ranks == [("0", "2"), ("1", "2")], out.stdout


@pytest.mark.slow
@_needs_multiprocess_collectives
def test_elastic_barrier_detects_dead_rank(tmp_path):
    """A killed rank in a 2-process run produces a clean WorkerFailure within
    the timeout instead of an indefinite hang (SURVEY §5.3)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import sys, time\n"
        "import tpu_mx as mx\n"
        "mx.kvstore.dist_init()\n"
        "import jax\n"
        "rank = jax.process_index()\n"
        "mx.elastic.barrier('warmup', timeout=60)  # both alive: fine\n"
        "print(f'WARMUP-OK rank={rank}', flush=True)\n"
        "if rank == 1:\n"
        "    sys.exit(0)  # rank 1 'dies' before the next barrier\n"
        "t0 = time.time()\n"
        "try:\n"
        "    mx.elastic.barrier('epoch', timeout=8)\n"
        "    print('UNEXPECTED-PASS', flush=True)\n"
        "except mx.elastic.WorkerFailure as e:\n"
        "    dt = time.time() - t0\n"
        "    assert dt < 30, dt\n"
        "    assert 'resume' in str(e)\n"
        "    print(f'DETECTED rank={rank} after {dt:.1f}s', flush=True)\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/launch.py"), "-n", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, env=_env_cpu(), timeout=300)
    assert "DETECTED rank=0" in out.stdout, (out.stdout, out.stderr[-1500:])
    assert "UNEXPECTED-PASS" not in out.stdout


def test_auto_resume_contract(tmp_path):
    """latest_checkpoint + auto_resume restart training from the newest
    epoch's params (single-process check of the --resume contract)."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import nd
    from tpu_mx.gluon import nn

    net = nn.Dense(3, in_units=4)
    net.initialize()
    prefix = str(tmp_path / "ckpt")
    for epoch in (0, 1, 2):
        net.weight.set_data(nd.full((3, 4), float(epoch)))
        net.save_parameters(f"{prefix}-{epoch:04d}.params")
    epoch, path = mx.elastic.latest_checkpoint(prefix)
    assert epoch == 2 and path.endswith("-0002.params")

    net2 = nn.Dense(3, in_units=4)
    start = mx.elastic.auto_resume(prefix, net=net2)
    assert start == 3
    np.testing.assert_allclose(net2.weight.data().asnumpy(), 2.0)
    # fresh run: no checkpoints -> epoch 0
    assert mx.elastic.auto_resume(str(tmp_path / "none")) == 0


def test_ssh_launcher_command_construction(tmp_path):
    """--launcher ssh builds the right per-rank ssh argv + env protocol
    (REF:dmlc_tracker/ssh.py) — validated without a cluster."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib
        launch = importlib.import_module("launch")
    finally:
        sys.path.pop(0)

    hf = tmp_path / "hosts.txt"
    hf.write_text("# cluster\nnode-a\nnode-b  # gpu box\n\n")
    hosts = launch.read_hostfile(str(hf))
    assert hosts == ["node-a", "node-b"]

    cmds = launch.build_ssh_commands(
        hosts, 4, "head:9999", ["python", "train.py", "--lr", "0.1"],
        env_extra=["FOO=bar baz"])
    assert len(cmds) == 4
    # round-robin placement
    assert [h for h, _ in cmds] == ["node-a", "node-b", "node-a", "node-b"]
    for rank, (host, argv) in enumerate(cmds):
        assert argv[0] == "ssh" and argv[-2] == host
        remote = argv[-1]
        assert f"TPUMX_PROC_ID={rank}" in remote
        assert "TPUMX_NUM_PROC=4" in remote
        assert "TPUMX_COORDINATOR=head:9999" in remote
        assert "FOO='bar baz'" in remote
        assert remote.endswith("python train.py --lr 0.1")

    with pytest.raises(ValueError):
        empty = tmp_path / "empty.txt"
        empty.write_text("# nothing\n")
        launch.read_hostfile(str(empty))


@pytest.mark.slow
@_needs_multiprocess_collectives
def test_dist_sync_kvstore_cross_process_sum(tmp_path):
    """Eager dist_sync push/pull performs a REAL cross-process reduce
    (REF:tests/nightly/dist_sync_kvstore.py): pulled values can only arise
    from summing both ranks' pushes."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import numpy as np\n"
        "import tpu_mx as mx\n"
        "from tpu_mx import nd\n"
        "mx.kvstore.dist_init()\n"
        "kv = mx.kvstore.create('dist_sync')\n"
        "rank, size = kv.rank, kv.num_workers\n"
        "assert size == 2\n"
        "# no-updater path: pull returns the cross-worker sum of pushes\n"
        "kv.init('a', nd.zeros((3, 4)))\n"
        "kv.push('a', nd.full((3, 4), rank + 1.0))  # ranks push 1s and 2s\n"
        "out = nd.zeros((3, 4))\n"
        "kv.pull('a', out=out)\n"
        "np.testing.assert_allclose(out.asnumpy(), 3.0)  # 1 + 2\n"
        "# multi-key, shaped: sum_r (rank+1)*arange = 3*arange\n"
        "base = np.arange(6, dtype=np.float32).reshape(2, 3)\n"
        "kv.init(['k0', 'k1'], [nd.zeros((2, 3)), nd.zeros((2, 3))])\n"
        "kv.push(['k0', 'k1'], [nd.array(base * (rank + 1)),\n"
        "                        nd.array(base * 10 * (rank + 1))])\n"
        "o0, o1 = nd.zeros((2, 3)), nd.zeros((2, 3))\n"
        "kv.pull(['k0', 'k1'], out=[o0, o1])\n"
        "np.testing.assert_allclose(o0.asnumpy(), base * 3)\n"
        "np.testing.assert_allclose(o1.asnumpy(), base * 30)\n"
        "# updater path (update_on_kvstore): w += global grad sum, same on\n"
        "# every rank\n"
        "kv.set_updater(lambda k, g, w: w.__iadd__(g))\n"
        "kv.init('w', nd.zeros((5,)))\n"
        "kv.push('w', nd.full((5,), float(2 ** rank)))  # 1 and 2 -> sum 3\n"
        "wout = nd.zeros((5,))\n"
        "kv.pull('w', out=wout)\n"
        "np.testing.assert_allclose(wout.asnumpy(), 3.0)\n"
        "print(f'KVOK rank={rank}', flush=True)\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/launch.py"), "-n", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, env=_env_cpu(), timeout=300)
    assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-2000:])
    import re
    assert sorted(re.findall(r"KVOK rank=(\d)", out.stdout)) == ["0", "1"], \
        out.stdout


def test_bandwidth_tool():
    """tools/bandwidth.py (REF:tools/bandwidth/measure.py analog) emits
    parseable per-collective records with positive bandwidth."""
    import json as _json
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth.py"),
         "--devices", "8", "--sizes", "0.5", "--iters", "2"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PALLAS_AXON_POOL_IPS": "",
             "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-500:]
    recs = [_json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]
    names = {r["collective"] for r in recs}
    assert names == {"psum", "all_gather", "reduce_scatter", "ppermute"}
    assert all(r["alg_bandwidth_gbps"] > 0 for r in recs)
    assert all(r["devices"] == 8 for r in recs)


@pytest.mark.slow
def test_bench_scaling_mode():
    """BENCH_MODELS=scaling measures weak-scaling efficiency on the
    virtual mesh (the BASELINE metric-3 harness)."""
    import json as _json
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PALLAS_AXON_POOL_IPS": "",
             "BENCH_SMOKE": "1", "BENCH_MODELS": "scaling",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stderr[-500:]
    rec = _json.loads([l for l in out.stdout.splitlines()
                       if l.startswith("{")][-1])
    assert rec["metric"].startswith("weak_scaling_efficiency")
    assert 0 < rec["value"] <= 1.5


@pytest.mark.slow
def test_bench_lstm_ssd_smoke():
    """BENCH_MODELS=lstm,ssd (BASELINE workloads 3 and 5) run end-to-end
    in smoke mode and emit both records."""
    import json as _json
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**_env_cpu(), "BENCH_SMOKE": "1",
             "BENCH_MODELS": "lstm,ssd"})
    assert out.returncode == 0, out.stderr[-500:]
    rec = _json.loads([l for l in out.stdout.splitlines()
                       if l.startswith("{")][-1])
    assert rec["metric"] == "lstm_smoke_tokens_per_sec" and rec["value"] > 0
    assert rec["ssd"]["metric"] == "ssd_smoke_images_per_sec"
    assert rec["ssd"]["value"] > 0


@pytest.mark.slow
def test_bench_lstm_ssd_smoke_bf16():
    """The on-chip default dtype path (bf16 cast + multi_precision
    masters) must execute end-to-end, not only on TPU time: pin the
    dtype knobs to bfloat16 in smoke (smoke defaults to f32)."""
    import json as _json
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**_env_cpu(), "BENCH_SMOKE": "1",
             "BENCH_MODELS": "lstm,ssd",
             "BENCH_LSTM_DTYPE": "bfloat16",
             "BENCH_SSD_DTYPE": "bfloat16"})
    assert out.returncode == 0, out.stderr[-500:]
    rec = _json.loads([l for l in out.stdout.splitlines()
                       if l.startswith("{")][-1])
    assert rec["dtype"] == "bfloat16" and rec["value"] > 0
    assert rec["ssd"]["dtype"] == "bfloat16" and rec["ssd"]["value"] > 0


def test_parse_log_table():
    """tools/parse_log.py (REF:tools/parse_log.py analog): Speedometer +
    fit log lines -> per-epoch table."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "parse_log", os.path.join(REPO, "tools", "parse_log.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    lines = [
        "INFO Epoch[0] Batch [20]\tSpeed: 100.00 samples/sec\taccuracy=0.5",
        "INFO Epoch[0] Batch [40]\tSpeed: 140.00 samples/sec\taccuracy=0.6",
        "INFO Epoch[0] Train-accuracy=0.612000",
        "INFO Epoch[0] Time cost=12.500",
        "INFO Epoch[0] Validation-accuracy=0.580000",
        "INFO Epoch[1] Batch [20]\tSpeed: 150.00 samples/sec\taccuracy=0.7",
        "INFO Epoch[1] Train-accuracy=0.713000",
        "INFO Epoch[1] Time cost=11.000",
        "unrelated noise line",
    ]
    rows = mod.parse(lines)
    assert len(rows) == 2
    assert rows[0]["epoch"] == 0
    assert rows[0]["speed_mean"] == 120.0
    assert rows[0]["train-accuracy"] == 0.612
    assert rows[0]["val-accuracy"] == 0.58
    assert rows[0]["time_s"] == 12.5
    assert rows[1]["speed_mean"] == 150.0
    md = mod.render(rows, "markdown")
    assert "| epoch |" in md and "120.0" in md
    csv = mod.render(rows, "csv")
    assert csv.splitlines()[0].startswith("epoch,")
    import json as _json
    assert _json.loads(mod.render(rows, "json"))[1]["epoch"] == 1


def test_strict_kvstore_flag_raises_on_eager_dist(monkeypatch):
    """TPUMX_STRICT_KVSTORE=1 turns the slow eager dist push into a loud
    error (VERDICT r3 weak#6) instead of a silent degradation."""
    import tpu_mx as mx
    from tpu_mx.base import MXNetError
    kv = mx.kv.create("dist_sync")
    # single process: pretend we're a 2-worker job so _global_sum engages
    monkeypatch.setattr(kv, "_is_dist", True, raising=False)
    monkeypatch.setattr(kv, "_num_workers", 2, raising=False)
    monkeypatch.setenv("TPUMX_STRICT_KVSTORE", "1")
    kv.init("w", mx.nd.zeros((3,)))
    with pytest.raises(MXNetError, match="STRICT_KVSTORE"):
        kv.push("w", mx.nd.ones((3,)))


@pytest.mark.slow
@_needs_multiprocess_collectives
def test_launch_two_process_compiled_train_step(tmp_path):
    """Full multi-host SPMD path: TWO processes x 4 virtual devices form
    one dp=8 mesh and run the SAME CompiledTrainStep — both ranks must
    produce identical loss/weights, equal to a single-process dp=8 run
    (SURVEY §2.3 'DP multi-host sync' beyond the kvstore-math check)."""
    import numpy as np
    script = tmp_path / "worker.py"
    script.write_text(
        "import numpy as np\n"
        "import tpu_mx as mx\n"
        "mx.kvstore.dist_init()\n"
        "import jax\n"
        "assert jax.device_count() == 8, jax.device_count()\n"
        "from tpu_mx import gluon, nd\n"
        "from tpu_mx.gluon import nn\n"
        "from tpu_mx.parallel import CompiledTrainStep, make_mesh\n"
        "np.random.seed(0)\n"
        "mx.random.seed(0)\n"
        "net = nn.HybridSequential()\n"
        "net.add(nn.Dense(16, in_units=8, activation='relu'),\n"
        "        nn.Dense(4, in_units=16))\n"
        "net.initialize(init='xavier')\n"
        "net(nd.ones((1, 8)))\n"
        "mesh = make_mesh({'dp': 8}, devices=jax.devices())\n"
        "step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),\n"
        "                         mx.optimizer.create('sgd', learning_rate=0.1),\n"
        "                         mesh=mesh)\n"
        "x = np.random.RandomState(7).rand(16, 8).astype(np.float32)\n"
        "y = np.random.RandomState(8).randint(0, 4, (16,)).astype(np.float32)\n"
        "loss = None\n"
        "for _ in range(3):\n"
        "    loss = step.step(nd.array(x), nd.array(y))\n"
        "print(f'RANK{jax.process_index()} "
        "LOSS={float(np.asarray(loss._data)):.6f}', flush=True)\n")
    env = _env_cpu()
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=4").strip()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/launch.py"), "-n", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, env=env, timeout=400)
    assert out.returncode == 0, out.stderr[-2000:]
    import re
    losses = {m.group(1): float(m.group(2)) for m in
              re.finditer(r"RANK(\d) LOSS=([\d.]+)", out.stdout)}
    assert set(losses) == {"0", "1"}, out.stdout
    assert losses["0"] == losses["1"]  # equal to 6 printed decimals

    # single-process dp=8 oracle (conftest's virtual mesh), same seeds
    import jax
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon import nn
    from tpu_mx.parallel import CompiledTrainStep, make_mesh
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"),
            nn.Dense(4, in_units=16))
    net.initialize(init="xavier")
    net(nd.ones((1, 8)))
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd", learning_rate=0.1),
                             mesh=make_mesh({"dp": 8},
                                            devices=jax.devices()))
    x = np.random.RandomState(7).rand(16, 8).astype(np.float32)
    y = np.random.RandomState(8).randint(0, 4, (16,)).astype(np.float32)
    for _ in range(3):
        loss = step.step(nd.array(x), nd.array(y))
    np.testing.assert_allclose(float(np.asarray(loss._data)),
                               losses["0"], rtol=1e-5)


def test_artifact_protocol_merge_and_clobber_guard(tmp_path):
    """The on-chip artifact write contract (tools/artifact_protocol.py):
    partial reruns merge (own keys win, sibling rows survive), a TPU-less
    process refuses to clobber a platform=tpu artifact, cross-platform
    rows never merge, and writes are atomic + corruption-tolerant."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from artifact_protocol import (load_prior, merge_prior_sections,
                                       refuses_clobber, write_atomic)
    finally:
        sys.path.pop(0)

    out = str(tmp_path / "artifact.json")
    # absent / corrupt priors load as {}
    assert load_prior(out) == {}
    with open(out, "w") as f:
        f.write("{not json")
    assert load_prior(out) == {}
    with open(out, "w") as f:
        f.write('["a", "list"]')
    assert load_prior(out) == {}

    full = {"platform": "tpu",
            "configs": {"a:1": {"v": 1}, "b:2": {"v": 2}}}
    write_atomic(out, full)
    prior = load_prior(out)
    assert prior == full

    # a TPU-less process must refuse; a TPU process must not
    assert refuses_clobber(prior, "cpu")
    assert not refuses_clobber(prior, "tpu")
    assert not refuses_clobber({}, "cpu")  # nothing to protect

    # partial rerun: own key wins, sibling survives
    rerun = {"platform": "tpu", "configs": {"b:2": {"v": 99}}}
    merge_prior_sections(rerun, prior, ("configs",),
                         require_platform="tpu")
    assert rerun["configs"] == {"a:1": {"v": 1}, "b:2": {"v": 99}}

    # cross-platform rows never merge
    cpu_run = {"platform": "cpu", "configs": {"c:3": {"v": 3}}}
    merge_prior_sections(cpu_run, prior, ("configs",),
                         require_platform="cpu")
    assert cpu_run["configs"] == {"c:3": {"v": 3}}

    # without a platform gate the merge is unconditional (longctx mode)
    ungated = {"flash": {"T=2": {"v": 2}}}
    merge_prior_sections(ungated, {"flash": {"T=1": {"v": 1}}}, ("flash",))
    assert ungated["flash"] == {"T=1": {"v": 1}, "T=2": {"v": 2}}


def test_watch_stage_predicates(tmp_path):
    """The staged watcher's done-predicates key off artifact contents:
    fresh round -> all pending; a flash row without its 'complete' stamp
    (mid-row wedge) stays pending; stamped rows + a successful longctx
    row flip done.  Run under an isolated TPUMX_ROUND so no real round
    artifact is touched."""
    import json as _json
    import textwrap
    script = tmp_path / "drive.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, os.path.join(%r, 'tools'))
        import tpu_watch as w
        dm = {n: bool(d()) for n, d, _ in w.STAGES}
        assert not any(dm.values()), dm
        from flash_sweep import DEFAULT_LENS
        from longctx_bench import DEFAULT_DENSE_AT, DEFAULT_LENS as LC
        def dump(obj, path):
            with open(path, "w") as f:
                json.dump(obj, f)
        # partial flash row (no complete stamp on the last T): pending
        dump({"sweep": {f"T={t}": ({"complete": True}
              if t != DEFAULT_LENS[-1] else {"flash": {}})
              for t in DEFAULT_LENS}}, w.artifact("FLASH_SWEEP"))
        assert not w.flash_sweep_done()
        dump({"sweep": {f"T={t}": {"complete": True}
              for t in DEFAULT_LENS}}, w.artifact("FLASH_SWEEP"))
        assert w.flash_sweep_done()
        # longctx needs >=1 success AND the dense row
        dump({"flash_kernel": {f"T={t}": {"error": "x"} for t in LC},
              "dense_comparison": {}}, w.artifact("LONGCTX"))
        assert not w.longctx_done()
        dump({"flash_kernel": dict(
                {f"T={t}": {"error": "x"} for t in LC},
                **{f"T={LC[0]}": {"tok_per_s": 1}}),
              "dense_comparison": {f"T={DEFAULT_DENSE_AT}": {}}},
             w.artifact("LONGCTX"))
        assert w.longctx_done()
        print("PREDICATES-OK")
    """ % REPO))
    env = dict(_env_cpu(), TPUMX_ROUND="rtest")
    import glob as _glob
    # pre-clean: a SIGKILLed prior run can leave rtest artifacts that
    # would flip the child's all-pending assertion
    for p in _glob.glob(os.path.join(REPO, "*_rtest.json*")):
        os.remove(p)
    try:
        out = subprocess.run([sys.executable, str(script)],
                             capture_output=True, text=True, env=env,
                             timeout=120)
    finally:
        # clean up any rtest artifacts regardless of outcome (incl. a
        # TimeoutExpired: the child may have written some before dying)
        for p in _glob.glob(os.path.join(REPO, "*_rtest.json*")):
            os.remove(p)
    assert "PREDICATES-OK" in out.stdout, (out.stdout, out.stderr[-1500:])
