"""HybridBlock.export() → StableHLO artifact → SymbolBlock.imports roundtrip
(REF:python/mxnet/gluon/block.py export/SymbolBlock; SURVEY §5.4 'export() →
StableHLO artifact')."""
import json
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.gluon import nn, SymbolBlock
from tpu_mx.base import MXNetError


def _small_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, in_channels=3, activation="relu"))
    net.add(nn.BatchNorm())
    net.add(nn.GlobalAvgPool2D())
    net.add(nn.Flatten())
    net.add(nn.Dense(5))
    return net


@pytest.mark.slow
def test_export_roundtrip_bit_identical(tmp_path):
    net = _small_net()
    net.initialize(init="xavier")
    net.hybridize()
    x = nd.array(np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32))
    y_ref = net(x)  # records input avals + caches the jit
    prefix = str(tmp_path / "model")
    net.export(prefix, epoch=3)

    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0003.params.npz")
    assert os.path.exists(prefix + "-0003.stablehlo")
    manifest = json.load(open(prefix + "-symbol.json"))
    assert manifest["format"] == "tpu_mx-stablehlo-v1"
    assert manifest["inputs"][0]["shape"] == [2, 3, 8, 8]

    blk = SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                              prefix + "-0003.params.npz")
    y = blk(x)
    np.testing.assert_array_equal(y.asnumpy(), y_ref.asnumpy())


def test_export_with_example_inputs_no_prior_call(tmp_path):
    net = _small_net()
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(1).rand(1, 3, 6, 6).astype(np.float32))
    _ = net(x)  # finalize deferred shapes (eager; no hybridize)
    prefix = str(tmp_path / "m2")
    net.export(prefix, epoch=0, example_inputs=[x])
    blk = SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                              prefix + "-0000.params.npz")
    np.testing.assert_allclose(blk(x).asnumpy(), net(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_export_without_shapes_raises(tmp_path):
    net = _small_net()
    net.initialize(init="xavier")
    with pytest.raises(MXNetError):
        net.export(str(tmp_path / "m3"))


def test_imports_rejects_bad_format(tmp_path):
    p = tmp_path / "bad-symbol.json"
    p.write_text(json.dumps({"format": "mxnet-json-v1"}))
    with pytest.raises(MXNetError):
        SymbolBlock.imports(str(p), ["data"], "unused")
