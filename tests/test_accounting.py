"""Capacity accounting: the device-memory ledger, attribution identity,
and exhaustion forensics (tpu_mx/serving/accounting.py — ISSUE 14).

Covers: ledger exactness under the 4-thread allocator hammer with
holder attribution (share/free interleavings; the identity — per block,
attributed refs == refcount; per tenant, amortized bytes sum EXACTLY to
pool-used bytes — asserted after every phase), cache-level attribution
through share/COW/pressure-evict interleavings (plan pins, commit
handoff, index holder, fork, COW), loud mis-attribution, forensic
dumps on CacheExhausted in BOTH decode arms (schema-valid, naming 100%
of live holders), the would-fit ``capacity_signal`` admission gate, the
per-tenant pool gauges, and the jax-less ``tools/capacity_report.py``
rc contract.
"""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from tpu_mx import telemetry, tracing
from tpu_mx.base import MXNetError
from tpu_mx import serving
from tpu_mx.serving import (BlockAllocator, CacheExhausted,
                            ContinuousBatchingScheduler, PagedKVCache,
                            Request, Server, TinyLM,
                            validate_forensic_doc)
from tpu_mx.serving import tenancy
from tpu_mx.serving.accounting import INDEX_TENANT, UNATTRIBUTED

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    tracing.reset()
    tenancy.reset_label_registry()
    yield
    tracing.reset()
    tenancy.reset_label_registry()


def tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("seed", 0)
    return TinyLM(**kw)


def kv(rng, n, layers=2, heads=2, dim=4):
    k = rng.rand(layers, n, heads, dim).astype(np.float32)
    return k, (k * 0.5).astype(np.float32)


def assert_identity(alloc):
    """The audit must pass AND agree with the raw refcount surface."""
    report = alloc.audit()
    assert report["used_blocks"] == alloc.used
    total = sum(t["bytes_amortized"] for t in report["tenants"].values())
    assert abs(total - report["used_bytes"]) < 1e-6 * max(
        report["used_bytes"], 1)
    return report


# ---------------------------------------------------------------------------
# ledger exactness: allocator level
# ---------------------------------------------------------------------------
def test_ledger_identity_under_4_thread_hammer():
    """The ISSUE-12 hammer, now with holder attribution: 4 threads
    share/free under their own holders; the accounting identity holds
    at the join point, after a partial free phase, and drains to zero."""
    a = BlockAllocator(64, block_bytes=512)
    owned = [[] for _ in range(4)]
    errs = []

    def worker(i, iters=400):
        rng = np.random.RandomState(200 + i)
        holder = f"seq:hammer-{i}"
        try:
            for _ in range(iters):
                r = rng.rand()
                if owned[i] and r < 0.35:
                    a.free([owned[i].pop()], holder=holder)
                elif owned[i] and r < 0.55:
                    bid = owned[i][int(rng.randint(len(owned[i])))]
                    a.incref([bid], holder=holder)
                    owned[i].append(bid)
                else:
                    try:
                        owned[i].extend(a.alloc(int(rng.randint(1, 4)),
                                                holder=holder))
                        # describe-after-hold, the cache's discipline: a
                        # fully drained holder forgets its meta, so the
                        # attribution rides each (re)acquisition
                        a.describe(holder, kind="sequence",
                                   tenant=f"tenant-{i % 2}")
                    except CacheExhausted:
                        if owned[i]:
                            a.free([owned[i].pop()], holder=holder)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs

    # phase 1: exact attribution at the join point
    report = assert_identity(a)
    held = {}
    for lst in owned:
        for b in lst:
            held[b] = held.get(b, 0) + 1
    assert a.refcounts() == held
    by_holder = {h["id"]: h["blocks"] for h in report["holders"]}
    for i, lst in enumerate(owned):
        if lst:
            assert by_holder[f"seq:hammer-{i}"] == len(lst)
    # tenant-{0,1} aggregate threads {0,2} and {1,3}
    for t in report["tenants"]:
        assert t.startswith("tenant-")

    # phase 2: half of every ledger drains — identity still exact
    for i, lst in enumerate(owned):
        drop, owned[i] = lst[::2], lst[1::2]
        a.free(drop, holder=f"seq:hammer-{i}")
    assert_identity(a)

    # phase 3: full drain — zero residual attribution
    for i, lst in enumerate(owned):
        a.free(lst, holder=f"seq:hammer-{i}")
    report = a.audit()
    assert report["used_blocks"] == 0 and not report["tenants"]
    assert report["high_watermark_blocks"] > 0   # the peak survived


def test_misattributed_free_is_loud_and_mutates_nothing():
    a = BlockAllocator(8)
    ids = a.alloc(2, holder="seq:a")
    with pytest.raises(MXNetError):
        a.free(ids, holder="seq:b")      # b holds no reference
    assert a.refcount(ids[0]) == 1       # nothing moved
    assert_identity(a)
    a.free(ids, holder="seq:a")
    assert a.audit()["used_blocks"] == 0


def test_unattributed_callers_stay_ledgered():
    """Bare alloc/incref/free (the pre-ledger API) files under the
    anonymous holder — the identity never has a blind spot."""
    a = BlockAllocator(8, block_bytes=64)
    ids = a.alloc(3)
    a.incref(ids[:1])
    report = assert_identity(a)
    assert set(report["tenants"]) == {UNATTRIBUTED}
    assert report["tenants"][UNATTRIBUTED]["bytes_amortized"] == 3 * 64
    a.free(ids[:1])
    a.free(ids)
    assert a.audit()["used_blocks"] == 0


# ---------------------------------------------------------------------------
# ledger exactness: cache level (share / COW / pressure-evict)
# ---------------------------------------------------------------------------
def test_cache_attribution_through_share_cow_and_pressure_evict():
    rng = np.random.RandomState(3)
    cache = PagedKVCache(2, 2, 4, block_size=4, num_blocks=12,
                         share_prefix=True)
    bb = cache.allocator.ledger.block_bytes
    tokens = list(range(1, 13))   # 3 full blocks
    k, v = kv(rng, 12)
    cache.prefill("s0", k, v, tokens=tokens, tenant="alpha")
    rep = assert_identity(cache.allocator)
    # 3 blocks, each refcount 2 (sequence + index): alpha's amortized
    # share is half of each, the index pseudo-tenant the other half
    assert rep["tenants"]["alpha"]["bytes_amortized"] == pytest.approx(
        1.5 * bb)
    assert rep["tenants"][INDEX_TENANT]["bytes_amortized"] == \
        pytest.approx(1.5 * bb)
    assert rep["tenants"]["alpha"]["bytes_exclusive"] == 3 * bb

    # a second tenant rides the shared prefix: match pins under ITS name
    plan = cache.match_prefix(tokens + [99], tenant="beta")
    assert plan is not None and plan.tokens_matched == 12
    rep = assert_identity(cache.allocator)
    pinned = [h for h in rep["holders"] if h["pinned"]]
    assert len(pinned) == 1 and pinned[0]["tenant"] == "beta"

    ks, vs = kv(rng, 1)   # suffix: the 13-token prompt's final position
    cache.commit_prefill("s1", plan, ks, vs, tokens + [99], tenant="beta")
    rep = assert_identity(cache.allocator)
    assert not any(h["pinned"] for h in rep["holders"])   # pins handed off
    assert rep["tenants"]["beta"]["bytes_amortized"] > 0

    # fork inherits the parent's tenant; COW on divergent append
    cache.fork("s1", "s1-fork")
    rep = assert_identity(cache.allocator)
    assert rep["tenants"]["beta"]["holders"] == 2
    cache.reserve("s1-fork")              # COWs the shared tail
    assert_identity(cache.allocator)

    # pressure: filling the pool forces index leaf eviction mid-stream
    k2, v2 = kv(rng, 12)
    try:
        cache.prefill("s2", k2, v2, tenant="gamma")
    except CacheExhausted:
        pass   # genuinely full of live data is also a valid outcome
    assert_identity(cache.allocator)

    # drain: free everything, drop the index — zero residual bytes
    for sid in ("s0", "s1", "s1-fork", "s2"):
        cache.free_sequence(sid)
    assert_identity(cache.allocator)
    cache.drop_prefix_cache()
    rep = cache.audit()
    assert rep["used_blocks"] == 0 and not rep["tenants"]
    assert not cache.allocator.refcounts()


# ---------------------------------------------------------------------------
# forensic dump on exhaustion, both decode arms
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["0", "1"])
def test_forensic_dump_on_exhaustion_both_decode_arms(mode, monkeypatch,
                                                      tmp_path):
    monkeypatch.setenv("TPUMX_PAGED_DECODE", mode)
    monkeypatch.setenv("TPUMX_PREFIX_SHARING", "1")
    prefix = str(tmp_path / "cap")
    srv = Server(tiny(), num_blocks=6, block_size=4, max_batch=4,
                 max_tokens=10 ** 6, blackbox=prefix,
                 tenants={"a": {"weight": 1.0}, "b": {"weight": 1.0}})
    reqs = [srv.submit([1, 2, 3, 4, 5, 6, 7], max_new_tokens=8,
                       tenant="a" if i % 2 else "b") for i in range(5)]
    srv.run_until_idle()
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 8, r
    recs = srv.engine.cache.forensic_records()
    exh = [r for r in recs if r["kind"] == "exhaustion"]
    assert exh, "the undersized pool must have exhausted"
    # the record names 100% of the holders live at fault time: its
    # attributed refs sum to the pool's total refcount
    for rec in exh:
        assert sum(h["blocks"] for h in rec["holders"]) == \
            rec["pool"]["total_refs"]
        tenants = {h["tenant"] for h in rec["holders"]
                   if h["kind"] == "sequence"}
        assert tenants <= {"a", "b"}
    # the rolling on-disk dump is schema-valid and (after a forced
    # flush — disk dumps are rate-limited) matches the ring exactly
    path = prefix + "-capacity.json"
    assert os.path.exists(path)
    assert srv.engine.cache.flush_forensics() == path
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    validate_forensic_doc(doc)
    assert len(doc["records"]) == len(recs)
    # the exhaustion landed on the flight-recorder timeline, naming the
    # forensic file
    evs = [e for e in tracing.snapshot()
           if e["event"] == "serve.capacity_exhausted"]
    assert evs and evs[-1]["data"]["forensic"] == path
    # the ledger survived the ordeal exactly
    srv.engine.cache.drop_prefix_cache()
    rep = srv.engine.cache.audit()
    assert rep["used_blocks"] == 0 and not rep["tenants"]


def test_unarmed_cache_records_forensics_in_memory_only(tmp_path):
    cache = PagedKVCache(2, 2, 4, block_size=4, num_blocks=2,
                         share_prefix=False)
    rng = np.random.RandomState(0)
    k, v = kv(rng, 8)
    cache.prefill("s0", k, v)
    with pytest.raises(CacheExhausted):
        cache.prefill("s1", *kv(rng, 8))
    recs = cache.forensic_records()
    assert recs and recs[-1]["kind"] == "exhaustion"
    assert not list(tmp_path.iterdir())   # nothing written anywhere


# ---------------------------------------------------------------------------
# the would-fit capacity signal
# ---------------------------------------------------------------------------
def test_capacity_signal_gates_admission_until_blocks_free():
    sched = ContinuousBatchingScheduler(max_batch=4, max_tokens=10 ** 6)
    sched.submit(Request([1] * 16, 4))
    # a published signal with no free/reclaimable capacity: the head
    # stays queued instead of popping just to bounce on CacheExhausted
    sched.capacity_signal = {"block_size": 4, "free_blocks": 1,
                             "reclaimable_blocks": 1}
    assert sched.take_prefills() == []
    assert sched.queue_depth() == 1
    # capacity appears (decode evictions freed blocks): admitted
    sched.capacity_signal = {"block_size": 4, "free_blocks": 3,
                             "reclaimable_blocks": 1}
    got = sched.take_prefills()
    assert len(got) == 1
    # no signal (bare scheduler, or right after an engine restart):
    # gating is off — exactly the pre-ledger behavior
    sched2 = ContinuousBatchingScheduler(max_batch=4, max_tokens=10 ** 6)
    sched2.submit(Request([1] * 16, 4))
    assert len(sched2.take_prefills()) == 1


def test_server_publishes_capacity_signal_and_pool_gauges():
    telemetry.reset()
    try:
        srv = Server(tiny(), num_blocks=32, block_size=4,
                     tenants={"acme": {"weight": 1.0}})
        srv.submit([1, 2, 3, 4, 5], max_new_tokens=3, tenant="acme")
        srv.run_until_idle()
        sig = srv.capacity_signal
        assert sig is not None and sig["num_blocks"] == 32
        assert sig["free_blocks"] + sig["used_blocks"] == 32
        assert srv.scheduler.capacity_signal is sig
        # pool gauges: cataloged, and the per-tenant amortized series
        # sum to the used-bytes gauge (the identity, live)
        for rec in telemetry.snapshot():
            telemetry.validate_record(rec)
            assert rec["name"] in telemetry.KNOWN_METRICS, rec["name"]
        assert telemetry.get("serve.pool_used_bytes") is not None
        used = telemetry.get("serve.pool_used_bytes").value
        total = 0.0
        for labels, m in telemetry.series("serve.pool_bytes"):
            if labels.get("kind") == "amortized":
                total += m.value
        assert total == pytest.approx(used)
        # a drained tenant's gauge reads 0, not a frozen stale value
        am = telemetry.get("serve.pool_bytes", tenant="acme",
                           kind="amortized")
        assert am is not None and am.value == 0.0
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# the jax-less report tool
# ---------------------------------------------------------------------------
def _run_capacity_report(args):
    code = ("import sys, runpy; "
            "sys.modules['jax'] = None; "
            "sys.modules['tpu_mx'] = None; "
            f"sys.argv = ['capacity_report.py'] + {args!r}; "
            "runpy.run_path("
            f"{os.path.join(REPO, 'tools', 'capacity_report.py')!r}, "
            "run_name='__main__')")
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)


@pytest.mark.slow
def test_capacity_report_validate_rc_contract(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUMX_PREFIX_SHARING", "1")
    jsonl = tmp_path / "t.jsonl"
    prefix = str(tmp_path / "sv")
    srv = Server(tiny(), num_blocks=6, block_size=4, max_batch=4,
                 max_tokens=10 ** 6, blackbox=prefix)
    for i in range(5):
        srv.submit([1, 2, 3, 4, 5, 6, 7], max_new_tokens=8)
    srv.run_until_idle()
    telemetry.flush(path=str(jsonl))
    srv.engine.cache.flush_forensics()
    forensics = prefix + "-capacity.json"
    assert os.path.exists(forensics)

    run = _run_capacity_report([str(jsonl), "--forensics", forensics,
                               "--validate"])
    assert run.returncode == 0, run.stderr + run.stdout
    for marker in ("Ledger timeline", "Per-tenant pool attribution",
                   "Exhaustion forensics", "schema OK"):
        assert marker in run.stdout, (marker, run.stdout)

    # rc 1: a forensic record violating the holders-complete gate
    with open(forensics, encoding="utf-8") as f:
        doc = json.load(f)
    doc["records"][0]["holders"] = doc["records"][0]["holders"][:-1]
    bad = tmp_path / "bad-capacity.json"
    bad.write_text(json.dumps(doc))
    run = _run_capacity_report([str(jsonl), "--forensics", str(bad),
                               "--validate"])
    assert run.returncode == 1
    assert "100% of live holders" in run.stderr

    # rc 2: unreadable input
    run = _run_capacity_report([str(tmp_path / "missing.jsonl"),
                               "--validate"])
    assert run.returncode == 2


def test_slo_report_renders_no_data_sentinel_as_na(tmp_path):
    """Satellite (ISSUE 14): the -1 NO_DATA gauges render as n/a, never
    as a negative estimate/attainment in the monitor-gauge section."""
    jsonl = tmp_path / "t.jsonl"
    recs = [
        {"name": "serve.slo_estimate_seconds", "type": "gauge",
         "value": -1.0, "ts": 1.0, "labels": {"slo": "itl_p99"}},
        {"name": "serve.slo_attainment", "type": "gauge", "value": -1.0,
         "ts": 1.0, "labels": {"slo": "itl_p99", "window": "10s"}},
        {"name": "serve.slo_burn_rate", "type": "gauge", "value": 0.25,
         "ts": 1.0, "labels": {"slo": "itl_p99", "window": "10s"}},
    ]
    jsonl.write_text("".join(json.dumps(r) + "\n" for r in recs))
    run = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "slo_report.py"),
         str(jsonl), "--validate"],
        capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, run.stderr + run.stdout
    gauges = [ln for ln in run.stdout.splitlines()
              if "serve.slo_" in ln]
    nas = [ln for ln in gauges if "n/a" in ln]
    assert len(nas) == 2, gauges                 # the two -1 sentinels
    assert not any(" -1" in ln for ln in gauges), gauges
    assert any("0.25" in ln for ln in gauges), gauges   # real data kept
