"""Native C++ data pipeline tests (reference analog: the C++ iterator tests
plus tests/python/unittest/test_io.py).  Oracle: the Python ImageRecordIter
decode path (same libjpeg family underneath)."""
import os

import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import recordio

cv2 = pytest.importorskip("cv2")


@pytest.fixture(scope="module")
def rec_file(tmp_path_factory):
    """24 small JPEG records, labels = index, various sizes."""
    d = tmp_path_factory.mktemp("rec")
    path = str(d / "data.rec")
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    imgs = []
    for i in range(24):
        h, w = rng.randint(40, 90), rng.randint(40, 90)
        img = rng.randint(0, 255, (h, w, 3), np.uint8)
        header = recordio.IRHeader(0, float(i), i, 0)
        rec.write(recordio.pack_img(header, img, quality=95))
        imgs.append(img)
    rec.close()
    return path, imgs


def _pipe(path, **kw):
    from tpu_mx.lib.recordio_cpp import NativeImagePipe
    args = dict(batch_size=8, data_shape=(3, 32, 32), preprocess_threads=3,
                prefetch_buffer=3)
    args.update(kw)
    return NativeImagePipe(path, **args)


def test_native_builds_and_counts(rec_file):
    path, imgs = rec_file
    p = _pipe(path)
    assert len(p) == 24
    p.close()


def test_native_batches_and_labels(rec_file):
    path, _ = rec_file
    p = _pipe(path)
    seen_labels = []
    batches = 0
    while True:
        out = p.next_batch()
        if out is None:
            break
        data, label = out
        assert data.shape == (8, 3, 32, 32)
        assert data.dtype == np.float32
        assert np.isfinite(data).all()
        seen_labels.extend(label.tolist())
        batches += 1
    assert batches == 3
    assert sorted(int(l) for l in seen_labels) == list(range(24))
    p.close()


def test_native_epoch_reset_and_shuffle(rec_file):
    path, _ = rec_file
    p = _pipe(path, shuffle=True, seed=7)
    def epoch_labels():
        out, labels = p.next_batch(), []
        while out is not None:
            labels.extend(out[1].tolist())
            out = p.next_batch()
        return labels
    e1 = epoch_labels()
    p.reset()
    e2 = epoch_labels()
    assert sorted(e1) == sorted(e2) == list(map(float, range(24)))
    assert e1 != e2  # reshuffled across epochs
    p.close()


def test_native_matches_python_decode(rec_file):
    """Center-crop, no resize: native output must closely match the Python
    cv2 pipeline (both are libjpeg decodes; only rounding may differ)."""
    path, _ = rec_file
    from tpu_mx.io import ImageRecordIter
    py_iter = ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                              batch_size=8, shuffle=False,
                              preprocess_threads=2, use_native=False)
    p = _pipe(path)
    nb = py_iter.next()
    py_data = nb.data[0].asnumpy()
    nat_data, nat_label = p.next_batch()
    assert nat_data.shape == py_data.shape
    # same labels, same order
    np.testing.assert_array_equal(nat_label,
                                  nb.label[0].asnumpy().astype(np.float32))
    diff = np.abs(nat_data - py_data)
    assert np.mean(diff) < 2.0 and np.median(diff) < 1.5, \
        f"decode divergence: mean {diff.mean()}, max {diff.max()}"
    p.close()


def test_native_mean_std_normalization(rec_file):
    path, _ = rec_file
    p0 = _pipe(path)
    p1 = _pipe(path, mean=(10.0, 20.0, 30.0), std=(2.0, 4.0, 8.0))
    d0, _ = p0.next_batch()
    d1, _ = p1.next_batch()
    for c, (m, s) in enumerate([(10, 2), (20, 4), (30, 8)]):
        np.testing.assert_allclose(d1[:, c], (d0[:, c] - m) / s,
                                   rtol=1e-5, atol=1e-5)
    p0.close()
    p1.close()


def test_native_deterministic_augment(rec_file):
    path, _ = rec_file
    a = _pipe(path, rand_crop=True, rand_mirror=True, seed=42,
              data_shape=(3, 24, 24))
    b = _pipe(path, rand_crop=True, rand_mirror=True, seed=42,
              data_shape=(3, 24, 24))
    da, la = a.next_batch()
    db, lb = b.next_batch()
    np.testing.assert_array_equal(da, db)
    np.testing.assert_array_equal(la, lb)
    a.close()
    b.close()


def test_native_bad_file(tmp_path):
    bad = tmp_path / "bad.rec"
    bad.write_bytes(b"not a recordio file at all")
    from tpu_mx.lib.recordio_cpp import NativeImagePipe
    with pytest.raises(IOError):
        NativeImagePipe(str(bad), batch_size=2, data_shape=(3, 8, 8))


def test_runtime_feature_flag():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPP_RECORDIO")


def test_image_record_iter_native_default(rec_file):
    """ImageRecordIter picks the native pipeline automatically and yields
    the same epoch as the Python path."""
    path, _ = rec_file
    from tpu_mx.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                         batch_size=8)
    assert it._native is not None
    labels = []
    for batch in it:
        assert batch.data[0].shape == (8, 3, 32, 32)
        labels.extend(batch.label[0].asnumpy().tolist())
        assert batch.pad == 0  # 24 % 8 == 0
    assert sorted(int(l) for l in labels) == list(range(24))
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter_native_pad(rec_file):
    path, _ = rec_file
    from tpu_mx.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                         batch_size=10)
    pads = [b.pad for b in it]
    assert pads == [0, 0, 6]  # 24 records, batch 10 -> last pad 6


def test_native_reset_recovers_from_bad_record(tmp_path):
    """A corrupt record fails the epoch; reset() must un-poison the pipe."""
    import struct
    path = str(tmp_path / "mixed.rec")
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    img = rng.randint(0, 255, (40, 40, 3), np.uint8)
    rec.write(recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img))
    # corrupt record: valid header, garbage jpeg payload
    rec.write(struct.pack("<IfQQ", 0, 2.0, 1, 0) + b"\x00" * 64)
    rec.close()
    p = _pipe(path, batch_size=2, data_shape=(3, 16, 16),
              preprocess_threads=1)
    with pytest.raises(IOError):
        p.next_batch()
    p.reset()
    with pytest.raises(IOError):  # same data still fails, but freshly
        p.next_batch()
    p.close()


def test_use_native_true_raises_on_png(tmp_path):
    path = str(tmp_path / "png.rec")
    rec = recordio.MXRecordIO(path, "w")
    img = np.zeros((20, 20, 3), np.uint8)
    rec.write(recordio.pack_img(recordio.IRHeader(0, 0.0, 0, 0), img,
                                img_fmt=".png"))
    rec.close()
    from tpu_mx.io import ImageRecordIter
    from tpu_mx.base import MXNetError
    with pytest.raises(MXNetError, match="use_native"):
        ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                        batch_size=1, use_native=True)


def test_native_split_record_roundtrip(tmp_path):
    """Records whose payload embeds the RecordIO magic are split by the
    dmlc writer; the native scanner must rejoin them with the magic bytes
    (recordio.py MXRecordIO.read does _MAGIC_BYTES.join)."""
    import struct
    path = str(tmp_path / "split.rec")
    magic = struct.pack("<I", 0xCED7230A)
    img = np.random.RandomState(3).randint(0, 255, (40, 40, 3), np.uint8)
    payload = recordio.pack_img(recordio.IRHeader(0, 7.0, 0, 0), img)
    # hand-write a dmlc-style split record: parts joined by magic
    cut = len(payload) // 2
    parts = [payload[:cut], payload[cut:]]
    joined = (magic + b"".join(parts[0:1]) + magic + parts[1])
    with open(path, "wb") as f:
        def emit(cflag, data):
            lrec = (cflag << 29) | len(data)
            f.write(magic + struct.pack("<I", lrec) + data)
            f.write(b"\x00" * ((4 - len(data) % 4) % 4))
        emit(1, parts[0])
        emit(3, parts[1])
    # python reader oracle
    r = recordio.MXRecordIO(path, "r")
    raw = r.read()
    r.close()
    assert raw == parts[0] + magic + parts[1]
    # the native pipe must decode it identically IF the rejoined payload is
    # a valid record; here the magic falls inside the jpeg stream, so just
    # check the pipe parses the file into exactly one record
    from tpu_mx.lib.recordio_cpp import NativeImagePipe
    p = NativeImagePipe(path, batch_size=1, data_shape=(3, 16, 16),
                        preprocess_threads=1)
    assert len(p) == 1
    p.close()


def test_sparse_dot_transpose_b():
    from tpu_mx.ndarray import sparse
    from tpu_mx import nd
    dense = np.zeros((2, 3), np.float32)
    dense[0, 1], dense[1, 2] = 2.0, 3.0
    csr = sparse.csr_matrix(dense)
    rhs = np.random.RandomState(0).rand(4, 3).astype(np.float32)
    out = sparse.dot(csr, nd.array(rhs), transpose_b=True)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs.T, rtol=1e-5)
    # dense · csr with transpose_a
    lhs = np.random.RandomState(1).rand(2, 5).astype(np.float32)
    out2 = sparse.dot(nd.array(lhs), csr, transpose_a=True)
    np.testing.assert_allclose(out2.asnumpy(), lhs.T @ dense, rtol=1e-5)


def test_libsvm_sparse_labels(tmp_path):
    d = tmp_path / "d.libsvm"
    l = tmp_path / "l.libsvm"
    d.write_text("0 0:1.0\n0 1:2.0\n")
    l.write_text("0 0:1.0 2:5.0\n0 1:3.0\n")
    from tpu_mx.io import LibSVMIter
    it = LibSVMIter(data_libsvm=str(d), data_shape=(3,), batch_size=2,
                    label_libsvm=str(l), label_shape=(3,))
    assert it.getpad() == 0  # before first batch: must not crash
    b = next(iter(it))
    np.testing.assert_array_equal(
        b.label[0].asnumpy(),
        np.array([[1.0, 0.0, 5.0], [0.0, 3.0, 0.0]], np.float32))


def test_native_im2rec_roundtrip(tmp_path):
    """The C++ packer's .rec/.idx must read back through the PYTHON
    recordio reader with intact headers/labels/ids and decodable images
    (format interchangeability with tools/im2rec.py, REF:tools/im2rec.cc)."""
    import cv2
    from tpu_mx import recordio
    from tpu_mx.lib.recordio_cpp import native_im2rec

    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    rng = np.random.RandomState(0)
    lines = []
    for i in range(6):
        img = (rng.rand(40 + i, 60, 3) * 255).astype(np.uint8)
        cv2.imwrite(str(imgdir / f"im{i}.jpg"),
                    img, [cv2.IMWRITE_JPEG_QUALITY, 95])
        # multi-label rows for i >= 3
        labels = [float(i)] if i < 3 else [float(i), float(i) * 0.5]
        lines.append("\t".join([str(i)] + [f"{v}" for v in labels]
                               + [f"im{i}.jpg"]))
    lst = tmp_path / "d.lst"
    lst.write_text("\n".join(lines) + "\n")

    n = native_im2rec(str(lst), str(imgdir), str(tmp_path / "d"),
                      resize=32, quality=90, num_thread=3)
    assert n == 6
    idx_lines = (tmp_path / "d.idx").read_text().strip().splitlines()
    assert len(idx_lines) == 6 and idx_lines[0].split("\t")[1] == "0"

    rec = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"),
                                     str(tmp_path / "d.rec"), "r")
    for i in range(6):
        header, img_bytes = recordio.unpack(rec.read_idx(i))
        assert header.id == i
        if i < 3:
            assert header.flag == 0 and abs(header.label - i) < 1e-6
        else:
            assert header.flag == 2
            np.testing.assert_allclose(header.label, [i, i * 0.5])
        arr = cv2.imdecode(np.frombuffer(img_bytes, np.uint8),
                           cv2.IMREAD_COLOR)
        assert arr is not None and min(arr.shape[:2]) == 32  # shorter side

    # and the native PIPE must accept the native-packed file too
    from tpu_mx.lib.recordio_cpp import NativeImagePipe
    pipe = NativeImagePipe(str(tmp_path / "d.rec"), batch_size=2,
                           data_shape=(3, 24, 24), resize=24,
                           preprocess_threads=2)
    data, label = pipe.next_batch()
    assert data.shape == (2, 3, 24, 24)


def test_native_im2rec_skips_bad_and_matches_upscale_semantics(tmp_path):
    """Missing files and non-JPEGs are SKIPPED (not fatal, matching the
    Python packer), and small images are stored unresized without
    upscale=True."""
    import cv2
    from tpu_mx import recordio
    from tpu_mx.lib.recordio_cpp import native_im2rec

    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    rng = np.random.RandomState(0)
    small = (rng.rand(20, 30, 3) * 255).astype(np.uint8)
    cv2.imwrite(str(imgdir / "small.jpg"), small)
    big = (rng.rand(100, 120, 3) * 255).astype(np.uint8)
    cv2.imwrite(str(imgdir / "big.jpg"), big)
    (imgdir / "fake.png").write_bytes(b"\x89PNG\r\n not a jpeg")
    lst = tmp_path / "d.lst"
    lst.write_text("0\t0.0\tsmall.jpg\n"
                   "1\t1.0\tmissing.jpg\n"
                   "2\t2.0\tfake.png\n"
                   "3\t3.0\tbig.jpg\n")
    n = native_im2rec(str(lst), str(imgdir), str(tmp_path / "d"), resize=64)
    assert n == 2  # small + big packed; missing + png skipped
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"),
                                     str(tmp_path / "d.rec"), "r")
    h0, img0 = recordio.unpack(rec.read_idx(0))
    a0 = cv2.imdecode(np.frombuffer(img0, np.uint8), cv2.IMREAD_COLOR)
    assert a0.shape[:2] == (20, 30)  # NOT upscaled to 64
    h3, img3 = recordio.unpack(rec.read_idx(3))
    a3 = cv2.imdecode(np.frombuffer(img3, np.uint8), cv2.IMREAD_COLOR)
    assert min(a3.shape[:2]) == 64   # downscaled
    # upscale=True does enlarge
    n = native_im2rec(str(lst), str(imgdir), str(tmp_path / "u"), resize=64,
                      upscale=True)
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "u.idx"),
                                     str(tmp_path / "u.rec"), "r")
    hu, imgu = recordio.unpack(rec.read_idx(0))
    au = cv2.imdecode(np.frombuffer(imgu, np.uint8), cv2.IMREAD_COLOR)
    assert min(au.shape[:2]) == 64


def test_native_im2rec_dct_downscale_still_resizes(tmp_path):
    """An image whose short side is an exact power-of-two multiple of the
    target (128 -> 64) must STILL be written at short side 64: the
    downscale-only decision uses original dims, not the DCT-downscaled
    decode dims."""
    import cv2
    from tpu_mx import recordio
    from tpu_mx.lib.recordio_cpp import native_im2rec
    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    img = (np.random.RandomState(0).rand(128, 192, 3) * 255).astype(np.uint8)
    cv2.imwrite(str(imgdir / "a.jpg"), img)
    (tmp_path / "d.lst").write_text("0\t1.0\ta.jpg\n")
    n = native_im2rec(str(tmp_path / "d.lst"), str(imgdir),
                      str(tmp_path / "d"), resize=64)
    assert n == 1
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"),
                                     str(tmp_path / "d.rec"), "r")
    _h, img_bytes = recordio.unpack(rec.read_idx(0))
    a = cv2.imdecode(np.frombuffer(img_bytes, np.uint8), cv2.IMREAD_COLOR)
    assert min(a.shape[:2]) == 64, a.shape


def test_native_packed_rec_through_image_record_iter(tmp_path):
    """A --native-packed .rec feeds mx.io.ImageRecordIter end-to-end (the
    CLI drive's assertion, kept as a regression test)."""
    import cv2
    import tpu_mx as mx
    from tpu_mx.lib.recordio_cpp import native_im2rec
    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    rng = np.random.RandomState(2)
    lines = []
    for i in range(6):
        img = (rng.rand(40, 50, 3) * 255).astype(np.uint8)
        cv2.imwrite(str(imgdir / f"i{i}.jpg"), img)
        lines.append(f"{i}\t{float(i % 2)}\ti{i}.jpg")
    (tmp_path / "d.lst").write_text("\n".join(lines) + "\n")
    n = native_im2rec(str(tmp_path / "d.lst"), str(imgdir),
                      str(tmp_path / "d"), resize=32)
    assert n == 6
    it = mx.io.ImageRecordIter(path_imgrec=str(tmp_path / "d.rec"),
                               data_shape=(3, 28, 28), batch_size=3,
                               resize=28)
    batch = next(iter(it))
    assert batch.data[0].shape == (3, 3, 28, 28)
    assert batch.label[0].shape == (3,)


# ---------------------------------------------------------------------------
# native detection pipeline (VERDICT r3 ask#4;
# REF:src/io/iter_image_det_recordio.cc + image_det_aug_default.cc)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def det_rec_file(tmp_path_factory):
    """16 JPEG records with [cls,x1,y1,x2,y2]*m labels (m in 1..3)."""
    d = tmp_path_factory.mktemp("detrec")
    path = str(d / "det.rec")
    rng = np.random.RandomState(5)
    # indexed so the Python ImageDetIter (MXIndexedRecordIO) can read too
    rec = recordio.MXIndexedRecordIO(str(d / "det.idx"), path, "w")
    all_labels = []
    for i in range(16):
        h, w = rng.randint(50, 100), rng.randint(50, 100)
        img = rng.randint(0, 255, (h, w, 3), np.uint8)
        m = rng.randint(1, 4)
        rows = []
        for _ in range(m):
            x1, y1 = rng.uniform(0, 0.5, 2)
            bw, bh = rng.uniform(0.2, 0.45, 2)
            rows.append([float(rng.randint(0, 5)), x1, y1,
                         min(1.0, x1 + bw), min(1.0, y1 + bh)])
        label = np.asarray(rows, np.float32).ravel()
        header = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=95))
        all_labels.append(np.asarray(rows, np.float32))
    rec.close()
    return path, all_labels


def _det_pipe(path, **kw):
    from tpu_mx.lib.recordio_cpp import NativeDetPipe
    args = dict(batch_size=4, data_shape=(3, 48, 48), max_objects=3,
                preprocess_threads=3, prefetch_buffer=3)
    args.update(kw)
    return NativeDetPipe(path, **args)


def test_det_pipe_shapes_and_padding(det_rec_file):
    path, labels = det_rec_file
    p = _det_pipe(path)
    seen = 0
    while True:
        out = p.next_batch()
        if out is None:
            break
        data, label = out
        assert data.shape == (4, 3, 48, 48)
        assert label.shape == (4, 3, 5)
        assert np.isfinite(data).all()
        for row_block in label:
            valid = row_block[:, 0] >= 0
            # all valid rows precede padding, coordinates normalized
            assert (row_block[~valid] == -1).all()
            assert (row_block[valid][:, 1:] >= 0).all()
            assert (row_block[valid][:, 1:] <= 1).all()
        seen += 1
    assert seen == 4
    p.close()


def test_det_pipe_boxes_match_python_iterator(det_rec_file, tmp_path):
    """No-augment path: native boxes must equal the Python ImageDetIter's
    exactly (force-resize keeps normalized boxes); pixels close on smooth
    images (random-noise JPEGs are a resampler-divergence worst case —
    cv2's fixed-point bilinear vs the native float bilinear legitimately
    differ there; see test_native_matches_python_decode for the
    decode-only tight bound)."""
    path, _ = det_rec_file
    # smooth synthetic images: low-frequency gradients
    spath = str(tmp_path / "smooth.rec")
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "smooth.idx"), spath,
                                     "w")
    rng = np.random.RandomState(9)
    for i in range(16):
        h, w = rng.randint(50, 100), rng.randint(50, 100)
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        img = np.stack([127 + 100 * np.sin(yy / h * 3 + c) *
                        np.cos(xx / w * 2 + c) for c in range(3)],
                       axis=-1).clip(0, 255).astype(np.uint8)
        label = np.asarray([1.0, 0.2, 0.2, 0.7, 0.7], np.float32)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, quality=95))
    rec.close()
    path = spath
    p = _det_pipe(path, batch_size=16, max_objects=3)
    data_n, label_n = p.next_batch()
    p.close()

    from tpu_mx.image.detection import (DetBorrowAug, DetForceResizeAug,
                                        ImageDetIter)
    from tpu_mx.image.image import CastAug
    # like-for-like resampling: the Python default is bicubic
    # (inter_method=2); the native pipeline is bilinear — pin bilinear
    it = ImageDetIter(16, (3, 48, 48), path_imgrec=path, max_objects=3,
                      aug_list=[DetForceResizeAug((48, 48), interp=1),
                                DetBorrowAug(CastAug())])
    batch = it.next()
    data_p = batch.data[0].asnumpy()
    label_p = batch.label[0].asnumpy()

    np.testing.assert_allclose(label_n, label_p, atol=1e-6)
    # uint8 bilinear resamplers: small per-pixel differences allowed
    assert np.mean(np.abs(data_n - data_p)) < 3.0
    assert np.max(np.abs(data_n - data_p)) < 64.0


def test_det_pipe_deterministic_augment(det_rec_file):
    path, _ = det_rec_file
    kw = dict(rand_crop=True, rand_mirror=True, seed=11, batch_size=16)
    p1 = _det_pipe(path, **kw)
    d1, l1 = p1.next_batch()
    p1.close()
    p2 = _det_pipe(path, **kw)
    d2, l2 = p2.next_batch()
    p2.close()
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(l1, l2)
    # a different seed actually changes the augmentation draws
    p3 = _det_pipe(path, rand_crop=True, rand_mirror=True, seed=12,
                   batch_size=16)
    d3, _ = p3.next_batch()
    p3.close()
    assert np.abs(d1 - d3).max() > 0


def test_det_pipe_crop_keeps_covered_boxes(det_rec_file):
    """Cropped samples keep >=1 box, classes drawn from the original set,
    coordinates valid — the IoU-constrained-crop contract."""
    path, labels = det_rec_file
    p = _det_pipe(path, rand_crop=True, seed=3, batch_size=16,
                  min_object_covered=0.3)
    _, label = p.next_batch()
    p.close()
    for i in range(16):
        rows = label[i]
        valid = rows[rows[:, 0] >= 0]
        assert len(valid) >= 1  # the accepted crop covered >= one box
        orig_classes = set(labels[i][:, 0].tolist())
        assert set(valid[:, 0].tolist()) <= orig_classes
        assert (valid[:, 3] > valid[:, 1]).all()
        assert (valid[:, 4] > valid[:, 2]).all()


def test_det_pipe_mirror_flips_pixels_and_boxes(det_rec_file):
    path, _ = det_rec_file
    base = _det_pipe(path, batch_size=16, seed=21)
    d0, l0 = base.next_batch()
    base.close()
    mir = _det_pipe(path, batch_size=16, rand_mirror=True, seed=21)
    d1, l1 = mir.next_batch()
    mir.close()
    flipped = unchanged = 0
    for i in range(16):
        if np.array_equal(d1[i], d0[i]):
            unchanged += 1
            np.testing.assert_array_equal(l1[i], l0[i])
        else:
            np.testing.assert_array_equal(d1[i], d0[i][:, :, ::-1])
            flipped += 1
            v = l0[i][:, 0] >= 0
            np.testing.assert_allclose(l1[i][v, 1], 1.0 - l0[i][v, 3],
                                       atol=1e-6)
            np.testing.assert_allclose(l1[i][v, 3], 1.0 - l0[i][v, 1],
                                       atol=1e-6)
    assert flipped > 0 and unchanged > 0  # p=0.5 coin actually flipped


def test_image_det_record_iter_end_to_end(det_rec_file):
    path, _ = det_rec_file
    it = mx.io.ImageDetRecordIter(path, (3, 48, 48), batch_size=4)
    assert it.max_objects == 3  # header-only scan found the widest block
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (4, 3, 48, 48)
    assert batches[0].label[0].shape == (4, 3, 5)
    it.reset()
    assert len(list(it)) == 4


@pytest.mark.slow
def test_det_native_throughput_3x_python(tmp_path):
    """VERDICT r3 ask#4 'done' bar: native det pipeline >=3x the Python
    iterator's throughput on the same records."""
    import time
    rng = np.random.RandomState(0)
    path = str(tmp_path / "perf.rec")
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "perf.idx"), path, "w")
    for i in range(64):
        img = rng.randint(0, 255, (220, 220, 3), np.uint8)
        label = np.asarray([[1.0, 0.1, 0.1, 0.8, 0.8]], np.float32).ravel()
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, quality=90))
    rec.close()

    def drain_native():
        p = _det_pipe(path, batch_size=16, data_shape=(3, 128, 128),
                      max_objects=1, rand_crop=True, rand_mirror=True,
                      preprocess_threads=4)
        n = 0
        for _ in range(2):
            while True:
                out = p.next_batch()
                if out is None:
                    break
                n += out[0].shape[0]
            p.reset()
        p.close()
        return n

    def drain_python():
        from tpu_mx.image.detection import ImageDetIter
        it = ImageDetIter(16, (3, 128, 128), path_imgrec=path,
                          max_objects=1, rand_crop=1, rand_mirror=True)
        n = 0
        for _ in range(2):
            for batch in it:
                n += batch.data[0].shape[0]
            it.reset()
        return n

    drain_native()  # warm the library/buffers outside the timed region
    t0 = time.perf_counter()
    n_native = drain_native()
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    n_python = drain_python()
    t_python = time.perf_counter() - t0
    assert n_native == n_python
    speedup = (t_python / n_python) / (t_native / n_native)
    assert speedup >= 3.0, f"native only {speedup:.2f}x python"


def test_det_pipe_corrupt_label_header_fails_gracefully(tmp_path):
    """A det record whose header flag is garbage (huge, wrapping in
    uint32 flag*4 arithmetic) must surface as a clean decode error with
    no multi-GB allocation.  The allocation side is only observable
    under an address-space cap, which can't be applied inside the pytest
    process — native/tpumx_io_test.cpp TestDetLabelBoundsOverflow does
    that (rlimit + bad_alloc, mutation-checked); this test pins the
    public-surface behavior."""
    path = str(tmp_path / "corrupt.rec")
    rec = recordio.MXRecordIO(path, "w")
    # flag = 0x40000006 = 1073741830: a true multiple of 5 whose flag*4
    # wraps to 24 in uint32 — under uint32 bounds math 24 <= the 64-byte
    # payload would pass the check; the size_t math rejects it
    assert 0x40000006 % 5 == 0 and (0x40000006 * 4) % 2 ** 32 == 24
    header = recordio.IRHeader(0x40000006, 0.0, 0, 0)
    import struct
    payload = struct.pack("<IfQQ", *header) + b"\x00" * 64
    rec.write(payload)
    rec.close()
    p = _det_pipe(path, batch_size=1, max_objects=2)
    with pytest.raises(IOError, match="decode failed"):
        p.next_batch()
    p.close()


@pytest.mark.slow
def test_native_cpp_unit_tier(tmp_path):
    """The C++ unit tier (SURVEY §4 REF:tests/cpp analog): compile and
    run native/tpumx_io_test.cpp — HashUniform determinism,
    ResizeBilinear invariants, RecordIO scan incl. corrupt magic, and
    the det label-header uint32-overflow regression, all at the C++
    level where Python tests can't reach."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "native", "tpumx_io_test.cpp")
    binary = str(tmp_path / "tpumx_io_test")
    cc = subprocess.run(["g++", "-O1", "-std=c++17", src, "-o", binary,
                         "-ljpeg", "-lpthread"], timeout=180,
                        capture_output=True, text=True)
    assert cc.returncode == 0, f"native test compile failed:\n{cc.stderr}"
    out = subprocess.run([binary], capture_output=True, text=True,
                         timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ALL PASS" in out.stdout


def test_image_det_record_iter_python_fallback(det_rec_file):
    """use_native=False path: same iterator contract (shapes, label
    layout, epoch length) through the Python augmenters."""
    path, _ = det_rec_file
    it = mx.io.ImageDetRecordIter(path, (3, 48, 48), batch_size=4,
                                  use_native=False)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (4, 3, 48, 48)
    assert batches[0].label[0].shape == (4, 3, 5)
    lab = batches[0].label[0].asnumpy()
    valid = lab[lab[:, :, 0] >= 0]
    assert (valid[:, 1:] >= 0).all() and (valid[:, 1:] <= 1).all()
    it.reset()
    assert len(list(it)) == 4


def test_native_u8_nhwc_matches_f32_nchw(rec_file):
    """The uint8/NHWC TPU-feed variant must make the SAME augment
    decisions (counter-hash PRNG) and, normalized downstream, match the
    f32/NCHW output to float rounding."""
    path, _ = rec_file
    mean, std = (10.0, 20.0, 30.0), (2.0, 3.0, 4.0)
    kw = dict(batch_size=8, data_shape=(3, 32, 32), resize=40,
              rand_crop=True, rand_mirror=True, mean=mean, std=std,
              preprocess_threads=2, shuffle=True, seed=5)
    p32 = _pipe(path, **kw)
    pu8 = _pipe(path, output_dtype="uint8", output_layout="NHWC", **kw)
    d1, l1 = p32.next_batch()
    d2, l2 = pu8.next_batch()
    assert d1.dtype == np.float32 and d1.shape == (8, 3, 32, 32)
    assert d2.dtype == np.uint8 and d2.shape == (8, 32, 32, 3)
    np.testing.assert_array_equal(l1, l2)
    norm = (d2.astype(np.float32) - np.asarray(mean, np.float32)) \
        / np.asarray(std, np.float32)
    np.testing.assert_allclose(d1, norm.transpose(0, 3, 1, 2), atol=1e-5)
    p32.close()
    pu8.close()


def test_image_record_iter_output_flags(rec_file):
    """mx.io.ImageRecordIter surfaces the TPU-feed flags on both the
    native and the Python-fallback paths, with matching provide_data."""
    path, _ = rec_file
    for use_native in (True, False):
        it = mx.io.ImageRecordIter(
            path_imgrec=path, data_shape=(3, 32, 32), batch_size=8,
            resize=40, use_native=use_native, output_dtype="uint8",
            output_layout="NHWC", seed=3)
        assert it.provide_data[0].shape == (8, 32, 32, 3)
        b = it.next()
        arr = b.data[0].asnumpy()
        assert arr.shape == (8, 32, 32, 3)
        assert arr.dtype == np.uint8 or arr.max() > 1.5  # raw pixel range
        # raw pixels: no normalization applied
        assert arr.min() >= 0 and arr.max() <= 255


def test_device_prefetch_iter_normalizes_on_device(rec_file):
    """DevicePrefetchIter(normalize=...) applied to a uint8 NHWC feed
    must equal the host-normalized float iterator output."""
    path, _ = rec_file
    mean, std = (10.0, 20.0, 30.0), (2.0, 3.0, 4.0)
    common = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=8,
                  resize=40, seed=11)
    it_f32 = mx.io.ImageRecordIter(
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        std_r=std[0], std_g=std[1], std_b=std[2], **common)
    it_u8 = mx.io.DevicePrefetchIter(
        mx.io.ImageRecordIter(output_dtype="uint8", output_layout="NHWC",
                              **common),
        normalize=(mean, std), normalize_axis=-1)
    b1 = it_f32.next()
    b2 = it_u8.next()
    a1 = b1.data[0].asnumpy()                      # (B, C, H, W) normalized
    a2 = b2.data[0].asnumpy().transpose(0, 3, 1, 2)
    np.testing.assert_allclose(a1, a2, atol=1e-5)
    # labels untouched by normalize
    np.testing.assert_array_equal(b1.label[0].asnumpy(),
                                  b2.label[0].asnumpy())


def test_det_pipe_u8_nhwc_matches_f32_nchw(det_rec_file):
    """Det pipe TPU-feed variant: same counter-hash augment decisions, so
    u8/NHWC normalized downstream must match f32/NCHW, boxes identical."""
    path, _ = det_rec_file
    mean, std = (5.0, 6.0, 7.0), (2.0, 2.5, 3.0)
    kw = dict(rand_crop=True, rand_mirror=True, mean=mean, std=std,
              shuffle=True, seed=9)
    p32 = _det_pipe(path, **kw)
    pu8 = _det_pipe(path, output_dtype="uint8", output_layout="NHWC", **kw)
    d1, l1 = p32.next_batch()
    d2, l2 = pu8.next_batch()
    assert d1.shape == (4, 3, 48, 48) and d1.dtype == np.float32
    assert d2.shape == (4, 48, 48, 3) and d2.dtype == np.uint8
    np.testing.assert_array_equal(l1, l2)  # boxes bit-identical
    norm = (d2.astype(np.float32) - np.asarray(mean, np.float32)) \
        / np.asarray(std, np.float32)
    np.testing.assert_allclose(d1, norm.transpose(0, 3, 1, 2), atol=1e-5)
    p32.close()
    pu8.close()


def test_image_det_record_iter_u8_nhwc(det_rec_file):
    """mx.io.ImageDetRecordIter carries the TPU-feed flags (native-only;
    the variants must refuse the Python fallback rather than silently
    change contract)."""
    path, _ = det_rec_file
    it = mx.io.ImageDetRecordIter(path, (3, 48, 48), batch_size=4,
                                  output_dtype="uint8",
                                  output_layout="NHWC")
    assert it.provide_data[0].shape == (4, 48, 48, 3)
    b = it.next()
    arr = b.data[0].asnumpy()
    assert arr.shape == (4, 48, 48, 3) and arr.min() >= 0 and arr.max() <= 255
    assert b.label[0].shape == (4, it.max_objects, 5)
    with pytest.raises(Exception):
        mx.io.ImageDetRecordIter(path, (3, 48, 48), batch_size=4,
                                 output_dtype="uint8", use_native=False)


def test_device_prefetch_normalize_nchw_axis(rec_file):
    """The u8/NCHW + normalize_axis=1 combination (the SSD example's
    feed) must equal host-side f32 normalization too."""
    path, _ = rec_file
    mean, std = (9.0, 19.0, 29.0), (2.0, 4.0, 8.0)
    common = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=8,
                  resize=40, seed=13)
    it_f32 = mx.io.ImageRecordIter(
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        std_r=std[0], std_g=std[1], std_b=std[2], **common)
    it_u8 = mx.io.DevicePrefetchIter(
        mx.io.ImageRecordIter(output_dtype="uint8", **common),
        normalize=(mean, std), normalize_axis=1)
    a1 = it_f32.next().data[0].asnumpy()
    a2 = it_u8.next().data[0].asnumpy()
    np.testing.assert_allclose(a1, a2, atol=1e-5)
