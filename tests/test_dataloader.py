"""gluon.data.DataLoader: sequential, threaded, and process/shm worker
paths must deliver identical, ordered batches (the reference's
tests/python/unittest/test_gluon_data.py territory)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.gluon.data import ArrayDataset, DataLoader, SimpleDataset


class _SquareDataset:
    """Pure-Python transform — the GIL-holding case process workers exist
    for."""

    def __init__(self, n):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        x = np.full((3,), i, np.float32)
        return x * x, np.float32(i)


def _collect(loader):
    out = []
    for batch in loader:
        data, label = batch
        out.append((np.asarray(data._data), np.asarray(label._data)))
    return out


@pytest.mark.parametrize("kwargs", [
    dict(num_workers=0),
    dict(num_workers=2),                      # threads
    dict(num_workers=2, thread_pool=False),   # processes + shm
])
def test_dataloader_paths_identical(kwargs):
    ds = _SquareDataset(23)
    ref = _collect(DataLoader(ds, batch_size=5, num_workers=0))
    got = _collect(DataLoader(ds, batch_size=5, **kwargs))
    assert len(ref) == len(got) == 5  # 23/5 -> keep: 4 full + 1 of 3
    assert got[-1][0].shape == (3, 3)
    for (rd, rl), (gd, gl) in zip(ref, got):
        np.testing.assert_array_equal(rd, gd)
        np.testing.assert_array_equal(rl, gl)


def test_dataloader_process_workers_single_array():
    ds = SimpleDataset([np.full((2,), i, np.float32) for i in range(8)])
    batches = list(DataLoader(ds, batch_size=4, num_workers=2,
                              thread_pool=False))
    assert len(batches) == 2
    np.testing.assert_array_equal(np.asarray(batches[0]._data)[:, 0],
                                  [0, 1, 2, 3])


class _FailingDataset:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.zeros((2,), np.float32)


@pytest.mark.parametrize("kwargs", [
    dict(num_workers=2),
    dict(num_workers=2, thread_pool=False),
])
def test_dataloader_worker_error_propagates(kwargs):
    loader = DataLoader(_FailingDataset(), batch_size=4, **kwargs)
    with pytest.raises((ValueError, RuntimeError), match="boom at 5"):
        list(loader)


def test_dataloader_shuffle_covers_dataset():
    ds = ArrayDataset(nd.array(np.arange(20, dtype=np.float32)[:, None]),
                      nd.array(np.arange(20, dtype=np.float32)))
    seen = []
    for data, label in DataLoader(ds, batch_size=4, shuffle=True,
                                  num_workers=2, thread_pool=False):
        seen.extend(np.asarray(label._data).ravel().tolist())
    assert sorted(seen) == list(range(20))


def test_dataloader_process_early_close_unlinks_shm():
    """Breaking out of the epoch must not leak /dev/shm segments (the
    prefetch window's unconsumed batches get unlinked on generator
    close)."""
    import glob
    before = set(glob.glob("/dev/shm/psm_*"))
    ds = _SquareDataset(40)
    loader = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=False,
                        prefetch=6)
    for i, _batch in enumerate(loader):
        if i == 1:
            break  # leaves up to `prefetch` results in flight
    import gc
    gc.collect()  # close the abandoned generator -> finally block
    after = set(glob.glob("/dev/shm/psm_*"))
    assert after - before == set(), f"leaked shm: {after - before}"


def test_dataloader_process_ndarray_samples_rejected():
    ds = SimpleDataset([nd.zeros((2,)) for _ in range(4)])
    loader = DataLoader(ds, batch_size=2, num_workers=1, thread_pool=False)
    with pytest.raises(RuntimeError, match="numpy"):
        list(loader)
