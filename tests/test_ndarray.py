"""NDArray handle semantics tests (model: REF:tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import tpu_mx as mx
from tpu_mx import nd
from tpu_mx.test_utils import assert_almost_equal


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3) and a.dtype == np.float32
    assert_almost_equal(a, np.zeros((2, 3)))
    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.0)
    assert_almost_equal(c, np.full((2, 2), 7.0))
    d = nd.arange(0, 10, 2)
    assert_almost_equal(d, np.arange(0, 10, 2, dtype=np.float32))
    e = nd.array([[1, 2], [3, 4]])
    assert e.shape == (2, 2)


def test_arithmetic_broadcast():
    a = nd.array(np.arange(6).reshape(2, 3).astype(np.float32))
    b = nd.array(np.ones((1, 3), np.float32))
    assert_almost_equal(a + b, a.asnumpy() + 1)
    assert_almost_equal(a - 2.0, a.asnumpy() - 2)
    assert_almost_equal(3.0 - a, 3 - a.asnumpy())
    assert_almost_equal(a * a, a.asnumpy() ** 2)
    assert_almost_equal(a / (a + 1), a.asnumpy() / (a.asnumpy() + 1))
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(-a, -a.asnumpy())


def test_inplace_and_setitem():
    a = nd.zeros((3, 3))
    a[:] = 2.0
    assert_almost_equal(a, np.full((3, 3), 2.0))
    a += 1
    assert_almost_equal(a, np.full((3, 3), 3.0))
    a[1] = 9.0
    assert a.asnumpy()[1, 0] == 9.0
    a[0, 1] = -1.0
    assert a.asnumpy()[0, 1] == -1.0
    a[0:2, 0] = 5.0
    assert a.asnumpy()[1, 0] == 5.0
    ver = a._version
    a *= 2
    assert a._version > ver


def test_indexing_slicing():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(a[1], x[1])
    assert_almost_equal(a[:, 1], x[:, 1])
    assert_almost_equal(a[1, 2, 3], x[1, 2, 3])
    assert_almost_equal(a[:, :, ::2], x[:, :, ::2])
    idx = nd.array(np.array([0, 1]), dtype="int32")
    assert_almost_equal(a[idx], x[[0, 1]])


def test_reshape_transpose():
    x = np.arange(12).reshape(3, 4).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(a.reshape(4, 3), x.reshape(4, 3))
    assert_almost_equal(a.reshape((2, 6)), x.reshape(2, 6))
    assert_almost_equal(nd.reshape(a, shape=(-1, 2)), x.reshape(-1, 2))
    assert_almost_equal(nd.reshape(a, shape=(0, -1)), x.reshape(3, -1))
    assert_almost_equal(a.T, x.T)
    assert_almost_equal(a.transpose(), x.T)
    assert_almost_equal(a.expand_dims(0), x[None])
    assert_almost_equal(nd.flatten(nd.array(np.ones((2, 3, 4)))), np.ones((2, 12)))


def test_reductions():
    x = np.random.rand(3, 4, 5).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(a.sum(), x.sum())
    assert_almost_equal(a.sum(axis=1), x.sum(1))
    assert_almost_equal(nd.sum(a, axis=(0, 2)), x.sum((0, 2)))
    assert_almost_equal(a.mean(axis=0, keepdims=True), x.mean(0, keepdims=True))
    assert_almost_equal(a.max(axis=2), x.max(2))
    assert_almost_equal(a.min(), x.min())
    assert_almost_equal(nd.norm(a), np.sqrt((x ** 2).sum()))
    assert int(a.argmax().asscalar()) == x.argmax()


def test_dot_batchdot():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 5).astype(np.float32)
    assert_almost_equal(nd.dot(nd.array(a), nd.array(b)), a @ b)
    assert_almost_equal(nd.dot(nd.array(a), nd.array(b.T), transpose_b=True), a @ b)
    ba = np.random.rand(2, 3, 4).astype(np.float32)
    bb = np.random.rand(2, 4, 5).astype(np.float32)
    assert_almost_equal(nd.batch_dot(nd.array(ba), nd.array(bb)), ba @ bb)


def test_concat_stack_split():
    a = np.ones((2, 3), np.float32)
    b = np.zeros((2, 3), np.float32)
    assert_almost_equal(nd.concat(nd.array(a), nd.array(b), dim=1),
                        np.concatenate([a, b], 1))
    assert_almost_equal(nd.stack(nd.array(a), nd.array(b), axis=0), np.stack([a, b]))
    parts = nd.split(nd.array(np.arange(8).reshape(2, 4).astype(np.float32)), 2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (2, 2)


def test_take_pick_onehot():
    w = np.random.rand(10, 4).astype(np.float32)
    idx = np.array([1, 5, 7])
    assert_almost_equal(nd.take(nd.array(w), nd.array(idx, dtype="int32")), w[idx])
    data = np.random.rand(3, 5).astype(np.float32)
    picks = np.array([0, 2, 4])
    assert_almost_equal(nd.pick(nd.array(data), nd.array(picks, dtype="int32"), axis=1),
                        data[np.arange(3), picks])
    oh = nd.one_hot(nd.array(np.array([0, 2]), dtype="int32"), 3)
    assert_almost_equal(oh, np.eye(3, dtype=np.float32)[[0, 2]])


def test_type_cast_and_context():
    a = nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == np.float16
    c = a.astype("int32")
    assert c.dtype == np.int32
    assert a.context.kind in ("cpu", "tpu")
    d = a.as_in_context(mx.cpu(0))
    assert d.context.kind == "cpu"


def test_copy_copyto():
    a = nd.ones((2, 2))
    b = a.copy()
    b[:] = 5
    assert a.asnumpy()[0, 0] == 1.0
    c = nd.zeros((2, 2))
    a.copyto(c)
    assert c.asnumpy()[0, 0] == 1.0


def test_save_load(tmp_path):
    a = nd.array(np.random.rand(3, 3).astype(np.float32))
    b = nd.array(np.random.rand(2,).astype(np.float32))
    f = str(tmp_path / "nds.npz")
    nd.save(f, [a, b])
    la, lb = nd.load(f)
    assert_almost_equal(la, a)
    assert_almost_equal(lb, b)
    nd.save(f, {"x": a, "y": b})
    d = nd.load(f)
    assert_almost_equal(d["x"], a)


def test_wait_and_scalar():
    a = nd.ones((2,))
    a.wait_to_read()
    nd.waitall()
    s = nd.array([3.5])
    assert float(s.asscalar()) == 3.5
    assert len(a) == 2
    with pytest.raises(ValueError):
        bool(nd.ones((2, 2)))


def test_comparison_where_clip():
    x = np.array([[1.0, -2.0], [3.0, 0.0]], np.float32)
    a = nd.array(x)
    assert_almost_equal(a > 0, (x > 0).astype(np.float32))
    assert_almost_equal(nd.where(a > 0, a, -a), np.abs(x))
    assert_almost_equal(nd.clip(a, -1, 1), np.clip(x, -1, 1))


def test_elementwise_math():
    x = np.random.rand(4, 4).astype(np.float32) + 0.5
    a = nd.array(x)
    assert_almost_equal(nd.sqrt(a), np.sqrt(x))
    assert_almost_equal(nd.exp(a), np.exp(x), rtol=1e-4)
    assert_almost_equal(nd.log(a), np.log(x))
    assert_almost_equal(nd.sigmoid(a), 1 / (1 + np.exp(-x)), rtol=1e-4)
    assert_almost_equal(nd.tanh(a), np.tanh(x), rtol=1e-4)
    assert_almost_equal(nd.relu(nd.array(x - 1)), np.maximum(x - 1, 0))
    assert_almost_equal(nd.square(a), x ** 2)
    assert_almost_equal(nd.abs(nd.array(-x)), x)
    assert_almost_equal(nd.maximum(a, 1.0), np.maximum(x, 1.0))


def test_random_ops():
    mx.random.seed(42)
    a = nd.random.uniform(0, 1, shape=(100,))
    assert 0 <= a.asnumpy().min() and a.asnumpy().max() <= 1
    mx.random.seed(42)
    b = nd.random.uniform(0, 1, shape=(100,))
    assert_almost_equal(a, b)  # deterministic under fixed seed
    c = nd.random.normal(0, 1, shape=(1000,))
    assert abs(float(c.asnumpy().mean())) < 0.2
    d = nd.random.randint(0, 10, shape=(50,))
    assert d.asnumpy().min() >= 0 and d.asnumpy().max() < 10


def test_topk_sort():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], np.float32)
    a = nd.array(x)
    v = nd.topk(a, k=2, ret_typ="value")
    assert_almost_equal(v, np.sort(x, axis=1)[:, ::-1][:, :2])
    s = nd.sort(a, axis=1)
    assert_almost_equal(s, np.sort(x, 1))
    i = nd.argsort(a, axis=1)
    assert_almost_equal(i, np.argsort(x, 1).astype(np.float32))
