"""All five reference workloads' example scripts run under --smoke with
"does it learn" assertions (the reference's trainer-level test tier,
SURVEY §4 tests/python/train; VERDICT r1 weak#4: every example in CI)."""
import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # end-to-end example smokes (~4 min together)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=900):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, script), "--smoke", *args],
        capture_output=True, text=True, env=env, timeout=timeout)
    assert out.returncode == 0, (script, out.stdout[-800:], out.stderr[-2000:])
    return out.stdout


def test_mnist_example_smoke():
    out = _run("examples/mnist/train_mnist.py", "--epochs", "2")
    assert "final accuracy" in out


def test_bert_pretrain_smoke():
    # the script itself asserts the MLM loss decreases (mean of first vs
    # last steps); rc=0 means it learned
    out = _run("examples/bert/pretrain.py")
    assert re.search(r"loss [\d.]+ -> [\d.]+", out), out[-500:]


def test_ssd_train_smoke():
    # script asserts detection loss decreases and runs the NMS detect path
    out = _run("examples/ssd/train.py")
    assert "detections:" in out, out[-500:]


def test_word_lm_smoke():
    # script asserts perplexity beats the uniform baseline
    out = _run("examples/word_lm/train.py")
    assert "final perplexity" in out, out[-500:]


def test_imagenet_example_smoke():
    out = _run("examples/image_classification/train_imagenet.py",
               "--epochs", "2")
    losses = [float(m) for m in re.findall(r"epoch \d+: loss ([\d.]+)", out)]
    assert len(losses) == 2 and losses[-1] < losses[0], out[-500:]


def test_long_context_example_smoke():
    # the script asserts the ring path engaged AND the long-range copy
    # learned (loss < 0.7x start) — SURVEY §5.7's capability end to end
    out = _run("examples/long_context/train.py")
    m = re.search(r"ring_dispatches=(\d+)", out)
    assert m and int(m.group(1)) > 0, out[-300:]


def test_estimator_example_smoke():
    out = _run("examples/estimator/train.py")
    assert "accuracy" in out and "checkpoints:" in out, out[-500:]


def test_quantization_example_smoke():
    # script asserts int8 accuracy drop <= 2% vs its trained float model
    out = _run("examples/quantization/quantize_cnn.py")
    assert "PASSED" in out and "int8    accuracy" in out, out[-500:]


def test_moe_example_smoke():
    # script asserts the MoE LM learned; also exercises the (y, aux)
    # contract and the Switch load-balance term end to end
    out = _run("examples/moe/train_moe_lm.py")
    assert re.search(r"loss [\d.]+ -> [\d.]+", out), out[-500:]
