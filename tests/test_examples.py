"""Examples must keep running (the reference's trainer-level 'does it
learn' tier, SURVEY §4 tests/python/train).  Only the fastest script runs
in CI; the rest are exercised by their own --smoke flags."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_mnist_example_smoke():
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples/mnist/train_mnist.py"),
         "--smoke", "--epochs", "2"],
        capture_output=True, text=True, env=env, timeout=500)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "final accuracy" in out.stdout
