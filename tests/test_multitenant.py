"""Multi-tenant serving: shared-prefix KV reuse + SLO-weighted fairness
(tpu_mx/serving/prefix_cache.py, tenancy.py, the refcounted allocator —
ISSUE 12).

Covers: allocator refcount invariants under a concurrent
share/cow/free hammer (double-free stays loud), copy-on-write semantics
(fork + divergent append never mutates a sharer's bits, in both storage
modes), the prefix trie (match with the final-token cap, insertion,
LRU-leaf eviction under pool pressure, exhaustion backpressure
unchanged), greedy-stream BIT-equality with sharing on vs off in both
decode arms, tenant quotas (``tenant_quota`` rejects) and
weighted-fairness admission ordering (including the SLO burn-rate
boost), preemption never corrupting a shared prefix, and the
cached-prefill attribution surface (serve.prefill ``cached``,
timeline ``cached_tokens``, tenant label on the timeline event).
"""
import os
import threading

import numpy as np
import pytest

from tpu_mx import telemetry, tracing
from tpu_mx.base import MXNetError
from tpu_mx import serving
from tpu_mx.serving import (AdmissionReject, BlockAllocator, CacheExhausted,
                            ContinuousBatchingScheduler, EngineCore,
                            PagedKVCache, Request, Server, TenantConfig,
                            TenantTable, TinyLM)
from tpu_mx.serving import tenancy
from tpu_mx.serving.slo import SLOMonitor


@pytest.fixture(autouse=True)
def _fresh_state():
    """Tracing/telemetry/tenant-label state is process-global —
    isolate every test (the label cap is first-come-first-named)."""
    tracing.reset()
    tenancy.reset_label_registry()
    yield
    tracing.reset()
    tenancy.reset_label_registry()


def tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("seed", 0)
    return TinyLM(**kw)


def shared_cache(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    return PagedKVCache(2, 2, 4, share_prefix=True, **kw)


def kv(rng, n, layers=2, heads=2, dim=4):
    k = rng.rand(layers, n, heads, dim).astype(np.float32)
    return k, (k * 0.5).astype(np.float32)


# ---------------------------------------------------------------------------
# allocator refcounts
# ---------------------------------------------------------------------------
def test_refcount_share_free_roundtrip():
    a = BlockAllocator(4)
    ids = a.alloc(2)
    a.incref(ids)                      # a sharer appears
    assert all(a.refcount(b) == 2 for b in ids)
    a.free(ids)                        # first holder leaves
    assert a.used == 2                 # blocks survive at refcount 1
    assert a.available == 2
    a.free(ids)                        # last holder leaves
    assert a.used == 0 and a.available == 4
    assert a.refcounts() == {}


def test_refcount_double_free_and_foreign_incref_are_loud():
    a = BlockAllocator(2)
    ids = a.alloc(1)
    a.free(ids)
    with pytest.raises(MXNetError):
        a.free(ids)                    # double free
    with pytest.raises(MXNetError):
        a.incref(ids)                  # resurrecting a freed block
    with pytest.raises(MXNetError):
        a.incref([99])                 # foreign id


def test_refcount_invariants_under_4_thread_hammer():
    """share/cow/free interleavings from 4 threads: counts stay exact,
    nothing leaks, nothing is freed twice silently."""
    a = BlockAllocator(64)
    # each thread's ledger: list of block ids it holds ONE reference to
    # (a block may appear in several threads' ledgers = sharing)
    owned = [[] for _ in range(4)]
    errs = []

    def worker(i, iters=400):
        rng = np.random.RandomState(100 + i)
        try:
            for _ in range(iters):
                r = rng.rand()
                if owned[i] and r < 0.35:
                    a.free([owned[i].pop()])
                elif owned[i] and r < 0.55:
                    # "share": take another reference on a block this
                    # thread already holds (fork/index shape)
                    bid = owned[i][int(rng.randint(len(owned[i])))]
                    a.incref([bid])
                    owned[i].append(bid)
                else:
                    try:
                        owned[i].extend(a.alloc(int(rng.randint(1, 4))))
                    except CacheExhausted:
                        if owned[i]:
                            a.free([owned[i].pop()])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs
    # exact accounting: per-block reference totals match the ledgers
    held = {}
    for lst in owned:
        for b in lst:
            held[b] = held.get(b, 0) + 1
    assert a.refcounts() == held
    assert a.used == len(held)
    for lst in owned:
        a.free(lst)
    assert a.used == 0 and a.refcounts() == {}


# ---------------------------------------------------------------------------
# copy-on-write
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("storage", ["host", "device"])
def test_fork_then_divergent_append_copies_on_write(storage):
    cache = PagedKVCache(2, 2, 4, block_size=4, num_blocks=32,
                         storage=storage, share_prefix=True)
    rng = np.random.RandomState(0)
    k, v = kv(rng, 6)                  # 6 tokens: 1 full + 1 partial block
    cache.prefill("p", k, v)
    cache.fork("p", "c")
    assert cache.block_table("p") == cache.block_table("c")
    before_k, before_v = cache.gather("p", 1)
    # child appends: its shared partial tail must be COW'd
    pos = cache.reserve("c")
    assert pos == 6
    assert cache.block_table("c")[-1] != cache.block_table("p")[-1]
    for layer in range(2):
        cache.write("c", layer, np.full((2, 4), 9.0, np.float32),
                    np.full((2, 4), 9.0, np.float32))
    after_k, after_v = cache.gather("p", 1)
    assert np.array_equal(before_k, after_k)       # parent bits untouched
    assert np.array_equal(before_v, after_v)
    ck, _ = cache.gather("c", 1)
    assert np.all(ck[6] == 9.0)                    # child sees its write
    assert np.array_equal(ck[:6], before_k)        # and the shared prefix
    assert cache.prefix_stats()["cow_copies"] == 1
    cache.free_sequence("p")
    cache.free_sequence("c")
    assert cache.allocator.used == 0


def test_parent_append_after_fork_also_cows():
    cache = shared_cache()
    rng = np.random.RandomState(1)
    k, v = kv(rng, 5)
    cache.prefill("p", k, v)
    cache.fork("p", "c")
    cache.reserve("p")                 # parent diverges first
    assert cache.block_table("p")[-1] != cache.block_table("c")[-1]
    # child's tail is now refcount 1 — its append writes in place
    tail = cache.block_table("c")[-1]
    cache.reserve("c")
    assert cache.block_table("c")[-1] == tail
    assert cache.prefix_stats()["cow_copies"] == 1


# ---------------------------------------------------------------------------
# prefix trie
# ---------------------------------------------------------------------------
def test_match_caps_at_final_token_and_shares_physical_blocks():
    cache = shared_cache()
    rng = np.random.RandomState(2)
    toks = list(range(8))              # exactly 2 full blocks
    k, v = kv(rng, 8)
    cache.prefill("a", k, v, tokens=toks)
    # identical prompt: only block 0 may match (block 1's end == len,
    # but the FINAL token must be computed for its logits — cap len-1)
    plan = cache.match_prefix(toks)
    assert plan is not None and plan.tokens_matched == 4
    assert plan.blocks == cache.block_table("a")[:1]
    cache.commit_prefill("b", plan, k[:, 4:], v[:, 4:], toks)
    assert cache.block_table("b")[0] == cache.block_table("a")[0]
    # longer prompt extending the template: both full blocks match
    ext = toks + [9, 9]
    plan = cache.match_prefix(ext)
    assert plan.tokens_matched == 8
    cache.abandon_plan(plan)
    # a diverging prompt matches only the common prefix
    plan = cache.match_prefix([0, 1, 2, 3, 7, 7, 7, 7, 7])
    assert plan.tokens_matched == 4
    cache.abandon_plan(plan)
    assert cache.match_prefix([5, 5, 5, 5, 5]) is None      # miss


def test_pressure_evicts_lru_index_blocks_but_backpressure_stands():
    cache = PagedKVCache(2, 2, 4, block_size=4, num_blocks=4,
                         share_prefix=True)
    rng = np.random.RandomState(3)
    k, v = kv(rng, 8)
    cache.prefill("a", k, v, tokens=list(range(8)))   # 2 blocks, indexed
    cache.free_sequence("a")           # index keeps both blocks alive
    assert cache.allocator.used == 2
    # a new 3-block prefill only fits by evicting the cached prefix
    k3, v3 = kv(rng, 12)
    cache.prefill("b", k3, v3, tokens=list(range(20, 32)))
    assert cache.has_sequence("b")
    assert cache.prefix_stats()["evictions"] >= 1
    # pool now genuinely full of LIVE data + its index refs: the next
    # allocation must still raise (the index never masks real pressure)
    with pytest.raises(CacheExhausted):
        cache.prefill("c", *kv(rng, 8))
    assert not cache.has_sequence("c")


def test_index_survives_sequence_free_for_future_hits():
    cache = shared_cache()
    rng = np.random.RandomState(4)
    toks = list(range(9))
    k, v = kv(rng, 9)
    cache.prefill("a", k, v, tokens=toks)
    expect_k, expect_v = cache.gather("a", 0)
    cache.free_sequence("a")
    plan = cache.match_prefix(toks)    # the template outlives its author
    assert plan is not None and plan.tokens_matched == 8
    kp, vp = cache.gather_plan(plan)
    assert np.array_equal(kp[0], expect_k[:8])
    assert np.array_equal(vp[0], expect_v[:8])
    cache.abandon_plan(plan)
    cache.drop_prefix_cache()
    assert cache.allocator.refcounts() == {}


# ---------------------------------------------------------------------------
# greedy-stream bit-equality, both decode arms
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["0", "1"])
def test_greedy_streams_bit_identical_sharing_on_vs_off(mode, monkeypatch):
    monkeypatch.setenv("TPUMX_PAGED_DECODE", mode)
    model = tiny(embed_dim=32, num_heads=2, num_layers=2, seed=5)
    tpl = list(np.random.RandomState(6).randint(1, 60, size=20))
    prompts = [tpl + [i + 1, i + 2] for i in range(6)] + [tpl[:11]]

    def run(share):
        srv = Server(model, num_blocks=256, block_size=8, max_batch=4,
                     prefix_sharing=share)
        reqs = [srv.submit(p, max_new_tokens=8) for p in prompts]
        srv.run_until_idle()
        stats = srv.engine.cache.prefix_stats()
        srv.engine.cache.drop_prefix_cache()
        assert srv.engine.cache.allocator.refcounts() == {}
        return [r.tokens for r in reqs], stats

    on, stats = run(True)
    off, _ = run(False)
    assert on == off
    assert stats["hits"] >= 6          # the template actually shared
    assert stats["prefill_bytes_saved"] > 0


# ---------------------------------------------------------------------------
# tenancy: quotas, fairness, boost
# ---------------------------------------------------------------------------
def test_tenant_quota_rejects_with_reason():
    sched = ContinuousBatchingScheduler(
        tenants={"capped": {"max_inflight": 2, "token_quota": 100}})
    sched.submit(Request([1], 2, tenant="capped"))
    sched.submit(Request([1], 2, tenant="capped"))
    with pytest.raises(AdmissionReject) as e:
        sched.submit(Request([1], 2, tenant="capped"))
    assert e.value.reason == "tenant_quota"
    # other tenants are unaffected by one tenant's quota
    sched.submit(Request([1], 2, tenant="other"))
    # token quota: a single oversized admission for the capped tenant
    with pytest.raises(AdmissionReject) as e:
        sched.submit(Request([1] * 50, 60, tenant="capped"))
    assert e.value.reason == "tenant_quota"
    # the rejected handle is failed + counted with its tenant label
    assert telemetry.get("serve.requests", state="rejected",
                         tenant="capped").value == 2


def test_weighted_fair_admission_tracks_weight_ratio():
    sched = ContinuousBatchingScheduler(
        max_pending=100, max_batch=2, max_tokens=40,
        tenants={"hi": {"weight": 2.0}, "lo": {"weight": 1.0}})
    for i in range(15):
        sched.submit(Request([1] * 5, 5, tenant="hi", request_id=f"h{i}"))
        sched.submit(Request([1] * 5, 5, tenant="lo", request_id=f"l{i}"))
    admitted = []
    for _ in range(6):
        admitted.extend(r.tenant for r in sched.take_prefills())
    hi, lo = admitted.count("hi"), admitted.count("lo")
    assert hi == 2 * lo, admitted       # 2:1 token bandwidth, exactly
    # FIFO within a tenant
    hid = [r for r in admitted]  # order sanity via ids requires handles
    assert admitted[0] == "hi"          # ties break by queue order


def test_single_tenant_admission_is_plain_fifo():
    """One tenant present → the pre-tenancy policy exactly, including
    stop-at-the-head on budget."""
    sched = ContinuousBatchingScheduler(max_pending=10, max_batch=8,
                                        max_tokens=13)
    small = Request([1], 1, request_id="small")      # budget 2
    big = Request([1] * 6, 6, request_id="big")      # budget 12
    tail = Request([1], 1, request_id="tail")        # would fit, but FIFO
    sched.submit(small)
    sched.submit(big)
    sched.submit(tail)
    got = sched.take_prefills()
    for r in got:
        sched.mark_running(r)
    assert [r.id for r in got] == ["small"]
    # head "big" no longer fits (2 + 12 > 13): admission stops AT the
    # head — "tail" is not pulled around it within one tenant
    assert sched.take_prefills() == []


def test_slo_breaching_tenant_gets_boosted():
    """A tenant whose per-tenant burn is breaching is admitted at
    boosted weight until the breach clears.  Tenant names are unique to
    this test: telemetry series are process-global and cumulative, so
    reusing another test's labels would couple the assertion to test
    order."""
    h = telemetry.histogram("serve.itl_seconds", tenant="boost-bad")
    for _ in range(50):
        h.observe(0.5)                 # way over the 50ms target
    g = telemetry.histogram("serve.itl_seconds", tenant="boost-good")
    for _ in range(50):
        g.observe(0.001)
    mon = SLOMonitor(("itl_p99 < 50ms",), windows=(5.0, 30.0))
    sig = mon.refresh(force=True)
    assert "boost-bad" in sig["breaching_tenants"]
    assert "boost-good" not in sig["breaching_tenants"]
    assert telemetry.get("serve.slo_tenant_burn_rate", slo="itl_p99",
                         tenant="boost-bad").value >= 1.0
    sched = ContinuousBatchingScheduler(
        max_pending=100, max_batch=2, max_tokens=40, slo_boost=2.0,
        tenants={"boost-bad": {"weight": 1.0},
                 "boost-good": {"weight": 1.0}})
    sched.slo_signal = sig
    for i in range(15):
        sched.submit(Request([1] * 5, 5, tenant="boost-bad",
                             request_id=f"b{i}"))
        sched.submit(Request([1] * 5, 5, tenant="boost-good",
                             request_id=f"g{i}"))
    admitted = []
    for _ in range(6):
        admitted.extend(r.tenant for r in sched.take_prefills())
    bad = admitted.count("boost-bad")
    good = admitted.count("boost-good")
    assert bad == 2 * good, admitted    # equal weights, boosted 2x


def test_returning_tenant_enters_at_the_floor_not_zero():
    """A tenant that was idle (or new) while others accrued virtual
    time must enter at the system floor — not at a stale-low clock
    that would let it monopolize admission until it 'catches up'."""
    sched = ContinuousBatchingScheduler(
        max_pending=100, max_batch=1, max_tokens=40,
        tenants={"a": {}, "b": {}})
    for i in range(5):
        sched.submit(Request([1] * 5, 5, tenant="a", request_id=f"a{i}"))
    for _ in range(3):                 # "a" serves alone for a while
        assert sched.take_prefills()
    for i in range(5):                 # now "b" bursts in
        sched.submit(Request([1] * 5, 5, tenant="b", request_id=f"b{i}"))
    order = []
    for _ in range(4):
        order.extend(r.tenant for r in sched.take_prefills())
    # equal weights: b gets its floor-entry pick, then they alternate —
    # never three b's in a row burning down a phantom deficit
    assert order.count("b") == 2, order


def test_past_cap_tenant_receives_aggregated_overflow_boost():
    """Burn is measured under the cardinality-capped label, so a tenant
    past the cap breaches as `_other` — the boost must follow the label
    or capped tenants could never be boosted."""
    for i in range(tenancy.TENANT_LABEL_CAP):
        tenancy.label_for(f"pad{i}")   # fill the cap
    sched = ContinuousBatchingScheduler(slo_boost=3.0)
    sched.slo_signal = {"slos": {"itl_p99": {"tenants": {
        tenancy.OVERFLOW_LABEL: {"breaching": True}}}}}
    boosted = sched._breaching_tenants()
    assert sched._effective_weight("past-cap-newcomer", boosted) == 3.0
    # capped tenants keep their own-label boost path
    assert sched._effective_weight("pad0", boosted) == 1.0


def test_tenant_label_cardinality_cap_overflows():
    for i in range(tenancy.TENANT_LABEL_CAP):
        assert tenancy.label_for(f"t{i}") == f"t{i}"
    assert tenancy.label_for("straggler") == tenancy.OVERFLOW_LABEL
    assert tenancy.label_for("t0") == "t0"          # stable
    assert tenancy.label_for("straggler") == tenancy.OVERFLOW_LABEL


def test_tenant_table_coercion_and_defaults():
    t = TenantTable.coerce({"a": {"weight": 3.0}, "b": None})
    assert t.get("a").weight == 3.0
    assert t.get("b").weight == 1.0
    assert t.get("unknown").max_inflight is None    # permissive default
    assert TenantTable.coerce(t) is t
    assert len(TenantTable.coerce(None)) == 0
    with pytest.raises(ValueError):
        TenantConfig("x", weight=0)
    with pytest.raises(ValueError):
        TenantTable([TenantConfig("x"), TenantConfig("x")])


# ---------------------------------------------------------------------------
# preemption + sharing
# ---------------------------------------------------------------------------
def test_preemption_never_corrupts_shared_prefix():
    """Preempt a sequence whose blocks are shared with a live sibling:
    the sibling's reads stay bit-identical and both requests complete
    with the exact streams an uncontended pool produces."""
    model = tiny(embed_dim=32, seed=7)
    tpl = list(np.random.RandomState(8).randint(1, 60, size=17))
    prompts = [tpl + [1], tpl + [2], tpl + [3]]

    def run(num_blocks):
        srv = Server(model, num_blocks=num_blocks, block_size=8,
                     max_batch=3, prefix_sharing=True)
        reqs = [srv.submit(p, max_new_tokens=12) for p in prompts]
        srv.run_until_idle()
        assert all(r.state == "done" for r in reqs)
        requeues = sum(r.requeues for r in reqs)
        cache = srv.engine.cache
        cache.drop_prefix_cache()
        assert cache.allocator.refcounts() == {}
        return [r.tokens for r in reqs], requeues

    roomy, r0 = run(256)
    assert r0 == 0
    # 7 blocks: the three 18-token prompts share their 2 template
    # blocks (3+1+1 at prefill) and decode growth past 24 tokens needs
    # 3 more — one reservation must preempt a sibling that SHARES the
    # template blocks
    tight, r1 = run(7)
    assert r1 > 0, "pool was not tight enough to force preemption"
    assert tight == roomy


def test_victim_selection_prefers_low_weight_tenant():
    """Three one-block sequences exactly fill the pool; the first
    decode reservation must evict one of the OTHER two — and it picks
    by tenant weight, not age."""
    def run(w_b, w_c):
        eng = EngineCore(tiny(seed=9), block_size=4, num_blocks=3,
                         share_prefix=False)
        reqs = []
        for name, w in (("a", 1.0), ("b", w_b), ("c", w_c)):
            r = Request([1, 2, 3, 4], 8, request_id=name, tenant=name)
            r.tenant_weight = w
            first, _ = eng.prefill(r)
            reqs.append((r, first))
        _, pre = eng.decode(reqs)
        return [r.id for r in pre]

    # a's reservation evicts the lowest-weight candidate among b/c
    assert run(0.5, 2.0)[0] == "b"
    assert run(2.0, 0.5)[0] == "c"


# ---------------------------------------------------------------------------
# attribution + observability surfaces
# ---------------------------------------------------------------------------
def test_cached_prefill_attribution_and_tenant_on_timeline():
    model = tiny(embed_dim=32, seed=10)
    tpl = list(np.random.RandomState(11).randint(1, 60, size=18))
    srv = Server(model, num_blocks=128, block_size=8, max_batch=2,
                 prefix_sharing=True)
    a = srv.submit(tpl + [1], max_new_tokens=3, tenant="acme")
    srv.run_until_idle()
    b = srv.submit(tpl + [2], max_new_tokens=3, tenant="acme")
    srv.run_until_idle()
    assert a.timeline.cached_tokens == 0
    assert b.timeline.cached_tokens == 16           # 2 full 8-blocks
    evs = [e for e in tracing.snapshot()
           if e["event"] == "serve.request_timeline"]
    by_req = {e["data"]["request"]: e["data"] for e in evs}
    assert by_req[a.id]["cached_tokens"] == 0
    assert by_req[b.id]["cached_tokens"] == 16
    assert by_req[b.id]["tenant"] == "acme"
    prefills = [e for e in tracing.snapshot()
                if e["event"] == "serve.prefill"]
    assert [e["data"]["cached"] for e in prefills] == [0, 16]
    # per-tenant terminal count + SLO pair twins exist
    assert telemetry.get("serve.requests", state="completed",
                         tenant="acme").value == 2
    assert telemetry.get("serve.ttft_seconds", tenant="acme").count == 2
    assert telemetry.get("serve.prefix_hit_ratio").value > 0


def test_commit_prefill_failure_releases_pins_and_fresh_blocks():
    """All-or-nothing: a fault INSIDE commit_prefill (bad suffix shape,
    fill error) must release the plan's pins AND any freshly allocated
    blocks — a leak here shrinks the pool forever and fails the CI
    post-storm refcount audit."""
    cache = shared_cache(num_blocks=16)
    toks = list(range(9))
    k, v = kv(np.random.RandomState(20), 9)
    cache.prefill("a", k, v, tokens=toks)
    plan = cache.match_prefix(toks)
    assert plan is not None
    with pytest.raises(ValueError):
        cache.commit_prefill("b", plan, k[:, :2, :1], v[:, :2, :1], toks)
    assert not cache.has_sequence("b")
    cache.free_sequence("a")
    cache.drop_prefix_cache()
    assert cache.allocator.refcounts() == {}


def test_commit_prefill_already_cached_keeps_live_sequence_intact():
    """The already-cached guard must only release THIS call's pins —
    popping the pre-existing live sequence's registration would leak
    its blocks and orphan its handle."""
    cache = shared_cache(num_blocks=16)
    toks = list(range(9))
    k, v = kv(np.random.RandomState(21), 9)
    cache.prefill("a", k, v, tokens=toks)
    want_k, want_v = cache.gather("a", 0)
    plan = cache.match_prefix(toks)
    with pytest.raises(MXNetError):
        cache.commit_prefill("a", plan, k[:, 8:], v[:, 8:], toks)
    assert cache.has_sequence("a")                 # still registered
    got_k, got_v = cache.gather("a", 0)            # still readable
    assert np.array_equal(got_k, want_k)
    assert np.array_equal(got_v, want_v)
    cache.free_sequence("a")
    cache.drop_prefix_cache()
    assert cache.allocator.refcounts() == {}


def test_plan_double_consumption_is_loud():
    """A plan's pins are released exactly once: double abandon, or
    abandon after commit, must raise — not silently steal another
    holder's reference (the refcount analog of double-free)."""
    cache = shared_cache(num_blocks=16)
    toks = list(range(9))
    k, v = kv(np.random.RandomState(22), 9)
    cache.prefill("a", k, v, tokens=toks)
    plan = cache.match_prefix(toks)
    cache.abandon_plan(plan)
    with pytest.raises(MXNetError):
        cache.abandon_plan(plan)               # double abandon
    plan2 = cache.match_prefix(toks)
    cache.commit_prefill("b", plan2, k[:, 8:], v[:, 8:], toks)
    with pytest.raises(MXNetError):
        cache.abandon_plan(plan2)              # abandon after commit
    # no reference was stolen: both sequences still audit clean
    cache.free_sequence("a")
    cache.free_sequence("b")
    cache.drop_prefix_cache()
    assert cache.allocator.refcounts() == {}


def test_sharing_refuses_lossy_pool_dtype():
    """A quantized pool would feed the suffix prefill pool-rounded
    prefix K/V where the sharing-off arm recomputes at model precision
    — sharing must refuse loudly rather than break bit-equality."""
    with pytest.raises(ValueError):
        PagedKVCache(2, 2, 4, dtype=np.float16, share_prefix=True)
    # sharing off: lossy pools stay allowed (the decode arms quantize
    # consistently for every token)
    PagedKVCache(2, 2, 4, dtype=np.float16, share_prefix=False)


def test_tenant_quota_covers_mid_prefill_window():
    """A request popped by take_prefills but not yet running is still
    in flight: a concurrent submit in that window must count it, or
    max_inflight is exceeded exactly when the step thread is busy."""
    sched = ContinuousBatchingScheduler(
        max_batch=4, tenants={"t": {"max_inflight": 1}})
    sched.submit(Request([1], 2, tenant="t"))
    popped = sched.take_prefills()
    assert len(popped) == 1
    with pytest.raises(AdmissionReject) as e:
        sched.submit(Request([1], 2, tenant="t"))
    assert e.value.reason == "tenant_quota"
    sched.mark_running(popped[0])


def test_defer_refunds_vtime_charge():
    """A deferred admission (cache backpressure — never started) gets
    its pick-time vtime charge back: a tenant bouncing on memory
    pressure must not fall behind the weight ratio while receiving
    zero service.  A requeue (real service consumed) keeps the
    charge."""
    sched = ContinuousBatchingScheduler(
        max_batch=1, tenants={"a": {}, "b": {}})
    sched.submit(Request([1] * 4, 4, tenant="a"))
    sched.submit(Request([1] * 4, 4, tenant="b"))
    got = sched.take_prefills()
    assert len(got) == 1
    charged = dict(sched._vtime)[got[0].tenant]
    sched.defer(got)
    assert sched._vtime[got[0].tenant] < charged


def test_restart_with_sharing_loses_nothing():
    """A NaN-poisoned decode restarts the engine mid-storm with sharing
    on: zero lost requests, and the rebuilt engine's fresh cache audits
    clean."""
    from tpu_mx.contrib import chaos
    model = tiny(embed_dim=32, seed=12)
    tpl = list(np.random.RandomState(13).randint(1, 60, size=14))
    srv = Server(model, num_blocks=128, block_size=8, max_batch=4,
                 backoff=0.0, prefix_sharing=True)
    with chaos.enable(seed=0, nan_after=3):
        reqs = [srv.submit(tpl + [i], max_new_tokens=4) for i in range(4)]
        srv.run_until_idle()
    assert srv.restarts == 1
    assert all(r.state == "done" and len(r.tokens) == 4 for r in reqs)
    cache = srv.engine.cache
    cache.drop_prefix_cache()
    assert cache.allocator.refcounts() == {}
