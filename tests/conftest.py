"""Test config: run everything on a virtual 8-device CPU mesh.

The environment's sitecustomize registers the `axon` TPU backend and imports
jax at interpreter startup with JAX_PLATFORMS=axon — initializing it tries to
claim the single real TPU chip, which would serialize/deadlock test runs.
jax is therefore ALREADY imported when this conftest runs; env vars are too
late, so force the CPU platform through jax.config and set the XLA host
device count before the first backend client is created (SURVEY §4: XLA's
CPU backend is the "fake TPU" for sharding tests; the driver validates the
multi-chip path the same way via __graft_entry__.dryrun_multichip).
"""
import os

# TPUMX_TEST_TPU=1 skips the CPU pin so the on-chip tier can actually run:
#   TPUMX_TEST_TPU=1 python -m pytest tests/ -m tpu
# (one process only — the chip serializes; see docstring above)
_TPU_TIER = os.environ.get("TPUMX_TEST_TPU") == "1"

if not _TPU_TIER:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=8").strip()

import jax

if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    """Fixed seeds per test — the reference's @with_seed decorator pattern."""
    np.random.seed(0)
    import tpu_mx as mx
    mx.random.seed(0)
    yield
