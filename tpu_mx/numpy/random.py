"""`mx.np.random` (REF:python/mxnet/numpy/random.py) — numpy-style
sampling from the framework RNG stream (explicit-key JAX PRNG under the
hood: traced keys inside functional traces, eager splits otherwise)."""
from __future__ import annotations

import jax
import jax.numpy as _jnp
import numpy as _onp

from .. import random as _random
from ..ndarray import NDArray

__all__ = ["uniform", "normal", "randn", "rand", "randint", "choice",
           "shuffle", "permutation", "multinomial", "beta", "gamma",
           "exponential", "seed",
           "poisson", "binomial", "chisquare", "geometric", "gumbel", "laplace", "logistic", "lognormal", "pareto", "power", "rayleigh", "weibull"]


def seed(s):
    _random.seed(s)


def _shape(size):
    if size is None:
        return ()
    return (size,) if isinstance(size, int) else tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.uniform(key, _shape(size),
                                      dtype or _jnp.float32,
                                      minval=low, maxval=high))


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    out = jax.random.normal(key, _shape(size), dtype or _jnp.float32)
    return NDArray(out * scale + loc)


def randn(*size):
    return normal(size=size or None)


def rand(*size):
    return uniform(size=size or None)


def randint(low, high=None, size=None, dtype=None, ctx=None):
    if high is None:
        low, high = 0, low
    key = _random.take_key()
    return NDArray(jax.random.randint(key, _shape(size), low, high,
                                      dtype or _jnp.int32))


def choice(a, size=None, replace=True, p=None, ctx=None):
    key = _random.take_key()
    arr = _jnp.arange(a) if isinstance(a, int) else _jnp.asarray(
        a._data if isinstance(a, NDArray) else a)
    pr = None if p is None else _jnp.asarray(
        p._data if isinstance(p, NDArray) else p)
    return NDArray(jax.random.choice(key, arr, _shape(size),
                                     replace=replace, p=pr))


def permutation(x):
    key = _random.take_key()
    arr = _jnp.arange(x) if isinstance(x, int) else _jnp.asarray(
        x._data if isinstance(x, NDArray) else x)
    return NDArray(jax.random.permutation(key, arr))


def shuffle(x):
    """In-place shuffle along axis 0 (numpy contract; the NDArray handle
    is rebound to the permuted buffer)."""
    if not isinstance(x, NDArray):
        raise TypeError("shuffle needs an NDArray")
    key = _random.take_key()
    x._rebind(jax.random.permutation(key, x._data))


def multinomial(n, pvals, size=None):
    key = _random.take_key()
    pv = _jnp.asarray(pvals._data if isinstance(pvals, NDArray) else pvals)
    draws = jax.random.categorical(
        key, _jnp.log(_jnp.maximum(pv, 1e-30)), shape=_shape(size) + (n,))
    counts = jax.vmap(lambda d: _jnp.bincount(d, length=pv.shape[-1]))(
        draws.reshape(-1, n)) if draws.ndim > 1 else _jnp.bincount(
        draws, length=pv.shape[-1])
    return NDArray(counts.reshape(_shape(size) + (pv.shape[-1],))
                   if size is not None else counts)


def beta(a, b, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.beta(key, a, b, _shape(size),
                                   dtype or _jnp.float32))


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    out = jax.random.gamma(key, shape, _shape(size), dtype or _jnp.float32)
    return NDArray(out * scale)


def exponential(scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.exponential(
        key, _shape(size), dtype or _jnp.float32) * scale)


# ---------------------------------------------------------------------------
# round-3 widening: the remaining heavily-used numpy.random samplers
# (REF:src/operator/random/sampler.h families).  Each draw consumes one key
# from the framework stream (seeded by mx.random.seed), so results are
# reproducible and trace-safe like the rest of this module.
# ---------------------------------------------------------------------------

def poisson(lam=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.poisson(key, lam, _shape(size)).astype(
        dtype or _jnp.int32))


def binomial(n, p, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.binomial(key, n, p, _shape(size)).astype(
        dtype or _jnp.int32))


def chisquare(df, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.chisquare(key, df, _shape(size),
                                        dtype or _jnp.float32))


def geometric(p, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.geometric(key, p, _shape(size)).astype(
        dtype or _jnp.int32))


def gumbel(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(loc + scale * jax.random.gumbel(
        key, _shape(size), dtype or _jnp.float32))


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(loc + scale * jax.random.laplace(
        key, _shape(size), dtype or _jnp.float32))


def logistic(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(loc + scale * jax.random.logistic(
        key, _shape(size), dtype or _jnp.float32))


def lognormal(mean=0.0, sigma=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(_jnp.exp(mean + sigma * jax.random.normal(
        key, _shape(size), dtype or _jnp.float32)))


def pareto(a, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.pareto(key, a, _shape(size),
                                     dtype or _jnp.float32) - 1.0)


def power(a, size=None, dtype=None, ctx=None):
    # X = U^(1/a): numpy's power distribution
    key = _random.take_key()
    u = jax.random.uniform(key, _shape(size), dtype or _jnp.float32)
    return NDArray(u ** (1.0 / a))


def rayleigh(scale=1.0, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.rayleigh(key, scale, _shape(size),
                                       dtype or _jnp.float32))


def weibull(a, size=None, dtype=None, ctx=None):
    key = _random.take_key()
    return NDArray(jax.random.weibull_min(key, 1.0, a, _shape(size),
                                          dtype or _jnp.float32))
