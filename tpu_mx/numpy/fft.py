"""mx.np.fft — FFT family over jax.numpy.fft through the autograd-aware
dispatch layer (REF:python/mxnet/numpy/fft counterpart surface; upstream
exposed FFTs via contrib ops backed by cuFFT, src/operator/contrib/fft).
On TPU the FFTs lower to XLA's native Fft HLO."""
from __future__ import annotations

import jax.numpy as _jnp

from ..ndarray import NDArray
from ..ndarray import ops as _ops


def _wrap(name):
    jfn = getattr(_jnp.fft, name)

    def op(a, *args, **kwargs):
        return _ops._apply(lambda x: jfn(x, *args, **kwargs), [a],
                           f"fft.{name}")

    op.__name__ = name
    op.__doc__ = f"mx.np.fft.{name} — jax.numpy.fft.{name}"
    return op


_WRAPPED = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft",
            "irfft", "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft",
            "fftshift", "ifftshift"]
for _name in _WRAPPED:
    globals()[_name] = _wrap(_name)


def fftfreq(n, d=1.0, dtype=None, ctx=None):
    return NDArray(_jnp.fft.fftfreq(n, d, dtype=dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, ctx=None):
    return NDArray(_jnp.fft.rfftfreq(n, d, dtype=dtype or "float32"))


__all__ = _WRAPPED + ["fftfreq", "rfftfreq"]
