"""`mx.np.linalg` (REF:python/mxnet/numpy/linalg.py) — jax.numpy.linalg
through the autograd-aware dispatch layer."""
from __future__ import annotations

import jax.numpy as _jnp

from ..ndarray import ops as _ops


def _wrap(name):
    jfn = getattr(_jnp.linalg, name)

    def op(*args, **kwargs):
        return _ops._apply(lambda *raw: jfn(*raw, **kwargs), list(args),
                           f"linalg_{name}")

    op.__name__ = name
    return op


# NB: eig/eigvals are CPU-only in XLA (nonsymmetric eigendecomposition);
# on a TPU runtime they raise jax's backend error - DIVERGENCES.md #18
_WRAPPED = ["cholesky", "cond", "det", "eig", "eigh", "eigvals",
            "eigvalsh", "inv", "lstsq",
            "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv",
            "qr", "slogdet", "solve", "svd", "tensorinv", "tensorsolve"]
for _name in _WRAPPED:
    globals()[_name] = _wrap(_name)

__all__ = list(_WRAPPED)
