"""`mx.np` — the NumPy-compatible array namespace (MXNet ≥1.6,
REF:python/mxnet/numpy/ — ~50k LoC of C++-backed wrappers upstream).

TPU-native design: every function wraps the matching `jax.numpy` routine
through `ops._apply`, so results are framework NDArrays that participate
in autograd recording and in functional (hybridize/CompiledTrainStep)
traces exactly like the classic `nd` ops — one dispatch layer, not a
parallel engine.  Upstream keeps a separate np ndarray type; here the
unified NDArray already has numpy semantics (a documented divergence).

Default dtype is float32 (the upstream mx.np contract, and the only
sensible default on TPU).
"""
from __future__ import annotations

import builtins as _builtins

import numpy as _onp
import jax.numpy as _jnp

from ..ndarray import NDArray
from ..ndarray import ops as _ops

newaxis = None
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
euler_gamma = _onp.euler_gamma
float32, float64, float16 = "float32", "float64", "float16"
int32, int64, int8, uint8 = "int32", "int64", "int8", "uint8"
bool_ = "bool"
ndarray = NDArray


def _to_f32(dtype, obj):
    if dtype is not None:
        return dtype
    a = _onp.asarray(obj)
    if a.dtype == _onp.float64:
        return _onp.float32  # mx.np default-dtype contract
    return None


def array(object, dtype=None, ctx=None):
    a = _onp.asarray(object)
    return NDArray(_jnp.asarray(a, _to_f32(dtype, a)))


def zeros(shape, dtype=None, ctx=None, **kw):
    return NDArray(_jnp.zeros(shape, dtype or "float32"))


def ones(shape, dtype=None, ctx=None, **kw):
    return NDArray(_jnp.ones(shape, dtype or "float32"))


def full(shape, fill_value, dtype=None, ctx=None, **kw):
    return NDArray(_jnp.full(shape, fill_value,
                             dtype or _to_f32(None, fill_value) or None))


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    out = _jnp.arange(start, stop, step, dtype)
    if dtype is None and out.dtype == _jnp.float64:
        out = out.astype(_jnp.float32)
    return NDArray(out)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None,
             **kw):
    return NDArray(_jnp.linspace(start, stop, num, endpoint=endpoint,
                                 dtype=dtype or "float32"))


def eye(N, M=None, k=0, dtype=None, ctx=None, **kw):
    return NDArray(_jnp.eye(N, M, k, dtype or "float32"))


def identity(n, dtype=None, ctx=None):
    return NDArray(_jnp.identity(n, dtype or "float32"))


def _wrap(jnp_name, public=None):
    jfn = getattr(_jnp, jnp_name)

    def op(*args, **kwargs):
        # sequence-taking routines (concatenate, stack, …) receive a list
        # of arrays as ONE argument; flatten it through the dispatch layer
        # so every element participates in autograd, rebuild inside
        # NB: module globals shadow builtins like any/all/sum with wrapped
        # np ops — reach for the real builtins in here
        # NDArray kwargs (e.g. average(..., weights=w)) are unwrapped to
        # raw values: they compute correctly but are CONSTANTS to autograd
        # — pass arrays positionally when their gradient matters
        kwargs = {k: (v._data if isinstance(v, NDArray) else v)
                  for k, v in kwargs.items()}
        flat, spec = [], []
        for a in args:
            if isinstance(a, (list, tuple)) and _builtins.any(
                    isinstance(x, NDArray) for x in a):
                spec.append(len(a))
                flat.extend(a)
            else:
                spec.append(None)
                flat.append(a)

        def call(*raw):
            it = iter(raw)
            rebuilt = [[next(it) for _ in range(n)] if n is not None
                       else next(it) for n in spec]
            return jfn(*rebuilt, **kwargs)

        return _ops._apply(call, flat, public or jnp_name)

    op.__name__ = public or jnp_name
    op.__doc__ = (f"mx.np.{public or jnp_name} — jax.numpy.{jnp_name} "
                  "through the autograd-aware dispatch layer "
                  "(REF:python/mxnet/numpy)")
    return op


# one generated wrapper per jnp routine; names follow numpy.  Keep sorted.
_WRAPPED = [
    "abs", "absolute", "add", "all", "allclose", "amax", "amin", "any",
    "append",
    "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctan2",
    "arctanh", "argmax", "argmin", "argsort", "around", "array_split",
    "atleast_1d",
    "atleast_2d", "atleast_3d", "average", "bincount", "bitwise_and",
    "bitwise_or", "bitwise_xor", "broadcast_arrays", "broadcast_to",
    "cbrt", "ceil", "clip", "column_stack", "concatenate", "copysign",
    "cos", "cosh", "cross", "cumprod", "cumsum", "deg2rad", "degrees",
    "delete", "diag", "diagflat", "diagonal", "diff", "divide", "divmod",
    "dot", "dsplit", "dstack",
    "ediff1d", "einsum", "equal", "exp", "exp2", "expand_dims", "expm1",
    "flatnonzero", "flip", "fliplr", "flipud", "floor", "floor_divide",
    "fmax", "fmin", "fmod", "gcd", "greater", "greater_equal",
    "histogram", "hsplit",
    "hstack", "hypot", "inner", "insert", "interp", "invert", "isclose",
    "isfinite", "isinf",
    "isnan", "isneginf", "isposinf", "kron", "lcm", "ldexp", "less",
    "less_equal", "log", "log10", "log1p", "log2", "logaddexp",
    "logaddexp2",
    "logical_and", "logical_not", "logical_or", "logical_xor", "matmul",
    "max", "maximum", "mean", "median", "meshgrid", "min", "minimum",
    "mod", "moveaxis", "multiply", "nan_to_num", "nanmax", "nanmean",
    "nanmin", "nansum", "negative", "nonzero",
    "not_equal", "outer", "pad", "percentile", "polyval", "power", "prod",
    "ptp", "quantile", "rad2deg", "radians", "ravel", "reciprocal",
    "remainder",
    "repeat", "reshape", "resize", "roll", "rot90", "searchsorted",
    "sign", "sin",
    "sinh", "sort", "split", "sqrt", "square", "squeeze", "stack", "std",
    "subtract", "sum", "swapaxes", "take", "take_along_axis", "tan",
    "tanh", "tensordot",
    "tile", "trace", "transpose", "tril", "triu", "true_divide", "trunc",
    "unique", "unravel_index", "vander", "var", "vdot", "vsplit",
    "vstack", "where",
]
for _name in _WRAPPED:
    globals()[_name] = _wrap(_name)
round = globals()["around"]
concat = globals()["concatenate"]
fix = globals()["trunc"]  # numpy fix == round toward zero (jnp.fix removed)


def frexp(x):
    """Mantissa/exponent decomposition with a DIFFERENTIABLE mantissa.

    jnp.frexp is built from bitwise ops, so d(mantissa)/dx is silently
    zero even in raw jax.  The exponent is piecewise constant in x, so
    the true derivative is ``d(m)/dx = 2**-e``; it is attached
    STRAIGHT-THROUGH: the returned VALUES are exactly jnp.frexp's bits
    (the gradient path contributes an exact zero, clamped so inf/nan
    inputs cannot leak a nan through ``inf - inf``), while the gradient
    flows via ``x * 2**-e`` computed as two half-power scalings so
    neither factor overflows across the full exponent range.  Subnormal
    inputs follow the backend's flush-to-zero arithmetic — divergence
    #26 in docs/DIVERGENCES.md."""
    import jax as _jax

    def call(v):
        if not _jnp.issubdtype(v.dtype, _jnp.floating):
            v = v.astype(_jnp.result_type(float))
        m_exact, e = _jnp.frexp(v)
        e_sg = _jax.lax.stop_gradient(e)
        h = (-e_sg) // 2
        scaled = (v * _jnp.exp2(h.astype(v.dtype))) \
            * _jnp.exp2((-e_sg - h).astype(v.dtype))
        # zero (not nan) straight-through delta for inf/nan inputs: the
        # value must stay m_exact's bits there, with no gradient
        scaled = _jnp.where(_jnp.isfinite(scaled), scaled, 0)
        m = m_exact + (scaled - _jax.lax.stop_gradient(scaled))
        return m, e
    return _ops._apply(call, [x], "frexp")


def zeros_like(a, dtype=None, **kw):
    return _ops._apply(lambda x: _jnp.zeros_like(x, dtype), [a],
                       "zeros_like")


def ones_like(a, dtype=None, **kw):
    return _ops._apply(lambda x: _jnp.ones_like(x, dtype), [a],
                       "ones_like")


def full_like(a, fill_value, dtype=None, **kw):
    return _ops._apply(lambda x: _jnp.full_like(x, fill_value, dtype), [a],
                       "full_like")


def empty(shape, dtype=None, ctx=None, **kw):
    # functional arrays are never uninitialized; zeros is the honest analog
    return zeros(shape, dtype, ctx)


def empty_like(a, dtype=None, **kw):
    return zeros_like(a, dtype)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None, **kw):
    return NDArray(_jnp.logspace(start, stop, num, endpoint=endpoint,
                                 base=base, dtype=dtype or "float32"))


def indices(dimensions, dtype="int32", ctx=None):
    # numpy contract: ONE stacked array of shape (ndim, *dimensions)
    return NDArray(_jnp.indices(tuple(dimensions), dtype=dtype))


def diag_indices(n, ndim=2):
    return tuple(NDArray(a) for a in _jnp.diag_indices(n, ndim))


def may_share_memory(a, b):
    return False  # functional arrays never alias


def shape(a):
    return tuple(a.shape)


def ndim(a):
    return len(a.shape)


def size(a):
    return int(_onp.prod(a.shape)) if a.shape else 1


from . import fft         # noqa: E402
from . import linalg      # noqa: E402
from . import random      # noqa: E402

__all__ = (["array", "zeros", "ones", "full", "arange", "linspace", "eye",
            "identity", "zeros_like", "ones_like", "full_like", "ndarray", "fix",
            "newaxis", "pi", "e", "inf", "nan", "fft", "linalg", "random",
            "shape", "ndim", "size", "round", "concat", "empty", "frexp",
            "empty_like", "logspace", "indices", "diag_indices"] + _WRAPPED)
